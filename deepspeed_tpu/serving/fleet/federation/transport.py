"""Socket transport for framed worker-protocol messages.

A :class:`FrameConnection` wraps one connected TCP socket and speaks
``frames.py`` records: JSON control messages, each optionally followed
by one raw blob frame (flagged in-band with ``"_blob": true`` so the
reader knows to consume the companion frame). All wire faults surface
as named :class:`~.frames.FrameError`s (timeout / truncated /
malformed / oversize / corrupt) or :class:`PeerGone` on a clean
disconnect — the remote-replica layer maps these onto
``WorkerProtocolError`` and ``ReplicaDead`` exactly like the pipe
backend does.

Wire-revision negotiation: the DECODER accepts DSF1 and DSF2 frames
unconditionally (the magic selects the layout), but a connection only
*sends* DSF2 after :meth:`FrameConnection.negotiate` records that the
peer advertised ``wire_rev >= 2`` in the init/ready exchange — so a
DSF1-only peer keeps interoperating and a new↔new pair gets crc32
integrity on every frame.

Backpressure: ``send_timeout_s`` puts a deadline on every ``sendall``
so one wedged peer (full receive window, half-open TCP) surfaces as a
named ``FrameError("timeout")`` instead of stalling the fleet's
dispatch thread forever.

Fault injection: ``fault_injector`` (see ``netfaults.py``) intercepts
outbound frames one at a time — the deterministic chaos instrument for
the wire. None (the default) is the zero-overhead production path.

Wire accountant (the PR-8 "measured not claimed" discipline applied to
the federation wire): set ``conn.peer`` to a peer id and every frame
that crosses this connection is tallied into the process registry —
``wire/{tx,rx}_{frames,bytes}/<kind>/<peer>`` counters whose byte
totals reconcile EXACTLY with ``encode_frame`` output sizes (tx counts
the encoded frame as handed to the wire layer; rx counts the decoder's
consumed bytes, header + payload, which is the same number), plus
``wire/faults/<kind>/<peer>`` for every named ``FrameError``
(corrupt / timeout / truncated / malformed / oversize). ``peer`` unset
(the default) keeps the connection unaccounted — codec tests and
anonymous sockets never pollute the registry.

Stdlib-only; no jax.
"""

import json
import socket

from deepspeed_tpu.observability.metrics import get_registry
from deepspeed_tpu.serving.fleet.federation.frames import (
    DEFAULT_MAX_FRAME_BYTES,
    FrameDecoder,
    FrameError,
    KIND_BLOB,
    KIND_JSON,
    encode_frame,
)

_RECV_CHUNK = 1 << 16

_KIND_LABELS = {KIND_JSON: "json", KIND_BLOB: "blob"}


class PeerGone(ConnectionError):
    """The peer closed the stream cleanly (EOF between frames)."""


def parse_address(address):
    """``"host:port"`` → ``(host, port)``; port may be 0 (ephemeral)."""
    host, sep, port = str(address).rpartition(":")
    if not sep or not host:
        raise ValueError(
            f"address {address!r} must be HOST:PORT (e.g. 127.0.0.1:7077)")
    try:
        return host, int(port)
    except ValueError:
        raise ValueError(f"address {address!r} has a non-integer port")


def connect(host, port, timeout_s=5.0,
            max_frame_bytes=DEFAULT_MAX_FRAME_BYTES,
            send_timeout_s=None):
    """Dial a federation peer; OSError propagates to the caller (a
    failed dial is a spawn failure, not a protocol error)."""
    sock = socket.create_connection((host, int(port)), timeout=timeout_s)
    return FrameConnection(sock, max_frame_bytes=max_frame_bytes,
                           send_timeout_s=send_timeout_s)


class FrameConnection:
    def __init__(self, sock, max_frame_bytes=DEFAULT_MAX_FRAME_BYTES,
                 send_timeout_s=None):
        try:
            sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        except OSError:
            pass  # not a TCP socket (e.g. socketpair in tests)
        self._sock = sock
        self._decoder = FrameDecoder(max_frame_bytes)
        self.closed = False
        self.send_timeout_s = send_timeout_s
        self.tx_rev = 1            # until the peer advertises wire_rev 2
        self.fault_injector = None  # netfaults.WireFaultInjector or None
        # peer id for the wire accountant; None = unaccounted connection
        self.peer = None
        # rx watermark: decoder bytes already attributed to rx counters
        self._rx_accounted = 0

    def _account_tx(self, data):
        """Tally one outbound encoded frame — called BEFORE the fault
        injector so each logical frame counts exactly once no matter
        what chaos (duplicate / blackhole / drip) does downstream."""
        if self.peer is None:
            return
        reg = get_registry()
        kind = _KIND_LABELS.get(data[4], "other")
        reg.counter(f"wire/tx_frames/{kind}/{self.peer}").inc()
        reg.counter(f"wire/tx_bytes/{kind}/{self.peer}").inc(len(data))

    def _account_rx(self, kind):
        """Attribute the decoder's newly-consumed bytes (header +
        payload — exactly ``len(encode_frame(...))`` for the frame just
        returned) to this peer's rx counters."""
        if self.peer is None:
            return
        delta = self._decoder.consumed - self._rx_accounted
        self._rx_accounted = self._decoder.consumed
        reg = get_registry()
        label = _KIND_LABELS.get(kind, "other")
        reg.counter(f"wire/rx_frames/{label}/{self.peer}").inc()
        reg.counter(f"wire/rx_bytes/{label}/{self.peer}").inc(delta)

    def _account_fault(self, fault_kind):
        """One named wire fault (corrupt / timeout / truncated /
        malformed / oversize) against this peer. Damaged frames land
        here, never in the rx byte tally."""
        if self.peer is None:
            # keep the rx watermark honest even while unaccounted
            self._rx_accounted = self._decoder.consumed
            return
        get_registry().counter(
            f"wire/faults/{fault_kind}/{self.peer}").inc()
        self._rx_accounted = self._decoder.consumed

    def fileno(self):
        return self._sock.fileno()

    def negotiate(self, peer_rev):
        """Record the peer's advertised ``wire_rev`` (from its init or
        ready message). Missing/old advertisements keep DSF1."""
        self.tx_rev = 2 if peer_rev is not None and int(peer_rev) >= 2 \
            else 1

    def send_msg(self, msg, blob=None):
        """One JSON frame, plus one blob frame when ``blob`` is given.
        OSError (broken pipe, reset) propagates to the caller; a send
        that stalls past ``send_timeout_s`` raises the named
        ``FrameError("timeout")``."""
        head = dict(msg)
        if blob is not None:
            head["_blob"] = True
        self._send_frame(encode_frame(
            json.dumps(head, default=float).encode("utf-8"),
            rev=self.tx_rev))
        if blob is not None:
            self._send_frame(encode_frame(blob, KIND_BLOB,
                                          rev=self.tx_rev))

    def _send_frame(self, data):
        """One encoded frame onto the wire — the per-frame hook point
        the fault injector keys its ordinal schedule on."""
        self._account_tx(data)
        if self.fault_injector is not None:
            self.fault_injector.send(self, data)
        else:
            self._raw_send(data)

    def _raw_send(self, data):
        self._sock.settimeout(self.send_timeout_s)
        try:
            self._sock.sendall(data)
        except socket.timeout:
            # the peer stopped draining its receive window (wedged or
            # half-open): a partial frame may be on the wire, so the
            # connection is desynchronized — the caller contains it the
            # same way it contains a read timeout
            self._account_fault("timeout")
            raise FrameError(
                "timeout",
                f"send stalled past {self.send_timeout_s}s "
                "(peer not draining)")

    def _recv_frame(self, timeout_s):
        while True:
            try:
                frame = self._decoder.next_frame()
            except FrameError as exc:
                self._account_fault(exc.kind)
                raise
            if frame is not None:
                self._account_rx(frame[0])
                return frame
            self._sock.settimeout(timeout_s)
            try:
                chunk = self._sock.recv(_RECV_CHUNK)
            except socket.timeout:
                self._account_fault("timeout")
                raise FrameError(
                    "timeout", f"no reply within {timeout_s}s")
            if not chunk:
                try:
                    self._decoder.eof()  # raises "truncated" mid-frame
                except FrameError as exc:
                    self._account_fault(exc.kind)
                    raise
                raise PeerGone("peer closed the connection")
            self._decoder.feed(chunk)

    def recv_msg(self, timeout_s=None):
        """→ ``(msg, blob)``; ``blob`` is None unless the message was
        sent with a companion blob frame."""
        kind, payload = self._recv_frame(timeout_s)
        if kind != KIND_JSON:
            self._account_fault("malformed")
            raise FrameError("malformed", "blob frame without JSON header")
        try:
            msg = json.loads(payload.decode("utf-8"))
        except (ValueError, UnicodeDecodeError) as exc:
            self._account_fault("malformed")
            raise FrameError("malformed", f"undecodable JSON frame: {exc}")
        if not isinstance(msg, dict):
            self._account_fault("malformed")
            raise FrameError("malformed", "JSON frame is not an object")
        blob = None
        if msg.pop("_blob", False):
            kind, blob = self._recv_frame(timeout_s)
            if kind != KIND_BLOB:
                self._account_fault("malformed")
                raise FrameError(
                    "malformed", "expected blob frame after _blob header")
        return msg, blob

    def close(self):
        if self.closed:
            return
        self.closed = True
        try:
            self._sock.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        try:
            self._sock.close()
        except OSError:
            pass
