"""Socket transport for framed worker-protocol messages.

A :class:`FrameConnection` wraps one connected TCP socket and speaks
``frames.py`` records: JSON control messages, each optionally followed
by one raw blob frame (flagged in-band with ``"_blob": true`` so the
reader knows to consume the companion frame). All wire faults surface
as named :class:`~.frames.FrameError`s (timeout / truncated /
malformed / oversize) or :class:`PeerGone` on a clean disconnect —
the remote-replica layer maps these onto ``WorkerProtocolError`` and
``ReplicaDead`` exactly like the pipe backend does.

Stdlib-only; no jax.
"""

import json
import socket

from deepspeed_tpu.serving.fleet.federation.frames import (
    DEFAULT_MAX_FRAME_BYTES,
    FrameDecoder,
    FrameError,
    KIND_BLOB,
    KIND_JSON,
    encode_frame,
)

_RECV_CHUNK = 1 << 16


class PeerGone(ConnectionError):
    """The peer closed the stream cleanly (EOF between frames)."""


def parse_address(address):
    """``"host:port"`` → ``(host, port)``; port may be 0 (ephemeral)."""
    host, sep, port = str(address).rpartition(":")
    if not sep or not host:
        raise ValueError(
            f"address {address!r} must be HOST:PORT (e.g. 127.0.0.1:7077)")
    try:
        return host, int(port)
    except ValueError:
        raise ValueError(f"address {address!r} has a non-integer port")


def connect(host, port, timeout_s=5.0,
            max_frame_bytes=DEFAULT_MAX_FRAME_BYTES):
    """Dial a federation peer; OSError propagates to the caller (a
    failed dial is a spawn failure, not a protocol error)."""
    sock = socket.create_connection((host, int(port)), timeout=timeout_s)
    return FrameConnection(sock, max_frame_bytes=max_frame_bytes)


class FrameConnection:
    def __init__(self, sock, max_frame_bytes=DEFAULT_MAX_FRAME_BYTES):
        try:
            sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        except OSError:
            pass  # not a TCP socket (e.g. socketpair in tests)
        self._sock = sock
        self._decoder = FrameDecoder(max_frame_bytes)
        self.closed = False

    def fileno(self):
        return self._sock.fileno()

    def send_msg(self, msg, blob=None):
        """One JSON frame, plus one blob frame when ``blob`` is given.
        OSError (broken pipe, reset) propagates to the caller."""
        head = dict(msg)
        if blob is not None:
            head["_blob"] = True
        data = encode_frame(json.dumps(head, default=float).encode("utf-8"))
        if blob is not None:
            data += encode_frame(blob, KIND_BLOB)
        self._sock.sendall(data)

    def _recv_frame(self, timeout_s):
        while True:
            frame = self._decoder.next_frame()
            if frame is not None:
                return frame
            self._sock.settimeout(timeout_s)
            try:
                chunk = self._sock.recv(_RECV_CHUNK)
            except socket.timeout:
                raise FrameError(
                    "timeout", f"no reply within {timeout_s}s")
            if not chunk:
                self._decoder.eof()  # raises "truncated" when mid-frame
                raise PeerGone("peer closed the connection")
            self._decoder.feed(chunk)

    def recv_msg(self, timeout_s=None):
        """→ ``(msg, blob)``; ``blob`` is None unless the message was
        sent with a companion blob frame."""
        kind, payload = self._recv_frame(timeout_s)
        if kind != KIND_JSON:
            raise FrameError("malformed", "blob frame without JSON header")
        try:
            msg = json.loads(payload.decode("utf-8"))
        except (ValueError, UnicodeDecodeError) as exc:
            raise FrameError("malformed", f"undecodable JSON frame: {exc}")
        if not isinstance(msg, dict):
            raise FrameError("malformed", "JSON frame is not an object")
        blob = None
        if msg.pop("_blob", False):
            kind, blob = self._recv_frame(timeout_s)
            if kind != KIND_BLOB:
                raise FrameError(
                    "malformed", "expected blob frame after _blob header")
        return msg, blob

    def close(self):
        if self.closed:
            return
        self.closed = True
        try:
            self._sock.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        try:
            self._sock.close()
        except OSError:
            pass
