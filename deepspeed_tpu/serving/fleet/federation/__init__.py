"""Cross-host fleet federation: socket transport for the worker
protocol, remote (non-child) replicas, an HTTP request front-end, and
zero-downtime rolling weight updates.

Import discipline mirrors ``serving.fleet``: the frame codec, transport,
and config are stdlib-only (importable with no jax present); everything
that touches an engine is loaded lazily.
"""

from deepspeed_tpu.serving.fleet.federation.config import FederationConfig
from deepspeed_tpu.serving.fleet.federation.frames import (
    FrameError,
    FrameDecoder,
    encode_frame,
    DEFAULT_MAX_FRAME_BYTES,
)
from deepspeed_tpu.serving.fleet.federation.transport import (
    FrameConnection,
    PeerGone,
    connect,
    parse_address,
)

_LAZY = {
    "RemoteReplica": "deepspeed_tpu.serving.fleet.federation.remote",
    "FleetFrontend": "deepspeed_tpu.serving.fleet.federation.frontend",
    "FrontendOverloaded": "deepspeed_tpu.serving.fleet.federation.frontend",
    "WireFaultInjector": "deepspeed_tpu.serving.fleet.federation.netfaults",
    "WireFaultPlan": "deepspeed_tpu.serving.fleet.federation.netfaults",
    "RollingUpdate": "deepspeed_tpu.serving.fleet.federation.rolling",
    "RollingUpdateError": "deepspeed_tpu.serving.fleet.federation.rolling",
    "FederationWorkerServer": "deepspeed_tpu.serving.fleet.federation.worker",
}

__all__ = [
    "FederationConfig",
    "FrameError",
    "FrameDecoder",
    "encode_frame",
    "DEFAULT_MAX_FRAME_BYTES",
    "FrameConnection",
    "PeerGone",
    "connect",
    "parse_address",
] + sorted(_LAZY)


def __getattr__(name):
    if name in _LAZY:
        import importlib
        module = importlib.import_module(_LAZY[name])
        value = getattr(module, name)
        globals()[name] = value
        return value
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
