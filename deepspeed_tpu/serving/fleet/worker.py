"""Fleet worker: one ServingEngine subprocess on a line-JSON protocol.

``python -m deepspeed_tpu.serving.fleet.worker`` reads an ``init`` spec
line on stdin (serving config dict + model spec + role + optional
telemetry port), builds its engine, then serves ops until ``stop``:

    {"op": "submit", "id", "prompt", "max_new_tokens", "priority"}
    {"op": "advance"}                 -> events/finished/handoffs/stats
    {"op": "export", "id"}            -> base64 handoff blob
    {"op": "inject", "blob": b64}     -> accepted true/false
    {"op": "slot_cap", "n": N}        -> admission cap (rolling drain)
    {"op": "swap", "spec": {...}}     -> rebuild engine (rolling update)
    {"op": "stop"}

Replies go to stdout prefixed with the ``@fleet `` sentinel so they
multiplex cleanly with engine logging (the parent passes everything
else through). Every op is answered before the next is read — the
synchronous exchange is what keeps multi-process dispatch replayable.

Each worker is its own process and device world: ``JAX_PLATFORMS`` /
``XLA_FLAGS`` from the environment pick its backend and virtual device
subset, and ``telemetry_port`` lights up the per-replica PR-8
``/metrics`` + ``/healthz`` + ``/statusz`` endpoint the router-side
scrape client (observability/export.py) reads.
"""

import base64
import json
import os
import signal
import sys
import time

import numpy as np

from .handoff import deserialize_handoff, serialize_handoff
from .replica import PROTOCOL_SENTINEL, engine_stats


def _reply(msg: dict):
    # default=float: metrics snapshots carry numpy scalars
    sys.stdout.write(PROTOCOL_SENTINEL + json.dumps(msg, default=float)
                     + "\n")
    sys.stdout.flush()


def _build_engine(spec: dict):
    from ..config import ServingConfig
    from ..engine import ServingEngine
    model_spec = dict(spec.get("model") or {})
    seed = model_spec.pop("seed", 0)
    if spec.get("checkpoint"):
        from ...models.gpt import GPT, GPTConfig
        from ...runtime.checkpointing import load_module_params
        params = load_module_params(spec["checkpoint"])
        module = GPT(GPTConfig(**model_spec))
    else:
        from benchmarks.serving.load_harness import build_demo_model
        module, params = build_demo_model(seed=seed, **model_spec)
    serving = dict(spec.get("serving") or {})
    serving.pop("fleet", None)      # a replica IS the fleet's leaf
    return ServingEngine(module, params, ServingConfig(**serving))


class _Worker:
    # ``_reply`` is an instance METHOD (defaulting to the stdout pipe
    # dialect) so the federation socket worker can subclass and answer
    # over a FrameConnection instead — one op surface, two transports.
    def _reply(self, msg: dict):
        _reply(msg)

    def __init__(self, spec: dict):
        self.replica_id = spec.get("replica_id", 0)
        self.role = spec.get("role", "full")
        self._spec = dict(spec)
        if spec.get("trace"):
            # fleet-wide tracing: this worker's spans (queue wait,
            # admit, prefill chunks, handoff inject, decode residency —
            # each tagged with its request's trace_id) record into a
            # process-local tracer the parent pulls via ``trace_dump``
            # and stitches into one fleet Chrome trace
            from ...observability.trace import Tracer, activate
            activate(Tracer())
        self.engine = _build_engine(spec)
        if self.role == "prefill":
            self.engine.set_prefill_role(True)
        telemetry_port = self._start_telemetry(spec)
        self._handles = {}           # id -> Request
        self._reported = set()       # ids whose completion already went out
        self._admit_reported = set() # ids whose first admission went out
        self._events = []            # [[id, token, engine iteration]]
        self._staged = {}            # id -> (slot, req) awaiting export
        # deterministic chaos hooks (the fleet scenario pack's vehicle):
        # {"hang_at_advance": N, "hang_s": S} wedges op_advance at engine
        # iteration N — the parent's reply timeout must contain it
        chaos = dict(spec.get("chaos") or {})
        self._hang_at = chaos.get("hang_at_advance")
        self._hang_s = float(chaos.get("hang_s", 600.0))
        # PR-4 preemption parity (runtime/resilience/preemption.py): a
        # supervised teardown (SIGTERM from the parent's kill path or
        # the orchestrator) ships this worker's partial metrics snapshot
        # up the pipe before the default termination runs — a killed
        # replica's work must not vanish without a trace
        try:
            signal.signal(signal.SIGTERM, self._on_sigterm)
        except ValueError:
            # federation tests host a socket worker on a non-main
            # thread, where installing handlers is forbidden; the
            # engine is still torn down by the stop op
            pass
        self._reply({"op": "ready", "replica_id": self.replica_id,
                     "telemetry_port": telemetry_port})

    def _start_telemetry(self, spec):
        port = spec.get("telemetry_port")
        if port is None:
            return None
        # bugfix ride-along: remote workers must bind their scrape
        # endpoint on the federation listen interface, not the
        # 127.0.0.1 the in-process spawn path assumed — the router's
        # scrape client dials the host it dialed the worker on
        host = spec.get("telemetry_host") or "127.0.0.1"
        return self.engine.start_telemetry(port=port, host=host).port

    def _on_sigterm(self, signum, frame):
        try:
            self._reply({"op": "partial_metrics",
                    "replica_id": self.replica_id,
                    "reason": f"signal {signum}",
                    "iteration": self.engine.iteration,
                    "metrics": self.engine.metrics.snapshot()})
        finally:
            # chain to the default action so termination semantics are
            # exactly what the parent expects (the PreemptionHandler
            # re-deliver pattern)
            signal.signal(signum, signal.SIG_DFL)
            os.kill(os.getpid(), signum)

    def _on_token(self, req, token):
        self._events.append([req.request_id, int(token),
                             self.engine.iteration])

    def _completions(self):
        done = []
        for rid, req in list(self._handles.items()):
            if req.done and rid not in self._reported:
                self._reported.add(rid)
                done.append({
                    "id": rid, "status": req.status,
                    "shed_reason": req.shed_reason,
                    "submitted_iteration": req.submitted_iteration,
                    "first_token_iteration": req.first_token_iteration,
                    "finished_iteration": req.finished_iteration,
                    "preemptions": req.preemptions,
                })
        return done

    def op_submit(self, msg):
        req = self.engine.submit(
            np.asarray(msg["prompt"], np.int32), msg["max_new_tokens"],
            request_id=msg["id"], priority=msg.get("priority", 0),
            on_token=self._on_token, trace_id=msg.get("trace_id"))
        self._handles[msg["id"]] = req
        self._reply({"op": "submitted", "id": msg["id"], "status": req.status})

    def _admissions(self):
        """Ids admitted since the last advance reply (first admission
        only — a preempt/resume cycle is not a fresh queue->admit
        transition): the parent stamps its fleet-clock admit mark for
        the per-request waterfall from these."""
        out = []
        for rid, req in self._handles.items():
            if (req.admitted_iteration is not None
                    and rid not in self._admit_reported):
                self._admit_reported.add(rid)
                out.append(rid)
        return sorted(out, key=str)

    def op_advance(self, msg):
        if self._hang_at is not None \
                and self.engine.iteration >= self._hang_at:
            time.sleep(self._hang_s)   # chaos: a wedged worker — the
                                       # parent's reply timeout fires
        self.engine.advance()
        for slot, req in self.engine.take_handoff_ready():
            self._staged[req.request_id] = (slot, req)
        events, self._events = self._events, []
        stats = {k: v for k, v in engine_stats(
            self.engine, self.replica_id, self.role).to_dict().items()
            if k not in ("replica_id", "alive", "role")}
        self._reply({"op": "advanced", "iteration": self.engine.iteration,
                "events": events, "finished": self._completions(),
                "admitted": self._admissions(),
                "handoff_ready": sorted(self._staged, key=str),
                "stats": stats})

    def _export_blob(self, msg) -> bytes:
        """Pop the staged handoff and serialize it — shared by the pipe
        dialect (base64 in the JSON reply) and the federation socket
        (raw blob frame)."""
        slot, req = self._staged.pop(msg["id"])
        payload = self.engine.export_handoff(slot, req)
        self._handles.pop(msg["id"], None)   # completion lands elsewhere
        return serialize_handoff(payload)

    def op_export(self, msg):
        self._reply({"op": "payload", "id": msg["id"],
                "blob": base64.b64encode(
                    self._export_blob(msg)).decode("ascii")})

    def _inject_payload(self, payload):
        rid = payload["request"]["request_id"]
        live = self.engine.inject_handoff(payload,
                                          on_token=self._on_token)
        if live is not None:
            self._handles[rid] = live
            self._admit_reported.add(rid)   # injection IS the admission
        self._reply({"op": "injected", "id": rid,
                "accepted": live is not None})

    def op_inject(self, msg):
        self._inject_payload(
            deserialize_handoff(base64.b64decode(msg["blob"])))

    def op_slot_cap(self, msg):
        """Rolling-update drain lever: the parent squeezes this
        replica's admission cap over the wire (the PR 10 slot-cap path)
        so in-flight requests finish while nothing new is admitted."""
        self.engine.set_slot_cap(int(msg["n"]))
        self._reply({"op": "slot_capped", "n": int(msg["n"]),
                     "iteration": self.engine.iteration})

    def op_swap(self, msg):
        """Rolling weight update: rebuild the engine from a new spec
        (checkpoint or model seed). Refused while requests are in
        flight — the parent drains first; a swap must never drop work."""
        if self._handles and not all(r.done for r in self._handles.values()):
            self._reply({"op": "error",
                         "detail": "swap refused: requests in flight"})
            return
        spec = dict(self._spec)
        spec.update(msg.get("spec") or {})
        self.engine.close()
        self._spec = spec
        self.engine = _build_engine(spec)
        if self.role == "prefill":
            self.engine.set_prefill_role(True)
        telemetry_port = self._start_telemetry(spec)
        self._handles.clear()
        self._reported.clear()
        self._admit_reported.clear()
        self._events = []
        self._staged.clear()
        self._reply({"op": "swapped", "replica_id": self.replica_id,
                     "telemetry_port": telemetry_port,
                     "iteration": self.engine.iteration})

    def op_trace_dump(self, msg):
        """Ship this worker's recorded span stream as Chrome-trace
        event dicts (JSON-able) for fleet-level stitching."""
        from ...observability.trace import active_tracer, chrome_trace_events
        tracer = active_tracer()
        events = chrome_trace_events(tracer.events) if tracer else []
        self._reply({"op": "trace", "replica_id": self.replica_id,
                "events": events})

    def serve(self):
        for line in sys.stdin:
            line = line.strip()
            if not line:
                continue
            msg = json.loads(line)
            op = msg.get("op")
            if op == "stop":
                break
            handler = getattr(self, f"op_{op}", None)
            if handler is None:
                self._reply({"op": "error", "detail": f"unknown op {op!r}"})
                continue
            try:
                handler(msg)
            except Exception as e:   # ds-tpu: lint-ok[PY001] — the
                # protocol boundary: an op failure must reach the parent
                # as a typed error reply, never kill the pipe silently
                self._reply({"op": "error", "detail": f"{op}: {e}"})
        self.engine.close()
        self._reply({"op": "bye"})


def main():
    from ...utils.host_env import honor_jax_platforms_env
    honor_jax_platforms_env()
    first = sys.stdin.readline()
    if not first:
        return 2
    spec = json.loads(first)
    if spec.get("op") != "init":
        _reply({"op": "error", "detail": "first line must be the init spec"})
        return 2
    _Worker(spec).serve()
    return 0


if __name__ == "__main__":
    sys.exit(main())
