"""Fleet configuration (the ``serving.fleet`` sub-block).

Stdlib-only (same contract as ``serving/config.py``): ``runtime/
config.py`` reaches this dataclass through ``ServingConfig``, and that
import path must stay jax-free for the dependency-free tooling jobs
(ds_tpu_lint in CI).

Reference frame: DeepSpeed-Inference's multi-GPU serving architecture
(arXiv:2207.00032) — the layer ABOVE one engine: N supervised replicas,
a prefix-affinity front-end router, and (optionally) disaggregated
prefill/decode where prompt-heavy replicas hand page-granular KV to
decode replicas so bursty prompt traffic cannot starve steady-state
decode (docs/serving.md "Multi-replica fleet").
"""

from dataclasses import dataclass, field
from typing import Optional

from .supervision import SupervisionConfig

ROUTERS = ("prefix_affinity", "least_loaded")
BACKENDS = ("inprocess", "process")


@dataclass
class FleetConfig:
    """Multi-replica serving knobs.

    Every replica runs the SAME ``ServingConfig`` (minus this block):
    identical compiled shapes, identical page geometry — which is what
    makes the page handoff a transfer instead of a recompute, and what
    keeps routing decisions replayable (the router only ever reads
    deterministic per-replica state on the fleet step clock).
    """
    enabled: bool = True
    replicas: int = 2                # engines at fleet start
    backend: str = "inprocess"       # "inprocess" = N engines, one
                                     # process, lockstep clock (the
                                     # deterministic/CI path);
                                     # "process" = one worker subprocess
                                     # per replica (fleet/worker.py line
                                     # protocol + /healthz endpoint)
    router: str = "prefix_affinity"  # dispatch policy: route to the
                                     # replica whose radix prefix cache
                                     # most likely holds the prompt head,
                                     # least-loaded fallback; or pure
                                     # "least_loaded"
    affinity_queue_factor: float = 2.0
                                     # affinity yields to least-loaded
                                     # when the affine replica's queue
                                     # exceeds factor * slot_cap (a hot
                                     # prefix must not melt one replica)
    affinity_index_size: int = 512   # prompt-head runs remembered per
                                     # replica (LRU) by the router
    disaggregate: bool = False       # split roles: prefill replicas run
                                     # chunked prefill + first token then
                                     # hand page-granular KV to decode
                                     # replicas (requires serving.paging)
    prefill_replicas: int = 1        # leading replicas that take the
                                     # prefill role when disaggregated
    health_every_steps: int = 8      # fleet steps between health sweeps
    max_missed_health: int = 2       # consecutive missed checks before a
                                     # replica is declared dead and its
                                     # in-flight requests requeue through
                                     # the router
    autoscale: bool = False          # act on ServingAutoscaler
                                     # target_replicas: spawn on
                                     # sustained backlog, drain via the
                                     # preemption/slot-cap path on
                                     # scale-down
    min_replicas: int = 1
    max_replicas: int = 8
    autoscale_every_steps: int = 16  # fleet steps between autoscaler
                                     # observations
    replica_telemetry: bool = False  # per-replica /metrics endpoints on
                                     # ephemeral ports (the router-level
                                     # endpoint is separate — see
                                     # ServingFleet.start_telemetry)
    aggregate_telemetry: bool = True # fleet telemetry aggregator
                                     # (observability/fleet.py): poll
                                     # every replica (scrape or direct
                                     # snapshot) on the cadence below and
                                     # serve the merged view from the
                                     # router's /metrics + /statusz
    aggregate_every_steps: int = 8   # fleet steps between aggregator
                                     # polls (bounded cadence — never per
                                     # engine step)
    stale_after_s: float = 30.0      # a replica whose last successful
                                     # sample is older than this reads
                                     # ``stale`` in the aggregated view
                                     # (dead vs one dropped scrape)
    replica_trace: bool = False      # process workers activate a span
                                     # tracer so their dumps can be
                                     # stitched into one fleet Chrome
                                     # trace (stitched_trace()); the
                                     # in-process backend records into
                                     # the router's own tracer
    flight_recorder_events: int = 256
                                     # fleet-level request-lifecycle ring
                                     # (submit/admit/handoff/failover/
                                     # finish on the fleet step clock);
                                     # 0 disables
    worker_reply_timeout_s: float = 120.0
                                     # process backend: how long the
                                     # manager waits on one worker reply
                                     # before declaring the pipe wedged
                                     # (WorkerProtocolError -> death ->
                                     # supervision)
    supervision: Optional[SupervisionConfig] = field(default=None)
                                     # self-healing policy (restart with
                                     # backoff, crash-loop retirement,
                                     # degraded disaggregation, handoff
                                     # retry budget); absent = defaults
                                     # (ENABLED — supervision.enabled:
                                     # false restores fatal/no-respawn
                                     # PR-12 semantics)
    federation: Optional["FederationConfig"] = field(default=None)
                                     # cross-host federation (socket
                                     # transport for remote non-child
                                     # replicas, HTTP front-end, rolling
                                     # update policy); absent/None =
                                     # single-host fleet, no peers — the
                                     # manager still reads rolling
                                     # defaults from None safely
    slo: Optional["SloConfig"] = field(default=None)
                                     # declarative SLO watch over the
                                     # aggregated telemetry sample
                                     # (observability/slo.py): fire/
                                     # clear hysteresis, bounded
                                     # incident log; absent = defaults
                                     # (watch DISABLED — slo.enabled:
                                     # true arms it)

    def __post_init__(self):
        # nested-dict lift, same contract as ServingConfig.__post_init__
        # ({"serving": {"fleet": {"supervision": {...}}}} arrives as a
        # plain dict); None means "all defaults", which keeps the
        # manager's config reads unconditional
        if self.supervision is None:
            self.supervision = SupervisionConfig()
        elif isinstance(self.supervision, dict):
            self.supervision = SupervisionConfig(**self.supervision)
        if isinstance(self.federation, dict):
            from .federation.config import FederationConfig
            self.federation = FederationConfig(**self.federation)
        if self.slo is None:
            from deepspeed_tpu.observability.slo import SloConfig
            self.slo = SloConfig()
        elif isinstance(self.slo, dict):
            from deepspeed_tpu.observability.slo import SloConfig
            self.slo = SloConfig(**self.slo)

    def validate(self, serving_config=None) -> "FleetConfig":
        if self.replicas < 1:
            raise ValueError(
                f"serving.fleet.replicas must be >= 1, got {self.replicas}")
        if self.backend not in BACKENDS:
            raise ValueError(
                f"serving.fleet.backend must be one of {BACKENDS}, got "
                f"{self.backend!r}")
        if self.router not in ROUTERS:
            raise ValueError(
                f"serving.fleet.router must be one of {ROUTERS}, got "
                f"{self.router!r}")
        if self.affinity_queue_factor <= 0:
            raise ValueError(
                "serving.fleet.affinity_queue_factor must be > 0, got "
                f"{self.affinity_queue_factor}")
        if self.affinity_index_size < 1:
            raise ValueError(
                "serving.fleet.affinity_index_size must be >= 1, got "
                f"{self.affinity_index_size}")
        if self.disaggregate:
            if self.replicas < 2:
                raise ValueError(
                    "serving.fleet.disaggregate needs >= 2 replicas "
                    "(at least one prefill and one decode), got "
                    f"{self.replicas}")
            if not 1 <= self.prefill_replicas < self.replicas:
                raise ValueError(
                    f"serving.fleet.prefill_replicas must satisfy 1 <= n "
                    f"< replicas ({self.replicas}), got "
                    f"{self.prefill_replicas}")
            if serving_config is not None and not serving_config.paged:
                raise ValueError(
                    "serving.fleet.disaggregate requires the block-paged "
                    "KV cache (serving.paging) — the prefill->decode "
                    "handoff is a page transfer")
        if self.health_every_steps < 1:
            raise ValueError(
                "serving.fleet.health_every_steps must be >= 1, got "
                f"{self.health_every_steps}")
        if self.max_missed_health < 1:
            raise ValueError(
                "serving.fleet.max_missed_health must be >= 1, got "
                f"{self.max_missed_health}")
        if self.min_replicas < 1 or self.max_replicas < self.min_replicas:
            raise ValueError(
                "serving.fleet needs 1 <= min_replicas <= max_replicas, "
                f"got min={self.min_replicas} max={self.max_replicas}")
        if self.autoscale_every_steps < 1:
            raise ValueError(
                "serving.fleet.autoscale_every_steps must be >= 1, got "
                f"{self.autoscale_every_steps}")
        if self.aggregate_every_steps < 1:
            raise ValueError(
                "serving.fleet.aggregate_every_steps must be >= 1, got "
                f"{self.aggregate_every_steps}")
        if self.stale_after_s <= 0:
            raise ValueError(
                "serving.fleet.stale_after_s must be > 0, got "
                f"{self.stale_after_s}")
        if self.flight_recorder_events < 0:
            raise ValueError(
                "serving.fleet.flight_recorder_events must be >= 0 "
                f"(0 disables), got {self.flight_recorder_events}")
        if self.worker_reply_timeout_s <= 0:
            raise ValueError(
                "serving.fleet.worker_reply_timeout_s must be > 0, got "
                f"{self.worker_reply_timeout_s}")
        self.supervision.validate()
        self.slo.validate()
        if self.federation is not None:
            self.federation.validate()
            if len(self.federation.peers) > self.replicas:
                raise ValueError(
                    "serving.fleet.federation.peers lists "
                    f"{len(self.federation.peers)} peers but the fleet "
                    f"only has {self.replicas} replicas — peers fill the "
                    "leading replica ids")
        if self.disaggregate and self.min_replicas < 2:
            # a disaggregated fleet can never drain below one prefill +
            # one decode replica
            self.min_replicas = 2
        return self

    def role_for(self, replica_id: int) -> str:
        """Role of replica ``replica_id`` at spawn: the leading
        ``prefill_replicas`` take the prefill role when disaggregated,
        everything else serves end-to-end ("full") or decode-only."""
        if not self.disaggregate:
            return "full"
        return ("prefill" if replica_id < self.prefill_replicas
                else "decode")
