"""Replica manager + fleet front-end: the layer above one engine.

``ServingFleet`` owns N supervised replicas (``fleet/replica.py``), a
prefix-affinity router (``fleet/router.py``), the disaggregated
prefill->decode page-handoff pump, dead-replica failover, and the
closed autoscaling loop (``elasticity/serving_autoscaler.py``
``target_replicas`` finally ACTS here: sustained backlog spawns
replicas, scale-down drains through the PR-10 preemption/slot-cap path).

The fleet runs on its own deterministic step clock: one ``advance()``
advances every live replica one engine iteration (lockstep), then moves
handoffs, detects deaths, and evaluates scaling. Every decision reads
host ints snapshotted on that clock, so a replayed trace reproduces the
same dispatch/handoff/failover sequence bit-exactly — the engine-level
replay discipline, one level up.

Clients hold ``FleetRequest`` handles: one stable object per request no
matter how many replicas serve it (prefill -> decode handoff, failover
re-prefill). Tokens stream into the handle from whichever replica
currently owns the request; under greedy sampling the merged stream is
bit-equal to a single uninterrupted engine (the QoS resume guarantee,
inherited wholesale).
"""

import time
from collections import deque
from dataclasses import replace
from typing import Dict, List, Optional

import numpy as np

from ...observability.fleet import (FleetTelemetryAggregator,
                                    FlightRecorder, make_trace_id,
                                    per_request_breakdown)
from ...observability.metrics import get_registry, percentile
from ...utils.logging import log_dist
from ..request import Request
from .config import FleetConfig
from .handoff import (HandoffError, deserialize_handoff,
                      serialize_handoff, stamp_handoff, verify_handoff)
from .replica import (LocalReplica, ProcessReplica, ReplicaCrash,
                      ReplicaDead)
from .router import Router
from .supervision import ReplicaSupervisor, SupervisionConfig

TERMINAL = ("finished", "timeout", "cancelled", "shed")
LOG_LIMIT = 4096     # dispatch/handoff log entries kept (replay asserts
                     # run over bounded traces; a long-lived server must
                     # not grow them forever)
DEAD_REPLICAS_KEPT = 16   # corpse history: dead replicas stay readable
                          # in snapshots (their served work must not
                          # vanish) up to this many; older ones are
                          # pruned — a supervised fleet restarts without
                          # bound and must not do O(ever-spawned) work
                          # per step


class FleetRequest:
    """One client request as the FLEET sees it: a stable handle whose
    tokens/status survive handoffs and replica deaths. Field names
    mirror ``serving.request.Request`` so the bench/CLI reporting paths
    work on either."""

    def __init__(self, prompt, max_new_tokens: int, request_id,
                 priority: int = 0, on_token=None, trace_id=None):
        self.request_id = request_id
        # the distributed trace identity: stamped by the fleet at
        # submit, propagated to every replica that ever serves this
        # request (worker protocol + handoff wire) so one id joins its
        # spans and lifecycle events fleet-wide
        self.trace_id = trace_id
        self.prompt = np.asarray(prompt, np.int32).reshape(-1)
        self.max_new_tokens = int(max_new_tokens)
        self.priority = int(priority)
        self.on_token = on_token
        self.tokens: List[int] = []
        self.status = "queued"
        self.shed_reason: Optional[str] = None
        self.replica_id: Optional[int] = None   # current owner (None
                                                # while a handoff is in
                                                # transit)
        self.prefill_replica_id: Optional[int] = None
        self.handoffs = 0
        self.failovers = 0
        self.preemptions = 0
        self.weights_version = 0    # version of the replica that served
                                    # this request (rolling updates bump
                                    # it; the parity tests split on it)
        self._inner: Optional[Request] = None   # local-backend engine req
        self.submitted_at = time.perf_counter()
        self.first_token_at: Optional[float] = None
        self.finished_at: Optional[float] = None
        # fleet-clock stamps (deterministic run-to-run)
        self.submitted_iteration: Optional[int] = None
        self.admitted_iteration: Optional[int] = None
        self.first_token_iteration: Optional[int] = None
        self.finished_iteration: Optional[int] = None

    @property
    def done(self) -> bool:
        return self.status in TERMINAL

    @property
    def output_tokens(self) -> List[int]:
        return list(self.tokens)

    @property
    def ttft_s(self) -> Optional[float]:
        if self.first_token_at is None:
            return None
        return self.first_token_at - self.submitted_at

    @property
    def latency_s(self) -> Optional[float]:
        if self.finished_at is None:
            return None
        return self.finished_at - self.submitted_at

    def remaining_budget(self) -> int:
        return self.max_new_tokens - len(self.tokens)

    def effective_prompt(self) -> np.ndarray:
        if not self.tokens:
            return self.prompt
        return np.concatenate(
            [self.prompt, np.asarray(self.tokens, np.int32)])

    def __repr__(self):
        return (f"FleetRequest(id={self.request_id!r}, "
                f"status={self.status}, replica={self.replica_id}, "
                f"generated={len(self.tokens)}/{self.max_new_tokens}, "
                f"handoffs={self.handoffs}, failovers={self.failovers})")


class ServingFleet:
    """N supervised replicas behind one prefix-affinity front end.

    Usage (the single-engine surface, one level up)::

        fleet = ServingFleet(module, params, cfg)   # cfg.fleet block set
        reqs = [fleet.submit(p, max_new_tokens=32) for p in prompts]
        fleet.run()
        reqs[0].output_tokens
        fleet.close()

    ``backend="process"`` ignores ``module/params`` and spawns
    ``fleet/worker.py`` subprocesses from ``spec`` (model/checkpoint +
    serving config dict) — each its own device world and telemetry
    endpoint.
    """

    def __init__(self, module, params, config, *, spec: Optional[dict] =
                 None, monitor=None):
        from ..config import ServingConfig
        if isinstance(config, dict):
            config = ServingConfig(**config)
        self.config = config.validate()
        if not self.config.fleet_enabled:
            raise ValueError("ServingFleet needs an enabled serving.fleet "
                             "block (plain ServingEngine serves without "
                             "one)")
        self.fcfg: FleetConfig = self.config.fleet
        self._module = module
        self._params = params
        # replicas never see the fleet block: a replica IS the leaf
        self._replica_config = replace(self.config, fleet=None)
        self._spec = spec
        if self.fcfg.backend == "process" and spec is None:
            raise ValueError(
                "backend='process' needs spec= (model/checkpoint + "
                "serving config dict) — workers rebuild the engine from "
                "it")
        # -- federation (remote peers + HTTP front-end + rolling) ----------
        self.fedcfg = self.fcfg.federation
        self._peers = list(self.fedcfg.peers) if self.fedcfg else []
        if self._peers and spec is None:
            raise ValueError(
                "serving.fleet.federation.peers needs spec= — remote "
                "workers rebuild their engine from it over the wire")
        self._lineage_peer: Dict[int, str] = {}   # lineage -> address:
                                                  # a remote restart is a
                                                  # RE-DIAL of its peer
        self._lineage_epoch: Dict[int, int] = {}  # lineage -> incarnation
                                                  # epoch stamped into
                                                  # every request so a
                                                  # zombie's delayed
                                                  # reply is fenced
        self._draining = set()      # rids excluded from dispatch while a
                                    # rolling update drains them
        self._frontend = None       # FleetFrontend (drained each step)
        self.rolling = None         # in-flight RollingUpdate
        self.weights_version = 0    # bumped when a rolling update lands
        self.rolling_updates = 0    # completed updates
        self.rolling_swaps = 0      # individual replicas swapped
        page_len = (self.config.paging.page_len if self.config.paged
                    else self.config.prefill_bucket)
        self.router = Router(self.fcfg, page_len)
        self._replicas: Dict[int, object] = {}
        self._next_rid = 0
        self._failed = set()            # rids whose failover already ran
        self._handles: Dict[object, FleetRequest] = {}   # LIVE handles
        self._handoff_backlog = deque() # [{"payload","handle","attempts",
                                        #   "not_before"}]
        self._iteration = 0
        self.dispatch_log: List[tuple] = []   # (request_id, replica_id)
        self.handoff_log: List[tuple] = []    # (request_id, src, dst) —
                                              # capped at LOG_LIMIT
        self.handoffs_completed = 0           # monotonic (the log trims)
        self.failovers = 0
        self.replicas_spawned = 0
        self.replicas_retired = 0
        self.dead_replicas = 0
        self.requests_submitted = 0
        self.requests_finished = 0
        self.requests_shed = 0
        self.last_scale_decision: Optional[dict] = None
        self.telemetry = None
        # -- supervision (the self-healing layer) --------------------------
        self.scfg: SupervisionConfig = self.fcfg.supervision
        self._supervised = bool(self.scfg.enabled)
        self.supervisor = ReplicaSupervisor(self.scfg)
        self._lineage: Dict[int, int] = {}   # rid -> lineage id
        self.replica_restarts = 0       # incarnations respawned
        self.handoffs_dropped = 0       # payloads past the retry budget
        self.handoff_retries = 0        # FAILED injection attempts
        self.degraded = False           # prefill pool empty: decode
                                        # replicas run their own chunked
                                        # prefill until one returns
        self.degraded_entered = 0
        self._orphans = deque()         # handles waiting for a restart
                                        # (no dispatchable replica when
                                        # they needed one)
        self._protocol_errors_pruned = 0
                                        # protocol errors carried from
                                        # pruned corpses (the snapshot
                                        # counter must never decrease)
        self.chaos_corrupt_handoffs = 0 # chaos hook: truncate the next N
                                        # handoff payloads in transit
                                        # (models wire corruption)
        self.chaos_flip_handoff_bits = 0
                                        # chaos hook: flip ONE byte in
                                        # the next N handoff payloads
                                        # AFTER the digest stamp — the
                                        # flipped-bit case only the v3
                                        # integrity digest catches
        self.handoffs_rejected_corrupt = 0
                                        # payloads refused by the
                                        # pre-injection digest gate (a
                                        # flipped bit never enters a KV
                                        # pool)
        self._stale_fence_pruned = [0, 0]
                                        # [stale_epoch, duplicate] reply
                                        # counts carried from pruned
                                        # corpses (snapshot counters
                                        # must never decrease)
        # fleet-level flight recorder: request lifecycle events on the
        # FLEET step clock (submit/admit/first_token/handoff/failover/
        # terminal) — the per-request waterfall's input and the crash
        # path's last-N-requests timeline
        self.recorder = FlightRecorder(self.fcfg.flight_recorder_events)
        # bounded-cadence telemetry aggregator: every replica's metrics
        # (scraped or direct) merged into one fleet view served from
        # the router process
        self._aggregator = (
            FleetTelemetryAggregator(stale_after_s=self.fcfg.stale_after_s)
            if self.fcfg.aggregate_telemetry else None)
        # declarative SLO watch (observability/slo.py): evaluated on
        # the aggregation cadence against a sample built from the
        # fleet's own books — deterministic on the fleet step clock
        self.slo_watch = None
        if self.fcfg.slo is not None and self.fcfg.slo.enabled:
            from ...observability.slo import SloWatch
            self.slo_watch = SloWatch.from_config(self.fcfg.slo)
        self._scaler = None
        if self.fcfg.autoscale:
            from ...elasticity.serving_autoscaler import (
                ServingAutoscaleConfig, ServingAutoscaler)
            from ...observability.metrics import MetricsRegistry
            self._scale_registry = MetricsRegistry()
            self._scaler = ServingAutoscaler(
                engine=None,
                config=ServingAutoscaleConfig(
                    min_slots=1, max_replicas=self.fcfg.max_replicas),
                registry=self._scale_registry,
                replica_slots=self.config.num_slots)
        for i in range(self.fcfg.replicas):
            # peers fill the LEADING replica ids so role_for assigns
            # disaggregated roles to remote peers exactly as to locals
            self._spawn_replica(
                peer=self._peers[i] if i < len(self._peers) else None)
        self.replicas_spawned = 0       # construction is not a scale-up
        log_dist(
            f"serving fleet: {len(self._replicas)} replicas "
            f"({self.fcfg.backend}, router={self.fcfg.router}"
            f"{', disaggregated ' + str(self.fcfg.prefill_replicas) + ' prefill' if self.fcfg.disaggregate else ''})",
            ranks=[0])

    # -- replica lifecycle -------------------------------------------------
    def _spawn_replica(self, role: Optional[str] = None,
                       lineage: Optional[int] = None,
                       peer: Optional[str] = None):
        rid = self._next_rid
        self._next_rid += 1
        role = role or self.fcfg.role_for(rid)
        if lineage is None:
            lineage = self.supervisor.register(role)
        if peer is None:
            # a remote lineage restarts by RE-DIALING its peer: the
            # engine on the other end survives a dropped connection
            peer = self._lineage_peer.get(lineage)
        self._lineage[rid] = lineage
        # the aggregator needs a scrape target, so a process/remote
        # replica under aggregation always gets an endpoint even when
        # per-replica telemetry wasn't asked for explicitly
        want_port = (self.fcfg.replica_telemetry
                     or self._aggregator is not None)
        if peer is not None:
            from .federation.remote import RemoteReplica
            self._lineage_peer[lineage] = peer
            # per-incarnation epoch: every re-dial of this lineage gets
            # the next epoch, so a pre-restart incarnation's delayed
            # reply can never be applied by its successor
            epoch = self._lineage_epoch.get(lineage, -1) + 1
            self._lineage_epoch[lineage] = epoch
            fed = self.fedcfg
            rep = RemoteReplica(
                rid, role, peer,
                {**self._spec,
                 "telemetry_port": 0 if want_port else None,
                 # bugfix: the worker must bind /metrics on the dialed
                 # interface, and the router scrapes that same host —
                 # no localhost assumption on either end
                 "telemetry_host": peer.rpartition(":")[0],
                 "trace": self.fcfg.replica_trace},
                connect_timeout_s=fed.connect_timeout_s,
                reply_timeout_s=fed.reply_timeout_s,
                max_frame_bytes=fed.max_frame_bytes,
                epoch=epoch,
                heartbeat_timeout_s=fed.heartbeat_timeout_s,
                send_timeout_s=fed.send_timeout_s)
        elif self.fcfg.backend == "process":
            rep = ProcessReplica(rid, role,
                                 {**self._spec,
                                  "telemetry_port": 0 if want_port
                                  else None,
                                  "trace": self.fcfg.replica_trace},
                                 reply_timeout_s=self.fcfg
                                 .worker_reply_timeout_s)
        else:
            rep = LocalReplica(rid, role, self._module, self._params,
                               self._replica_config,
                               telemetry=self.fcfg.replica_telemetry)
        # spawns during/after a rolling update serve the NEW weights
        # (the update stamps _module/_params/_spec at start)
        rep.weights_version = (self.rolling.version
                               if self.rolling is not None
                               and not self.rolling.done
                               else self.weights_version)
        self._replicas[rid] = rep
        if self._aggregator is not None:
            if rep.backend != "inprocess" and rep.telemetry_port:
                # reuse the replica's cached client: health sweeps and
                # aggregator polls accumulate one staleness stamp
                self._aggregator.add_scrape(rid, client=rep.scrape_client)
            else:
                self._aggregator.add_direct(rid, rep.metrics_sample)
        self.replicas_spawned += 1
        return rep

    def kill_replica(self, rid: int):
        """Hard-kill one replica (the chaos/failover hook): the next
        ``advance()`` detects the death and requeues its in-flight
        requests through the router."""
        self._replicas[rid].kill()

    def _alive(self, roles=None) -> List[int]:
        return [rid for rid, rep in sorted(self._replicas.items())
                if rep.alive and (roles is None or rep.role in roles)]

    def _stats(self, rids) -> List:
        out = []
        for r in rids:
            s = self._replicas[r].stats()
            if self._replicas[r].backend == "remote":
                # scrape-driven routing (the deferred PR-12 half): a
                # remote peer's synchronous stats ride the advance
                # reply, but between replies its aggregator sample is
                # the fresher load signal — stamp it so the router can
                # weigh both (scraped off-step, read on-step: for a
                # given scrape history the route replays bit-exactly)
                s.scraped_load = self._scraped_load(r)
            out.append(s)
        return out

    def _scraped_load(self, rid) -> Optional[float]:
        if self._aggregator is None:
            return None
        entry = self._aggregator.replicas.get(rid)
        sample = entry.get("sample") if entry else None
        if not sample:
            return None
        total, seen = 0.0, False
        for suffix in ("serving_queue_depth", "serving_active_slots"):
            for key, value in sample.items():
                if key.endswith(suffix):
                    total += float(value)
                    seen = True
                    break
        return total if seen else None

    def _submit_roles(self):
        if not self.fcfg.disaggregate:
            return ("full",)
        # degraded disaggregation: with the prefill pool empty, decode
        # replicas temporarily take submissions end-to-end (their own
        # chunked prefill) instead of stranding the queue
        return ("decode",) if self.degraded else ("prefill",)

    def _dispatchable(self, rids: List[int]) -> List[int]:
        """Filter a live-replica list down to the ones the aggregated
        telemetry considers dispatch-healthy (``up`` and not stale).
        Never empties the list on telemetry alone — with every replica
        stale the fleet still dispatches rather than bricking on its
        own observability plane. Replicas a rolling update is draining
        are excluded first (they finish what they own, take nothing
        new), with the same never-empty fallback."""
        undrained = [r for r in rids if r not in self._draining]
        rids = undrained if undrained else rids
        if self._aggregator is None:
            return rids
        healthy = [r for r in rids if self._aggregator.healthy(r)]
        return healthy if healthy else rids

    def _park(self, handle: FleetRequest):
        """No dispatchable replica right now but capacity is coming
        back (a pending restart, or degraded mode about to cover the
        missing role): hold the handle until it does (re-dispatched
        FIFO from ``advance()``)."""
        handle.replica_id = None
        self._handles[handle.request_id] = handle
        self._orphans.append(handle)
        self.recorder.record("parked", request_id=handle.request_id,
                             trace_id=handle.trace_id,
                             iteration=self._iteration)

    def _can_wait_for_capacity(self) -> bool:
        """Parking beats raising when capacity will return: a restart
        is scheduled, or the fleet is disaggregated with live decode
        replicas (degraded mode covers a lost prefill pool on the next
        fleet step)."""
        if not self._supervised:
            return False
        if self.supervisor.pending():
            return True
        return bool(self.fcfg.disaggregate and self._alive(("decode",)))

    # -- client API --------------------------------------------------------
    def submit(self, prompt, max_new_tokens: Optional[int] = None,
               request_id=None, priority: int = 0,
               on_token=None, trace_id=None) -> FleetRequest:
        """Route one request to a replica (prefix affinity or least
        loaded) and return its fleet-level handle. ``trace_id`` lets a
        front-end mint the id at accept time (so the HTTP reply can
        carry it before dispatch); None derives it here as before."""
        if max_new_tokens is None:
            max_new_tokens = self.config.default_max_new_tokens
        if request_id is None:
            request_id = f"f{self.requests_submitted}"
        eligible = self._dispatchable(self._alive(self._submit_roles()))
        if not eligible and not self._can_wait_for_capacity():
            raise RuntimeError("fleet: no live replica accepts submissions")
        handle = FleetRequest(prompt, max_new_tokens, request_id,
                              priority=priority, on_token=on_token,
                              trace_id=trace_id or make_trace_id(
                                  request_id, self.requests_submitted))
        handle.submitted_iteration = self._iteration
        self.requests_submitted += 1
        if not eligible:
            self.recorder.record("submit", request_id=request_id,
                                 trace_id=handle.trace_id,
                                 replica_id=None,
                                 iteration=self._iteration,
                                 prompt_len=int(handle.prompt.shape[0]))
            self._park(handle)      # supervision will bring one back
            return handle
        target = self.router.route(
            np.asarray(prompt, np.int32), self._stats(eligible),
            step=self._iteration, request_id=request_id)
        self.dispatch_log.append((request_id, target))
        del self.dispatch_log[:-LOG_LIMIT]
        self.recorder.record("submit", request_id=request_id,
                             trace_id=handle.trace_id, replica_id=target,
                             iteration=self._iteration,
                             prompt_len=int(handle.prompt.shape[0]))
        self._dispatch(handle, target, handle.prompt, max_new_tokens)
        return handle

    def _on_token_cb(self, handle: FleetRequest):
        def cb(_req, token):
            if handle.first_token_at is None:
                handle.first_token_at = time.perf_counter()
                handle.first_token_iteration = self._iteration
                self.recorder.record(
                    "first_token", request_id=handle.request_id,
                    trace_id=handle.trace_id,
                    replica_id=handle.replica_id,
                    iteration=self._iteration)
            handle.tokens.append(int(token))
            if handle.on_token is not None:
                handle.on_token(handle, int(token))
        return cb

    def _dispatch(self, handle: FleetRequest, rid: int, prompt,
                  max_new: int):
        rep = self._replicas[rid]
        handle.replica_id = rid
        handle.weights_version = getattr(rep, "weights_version", 0)
        if handle.prefill_replica_id is None:
            handle.prefill_replica_id = rid
        if rep.backend == "inprocess":
            inner = rep.submit(prompt, max_new,
                               request_id=handle.request_id,
                               priority=handle.priority,
                               on_token=self._on_token_cb(handle),
                               trace_id=handle.trace_id)
            handle._inner = inner
            if inner.done:          # QoS shed/refused at submit
                self._finalize(handle, inner.status, inner.shed_reason)
                return
        else:
            try:
                reply = rep.submit(prompt, max_new,
                                   request_id=handle.request_id,
                                   priority=handle.priority,
                                   trace_id=handle.trace_id)
            except ReplicaDead:
                # undetected death discovered at dispatch time (e.g. an
                # OOM-killed worker between health sweeps): reroute NOW
                # — the request must not ride a corpse or get lost; the
                # death sweep reaps the replica next advance. Bounded:
                # each retry excludes one more dead replica.
                eligible = self._dispatchable(
                    self._alive(self._submit_roles()))
                if not eligible:
                    if self._can_wait_for_capacity():
                        self._park(handle)
                        return
                    raise RuntimeError(
                        "fleet: no live replica accepts submissions")
                target = self.router.route(
                    prompt, self._stats(eligible), step=self._iteration,
                    request_id=handle.request_id)
                self.dispatch_log.append((handle.request_id, target))
                del self.dispatch_log[:-LOG_LIMIT]
                return self._dispatch(handle, target, prompt, max_new)
            if reply.get("status") in TERMINAL:
                self._finalize(handle, reply["status"], None)
                return
        self._handles[handle.request_id] = handle

    def _finalize(self, handle: FleetRequest, status: str,
                  shed_reason=None):
        handle.status = status
        handle.shed_reason = shed_reason
        handle.finished_at = time.perf_counter()
        handle.finished_iteration = self._iteration
        handle._inner = None
        if status == "finished":
            self.requests_finished += 1
        elif status == "shed":
            self.requests_shed += 1
        self.recorder.record(status, request_id=handle.request_id,
                             trace_id=handle.trace_id,
                             replica_id=handle.replica_id,
                             iteration=self._iteration,
                             tokens=len(handle.tokens),
                             handoffs=handle.handoffs,
                             failovers=handle.failovers,
                             shed_reason=shed_reason)
        self._handles.pop(handle.request_id, None)

    # -- the fleet step ----------------------------------------------------
    def advance(self):
        """One fleet iteration: respawn replicas whose restart backoff
        elapsed, detect deaths and fail their requests over, advance
        every live replica one engine step (lockstep), harvest
        completions, pump page handoffs, run the health sweep and the
        autoscaler on their cadences."""
        if self._frontend is not None:
            # HTTP arrivals enter the deterministic clock HERE, in FIFO
            # mailbox order — handler threads never touch the fleet
            self._frontend.drain(self)
        self._supervise_tick()
        for rid, rep in sorted(self._replicas.items()):
            if not rep.alive and rid not in self._failed:
                self._fail_replica(rid)
        if not self._alive():
            if self._supervised and self.supervisor.pending():
                # every incarnation is down but restarts are scheduled:
                # this step only advances the backoff clock
                self._iteration += 1
                return
            raise RuntimeError(
                "fleet: every replica is dead — nothing left to serve "
                "the backlog")
        self._update_degraded()
        if self.fcfg.disaggregate and self.busy:
            for role in ("prefill", "decode"):
                if self._alive((role,)):
                    continue
                if role == "prefill" and self.degraded:
                    continue     # decode replicas are covering prefill
                if self._supervised and \
                        self.supervisor.pending((role, "full")):
                    continue     # a restart is due: wait, don't brick
                # a one-sided fleet can neither prefill nor finish and
                # nothing is coming back: fail loudly (containment =
                # partial snapshot + restart) instead of spinning on a
                # stalled backlog
                raise RuntimeError(
                    f"fleet: disaggregated fleet lost every {role} "
                    "replica — in-flight work cannot complete")
        self._redispatch_orphans()
        if self.rolling is not None and not self.rolling.done:
            self.rolling.tick(self)
        handoff_ready = []   # [(rid, id)] from process replicas
        for rid in self._alive():
            rep = self._replicas[rid]
            if rep.backend == "inprocess":
                try:
                    rep.advance()
                except Exception as e:   # ds-tpu: lint-ok[PY001] — the
                    # supervision boundary: ANY engine fault mid-advance
                    # (the ReplicaCrash chaos hook or a real XLA/host
                    # error) is one replica's death, not the fleet's
                    if not self._supervised:
                        raise    # PR-12 semantics: in-process crashes
                                 # are fatal without supervision
                    # contain it: the crashed engine is discarded
                    # wholesale (state untrustworthy), its requests fail
                    # over with tokens retained, and supervision decides
                    # restart vs crash-loop retirement
                    rep.alive = False
                    log_dist(f"fleet: replica {rid} crashed mid-advance "
                             f"({type(e).__name__}: {e}) — containing",
                             ranks=[0])
                    self._fail_replica(rid)
                    continue
            else:
                try:
                    reply = rep.advance()
                except ReplicaDead:
                    continue     # detected at the top of the next step
                except RuntimeError as e:
                    # the worker answered the advance op with a typed
                    # error reply: its ENGINE faulted mid-step (the pipe
                    # itself is fine, but the engine state is suspect) —
                    # one replica's fault must not kill the fleet loop
                    if not self._supervised:
                        raise
                    rep.alive = False
                    log_dist(f"fleet: replica {rid} advance failed "
                             f"({e}) — containing", ranks=[0])
                    self._fail_replica(rid)
                    continue
                self._apply_worker_reply(rid, reply)
                handoff_ready.extend((rid, hid)
                                     for hid in reply.get("handoff_ready",
                                                          []))
        self._record_admissions()
        self._harvest_local()
        self._pump_handoffs(handoff_ready)
        if self._iteration % self.fcfg.health_every_steps == 0:
            self._health_sweep()
        if self._scaler is not None and \
                self._iteration % self.fcfg.autoscale_every_steps == 0:
            self._autoscale_tick()
        if self._aggregator is not None and \
                self._iteration % self.fcfg.aggregate_every_steps == 0:
            # off-thread: a wedged replica endpoint (scrape timeout x
            # retry) must never stall the dispatch/harvest data plane
            self._aggregator.poll_async()
        if self.slo_watch is not None and \
                self._iteration % self.fcfg.aggregate_every_steps == 0:
            for rec in self.slo_watch.evaluate(self.slo_sample(),
                                               self._iteration):
                self.recorder.record(f"slo_{rec['event']}",
                                     iteration=self._iteration,
                                     rule=rec["rule"])
                log_dist(f"fleet: slo {rec['event']} rule="
                         f"{rec['rule']} step={rec['step']}", ranks=[0])
        self._iteration += 1

    @property
    def iteration(self) -> int:
        """Fleet step counter — the deterministic clock traces replay
        against (the fleet mirror of ``ServingEngine.iteration``)."""
        return self._iteration

    @property
    def busy(self) -> bool:
        return (bool(self._handles) or bool(self._handoff_backlog)
                or bool(self._orphans))

    # -- supervision (restart, backoff, crash-loop, degraded mode) ---------
    def _supervise_tick(self):
        """Spawn every lineage whose restart backoff elapsed. A spawn
        that fails (a worker that dies at init, say) reports straight
        back to the supervisor — it counts as another crash, so a
        deterministic init-crasher backs off and eventually retires
        instead of spinning the fleet step."""
        if not self._supervised:
            return
        for lid, role in self.supervisor.take_due(self._iteration):
            try:
                rep = self._spawn_replica(role=role, lineage=lid)
            except Exception as e:   # ds-tpu: lint-ok[PY001] — a failed
                # respawn must feed the crash-loop detector, never kill
                # the fleet step serving the survivors
                verdict = self.supervisor.on_death(lid, self._iteration)
                if verdict == "retired":
                    self._note_crash_loop_retirement(lid, role)
                log_dist(f"fleet: restart of lineage {lid} ({role}) "
                         f"failed ({e}) — {verdict}", ranks=[0])
                continue
            self.replica_restarts += 1
            get_registry().counter("fleet/replica_restarts").inc()
            self.recorder.record("replica_restarted",
                                 replica_id=rep.replica_id,
                                 iteration=self._iteration, lineage=lid)
            log_dist(f"fleet: supervision respawned lineage {lid} as "
                     f"replica {rep.replica_id} ({role})", ranks=[0])

    def _note_crash_loop_retirement(self, lid: int, role: str):
        self.replicas_retired += 1
        get_registry().counter("fleet/replicas_retired").inc()
        self.recorder.record("replica_retired", replica_id=None,
                             iteration=self._iteration, lineage=lid,
                             crash_loop=True)
        log_dist(f"fleet: lineage {lid} ({role}) crash-looped "
                 f"(> {self.scfg.max_restarts} deaths within "
                 f"{self.scfg.crash_window_steps} steps) — permanently "
                 "retired; serving continues on the survivors",
                 ranks=[0])

    def _update_degraded(self):
        """Degraded disaggregation: when the prefill pool empties while
        decode replicas survive, submissions run end-to-end on decode
        replicas (their own chunked prefill) instead of stranding the
        queue; exits automatically the step a prefill replica returns."""
        if not (self.fcfg.disaggregate and self._supervised):
            return
        prefill = self._alive(("prefill",))
        decode = self._alive(("decode",))
        if not self.degraded and not prefill and decode:
            self.degraded = True
            self.degraded_entered += 1
            get_registry().gauge("fleet/degraded_mode").set(1)
            get_registry().counter("fleet/degraded_entered").inc()
            self.recorder.record("degraded_enter",
                                 iteration=self._iteration)
            log_dist("fleet: prefill pool empty — degraded mode: decode "
                     "replicas run their own chunked prefill until a "
                     "prefill replica returns", ranks=[0])
        elif self.degraded and prefill:
            self.degraded = False
            get_registry().gauge("fleet/degraded_mode").set(0)
            self.recorder.record("degraded_exit",
                                 iteration=self._iteration)
            log_dist("fleet: prefill replica back — leaving degraded "
                     "mode", ranks=[0])

    def _redispatch_orphans(self):
        """Re-dispatch requests that were parked with no dispatchable
        replica (FIFO on the fleet clock — deterministic re-admission
        through the ordinary router/failover path, tokens retained)."""
        while self._orphans:
            eligible = self._dispatchable(
                self._alive(self._submit_roles()))
            if not eligible:
                return
            handle = self._orphans.popleft()
            if handle.done:
                continue
            target = self.router.route(
                handle.effective_prompt(), self._stats(eligible),
                step=self._iteration, request_id=handle.request_id)
            self.dispatch_log.append((handle.request_id, target))
            del self.dispatch_log[:-LOG_LIMIT]
            self._dispatch(handle, target, handle.effective_prompt(),
                           handle.remaining_budget())

    def run(self, max_iterations: Optional[int] = None):
        it = 0
        while self.busy:
            self.advance()
            it += 1
            if max_iterations is not None and it >= max_iterations:
                break

    def _record_admissions(self):
        """Stamp the fleet-clock admit mark for handles whose replica
        admitted them this step (in-process: the inner request
        transitioned out of the queue during ``rep.advance()``; process
        replicas report admitted ids in their advance reply). First
        admission only — the waterfall's queue stage ends exactly
        once."""
        for handle in self._handles.values():
            inner = handle._inner
            if (handle.admitted_iteration is None and inner is not None
                    and inner.admitted_iteration is not None):
                self._mark_admitted(handle)

    def _mark_admitted(self, handle: FleetRequest):
        handle.admitted_iteration = self._iteration
        self.recorder.record("admit", request_id=handle.request_id,
                             trace_id=handle.trace_id,
                             replica_id=handle.replica_id,
                             iteration=self._iteration)

    # -- harvest -----------------------------------------------------------
    def _harvest_local(self):
        for handle in list(self._handles.values()):
            inner = handle._inner
            if inner is not None and inner.done:
                self._finalize(handle, inner.status, inner.shed_reason)

    def _apply_worker_reply(self, rid: int, reply: dict):
        for hid in reply.get("admitted", []):
            handle = self._handles.get(hid)
            if (handle is not None and handle.replica_id == rid
                    and handle.admitted_iteration is None):
                self._mark_admitted(handle)
        for hid, token, _it in reply.get("events", []):
            handle = self._handles.get(hid)
            if handle is None or handle.replica_id != rid:
                continue
            if handle.first_token_at is None:
                handle.first_token_at = time.perf_counter()
                handle.first_token_iteration = self._iteration
                self.recorder.record("first_token", request_id=hid,
                                     trace_id=handle.trace_id,
                                     replica_id=rid,
                                     iteration=self._iteration)
            handle.tokens.append(int(token))
            if handle.on_token is not None:
                handle.on_token(handle, int(token))
        for rec in reply.get("finished", []):
            handle = self._handles.get(rec["id"])
            if handle is not None and handle.replica_id == rid:
                self._finalize(handle, rec["status"],
                               rec.get("shed_reason"))

    # -- disaggregated handoff pump ---------------------------------------
    def _stage_handoff(self, payload: dict, handle):
        """Queue one exported payload for injection. The integrity
        digest is stamped HERE for the in-process path (remote exports
        arrive digest-verified off the wire), so every staged payload
        is verifiable at injection time. Chaos hooks model transit
        damage: a truncated blob, or a single flipped byte the v3
        digest alone can catch."""
        if "digest" not in payload:
            stamp_handoff(payload)
        if self.chaos_corrupt_handoffs > 0:
            self.chaos_corrupt_handoffs -= 1
            blob = serialize_handoff(payload)
            payload = {"_truncated": blob[:max(8, len(blob) // 3)],
                       "request": payload["request"]}
        elif self.chaos_flip_handoff_bits > 0 and payload.get("kv"):
            self.chaos_flip_handoff_bits -= 1
            payload = dict(payload)
            payload["kv"] = [dict(rec) for rec in payload["kv"]]
            rec = payload["kv"][0]
            name = sorted(rec)[0]
            arr = np.ascontiguousarray(rec[name]).copy()
            arr.view(np.uint8).flat[0] ^= 0xFF   # the flipped bit
            rec[name] = arr
        if self.fedcfg is not None \
                and self.fedcfg.outbound_queue_limit > 0:
            # backpressure: a wedged/starved decode pool must cost
            # bounded memory — past the bound the OLDEST staged payload
            # is dropped and its request re-prefills through failover
            while len(self._handoff_backlog) >= \
                    self.fedcfg.outbound_queue_limit:
                oldest = self._handoff_backlog.popleft()
                self.handoffs_dropped += 1
                get_registry().counter("fleet/handoffs_dropped").inc()
                old_handle = oldest["handle"]
                log_dist(
                    "fleet: outbound handoff queue over "
                    f"{self.fedcfg.outbound_queue_limit} entries — "
                    "dropping the oldest payload "
                    f"({oldest['payload'].get('request', {}).get('request_id')!r}) "
                    "and re-prefilling through failover", ranks=[0])
                if old_handle is not None and not old_handle.done:
                    self._failover(old_handle)
        self._handoff_backlog.append(
            {"payload": payload, "handle": handle, "attempts": 0,
             "not_before": 0, "exported_at": self._iteration})

    def _pump_handoffs(self, process_ready):
        """Export every staged prefill and inject into the least-loaded
        dispatch-healthy decode replica. Backlog discipline
        (deterministic — FIFO on the fleet clock):

        - page/slot STARVATION on the target is backpressure, not a
          failure: the payload retries next step, unbudgeted;
        - injection ERRORS (corrupt payload, dead replica, worker error
          reply) are retried with exponential fleet-step backoff and a
          bounded budget (``supervision.handoff_max_retries``); past it
          the payload is dropped and the request re-prefills through
          the ordinary failover path — tokens retained, token-exact,
          never stranded."""
        for rid in self._alive(("prefill",)):
            rep = self._replicas[rid]
            if rep.backend != "inprocess":
                continue
            for slot, req in rep.take_handoff_ready():
                handle = self._handles.get(req.request_id)
                payload = rep.export_handoff(slot, req)
                if handle is not None:
                    handle.replica_id = None       # in transit
                self._record_handoff_export(payload, rid)
                self._stage_handoff(payload, handle)
        for rid, hid in process_ready:
            rep = self._replicas[rid]
            if not rep.alive:
                continue
            handle = self._handles.get(hid)
            try:
                payload = rep.export_handoff_by_id(hid)
            except ReplicaDead:
                continue       # the death sweep requeues from the handle
            except (HandoffError, RuntimeError, ValueError) as e:
                # the export failed without killing the pipe: a torn
                # blob (HandoffError/binascii), or the worker's op_export
                # faulted and answered with a typed error reply
                # (RuntimeError). The staged state is gone either way —
                # nothing to retry; re-prefill the request elsewhere
                # rather than letting one replica's fault crash the
                # fleet loop
                log_dist(f"fleet: handoff export from replica {rid} "
                         f"failed ({e}) — failing the request over",
                         ranks=[0])
                self._count_if_digest_reject(e)
                self.handoffs_dropped += 1
                get_registry().counter("fleet/handoffs_dropped").inc()
                if handle is not None and not handle.done:
                    self._failover(handle)
                continue
            if handle is not None:
                handle.replica_id = None
            self._record_handoff_export(payload, rid)
            self._stage_handoff(payload, handle)
        retry = deque()
        while self._handoff_backlog:
            ent = self._handoff_backlog.popleft()
            if ent["not_before"] > self._iteration:
                retry.append(ent)       # still backing off
                continue
            payload, handle = ent["payload"], ent["handle"]
            if handle is not None and handle.done:
                continue    # finished via an earlier (ambiguously
                            # reported) injection: nothing left to send
            decode = self._dispatchable(self._alive(("decode",)))
            # refresh load per injection: a burst of handoffs must fan
            # out across decode replicas, not pile onto one snapshot
            target = self.router.pick_least_loaded(self._stats(decode)) \
                if decode else None
            if target is None:
                retry.append(ent)       # no target yet: wait, free
                continue
            rep = self._replicas[target]
            error = None
            try:
                accepted = self._inject(rep, payload, handle)
            except (HandoffError, ReplicaDead, RuntimeError,
                    ValueError) as e:
                accepted, error = False, e
            if accepted:
                src = (handle.prefill_replica_id if handle is not None
                       else None)
                hid = payload["request"]["request_id"]
                self.handoffs_completed += 1
                self.handoff_log.append((hid, src, target))
                del self.handoff_log[:-LOG_LIMIT]
                self.recorder.record(
                    "handoff_inject", request_id=hid,
                    trace_id=payload["request"].get("trace_id"),
                    replica_id=target, iteration=self._iteration,
                    src=src)
                # the waterfall's wire stage, as a fleet-level
                # histogram: steps from export to accepted injection
                get_registry().histogram("fleet/wire_rtt").observe(
                    self._iteration - ent.get("exported_at",
                                              self._iteration))
                if handle is not None:
                    handle.replica_id = target
                    handle.handoffs += 1
                    handle.weights_version = getattr(
                        self._replicas[target], "weights_version", 0)
                continue
            if error is None:
                retry.append(ent)       # starvation: retry next step
                continue
            self._count_if_digest_reject(error)
            ent["attempts"] += 1
            self.handoff_retries += 1
            get_registry().counter("fleet/handoff_retries").inc()
            hid = payload["request"]["request_id"]
            if ent["attempts"] > self.scfg.handoff_max_retries:
                self.handoffs_dropped += 1
                get_registry().counter("fleet/handoffs_dropped").inc()
                self.recorder.record(
                    "handoff_dropped", request_id=hid,
                    trace_id=payload["request"].get("trace_id"),
                    iteration=self._iteration,
                    attempts=ent["attempts"], error=str(error))
                log_dist(f"fleet: handoff for {hid!r} dropped after "
                         f"{ent['attempts']} failed injections "
                         f"({error}) — re-prefilling through failover",
                         ranks=[0])
                if handle is not None and not handle.done:
                    self._failover(handle)
                continue
            ent["not_before"] = self._iteration + \
                self.scfg.handoff_retry_delay_steps(ent["attempts"])
            retry.append(ent)
        self._handoff_backlog = retry

    def _count_if_digest_reject(self, e) -> None:
        """Count an integrity-gate rejection. Covers BOTH paths a
        digest mismatch surfaces on: a local ``verify_handoff`` raise
        (``HandoffError.kind == "digest"``) and a REMOTE worker's
        refusal, which crosses the wire as a typed error reply and
        re-raises here as RuntimeError carrying the stable message
        token."""
        if getattr(e, "kind", None) == "digest" \
                or "handoff digest mismatch" in str(e):
            self.handoffs_rejected_corrupt += 1
            get_registry().counter(
                "fleet/handoffs_rejected_corrupt").inc()

    def _record_handoff_export(self, payload: dict, src_rid: int):
        self.recorder.record(
            "handoff_export",
            request_id=payload["request"]["request_id"],
            trace_id=payload["request"].get("trace_id"),
            replica_id=src_rid, iteration=self._iteration,
            prefill_len=int(payload["prefill_len"]))

    def _inject(self, rep, payload, handle) -> bool:
        blob = payload.get("_truncated")
        if blob is not None:
            # chaos-corrupted in transit: decoding raises the named
            # HandoffError exactly as a real torn wire transfer would
            payload = deserialize_handoff(blob)
        # the pre-injection integrity gate: a payload whose bits
        # changed since export (wire, staging, at rest) raises the
        # named HandoffError(kind="digest") — a flipped bit NEVER
        # enters a KV pool (remote targets re-verify on their side too)
        verify_handoff(payload)
        if rep.backend == "inprocess":
            live = rep.inject_handoff(
                payload, on_token=(self._on_token_cb(handle)
                                   if handle is not None else None))
            if live is None:
                return False
            if handle is not None:
                handle._inner = live
            return True
        return rep.inject_handoff(payload)

    # -- failure containment ----------------------------------------------
    def _health_sweep(self):
        """Cadenced probe (every ``health_every_steps``): a hard death
        (process exit, kill) fails over immediately; a wedged-but-alive
        process replica (live pid, dead /healthz) accumulates misses and
        fails over after ``max_missed_health`` consecutive ones."""
        for rid in list(self._alive()):
            rep = self._replicas[rid]
            state = rep.probe_health()
            if state == "ok":
                rep.missed_health = 0
                continue
            if state == "dead":
                self._fail_replica(rid)
                continue
            rep.missed_health += 1
            if rep.missed_health >= self.fcfg.max_missed_health:
                rep.alive = False
                self._fail_replica(rid)

    def _fail_replica(self, rid: int):
        """Dead-replica containment — the fleet-level mirror of
        ``engine.recover()``: forget its router affinity, requeue every
        request it owned through the router with generated tokens
        RETAINED (the continuation re-prefills prompt + partial output
        elsewhere — token-exact under greedy sampling, the PR-10 resume
        guarantee), and reap the corpse."""
        rep = self._replicas[rid]
        rep.alive = False
        self._failed.add(rid)
        self.dead_replicas += 1
        self.router.forget_replica(rid)
        if self._aggregator is not None:
            self._aggregator.mark_dead(rid)
        self.recorder.record("replica_dead", replica_id=rid,
                             iteration=self._iteration)
        # hand the death to the supervision policy FIRST — restart after
        # backoff, or permanent retirement on a crash loop — so the
        # failovers below can park on the pending restart when this was
        # the last live replica instead of declaring total loss
        lid = self._lineage.pop(rid, None)
        if self._supervised and lid is not None:
            verdict = self.supervisor.on_death(lid, self._iteration)
            if verdict == "retired":
                self._note_crash_loop_retirement(lid, rep.role)
        # reap the corpse BEFORE failing its work over: kill() drains
        # the worker's partial-metrics line and closes the pipe fds, and
        # a total-loss RuntimeError out of the failover below must not
        # leave a zombie (or lose the partial snapshot)
        try:
            rep.kill()
        except Exception:   # ds-tpu: lint-ok[PY001] — reaping a corpse
            # must never take the fleet down with it
            pass
        victims = [h for h in self._handles.values()
                   if h.replica_id == rid and not h.done]
        for handle in victims:
            self._failover(handle)
        self._prune_dead()
        log_dist(f"fleet: replica {rid} dead — {len(victims)} requests "
                 "requeued through the router", ranks=[0])

    def _prune_dead(self):
        """Trim the corpse history to ``DEAD_REPLICAS_KEPT``: the most
        recent dead replicas stay in ``self._replicas`` (snapshots read
        their metrics and partial snapshots), everything older is
        dropped from the replica map, the failed set, the lineage map,
        and the aggregator."""
        dead = [rid for rid, rep in sorted(self._replicas.items())
                if not rep.alive]
        for rid in dead[:max(0, len(dead) - DEAD_REPLICAS_KEPT)]:
            rep = self._replicas.pop(rid, None)
            # the pruned corpse's protocol-error count rolls into the
            # carried total so snapshot()'s counter never goes DOWN
            self._protocol_errors_pruned += getattr(
                rep, "protocol_errors", 0)
            self._stale_fence_pruned[0] += getattr(
                rep, "stale_epoch_replies", 0)
            self._stale_fence_pruned[1] += getattr(
                rep, "duplicate_replies", 0)
            self._failed.discard(rid)
            self._lineage.pop(rid, None)
            if self._aggregator is not None:
                self._aggregator.forget(rid)

    def _failover(self, handle: FleetRequest):
        """Re-dispatch one orphaned request: continuation = original
        prompt + retained tokens, budget = what is still owed."""
        handle.failovers += 1
        handle.preemptions += 1
        self.failovers += 1
        handle._inner = None
        self.recorder.record("failover", request_id=handle.request_id,
                             trace_id=handle.trace_id,
                             replica_id=handle.replica_id,
                             iteration=self._iteration,
                             tokens_retained=len(handle.tokens))
        remaining = handle.remaining_budget()
        if remaining <= 0:          # owed nothing more: call it finished
            self._finalize(handle, "finished")
            return
        eligible = self._dispatchable(self._alive(self._submit_roles()))
        if not eligible:
            if self._can_wait_for_capacity():
                self._park(handle)
                return
            raise RuntimeError(
                "fleet: no live replica left to fail requests over to")
        target = self.router.route(
            handle.effective_prompt(), self._stats(eligible),
            step=self._iteration, request_id=handle.request_id)
        self.dispatch_log.append((handle.request_id, target))
        del self.dispatch_log[:-LOG_LIMIT]
        self._dispatch(handle, target, handle.effective_prompt(),
                       remaining)

    # -- closed-loop autoscaling ------------------------------------------
    def _autoscale_tick(self):
        """Publish fleet totals as the gauges the autoscaler reads, then
        ACT on its recommendation: spawn replicas toward
        ``target_replicas`` on sustained backlog, retire one (drained
        via the preemption/slot-cap path) on sustained idleness."""
        alive = self._alive()
        stats = self._stats(alive)
        reg = self._scale_registry
        reg.gauge("serving/queue_depth").set(
            sum(s.queue_depth for s in stats))
        reg.gauge("serving/active_slots").set(
            sum(s.active_slots for s in stats))
        reg.gauge("serving/slot_cap").set(
            sum(s.slot_cap for s in stats))
        decision = self._scaler.observe()
        self.last_scale_decision = decision
        if decision["action"] == "scale_up":
            target = min(decision["target_replicas"],
                         self.fcfg.max_replicas)
            while len(self._alive()) < target:
                rep = self._spawn_replica()
                log_dist(f"fleet: scale-up -> spawned replica "
                         f"{rep.replica_id} ({decision['reason']})",
                         ranks=[0])
        elif decision["action"] == "scale_down":
            if len(alive) > self.fcfg.min_replicas:
                rid = self._pick_retirable(alive)
                if rid is not None:
                    self._retire_replica(rid)

    def _pick_retirable(self, alive):
        """Highest-id replica whose removal keeps the fleet serviceable.
        Disaggregated fleets are role-aware: only a role with >= 2 live
        members may shrink (losing the last decode — or prefill —
        replica bricks the fleet regardless of the total count), decode
        capacity drains before prefill (autoscale spawns rejoin as
        decode). None = nothing is safely retirable."""
        if not self.fcfg.disaggregate:
            return max(alive)
        by_role = {}
        for rid in alive:
            by_role.setdefault(self._replicas[rid].role, []).append(rid)
        for role in ("decode", "full", "prefill"):
            rids = by_role.get(role, [])
            if len(rids) > 1:
                return max(rids)
        return None

    def pick_disposable_replica(self) -> int:
        """The chaos/retire victim selector the kill hooks share: the
        highest-id live replica whose death the fleet can absorb
        (role-aware under disaggregation); falls back to the highest id
        when nothing is safely disposable — the caller asked for a
        kill, so a bricking kill is honored loudly rather than
        silently skipped."""
        alive = self._alive()
        rid = self._pick_retirable(alive)
        return rid if rid is not None else max(alive)

    def _retire_replica(self, rid: int):
        """Graceful scale-down: drain the replica through the PR-10
        preemption/slot-cap path (active requests preempted with tokens
        retained), re-dispatch everything it still owns through the
        router, then stop it."""
        rep = self._replicas[rid]
        if rep.backend == "inprocess":
            rep.engine.set_slot_cap(1)      # preemption-path drain
        victims = [h for h in self._handles.values()
                   if h.replica_id == rid and not h.done]
        rep.alive = False                   # no more routing to it
        self._failed.add(rid)               # failover already handled here
        # a deliberate drain is not a crash: the supervisor must neither
        # respawn this lineage nor count it toward a crash loop
        self.supervisor.deregister(self._lineage.pop(rid, None))
        self.router.forget_replica(rid)
        if self._aggregator is not None:
            self._aggregator.mark_dead(rid)
        self.recorder.record("replica_retired", replica_id=rid,
                             iteration=self._iteration)
        for handle in victims:
            self._failover(handle)
        rep.stop()
        self.replicas_retired += 1
        self._prune_dead()
        log_dist(f"fleet: scale-down -> retired replica {rid} "
                 f"({len(victims)} requests re-dispatched)", ranks=[0])

    # -- federation: HTTP front-end + rolling updates ----------------------
    def attach_frontend(self, frontend):
        """Wire a ``FleetFrontend``: its mailbox drains into ``submit``
        at the top of every ``advance()`` (dispatch thread only — the
        HTTP handler threads never touch the fleet)."""
        self._frontend = frontend
        return frontend

    def start_rolling_update(self, *, checkpoint: Optional[str] = None,
                             module=None, params=None,
                             spec_update: Optional[dict] = None,
                             verify: Optional[bool] = None):
        """Begin a zero-downtime rolling weight update (federation/
        rolling.py): manifest-verify the target, then drain -> swap ->
        rejoin one replica per fleet step until the whole fleet serves
        the new weights. Progress rides ``advance()``; the returned
        ``RollingUpdate`` exposes ``done``/``snapshot()``."""
        from .federation.rolling import RollingUpdate, RollingUpdateError
        if self.rolling is not None and not self.rolling.done:
            raise RollingUpdateError(
                "a rolling update is already in progress "
                f"(v{self.rolling.version}, "
                f"{len(self.rolling.swapped)}/{len(self.rolling.order)} "
                "swapped)")
        fed = self.fedcfg
        if verify is None:
            verify = fed.rolling_verify if fed is not None else True
        drain_cap = fed.rolling_drain_slot_cap if fed is not None else 1
        self.rolling = RollingUpdate(
            self, checkpoint=checkpoint, module=module, params=params,
            spec_update=spec_update, verify=verify,
            drain_slot_cap=drain_cap)
        return self.rolling

    # -- telemetry ---------------------------------------------------------
    def per_request_breakdown(self, include_requests: bool = True) -> dict:
        """The per-request latency waterfall (observability/fleet.py):
        queue -> prefill -> handoff -> wire -> decode stage steps per
        traced request plus per-stage p50/p95 — stage sums telescope
        exactly to each request's end-to-end fleet steps. Derived from
        the flight recorder, so it covers the last-N completed
        requests."""
        return per_request_breakdown(self.recorder.events,
                                     include_requests=include_requests)

    def slo_sample(self) -> dict:
        """The merged sample the SLO watch judges (observability/
        slo.py), built from the fleet's own books on the step clock —
        every value is deterministic given the same request trace. An
        absent key (no completed requests yet, no remote peers) reads
        as "ok" for its rule."""
        sample = {}
        bd = self.per_request_breakdown(include_requests=True)
        # TTFT in fleet steps = submit->first_token = queue + prefill
        waits = [row["queue"] + row["prefill"]
                 for row in (bd.get("requests") or {}).values()]
        if waits:
            sample["ttft_p95_steps"] = float(percentile(waits, 95))
        if self.requests_submitted:
            sample["shed_rate"] = (self.requests_shed
                                   / self.requests_submitted)
        if self._replicas:
            sample["replica_up_fraction"] = (len(self._alive())
                                             / len(self._replicas))
        attempts = self.handoffs_completed + self.handoff_retries
        if attempts:
            sample["corrupt_handoff_rate"] = (
                self.handoffs_rejected_corrupt / attempts)
        # dispatch->reply RTT pooled across every remote peer's
        # sliding window (the wire accountant's histograms)
        rtts = []
        for name, hist in get_registry()._hists.items():
            if name.startswith("wire/rtt_ms/"):
                rtts.extend(hist.window)
        if rtts:
            sample["wire_rtt_p95_ms"] = float(percentile(rtts, 95))
        return sample

    def snapshot(self) -> dict:
        """The fleet section of /statusz: per-replica stats + serving
        snapshots, router policy/decisions, handoff + failover + scaling
        counters, the aggregated telemetry view, the flight-recorder
        timeline, and the per-request waterfall. Host state only."""
        replicas = {}
        for rid, rep in sorted(self._replicas.items()):
            entry = {"role": rep.role, "alive": rep.alive,
                     **rep.stats().to_dict()}
            if rep.backend == "inprocess":
                # a dead engine's host-side metrics stay readable: the
                # work it served before dying must not vanish from the
                # per-replica breakdown (or the kill-run bench block)
                entry["serving"] = rep.engine.metrics.snapshot()
            entry["telemetry_port"] = rep.telemetry_port
            entry["lineage"] = self._lineage.get(rid)
            pm = getattr(rep, "last_partial_metrics", None)
            if pm is not None:
                # the worker's SIGTERM snapshot: what a supervised
                # teardown managed to say on its way down
                entry["partial_metrics"] = pm
            replicas[str(rid)] = entry
        out = {
            "iteration": self._iteration,
            "backend": self.fcfg.backend,
            "disaggregate": self.fcfg.disaggregate,
            "degraded_mode": self.degraded,
            "degraded_entered": self.degraded_entered,
            "replicas": replicas,
            "router": self.router.stats(),
            "handoffs_in_transit": len(self._handoff_backlog),
            "handoffs_completed": self.handoffs_completed,
            "handoff_retries": self.handoff_retries,
            "handoffs_dropped": self.handoffs_dropped,
            "failovers": self.failovers,
            "dead_replicas": self.dead_replicas,
            "replicas_spawned": self.replicas_spawned,
            "replicas_retired": self.replicas_retired,
            "replica_restarts": self.replica_restarts,
            "requests_parked": len(self._orphans),
            "worker_protocol_errors": self._protocol_errors_pruned + sum(
                getattr(rep, "protocol_errors", 0)
                for rep in self._replicas.values()),
            "handoffs_rejected_corrupt": self.handoffs_rejected_corrupt,
            "stale_epoch_replies": self._stale_fence_pruned[0] + sum(
                getattr(rep, "stale_epoch_replies", 0)
                for rep in self._replicas.values()),
            "duplicate_replies": self._stale_fence_pruned[1] + sum(
                getattr(rep, "duplicate_replies", 0)
                for rep in self._replicas.values()),
            "supervision": self.supervisor.snapshot(),
            "requests_submitted": self.requests_submitted,
            "requests_finished": self.requests_finished,
            "requests_shed": self.requests_shed,
            "remote_replicas": sum(
                1 for rep in self._replicas.values()
                if rep.backend == "remote" and rep.alive),
            "weights_version": self.weights_version,
            "rolling_updates": self.rolling_updates,
            "rolling_swaps": self.rolling_swaps,
            "rolling": (self.rolling.snapshot()
                        if self.rolling is not None else None),
            "draining": sorted(self._draining),
            "autoscale": self.last_scale_decision,
            "flight_recorder": self.recorder.snapshot(),
            "per_request_breakdown": self.per_request_breakdown(
                include_requests=False),
        }
        if self._aggregator is not None:
            out["telemetry"] = self._aggregator.snapshot()
        if self.slo_watch is not None:
            # rides every snapshot AND the crash path (the exit/crash
            # dumps call snapshot()), so open incidents survive a wreck
            out["slo"] = self.slo_watch.snapshot()
        if self._frontend is not None:
            out["frontend"] = self._frontend.snapshot()
        return out

    def metrics_snapshot(self) -> dict:
        """The router-level /statusz payload: the process registry plus
        the fleet section (observability/export.py renders it). The
        aggregator's per-replica up/staleness gauges and merged totals
        fold into the registry view, so the router's /metrics carries
        ``ds_tpu_fleet_replica_*`` and ``ds_tpu_fleet_merged_*``
        series — the fleet-wide scrape surface."""
        from ...observability.metrics import get_registry
        reg = get_registry().snapshot()
        if self._aggregator is not None:
            reg.setdefault("gauges", {}).update(self._aggregator.gauges())
        return {"registry": reg, "fleet": self.snapshot()}

    # -- fleet-wide trace stitching ----------------------------------------
    def trace_dumps(self):
        """Collect the per-lane Chrome-trace dumps: the router
        process's own active tracer (which, on the in-process backend,
        also holds every replica's spans — one process, one stream)
        plus each process replica's ``trace_dump`` (workers record when
        ``serving.fleet.replica_trace`` is on)."""
        from ...observability.trace import active_tracer, chrome_trace_events
        dumps = []
        tracer = active_tracer()
        if tracer is not None and tracer.events:
            dumps.append(("router", chrome_trace_events(tracer.events)))
        for rid, rep in sorted(self._replicas.items()):
            events = rep.trace_dump()
            if events:
                dumps.append((f"replica{rid}:{rep.role}", events))
        return dumps

    def stitched_trace(self) -> dict:
        """ONE Chrome trace for the whole fleet: one process lane per
        replica (plus the router), request spans joined across lanes by
        their ``args.trace_id``. Load it in chrome://tracing or
        Perfetto; ``breakdown_from_trace`` rebuilds the per-request
        waterfall from it."""
        from ...observability.fleet import stitch_chrome_traces
        return stitch_chrome_traces(self.trace_dumps())

    def write_stitched_trace(self, path: str) -> str:
        from ...observability.fleet import write_stitched_trace
        return write_stitched_trace(self.trace_dumps(), path)

    def start_telemetry(self, port: int = 0, host: str = "127.0.0.1"):
        """Router-level /metrics + /healthz + /statusz (the fleet
        section rides /statusz); per-replica endpoints are separate
        (``serving.fleet.replica_telemetry``)."""
        if self.telemetry is not None:
            return self.telemetry
        from ...observability.export import TelemetryServer
        self.telemetry = TelemetryServer(self.metrics_snapshot, host=host,
                                         port=port).start()
        log_dist(f"fleet telemetry: http://{host}:{self.telemetry.port}"
                 "/statusz", ranks=[0])
        return self.telemetry

    def close(self):
        if self._frontend is not None:
            f, self._frontend = self._frontend, None
            f.stop()
        if self.telemetry is not None:
            t, self.telemetry = self.telemetry, None
            t.stop()
        for rep in self._replicas.values():
            try:
                rep.stop()
            except Exception:   # ds-tpu: lint-ok[PY001] — teardown must
                # reach every replica even when one refuses to die
                pass
