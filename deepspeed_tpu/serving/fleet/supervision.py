"""Replica supervision policy: restart, backoff, crash-loop retirement.

The ``ReplicaSupervisor`` is the fleet's self-healing brain — pure host
policy, stdlib-only (the same import contract as the rest of
``fleet/config.py``), driven entirely by the deterministic fleet step
clock so a replayed trace reproduces every restart decision bit-exactly.

The manager tracks replicas by *lineage*: one lineage is one logical
fleet member across however many incarnations supervision spawns for
it. When an incarnation dies (worker process exit, pipe protocol
error, in-process ``ReplicaCrash``, missed health checks), the manager
reports the death here and the supervisor answers with one of two
verdicts:

- ``"restart"`` — a fresh incarnation is due after an exponential
  backoff (``backoff_base_steps * 2^(in-window crashes - 1)`` fleet
  steps, capped at ``backoff_max_steps``; an isolated crash outside
  the window restarts at the base delay again); the manager spawns it
  from ``take_due()`` on a later fleet step. In-flight requests never wait for the restart —
  they fail over to the survivors immediately with their generated
  tokens retained (the PR-10 resume guarantee).
- ``"retired"`` — the lineage crash-looped: more than ``max_restarts``
  deaths inside a sliding ``crash_window_steps`` window. The fleet
  keeps serving on the survivors and never respawns this lineage
  (``fleet/replicas_retired``); restarting a deterministic crasher
  forever would burn capacity without ever serving a token.

Deliberate retirements (autoscaler scale-down, ``fleet.close()``) are
``deregister()``\\ ed instead — an intentional drain must not look like
a crash or trigger a respawn.
"""

from dataclasses import dataclass
from typing import Dict, List, Optional, Tuple


@dataclass
class SupervisionConfig:
    """The ``serving.fleet.supervision`` sub-block (docs/config.md).

    Also carries the handoff-injection hardening knobs: injection
    retries ride the same fleet-step backoff discipline the restart
    policy uses, so the whole self-healing layer is tuned in one
    place.
    """
    enabled: bool = True             # restart dead/crashed replicas
                                     # (both backends); false restores
                                     # the PR-12 behavior — detected
                                     # deaths fail over but nothing
                                     # respawns, and an in-process
                                     # ReplicaCrash is fatal
    max_restarts: int = 3            # deaths tolerated per lineage
                                     # inside crash_window_steps; one
                                     # more permanently retires it
    crash_window_steps: int = 256    # sliding window (fleet steps) the
                                     # crash-loop detector counts over
    backoff_base_steps: int = 2      # restart delay doubles per restart:
                                     # base * 2^n fleet steps ...
    backoff_max_steps: int = 64      # ... capped here
    handoff_max_retries: int = 3     # FAILED injection attempts per
                                     # handoff payload before the fleet
                                     # drops it and re-prefills the
                                     # request through failover
                                     # (starvation waits are free — only
                                     # errors count)
    handoff_backoff_steps: int = 1   # fleet steps between injection
                                     # retries, doubling per failure

    def validate(self) -> "SupervisionConfig":
        if self.max_restarts < 0:
            raise ValueError(
                "serving.fleet.supervision.max_restarts must be >= 0, "
                f"got {self.max_restarts}")
        if self.crash_window_steps < 1:
            raise ValueError(
                "serving.fleet.supervision.crash_window_steps must be "
                f">= 1, got {self.crash_window_steps}")
        if self.backoff_base_steps < 1:
            raise ValueError(
                "serving.fleet.supervision.backoff_base_steps must be "
                f">= 1, got {self.backoff_base_steps}")
        if self.backoff_max_steps < self.backoff_base_steps:
            raise ValueError(
                "serving.fleet.supervision.backoff_max_steps must be >= "
                f"backoff_base_steps ({self.backoff_base_steps}), got "
                f"{self.backoff_max_steps}")
        if self.handoff_max_retries < 0:
            raise ValueError(
                "serving.fleet.supervision.handoff_max_retries must be "
                f">= 0, got {self.handoff_max_retries}")
        if self.handoff_backoff_steps < 1:
            raise ValueError(
                "serving.fleet.supervision.handoff_backoff_steps must "
                f"be >= 1, got {self.handoff_backoff_steps}")
        return self

    def restart_delay_steps(self, restarts: int) -> int:
        """Backoff before restart number ``restarts + 1`` (0-indexed):
        exponential from ``backoff_base_steps``, capped."""
        return min(self.backoff_max_steps,
                   self.backoff_base_steps * (2 ** max(0, restarts)))

    def handoff_retry_delay_steps(self, attempts: int) -> int:
        """Backoff after the ``attempts``-th failed injection."""
        return min(self.backoff_max_steps,
                   self.handoff_backoff_steps * (2 ** max(0, attempts - 1)))


class ReplicaSupervisor:
    """Restart/retire policy over replica lineages (fleet-clock only)."""

    def __init__(self, config: SupervisionConfig):
        self.config = config
        self._lineages: Dict[int, dict] = {}
        self._next_lid = 0
        self.restarts_scheduled = 0
        self.retired_total = 0

    # -- lineage lifecycle -------------------------------------------------
    def register(self, role: str) -> int:
        """Admit one logical fleet member; returns its lineage id."""
        lid = self._next_lid
        self._next_lid += 1
        self._lineages[lid] = {"role": role, "crashes": [], "restarts": 0,
                               "retired": False, "due": None}
        return lid

    def deregister(self, lid: Optional[int]):
        """Forget a lineage the fleet retired ON PURPOSE (autoscaler
        drain, close()) — not a crash, never a respawn."""
        if lid is not None:
            self._lineages.pop(lid, None)

    # -- verdicts ----------------------------------------------------------
    def on_death(self, lid: int, step: int) -> str:
        """Record one incarnation death at fleet step ``step`` and
        decide: ``"restart"`` (a respawn is due after backoff) or
        ``"retired"`` (crash loop — the lineage is done)."""
        rec = self._lineages[lid]
        if rec["retired"]:
            return "retired"
        # the sliding crash-loop window: only deaths newer than
        # crash_window_steps count against max_restarts
        rec["crashes"] = [s for s in rec["crashes"]
                          if step - s < self.config.crash_window_steps]
        rec["crashes"].append(step)
        if len(rec["crashes"]) > self.config.max_restarts:
            rec["retired"] = True
            rec["due"] = None
            self.retired_total += 1
            return "retired"
        # backoff escalates with the IN-WINDOW crash count, so an
        # isolated crash long after the last one restarts at the base
        # delay again — only a tightening loop earns the long waits
        # (rec["restarts"] stays as lifetime telemetry)
        delay = self.config.restart_delay_steps(len(rec["crashes"]) - 1)
        rec["restarts"] += 1
        rec["due"] = step + delay
        self.restarts_scheduled += 1
        return "restart"

    def take_due(self, step: int) -> List[Tuple[int, str]]:
        """Pop every lineage whose backoff has elapsed at ``step`` —
        ``[(lineage_id, role)]`` in lineage order. The caller spawns
        them; a spawn that fails reports back via ``on_death``."""
        out = []
        for lid in sorted(self._lineages):
            rec = self._lineages[lid]
            if rec["due"] is not None and step >= rec["due"] \
                    and not rec["retired"]:
                rec["due"] = None
                out.append((lid, rec["role"]))
        return out

    def pending(self, roles=None) -> bool:
        """True when at least one restart is scheduled (optionally for
        one of ``roles``) — what keeps an all-dead fleet waiting on its
        backoff clock instead of declaring total loss."""
        return any(rec["due"] is not None and not rec["retired"]
                   and (roles is None or rec["role"] in roles)
                   for rec in self._lineages.values())

    def snapshot(self) -> dict:
        """JSON-able policy state for /statusz and the chaos report."""
        return {
            "enabled": self.config.enabled,
            "restarts_scheduled": self.restarts_scheduled,
            "retired_total": self.retired_total,
            "lineages": {
                str(lid): {"role": rec["role"],
                           "restarts": rec["restarts"],
                           "recent_crashes": len(rec["crashes"]),
                           "retired": rec["retired"],
                           "restart_due_step": rec["due"]}
                for lid, rec in sorted(self._lineages.items())},
        }
