"""Request queue + admission policy.

Reference frame: DeepSpeed-Inference/MII serve requests by re-forming
whole batches; the continuous-batching scheduler here instead admits
individual requests into free KV-cache slots BETWEEN decode steps, so
one straggler never holds the batch (the Orca/vLLM scheduling insight,
applied with TPU-static shapes: admission changes slot METADATA, never
the compiled decode shape).

Ordering: strict priority across classes (higher ``request.priority``
admits first), FIFO within a class — which degenerates to plain FIFO
when every request carries the default priority, so the pre-QoS
behaviour is unchanged for priority-free traffic. Head-of-line blocking
exists on slot/page availability only — every queued request already
fits a slot (submit() validates the token budget), so the head never
blocks the tail for shape reasons.

Robustness contract: queued requests can carry a ``deadline_steps``
queue TTL (``expire`` sweeps them out on the engine-iteration clock so a
saturated server sheds load deterministically instead of growing an
unbounded backlog), ``remove`` supports client-side ``cancel()``,
``requeue`` re-inserts preempted requests at the FRONT of their class
(they were already admitted once — resumption must not wait behind new
arrivals of the same class), and ``shed_queued`` backs the degradation
ladder's queued-request sweep.
"""

from collections import deque
from typing import Callable, Dict, List, Optional

from .request import Request


class FifoScheduler:
    """Priority admission queue over the slot pool (FIFO within class)."""

    def __init__(self, config):
        self.config = config
        self._queues: Dict[int, deque] = {}   # priority -> FIFO deque

    def __len__(self) -> int:
        return sum(len(q) for q in self._queues.values())

    @property
    def depth(self) -> int:
        return len(self)

    def _priorities(self) -> List[int]:
        """Admission order: highest priority first."""
        return sorted(self._queues, reverse=True)

    def add(self, request: Request):
        cap = self.config.max_queue
        if cap is not None and len(self) >= cap:
            raise RuntimeError(
                f"serving queue full ({cap} requests); raise max_queue or "
                "apply client-side backpressure")
        self._queues.setdefault(request.priority, deque()).append(request)

    def requeue(self, request: Request):
        """Front-of-class re-insert for preempted/recovered requests. No
        queue-cap check: the request was already admitted once, and
        bouncing it here would turn a preemption into a drop."""
        self._queues.setdefault(request.priority,
                                deque()).appendleft(request)

    def next_request(self) -> Optional[Request]:
        """Pop the next admissible request (None when the queue is empty):
        the FIFO head of the highest non-empty priority class."""
        for p in self._priorities():
            q = self._queues[p]
            if q:
                return q.popleft()
        return None

    def peek(self) -> Optional[Request]:
        """The queue head WITHOUT popping it. The engine admits in two
        phases — reserve resources (pages / a slot, possibly via
        preemption) for the head, then pop — so a resource-starved head
        stays queued and class order is preserved while it waits."""
        for p in self._priorities():
            q = self._queues[p]
            if q:
                return q[0]
        return None

    def queued(self) -> List[Request]:
        """Every queued request in admission order."""
        return [r for p in self._priorities() for r in self._queues[p]]

    def _discard(self, requests: List[Request]):
        gone = set(map(id, requests))
        for p, q in self._queues.items():
            if any(id(r) in gone for r in q):
                self._queues[p] = deque(r for r in q if id(r) not in gone)

    def expire(self, iteration: int) -> List[Request]:
        """Remove queued requests whose deadline passed the engine clock
        (deterministic: the iteration count, not wall time). Callers
        complete them with ``timeout`` status. Preempted requests that
        already generated tokens are exempt — their progress is
        resumable, and discarding it would waste paid-for compute."""
        expired = [r for r in self.queued()
                   if not r.tokens
                   and r.deadline_iteration() is not None
                   and iteration >= r.deadline_iteration()]
        if expired:
            self._discard(expired)
        return expired

    def shed_queued(self, predicate: Callable[[Request], bool]
                    ) -> List[Request]:
        """Remove and return queued requests matching ``predicate`` (the
        degradation ladder's sweep). Callers complete them with ``shed``
        status."""
        matched = [r for r in self.queued() if predicate(r)]
        if matched:
            self._discard(matched)
        return matched

    def remove(self, request_id) -> Optional[Request]:
        """Remove one queued request by id (for ``cancel``); None when no
        queued request carries that id."""
        for r in self.queued():
            if r.request_id == request_id:
                self._discard([r])
                return r
        return None

    def validate_request(self, prompt_len: int, max_new_tokens: int):
        """Refuse requests that can never fit a slot — the serving analog
        of the engine.generate max_seq_len check (clear error at submit
        time, not a truncated response later)."""
        if prompt_len < 1:
            raise ValueError("empty prompt")
        if max_new_tokens < 1:
            raise ValueError(
                f"max_new_tokens must be >= 1, got {max_new_tokens}")
        budget = self.config.max_len
        if prompt_len + max_new_tokens > budget:
            raise ValueError(
                f"prompt_len ({prompt_len}) + max_new_tokens "
                f"({max_new_tokens}) = {prompt_len + max_new_tokens} "
                f"exceeds the per-slot budget max_len={budget}; shorten "
                "the prompt, reduce max_new_tokens, or raise "
                "serving.max_len")
