"""Request queue + admission policy.

Reference frame: DeepSpeed-Inference/MII serve requests by re-forming
whole batches; the continuous-batching scheduler here instead admits
individual requests into free KV-cache slots BETWEEN decode steps, so
one straggler never holds the batch (the Orca/vLLM scheduling insight,
applied with TPU-static shapes: admission changes slot METADATA, never
the compiled decode shape).

FIFO with head-of-line blocking on slot availability only — every
queued request already fits a slot (submit() validates the token
budget), so the head never blocks the tail for shape reasons.

Robustness contract: queued requests can carry a ``deadline_steps``
queue TTL (``expire`` sweeps them out on the engine-iteration clock so a
saturated server sheds load deterministically instead of growing an
unbounded backlog), and ``remove`` supports client-side ``cancel()``.
"""

from collections import deque
from typing import List, Optional

from .request import Request


class FifoScheduler:
    """FIFO admission queue over the slot pool."""

    def __init__(self, config):
        self.config = config
        self._queue = deque()

    def __len__(self) -> int:
        return len(self._queue)

    @property
    def depth(self) -> int:
        return len(self._queue)

    def add(self, request: Request):
        cap = self.config.max_queue
        if cap is not None and len(self._queue) >= cap:
            raise RuntimeError(
                f"serving queue full ({cap} requests); raise max_queue or "
                "apply client-side backpressure")
        self._queue.append(request)

    def next_request(self) -> Optional[Request]:
        """Pop the next admissible request (None when the queue is empty).
        All queued requests fit by construction, so this is pure FIFO."""
        if not self._queue:
            return None
        return self._queue.popleft()

    def peek(self) -> Optional[Request]:
        """The queue head WITHOUT popping it. The paged engine admits in
        two phases — reserve pages for the head, then pop — so a
        page-starved head stays queued (admission gates on free pages,
        not free slots) and FIFO order is preserved while it waits."""
        return self._queue[0] if self._queue else None

    def expire(self, iteration: int) -> List[Request]:
        """Remove queued requests whose deadline passed the engine clock
        (deterministic: the iteration count, not wall time). Callers
        complete them with ``timeout`` status."""
        expired = [r for r in self._queue
                   if r.deadline_iteration() is not None
                   and iteration >= r.deadline_iteration()]
        if expired:
            gone = set(map(id, expired))
            self._queue = deque(r for r in self._queue
                                if id(r) not in gone)
        return expired

    def remove(self, request_id) -> Optional[Request]:
        """Remove one queued request by id (for ``cancel``); None when no
        queued request carries that id."""
        for r in self._queue:
            if r.request_id == request_id:
                self._queue.remove(r)
                return r
        return None

    def validate_request(self, prompt_len: int, max_new_tokens: int):
        """Refuse requests that can never fit a slot — the serving analog
        of the engine.generate max_seq_len check (clear error at submit
        time, not a truncated response later)."""
        if prompt_len < 1:
            raise ValueError("empty prompt")
        if max_new_tokens < 1:
            raise ValueError(
                f"max_new_tokens must be >= 1, got {max_new_tokens}")
        budget = self.config.max_len
        if prompt_len + max_new_tokens > budget:
            raise ValueError(
                f"prompt_len ({prompt_len}) + max_new_tokens "
                f"({max_new_tokens}) = {prompt_len + max_new_tokens} "
                f"exceeds the per-slot budget max_len={budget}; shorten "
                "the prompt, reduce max_new_tokens, or raise "
                "serving.max_len")
