"""Request queue + admission policy.

Reference frame: DeepSpeed-Inference/MII serve requests by re-forming
whole batches; the continuous-batching scheduler here instead admits
individual requests into free KV-cache slots BETWEEN decode steps, so
one straggler never holds the batch (the Orca/vLLM scheduling insight,
applied with TPU-static shapes: admission changes slot METADATA, never
the compiled decode shape).

FIFO with head-of-line blocking on slot availability only — every
queued request already fits a slot (submit() validates the token
budget), so the head never blocks the tail for shape reasons.
"""

from collections import deque
from typing import Optional

from .request import Request


class FifoScheduler:
    """FIFO admission queue over the slot pool."""

    def __init__(self, config):
        self.config = config
        self._queue = deque()

    def __len__(self) -> int:
        return len(self._queue)

    @property
    def depth(self) -> int:
        return len(self._queue)

    def add(self, request: Request):
        cap = self.config.max_queue
        if cap is not None and len(self._queue) >= cap:
            raise RuntimeError(
                f"serving queue full ({cap} requests); raise max_queue or "
                "apply client-side backpressure")
        self._queue.append(request)

    def next_request(self) -> Optional[Request]:
        """Pop the next admissible request (None when the queue is empty).
        All queued requests fit by construction, so this is pure FIFO."""
        if not self._queue:
            return None
        return self._queue.popleft()

    def validate_request(self, prompt_len: int, max_new_tokens: int):
        """Refuse requests that can never fit a slot — the serving analog
        of the engine.generate max_seq_len check (clear error at submit
        time, not a truncated response later)."""
        if prompt_len < 1:
            raise ValueError("empty prompt")
        if max_new_tokens < 1:
            raise ValueError(
                f"max_new_tokens must be >= 1, got {max_new_tokens}")
        budget = self.config.max_len
        if prompt_len + max_new_tokens > budget:
            raise ValueError(
                f"prompt_len ({prompt_len}) + max_new_tokens "
                f"({max_new_tokens}) = {prompt_len + max_new_tokens} "
                f"exceeds the per-slot budget max_len={budget}; shorten "
                "the prompt, reduce max_new_tokens, or raise "
                "serving.max_len")
