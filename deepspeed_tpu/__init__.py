"""deepspeed_tpu — a TPU-native training/inference framework with
DeepSpeed's capabilities (reference: jimwu6/DeepSpeed v0.7.0).

Public facade mirrors ``deepspeed/__init__.py``: ``initialize`` (:51),
``init_inference`` (:222), ``init_distributed``, ``add_config_arguments``
(:206). The engine returned by ``initialize`` is the TPU-native
DeepSpeedEngine (runtime/engine.py here vs runtime/engine.py:180 there).
"""

__version__ = "0.1.0"

from .utils import jax_compat as _jax_compat  # noqa: F401  (API-drift shims)
from . import comm  # noqa: F401
from .comm import init_distributed  # noqa: F401
from .runtime.config import DeepSpeedConfig  # noqa: F401


def initialize(args=None,
               model=None,
               optimizer=None,
               model_parameters=None,
               training_data=None,
               lr_scheduler=None,
               mpu=None,
               dist_init_required=None,
               collate_fn=None,
               config=None,
               config_params=None,
               *,
               loss_fn=None,
               sample_batch=None,
               rng=None,
               mesh=None):
    """Create a training engine (reference: deepspeed.initialize,
    deepspeed/__init__.py:51).

    Returns (engine, optimizer, dataloader, lr_scheduler) like the
    reference. TPU-specific inputs: ``loss_fn(model, params, batch, rng,
    train) -> loss``, ``sample_batch`` for shape-based init (or pass
    initialized flax variables via ``model_parameters``), optional ``mesh``.
    """
    from .runtime.engine import DeepSpeedEngine
    from .runtime.config import DeepSpeedConfig

    cfg = config if config is not None else config_params
    if cfg is None and args is not None and hasattr(args, "deepspeed_config") \
            and args.deepspeed_config:
        cfg = args.deepspeed_config
    if isinstance(cfg, str):
        import json
        with open(cfg) as f:
            cfg = json.load(f)
    if isinstance(cfg, dict):
        cfg = DeepSpeedConfig.from_dict(cfg)

    pipeline = False
    try:
        from .runtime.pipe.module import PipelineModule
        pipeline = isinstance(model, PipelineModule)
    except ImportError:
        pass

    if pipeline:
        if getattr(model, "heterogeneous", False):
            # heterogeneous LayerSpec stacks execute the 1F1B instruction
            # stream host-side (reference: _exec_schedule, pipe/engine.py
            # :1354); a mesh with a "data" axis composes DP with it
            # (stage params replicated, micros batch-sharded)
            from .runtime.pipe.host_engine import HostDrivenPipelineEngine
            engine = HostDrivenPipelineEngine(
                model, cfg, loss_fn=loss_fn, sample_batch=sample_batch,
                rng=rng, optimizer=optimizer, lr_scheduler=lr_scheduler,
                mesh=mesh, params=model_parameters)
        else:
            from .runtime.pipe.engine import PipelineEngine
            engine = PipelineEngine(model, cfg, loss_fn=loss_fn,
                                    sample_batch=sample_batch, rng=rng,
                                    mesh=mesh, optimizer=optimizer,
                                    lr_scheduler=lr_scheduler,
                                    params=model_parameters)
    else:
        engine = DeepSpeedEngine(model, cfg, loss_fn=loss_fn,
                                 params=model_parameters,
                                 sample_batch=sample_batch, rng=rng, mesh=mesh,
                                 optimizer=optimizer, lr_scheduler=lr_scheduler,
                                 mpu=mpu)

    dataloader = None
    if training_data is not None:
        from .runtime.dataloader import (DeepSpeedDataLoader,
                                         PrefetchingLoader)
        dataloader = PrefetchingLoader(DeepSpeedDataLoader(
            training_data,
            batch_size=engine.config.train_batch_size,
            collate_fn=collate_fn))
    return engine, engine.optimizer, dataloader, engine.lr_schedule


def init_inference(model=None, **kwargs):
    """Create an inference engine (reference: deepspeed/__init__.py:222)."""
    from .inference.engine import InferenceEngine
    return InferenceEngine(model, **kwargs)


def _lazy_exports():
    """Reference facade names (deepspeed/__init__.py:27-49) resolved on
    first use so importing the package stays light."""
    return {
        "zero": lambda: __import__(
            "deepspeed_tpu.runtime.zero", fromlist=["zero"]),
        "moe": lambda: __import__("deepspeed_tpu.moe", fromlist=["moe"]),
        "pipe": lambda: __import__(
            "deepspeed_tpu.runtime.pipe", fromlist=["pipe"]),
        "checkpointing": lambda: _from(
            "deepspeed_tpu.runtime.activation_checkpointing",
            "checkpointing"),
        "PipelineModule": lambda: _from(
            "deepspeed_tpu.runtime.pipe.module", "PipelineModule"),
        "LayerSpec": lambda: _from(
            "deepspeed_tpu.runtime.pipe.module", "LayerSpec"),
        "TiedLayerSpec": lambda: _from(
            "deepspeed_tpu.runtime.pipe.module", "TiedLayerSpec"),
        "OnDevice": lambda: _from(
            "deepspeed_tpu.utils.init_on_device", "OnDevice"),
        "DeepSpeedTransformerLayer": lambda: _from(
            "deepspeed_tpu.ops.transformer", "DeepSpeedTransformerLayer"),
        "DeepSpeedTransformerConfig": lambda: _from(
            "deepspeed_tpu.ops.transformer", "DeepSpeedTransformerConfig"),
        "log_dist": lambda: _from("deepspeed_tpu.utils.logging", "log_dist"),
    }


def _from(mod, name):
    return getattr(__import__(mod, fromlist=[name]), name)


def __getattr__(name):
    factory = _lazy_exports().get(name)
    if factory is None:
        raise AttributeError(f"module 'deepspeed_tpu' has no attribute {name!r}")
    value = factory()
    globals()[name] = value
    return value


def add_config_arguments(parser):
    """argparse integration (reference: deepspeed/__init__.py:206)."""
    group = parser.add_argument_group("DeepSpeed-TPU",
                                      "DeepSpeed-TPU configurations")
    group.add_argument("--deepspeed", default=False, action="store_true",
                       help="Enable DeepSpeed-TPU (always on; kept for parity)")
    group.add_argument("--deepspeed_config", default=None, type=str,
                       help="Path to the DeepSpeed JSON config")
    group.add_argument("--local_rank", type=int, default=-1,
                       help="Local rank (launcher-provided; unused on TPU)")
    return parser
