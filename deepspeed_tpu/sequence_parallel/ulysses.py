"""DeepSpeed-Ulysses-style sequence parallelism.

The idea (absent from the reference snapshot; modern DeepSpeed's
``DistributedAttention`` wraps a local attention with two all-to-alls):
activations arrive sharded on the sequence dim over the ``seq`` mesh
axis. Attention needs the full sequence, but is embarrassingly parallel
over heads — so an all-to-all converts the seq shard into a head shard,
the unmodified local attention core runs on full sequences, and a second
all-to-all converts back.

TPU-native: a ``shard_map`` region with ``jax.lax.all_to_all`` over the
``seq`` axis (lowering to XLA AllToAll on ICI), composing with batch
sharding over data/fsdp and head sharding over model (tensor parallel).
"""

from functools import partial
from typing import Optional

import jax
from jax import lax
from jax.sharding import PartitionSpec as P

from ..comm.mesh import get_global_mesh
from ..utils.jax_compat import shard_map

# q/k/v/out layout everywhere: [batch, seq, heads, head_dim]
_BATCH_AXES = ("data", "fsdp")
_HEAD_AXIS = "model"
_SEQ_AXIS = "seq"


def _qkv_spec(q_shape, mesh, batch_axes, seq_axis, head_axis):
    return P(_fit_axes(q_shape[0], batch_axes, mesh), seq_axis,
             _fit_axes(q_shape[2], head_axis, mesh), None)


def _fit_axes(dim_size, axes, mesh):
    """Longest prefix of ``axes`` whose cumulative product divides dim_size.

    The engine traces the model on tiny sample batches (batch=1) where the
    full data/fsdp sharding can't apply; sharding the batch dim is a
    throughput concern, not a correctness one, so degrade gracefully."""
    kept = []
    prod = 1
    for a in (axes if isinstance(axes, (tuple, list)) else (axes,)):
        nxt = prod * mesh.shape.get(a, 1)
        if dim_size % nxt != 0:
            break
        kept.append(a)
        prod = nxt
    if not kept:
        return None
    return tuple(kept) if len(kept) > 1 else kept[0]


def _bhqk_spec(shape, mesh, batch_axes, head_sub_axes):
    """Spec for a [b|1, h|1, sq|1, sk] operand (mask/bias/keep) entering
    the shard_map region: batch sharded when real, the head dim sharded
    the way the post-all-to-all q/k/v heads are laid out (outer TP axis,
    then the seq axis — the a2a keeps chunk ``seq_index`` of each local
    head block), q/k dims replicated (the local core sees full sequence).
    Broadcast (size-1) dims stay replicated."""
    b, h = shape[0], shape[1]
    return P(_fit_axes(b, batch_axes, mesh) if b > 1 else None,
             _fit_axes(h, head_sub_axes, mesh) if h > 1 else None,
             None, None)


def ulysses_attention(q, k, v, *, bias=None, mask=None, causal=False,
                      softmax_scale=None, dropout_rate=0.0, dropout_rng=None,
                      deterministic=True, attn_fn=None, mesh=None,
                      axis_name=_SEQ_AXIS, batch_axes=_BATCH_AXES,
                      head_axis=_HEAD_AXIS):
    """Full-sequence attention over seq-sharded inputs, [B, S, H, D] global.

    ``attn_fn(q, k, v, causal=..., softmax_scale=...)`` is the local
    attention core (default: the ops.transformer dispatch, so the Pallas
    flash kernel is used on TPU when eligible). Requires
    ``H / tp_degree`` divisible by the seq-axis size.

    bias/mask ([b|1, h|1, sq|1, sk]) ride into the region pre-sharded on
    the head dim to match the post-all-to-all head layout — no extra
    collective. Dropout keeps EXACT parity with the replicated path with
    ZERO operand traffic: the attention core's counter-based keep hash is
    keyed on GLOBAL (batch, head, row, col) coordinates, so each device
    passes its head/batch offsets and regenerates precisely its tile of
    the replicated sample — nothing of shape [sq, sk] is ever
    materialized (on TPU the flash kernel samples in-tile; the dense
    fallback fuses the hash into the softmax chain).
    """
    mesh = mesh or get_global_mesh()
    sp = mesh.shape[axis_name]
    if attn_fn is None:
        from ..ops.transformer.attention import attention
        attn_fn = partial(attention, seq_parallel="none")
    dropout_on = dropout_rate > 0.0 and not deterministic
    if dropout_on and dropout_rng is None:
        raise ValueError("ulysses_attention: dropout_rate > 0 with "
                         "deterministic=False requires dropout_rng")
    if sp == 1:
        # keep the documented (q, k, v, causal=, softmax_scale=) attn_fn
        # contract when no operands ride along; only operand-carrying
        # calls need the full attention() signature
        extra_kwargs = {}
        if bias is not None:
            extra_kwargs["bias"] = bias
        if mask is not None:
            extra_kwargs["mask"] = mask
        if dropout_on:
            extra_kwargs.update(dropout_rate=dropout_rate,
                                dropout_rng=dropout_rng,
                                deterministic=deterministic)
        return attn_fn(q, k, v, causal=causal, softmax_scale=softmax_scale,
                       **extra_kwargs)

    n_heads, seq_len = q.shape[2], q.shape[1]
    tp = mesh.shape.get(head_axis, 1)
    local_heads = n_heads // tp
    if local_heads % sp != 0:
        raise ValueError(
            f"Ulysses needs heads/tp ({n_heads}/{tp}={local_heads}) divisible "
            f"by the seq-parallel degree {sp}")
    if seq_len % sp != 0:
        raise ValueError(f"sequence length {seq_len} not divisible by sp={sp}")

    spec = _qkv_spec(q.shape, mesh, batch_axes, axis_name, head_axis)
    head_sub = ((head_axis, axis_name) if tp > 1 else (axis_name,))

    extras = [(name, t) for name, t in
              (("bias", bias), ("mask", mask),
               ("dropout_rng", dropout_rng if dropout_on else None))
              if t is not None]
    extra_specs = tuple(P() if name == "dropout_rng"
                        else _bhqk_spec(t.shape, mesh, batch_axes, head_sub)
                        for name, t in extras)
    extra_names = tuple(name for name, _ in extras)

    # which batch axes the q spec actually shards (batch offset inputs)
    batch_used = spec[0]
    batch_used = (() if batch_used is None else
                  batch_used if isinstance(batch_used, tuple)
                  else (batch_used,))

    def local_fn(q, k, v, *extra):
        ops = dict(zip(extra_names, extra))
        # [b, s/sp, h, d] -> [b, s, h/sp, d]: the head<->seq swap
        q, k, v = (lax.all_to_all(t, axis_name, split_axis=2, concat_axis=1,
                                  tiled=True) for t in (q, k, v))
        kwargs = {n: t for n, t in ops.items() if n != "dropout_rng"}
        if dropout_on:
            # global coordinates of this device's head/batch window, so
            # the core's position-keyed dropout hash regenerates exactly
            # the replicated sample's tile (see module docstring)
            h_per_dev = local_heads // sp
            head_off = lax.axis_index(axis_name) * h_per_dev
            if tp > 1:
                head_off = head_off + lax.axis_index(head_axis) * local_heads
            batch_off = 0
            for a in batch_used:
                batch_off = batch_off * mesh.shape[a] + lax.axis_index(a)
            batch_off = batch_off * q.shape[0]
            kwargs.update(dropout_rate=dropout_rate,
                          dropout_rng=ops["dropout_rng"],
                          deterministic=False,
                          dropout_offsets=(n_heads, head_off, batch_off))
        out = attn_fn(q, k, v, causal=causal, softmax_scale=softmax_scale,
                      **kwargs)
        # [b, s, h/sp, d] -> [b, s/sp, h, d]
        return lax.all_to_all(out, axis_name, split_axis=1, concat_axis=2,
                              tiled=True)

    return shard_map(
        local_fn, mesh=mesh, in_specs=(spec, spec, spec) + extra_specs,
        out_specs=spec)(q, k, v, *(t for _, t in extras))
