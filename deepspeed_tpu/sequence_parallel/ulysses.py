"""DeepSpeed-Ulysses-style sequence parallelism.

The idea (absent from the reference snapshot; modern DeepSpeed's
``DistributedAttention`` wraps a local attention with two all-to-alls):
activations arrive sharded on the sequence dim over the ``seq`` mesh
axis. Attention needs the full sequence, but is embarrassingly parallel
over heads — so an all-to-all converts the seq shard into a head shard,
the unmodified local attention core runs on full sequences, and a second
all-to-all converts back.

TPU-native: a ``shard_map`` region with ``jax.lax.all_to_all`` over the
``seq`` axis (lowering to XLA AllToAll on ICI), composing with batch
sharding over data/fsdp and head sharding over model (tensor parallel).
"""

from functools import partial
from typing import Optional

import jax
from jax import lax
from jax.sharding import PartitionSpec as P

from ..comm.mesh import get_global_mesh
from ..utils.jax_compat import shard_map

# q/k/v/out layout everywhere: [batch, seq, heads, head_dim]
_BATCH_AXES = ("data", "fsdp")
_HEAD_AXIS = "model"
_SEQ_AXIS = "seq"


def _qkv_spec(q_shape, mesh, batch_axes, seq_axis, head_axis):
    return P(_fit_axes(q_shape[0], batch_axes, mesh), seq_axis,
             _fit_axes(q_shape[2], head_axis, mesh), None)


def _fit_axes(dim_size, axes, mesh):
    """Longest prefix of ``axes`` whose cumulative product divides dim_size.

    The engine traces the model on tiny sample batches (batch=1) where the
    full data/fsdp sharding can't apply; sharding the batch dim is a
    throughput concern, not a correctness one, so degrade gracefully."""
    kept = []
    prod = 1
    for a in (axes if isinstance(axes, (tuple, list)) else (axes,)):
        nxt = prod * mesh.shape.get(a, 1)
        if dim_size % nxt != 0:
            break
        kept.append(a)
        prod = nxt
    if not kept:
        return None
    return tuple(kept) if len(kept) > 1 else kept[0]


def ulysses_attention(q, k, v, *, causal=False, softmax_scale=None,
                      attn_fn=None, mesh=None, axis_name=_SEQ_AXIS,
                      batch_axes=_BATCH_AXES, head_axis=_HEAD_AXIS):
    """Full-sequence attention over seq-sharded inputs, [B, S, H, D] global.

    ``attn_fn(q, k, v, causal=..., softmax_scale=...)`` is the local
    attention core (default: the ops.transformer dispatch, so the Pallas
    flash kernel is used on TPU when eligible). Requires
    ``H / tp_degree`` divisible by the seq-axis size.
    """
    mesh = mesh or get_global_mesh()
    sp = mesh.shape[axis_name]
    if attn_fn is None:
        from ..ops.transformer.attention import attention
        attn_fn = partial(attention, seq_parallel="none")
    if sp == 1:
        return attn_fn(q, k, v, causal=causal, softmax_scale=softmax_scale)

    n_heads, seq_len = q.shape[2], q.shape[1]
    tp = mesh.shape.get(head_axis, 1)
    local_heads = n_heads // tp
    if local_heads % sp != 0:
        raise ValueError(
            f"Ulysses needs heads/tp ({n_heads}/{tp}={local_heads}) divisible "
            f"by the seq-parallel degree {sp}")
    if seq_len % sp != 0:
        raise ValueError(f"sequence length {seq_len} not divisible by sp={sp}")

    spec = _qkv_spec(q.shape, mesh, batch_axes, axis_name, head_axis)

    def local_fn(q, k, v):
        # [b, s/sp, h, d] -> [b, s, h/sp, d]: the head<->seq swap
        q, k, v = (lax.all_to_all(t, axis_name, split_axis=2, concat_axis=1,
                                  tiled=True) for t in (q, k, v))
        out = attn_fn(q, k, v, causal=causal, softmax_scale=softmax_scale)
        # [b, s, h/sp, d] -> [b, s/sp, h, d]
        return lax.all_to_all(out, axis_name, split_axis=1, concat_axis=2,
                              tiled=True)

    return shard_map(local_fn, mesh=mesh, in_specs=(spec, spec, spec),
                     out_specs=spec)(q, k, v)
