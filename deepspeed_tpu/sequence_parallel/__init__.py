"""Sequence / context parallelism (long-context training).

ABSENT in the reference snapshot (pre-0.10 DeepSpeed — SURVEY.md §2.2 row
SP/CP); built here as a first-class mesh axis because long-context is a
core capability of the modern framework this replaces. Two strategies:

- Ulysses (``ulysses_attention``): all-to-all that trades the sequence
  shard for a head shard around the attention core, so each device runs
  *full-sequence* attention on ``heads/sp`` heads. Communication is two
  all-to-alls per attention (O(S*D/P) per device), riding ICI.
- Ring attention (``ring_attention``): K/V blocks rotate around the
  ``seq`` axis ring via ``lax.ppermute`` while each device keeps its
  query shard, accumulating with an online (flash-style) softmax. No
  head-count divisibility requirement; comm overlaps with blockwise
  compute.

Both are ``shard_map`` regions over the global mesh, so they compose with
data/fsdp batch sharding and tensor-parallel head sharding, and nest
inside the engine's jitted train step.
"""

from .ulysses import ulysses_attention
from .ring import ring_attention

__all__ = ["ulysses_attention", "ring_attention"]
