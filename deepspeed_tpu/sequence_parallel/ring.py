"""Ring attention over the ``seq`` mesh axis.

Each device keeps its query shard resident and rotates K/V blocks around
the ring with ``lax.ppermute`` (XLA CollectivePermute -> nearest-neighbor
ICI hops), accumulating the attention output with an online flash-style
softmax. Memory per device is O(S/P) for K/V and O(S/P * D) for the
accumulator, so sequence length scales linearly with ring size — the
long-context capability the reference snapshot lacks (SURVEY.md §2.2).

Causality is enforced per block pair from the *global* block indices:
block ``src < my`` attends fully, ``src == my`` applies the triangular
mask, ``src > my`` contributes nothing (still computed — SPMD uniform —
but masked to -inf).
"""

from functools import partial

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from ..comm.mesh import get_global_mesh
from ..utils.jax_compat import shard_map
from .ulysses import _fit_axes

_BATCH_AXES = ("data", "fsdp")
_HEAD_AXIS = "model"
_SEQ_AXIS = "seq"

_NEG_INF = float(jnp.finfo(jnp.float32).min)


def _ring_local(q, k, v, *, axis_name, causal, softmax_scale):
    """Local shard computation: q/k/v [b, s_l, h, d]."""
    sp = lax.psum(1, axis_name)
    my = lax.axis_index(axis_name)
    b, s_l, h, d = q.shape
    scale = softmax_scale if softmax_scale is not None else d ** -0.5

    q32 = q.astype(jnp.float32) * scale
    qpos = jnp.arange(s_l)[:, None]          # local row offsets
    kpos = jnp.arange(s_l)[None, :]
    perm = [(i, (i + 1) % sp) for i in range(sp)]

    def step(carry, t):
        k_blk, v_blk, acc, m, denom = carry
        src = (my - t) % sp                  # global block index of k_blk
        # [b, h, s_l, s_l] logits
        logits = jnp.einsum("bqhd,bkhd->bhqk", q32,
                            k_blk.astype(jnp.float32))
        if causal:
            gq = my * s_l + qpos             # global positions
            gk = src * s_l + kpos
            logits = jnp.where((gk <= gq)[None, None], logits, _NEG_INF)
        m_new = jnp.maximum(m, logits.max(axis=-1))
        # rows with no valid key yet keep m == -inf; guard the exp args
        safe_m = jnp.where(jnp.isfinite(m_new), m_new, 0.0)
        p = jnp.exp(logits - safe_m[..., None])
        p = jnp.where(jnp.isfinite(logits), p, 0.0)
        corr = jnp.where(jnp.isfinite(m), jnp.exp(m - safe_m), 0.0)
        acc = acc * corr[..., None] + jnp.einsum(
            "bhqk,bkhd->bhqd", p, v_blk.astype(jnp.float32))
        denom = denom * corr + p.sum(axis=-1)
        k_blk = lax.ppermute(k_blk, axis_name, perm)
        v_blk = lax.ppermute(v_blk, axis_name, perm)
        return (k_blk, v_blk, acc, m_new, denom), None

    acc0 = jnp.zeros((b, h, s_l, d), jnp.float32)
    m0 = jnp.full((b, h, s_l), _NEG_INF, jnp.float32)
    den0 = jnp.zeros((b, h, s_l), jnp.float32)
    (_, _, acc, _, denom), _ = lax.scan(
        step, (k, v, acc0, m0, den0), jnp.arange(sp))

    out = acc / jnp.maximum(denom, 1e-30)[..., None]
    return out.transpose(0, 2, 1, 3).astype(q.dtype)   # [b, s_l, h, d]


def ring_attention(q, k, v, *, causal=True, softmax_scale=None, mesh=None,
                   axis_name=_SEQ_AXIS, batch_axes=_BATCH_AXES,
                   head_axis=_HEAD_AXIS):
    """Ring attention over seq-sharded [B, S, H, D] global arrays.

    Unlike Ulysses there is no head-divisibility requirement, so it also
    covers few-head / GQA-ish models; comm is P-1 neighbor permutes.
    """
    mesh = mesh or get_global_mesh()
    sp = mesh.shape[axis_name]
    if sp == 1:
        from ..ops.transformer.attention import attention as attn_fn
        return attn_fn(q, k, v, causal=causal, softmax_scale=softmax_scale)
    if q.shape[1] % sp != 0:
        raise ValueError(f"sequence length {q.shape[1]} not divisible by sp={sp}")

    spec = P(_fit_axes(q.shape[0], batch_axes, mesh), axis_name,
             _fit_axes(q.shape[2], head_axis, mesh), None)
    local = partial(_ring_local, axis_name=axis_name, causal=causal,
                    softmax_scale=softmax_scale)
    return shard_map(local, mesh=mesh, in_specs=(spec, spec, spec),
                     out_specs=spec)(q, k, v)
