"""Ring attention over the ``seq`` mesh axis.

Each device keeps its query shard resident and rotates K/V blocks around
the ring with ``lax.ppermute`` (XLA CollectivePermute -> nearest-neighbor
ICI hops), accumulating the attention output with an online flash-style
softmax. Memory per device is O(S/P) for K/V and O(S/P * D) for the
accumulator, so sequence length scales linearly with ring size — the
long-context capability the reference snapshot lacks (SURVEY.md §2.2).

Causality is enforced per block pair from the *global* block indices:
block ``src < my`` attends fully, ``src == my`` applies the triangular
mask, ``src > my`` contributes nothing (still computed — SPMD uniform —
but masked to -inf).
"""

from functools import partial

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from ..comm.mesh import get_global_mesh
from ..utils.jax_compat import shard_map
from .ulysses import _fit_axes

_BATCH_AXES = ("data", "fsdp")
_HEAD_AXIS = "model"
_SEQ_AXIS = "seq"

_NEG_INF = float(jnp.finfo(jnp.float32).min)


def _ring_local(q, k, v, bias=None, mask=None, dropout_rng=None, *,
                axis_name, causal, softmax_scale, dropout_rate=0.0,
                block_q=1024):
    """Local shard computation: q/k/v [b, s_l, h, d].

    ``bias``/``mask`` arrive with their sq dim already local (sharded over
    the ring axis, or broadcast size-1) and their sk dim GLOBAL — each
    step dynamic-slices the current source block's key columns. Dropout
    samples per (q-chunk, k-block) pair from fold_in: iid bernoulli with
    the configured rate, deterministic in the ring layout, but not
    bit-identical to the replicated path's sample (unlike Ulysses, whose
    local logits tile the global [b,h,sq,sk] array).

    Memory: when the local shard exceeds ``block_q`` rows, each ring step
    processes q in chunks (row-independent online-softmax updates mapped
    over a rematerialized per-chunk body), bounding live logits at
    [b, h, block_q, s_l] in BOTH fwd and bwd instead of [b, h, s_l, s_l]
    — 128k-class global sequences stay trainable on modest rings."""
    sp = lax.psum(1, axis_name)
    my = lax.axis_index(axis_name)
    b, s_l, h, d = q.shape
    scale = softmax_scale if softmax_scale is not None else d ** -0.5

    import math
    cq = min(block_q, s_l)
    if s_l % cq != 0:
        # largest divisor <= block_q keeps the memory bound (a ragged
        # block_q must not silently reintroduce O(s_l^2) logits); only
        # pathological s_l (no divisor >= 128) falls back to one chunk
        cq = math.gcd(s_l, cq)
        if cq < min(128, s_l):
            cq = s_l
    n_chunks = s_l // cq

    q32 = q.astype(jnp.float32) * scale
    kpos = jnp.arange(s_l)[None, :]
    perm = [(i, (i + 1) % sp) for i in range(sp)]
    dropout_on = dropout_rate > 0.0 and dropout_rng is not None

    def chunk_update(k_blk, v_blk, src, qo, q_c, acc_c, m_c, den_c):
        """Online-softmax update for q rows [qo, qo+cq) against k_blk.
        q_c [b, cq, h, d]; acc_c [b, h, cq, d]; m_c/den_c [b, h, cq]."""
        logits = jnp.einsum("bqhd,bkhd->bhqk", q_c,
                            k_blk.astype(jnp.float32))
        if bias is not None:
            # sk dim: global (step-sliced), already-local, or broadcast 1
            bias_blk = lax.dynamic_slice_in_dim(
                bias, src * s_l, s_l, axis=-1) \
                if bias.shape[-1] not in (s_l, 1) else bias
            if bias_blk.shape[-2] != 1:
                bias_blk = lax.dynamic_slice_in_dim(bias_blk, qo, cq, axis=-2)
            logits = logits + bias_blk
        if causal:
            gq = my * s_l + qo + jnp.arange(cq)[:, None]  # global positions
            gk = src * s_l + kpos
            logits = jnp.where((gk <= gq)[None, None], logits, _NEG_INF)
        if mask is not None:
            mask_blk = lax.dynamic_slice_in_dim(
                mask, src * s_l, s_l, axis=-1) \
                if mask.shape[-1] not in (s_l, 1) else mask
            if mask_blk.shape[-2] != 1:
                mask_blk = lax.dynamic_slice_in_dim(mask_blk, qo, cq, axis=-2)
            logits = jnp.where(mask_blk, logits, _NEG_INF)
        m_new = jnp.maximum(m_c, logits.max(axis=-1))
        # rows with no valid key yet keep m ~ _NEG_INF, which is the
        # FINITE finfo.min — threshold guards (like the flash kernel's
        # NEG_INF/2 tests), not isfinite, are what actually fire here
        safe_m = jnp.where(m_new > _NEG_INF / 2, m_new, 0.0)
        p = jnp.exp(logits - safe_m[..., None])
        p = jnp.where(logits > _NEG_INF / 2, p, 0.0)
        corr = jnp.where(m_c > _NEG_INF / 2, jnp.exp(m_c - safe_m), 0.0)
        p_use = p
        if dropout_on:
            # dropout zeroes softmax PROBS: the denominator accumulates
            # the un-dropped sums, the numerator the dropped ones
            blk_rng = jax.random.fold_in(
                jax.random.fold_in(dropout_rng, my * sp + src),
                qo // cq if n_chunks > 1 else 0)
            keep = jax.random.bernoulli(blk_rng, 1.0 - dropout_rate, p.shape)
            p_use = jnp.where(keep, p / (1.0 - dropout_rate), 0.0)
        acc_c = acc_c * corr[..., None] + jnp.einsum(
            "bhqk,bkhd->bhqd", p_use, v_blk.astype(jnp.float32))
        den_c = den_c * corr + p.sum(axis=-1)
        return acc_c, m_new, den_c

    # chunk-major state layout for the WHOLE scan (one reshape in, one
    # out — per-step transposes of the carry would copy acc each step);
    # q chunks are precomputed once, loop-invariant
    q_cs = q32.reshape(b, n_chunks, cq, h, d).transpose(1, 0, 2, 3, 4)
    offs = jnp.arange(n_chunks) * cq

    def step(carry, t):
        k_blk, v_blk, acc, m, denom = carry   # acc [nq,b,h,cq,d] etc.
        src = (my - t) % sp                  # global block index of k_blk
        if n_chunks == 1:
            a, mm, dd = chunk_update(k_blk, v_blk, src, 0,
                                     q_cs[0], acc[0], m[0], denom[0])
            acc, m, denom = a[None], mm[None], dd[None]
        else:
            # chunk rows are independent: map a REMATERIALIZED per-chunk
            # body so neither fwd nor bwd ever holds more than one
            # chunk's logits
            @jax.checkpoint
            def one(args):
                qo, q_c, a_c, m_c, d_c = args
                return chunk_update(k_blk, v_blk, src, qo, q_c, a_c, m_c, d_c)

            acc, m, denom = lax.map(one, (offs, q_cs, acc, m, denom))
        k_blk = lax.ppermute(k_blk, axis_name, perm)
        v_blk = lax.ppermute(v_blk, axis_name, perm)
        return (k_blk, v_blk, acc, m, denom), None

    acc0 = jnp.zeros((n_chunks, b, h, cq, d), jnp.float32)
    m0 = jnp.full((n_chunks, b, h, cq), _NEG_INF, jnp.float32)
    den0 = jnp.zeros((n_chunks, b, h, cq), jnp.float32)
    (_, _, acc, _, denom), _ = lax.scan(
        step, (k, v, acc0, m0, den0), jnp.arange(sp))

    acc = acc.transpose(1, 2, 0, 3, 4).reshape(b, h, s_l, d)
    denom = denom.transpose(1, 2, 0, 3).reshape(b, h, s_l)
    out = acc / jnp.maximum(denom, 1e-30)[..., None]
    return out.transpose(0, 2, 1, 3).astype(q.dtype)   # [b, s_l, h, d]


def ring_attention(q, k, v, *, bias=None, mask=None, causal=True,
                   softmax_scale=None, dropout_rate=0.0, dropout_rng=None,
                   deterministic=True, mesh=None, axis_name=_SEQ_AXIS,
                   batch_axes=_BATCH_AXES, head_axis=_HEAD_AXIS,
                   block_q=1024):
    """Ring attention over seq-sharded [B, S, H, D] global arrays.

    Unlike Ulysses there is no head-divisibility requirement, so it also
    covers few-head / GQA-ish models; comm is P-1 neighbor permutes.

    bias/mask ([b|1, h|1, sq|1, sk]): the sq dim is sharded over the ring
    (when full-size), the sk dim stays global per device and each step
    slices the current source block — O(S^2/P) operand memory, the price
    of an explicit dense mask (banded/causal patterns should use
    ``causal`` which is index-computed, O(1)). Dropout is iid per
    (q-block, k-block) via fold_in — not bit-identical to the replicated
    path's sample (see _ring_local)."""
    mesh = mesh or get_global_mesh()
    sp = mesh.shape[axis_name]
    dropout_on = dropout_rate > 0.0 and not deterministic
    if dropout_on and dropout_rng is None:
        raise ValueError("ring_attention: dropout_rate > 0 with "
                         "deterministic=False requires dropout_rng")
    if sp == 1:
        from ..ops.transformer.attention import attention as attn_fn
        return attn_fn(q, k, v, bias=bias, mask=mask, causal=causal,
                       softmax_scale=softmax_scale,
                       dropout_rate=dropout_rate, dropout_rng=dropout_rng,
                       deterministic=deterministic)
    if q.shape[1] % sp != 0:
        raise ValueError(f"sequence length {q.shape[1]} not divisible by sp={sp}")

    spec = P(_fit_axes(q.shape[0], batch_axes, mesh), axis_name,
             _fit_axes(q.shape[2], head_axis, mesh), None)

    def _op_spec(t):
        # [b|1, h|1, sq|1, sk]: shard sq over the ring when full-size;
        # sk global (stepwise-sliced); batch/head when real and divisible
        b, h, sq = t.shape[0], t.shape[1], t.shape[2]
        return P(_fit_axes(b, batch_axes, mesh) if b > 1 else None,
                 _fit_axes(h, head_axis, mesh) if h > 1 else None,
                 axis_name if sq == q.shape[1] and sq % sp == 0 else None,
                 None)

    extras = [("bias", bias), ("mask", mask),
              ("dropout_rng", dropout_rng if dropout_on else None)]
    present = [(n, t) for n, t in extras if t is not None]
    extra_specs = tuple(P() if n == "dropout_rng" else _op_spec(t)
                        for n, t in present)
    names = tuple(n for n, _ in present)

    def local(q, k, v, *extra):
        ops = dict(zip(names, extra))
        return _ring_local(q, k, v, bias=ops.get("bias"),
                           mask=ops.get("mask"),
                           dropout_rng=ops.get("dropout_rng"),
                           axis_name=axis_name, causal=causal,
                           softmax_scale=softmax_scale,
                           dropout_rate=dropout_rate if dropout_on else 0.0,
                           block_q=block_q)

    return shard_map(local, mesh=mesh,
                     in_specs=(spec, spec, spec) + extra_specs,
                     out_specs=spec)(q, k, v, *(t for _, t in present))
