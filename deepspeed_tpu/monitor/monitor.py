"""Metrics monitor fan-out.

Reference: deepspeed/monitor/monitor.py:24 MonitorMaster fans (label, value,
step) events to TensorBoard/W&B/CSV writers per the config blocks. Straight
port; writers import lazily so missing backends degrade to warnings.
"""

import os
from ..utils.logging import logger


class Monitor:
    def __init__(self, config):
        self.config = config

    def write_events(self, event_list):
        raise NotImplementedError


class TensorBoardMonitor(Monitor):
    def __init__(self, cfg):
        super().__init__(cfg)
        self.summary_writer = None
        try:
            from torch.utils.tensorboard import SummaryWriter
        except (ImportError, AttributeError, TypeError) as e:
            # not installed, or the classic torch/protobuf/distutils
            # version-skew crashes that surface as AttributeError/TypeError
            logger.warning(f"TensorBoard monitor disabled: {e}")
            return
        try:
            log_dir = os.path.join(cfg.output_path or "./runs", cfg.job_name)
            self.summary_writer = SummaryWriter(log_dir=log_dir)
        except (OSError, ValueError, RuntimeError, TypeError) as e:
            # unwritable log dir / malformed config (e.g. job_name: null
            # -> TypeError in os.path.join) / writer init failure:
            # degrade, training must not die for a monitor. Anything else
            # propagates.
            logger.warning(f"TensorBoard monitor disabled: {e}")
            self.summary_writer = None

    def write_events(self, event_list, flush=True):
        if self.summary_writer is None:
            return
        for label, value, step in event_list:
            self.summary_writer.add_scalar(label, value, step)
        if flush:
            self.summary_writer.flush()


class WandbMonitor(Monitor):
    def __init__(self, cfg):
        super().__init__(cfg)
        self.enabled = False
        try:
            import wandb
        except (ImportError, AttributeError, TypeError) as e:
            # not installed, or dependency version skew at import time
            logger.warning(f"W&B monitor disabled: {e}")
            return
        # wandb.Error is the root of wandb's own failures (auth, comms);
        # OSError covers offline/disk issues. Anything else propagates.
        try:
            wandb.init(project=cfg.project, group=cfg.group, entity=cfg.team)
            self._wandb = wandb
            self.enabled = True
        except (wandb.Error, OSError, ValueError, RuntimeError) as e:
            logger.warning(f"W&B monitor disabled: {e}")

    def write_events(self, event_list):
        if not self.enabled:
            return
        for label, value, step in event_list:
            self._wandb.log({label: value}, step=step)


class csv_monitor(Monitor):
    def __init__(self, cfg):
        super().__init__(cfg)
        self.log_dir = os.path.join(cfg.output_path or "./csv_logs", cfg.job_name)
        os.makedirs(self.log_dir, exist_ok=True)

    def write_events(self, event_list):
        import csv
        for label, value, step in event_list:
            fname = os.path.join(self.log_dir,
                                 label.replace("/", "_") + ".csv")
            new = not os.path.exists(fname)
            with open(fname, "a", newline="") as f:
                w = csv.writer(f)
                if new:
                    w.writerow(["step", label])
                w.writerow([step, value])


class MonitorMaster(Monitor):
    """Dispatches to every enabled writer (rank 0 only)."""

    def __init__(self, ds_config):
        self.tb_monitor = None
        self.wandb_monitor = None
        self.csv_monitor = None
        from .. import comm as dist
        self._rank0 = dist.get_rank() == 0
        if self._rank0:
            if ds_config.tensorboard.enabled:
                self.tb_monitor = TensorBoardMonitor(ds_config.tensorboard)
            if ds_config.wandb.enabled:
                self.wandb_monitor = WandbMonitor(ds_config.wandb)
            if ds_config.csv_monitor.enabled:
                self.csv_monitor = csv_monitor(ds_config.csv_monitor)

    @property
    def enabled(self):
        return any([self.tb_monitor, self.wandb_monitor, self.csv_monitor])

    def write_events(self, event_list):
        if not self._rank0:
            return
        for m in (self.tb_monitor, self.wandb_monitor, self.csv_monitor):
            if m is not None:
                m.write_events(event_list)

    def write_event(self, label, value, step):
        """One immediate event — for rare out-of-band transitions
        (resilience rollbacks, emergency saves) that must reach the
        writers even if the run dies before the next buffered flush."""
        self.write_events([(label, value, step)])
