// Host-side vectorized Adagrad for ZeRO-Offload.
//
// TPU-native analog of the reference's csrc/adagrad/cpu_adagrad.cpp
// (AVX SIMD + OpenMP): accumulator state lives in host RAM as fp32; each
// step consumes the device-reduced gradient shard and produces updated
// master weights plus an optional bf16 downcast for the device — the
// same C-ABI/ctypes pattern as cpu_adam.cpp.
//
// Build: g++ -O3 -march=native -fopenmp -fPIC -shared

#include <cmath>
#include <cstdint>
#include <cstring>
#include <unordered_map>
#include <mutex>

namespace {

struct AdagradState {
  float lr, eps, weight_decay;
};

std::unordered_map<int, AdagradState> g_states;
std::mutex g_mu;

inline uint16_t f32_to_bf16(float f) {
  uint32_t bits;
  std::memcpy(&bits, &f, 4);
  uint32_t rounding = 0x7fff + ((bits >> 16) & 1);
  return static_cast<uint16_t>((bits + rounding) >> 16);
}

}  // namespace

extern "C" {

int ds_adagrad_create(int id, float lr, float eps, float weight_decay) {
  std::lock_guard<std::mutex> lock(g_mu);
  g_states[id] = AdagradState{lr, eps, weight_decay};
  return 0;
}

int ds_adagrad_destroy(int id) {
  std::lock_guard<std::mutex> lock(g_mu);
  g_states.erase(id);
  return 0;
}

// One fused step over a flat shard. params/accum fp32 updated in place;
// grads fp32. lr < 0 keeps the lr set at create time. Matches
// optax.adagrad: accum += g^2; p -= lr * g / (sqrt(accum) + eps), with
// weight decay as classic L2 into the gradient (reference semantics).
int ds_adagrad_update(int id, float lr, const float* grads, float* params,
                      float* exp_avg_sq, int64_t n,
                      uint16_t* params_out_bf16) {
  AdagradState* st;
  {
    std::lock_guard<std::mutex> lock(g_mu);
    auto it = g_states.find(id);
    if (it == g_states.end()) return -1;
    st = &it->second;
  }
  const float step_lr = lr >= 0.f ? lr : st->lr;
  const float eps = st->eps;
  const float wd = st->weight_decay;

#pragma omp parallel for schedule(static)
  for (int64_t i = 0; i < n; ++i) {
    float g = grads[i];
    float p = params[i];
    if (wd != 0.f) g += wd * p;
    float a = exp_avg_sq[i] + g * g;
    exp_avg_sq[i] = a;
    p = p - step_lr * g / (std::sqrt(a) + eps);
    params[i] = p;
    if (params_out_bf16) params_out_bf16[i] = f32_to_bf16(p);
  }
  return 0;
}

int ds_adagrad_simd_level(void) {
#if defined(__AVX2__)
  return 2;
#else
  return 1;
#endif
}

}  // extern "C"
