// Host-side vectorized Adam/AdamW for ZeRO-Offload.
//
// TPU-native analog of the reference's csrc/adam/cpu_adam.cpp (AVX256/512
// SIMD via csrc/includes/simd.h, OpenMP over parameter chunks): optimizer
// state lives in host RAM as fp32; each step consumes the device-reduced
// gradient shard and produces updated master weights plus a downcast
// bf16/fp32 copy for the device. Exposed as a plain C ABI consumed via
// ctypes (no pybind11 in this image).
//
// Build: g++ -O3 -march=native -fopenmp -fPIC -shared

#include <cmath>
#include <cstdint>
#include <cstring>
#include <unordered_map>
#include <mutex>

namespace {

struct AdamState {
  float lr, beta1, beta2, eps, weight_decay;
  bool adamw;  // decoupled weight decay (AdamW) vs L2-into-grad (Adam)
};

std::unordered_map<int, AdamState> g_states;
std::mutex g_mu;

inline uint16_t f32_to_bf16(float f) {
  uint32_t bits;
  std::memcpy(&bits, &f, 4);
  // round-to-nearest-even, matching XLA's f32->bf16 conversion
  uint32_t rounding = 0x7fff + ((bits >> 16) & 1);
  return static_cast<uint16_t>((bits + rounding) >> 16);
}

}  // namespace

extern "C" {

int ds_adam_create(int id, float lr, float beta1, float beta2, float eps,
                   float weight_decay, int adamw) {
  std::lock_guard<std::mutex> lock(g_mu);
  g_states[id] = AdamState{lr, beta1, beta2, eps, weight_decay, adamw != 0};
  return 0;
}

int ds_adam_destroy(int id) {
  std::lock_guard<std::mutex> lock(g_mu);
  g_states.erase(id);
  return 0;
}

// One fused step over a flat shard. params/m/v fp32 (updated in place),
// grads fp32. ``step`` is the 1-based optimizer step for bias correction —
// caller-owned so every leaf/shard of one optimizer step shares it. If
// params_out_bf16 != nullptr, also writes a bf16 copy of the updated
// weights (the buffer handed back to the device). lr < 0 keeps the lr set
// at create time.
int ds_adam_update(int id, int64_t step, float lr, const float* grads,
                   float* params, float* exp_avg, float* exp_avg_sq,
                   int64_t n, uint16_t* params_out_bf16) {
  AdamState* st;
  {
    std::lock_guard<std::mutex> lock(g_mu);
    auto it = g_states.find(id);
    if (it == g_states.end()) return -1;
    st = &it->second;
  }
  if (step < 1) return -2;
  const float step_lr = lr >= 0.f ? lr : st->lr;
  const float b1 = st->beta1, b2 = st->beta2, eps = st->eps;
  const float wd = st->weight_decay;
  const bool adamw = st->adamw;
  const float bc1 = 1.f - std::pow(b1, static_cast<float>(step));
  const float bc2 = 1.f - std::pow(b2, static_cast<float>(step));
  const float step_size = step_lr / bc1;
  const float inv_sqrt_bc2 = 1.f / std::sqrt(bc2);
  const float decay_factor = adamw ? (1.f - step_lr * wd) : 1.f;

  // The loop auto-vectorizes under -O3 -march=native (vsqrtps/vfmadd on
  // AVX2 hosts — same effect as the reference's hand-written simd.h).
#pragma omp parallel for schedule(static)
  for (int64_t i = 0; i < n; ++i) {
    float g = grads[i];
    float p = params[i];
    if (!adamw && wd != 0.f) g += wd * p;  // classic Adam L2
    float m = b1 * exp_avg[i] + (1.f - b1) * g;
    float v = b2 * exp_avg_sq[i] + (1.f - b2) * g * g;
    exp_avg[i] = m;
    exp_avg_sq[i] = v;
    p = p * decay_factor - step_size * m / (std::sqrt(v) * inv_sqrt_bc2 + eps);
    params[i] = p;
    if (params_out_bf16) params_out_bf16[i] = f32_to_bf16(p);
  }
  return 0;
}

// Capability probe for ds_report: 2 = AVX2 build, 1 = scalar, 0 = n/a.
int ds_adam_simd_level(void) {
#if defined(__AVX2__)
  return 2;
#else
  return 1;
#endif
}

}  // extern "C"
