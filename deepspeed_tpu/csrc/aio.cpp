// Asynchronous file I/O for NVMe offload (ZeRO-Infinity swap).
//
// TPU-native analog of the reference's csrc/aio/ (libaio + pthread queue,
// deepspeed_aio_thread.cpp): a worker-thread pool drains a request queue
// of pread/pwrite jobs against local SSD, so optimizer/param shard swaps
// overlap with TPU compute. Plain C ABI for ctypes (no pybind11 here).
// Uses positional pread/pwrite on a per-request fd — simpler than
// io_submit and just as fast for the large sequential blocks this
// workload issues (multi-MB shard files).
//
// Build: g++ -O3 -fPIC -shared -pthread

#include <fcntl.h>
#include <unistd.h>
#include <cerrno>
#include <condition_variable>
#include <cstdint>
#include <cstring>
#include <deque>
#include <mutex>
#include <string>
#include <thread>
#include <unordered_map>
#include <unordered_set>
#include <vector>

namespace {

struct Request {
  int64_t ticket;
  bool write;
  std::string path;
  void* buf;
  int64_t nbytes;
  int64_t offset;
};

struct Handle {
  std::vector<std::thread> workers;
  std::deque<Request> queue;
  std::mutex mu;
  std::condition_variable cv_submit, cv_done;
  std::unordered_map<int64_t, int> done;  // ticket -> errno (0 = ok)
  std::unordered_set<int64_t> pending;    // submitted, not yet completed
  int64_t next_ticket = 1;
  int64_t inflight = 0;
  bool shutdown = false;

  explicit Handle(int n_threads) {
    for (int i = 0; i < n_threads; ++i)
      workers.emplace_back([this] { this->run(); });
  }

  ~Handle() {
    {
      std::lock_guard<std::mutex> lock(mu);
      shutdown = true;
    }
    cv_submit.notify_all();
    for (auto& t : workers) t.join();
  }

  static int do_io(const Request& r) {
    int flags = r.write ? (O_WRONLY | O_CREAT) : O_RDONLY;
    int fd = ::open(r.path.c_str(), flags, 0644);
    if (fd < 0) return errno ? errno : EIO;
    char* p = static_cast<char*>(r.buf);
    int64_t remaining = r.nbytes;
    int64_t off = r.offset;
    int err = 0;
    while (remaining > 0) {
      ssize_t got = r.write ? ::pwrite(fd, p, remaining, off)
                            : ::pread(fd, p, remaining, off);
      if (got < 0) {
        if (errno == EINTR) continue;
        err = errno ? errno : EIO;
        break;
      }
      if (got == 0) {  // short read: file smaller than requested
        err = EIO;
        break;
      }
      p += got;
      off += got;
      remaining -= got;
    }
    ::close(fd);
    return err;
  }

  void run() {
    for (;;) {
      Request r;
      {
        std::unique_lock<std::mutex> lock(mu);
        cv_submit.wait(lock, [this] { return shutdown || !queue.empty(); });
        if (queue.empty()) return;  // shutdown
        r = std::move(queue.front());
        queue.pop_front();
      }
      int err = do_io(r);
      {
        std::lock_guard<std::mutex> lock(mu);
        done[r.ticket] = err;
        pending.erase(r.ticket);
        --inflight;
      }
      cv_done.notify_all();
    }
  }

  int64_t submit(bool write, const char* path, void* buf, int64_t nbytes,
                 int64_t offset) {
    int64_t t;
    {
      std::lock_guard<std::mutex> lock(mu);
      if (shutdown) return -1;
      t = next_ticket++;
      queue.push_back(Request{t, write, path, buf, nbytes, offset});
      pending.insert(t);
      ++inflight;
    }
    cv_submit.notify_one();
    return t;
  }

  // Safe against double-wait: a ticket that is neither pending nor in
  // done was already consumed (or never issued) — return 0 instead of
  // blocking forever.
  int wait(int64_t ticket) {
    std::unique_lock<std::mutex> lock(mu);
    cv_done.wait(lock, [&] {
      return done.count(ticket) > 0 || pending.count(ticket) == 0;
    });
    auto it = done.find(ticket);
    if (it == done.end()) return 0;
    int err = it->second;
    done.erase(it);
    return err;
  }

  // Barrier only: waits until no request is in flight. Completion records
  // are NOT consumed — callers still wait(ticket) individually (so a
  // barrier between prefetch and swap_in cannot orphan the read ticket).
  int wait_all() {
    std::unique_lock<std::mutex> lock(mu);
    cv_done.wait(lock, [&] { return inflight == 0; });
    int worst = 0;
    for (auto& kv : done)
      if (kv.second != 0) worst = kv.second;
    return worst;
  }
};

}  // namespace

extern "C" {

void* ds_aio_new(int n_threads) {
  if (n_threads <= 0) n_threads = 4;
  return new Handle(n_threads);
}

void ds_aio_free(void* h) { delete static_cast<Handle*>(h); }

// Returns a ticket (>0) or -1. Buffer must stay alive until waited on.
int64_t ds_aio_pread(void* h, const char* path, void* buf, int64_t nbytes,
                     int64_t offset) {
  return static_cast<Handle*>(h)->submit(false, path, buf, nbytes, offset);
}

int64_t ds_aio_pwrite(void* h, const char* path, const void* buf,
                      int64_t nbytes, int64_t offset) {
  return static_cast<Handle*>(h)->submit(true, path, const_cast<void*>(buf),
                                         nbytes, offset);
}

// 0 on success, else errno of the failed transfer.
int ds_aio_wait(void* h, int64_t ticket) {
  return static_cast<Handle*>(h)->wait(ticket);
}

int ds_aio_wait_all(void* h) { return static_cast<Handle*>(h)->wait_all(); }

}  // extern "C"
