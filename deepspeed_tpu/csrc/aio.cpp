// Asynchronous file I/O for NVMe offload (ZeRO-Infinity swap).
//
// TPU-native analog of the reference's csrc/aio/ (libaio + pthread queue,
// deepspeed_aio_thread.cpp). Two engines behind one C ABI:
//
// 1. io_uring (preferred): raw-syscall ring (no liburing in the image) —
//    submission enqueues an SQE and returns; the KERNEL performs the
//    transfer with no dedicated userspace thread, and waits reap CQEs.
//    This is the genuinely-async engine class the reference gets from
//    libaio io_submit.
// 2. worker-thread pool (fallback when io_uring_setup is unavailable,
//    e.g. seccomp-filtered sandboxes): threads drain a queue of
//    positional pread/pwrite jobs.
//
// Plain C ABI for ctypes (no pybind11 here).
// Build: g++ -O3 -fPIC -shared -pthread

#include <fcntl.h>
#include <linux/io_uring.h>
#include <sys/mman.h>
#include <sys/syscall.h>
#include <unistd.h>
#include <atomic>
#include <cerrno>
#include <condition_variable>
#include <cstdint>
#include <cstdlib>
#include <cstring>
#include <deque>
#include <mutex>
#include <string>
#include <thread>
#include <unordered_map>
#include <unordered_set>
#include <vector>

namespace {

struct Request {
  int64_t ticket;
  bool write;
  std::string path;
  void* buf;
  int64_t nbytes;
  int64_t offset;
};

// ---------------------------------------------------------------------
// io_uring engine (raw syscalls; see file header)
// ---------------------------------------------------------------------

struct UringOp {
  int fd;
  bool write;
  char* buf;        // next byte to transfer
  int64_t remaining;
  int64_t offset;
};

class UringEngine {
 public:
  static UringEngine* TryCreate(unsigned entries) {
    if (const char* f = std::getenv("DS_TPU_AIO_FORCE_THREADS"))
      if (f[0] == '1') return nullptr;
    io_uring_params p;
    std::memset(&p, 0, sizeof(p));
    int fd = static_cast<int>(syscall(__NR_io_uring_setup, entries, &p));
    if (fd < 0) return nullptr;
    auto* e = new UringEngine();
    if (!e->init(fd, p)) {
      delete e;  // init() stored fd in ring_fd_; the dtor closes it once
      return nullptr;
    }
    return e;
  }

  // Drain every in-flight op before tearing the ring down — the thread
  // engine's destructor joins its workers, giving the same guarantee
  // that queued writes land and the kernel stops touching user buffers.
  ~UringEngine() {
    if (cqes_) {
      std::lock_guard<std::mutex> lock(mu_);
      while (!ops_.empty()) {
        enter_getevents();
        drain_cq_locked();
      }
    }
    if (sq_ring_) ::munmap(sq_ring_, sq_ring_sz_);
    if (cq_ring_ && cq_ring_ != sq_ring_) ::munmap(cq_ring_, cq_ring_sz_);
    if (sqes_) ::munmap(sqes_, sqes_sz_);
    if (ring_fd_ >= 0) ::close(ring_fd_);
  }

  int64_t submit(bool write, const char* path, void* buf, int64_t nbytes,
                 int64_t offset) {
    int flags = write ? (O_WRONLY | O_CREAT) : O_RDONLY;
    int fd = ::open(path, flags, 0644);
    std::unique_lock<std::mutex> lock(mu_);
    if (fd < 0) {  // error surfaces at wait(), like the thread engine
      int64_t t = next_ticket_++;
      done_[t] = errno ? errno : EIO;
      return t;
    }
    int64_t t = next_ticket_++;
    if (nbytes == 0) {  // zero-length transfer: trivially complete
      ::close(fd);
      done_[t] = 0;
      return t;
    }
    // bound in-flight ops to the SQ depth so completions can never
    // overflow the CQ ring (cq_entries = 2 * sq_entries)
    while (ops_.size() >= entries_) {
      drain_cq_locked();
      if (ops_.size() < entries_) break;
      lock.unlock();
      enter_getevents();
      lock.lock();
    }
    ops_[t] = UringOp{fd, write, static_cast<char*>(buf), nbytes, offset};
    push_sqe_locked(t);
    return t;
  }

  int wait(int64_t ticket) {
    std::unique_lock<std::mutex> lock(mu_);
    for (;;) {
      drain_cq_locked();
      auto it = done_.find(ticket);
      if (it != done_.end()) {
        int err = it->second;
        done_.erase(it);
        return err;
      }
      if (ops_.find(ticket) == ops_.end()) return 0;  // double-wait
      // block OUTSIDE the lock so concurrent submits keep flowing
      lock.unlock();
      enter_getevents();
      lock.lock();
    }
  }

  int wait_all() {
    std::unique_lock<std::mutex> lock(mu_);
    for (;;) {
      drain_cq_locked();
      if (ops_.empty()) break;
      lock.unlock();
      enter_getevents();
      lock.lock();
    }
    int worst = 0;
    for (auto& kv : done_)
      if (kv.second != 0) worst = kv.second;
    return worst;
  }

 private:
  bool init(int fd, const io_uring_params& p) {
    ring_fd_ = fd;
    entries_ = p.sq_entries;
    sq_ring_sz_ = p.sq_off.array + p.sq_entries * sizeof(unsigned);
    cq_ring_sz_ = p.cq_off.cqes + p.cq_entries * sizeof(io_uring_cqe);
    bool single_map = p.features & IORING_FEAT_SINGLE_MMAP;
    if (single_map && cq_ring_sz_ > sq_ring_sz_) sq_ring_sz_ = cq_ring_sz_;
    sq_ring_ = ::mmap(nullptr, sq_ring_sz_, PROT_READ | PROT_WRITE,
                      MAP_SHARED | MAP_POPULATE, fd, IORING_OFF_SQ_RING);
    if (sq_ring_ == MAP_FAILED) { sq_ring_ = nullptr; return false; }
    if (single_map) {
      cq_ring_ = sq_ring_;
    } else {
      cq_ring_ = ::mmap(nullptr, cq_ring_sz_, PROT_READ | PROT_WRITE,
                        MAP_SHARED | MAP_POPULATE, fd, IORING_OFF_CQ_RING);
      if (cq_ring_ == MAP_FAILED) { cq_ring_ = nullptr; return false; }
    }
    sqes_sz_ = p.sq_entries * sizeof(io_uring_sqe);
    sqes_ = static_cast<io_uring_sqe*>(
        ::mmap(nullptr, sqes_sz_, PROT_READ | PROT_WRITE,
               MAP_SHARED | MAP_POPULATE, fd, IORING_OFF_SQES));
    if (sqes_ == MAP_FAILED) { sqes_ = nullptr; return false; }

    auto* sq = static_cast<char*>(sq_ring_);
    sq_head_ = reinterpret_cast<std::atomic<unsigned>*>(sq + p.sq_off.head);
    sq_tail_ = reinterpret_cast<std::atomic<unsigned>*>(sq + p.sq_off.tail);
    sq_mask_ = *reinterpret_cast<unsigned*>(sq + p.sq_off.ring_mask);
    sq_array_ = reinterpret_cast<unsigned*>(sq + p.sq_off.array);
    auto* cq = static_cast<char*>(cq_ring_);
    cq_head_ = reinterpret_cast<std::atomic<unsigned>*>(cq + p.cq_off.head);
    cq_tail_ = reinterpret_cast<std::atomic<unsigned>*>(cq + p.cq_off.tail);
    cq_mask_ = *reinterpret_cast<unsigned*>(cq + p.cq_off.ring_mask);
    cqes_ = reinterpret_cast<io_uring_cqe*>(cq + p.cq_off.cqes);
    return true;
  }

  void enter_getevents() {
    // tolerate EINTR; any other failure leaves the CQ state for the
    // caller's drain to observe (non-blocking poll next round)
    for (;;) {
      long r = syscall(__NR_io_uring_enter, ring_fd_, 0u, 1u,
                       IORING_ENTER_GETEVENTS, nullptr, 0);
      if (r >= 0 || errno != EINTR) return;
    }
  }

  // Publish one SQE for an op already in ops_ and hand it to the kernel.
  // The in-flight bound (<= sq entries) plus the synchronous enter after
  // every publish guarantees a free SQ slot here.
  void push_sqe_locked(int64_t ticket) {
    const UringOp& op = ops_[ticket];
    unsigned tail = sq_tail_->load(std::memory_order_relaxed);
    unsigned idx = tail & sq_mask_;
    io_uring_sqe* sqe = &sqes_[idx];
    std::memset(sqe, 0, sizeof(*sqe));
    sqe->opcode = op.write ? IORING_OP_WRITE : IORING_OP_READ;
    sqe->fd = op.fd;
    sqe->addr = reinterpret_cast<uint64_t>(op.buf);
    sqe->len = static_cast<unsigned>(op.remaining);
    sqe->off = static_cast<uint64_t>(op.offset);
    sqe->user_data = static_cast<uint64_t>(ticket);
    sq_array_[idx] = idx;
    sq_tail_->store(tail + 1, std::memory_order_release);
    // the SQE is published: retry the submit syscall until the kernel
    // takes it (EINTR/EAGAIN) — "not submitted" is not a representable
    // state once the tail has advanced
    for (;;) {
      long r = syscall(__NR_io_uring_enter, ring_fd_, 1u, 0u, 0u,
                       nullptr, 0);
      if (r >= 0) return;
      if (errno != EINTR && errno != EAGAIN) {
        // unrecoverable (EBADF/EFAULT — programming errors): fail the op
        auto it = ops_.find(ticket);
        if (it != ops_.end()) complete_locked(it, errno);
        return;
      }
    }
  }

  void drain_cq_locked() {
    for (;;) {
      unsigned head = cq_head_->load(std::memory_order_relaxed);
      if (head == cq_tail_->load(std::memory_order_acquire)) break;
      io_uring_cqe cqe = cqes_[head & cq_mask_];
      cq_head_->store(head + 1, std::memory_order_release);
      finish_locked(static_cast<int64_t>(cqe.user_data), cqe.res);
    }
  }

  void finish_locked(int64_t ticket, int res) {
    auto it = ops_.find(ticket);
    if (it == ops_.end()) return;
    UringOp& op = it->second;
    if (res < 0) {
      complete_locked(it, -res);
    } else if (res == 0) {
      complete_locked(it, EIO);  // short read: file smaller than asked
    } else if (res < op.remaining) {
      op.buf += res;
      op.offset += res;
      op.remaining -= res;
      push_sqe_locked(ticket);   // continue the partial transfer
    } else {
      complete_locked(it, 0);
    }
  }

  void complete_locked(std::unordered_map<int64_t, UringOp>::iterator it,
                       int err) {
    ::close(it->second.fd);
    done_[it->first] = err;
    ops_.erase(it);
  }

  int ring_fd_ = -1;
  unsigned entries_ = 0;
  void* sq_ring_ = nullptr;
  void* cq_ring_ = nullptr;
  io_uring_sqe* sqes_ = nullptr;
  size_t sq_ring_sz_ = 0, cq_ring_sz_ = 0, sqes_sz_ = 0;
  std::atomic<unsigned>* sq_head_ = nullptr;
  std::atomic<unsigned>* sq_tail_ = nullptr;
  unsigned sq_mask_ = 0;
  unsigned* sq_array_ = nullptr;
  std::atomic<unsigned>* cq_head_ = nullptr;
  std::atomic<unsigned>* cq_tail_ = nullptr;
  unsigned cq_mask_ = 0;
  io_uring_cqe* cqes_ = nullptr;

  std::mutex mu_;
  std::unordered_map<int64_t, UringOp> ops_;   // in flight
  std::unordered_map<int64_t, int> done_;      // ticket -> errno
  int64_t next_ticket_ = 1;
};

// ---------------------------------------------------------------------
// worker-thread fallback engine (original implementation)
// ---------------------------------------------------------------------

struct Handle {
  std::vector<std::thread> workers;
  std::deque<Request> queue;
  std::mutex mu;
  std::condition_variable cv_submit, cv_done;
  std::unordered_map<int64_t, int> done;  // ticket -> errno (0 = ok)
  std::unordered_set<int64_t> pending;    // submitted, not yet completed
  int64_t next_ticket = 1;
  int64_t inflight = 0;
  bool shutdown = false;

  explicit Handle(int n_threads) {
    for (int i = 0; i < n_threads; ++i)
      workers.emplace_back([this] { this->run(); });
  }

  ~Handle() {
    {
      std::lock_guard<std::mutex> lock(mu);
      shutdown = true;
    }
    cv_submit.notify_all();
    for (auto& t : workers) t.join();
  }

  static int do_io(const Request& r) {
    int flags = r.write ? (O_WRONLY | O_CREAT) : O_RDONLY;
    int fd = ::open(r.path.c_str(), flags, 0644);
    if (fd < 0) return errno ? errno : EIO;
    char* p = static_cast<char*>(r.buf);
    int64_t remaining = r.nbytes;
    int64_t off = r.offset;
    int err = 0;
    while (remaining > 0) {
      ssize_t got = r.write ? ::pwrite(fd, p, remaining, off)
                            : ::pread(fd, p, remaining, off);
      if (got < 0) {
        if (errno == EINTR) continue;
        err = errno ? errno : EIO;
        break;
      }
      if (got == 0) {  // short read: file smaller than requested
        err = EIO;
        break;
      }
      p += got;
      off += got;
      remaining -= got;
    }
    ::close(fd);
    return err;
  }

  void run() {
    for (;;) {
      Request r;
      {
        std::unique_lock<std::mutex> lock(mu);
        cv_submit.wait(lock, [this] { return shutdown || !queue.empty(); });
        if (queue.empty()) return;  // shutdown
        r = std::move(queue.front());
        queue.pop_front();
      }
      int err = do_io(r);
      {
        std::lock_guard<std::mutex> lock(mu);
        done[r.ticket] = err;
        pending.erase(r.ticket);
        --inflight;
      }
      cv_done.notify_all();
    }
  }

  int64_t submit(bool write, const char* path, void* buf, int64_t nbytes,
                 int64_t offset) {
    int64_t t;
    {
      std::lock_guard<std::mutex> lock(mu);
      if (shutdown) return -1;
      t = next_ticket++;
      queue.push_back(Request{t, write, path, buf, nbytes, offset});
      pending.insert(t);
      ++inflight;
    }
    cv_submit.notify_one();
    return t;
  }

  // Safe against double-wait: a ticket that is neither pending nor in
  // done was already consumed (or never issued) — return 0 instead of
  // blocking forever.
  int wait(int64_t ticket) {
    std::unique_lock<std::mutex> lock(mu);
    cv_done.wait(lock, [&] {
      return done.count(ticket) > 0 || pending.count(ticket) == 0;
    });
    auto it = done.find(ticket);
    if (it == done.end()) return 0;
    int err = it->second;
    done.erase(it);
    return err;
  }

  // Barrier only: waits until no request is in flight. Completion records
  // are NOT consumed — callers still wait(ticket) individually (so a
  // barrier between prefetch and swap_in cannot orphan the read ticket).
  int wait_all() {
    std::unique_lock<std::mutex> lock(mu);
    cv_done.wait(lock, [&] { return inflight == 0; });
    int worst = 0;
    for (auto& kv : done)
      if (kv.second != 0) worst = kv.second;
    return worst;
  }
};

// Engine dispatcher behind the C ABI: io_uring when the kernel allows
// it, the thread pool otherwise.
struct DsAio {
  UringEngine* uring = nullptr;
  Handle* threads = nullptr;

  ~DsAio() {
    delete uring;
    delete threads;
  }
};

}  // namespace

extern "C" {

void* ds_aio_new(int n_threads) {
  if (n_threads <= 0) n_threads = 4;
  auto* d = new DsAio();
  d->uring = UringEngine::TryCreate(64);
  if (!d->uring) d->threads = new Handle(n_threads);
  return d;
}

void ds_aio_free(void* h) { delete static_cast<DsAio*>(h); }

// 1 = io_uring, 0 = worker-thread fallback.
int ds_aio_backend(void* h) {
  return static_cast<DsAio*>(h)->uring ? 1 : 0;
}

// Returns a ticket (>0) or -1. Buffer must stay alive until waited on.
int64_t ds_aio_pread(void* h, const char* path, void* buf, int64_t nbytes,
                     int64_t offset) {
  auto* d = static_cast<DsAio*>(h);
  return d->uring ? d->uring->submit(false, path, buf, nbytes, offset)
                  : d->threads->submit(false, path, buf, nbytes, offset);
}

int64_t ds_aio_pwrite(void* h, const char* path, const void* buf,
                      int64_t nbytes, int64_t offset) {
  auto* d = static_cast<DsAio*>(h);
  void* b = const_cast<void*>(buf);
  return d->uring ? d->uring->submit(true, path, b, nbytes, offset)
                  : d->threads->submit(true, path, b, nbytes, offset);
}

// 0 on success, else errno of the failed transfer.
int ds_aio_wait(void* h, int64_t ticket) {
  auto* d = static_cast<DsAio*>(h);
  return d->uring ? d->uring->wait(ticket) : d->threads->wait(ticket);
}

int ds_aio_wait_all(void* h) {
  auto* d = static_cast<DsAio*>(h);
  return d->uring ? d->uring->wait_all() : d->threads->wait_all();
}

}  // extern "C"
