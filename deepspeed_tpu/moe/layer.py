"""MoE layer facade.

Reference: deepspeed/moe/layer.py:15 ``MoE`` — wraps TopKGate + Experts +
MOELayer, exposing (output, l_aux, exp_counts). Same surface here as a flax
module; ``ep_size`` is validated against the mesh's expert axis instead of
creating process groups (deepspeed/utils/groups.py).
"""

from typing import Any, Callable, Optional

import flax.linen as nn

from deepspeed_tpu.models.layers import QDense
import jax.numpy as jnp

from ..comm.mesh import get_global_mesh
from ..utils.logging import logger
from .sharded_moe import MOELayer


class ExpertMLP(nn.Module):
    """Default expert: the standard FFN (reference: a torch nn.Module the
    user passes; this is the common case)."""
    d_model: int
    d_ff: int
    dtype: Any = jnp.bfloat16
    param_dtype: Any = jnp.float32
    activation: str = "gelu"

    @nn.compact
    def __call__(self, x):
        import jax
        h = QDense(features=self.d_ff, dtype=self.dtype,
                            param_dtype=self.param_dtype,
                            kernel_init=nn.with_logical_partitioning(
                                nn.initializers.variance_scaling(
                                    1.0, "fan_in", "normal"),
                                ("embed", "mlp")),
                            bias_init=nn.with_logical_partitioning(
                                nn.initializers.zeros, ("mlp",)),
                            name="fc_in")(x)
        h = jax.nn.gelu(h, approximate=True) if self.activation == "gelu" \
            else jax.nn.relu(h)
        return QDense(features=self.d_model, dtype=self.dtype,
                               param_dtype=self.param_dtype,
                               kernel_init=nn.with_logical_partitioning(
                                   nn.initializers.variance_scaling(
                                       1.0, "fan_in", "normal"),
                                   ("mlp", "embed")),
                               bias_init=nn.with_logical_partitioning(
                                   nn.initializers.zeros, ("embed",)),
                               name="fc_out")(h)


class MoE(nn.Module):
    """reference: deepspeed/moe/layer.py:15.

    __call__(x) -> (output, l_aux, exp_counts)."""
    hidden_size: int
    num_experts: int = 1
    ep_size: int = 1
    k: int = 1
    capacity_factor: float = 1.0
    eval_capacity_factor: float = 1.0
    min_capacity: int = 4
    d_ff: Optional[int] = None
    expert: Optional[Callable] = None    # factory(name=...) -> nn.Module
    noisy_gate_policy: Optional[str] = None
    drop_tokens: bool = True
    use_rts: bool = True
    dtype: Any = jnp.bfloat16
    param_dtype: Any = jnp.float32

    def setup(self):
        if self.num_experts % max(self.ep_size, 1) != 0:
            raise ValueError(
                f"num_experts={self.num_experts} must be divisible by "
                f"ep_size={self.ep_size}")
        factory = self.expert or (lambda name: ExpertMLP(
            d_model=self.hidden_size, d_ff=self.d_ff or 4 * self.hidden_size,
            dtype=self.dtype, param_dtype=self.param_dtype, name=name))
        self.moe_layer = MOELayer(
            d_model=self.hidden_size, num_experts=self.num_experts,
            expert_factory=factory, k=self.k,
            capacity_factor=self.capacity_factor,
            eval_capacity_factor=self.eval_capacity_factor,
            min_capacity=self.min_capacity,
            noisy_gate_policy=self.noisy_gate_policy,
            drop_tokens=self.drop_tokens, use_rts=self.use_rts,
            name="deepspeed_moe")

    def __call__(self, x, deterministic=True):
        try:
            ep_axis = get_global_mesh().shape.get("expert", 1)
            if ep_axis > 1 and self.num_experts % ep_axis != 0:
                logger.warning(
                    f"num_experts={self.num_experts} not divisible by mesh "
                    f"expert axis {ep_axis}; experts will replicate")
        except Exception:
            pass
        return self.moe_layer(x, deterministic=deterministic)


def split_params_into_different_moe_groups_for_optimizer(param_groups):
    """API parity with deepspeed/moe/utils.py:61. In the TPU build the
    optimizer shards expert vs dense params differently via the sharding
    rules (zero/sharding.py), so there is nothing to split — returned
    unchanged."""
    return param_groups


def is_moe_param(name_tuple) -> bool:
    """A param is an expert param iff its logical names carry "experts"."""
    return name_tuple is not None and "experts" in name_tuple
