from .layer import MoE, ExpertMLP, is_moe_param
from .sharded_moe import MOELayer, TopKGate, top1gating, top2gating
