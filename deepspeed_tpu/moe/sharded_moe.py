"""Expert-parallel MoE core: gating + dispatch.

Reference: deepspeed/moe/sharded_moe.py — top1gating (:175), top2gating
(:276) with capacity + load-balancing aux loss + random token selection;
MOELayer.forward (:489): gate -> _AllToAll (:87) -> local experts ->
_AllToAll back -> combine.

TPU-native: dispatch/combine are einsums with sharding constraints over the
"expert" mesh axis — the XLA SPMD partitioner lowers the resharding to the
same all-to-all the reference issues by hand over its expert process group
(created in deepspeed/utils/groups.py:107). Gating math is kept identical.
"""

import math
from typing import Optional

import jax
import jax.numpy as jnp
import flax.linen as nn

from deepspeed_tpu.models.layers import QDense

from ..comm.mesh import get_global_mesh


def _expert_constraint(x, spec_axes):
    """with_sharding_constraint over the expert axis, no-op off-mesh.

    Uses a concrete NamedSharding — a bare PartitionSpec under plain
    ``jit`` has no mesh context and silently fails."""
    from jax.sharding import PartitionSpec as P, NamedSharding
    try:
        from jax.sharding import get_abstract_mesh
        am = get_abstract_mesh()
        if not am.empty and any("Manual" in str(t) for t in am.axis_types):
            return x   # inside shard_map: constraint meshes don't mix
        mesh = get_global_mesh()
        if mesh.shape.get("expert", 1) == 1:
            return x
        return jax.lax.with_sharding_constraint(
            x, NamedSharding(mesh, P(*spec_axes)))
    except Exception:
        from ..utils.logging import warn_once
        import sys
        warn_once(f"expert sharding constraint skipped: {sys.exc_info()[1]}")
        return x


def _capacity(num_tokens: int, num_experts: int, capacity_factor: float,
              min_capacity: int) -> int:
    """reference: sharded_moe.py _capacity — ceil(T/E * factor), floored at
    min_capacity. Static under jit (token count is a trace-time constant)."""
    cap = math.ceil(num_tokens / num_experts * capacity_factor)
    return max(cap, min_capacity)


def _one_hot(idx, n):
    return jax.nn.one_hot(idx, n, dtype=jnp.float32)


def top1gating(logits, capacity_factor: float, min_capacity: int = 4,
               noisy_gate_policy: Optional[str] = None,
               drop_tokens: bool = True, use_rts: bool = True,
               rng: Optional[jax.Array] = None):
    """Switch-style top-1 gating (reference :175).

    logits: [T, E] fp32. Returns (l_aux, combine [T,E,C], dispatch [T,E,C],
    exp_counts [E])."""
    T, E = logits.shape
    if drop_tokens:
        capacity = _capacity(T, E, capacity_factor, min_capacity)
    else:
        # no-drop needs worst-case capacity T (static shapes under jit);
        # the [T,E,T] dispatch tensors explode quadratically, so refuse
        # beyond a sane budget (reference shrinks dynamically, which XLA
        # static shapes cannot express).
        if T * T * E > 2 ** 26:
            raise ValueError(
                f"drop_tokens=False needs [T,E,T] dispatch tensors; "
                f"T={T}, E={E} exceeds the budget — enable drop_tokens or "
                f"reduce tokens per step")
        capacity = T

    if noisy_gate_policy == "RSample" and rng is not None:
        logits_w_noise = logits + jax.random.gumbel(rng, logits.shape)
    else:
        logits_w_noise = logits
    gates = jax.nn.softmax(logits, axis=-1)

    indices1 = jnp.argmax(logits_w_noise, axis=-1)            # [T]
    mask1 = _one_hot(indices1, E)                             # [T, E]
    exp_counts = jnp.sum(mask1, axis=0)

    # load-balancing loss (reference: l_aux = E * sum(me*ce))
    me = jnp.mean(gates, axis=0)
    ce = jnp.mean(mask1, axis=0)
    l_aux = jnp.sum(me * ce) * E

    # position in the expert queue: cumsum of mask in arrival order is the
    # reference's default; random-token-selection re-ranks by uniform
    # noise so truncation under capacity is unbiased (reference :221)
    locations1 = jnp.cumsum(mask1, axis=0) - mask1            # [T, E]
    if use_rts and rng is not None:
        rts = jax.random.uniform(jax.random.fold_in(rng, 1), (T, E))
        priority = mask1 * rts
        order = jnp.argsort(-priority, axis=0)                # [T, E]
        ranks = jnp.argsort(order, axis=0).astype(jnp.float32)
        locations1 = jnp.where(mask1 > 0, ranks, locations1)

    pos_in_expert = jnp.sum(locations1 * mask1, axis=-1)      # [T]
    keep = (pos_in_expert < capacity) & (jnp.sum(mask1, axis=-1) > 0)
    mask1 = mask1 * keep[:, None].astype(mask1.dtype)

    gates1 = jnp.sum(gates * mask1, axis=-1)                  # [T]
    loc_oh = _one_hot(jnp.clip(pos_in_expert, 0, capacity - 1).astype(jnp.int32),
                      capacity)                               # [T, C]
    combine = gates1[:, None, None] * mask1[:, :, None] * loc_oh[:, None, :]
    dispatch = (combine > 0).astype(logits.dtype)
    return l_aux, combine.astype(logits.dtype), dispatch, exp_counts


def top2gating(logits, capacity_factor: float, min_capacity: int = 4,
               rng: Optional[jax.Array] = None):
    """GShard-style top-2 gating (reference :276)."""
    T, E = logits.shape
    capacity = _capacity(T, E, capacity_factor * 2, min_capacity)
    gates = jax.nn.softmax(logits, axis=-1)

    indices1 = jnp.argmax(gates, axis=-1)
    mask1 = _one_hot(indices1, E)
    logits_except1 = jnp.where(mask1 > 0, -jnp.inf, logits)
    indices2 = jnp.argmax(logits_except1, axis=-1)
    mask2 = _one_hot(indices2, E)

    # aux loss on first choice only (reference :300)
    me = jnp.mean(gates, axis=0)
    ce = jnp.mean(mask1, axis=0)
    l_aux = jnp.sum(me * ce) * E

    locations1 = jnp.cumsum(mask1, axis=0) - mask1
    locations2 = jnp.cumsum(mask2, axis=0) - mask2 + jnp.sum(mask1, axis=0,
                                                             keepdims=True)
    pos1 = jnp.sum(locations1 * mask1, axis=-1)
    pos2 = jnp.sum(locations2 * mask2, axis=-1)
    mask1 = mask1 * (pos1 < capacity)[:, None].astype(mask1.dtype)
    mask2 = mask2 * (pos2 < capacity)[:, None].astype(mask2.dtype)

    gates1 = jnp.sum(gates * mask1, axis=-1)
    gates2 = jnp.sum(gates * mask2, axis=-1)
    denom = jnp.clip(gates1 + gates2, 1e-9, None)
    gates1, gates2 = gates1 / denom, gates2 / denom

    loc1 = _one_hot(jnp.clip(pos1, 0, capacity - 1).astype(jnp.int32), capacity)
    loc2 = _one_hot(jnp.clip(pos2, 0, capacity - 1).astype(jnp.int32), capacity)
    combine = (gates1[:, None, None] * mask1[:, :, None] * loc1[:, None, :]
               + gates2[:, None, None] * mask2[:, :, None] * loc2[:, None, :])
    dispatch = (combine > 0).astype(logits.dtype)
    exp_counts = jnp.sum(mask1 + mask2, axis=0)
    return l_aux, combine.astype(logits.dtype), dispatch, exp_counts


class TopKGate(nn.Module):
    """Gating network (reference: TopKGate, sharded_moe.py:374)."""
    d_model: int
    num_experts: int
    k: int = 1
    capacity_factor: float = 1.0
    eval_capacity_factor: float = 1.0
    min_capacity: int = 4
    noisy_gate_policy: Optional[str] = None
    drop_tokens: bool = True
    use_rts: bool = True

    @nn.compact
    def __call__(self, x, deterministic=True):
        rng = None
        if not deterministic and (self.use_rts or self.noisy_gate_policy):
            rng = self.make_rng("gating")
        if self.noisy_gate_policy == "Jitter" and rng is not None:
            # reference TopKGate: multiplicative input jitter
            # (multiplicative_jitter, sharded_moe.py — uniform in
            # [1-eps, 1+eps], eps=1e-2) for routing exploration
            eps = 1e-2
            x = x * jax.random.uniform(jax.random.fold_in(rng, 2), x.shape,
                                       x.dtype, 1.0 - eps, 1.0 + eps)
        # gate weights kept fp32 (reference keeps wg in fp32)
        logits = QDense(
            features=self.num_experts, use_bias=False, dtype=jnp.float32,
            param_dtype=jnp.float32, name="wg")(x.astype(jnp.float32))
        factor = (self.capacity_factor if not deterministic
                  else self.eval_capacity_factor)
        if self.k == 1:
            return top1gating(logits, factor, self.min_capacity,
                              self.noisy_gate_policy if not deterministic else None,
                              self.drop_tokens, self.use_rts, rng)
        if self.k == 2:
            return top2gating(logits, factor, self.min_capacity, rng)
        raise ValueError("only k=1 and k=2 are supported (reference parity)")


class MOELayer(nn.Module):
    """Gate -> dispatch -> experts -> combine (reference MOELayer :432).

    ``expert_factory(name)`` builds one expert module; experts are stacked
    with nn.vmap and their params carry the "experts" logical axis, which
    the sharding rules map onto the "expert" mesh axis. The dispatch/combine
    einsums carry sharding constraints so GSPMD emits the all-to-all."""
    d_model: int
    num_experts: int
    expert_factory: any
    k: int = 1
    capacity_factor: float = 1.0
    eval_capacity_factor: float = 1.0
    min_capacity: int = 4
    noisy_gate_policy: Optional[str] = None
    drop_tokens: bool = True
    use_rts: bool = True

    @nn.compact
    def __call__(self, x, deterministic=True):
        b, s, d = x.shape
        tokens = x.reshape(b * s, d)

        gate = TopKGate(d_model=self.d_model, num_experts=self.num_experts,
                        k=self.k, capacity_factor=self.capacity_factor,
                        eval_capacity_factor=self.eval_capacity_factor,
                        min_capacity=self.min_capacity,
                        noisy_gate_policy=self.noisy_gate_policy,
                        drop_tokens=self.drop_tokens, use_rts=self.use_rts,
                        name="gate")
        l_aux, combine, dispatch, exp_counts = gate(tokens, deterministic)

        # dispatch: [T,E,C] x [T,d] -> [E,C,d]; the constraint shards E over
        # the expert axis => GSPMD all-to-all (reference _AllToAll :87)
        expert_in = jnp.einsum("tec,td->ecd", dispatch.astype(x.dtype), tokens)
        expert_in = _expert_constraint(expert_in, ("expert", None, None))

        experts = nn.vmap(
            lambda m, xi: m(xi),
            variable_axes={"params": 0},
            split_rngs={"params": True},
            in_axes=0, out_axes=0,
            metadata_params={nn.PARTITION_NAME: "experts"},
        )(self.expert_factory(name="experts"), expert_in)
        experts = _expert_constraint(experts, ("expert", None, None))

        out = jnp.einsum("tec,ecd->td", combine.astype(x.dtype), experts)
        return out.reshape(b, s, d), l_aux, exp_counts
