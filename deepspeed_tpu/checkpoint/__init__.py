from .reshape import DeepSpeedCheckpoint, reshape_checkpoint

__all__ = ["DeepSpeedCheckpoint", "reshape_checkpoint"]
