"""Offline checkpoint reshaping (TP/PP/DP degree changes).

Reference: deepspeed/checkpoint/ (962 LoC) — DeepSpeedCheckpoint
(deepspeed_checkpoint.py:37) re-maps per-rank Megatron shard files when
the parallel topology changes (reshape_3d_utils.py, reshape_meg_2d.py),
because torch checkpoints are rank-file-shaped.

Orbax checkpoints are *globally addressed*: every array is stored with
its global shape, so "reshaping" to a new mesh is simply restoring under
the new topology's shardings — the engine's load path already does this
(runtime/checkpointing.py restore-with-template). This module provides
the reference's offline surface on top of that fact:

- ``DeepSpeedCheckpoint``: inspect a checkpoint (params, shapes, step
  metadata) without building an engine.
- ``reshape_checkpoint``: rewrite a checkpoint for a target MeshSpec —
  verifying the new topology divides every sharded dim — so a resumed
  run fails fast at reshape time, not mid-restore on a pod.
"""

import json
import os
from typing import Dict, Optional

import numpy as np

from ..utils.logging import logger


class DeepSpeedCheckpoint:
    """reference surface: DeepSpeedCheckpoint(dir).show_*/get_* without
    the TP/PP slicing zoo (global addressing makes it unnecessary)."""

    def __init__(self, ckpt_dir: str, tag: Optional[str] = None):
        from ..runtime.checkpointing import LATEST_FILE
        self.dir = ckpt_dir
        if tag is None:
            with open(os.path.join(ckpt_dir, LATEST_FILE)) as f:
                tag = f.read().strip()
        self.tag = str(tag)
        self.path = os.path.join(os.path.abspath(ckpt_dir), self.tag)
        meta_path = os.path.join(self.path, "engine_meta.json")
        self.meta: Dict = {}
        if os.path.exists(meta_path):
            with open(meta_path) as f:
                self.meta = json.load(f)

    @property
    def global_steps(self) -> int:
        return int(self.meta.get("global_steps", 0))

    @property
    def zero_stage(self) -> int:
        return int(self.meta.get("zero_stage", 0))

    @property
    def dp_world_size(self) -> int:
        return int(self.meta.get("dp_world_size", 1))

    def load_params(self):
        from ..runtime.checkpointing import load_module_params
        return load_module_params(self.dir, tag=self.tag)

    def param_shapes(self) -> Dict[str, tuple]:
        import jax
        params = self.load_params()
        flat, _ = jax.tree.flatten_with_path(params)
        return {jax.tree_util.keystr(p): tuple(np.shape(v)) for p, v in flat}

    def show_parameters(self):
        for name, shape in self.param_shapes().items():
            print(f"{name}: {shape}")


class _ShapeOnlyMesh:
    """Duck-typed stand-in for jax.sharding.Mesh: the sharding rules only
    consult ``mesh.shape`` — lets offline validation run without devices."""

    def __init__(self, axis_sizes: Dict[str, int]):
        self.shape = dict(axis_sizes)


def _validate_target_topology(src: DeepSpeedCheckpoint, params,
                              target_mesh_spec):
    """Check the target topology with the engine's actual sharding rules.

    Uses the logical-axis names recorded at save time (engine_meta.json
    ``param_logical_names``) and replays ``make_param_rules`` — the exact
    function the engine applies at restore — so a dim the rules *will*
    shard (qkv/mlp/vocab over ``model``, experts over ``expert``, the
    stage-3 fsdp pick) is checked for divisibility, and nothing else is.
    Reference analog: the degree-compatibility checks in
    deepspeed/checkpoint/reshape_3d_utils.py.
    """
    import jax
    from ..runtime.zero.sharding import make_param_rules, TP_RULES

    mesh = _ShapeOnlyMesh({"data": getattr(target_mesh_spec, "data", 1),
                           "fsdp": target_mesh_spec.fsdp,
                           "model": target_mesh_spec.model,
                           "expert": target_mesh_spec.expert})
    names_by_key = src.meta.get("param_logical_names")
    flat, _ = jax.tree.flatten_with_path(params)

    if names_by_key is None:
        # pre-names checkpoint: fall back to the coarse any-dim heuristic
        logger.warning("checkpoint has no param_logical_names metadata; "
                       "falling back to shape-only topology validation")
        for path, v in flat:
            shape = np.shape(v)
            if not shape:
                continue
            for axis_name in ("model", "fsdp", "expert"):
                size = mesh.shape[axis_name]
                if size > 1 and not any(d % size == 0 for d in shape):
                    raise ValueError(
                        f"param {jax.tree_util.keystr(path)} shape {shape} "
                        f"has no dim divisible by {axis_name}={size}; "
                        "target topology cannot shard it")
        return

    rules = make_param_rules(src.zero_stage, 0)
    for path, v in flat:
        key = jax.tree_util.keystr(path)
        shape = np.shape(v)
        names = names_by_key.get(key)
        if not shape or names is None:
            continue
        names = tuple(names)
        # dims the rule table targets must divide their mesh axis — the
        # engine silently replicates otherwise, which breaks TP/EP math
        # expectations for weights that logically MUST be sharded
        for i, n in enumerate(names[:len(shape)]):
            axis = TP_RULES.get(n) if n is not None else None
            if axis in ("model", "expert"):
                size = mesh.shape.get(axis, 1)
                if size > 1 and shape[i] % size != 0:
                    raise ValueError(
                        f"param {key} dim {i} ('{n}', {shape[i]}) is not "
                        f"divisible by {axis}={size}; target topology "
                        "cannot shard a weight the engine's rules require "
                        "sharded — rejected")
        # stage-3: warn when the fsdp pick degrades to full replication
        if src.zero_stage == 3 and mesh.shape.get("fsdp", 1) > 1:
            spec = rules(names, shape, mesh)
            flat_axes = [a for ax in spec for a in
                         (ax if isinstance(ax, (tuple, list)) else (ax,))]
            if "fsdp" not in flat_axes and int(np.prod(shape)) > 0:
                logger.warning(
                    f"param {key} shape {shape} cannot shard over "
                    f"fsdp={mesh.shape['fsdp']} under the engine's rules; "
                    "it will be replicated on restore")


def reshape_checkpoint(src_dir: str, dst_dir: str, target_mesh_spec=None,
                       tag: Optional[str] = None):
    """Re-write ``src_dir`` under ``dst_dir`` validated against a target
    topology (reference: the ds_to_universal/reshape flow).

    The rewrite stores plain global arrays; restoring on the target mesh
    shards them per the engine's rules. With ``target_mesh_spec`` given,
    sharded-dim divisibility is checked up front (the reference's degree-
    compatibility checks in reshape_3d_utils.py).

    PARAMS-ONLY, like the reference's universal export: optimizer
    moments/loss scale are not carried (a warning is logged when the
    source has them) — resuming from a reshaped checkpoint restarts the
    optimizer state; use same-topology checkpoints to resume exactly.
    """
    import jax
    import orbax.checkpoint as ocp

    src = DeepSpeedCheckpoint(src_dir, tag)
    params = src.load_params()
    try:
        from ..runtime.checkpointing import _item_metadata
        disk = _item_metadata(ocp.PyTreeCheckpointer(),
                              os.path.join(src.path, "state"))
        extras = sorted(set(disk.keys()) - {"params"})
    except Exception:
        extras = []
    if extras:
        from ..utils.logging import logger
        logger.warning(
            f"reshape is params-only: source subtrees {extras} are NOT "
            "carried — resuming from the reshaped checkpoint restarts "
            "the optimizer state")

    if target_mesh_spec is not None:
        _validate_target_topology(src, params, target_mesh_spec)

    dst = os.path.join(os.path.abspath(dst_dir), src.tag)
    os.makedirs(dst, exist_ok=True)
    ocp.PyTreeCheckpointer().save(os.path.join(dst, "state"),
                                  {"params": params}, force=True)
    if src.meta:
        with open(os.path.join(dst, "engine_meta.json"), "w") as f:
            json.dump(src.meta, f)
    # same publication discipline as the engine save path: integrity
    # manifest (the reshaped tag becomes a verified fallback candidate)
    # and an atomic `latest` (a crash mid-write must not tear the tag)
    from ..runtime.resilience.manifest import write_latest, write_manifest
    write_manifest(dst, step=(src.meta or {}).get("global_steps"),
                   tag=src.tag)
    write_latest(os.path.abspath(dst_dir), src.tag)
    logger.info(f"reshaped checkpoint {src.path} -> {dst}")
    return dst
