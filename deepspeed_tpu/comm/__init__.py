"""``deepspeed_tpu.comm`` — the communication facade (reference: deepspeed/comm/).

Same op vocabulary as ``deepspeed.comm``; groups are mesh-axis names.
"""

from .comm import (ReduceOp, init_distributed, is_initialized, get_rank,
                   get_world_size, get_local_rank, barrier, all_reduce,
                   inference_all_reduce, all_gather, reduce_scatter,
                   all_to_all_single, broadcast, ppermute, send_recv_next,
                   send_recv_prev, axis_index, all_reduce_host,
                   all_gather_host, reduce_scatter_host, all_to_all_host,
                   configure, get_comms_logger, log_summary, CommsLogger,
                   timed_host_op)
from .mesh import (MESH_AXES, DENSE_DP_AXES, EXPERT_DP_AXES, MeshSpec,
                   build_mesh, set_global_mesh, get_global_mesh, axis_size,
                   dp_world_size, mp_world_size, pp_world_size, sp_world_size,
                   ep_world_size)
