"""Device-mesh management.

The reference builds a zoo of torch.distributed process groups
(deepspeed/utils/groups.py, deepspeed/runtime/pipe/topology.py). The
TPU-native equivalent is ONE ``jax.sharding.Mesh`` with named axes; every
"process group" becomes a mesh axis (or tuple of axes) and XLA lowers the
collectives onto ICI/DCN rings automatically.

Axis vocabulary (sizes default to 1, ``data`` absorbs the remainder):

- ``stage``  : pipeline-parallel stages           (reference: pipe_parallel_size)
- ``data``   : pure data parallel replicas        (reference: data_parallel group)
- ``expert`` : expert-parallel shard of the data group (reference: expert_parallel_size;
               dense params treat ("data","expert") as the full DP group, expert
               params are data-parallel over "data" only — mirrors
               deepspeed/utils/groups.py:107 _create_expert_and_data_parallel)
- ``fsdp``   : ZeRO-3 parameter-sharding axis (reference: ZeRO partitioning over DP ranks)
- ``seq``    : sequence/context parallel (Ulysses / ring attention — new capability)
- ``model``  : tensor parallel (reference: external Megatron mpu protocol)

Axis order is outer→inner = furthest→nearest in the interconnect: ``stage``
over DCN-ish links is fine, ``model`` innermost so TP collectives ride
nearest-neighbor ICI.
"""

from dataclasses import dataclass, field
from typing import Optional, Tuple

import numpy as np

MESH_AXES = ("stage", "data", "expert", "fsdp", "seq", "model")

# Composite "groups" expressed as axis tuples (the analog of the reference's
# process groups). PartitionSpecs may use these directly.
DENSE_DP_AXES = ("data", "expert", "fsdp")  # full data-parallel group for dense params
EXPERT_DP_AXES = ("data",)                  # data-parallel group for expert params


@dataclass(frozen=True)
class MeshSpec:
    """Declarative mesh shape. -1 for ``data`` means absorb remaining devices."""
    stage: int = 1
    data: int = -1
    expert: int = 1
    fsdp: int = 1
    seq: int = 1
    model: int = 1

    def resolve(self, n_devices: int) -> Tuple[int, ...]:
        fixed = [self.stage, self.expert, self.fsdp, self.seq, self.model]
        if any(s <= 0 for s in fixed):
            raise ValueError(f"Only the data axis may be -1, got {self}")
        prod = int(np.prod(fixed))
        data = self.data
        if data == -1:
            if n_devices % prod != 0:
                raise ValueError(
                    f"{n_devices} devices not divisible by fixed axes product {prod} ({self})")
            data = n_devices // prod
        total = prod * data
        if total != n_devices:
            raise ValueError(
                f"Mesh {self} needs {total} devices but {n_devices} are available")
        return (self.stage, data, self.expert, self.fsdp, self.seq, self.model)


_GLOBAL_MESH = None


def build_mesh(spec: Optional[MeshSpec] = None, devices=None, set_global: bool = True):
    """Build a ``jax.sharding.Mesh`` over all (or given) devices."""
    import jax
    from jax.sharding import Mesh

    if spec is None:
        spec = MeshSpec()
    if devices is None:
        devices = jax.devices()
    shape = spec.resolve(len(devices))
    dev_array = np.asarray(devices).reshape(shape)
    mesh = Mesh(dev_array, MESH_AXES)
    if set_global:
        set_global_mesh(mesh)
    return mesh


def set_global_mesh(mesh):
    global _GLOBAL_MESH
    _GLOBAL_MESH = mesh


def get_global_mesh():
    """Current global mesh; builds a trivial all-data mesh lazily if unset."""
    global _GLOBAL_MESH
    if _GLOBAL_MESH is None:
        _GLOBAL_MESH = build_mesh(MeshSpec(), set_global=False)
    return _GLOBAL_MESH


def peek_global_mesh():
    """Current global mesh or None — no lazy construction (for callers
    that must not invent a mesh, e.g. activation constraints)."""
    return _GLOBAL_MESH


def axis_size(axis, mesh=None) -> int:
    """Size of a mesh axis (or product over a tuple of axes).

    Unknown names raise a ValueError naming the declared axes instead of
    a bare KeyError (or a deep lax failure downstream)."""
    mesh = mesh or get_global_mesh()
    if isinstance(axis, (tuple, list)):
        return int(np.prod([axis_size(a, mesh) for a in axis]))
    if axis not in mesh.shape:
        raise ValueError(
            f"unknown mesh axis {axis!r}: declared axes are "
            f"{tuple(mesh.shape.keys())}")
    return mesh.shape[axis]


def dp_world_size(mesh=None) -> int:
    """Full data-parallel degree for dense params: data*expert*fsdp."""
    return axis_size(DENSE_DP_AXES, mesh)


def mp_world_size(mesh=None) -> int:
    return axis_size("model", mesh)


def pp_world_size(mesh=None) -> int:
    return axis_size("stage", mesh)


def sp_world_size(mesh=None) -> int:
    return axis_size("seq", mesh)


def ep_world_size(mesh=None) -> int:
    return axis_size("expert", mesh)
