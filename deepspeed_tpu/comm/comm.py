"""Communication facade.

TPU-native analog of ``deepspeed.comm`` (reference: deepspeed/comm/comm.py).
The reference wraps torch.distributed (NCCL); here the same op vocabulary is
backed by two paths:

1. **In-jit path** — the hot path. Functions take ``group`` as a mesh-axis
   name (or tuple of names) and lower to ``jax.lax`` collectives
   (psum / all_gather / psum_scatter / all_to_all / ppermute) that XLA
   schedules over ICI/DCN. These must be called inside ``shard_map``/``jit``
   with the relevant axes bound — exactly where the reference called NCCL
   from CUDA streams.

2. **Host path** — for benchmarks and eager-mode tests: ``*_host`` variants
   wrap the op in a one-shot ``shard_map`` over the global mesh.

``init_distributed`` (reference: comm/comm.py:577) performs the multi-host
rendezvous via ``jax.distributed.initialize`` over DCN instead of a
NCCL/MPI bootstrap.
"""

import os
import time
from enum import Enum
from typing import Optional

from ..observability.metrics import get_registry, record_traced_collective
from ..observability.trace import span as _span
from ..utils.logging import logger, log_dist
from .mesh import (MESH_AXES, MeshSpec, build_mesh, get_global_mesh,
                   peek_global_mesh, set_global_mesh,
                   axis_size, dp_world_size, mp_world_size, pp_world_size)


class ReduceOp(Enum):
    SUM = 0
    PRODUCT = 1
    MIN = 2
    MAX = 3
    AVG = 4
    UNUSED = 5


_INITIALIZED = False
_COMMS_LOGGER = None


def is_initialized() -> bool:
    return _INITIALIZED


def init_distributed(dist_backend: str = "ici",
                     auto_mpi_discovery: bool = True,
                     distributed_port: int = 29500,
                     verbose: bool = True,
                     timeout=None,
                     init_method: Optional[str] = None,
                     dist_init_required: Optional[bool] = None,
                     config=None,
                     rank: int = -1,
                     world_size: int = -1,
                     coordinator_address: Optional[str] = None,
                     num_processes: Optional[int] = None,
                     process_id: Optional[int] = None):
    """Multi-host rendezvous (reference: deepspeed/comm/comm.py:577).

    Single-process (one host driving its local chips) needs no rendezvous.
    Multi-host reads coordinator info from args or env
    (``DS_COORDINATOR_ADDRESS``/``DS_NUM_PROCESSES``/``DS_PROCESS_ID``, or the
    standard JAX/cloud-TPU envs that jax.distributed auto-detects).
    """
    global _INITIALIZED
    if _INITIALIZED:
        return
    import jax

    coordinator_address = coordinator_address or os.environ.get("DS_COORDINATOR_ADDRESS")
    num_processes = num_processes or _env_int("DS_NUM_PROCESSES")
    process_id = process_id if process_id is not None else _env_int("DS_PROCESS_ID")

    if coordinator_address is not None:
        if verbose:
            # Plain logger: log_dist queries jax.process_index(), which would
            # initialize the local backend before the rendezvous below.
            logger.info(f"Initializing distributed runtime: coordinator={coordinator_address} "
                        f"nprocs={num_processes} pid={process_id}")
        jax.distributed.initialize(coordinator_address=coordinator_address,
                                   num_processes=num_processes,
                                   process_id=process_id)
    elif world_size > 1 or _env_int("DS_NUM_PROCESSES", 0) > 1:
        # Fall back to jax auto-detection (GKE / TPU-VM metadata).
        jax.distributed.initialize()
    _INITIALIZED = True
    if verbose:
        log_dist(
            f"Distributed backend ready: {jax.process_count()} process(es), "
            f"{jax.device_count()} global device(s), platform={jax.default_backend()}",
            ranks=[0])


def _env_int(name, default=None):
    v = os.environ.get(name)
    return int(v) if v is not None else default


# ---------------------------------------------------------------------------
# Rank / world info. In the reference a "rank" is one GPU process; here a
# process drives many chips, so rank==process index and world==device count.
# ---------------------------------------------------------------------------

def get_rank() -> int:
    import jax
    return jax.process_index()


def get_world_size(group=None) -> int:
    import jax
    if group is None:
        return jax.device_count()
    return axis_size(group)


def get_local_rank() -> int:
    """Rank within the host. One JAX process drives all of a host's chips, so
    this is 0 unless the launcher packs several processes per host (then it
    exports DS_LOCAL_RANK, as the reference launcher exported LOCAL_RANK)."""
    return int(os.environ.get("DS_LOCAL_RANK", 0))


def barrier(group=None, name="ds_barrier"):
    """Cross-host barrier: all processes sync via a named global-device sync
    (reference: comm.py barrier -> NCCL barrier). Also flushes any dispatched
    async device work on this host."""
    import jax
    jax.effects_barrier()
    if jax.process_count() > 1:
        from jax.experimental import multihost_utils
        multihost_utils.sync_global_devices(name)


# ---------------------------------------------------------------------------
# Collective accounting (docs/observability.md, "Collective accounting").
#
# In-jit collectives execute inside XLA programs — host-timing one would
# require a per-op sync (exactly what TS002 forbids). Instead every
# wrapper records AT TRACE TIME: op, axis, dtype, and payload bytes go
# into a trace span (``comm/<op>``, carried in the span args) and the
# process tally in observability/metrics.py (``comm/traced_bytes/...``
# counters, keyed op:axis so ICI-bound model/fsdp traffic separates from
# DCN-bound data traffic). TrackedProgram diffs the tally around a
# compiling dispatch, turning the static record into a per-program
# bytes-moved-per-call estimate and a cumulative executed-traffic
# counter. Achieved bytes/sec is measurable only where a wall clock is
# honest — the host-path ops below, via the comms logger + the
# ``comm/host_bytes_per_s`` histogram.
# ---------------------------------------------------------------------------

def _group_label(group) -> str:
    """Stable axis label for tally keys and span args ("all" = whole
    mesh; tuples join with '+')."""
    if group is None:
        return "all"
    if isinstance(group, str):
        return group
    return "+".join(str(g) for g in group)


def _payload_nbytes(tensor) -> int:
    """Payload bytes from STATIC shape/dtype metadata — works on traced
    values (aval shapes are python ints), never reads device data."""
    shape = getattr(tensor, "shape", None)
    dtype = getattr(tensor, "dtype", None)
    if shape is None or dtype is None:
        return 0
    n = 1
    for d in shape:
        n *= int(d)
    try:
        itemsize = dtype.itemsize
    except AttributeError:
        import numpy as np
        itemsize = np.dtype(dtype).itemsize
    return n * int(itemsize)


def _note_collective(op: str, group, tensor, nbytes: Optional[int] = None):
    """Record one collective (trace-time) and return the ``comm/<op>``
    span to wrap the lax call — the span's wall time is TRACE time (a
    compile-cost signal), its args are the payload record."""
    if nbytes is None:
        nbytes = _payload_nbytes(tensor)
    axis = _group_label(group)
    record_traced_collective(op, axis, nbytes)
    return _span(f"comm/{op}", {"axis": axis, "bytes": int(nbytes),
                                "dtype": str(getattr(tensor, "dtype", "?"))})


# ---------------------------------------------------------------------------
# In-jit collectives (call inside shard_map with the axis bound).
# ---------------------------------------------------------------------------

def _declared_axes():
    """Axis names a collective may legally bind: the MESH_AXES vocabulary
    plus whatever the current global/abstract mesh declares (covers user
    shard_maps over custom meshes)."""
    axes = set(MESH_AXES)
    mesh = peek_global_mesh()
    if mesh is not None:
        axes.update(mesh.axis_names)
    try:
        from jax.sharding import get_abstract_mesh
        am = get_abstract_mesh()
        if not am.empty:
            axes.update(am.axis_names)
    except ImportError:  # older jax: no abstract-mesh API
        pass
    return axes


def _currently_bound(name) -> bool:
    """Is ``name`` a bound axis in the active trace? Covers user
    shard_maps over custom meshes on jax versions without the
    abstract-mesh API (jax.core.axis_frame resolves bound axis names
    there; raises NameError for unbound ones)."""
    try:
        import jax.core
        jax.core.axis_frame(name)
        return True
    except (NameError, AttributeError, ImportError, TypeError, KeyError):
        return False


def _axis(group):
    """Resolve+validate a group argument. A typo'd axis fails HERE with
    the declared axes listed, not five frames deep inside lax
    (ds_tpu_lint SC001 is the static half of this check)."""
    if group is None:
        return MESH_AXES  # whole mesh
    names = (group,) if isinstance(group, str) else tuple(group)
    declared = _declared_axes()
    bad = [n for n in names
           if isinstance(n, str) and n not in declared
           and not _currently_bound(n)]
    if bad:
        raise ValueError(
            f"unknown mesh axis/group {bad[0]!r}: declared axes are "
            f"{tuple(sorted(declared))}")
    return group


def all_reduce(tensor, op: ReduceOp = ReduceOp.SUM, group=None):
    """lax.psum/pmean/... over a mesh axis (reference: comm.py:500)."""
    import jax
    axis = _axis(group)
    if op not in (ReduceOp.SUM, ReduceOp.AVG, ReduceOp.MAX, ReduceOp.MIN,
                  ReduceOp.PRODUCT):
        # validate BEFORE recording: a rejected op must not inflate the
        # traced-bytes tally (or a compiling program's attribution)
        raise ValueError(f"Unsupported reduce op {op}")
    with _note_collective("all_reduce", group, tensor):
        if op == ReduceOp.SUM:
            return jax.lax.psum(tensor, axis)
        if op == ReduceOp.AVG:
            return jax.lax.pmean(tensor, axis)
        if op == ReduceOp.MAX:
            return jax.lax.pmax(tensor, axis)
        if op == ReduceOp.MIN:
            return jax.lax.pmin(tensor, axis)
        # PRODUCT: no lax product-reduce primitive — gather the factors
        # and multiply. (Correct for zeros/negatives, unlike
        # exp(psum(log)).)
        import jax.numpy as jnp
        gathered = jax.lax.all_gather(tensor, axis, axis=0, tiled=False)
        return jnp.prod(gathered, axis=0)


def inference_all_reduce(tensor, op: ReduceOp = ReduceOp.SUM, group="model"):
    return all_reduce(tensor, op=op, group=group)


def all_gather(tensor, group=None, axis: int = 0, tiled: bool = True):
    """lax.all_gather over a mesh axis (reference: all_gather_base comm.py:304).

    ``tiled=True`` concatenates along ``axis`` (torch all_gather_base
    semantics); ``tiled=False`` stacks a new leading dim.
    """
    import jax
    with _note_collective("all_gather", group, tensor):
        return jax.lax.all_gather(tensor, _axis(group), axis=axis,
                                  tiled=tiled)


def reduce_scatter(tensor, op: ReduceOp = ReduceOp.SUM, group=None, scatter_dimension: int = 0):
    """lax.psum_scatter (reference: reduce_scatter_fn comm.py:256)."""
    import jax
    assert op in (ReduceOp.SUM, ReduceOp.AVG)
    with _note_collective("reduce_scatter", group, tensor):
        out = jax.lax.psum_scatter(tensor, _axis(group),
                                   scatter_dimension=scatter_dimension,
                                   tiled=True)
        if op == ReduceOp.AVG:
            out = out / axis_size(_axis(group))
    return out


def all_to_all_single(tensor, group=None, split_axis: int = 0, concat_axis: int = 0):
    """lax.all_to_all (reference: all_to_all_single comm.py:355)."""
    import jax
    with _note_collective("all_to_all", group, tensor):
        return jax.lax.all_to_all(tensor, _axis(group),
                                  split_axis=split_axis,
                                  concat_axis=concat_axis, tiled=True)


def broadcast(tensor, src: int = 0, group=None):
    """Broadcast from mesh-coordinate ``src`` along the group axis.

    Implemented as select+psum — inside SPMD all members compute; the
    src member's value wins (reference: comm.py broadcast).
    """
    import jax
    import jax.numpy as jnp
    axis = _axis(group)
    with _note_collective("broadcast", group, tensor):
        idx = jax.lax.axis_index(axis)
        masked = jnp.where(idx == src, tensor, jnp.zeros_like(tensor))
        return jax.lax.psum(masked, axis)


def ppermute(tensor, perm, group):
    """Neighbor exchange (pipeline p2p / ring attention building block)."""
    import jax
    with _note_collective("ppermute", group, tensor):
        return jax.lax.ppermute(tensor, _axis(group), perm)


def send_recv_next(tensor, group):
    """Rotate +1 along a ring: rank i's value goes to rank i+1 (wraps)."""
    n = axis_size(group)
    perm = [(i, (i + 1) % n) for i in range(n)]
    return ppermute(tensor, perm, group)


def send_recv_prev(tensor, group):
    """Rotate -1 along a ring: rank i's value goes to rank i-1 (wraps)."""
    n = axis_size(group)
    perm = [(i, (i - 1) % n) for i in range(n)]
    return ppermute(tensor, perm, group)


def axis_index(group):
    import jax
    return jax.lax.axis_index(_axis(group))


# ---------------------------------------------------------------------------
# Host-level variants: one-shot shard_map over the global mesh. Used by the
# communication benchmarks (ds_bench analog) and eager tests.
# ---------------------------------------------------------------------------

def _host_collective(fn, tensor, group):
    import jax
    from jax.sharding import PartitionSpec as P
    from ..utils.jax_compat import shard_map

    mesh = get_global_mesh()
    axis = _axis(group)
    spec = P(axis)  # shard leading dim over the group
    f = shard_map(fn, mesh, (spec,), spec)
    return jax.jit(f)(tensor)


def all_reduce_host(tensor, op: ReduceOp = ReduceOp.SUM, group="data"):
    return _host_collective(lambda t: all_reduce(t, op=op, group=group), tensor, group)


def all_gather_host(tensor, group="data"):
    return _host_collective(lambda t: all_gather(t, group=group), tensor, group)


def reduce_scatter_host(tensor, group="data"):
    return _host_collective(lambda t: reduce_scatter(t, group=group), tensor, group)


def all_to_all_host(tensor, group="data"):
    return _host_collective(lambda t: all_to_all_single(t, group=group), tensor, group)


# ---------------------------------------------------------------------------
# Comms logging (reference: timed_op decorator comm.py:111 + CommsLogger).
# Host-path ops are wall-clock timed; in-jit ops are recorded at trace time.
# ---------------------------------------------------------------------------

class CommsLogger:
    def __init__(self, verbose=False, debug=False):
        self.verbose = verbose
        self.debug = debug
        self.comms_dict = {}

    def append(self, record_name, latency, msg_size):
        entry = self.comms_dict.setdefault(record_name, {})
        sz = entry.setdefault(msg_size, [0, 0.0])
        sz[0] += 1
        sz[1] += latency
        if self.verbose:
            logger.info(f"comm op: {record_name} | size: {msg_size} | latency(ms): {latency*1e3:.3f}")

    def log_all(self):
        from ..utils.logging import log_dist
        for name, sizes in self.comms_dict.items():
            for msg_size, (count, total) in sorted(sizes.items()):
                avg = total / max(count, 1)
                bw = msg_size / max(avg, 1e-12) / 1e9
                log_dist(f"{name}: size={msg_size}B count={count} avg={avg*1e3:.3f}ms algbw={bw:.2f}GB/s",
                         ranks=[0])


def configure(enabled=False, verbose=False, debug=False):
    global _COMMS_LOGGER
    _COMMS_LOGGER = CommsLogger(verbose=verbose, debug=debug) if enabled else None


def get_comms_logger():
    return _COMMS_LOGGER


def log_summary():
    if _COMMS_LOGGER is not None:
        _COMMS_LOGGER.log_all()


def timed_host_op(name, fn, tensor, *args, **kwargs):
    """Run a host-path op with wall-clock timing into the comms logger
    AND the shared registry (``comm/host_bytes_per_s`` histogram +
    ``comm/host_bytes_total`` counter) — the achieved-bandwidth side of
    the collective accounting; only host-path ops can be wall-timed
    honestly (their ``block_until_ready`` is the benchmark's own sync,
    not a step-path one)."""
    if _COMMS_LOGGER is None:
        return fn(tensor, *args, **kwargs)
    t0 = time.time()
    out = fn(tensor, *args, **kwargs)
    out.block_until_ready()
    elapsed = time.time() - t0
    nbytes = tensor.size * tensor.dtype.itemsize
    _COMMS_LOGGER.append(name, elapsed, nbytes)
    reg = get_registry()
    reg.counter("comm/host_bytes_total").inc(int(nbytes))
    if elapsed > 0:
        reg.histogram("comm/host_bytes_per_s").observe(nbytes / elapsed)
    return out
