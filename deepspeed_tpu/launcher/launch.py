"""Per-host launcher (reference: launcher/launch.py:90).

The reference forks --num_gpus ranks per node with RANK/LOCAL_RANK/
WORLD_SIZE/MASTER_* env. One JAX process drives all local TPU chips, so
here a single child is exec'd with the deepspeed_tpu rendezvous env
(DS_COORDINATOR_ADDRESS/DS_NUM_PROCESSES/DS_PROCESS_ID); signal handling
kills the child tree like the reference's sigkill handler (:176).
"""

import argparse
import os
import signal
import subprocess
import sys

from ..utils.logging import logger
from .runner import decode_world_info


def parse_args(argv=None):
    parser = argparse.ArgumentParser(prog="ds_tpu_launch")
    parser.add_argument("--world_info", required=True,
                        help="base64 {host: slots} map from the runner")
    parser.add_argument("--node_rank", type=int, required=True)
    parser.add_argument("--master_addr", required=True)
    parser.add_argument("--master_port", type=int, default=29500)
    parser.add_argument("training_script")
    parser.add_argument("training_script_args", nargs=argparse.REMAINDER)
    return parser.parse_args(argv)


def main(argv=None):
    args = parse_args(argv)
    world = decode_world_info(args.world_info)
    num_hosts = len(world)
    if not (0 <= args.node_rank < num_hosts):
        raise ValueError(f"node_rank {args.node_rank} out of range "
                         f"for {num_hosts} hosts")

    env = dict(os.environ)
    env["DS_COORDINATOR_ADDRESS"] = f"{args.master_addr}:{args.master_port}"
    env["DS_NUM_PROCESSES"] = str(num_hosts)
    env["DS_PROCESS_ID"] = str(args.node_rank)
    # reference-compatible aliases some user scripts read
    env["RANK"] = str(args.node_rank)
    env["WORLD_SIZE"] = str(num_hosts)
    env["MASTER_ADDR"] = args.master_addr
    env["MASTER_PORT"] = str(args.master_port)

    cmd = [sys.executable, "-u", args.training_script] + args.training_script_args
    logger.info(f"node {args.node_rank}/{num_hosts}: {' '.join(cmd)}")
    proc = subprocess.Popen(cmd, env=env)

    def _kill(signum, frame):
        logger.info(f"signal {signum}: killing child {proc.pid}")
        proc.terminate()
        try:
            proc.wait(timeout=10)
        except subprocess.TimeoutExpired:
            proc.kill()
        sys.exit(128 + signum)

    signal.signal(signal.SIGINT, _kill)
    signal.signal(signal.SIGTERM, _kill)
    return proc.wait()


if __name__ == "__main__":
    sys.exit(main())
