"""`ds_tpu` CLI: multi-host job launcher.

Reference: launcher/runner.py — fetch_hostfile (:157), include/exclude
filters (:198), world-info encoding (:298), runner selection, main (:317).
TPU shape: hostfile lines are ``hostname slots=N`` (slots = chips, kept
for reporting; process count is per-host). Runners build pdsh/ssh command
lines that exec ``python -m deepspeed_tpu.launcher.launch`` on every host
with the rendezvous env.
"""

import argparse
import base64
import json
import os
import re
import shlex
import subprocess
import sys
from collections import OrderedDict
from typing import Dict, List, Optional

from ..utils.logging import logger

DLTS_HOSTFILE = "/job/hostfile"


def fetch_hostfile(hostfile_path: str) -> "OrderedDict[str, int]":
    """Parse ``hostname slots=N`` lines (reference: runner.py:157)."""
    if not os.path.isfile(hostfile_path):
        logger.warning(f"Unable to find hostfile {hostfile_path}; "
                       "proceeding single-host")
        return OrderedDict()
    resource_pool: "OrderedDict[str, int]" = OrderedDict()
    with open(hostfile_path) as f:
        for line in f:
            line = line.strip()
            if not line or line.startswith("#"):
                continue
            m = re.match(r"^(\S+)\s+slots=(\d+)", line)
            if m is None:
                raise ValueError(f"Hostfile line malformed: '{line}' "
                                 "(expect 'hostname slots=N')")
            host, slots = m.group(1), int(m.group(2))
            if host in resource_pool:
                raise ValueError(f"Hostfile contains duplicate host {host}")
            resource_pool[host] = slots
    return resource_pool


def parse_inclusion_exclusion(resource_pool: Dict[str, int],
                              inclusion: str, exclusion: str
                              ) -> "OrderedDict[str, int]":
    """--include/--exclude host filters, 'host1,host2' or '@file' style
    (reference: runner.py:198 parse_resource_filter; TPU hosts are whole
    units, so no per-slot selection)."""
    active = OrderedDict(resource_pool)
    if inclusion:
        wanted = set(inclusion.split(","))
        unknown = wanted - set(active)
        if unknown:
            raise ValueError(f"--include hosts not in hostfile: {unknown}")
        active = OrderedDict((h, s) for h, s in active.items() if h in wanted)
    if exclusion:
        dropped = set(exclusion.split(","))
        unknown = dropped - set(active)
        if unknown:
            raise ValueError(f"--exclude hosts not in hostfile: {unknown}")
        active = OrderedDict((h, s) for h, s in active.items()
                             if h not in dropped)
    if not active:
        raise ValueError("No hosts remain after include/exclude filtering")
    return active


def encode_world_info(resource_pool: Dict[str, int]) -> str:
    """base64 world map passed down to per-host launchers
    (reference: runner.py:298)."""
    return base64.urlsafe_b64encode(
        json.dumps(resource_pool).encode()).decode()


def decode_world_info(encoded: str) -> Dict[str, int]:
    return json.loads(base64.urlsafe_b64decode(encoded.encode()).decode())


class MultiNodeRunner:
    """Reference: multinode_runner.py:13 ABC."""

    def __init__(self, args, world_info_base64: str):
        self.args = args
        self.world_info_base64 = world_info_base64

    def backend_exists(self) -> bool:
        raise NotImplementedError

    def get_cmd(self, environment: Dict[str, str],
                active_resources: Dict[str, int]) -> List[str]:
        raise NotImplementedError

    @property
    def user_arguments(self) -> List[str]:
        return list(map(shlex.quote, self.args.user_args))

    def _launch_cmd(self, proc_id_expr: str) -> str:
        """The per-host command: run the per-node launcher module. Starts
        with a cd into the launch directory (reference runner prepends the
        same) — remote shells begin in the login dir, where relative
        user_script/config paths would break while the single-host path
        silently worked."""
        import os
        exports = " ".join(
            f"{k}={shlex.quote(v)}" for k, v in self.exports.items())
        return (f"cd {shlex.quote(os.path.abspath(os.curdir))}; "
                f"{exports} {sys.executable} -m deepspeed_tpu.launcher.launch "
                f"--world_info={self.world_info_base64} "
                f"--node_rank={proc_id_expr} "
                f"--master_addr={self.args.master_addr} "
                f"--master_port={self.args.master_port} "
                f"{shlex.quote(self.args.user_script)} "
                + " ".join(self.user_arguments))

    exports: Dict[str, str] = {}


class PDSHRunner(MultiNodeRunner):
    """Reference: multinode_runner.py:45."""

    def backend_exists(self) -> bool:
        return bool(_which("pdsh"))

    def get_cmd(self, environment, active_resources):
        environment["PDSH_RCMD_TYPE"] = "ssh"
        hosts = ",".join(active_resources.keys())
        self.exports = {k: v for k, v in environment.items()
                        if k.startswith(("DS_", "XLA_", "JAX_", "TPU_"))}
        # %n is pdsh's node-rank substitution
        return ["pdsh", "-S", "-f", "1024", "-w", hosts,
                self._launch_cmd("%n")]


class SSHRunner(MultiNodeRunner):
    """Plain ssh loop (TPU-VM pods: `gcloud compute tpus tpu-vm ssh` is a
    drop-in by setting --ssh_cmd). One ssh per host, backgrounded."""

    def backend_exists(self) -> bool:
        return bool(_which(self.args.ssh_cmd.split()[0]))

    def get_cmd(self, environment, active_resources):
        self.exports = {k: v for k, v in environment.items()
                        if k.startswith(("DS_", "XLA_", "JAX_", "TPU_"))}
        cmds = []
        for rank, host in enumerate(active_resources):
            cmds.append(" ".join(
                self.args.ssh_cmd.split() + [host,
                                             shlex.quote(self._launch_cmd(str(rank)))]))
        # join each pid explicitly — a bare `wait` always exits 0 and would
        # mask remote training failures from CI/schedulers
        script = ("pids=(); "
                  + " ".join(f"{c} & pids+=($!);" for c in cmds)
                  + ' rc=0; for p in "${pids[@]}"; do wait "$p" || rc=1; '
                  "done; exit $rc")
        return ["bash", "-c", script]


def _which(prog):
    import shutil
    return shutil.which(prog)


def parse_args(argv=None):
    parser = argparse.ArgumentParser(
        prog="ds_tpu",
        description="deepspeed_tpu multi-host launcher (reference: the "
                    "`deepspeed` CLI)")
    parser.add_argument("-H", "--hostfile", default=DLTS_HOSTFILE,
                        help="hostname slots=N lines; absent = single host")
    parser.add_argument("-i", "--include", default="",
                        help="comma-separated hosts to include")
    parser.add_argument("-e", "--exclude", default="",
                        help="comma-separated hosts to exclude")
    parser.add_argument("--master_port", type=int, default=29500)
    parser.add_argument("--master_addr", default="",
                        help="coordinator address; default = first host")
    parser.add_argument("--launcher", default="pdsh",
                        choices=["pdsh", "ssh"],)
    parser.add_argument("--ssh_cmd", default="ssh",
                        help="ssh command prefix (e.g. 'gcloud compute tpus "
                             "tpu-vm ssh')")
    parser.add_argument("--force_multi", action="store_true",
                        help="force the multi-node path on one host")
    parser.add_argument("--autotuning", default="", choices=["", "tune"],
                        help="run the autotuner over the user script "
                             "instead of launching training (reference: "
                             "deepspeed --autotuning)")
    parser.add_argument("--autotuning_config", default="",
                        help="path to the tuning-space json (see "
                             "autotuning/runner.py run_autotuning_cli)")
    parser.add_argument("user_script", help="training script to launch")
    parser.add_argument("user_args", nargs=argparse.REMAINDER)
    return parser.parse_args(argv)


def main(argv=None):
    args = parse_args(argv)

    if args.autotuning:
        if not args.autotuning_config:
            raise SystemExit("--autotuning requires --autotuning_config")
        from ..autotuning.runner import run_autotuning_cli
        return run_autotuning_cli(args)

    resource_pool = fetch_hostfile(args.hostfile)

    if not resource_pool and not args.force_multi:
        # single host: exec the script in-process env, no rendezvous needed
        cmd = [sys.executable, args.user_script] + args.user_args
        logger.info(f"launching single-host: {' '.join(cmd)}")
        return subprocess.call(cmd)
    if not resource_pool:
        # --force_multi without a hostfile: the multi-node path on
        # localhost (otherwise the inclusion filter below raises a
        # misleading 'no hosts remain')
        resource_pool = {"localhost": 1}

    active = parse_inclusion_exclusion(resource_pool, args.include,
                                       args.exclude)
    args.master_addr = args.master_addr or next(iter(active))
    world_info = encode_world_info(active)

    runner_cls = {"pdsh": PDSHRunner, "ssh": SSHRunner}[args.launcher]
    runner = runner_cls(args, world_info)
    if not runner.backend_exists():
        raise RuntimeError(f"launcher backend '{args.launcher}' not found "
                           "on PATH")
    env = dict(os.environ)
    cmd = runner.get_cmd(env, active)
    logger.info(f"cmd = {' '.join(cmd)}")
    # env= matters: get_cmd mutates the copy (PDSH_RCMD_TYPE=ssh)
    return subprocess.call(cmd, env=env)


if __name__ == "__main__":
    sys.exit(main())
