"""Multi-host launcher (reference: deepspeed/launcher/).

The reference spawns one process per GPU per node via pdsh/mpirun
(launcher/runner.py:317, launcher/launch.py:90). On TPU pods the unit is
one process per HOST (each host drives its local chips through a single
JAX client), so the launcher's job is: parse the hostfile, pick a
coordinator, and start the training script on every host with
``DS_COORDINATOR_ADDRESS`` / ``DS_NUM_PROCESSES`` / ``DS_PROCESS_ID`` set
(consumed by deepspeed_tpu.comm.init_distributed ->
jax.distributed.initialize).
"""

from .runner import main as runner_main
from .launch import main as launch_main

__all__ = ["runner_main", "launch_main"]
