"""Compression-aware training as functional param transforms.

Reference: compression/compress.py — init_compression (:97) swaps
Linear/Embedding for compressible variants (basic_layer.py:134
LinearLayer_Compress) that fake-quantize weights / apply pruning masks in
forward; redundancy_clean (:127) bakes the compression in at the end.

Flax params are pure pytrees, so the TPU-native mechanism is a
*projection* applied to the param tree at the gradient-accumulation
boundary (quantize-aware training's straight-through estimator is exactly
"project after step"): fake-quant snaps matched weights to their
bits-wide grid, pruning applies magnitude masks. ``redundancy_clean``
returns the final projected tree for serving.
"""

from typing import Any, Dict, Optional

import numpy as np
import jax
import jax.numpy as jnp

from ..utils.logging import logger
from .config import CompressionConfig


def _matches(path: str, patterns) -> bool:
    return any(p == "*" or p in path for p in patterns)


def fake_quantize(w, bits: int = 8, symmetric: bool = True,
                  per_channel: bool = True):
    """Uniform fake quantization (reference: basic_layer.py weight
    quantization; kernels csrc/quantization). Keeps dtype; snaps values
    to the 2^bits grid — the straight-through forward."""
    q = 2 ** (bits - 1) - 1
    axis = tuple(range(w.ndim - 1)) if per_channel and w.ndim > 1 else None
    if symmetric:
        scale = jnp.max(jnp.abs(w), axis=axis, keepdims=True) / q
        scale = jnp.maximum(scale, 1e-8)
        return jnp.round(w / scale).clip(-q - 1, q) * scale
    lo = jnp.min(w, axis=axis, keepdims=True)
    hi = jnp.max(w, axis=axis, keepdims=True)
    scale = jnp.maximum((hi - lo) / (2 ** bits - 1), 1e-8)
    return jnp.round((w - lo) / scale) * scale + lo


def fake_quantize_activation(x, bits: int = 8, symmetric: bool = True):
    """Dynamic-range activation fake quantization with a straight-through
    estimator (reference: basic_layer.py:378 enable_activation_quantization
    with range_calibration='dynamic'; SymQuantizer/AsymQuantizer.apply are
    torch autograd STE functions — here the classic x + sg(q(x) - x)).
    Per-tensor range, recomputed per call (the 'dynamic' mode; 'static'
    calibration has no analog — XLA recomputes the range for free)."""
    q = fake_quantize(x, bits=bits, symmetric=symmetric, per_channel=False)
    return x + jax.lax.stop_gradient(q - x)


def magnitude_mask(w, ratio: float):
    """Unstructured sparse-pruning mask: zero the smallest |w| fraction
    (reference: sparse_pruning method=l1)."""
    if ratio <= 0:
        return jnp.ones_like(w, dtype=bool)
    k = int(np.prod(w.shape) * ratio)
    if k == 0:
        return jnp.ones_like(w, dtype=bool)
    thresh = jnp.sort(jnp.abs(w).reshape(-1))[k - 1]
    return jnp.abs(w) > thresh


def row_mask(w, ratio: float):
    """Structured row pruning: drop output rows with the smallest L2 norm
    (reference: basic_layer.py row pruning)."""
    if ratio <= 0 or w.ndim < 2:
        return jnp.ones_like(w, dtype=bool)
    norms = jnp.sqrt(jnp.sum(w * w, axis=tuple(range(w.ndim - 1))))
    k = int(norms.shape[0] * ratio)
    if k == 0:
        return jnp.ones_like(w, dtype=bool)
    thresh = jnp.sort(norms)[k - 1]
    return jnp.broadcast_to(norms > thresh, w.shape)


def channel_mask(w, ratio: float):
    """Structured input-channel pruning: drop rows of the FIRST dim with
    the smallest L2 norm (reference: basic_layer.py channel pruning)."""
    if ratio <= 0 or w.ndim < 2:
        return jnp.ones_like(w, dtype=bool)
    norms = jnp.sqrt(jnp.sum(w * w, axis=tuple(range(1, w.ndim))))
    k = int(norms.shape[0] * ratio)
    if k == 0:
        return jnp.ones_like(w, dtype=bool)
    thresh = jnp.sort(norms)[k - 1]
    keep = (norms > thresh).reshape((w.shape[0],) + (1,) * (w.ndim - 1))
    return jnp.broadcast_to(keep, w.shape)


def head_mask(w, ratio: float, num_heads: int):
    """Structured head pruning on an attention projection whose LAST dim
    is heads*head_dim: drop whole head-blocks by L2 norm (reference:
    basic_layer.py head pruning on the output projection's rows)."""
    out_dim = w.shape[-1]
    if ratio <= 0 or num_heads <= 1 or out_dim % num_heads != 0:
        return jnp.ones_like(w, dtype=bool)
    head_dim = out_dim // num_heads
    grouped = w.reshape(*w.shape[:-1], num_heads, head_dim)
    norms = jnp.sqrt(jnp.sum(
        grouped * grouped, axis=tuple(range(grouped.ndim - 2)) + (grouped.ndim - 1,)))
    k = int(num_heads * ratio)
    if k == 0:
        return jnp.ones_like(w, dtype=bool)
    thresh = jnp.sort(norms)[k - 1]
    keep = jnp.repeat(norms > thresh, head_dim)
    return jnp.broadcast_to(keep, w.shape)


class Compressor:
    """Schedule-driven param projection; apply() each step (cheap no-op
    before the schedule offsets)."""

    def __init__(self, config: CompressionConfig):
        self.config = config
        self._jitted: Dict[Any, Any] = {}

    def _project_leaf(self, path: str, w, step: int):
        if not hasattr(w, "ndim") or w.ndim == 0 or \
                not jnp.issubdtype(w.dtype, jnp.floating):
            return w
        c = self.config
        out = w
        if c.sparse_pruning.enabled and step >= c.sparse_pruning.schedule_offset:
            for g in c.sparse_pruning.groups.values():
                if _matches(path, g.modules):
                    out = out * magnitude_mask(
                        out, float(g.params.get("dense_ratio_delta", 0)
                                   or 1 - g.params.get("dense_ratio", 1)))
        if c.row_pruning.enabled and step >= c.row_pruning.schedule_offset:
            for g in c.row_pruning.groups.values():
                if _matches(path, g.modules):
                    out = out * row_mask(
                        out, 1 - g.params.get("dense_ratio", 1))
        if c.channel_pruning.enabled and \
                step >= c.channel_pruning.schedule_offset:
            for g in c.channel_pruning.groups.values():
                if _matches(path, g.modules):
                    out = out * channel_mask(
                        out, 1 - g.params.get("dense_ratio", 1))
        if c.head_pruning.enabled and step >= c.head_pruning.schedule_offset:
            for g in c.head_pruning.groups.values():
                if _matches(path, g.modules):
                    out = out * head_mask(
                        out, 1 - g.params.get("dense_ratio", 1),
                        num_heads=int(g.params.get("num_heads", 1)))
        if c.weight_quantization.enabled and \
                step >= c.weight_quantization.schedule_offset:
            for g in c.weight_quantization.groups.values():
                if _matches(path, g.modules):
                    out = fake_quantize(
                        out, bits=int(g.params.get("start_bits",
                                                   g.params.get("bits", 8))),
                        symmetric=g.params.get("quantization_type",
                                               "symmetric") == "symmetric")
        return out

    def active(self, step: int) -> bool:
        c = self.config
        return any(t.enabled and step >= t.schedule_offset
                   for t in (c.weight_quantization, c.sparse_pruning,
                             c.row_pruning, c.head_pruning, c.channel_pruning))

    def apply(self, params, step: int):
        """Project the param tree per the schedule (jitted per step-phase,
        not per step: the projection only changes when techniques toggle)."""
        if not self.active(step):
            return params
        phase = tuple(
            t.enabled and step >= t.schedule_offset
            for t in (self.config.weight_quantization,
                      self.config.sparse_pruning, self.config.row_pruning,
                      self.config.channel_pruning, self.config.head_pruning))
        if phase not in self._jitted:
            def project(tree):
                flat, treedef = jax.tree.flatten_with_path(tree)
                out = [self._project_leaf(jax.tree_util.keystr(p), w, step)
                       for p, w in flat]
                return jax.tree.unflatten(treedef, out)
            from ..observability.programs import track_program
            tag = "".join("1" if t else "0" for t in phase)
            self._jitted[phase] = track_program(
                f"compression/project_{tag}", jax.jit(project),
                subsystem="compression")
        return self._jitted[phase](params)


def init_compression(config: Optional[dict]) -> Optional[Compressor]:
    """Build a Compressor from the ``compression_training`` block
    (reference: init_compression compress.py:97); None when nothing is
    enabled."""
    cc = CompressionConfig.from_dict(config)
    if not cc.any_enabled():
        return None
    logger.info("compression-aware training enabled: " + ", ".join(
        f for f in cc.__dataclass_fields__ if getattr(cc, f).enabled))
    return Compressor(cc)


def redundancy_clean(params, config: Optional[dict]):
    """Final projection for serving (reference: compress.py:127)."""
    comp = init_compression(config)
    if comp is None:
        return params
    return comp.apply(params, step=1 << 30)


def apply_layer_reduction(params, config: Dict[str, Any]):
    """Layer reduction / distillation init (reference: compress.py:182
    student_initialization — the student keeps ``keep_number_layers``
    layers, each initialized from a chosen teacher layer).

    ``config``: the ``layer_reduction`` block —
      {"enabled": true, "keep_number_layers": K,
       "teacher_layer": [i0, ..., iK-1],          # which teacher layers
       "module_name_prefix": ...}                 # accepted, unused here

    Works on scan-stacked models ([L, ...] leaves under a scan collection
    like "h") by index-selecting the teacher layers on axis 0, and on
    unstacked models ("h_0".."h_{L-1}" subtrees) by re-keying. Returns
    (new_params, kept_layers)."""
    if not config or not config.get("enabled", False):
        return params, None
    teacher_layers = config.get("teacher_layer")
    keep = config.get("keep_number_layers")
    if teacher_layers is None:
        if keep is None:
            raise ValueError("layer_reduction needs teacher_layer or "
                             "keep_number_layers")
        # evenly spaced teacher layers (reference default policy)
        n_layers = _count_layers(params)
        idx = np.linspace(0, n_layers - 1, keep).round().astype(int)
        teacher_layers = [int(i) for i in idx]
    teacher_layers = list(teacher_layers)

    # unstacked layout: h_0 ... h_{L-1} subtrees
    keys = params.keys() if isinstance(params, dict) else ()
    layer_keys = sorted((k for k in keys if k.startswith("h_")),
                        key=lambda k: int(k.split("_")[1]))
    if layer_keys:
        new = {k: v for k, v in params.items() if not k.startswith("h_")}
        for si, ti in enumerate(teacher_layers):
            new[f"h_{si}"] = params[f"h_{ti}"]
        return new, teacher_layers

    # scan-stacked layout: every leaf under a stacked collection has
    # leading dim == n_layers
    n_layers = _count_layers(params)
    sel = jnp.asarray(teacher_layers)

    def one(x):
        if hasattr(x, "shape") and x.ndim >= 1 and x.shape[0] == n_layers:
            return jnp.take(x, sel, axis=0)
        return x

    stacked = {k: jax.tree.map(one, v) for k, v in params.items()
               if k in ("h", "blocks")}
    new = dict(params)
    new.update(stacked)
    return new, teacher_layers


def student_initialization(student_params, teacher_params,
                           config: Dict[str, Any]):
    """Distillation init (reference: compress.py:182): map selected
    TEACHER layers onto the (shallower) STUDENT's layer slots and copy
    the shared non-layer modules, before knowledge-distillation training.

    ``config``: a ds config dict with a ``compression_training.
    layer_reduction`` block, or the layer_reduction block itself —
      {"teacher_layer": [1, 3, 5, ...],   # teacher layer per student slot
       "other_module_name": [...]}        # non-layer modules to copy;
                                          # default: every shared subtree
    Works on scan-stacked ("h"/"blocks" [L, ...] leaves) and unrolled
    ("h_0".."h_{L-1}") layouts. Returns the initialized student tree."""
    cfg = config
    for key in ("compression_training", "layer_reduction"):
        if isinstance(cfg, dict) and key in cfg:
            cfg = cfg[key]
    teacher_layer = cfg.get("teacher_layer")
    if teacher_layer is None:
        raise ValueError("student_initialization needs "
                         "layer_reduction.teacher_layer")
    n_student = _count_layers(student_params)
    if len(teacher_layer) != n_student:
        raise ValueError(
            f"teacher_layer has {len(teacher_layer)} entries for a "
            f"student with {n_student} layers")
    reduced, _ = apply_layer_reduction(
        teacher_params, {"enabled": True, "teacher_layer": teacher_layer})

    # reconcile layer LAYOUTS: teacher checkpoints may be unrolled
    # (h_0..h_{L-1}) while the student is scan-stacked ("h"), or the
    # reverse — convert instead of silently skipping the copy
    red_unrolled = sorted((k for k in reduced if k.startswith("h_")),
                          key=lambda k: int(k.split("_")[1]))
    stu_keys = set(student_params.keys())
    stu_stacked = next((k for k in ("h", "blocks") if k in stu_keys), None)
    stu_unrolled = sorted((k for k in stu_keys if k.startswith("h_")),
                          key=lambda k: int(k.split("_")[1]))
    if red_unrolled and stu_stacked:
        per_layer = [reduced[k] for k in red_unrolled]
        reduced = {k: v for k, v in reduced.items()
                   if not k.startswith("h_")}
        reduced[stu_stacked] = jax.tree.map(
            lambda *xs: jnp.stack(xs), *per_layer)
    elif stu_unrolled and not red_unrolled:
        red_stacked = next((k for k in ("h", "blocks") if k in reduced),
                           None)
        if red_stacked is not None:
            stacked = reduced.pop(red_stacked)
            for i in range(len(stu_unrolled)):
                reduced[f"h_{i}"] = jax.tree.map(lambda x, _i=i: x[_i],
                                                 stacked)
    other = cfg.get("other_module_name")
    new = dict(student_params)
    copied_layers = False
    for k, v in reduced.items():
        is_layer = k in ("h", "blocks") or k.startswith("h_")
        if not is_layer and other is not None \
                and not any(o in k for o in other):
            continue
        if k in new:
            new[k] = v
            copied_layers = copied_layers or is_layer
    if not copied_layers:
        raise ValueError(
            "student_initialization copied no layer weights — teacher "
            f"layer keys {sorted(k for k in reduced if k.startswith('h_') or k in ('h', 'blocks'))} "
            f"do not match the student's {sorted(stu_keys)}")
    return new


def _count_layers(params) -> int:
    keys = params.keys() if isinstance(params, dict) else ()
    layer_keys = [k for k in keys if k.startswith("h_")]
    if layer_keys:
        return len(layer_keys)
    for k in ("h", "blocks"):
        if k in params:
            leaf = jax.tree.leaves(params[k])[0]
            return int(leaf.shape[0])
    raise ValueError("cannot locate transformer layers in params "
                     "(expected 'h'/'blocks' stack or 'h_N' subtrees)")
