from .compress import (Compressor, apply_layer_reduction,
                       fake_quantize_activation, init_compression,
                       redundancy_clean, student_initialization)
from .config import CompressionConfig
from .scheduler import CompressionScheduler

__all__ = ["Compressor", "apply_layer_reduction",
           "fake_quantize_activation", "init_compression",
           "redundancy_clean", "student_initialization",
           "CompressionConfig", "CompressionScheduler"]
