from .compress import (Compressor, init_compression, redundancy_clean)
from .config import CompressionConfig
from .scheduler import CompressionScheduler

__all__ = ["Compressor", "init_compression", "redundancy_clean",
           "CompressionConfig", "CompressionScheduler"]
