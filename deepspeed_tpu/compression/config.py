"""Compression config schema.

Reference: compression/config.py + constants.py — the
``compression_training`` block with per-technique sub-blocks
(weight_quantization, activation_quantization, sparse_pruning,
row_pruning, head_pruning, channel_pruning), each with
shared_parameters (schedule_offset, enabled) and different_groups
(per-module-pattern overrides). The schema is preserved; "modules"
patterns match flax param-tree path substrings instead of torch module
names.
"""

from dataclasses import dataclass, field
from typing import Dict, List, Optional


@dataclass
class TechniqueGroup:
    """One ``different_groups`` entry: params + module patterns."""
    params: Dict = field(default_factory=dict)
    modules: List[str] = field(default_factory=lambda: ["*"])
    related_modules: Optional[List[str]] = None


@dataclass
class TechniqueConfig:
    enabled: bool = False
    schedule_offset: int = 0          # step at which the technique kicks in
    shared_parameters: Dict = field(default_factory=dict)
    groups: Dict[str, TechniqueGroup] = field(default_factory=dict)

    @classmethod
    def from_dict(cls, d: dict) -> "TechniqueConfig":
        shared = dict(d.get("shared_parameters", {}))
        groups = {}
        for name, g in d.get("different_groups", {}).items():
            groups[name] = TechniqueGroup(
                params=dict(g.get("params", {})),
                modules=list(g.get("modules", ["*"])),
                related_modules=g.get("related_modules"))
        return cls(enabled=shared.get("enabled", bool(groups)),
                   schedule_offset=shared.get("schedule_offset", 0),
                   shared_parameters=shared, groups=groups)


@dataclass
class CompressionConfig:
    weight_quantization: TechniqueConfig = field(default_factory=TechniqueConfig)
    activation_quantization: TechniqueConfig = field(default_factory=TechniqueConfig)
    sparse_pruning: TechniqueConfig = field(default_factory=TechniqueConfig)
    row_pruning: TechniqueConfig = field(default_factory=TechniqueConfig)
    head_pruning: TechniqueConfig = field(default_factory=TechniqueConfig)
    channel_pruning: TechniqueConfig = field(default_factory=TechniqueConfig)

    @classmethod
    def from_dict(cls, d: Optional[dict]) -> "CompressionConfig":
        d = d or {}
        kw = {}
        for f in cls.__dataclass_fields__:
            if f in d:
                kw[f] = TechniqueConfig.from_dict(d[f])
        return cls(**kw)

    def any_enabled(self) -> bool:
        return any(getattr(self, f).enabled for f in self.__dataclass_fields__)
