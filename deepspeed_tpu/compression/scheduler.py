"""Compression scheduler (reference: compression/scheduler.py, stepped at
engine.py:1885): tracks the training step and applies the Compressor's
projection at gradient-accumulation boundaries."""

from typing import Optional

from .compress import Compressor


class CompressionScheduler:
    def __init__(self, compressor: Compressor):
        self.compressor = compressor
        self.training_steps = 0

    def step(self, params):
        """Call once per optimizer step; returns (possibly projected)
        params."""
        self.training_steps += 1
        return self.compressor.apply(params, self.training_steps)
