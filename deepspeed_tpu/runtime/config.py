"""The DeepSpeed-TPU config system.

TPU-native analog of the reference's ``DeepSpeedConfig``
(deepspeed/runtime/config.py — 1018 LoC of JSON parsing + ~80 accessors).
Same JSON schema and key names so reference configs load unchanged; the
mechanism is dataclasses instead of a dict of get_* readers. One extension
block: ``"mesh"`` declares the device-mesh axis sizes (the TPU replacement
for world-size/process-group arithmetic).

Batch-size arithmetic follows the reference exactly
(runtime/config.py _batch_assertion): train_batch_size =
micro_batch_per_device * gradient_accumulation_steps * dp_world_size.
"""

import json
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

from .config_utils import DeepSpeedConfigError, dict_to_dataclass, dataclass_to_dict
from .resilience.config import ResilienceConfig
from .tiering.config import TieringConfig
from ..observability.config import ObservabilityConfig
from ..serving.config import ServingConfig
from ..utils.logging import logger


# ---------------------------------------------------------------------------
# Precision
# ---------------------------------------------------------------------------

@dataclass
class FP16Config:
    """reference: fp16 block (runtime/config.py get_fp16_enabled etc.)"""
    enabled: bool = False
    auto_cast: bool = False
    loss_scale: float = 0.0           # 0 -> dynamic
    initial_scale_power: int = 16
    loss_scale_window: int = 1000
    hysteresis: int = 2
    min_loss_scale: float = 1.0

    @property
    def dynamic_loss_scale(self):
        return self.loss_scale == 0


@dataclass
class BF16Config:
    enabled: bool = False


# ---------------------------------------------------------------------------
# ZeRO
# ---------------------------------------------------------------------------

@dataclass
class OffloadParamConfig:
    """reference: runtime/zero/offload_config.py (offload_param)"""
    device: str = "none"              # none | cpu | nvme
    nvme_path: str = "/tmp/nvme"
    buffer_count: int = 5
    buffer_size: int = 100_000_000
    max_in_cpu: int = 1_000_000_000
    pin_memory: bool = False


@dataclass
class OffloadOptimizerConfig:
    """reference: runtime/zero/offload_config.py (offload_optimizer)"""
    device: str = "none"              # none | cpu | nvme
    nvme_path: str = "/tmp/nvme"
    buffer_count: int = 4
    pin_memory: bool = False
    pipeline_read: bool = False
    pipeline_write: bool = False
    fast_init: bool = False
    # NEW (TPU): route the optimizer step through the native C++ cpu_adam
    # kernel (csrc/cpu_adam.cpp) with state in host numpy — the reference's
    # actual ZeRO-Offload dataflow. False = XLA pinned_host offload (the
    # declarative path). device=nvme with native=True swaps Adam moments
    # to local SSD between steps via the aio op (ZeRO-Infinity).
    native: bool = False
    aio_threads: int = 4


@dataclass
class ZeroConfig:
    """reference: zero_optimization block (runtime/zero/config.py)"""
    stage: int = 0
    contiguous_gradients: bool = True
    reduce_scatter: bool = True
    reduce_bucket_size: int = 500_000_000
    allgather_partitions: bool = True
    allgather_bucket_size: int = 500_000_000
    overlap_comm: bool = False
    load_from_fp32_weights: bool = True
    elastic_checkpoint: bool = False
    offload_param: Optional[dict] = None
    offload_optimizer: Optional[dict] = None
    sub_group_size: int = 1_000_000_000_000
    cpu_offload: bool = False          # deprecated alias for offload_optimizer.device=cpu
    # Stage-3 knobs. On TPU "live parameters"/"prefetch" map onto how many
    # layers' params are gathered per scan block; persistence threshold maps
    # to the replicate-small-params rule.
    stage3_max_live_parameters: int = 1_000_000_000
    stage3_max_reuse_distance: int = 1_000_000_000
    stage3_prefetch_bucket_size: int = 50_000_000
    stage3_param_persistence_threshold: int = 100_000
    stage3_gather_16bit_weights_on_model_save: bool = False
    ignore_unused_parameters: bool = True
    round_robin_gradients: bool = False

    def __post_init__(self):
        if self.stage not in (0, 1, 2, 3):
            raise DeepSpeedConfigError(f"zero_optimization.stage must be 0-3, got {self.stage}")
        if isinstance(self.offload_param, dict):
            self.offload_param = dict_to_dataclass(OffloadParamConfig, self.offload_param,
                                                   "zero_optimization.offload_param")
        if isinstance(self.offload_optimizer, dict):
            self.offload_optimizer = dict_to_dataclass(OffloadOptimizerConfig, self.offload_optimizer,
                                                       "zero_optimization.offload_optimizer")
        if self.cpu_offload and self.offload_optimizer is None:
            self.offload_optimizer = OffloadOptimizerConfig(device="cpu")

    @property
    def offload_optimizer_device(self):
        return self.offload_optimizer.device if self.offload_optimizer else "none"

    @property
    def offload_param_device(self):
        return self.offload_param.device if self.offload_param else "none"


# ---------------------------------------------------------------------------
# Optimizer / scheduler
# ---------------------------------------------------------------------------

@dataclass
class OptimizerConfig:
    type: str = "Adam"
    params: Dict[str, Any] = field(default_factory=dict)
    legacy_fusion: bool = False


@dataclass
class SchedulerConfig:
    type: Optional[str] = None
    params: Dict[str, Any] = field(default_factory=dict)


# ---------------------------------------------------------------------------
# Activation checkpointing (reference: runtime/activation_checkpointing/config.py)
# ---------------------------------------------------------------------------

@dataclass
class DataTypesConfig:
    """``data_types`` block (reference: the grad_accum_dtype knob of
    DeepSpeed's data-type config). ``grad_accum_dtype`` selects the
    microbatch gradient-accumulation buffer dtype: None/"fp32" (default,
    the reference's reduce-in-fp32 semantics) or "bf16" — halves the
    resident grad-buffer HBM at a small accumulation-precision cost
    (meaningful over large gradient_accumulation_steps)."""
    grad_accum_dtype: Optional[str] = None

    def resolve(self):
        v = (self.grad_accum_dtype or "fp32").lower()
        if v in ("fp32", "float32"):
            return "float32"
        if v in ("bf16", "bfloat16"):
            return "bfloat16"
        raise DeepSpeedConfigError(
            f"data_types.grad_accum_dtype must be fp32 or bf16, got "
            f"{self.grad_accum_dtype!r}")


# Mirrors models.gpt.REMAT_POLICIES (kept in sync by a unit test; defined
# here so config validation never imports the model zoo). NEW (TPU): the
# reference always recomputes the whole region; XLA remat lets the policy
# choose WHAT to save — a real perf knob the autotuner can walk.
REMAT_POLICY_NAMES = ("none", "full", "dots", "dots_no_batch", "offload",
                      "attn_out")


@dataclass
class ActivationCheckpointingConfig:
    partition_activations: bool = False
    cpu_checkpointing: bool = False
    contiguous_memory_optimization: bool = False
    number_checkpoints: Optional[int] = None
    synchronize_checkpoint_boundary: bool = False
    profile: bool = False
    # NEW (TPU): which activations the checkpointed region SAVES
    # (models.gpt.REMAT_POLICIES key). None = "full" (recompute
    # everything, the reference semantics); "dots" = save matmul outputs;
    # "attn_out" = save only attention outputs (never recompute the flash
    # kernel); "offload" = saveable dots staged to pinned host memory.
    remat_policy: Optional[str] = None

    def __post_init__(self):
        if (self.remat_policy is not None
                and self.remat_policy not in REMAT_POLICY_NAMES):
            raise DeepSpeedConfigError(
                f"activation_checkpointing.remat_policy must be one of "
                f"{REMAT_POLICY_NAMES}, got {self.remat_policy!r}")


# ---------------------------------------------------------------------------
# Monitoring (reference: deepspeed/monitor/config.py)
# ---------------------------------------------------------------------------

@dataclass
class TensorBoardConfig:
    enabled: bool = False
    output_path: str = ""
    job_name: str = "DeepSpeedJobName"


@dataclass
class WandbConfig:
    enabled: bool = False
    group: Optional[str] = None
    team: Optional[str] = None
    project: str = "deepspeed_tpu"


@dataclass
class CSVConfig:
    enabled: bool = False
    output_path: str = ""
    job_name: str = "DeepSpeedJobName"


# ---------------------------------------------------------------------------
# Profiling (reference: deepspeed/profiling/config.py)
# ---------------------------------------------------------------------------

@dataclass
class FlopsProfilerConfig:
    enabled: bool = False
    profile_step: int = 1
    module_depth: int = -1
    top_modules: int = 1
    detailed: bool = True
    output_file: Optional[str] = None


@dataclass
class CommsLoggerConfig:
    enabled: bool = False
    verbose: bool = False
    prof_all: bool = True
    debug: bool = False
    prof_ops: List[str] = field(default_factory=list)


# ---------------------------------------------------------------------------
# Data pipeline (reference: curriculum_learning block) & regularization
# ---------------------------------------------------------------------------

@dataclass
class CurriculumConfig:
    enabled: bool = False
    curriculum_type: str = "seqlen"
    min_difficulty: int = 8
    max_difficulty: int = 1024
    schedule_type: str = "fixed_linear"
    schedule_config: Dict[str, Any] = field(default_factory=dict)


@dataclass
class ProgressiveLayerDropConfig:
    enabled: bool = False
    theta: float = 0.5
    gamma: float = 0.001


@dataclass
class EigenvalueConfig:
    enabled: bool = False
    verbose: bool = False
    max_iter: int = 100
    tol: float = 1e-2
    stability: float = 1e-6
    gas_boundary_resolution: int = 1
    layer_name: str = "layers"
    layer_num: int = 0


# ---------------------------------------------------------------------------
# AIO (reference: aio block for ZeRO-Infinity NVMe swap)
# ---------------------------------------------------------------------------

@dataclass
class AIOConfig:
    block_size: int = 1_048_576
    queue_depth: int = 8
    thread_count: int = 1
    single_submit: bool = False
    overlap_events: bool = True


# ---------------------------------------------------------------------------
# TPU extension: declarative mesh block
# ---------------------------------------------------------------------------

@dataclass
class MeshConfig:
    """NEW (TPU): axis sizes for the device mesh. data=-1 absorbs remainder."""
    stage: int = 1
    data: int = -1
    expert: int = 1
    fsdp: int = 1
    seq: int = 1
    model: int = 1

    def to_spec(self):
        """Bridge to the comm layer's MeshSpec consumed by build_mesh."""
        from ..comm.mesh import MeshSpec
        return MeshSpec(stage=self.stage, data=self.data, expert=self.expert,
                        fsdp=self.fsdp, seq=self.seq, model=self.model)


# ---------------------------------------------------------------------------
# Pipeline block (engine-level; reference keeps this on PipelineModule args)
# ---------------------------------------------------------------------------

@dataclass
class PipelineConfig:
    stages: int = 1
    partition_method: str = "parameters"
    seed_layers: bool = False
    activation_checkpoint_interval: int = 0


# ---------------------------------------------------------------------------
# Top-level
# ---------------------------------------------------------------------------

@dataclass
class DeepSpeedConfig:
    train_batch_size: Optional[int] = None
    train_micro_batch_size_per_gpu: Optional[int] = None
    gradient_accumulation_steps: Optional[int] = None

    optimizer: Optional[OptimizerConfig] = None
    scheduler: Optional[SchedulerConfig] = None

    fp16: FP16Config = field(default_factory=FP16Config)
    bf16: BF16Config = field(default_factory=BF16Config)
    zero_optimization: ZeroConfig = field(default_factory=ZeroConfig)

    gradient_clipping: float = 0.0
    prescale_gradients: bool = False
    gradient_predivide_factor: float = 1.0
    sparse_gradients: bool = False

    steps_per_print: int = 10
    wall_clock_breakdown: bool = False
    memory_breakdown: bool = False
    dump_state: bool = False
    # NEW (TPU): run the analysis-subsystem sharding checker at engine
    # init — every param/opt/grad PartitionSpec is validated against the
    # live mesh (declared axes, one-dim-per-axis, divisibility, opt state
    # extending the param spec). See docs/analysis.md.
    validate_sharding: bool = False
    # extra logical axis names the validator accepts beyond the live
    # mesh's, treated as size 1 — lets specs written for a larger target
    # mesh validate on a small host mesh, mirroring the lint packs'
    # KNOWN_AXES vocabulary so the static and runtime checks agree
    validate_sharding_extra_axes: List[str] = field(default_factory=list)

    activation_checkpointing: ActivationCheckpointingConfig = field(
        default_factory=ActivationCheckpointingConfig)
    data_types: DataTypesConfig = field(default_factory=DataTypesConfig)

    tensorboard: TensorBoardConfig = field(default_factory=TensorBoardConfig)
    wandb: WandbConfig = field(default_factory=WandbConfig)
    csv_monitor: CSVConfig = field(default_factory=CSVConfig)

    flops_profiler: FlopsProfilerConfig = field(default_factory=FlopsProfilerConfig)
    comms_logger: CommsLoggerConfig = field(default_factory=CommsLoggerConfig)

    curriculum_learning: CurriculumConfig = field(default_factory=CurriculumConfig)
    progressive_layer_drop: ProgressiveLayerDropConfig = field(
        default_factory=ProgressiveLayerDropConfig)
    eigenvalue: EigenvalueConfig = field(default_factory=EigenvalueConfig)

    aio: AIOConfig = field(default_factory=AIOConfig)
    mesh: MeshConfig = field(default_factory=MeshConfig)
    pipeline: PipelineConfig = field(default_factory=PipelineConfig)
    # continuous-batching serving engine (serving/engine.py); consumed by
    # ServingEngine.from_config — absent means "not serving". May carry a
    # nested "paging" sub-block (serving/paging/config.py): block-paged KV
    # cache + prefix sharing + chunked prefill; ServingConfig.__post_init__
    # lifts the nested dict (dict_to_dataclass is shallow).
    serving: Optional[ServingConfig] = None
    # fault-tolerant training (runtime/resilience/, docs/resilience.md);
    # absent means "no sentinel/preemption/watchdog" — checkpoint
    # manifests are still written (integrity is not opt-in)
    resilience: Optional[ResilienceConfig] = None
    # unified observability: trace spans + metrics registry + MFU
    # accounting (deepspeed_tpu/observability/, docs/observability.md);
    # absent/disabled leaves only the near-free no-op span path
    observability: Optional[ObservabilityConfig] = None
    # NEW (TPU): tiered parameter/optimizer residency manager — one
    # plan for where every leaf lives across HBM / host RAM / disk
    # (runtime/tiering/, docs/offload.md). Supersedes the per-device
    # offload_optimizer/offload_param blocks when enabled.
    tiering: Optional[TieringConfig] = None

    # free-form blocks consumed by their subsystems
    sparse_attention: Optional[Dict[str, Any]] = None
    compression_training: Optional[Dict[str, Any]] = None
    quantize_training: Optional[Dict[str, Any]] = None  # MoQ (runtime/quantize.py)
    elasticity: Optional[Dict[str, Any]] = None
    autotuning: Optional[Dict[str, Any]] = None
    data_efficiency: Optional[Dict[str, Any]] = None
    communication_data_type: Optional[str] = None
    checkpoint: Optional[Dict[str, Any]] = None
    zero_allow_untested_optimizer: bool = True

    _raw: Dict[str, Any] = field(default_factory=dict, repr=False)

    _SUBCONFIGS = {
        "optimizer": OptimizerConfig,
        "scheduler": SchedulerConfig,
        "fp16": FP16Config,
        "bf16": BF16Config,
        "zero_optimization": ZeroConfig,
        "activation_checkpointing": ActivationCheckpointingConfig,
        "data_types": DataTypesConfig,
        "tensorboard": TensorBoardConfig,
        "wandb": WandbConfig,
        "csv_monitor": CSVConfig,
        "flops_profiler": FlopsProfilerConfig,
        "comms_logger": CommsLoggerConfig,
        "curriculum_learning": CurriculumConfig,
        "progressive_layer_drop": ProgressiveLayerDropConfig,
        "eigenvalue": EigenvalueConfig,
        "aio": AIOConfig,
        "mesh": MeshConfig,
        "pipeline": PipelineConfig,
        "serving": ServingConfig,
        "resilience": ResilienceConfig,
        "observability": ObservabilityConfig,
        "tiering": TieringConfig,
    }

    @classmethod
    def from_dict(cls, d: dict, dp_world_size: Optional[int] = None) -> "DeepSpeedConfig":
        if d is None:
            d = {}
        d = dict(d)
        kwargs: Dict[str, Any] = {"_raw": dict(d)}
        field_names = {f.name for f in cls.__dataclass_fields__.values()}
        for k, v in d.items():
            if k in cls._SUBCONFIGS:
                if not isinstance(v, dict):
                    raise DeepSpeedConfigError(
                        f"Config section '{k}' must be a dict (e.g. {{\"enabled\": true}}), "
                        f"got {type(v).__name__}: {v!r}")
                kwargs[k] = dict_to_dataclass(cls._SUBCONFIGS[k], v, k)
            elif k in field_names:
                kwargs[k] = v
            else:
                logger.warning(f"Unknown top-level config key '{k}' ignored")
        cfg = cls(**kwargs)
        cfg.resolve_batch_sizes(dp_world_size)
        cfg.validate()
        return cfg

    @classmethod
    def from_file(cls, path: str, dp_world_size: Optional[int] = None) -> "DeepSpeedConfig":
        with open(path) as f:
            return cls.from_dict(json.load(f), dp_world_size)

    def resolve_batch_sizes(self, dp_world_size: Optional[int]):
        """Reference batch arithmetic (runtime/config.py _configure_train_batch_size):
        any two of {train_batch, micro_batch, gas} determine the third given
        dp_world_size; lone values fill with 1s."""
        if dp_world_size is None:
            return
        tb, mb, gas = (self.train_batch_size, self.train_micro_batch_size_per_gpu,
                       self.gradient_accumulation_steps)
        if tb is not None and mb is not None and gas is None:
            gas = tb // (mb * dp_world_size)
        elif tb is not None and mb is None and gas is not None:
            mb = tb // (gas * dp_world_size)
        elif tb is None and mb is not None and gas is not None:
            tb = mb * gas * dp_world_size
        elif tb is not None and mb is None and gas is None:
            gas = 1
            mb = tb // dp_world_size
        elif tb is None and mb is not None and gas is None:
            gas = 1
            tb = mb * dp_world_size
        elif tb is None and mb is None and gas is not None:
            mb = 1
            tb = gas * dp_world_size
        elif tb is None and mb is None and gas is None:
            tb, mb, gas = dp_world_size, 1, 1
        self.train_batch_size, self.train_micro_batch_size_per_gpu, self.gradient_accumulation_steps = tb, mb, gas
        if tb != mb * gas * dp_world_size:
            raise DeepSpeedConfigError(
                f"Batch arithmetic check failed: train_batch_size={tb} != "
                f"micro_batch={mb} * gas={gas} * dp_world={dp_world_size}")

    def validate(self):
        if self.fp16.enabled and self.bf16.enabled:
            raise DeepSpeedConfigError("fp16 and bf16 cannot both be enabled")
        if self.fp16.enabled and self.data_types.resolve() != "float32":
            raise DeepSpeedConfigError(
                "data_types.grad_accum_dtype=bf16 is incompatible with fp16 "
                "loss scaling (unscale needs fp32 headroom)")
        if self.gradient_clipping < 0:
            raise DeepSpeedConfigError("gradient_clipping must be >= 0")
        if (not isinstance(self.validate_sharding_extra_axes, (list, tuple))
                or not all(isinstance(a, str) and a
                           for a in self.validate_sharding_extra_axes)):
            raise DeepSpeedConfigError(
                "validate_sharding_extra_axes must be a list of non-empty "
                f"axis-name strings, got {self.validate_sharding_extra_axes!r}")
        if self.zero_optimization.stage > 0 and not (self.fp16.enabled or self.bf16.enabled):
            logger.info("ZeRO enabled with fp32 training (no fp16/bf16 block)")
        if self.tiering is not None and self.tiering.enabled:
            zero = self.zero_optimization
            if zero.offload_optimizer_device in ("cpu", "nvme"):
                raise DeepSpeedConfigError(
                    "tiering and zero_optimization.offload_optimizer both "
                    "set: the residency manager owns optimizer-state "
                    "placement — remove the offload_optimizer block (its "
                    "capability is the tiering plan's host/disk tiers)")
            if zero.offload_param_device in ("cpu", "nvme"):
                raise DeepSpeedConfigError(
                    "tiering and zero_optimization.offload_param both set: "
                    "the residency manager owns parameter placement — "
                    "remove the offload_param block "
                    "(tiering.offload_params covers it)")
        if self.serving is not None:
            # fail at config parse, not at ServingEngine construction —
            # the paging sub-block's page/chunk arithmetic in particular
            # (page_len | cache_len, chunk alignment) is easy to get wrong
            try:
                self.serving.validate()
            except ValueError as e:
                raise DeepSpeedConfigError(f"serving: {e}") from e

    def to_dict(self):
        d = dataclass_to_dict(self)
        d.pop("_raw", None)
        return d

    def print_config(self):
        logger.info(json.dumps(self.to_dict(), indent=2, default=str))
