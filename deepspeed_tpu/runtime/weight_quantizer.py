"""Checkpoint-load-time weight quantization (MoQ serving path).

Reference: deepspeed/runtime/weight_quantizer.py ``WeightQuantization`` —
grouped symmetric quantization of transformer weights while a checkpoint
is being loaded for inference, with extra grouping for MLP matrices and
per-layer scale merging (used by init_inference's ``quant`` knob and the
Megatron state-dict path).

TPU-native: tensors are jnp arrays inside pytrees/state dicts; the
quantized result is (int8 tree, fp32 group scales) and dequantization
happens inside the decode matmuls (module_inject/module_quantize.py —
weight-only int8 with the dequant fused into the gemm by XLA, the analog
of the reference's *_int8 inference gemms).
"""

from typing import Any, Dict, List, Optional

import numpy as np
import jax.numpy as jnp


class WeightQuantization:
    """Grouped symmetric weight quantizer (reference:
    weight_quantizer.py:5). ``mlp_extra_grouping`` doubles the group count
    for MLP weights (their larger dynamic range — same heuristic and
    shape-ratio detection as the reference's is_mlp/is_qkv)."""

    def __init__(self, mlp_extra_grouping: bool = True, mp_size: int = 1):
        self.mlp_extra_grouping = mlp_extra_grouping
        self.mp_size = mp_size
        self.dense_scales: List[Any] = []
        self.qkv_scales: List[Any] = []
        self.mlp4hh_scales: List[Any] = []
        self.mlph4h_scales: List[Any] = []

    # -- shape heuristics (reference :28-:34) ---------------------------
    def is_mlp(self, data, merge_count: int = 1) -> bool:
        r, c = data.shape[0], data.shape[1]
        return ((self.mp_size * r * merge_count) / c == 4
                or (self.mp_size * c * merge_count) / r == 4)

    def is_qkv(self, data) -> bool:
        r, c = data.shape[0], data.shape[1]
        return ((self.mp_size * r) / c == 3 or (self.mp_size * c) / r == 3)

    # -- core -----------------------------------------------------------
    def quantize_data(self, data, quantize_bits: int, groups: int,
                      key: Optional[str] = None):
        """Symmetric grouped quantization: flatten, split into ``groups``
        equal ranges, scale each by its absmax to the signed
        ``quantize_bits`` grid. Returns (int8 array in data's shape,
        per-group scale vector [groups])."""
        if not 1 <= quantize_bits <= 8:
            raise ValueError(
                f"quantize_bits must be in [1, 8] (int8 storage); got "
                f"{quantize_bits}")
        arr = jnp.asarray(data, jnp.float32)
        n = arr.size
        if n % groups != 0:
            groups = int(np.gcd(n, groups)) or 1
        flat = arr.reshape(groups, n // groups)
        absmax = jnp.max(jnp.abs(flat), axis=1, keepdims=True)
        qrange = float(1 << quantize_bits)
        scale = qrange / (2 * absmax + 1e-5)
        lo = -(1 << (quantize_bits - 1))
        hi = (1 << (quantize_bits - 1)) - 1
        q = jnp.clip(jnp.round(flat * scale), lo, hi).astype(jnp.int8)
        return q.reshape(arr.shape), scale.reshape(-1)

    def _bucket(self, key: str, inv_scale):
        if key and "dense_4h_to_h" in key:
            self.mlp4hh_scales.append(inv_scale)
        elif key and "dense_h_to_4h" in key:
            self.mlph4h_scales.append(inv_scale)
        elif key and "query_key_value" in key:
            self.qkv_scales.append(inv_scale)
        else:
            self.dense_scales.append(inv_scale)

    def Quantize(self, value_list, quantize_bits: int, groups: int,
                 key: str = ""):
        """Quantize a list of weight shards belonging to one logical
        parameter (reference :36). Returns the int8 shards; inverse scales
        are recorded per weight family for ``merge_scales``."""
        if self.mlp_extra_grouping and value_list and \
                value_list[0].ndim == 2 and self.is_mlp(
                    value_list[0], merge_count=len(value_list)):
            groups *= 2
        out, inv_scales = [], []
        for data in value_list:
            q, scale = self.quantize_data(data, quantize_bits, groups, key)
            out.append(q)
            inv_scales.append(1.0 / scale)
        self._bucket(key, jnp.concatenate(inv_scales))
        return out

    def merge_layer_scales(self, layer_scales):
        """Pad per-family scale vectors to a uniform width and stack
        (reference :60)."""
        mx = max(int(s.size) for s in layer_scales)
        padded = [jnp.pad(s.reshape(-1), (0, mx - s.size)) if s.size < mx
                  else s.reshape(-1) for s in layer_scales]
        return jnp.stack(padded)

    def merge_scales(self):
        """One [layers, families, width] scale tensor for the whole model
        (reference :71)."""
        per_layer = []
        for dense, qkv, m4, mh in zip(self.dense_scales, self.qkv_scales,
                                      self.mlp4hh_scales, self.mlph4h_scales):
            per_layer.append(self.merge_layer_scales([qkv, dense, mh, m4]))
        return jnp.stack(per_layer) if per_layer else jnp.zeros((0,))

    def sd_quantize(self, sd: Dict[str, Any], quantize_bits: int,
                    groups: int):
        """Quantize every 2-D attention/MLP weight of a flat state dict
        (reference: sd_quantize_megatron :106 — keyed on Megatron names;
        here any key containing the reference's weight-name markers)."""
        markers = ("attention.dense.weight", "query_key_value.weight",
                   "mlp.dense_4h_to_h.weight", "mlp.dense_h_to_4h.weight")
        out = dict(sd)
        for key, value in sd.items():
            if any(m in key for m in markers) and hasattr(value, "ndim") \
                    and value.ndim == 2:
                out[key] = self.Quantize([value], quantize_bits, groups,
                                         key=key)[0]
        return out, self.merge_scales()

    sd_quantize_megatron = sd_quantize

    def model_quantize(self, params, quantize_bits: int = 8,
                       groups: int = 0, quantize_policy=None):
        """Quantize a flax param tree for serving (reference:
        model_quantize :118 walks torch modules by policy; here the
        per-channel int8 transform shared with init_inference's
        quantize_weights path). Only int8 is supported on this path —
        other widths raise rather than silently quantizing at 8 bits;
        grouping is per output channel (groups<=0 accepts the default)."""
        if quantize_bits != 8:
            raise NotImplementedError(
                f"model_quantize supports quantize_bits=8 only (got "
                f"{quantize_bits}); sd_quantize supports widths 1-8")
        if quantize_policy is not None:
            raise NotImplementedError(
                "quantize_policy is a torch-module concept; the param-tree "
                "path quantizes every eligible >=2D weight")
        if groups > 0:
            from ..utils.logging import logger
            logger.warning("model_quantize grouping is per output channel; "
                           "the groups=%d knob is ignored", groups)
        from ..module_inject.module_quantize import quantize_param_tree
        return quantize_param_tree(params)
