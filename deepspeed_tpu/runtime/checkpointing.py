"""Engine checkpoint save/load.

Reference: engine.save_checkpoint (runtime/engine.py:2815) writes per-rank
shard files + a ``latest`` tag; load_checkpoint (:2472) handles world-size
changes. TPU-native: orbax sharded checkpoints — every host writes its
shards of the global arrays, and restore *reshards on load* to whatever
mesh/stage the new run uses (the capability the reference implements by
hand in deepspeed/checkpoint/ reshaping tools + universal checkpoints).
"""

import json
import os
from typing import Optional

import numpy as np
import jax
import jax.numpy as jnp

from ..utils.logging import logger, log_dist

LATEST_FILE = "latest"


def _checkpointer():
    import orbax.checkpoint as ocp
    return ocp.PyTreeCheckpointer()


def _item_metadata(ckptr, path):
    """Checkpoint structure metadata across orbax API drift: newer orbax
    wraps the tree in an object carrying ``item_metadata``, older returns
    the tree directly."""
    meta = ckptr.metadata(path)
    return getattr(meta, "item_metadata", meta)


def _partial_restore(ckptr, path, template):
    """PyTreeRestore of ``template``, tolerating extra on-disk keys.
    Newer orbax spells that ``partial_restore=True``; older versions
    (<=0.7) get the same semantics from the transforms API — an empty
    transforms dict with default-to-original makes ``item`` the output
    structure and silently drops disk keys it omits."""
    import inspect
    import orbax.checkpoint as ocp
    restore_args = ocp.checkpoint_utils.construct_restore_args(template)
    if "partial_restore" in inspect.signature(
            ocp.args.PyTreeRestore.__init__).parameters:
        kw = {"partial_restore": True}
    else:
        kw = {"transforms": {}}
    return ckptr.restore(path, args=ocp.args.PyTreeRestore(
        item=template, restore_args=restore_args, **kw))


def _async_checkpointer(engine):
    """One AsyncCheckpointer per engine (it owns a worker thread): the
    initial device->host snapshot is synchronous, the file writes run in
    the background — training steps (which DONATE params) are safe to
    continue immediately."""
    import orbax.checkpoint as ocp
    if getattr(engine, "_async_ckptr", None) is None:
        engine._async_ckptr = ocp.AsyncCheckpointer(
            ocp.PyTreeCheckpointHandler())
    return engine._async_ckptr


def finalize_pending_checkpoint(engine):
    """Block until the in-flight async save (if any) lands, then publish
    its ``latest`` tag. The tag is only ever written AFTER the state is
    durable, so a crash mid-write can never leave ``latest`` pointing at
    a partial checkpoint."""
    pending = getattr(engine, "_pending_ckpt", None)
    if pending is None:
        return None
    # the pending record is consumed no matter what: a failed background
    # write must neither wedge future saves nor get its latest tag
    # published on a retry (the partial-checkpoint corruption this
    # protocol exists to prevent)
    engine._pending_ckpt = None
    engine._async_ckptr.wait_until_finished()
    save_dir, tag, save_latest = pending
    if save_latest and jax.process_index() == 0:
        with open(os.path.join(save_dir, LATEST_FILE), "w") as f:
            f.write(str(tag))
    log_dist(f"async checkpoint {tag} finalized", ranks=[0])
    return os.path.join(save_dir, str(tag))


def close_async_checkpointer(engine):
    """Release the per-engine AsyncCheckpointer's worker resources after
    joining any pending save (call at engine teardown)."""
    try:
        finalize_pending_checkpoint(engine)
    finally:
        ckptr = getattr(engine, "_async_ckptr", None)
        if ckptr is not None:
            engine._async_ckptr = None
            ckptr.close()


def save_engine_checkpoint(engine, save_dir, tag=None, client_state=None,
                           save_latest=True, async_save=False):
    # at most one async save in flight: joining the previous one first
    # also publishes its latest tag
    finalize_pending_checkpoint(engine)
    # monitor events are buffered on-device between flush cadences; a
    # checkpoint is a durability point, so drain them to the writers
    if hasattr(engine, "flush_monitor"):
        engine.flush_monitor()
    tag = tag or f"global_step{engine.global_steps}"
    path = os.path.abspath(os.path.join(save_dir, str(tag)))
    os.makedirs(path, exist_ok=True)

    state = {"params": engine.params}
    if getattr(engine, "native_offload", None) is None:
        state["optimizer_state"] = engine.optimizer_state
    if engine.fp16_enabled and engine.loss_scale_state is not None:
        state["loss_scale"] = dict(engine.loss_scale_state._asdict())
    if async_save:
        _async_checkpointer(engine).save(
            os.path.join(path, "state"), state, force=True)
        engine._pending_ckpt = (os.path.abspath(save_dir), str(tag),
                                save_latest)
        save_latest = False   # published by finalize, post-durability
    else:
        ckptr = _checkpointer()
        ckptr.save(os.path.join(path, "state"), state, force=True)

    if getattr(engine, "native_offload", None) is not None:
        # per-process host-state shard files (reference: the per-rank
        # *_zero_pp_rank_N_optim_states.pt files, engine.py:2402)
        np.savez(os.path.join(
            path, f"native_opt_proc{jax.process_index()}.npz"),
            **engine.native_offload.state_dict())

    meta = {
        "global_steps": engine.global_steps,
        "global_samples": engine.global_samples,
        "skipped_steps": engine.skipped_steps,
        "zero_stage": engine.zero_stage,
        "dp_world_size": engine.dp_world_size,
        "client_state": client_state or {},
    }
    # logical axis names per param, so offline tools (checkpoint/reshape.py)
    # can validate a target topology with the SAME sharding rules the
    # engine applies at restore time, not a shape heuristic
    names = getattr(engine, "_param_names", None)
    if names is not None:
        flat, _ = jax.tree.flatten_with_path(
            names, is_leaf=lambda x: x is None or isinstance(x, tuple))
        meta["param_logical_names"] = {
            jax.tree_util.keystr(p): (list(n) if n is not None else None)
            for p, n in flat}
    if jax.process_index() == 0:
        with open(os.path.join(path, "engine_meta.json"), "w") as f:
            json.dump(meta, f)
        if save_latest:
            with open(os.path.join(save_dir, LATEST_FILE), "w") as f:
                f.write(str(tag))
    log_dist(f"saved checkpoint {path}", ranks=[0])
    return path


def load_module_params(load_dir, mesh=None, tag=None):
    """Restore only the model params from an engine checkpoint directory
    (reference: load_checkpoint with load_module_only=True,
    engine.py:2472) — used by the inference loader to serve weights
    trained by this framework without constructing a training engine."""
    if tag is None:
        latest = os.path.join(load_dir, LATEST_FILE)
        with open(latest) as f:
            tag = f.read().strip()
    path = os.path.join(os.path.abspath(load_dir), str(tag), "state")
    ckptr = _checkpointer()
    disk = _item_metadata(ckptr, path)
    if "params" not in disk.keys():
        raise ValueError(f"checkpoint at {path} has no 'params' subtree")
    # restore ONLY the params subtree: an Adam engine checkpoint is ~3x
    # the param bytes in optimizer moments that serving would immediately
    # discard (template from on-disk metadata; partial_restore skips the
    # rest on disk)
    template = {"params": jax.tree.map(
        lambda v: jax.ShapeDtypeStruct(v.shape, v.dtype), dict(disk["params"]))}
    restored = _partial_restore(ckptr, path, template)
    return restored["params"]


def load_engine_checkpoint(engine, load_dir, tag=None,
                           load_optimizer_states=True,
                           load_module_only=False):
    if tag is None:
        latest = os.path.join(load_dir, LATEST_FILE)
        if not os.path.exists(latest):
            logger.warning(f"no '{LATEST_FILE}' file at {load_dir}")
            return None, {}
        with open(latest) as f:
            tag = f.read().strip()
    path = os.path.abspath(os.path.join(load_dir, str(tag)))
    if not os.path.isdir(path):
        logger.warning(f"checkpoint path {path} does not exist")
        return None, {}

    import orbax.checkpoint as ocp

    # Restore directly into the engine's current shardings — loading a
    # checkpoint written at different dp/mp degrees reshards transparently
    # (reference: _get_all_zero_checkpoint_state_dicts resize rules).
    template = {
        "params": jax.tree.map(
            lambda sds, sh: jax.ShapeDtypeStruct(sds.shape, sds.dtype, sharding=sh),
            engine._param_shapes, engine.param_shardings),
    }
    if engine.fp16_enabled and engine.loss_scale_state is not None:
        template["loss_scale"] = {
            k: jax.ShapeDtypeStruct(v.shape, v.dtype)
            for k, v in engine.loss_scale_state._asdict().items()}
    native = getattr(engine, "native_offload", None)
    if load_optimizer_states and not load_module_only and native is None:
        # template from the engine's LIVE optimizer-state structure — it
        # differs by path (optax tree vs the streamed-offload {mu,nu,count}
        # dict) but always pairs leaf-for-leaf with opt_shardings
        template["optimizer_state"] = jax.tree.map(
            lambda x, sh: jax.ShapeDtypeStruct(x.shape, x.dtype, sharding=sh),
            engine.optimizer_state, engine.opt_shardings)

    ckptr = _checkpointer()
    item_path = os.path.join(path, "state")
    # orbax refuses structure mismatches in either direction, so: drop
    # template keys absent on disk (fp16<->fp32 / native<->optax
    # cross-loads — the guards below handle their absence), and use
    # partial_restore for disk keys the template omits (load_module_only,
    # load_optimizer_states=False)
    on_disk = set(_item_metadata(ckptr, item_path).keys())
    missing = sorted(set(template) - on_disk)
    if missing:
        logger.warning(f"checkpoint at {item_path} lacks {missing}; those "
                       "engine states keep their current values")
        template = {k: v for k, v in template.items() if k in on_disk}
    restored = _partial_restore(ckptr, item_path, template)

    engine.params = restored["params"]
    if load_optimizer_states and not load_module_only and "optimizer_state" in restored:
        engine.optimizer_state = restored["optimizer_state"]
    if native is not None:
        # masters must track the restored weights in EVERY load mode, else
        # the next step reverts the model to its construction-time values
        shard_file = os.path.join(
            path, f"native_opt_proc{jax.process_index()}.npz")
        will_load = (load_optimizer_states and not load_module_only
                     and os.path.exists(shard_file))
        native.reset_from_params(engine.params, skip_moments=will_load)
        if will_load:
            with np.load(shard_file) as z:
                native.load_state_dict({k: z[k] for k in z.files})
        elif load_optimizer_states and not load_module_only:
            logger.warning(f"no native offload state at {shard_file}; "
                           "optimizer moments reset (world-size change?)")
    if engine.fp16_enabled and "loss_scale" in restored:
        from .fp16.loss_scaler import LossScaleState
        ls = restored["loss_scale"]
        engine.loss_scale_state = LossScaleState(**{k: jnp.asarray(v) for k, v in ls.items()})

    meta_path = os.path.join(path, "engine_meta.json")
    client_state = {}
    if os.path.exists(meta_path):
        with open(meta_path) as f:
            meta = json.load(f)
        engine.global_steps = meta.get("global_steps", 0)
        engine.global_samples = meta.get("global_samples", 0)
        engine.skipped_steps = meta.get("skipped_steps", 0)
        client_state = meta.get("client_state", {})
    log_dist(f"loaded checkpoint {path} (step {engine.global_steps})", ranks=[0])
    return path, client_state
