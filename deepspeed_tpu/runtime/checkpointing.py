"""Engine checkpoint save/load.

Reference: engine.save_checkpoint (runtime/engine.py:2815) writes per-rank
shard files + a ``latest`` tag; load_checkpoint (:2472) handles world-size
changes. TPU-native: orbax sharded checkpoints — every host writes its
shards of the global arrays, and restore *reshards on load* to whatever
mesh/stage the new run uses (the capability the reference implements by
hand in deepspeed/checkpoint/ reshaping tools + universal checkpoints).
"""

import atexit
import json
import os
import weakref
from typing import Optional

import numpy as np
import jax
import jax.numpy as jnp

from ..utils.logging import logger, log_dist
from .resilience.manifest import (LATEST_FILE, CheckpointCorruptionError,
                                  gc_checkpoints, resolve_verified_tag,
                                  write_latest, write_manifest)

# Engines with an async save in flight: a clean interpreter exit must not
# drop a durable save just because nobody called wait_checkpoint() —
# finalize them best-effort at exit (weak refs: registration must never
# extend engine lifetime).
_PENDING_ENGINES = weakref.WeakSet()


def _finalize_all_pending():
    """atexit hook: join and publish every in-flight async save."""
    for engine in list(_PENDING_ENGINES):
        try:
            finalize_pending_checkpoint(engine)
        except Exception as e:  # ds-tpu: lint-ok[PY001] — atexit must never
            # raise; a failed finalize is logged, the tag stays unpublished
            # (exactly the partial-checkpoint protection this protocol gives)
            logger.warning(f"atexit checkpoint finalize failed: {e}")


atexit.register(_finalize_all_pending)


def _integrity_config(engine):
    """The engine's resilience.integrity block, defaulted when the config
    carries no resilience section (manifests are not opt-in)."""
    res = getattr(getattr(engine, "config", None), "resilience", None)
    if res is not None:
        return res.integrity
    from .resilience.config import IntegrityConfig
    return IntegrityConfig()


def _checkpointer():
    import orbax.checkpoint as ocp
    return ocp.PyTreeCheckpointer()


def _item_metadata(ckptr, path):
    """Checkpoint structure metadata across orbax API drift: newer orbax
    wraps the tree in an object carrying ``item_metadata``, older returns
    the tree directly."""
    meta = ckptr.metadata(path)
    return getattr(meta, "item_metadata", meta)


def _partial_restore(ckptr, path, template):
    """PyTreeRestore of ``template``, tolerating extra on-disk keys.
    Newer orbax spells that ``partial_restore=True``; older versions
    (<=0.7) get the same semantics from the transforms API — an empty
    transforms dict with default-to-original makes ``item`` the output
    structure and silently drops disk keys it omits."""
    import inspect
    import orbax.checkpoint as ocp
    restore_args = ocp.checkpoint_utils.construct_restore_args(template)
    if "partial_restore" in inspect.signature(
            ocp.args.PyTreeRestore.__init__).parameters:
        kw = {"partial_restore": True}
    else:
        kw = {"transforms": {}}
    return ckptr.restore(path, args=ocp.args.PyTreeRestore(
        item=template, restore_args=restore_args, **kw))


def _async_checkpointer(engine):
    """One AsyncCheckpointer per engine (it owns a worker thread): the
    initial device->host snapshot is synchronous, the file writes run in
    the background — training steps (which DONATE params) are safe to
    continue immediately."""
    import orbax.checkpoint as ocp
    if getattr(engine, "_async_ckptr", None) is None:
        engine._async_ckptr = ocp.AsyncCheckpointer(
            ocp.PyTreeCheckpointHandler())
    return engine._async_ckptr


def finalize_pending_checkpoint(engine):
    """Block until the in-flight async save (if any) lands, then publish
    its ``latest`` tag. The tag is only ever written AFTER the state is
    durable, so a crash mid-write can never leave ``latest`` pointing at
    a partial checkpoint."""
    pending = getattr(engine, "_pending_ckpt", None)
    if pending is None:
        return None
    # the pending record is consumed no matter what: a failed background
    # write must neither wedge future saves nor get its latest tag
    # published on a retry (the partial-checkpoint corruption this
    # protocol exists to prevent)
    engine._pending_ckpt = None
    engine._async_ckptr.wait_until_finished()
    save_dir, tag, save_latest, step = pending
    path = os.path.join(save_dir, str(tag))
    _publish_checkpoint(engine, save_dir, tag, save_latest, step)
    log_dist(f"async checkpoint {tag} finalized", ranks=[0])
    return path


def _publish_checkpoint(engine, save_dir, tag, save_latest, step):
    """Post-durability publication, shared by the sync save and the async
    finalize: integrity manifest, atomic ``latest`` tag, retention GC,
    and the torn-write fault-injection hook (tests corrupt a checkpoint
    the way a crash would — AFTER it was fully published).

    ``step`` is the step the checkpoint was TAKEN at, carried through
    the pending record — at async-finalize time ``engine.global_steps``
    has moved on, and a wrong manifest step would mis-order the
    verified-tag chain and the retention GC."""
    path = os.path.join(save_dir, str(tag))
    icfg = _integrity_config(engine)
    if jax.process_count() > 1:
        # every process's shard files (native npz, orbax per-process dirs)
        # must be durable before process 0 walks and hashes the tag dir
        from jax.experimental import multihost_utils
        multihost_utils.sync_global_devices(f"ckpt_publish_{tag}")
    if jax.process_index() == 0:
        if icfg.enabled:
            write_manifest(path, step=step, tag=str(tag),
                           algorithm=icfg.algorithm)
        if save_latest:
            # tmp + fsync + os.replace + dir fsync: a crash mid-write can
            # never leave a truncated `latest` that breaks every load
            write_latest(save_dir, str(tag))
        if icfg.keep_last_n > 0:
            gc_checkpoints(save_dir, icfg.keep_last_n, protect=(str(tag),))
        from .resilience.faults import active_injector
        inj = active_injector()
        if inj is not None:
            # process 0 only: one modeled torn write, one save ordinal
            inj.on_checkpoint_saved(path)
    engine._last_save_dir = os.path.abspath(save_dir)


def close_async_checkpointer(engine):
    """Release the per-engine AsyncCheckpointer's worker resources after
    joining any pending save (call at engine teardown)."""
    try:
        finalize_pending_checkpoint(engine)
    finally:
        ckptr = getattr(engine, "_async_ckptr", None)
        if ckptr is not None:
            engine._async_ckptr = None
            ckptr.close()


def save_engine_checkpoint(engine, save_dir, tag=None, client_state=None,
                           save_latest=True, async_save=False):
    # at most one async save in flight: joining the previous one first
    # also publishes its latest tag
    finalize_pending_checkpoint(engine)
    # monitor events are buffered on-device between flush cadences; a
    # checkpoint is a durability point, so drain them to the writers
    if hasattr(engine, "flush_monitor"):
        engine.flush_monitor()
    tag = tag or f"global_step{engine.global_steps}"
    path = os.path.abspath(os.path.join(save_dir, str(tag)))
    os.makedirs(path, exist_ok=True)

    state = {"params": engine.params}
    if getattr(engine, "native_offload", None) is None:
        state["optimizer_state"] = engine.optimizer_state
    if engine.fp16_enabled and engine.loss_scale_state is not None:
        state["loss_scale"] = dict(engine.loss_scale_state._asdict())
    if async_save:
        _async_checkpointer(engine).save(
            os.path.join(path, "state"), state, force=True)
        engine._pending_ckpt = (os.path.abspath(save_dir), str(tag),
                                save_latest, engine.global_steps)
        # publication (manifest + latest + GC) happens in finalize, after
        # durability; the atexit hook guarantees a clean interpreter exit
        # never drops the pending save
        _PENDING_ENGINES.add(engine)
    else:
        ckptr = _checkpointer()
        ckptr.save(os.path.join(path, "state"), state, force=True)

    if getattr(engine, "native_offload", None) is not None:
        # per-process host-state shard files (reference: the per-rank
        # *_zero_pp_rank_N_optim_states.pt files, engine.py:2402)
        np.savez(os.path.join(
            path, f"native_opt_proc{jax.process_index()}.npz"),
            **engine.native_offload.state_dict())

    meta = {
        "global_steps": engine.global_steps,
        "global_samples": engine.global_samples,
        "skipped_steps": engine.skipped_steps,
        "zero_stage": engine.zero_stage,
        "dp_world_size": engine.dp_world_size,
        "client_state": client_state or {},
    }
    # logical axis names per param, so offline tools (checkpoint/reshape.py)
    # can validate a target topology with the SAME sharding rules the
    # engine applies at restore time, not a shape heuristic
    names = getattr(engine, "_param_names", None)
    if names is not None:
        flat, _ = jax.tree.flatten_with_path(
            names, is_leaf=lambda x: x is None or isinstance(x, tuple))
        meta["param_logical_names"] = {
            jax.tree_util.keystr(p): (list(n) if n is not None else None)
            for p, n in flat}
    if jax.process_index() == 0:
        with open(os.path.join(path, "engine_meta.json"), "w") as f:
            json.dump(meta, f)
    if not async_save:
        _publish_checkpoint(engine, os.path.abspath(save_dir), str(tag),
                            save_latest, engine.global_steps)
    log_dist(f"saved checkpoint {path}", ranks=[0])
    return path


def load_module_params(load_dir, mesh=None, tag=None):
    """Restore only the model params from an engine checkpoint directory
    (reference: load_checkpoint with load_module_only=True,
    engine.py:2472) — used by the inference loader to serve weights
    trained by this framework without constructing a training engine."""
    explicit_tag = tag is not None
    if explicit_tag and not os.path.isdir(os.path.join(load_dir, str(tag))):
        # a plain wrong path is a caller mistake, not corruption — don't
        # mis-diagnose it as an integrity failure
        raise FileNotFoundError(
            f"checkpoint tag directory {os.path.join(load_dir, str(tag))} "
            "does not exist")
    if tag is None:
        latest = os.path.join(load_dir, LATEST_FILE)
        with open(latest) as f:
            tag = f.read().strip()
    # integrity gate (default policy — no engine config on this path):
    # serve only checkpoints whose manifest verifies. latest-driven loads
    # fall back along the retained-tag chain like the engine loader; an
    # explicitly named tag that fails raises (never serve different
    # weights than the caller asked for). Process 0 decides, peers take
    # the broadcast — same skewed-shared-FS discipline as the engine load.
    if jax.process_index() == 0:
        chosen, errors = resolve_verified_tag(load_dir, prefer_tag=str(tag))
        if chosen != str(tag) and explicit_tag:
            raise CheckpointCorruptionError(
                f"explicitly requested checkpoint {tag!r} under {load_dir} "
                f"failed integrity verification: "
                f"{_corruption_detail(errors)}")
        if chosen is None:
            raise CheckpointCorruptionError(
                f"no verified-good checkpoint under {load_dir} (latest "
                f"pointed at {tag!r}): {_corruption_detail(errors)}")
        if chosen != str(tag):
            logger.warning(
                f"checkpoint {tag!r} under {load_dir} failed integrity "
                f"verification ({_corruption_detail(errors)}); serving "
                f"newest verified-good tag {chosen!r}")
            tag = chosen
    if jax.process_count() > 1:
        tag = _broadcast_tag(str(tag))
    path = os.path.join(os.path.abspath(load_dir), str(tag), "state")
    ckptr = _checkpointer()
    disk = _item_metadata(ckptr, path)
    if "params" not in disk.keys():
        raise ValueError(f"checkpoint at {path} has no 'params' subtree")
    # restore ONLY the params subtree: an Adam engine checkpoint is ~3x
    # the param bytes in optimizer moments that serving would immediately
    # discard (template from on-disk metadata; partial_restore skips the
    # rest on disk)
    template = {"params": jax.tree.map(
        lambda v: jax.ShapeDtypeStruct(v.shape, v.dtype), dict(disk["params"]))}
    restored = _partial_restore(ckptr, path, template)
    return restored["params"]


def _corruption_detail(errors):
    return " | ".join(f"{t}: {'; '.join(e)}" for t, e in errors.items()) \
        or "no checkpoint tags found"


def _broadcast_tag(tag: str) -> str:
    """Process 0's tag decision, agreed across every process (fixed-size
    uint8 buffer; empty string = abort the load)."""
    from jax.experimental import multihost_utils
    buf = np.zeros(512, np.uint8)
    if jax.process_index() == 0:
        data = tag.encode()
        if len(data) > buf.size:
            raise ValueError(f"checkpoint tag too long to broadcast: {tag!r}")
        buf[:len(data)] = np.frombuffer(data, np.uint8)
    out = np.asarray(multihost_utils.broadcast_one_to_all(buf))
    return out.tobytes().rstrip(b"\x00").decode()


def load_engine_checkpoint(engine, load_dir, tag=None,
                           load_optimizer_states=True,
                           load_module_only=False):
    icfg = _integrity_config(engine)
    explicit_tag = tag is not None
    if tag is None:
        latest = os.path.join(load_dir, LATEST_FILE)
        if not os.path.exists(latest):
            logger.warning(f"no '{LATEST_FILE}' file at {load_dir}")
            return None, {}
        with open(latest) as f:
            tag = f.read().strip()
    # The verification/fallback DECISION is made by process 0 alone and
    # broadcast: shared-filesystem visibility can differ per host, and two
    # processes independently walking the tag chain could restore
    # DIFFERENT steps. (A process-0 raise below aborts the whole job —
    # peers block in the broadcast until the launcher reaps them, the
    # standard SPMD failure mode.)
    abort_load = False
    if (icfg.enabled and icfg.verify_on_load
            and jax.process_index() == 0):
        chosen, errors = resolve_verified_tag(load_dir, prefer_tag=str(tag))
        if chosen != str(tag):
            detail = _corruption_detail(errors)
            if explicit_tag and not os.path.isdir(
                    os.path.join(load_dir, str(tag))):
                # an explicitly named tag that simply is not there is a
                # caller mistake, not corruption — keep the legacy contract
                logger.warning(f"checkpoint path "
                               f"{os.path.join(load_dir, str(tag))} does "
                               "not exist")
                abort_load = True
            elif explicit_tag:
                # silently restoring a DIFFERENT step than the one the
                # caller named would corrupt their eval/resume — fallback
                # is a latest-driven policy only
                raise CheckpointCorruptionError(
                    f"explicitly requested checkpoint {tag!r} under "
                    f"{load_dir} failed integrity verification: {detail}")
            elif chosen is None:
                raise CheckpointCorruptionError(
                    f"no verified-good checkpoint under {load_dir} "
                    f"(latest pointed at {tag!r}): {detail}")
            elif not icfg.fallback_on_corruption:
                raise CheckpointCorruptionError(
                    f"checkpoint {tag!r} under {load_dir} failed integrity "
                    f"verification ({detail}) and "
                    "resilience.integrity.fallback_on_corruption is false")
            else:
                logger.warning(
                    f"checkpoint {tag!r} under {load_dir} failed integrity "
                    f"verification ({detail}); falling back to newest "
                    f"verified-good tag {chosen!r}")
                # repair the torn/stale `latest` so every later load goes
                # straight to the verified-good tag
                write_latest(load_dir, chosen)
                tag = chosen
    if jax.process_count() > 1:
        tag = _broadcast_tag("" if abort_load else str(tag))
        if not tag:
            return None, {}
    elif abort_load:
        return None, {}
    path = os.path.abspath(os.path.join(load_dir, str(tag)))
    if not os.path.isdir(path):
        logger.warning(f"checkpoint path {path} does not exist")
        return None, {}

    import orbax.checkpoint as ocp

    # Restore directly into the engine's current shardings — loading a
    # checkpoint written at different dp/mp degrees reshards transparently
    # (reference: _get_all_zero_checkpoint_state_dicts resize rules).
    template = {
        "params": jax.tree.map(
            lambda sds, sh: jax.ShapeDtypeStruct(sds.shape, sds.dtype, sharding=sh),
            engine._param_shapes, engine.param_shardings),
    }
    if engine.fp16_enabled and engine.loss_scale_state is not None:
        template["loss_scale"] = {
            k: jax.ShapeDtypeStruct(v.shape, v.dtype)
            for k, v in engine.loss_scale_state._asdict().items()}
    native = getattr(engine, "native_offload", None)
    if load_optimizer_states and not load_module_only and native is None:
        # template from the engine's LIVE optimizer-state structure — it
        # differs by path (optax tree vs the streamed-offload {mu,nu,count}
        # dict) but always pairs leaf-for-leaf with opt_shardings
        template["optimizer_state"] = jax.tree.map(
            lambda x, sh: jax.ShapeDtypeStruct(x.shape, x.dtype, sharding=sh),
            engine.optimizer_state, engine.opt_shardings)

    ckptr = _checkpointer()
    item_path = os.path.join(path, "state")
    # orbax refuses structure mismatches in either direction, so: drop
    # template keys absent on disk (fp16<->fp32 / native<->optax
    # cross-loads — the guards below handle their absence), and use
    # partial_restore for disk keys the template omits (load_module_only,
    # load_optimizer_states=False)
    on_disk = set(_item_metadata(ckptr, item_path).keys())
    missing = sorted(set(template) - on_disk)
    if missing:
        logger.warning(f"checkpoint at {item_path} lacks {missing}; those "
                       "engine states keep their current values")
        template = {k: v for k, v in template.items() if k in on_disk}
    restored = _partial_restore(ckptr, item_path, template)

    engine.params = restored["params"]
    if load_optimizer_states and not load_module_only and "optimizer_state" in restored:
        engine.optimizer_state = restored["optimizer_state"]
    if native is not None:
        # masters must track the restored weights in EVERY load mode, else
        # the next step reverts the model to its construction-time values
        shard_file = os.path.join(
            path, f"native_opt_proc{jax.process_index()}.npz")
        will_load = (load_optimizer_states and not load_module_only
                     and os.path.exists(shard_file))
        native.reset_from_params(engine.params, skip_moments=will_load)
        if will_load:
            with np.load(shard_file) as z:
                native.load_state_dict({k: z[k] for k in z.files})
        elif load_optimizer_states and not load_module_only:
            logger.warning(f"no native offload state at {shard_file}; "
                           "optimizer moments reset (world-size change?)")
    if engine.fp16_enabled and "loss_scale" in restored:
        from .fp16.loss_scaler import LossScaleState
        ls = restored["loss_scale"]
        engine.loss_scale_state = LossScaleState(**{k: jnp.asarray(v) for k, v in ls.items()})

    meta_path = os.path.join(path, "engine_meta.json")
    client_state = {}
    if os.path.exists(meta_path):
        with open(meta_path) as f:
            meta = json.load(f)
        engine.global_steps = meta.get("global_steps", 0)
        engine.global_samples = meta.get("global_samples", 0)
        engine.skipped_steps = meta.get("skipped_steps", 0)
        client_state = meta.get("client_state", {})
    log_dist(f"loaded checkpoint {path} (step {engine.global_steps})", ranks=[0])
    return path, client_state
