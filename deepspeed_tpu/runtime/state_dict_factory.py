"""Versioned Megatron checkpoint loading with TP merge/split.

Reference: deepspeed/runtime/state_dict_factory.py:17 SDLoaderFactory /
:197 MegatronSDLoader — serving a Megatron-trained GPT at a different
model-parallel degree than it was saved at requires qkv-aware merging
(ckpt_mp > target_mp) or splitting (ckpt_mp < target_mp) of the
column/row-parallel weights.

TPU-native twist: the engine/serving stack shards by NamedSharding
placement, so the only operation it ever needs is the MERGE to a full
state dict (placement re-splits for free at any degree). ``split`` is
still provided for API parity and for writing Megatron-compatible
sharded checkpoints back out.

Category rules (substring-matched, like the reference merge loop):
- column-parallel (cat dim 0 of the [out, in] torch layout):
  ``mlp.dense_h_to_4h``, ``word_embeddings``, ``lm_head``
- row-parallel (cat dim 1): ``attention.dense.weight``,
  ``mlp.dense_4h_to_h.weight`` (their biases are replicated)
- qkv (version-aware): ``attention.query_key_value`` — ckpt version 1.0
  stores each rank's shard as [q_r; k_r; v_r], so a naive concat
  interleaves wrongly; the merge regroups per category
  (reference merge_query_key_value :252, split_query_key_value :320).
  Version >= 2.0 is plain dim-0 concat.
- everything else is replicated: rank-0 wins.
"""

import json
import os
from typing import Dict, List, Optional

import numpy as np

from ..utils.logging import logger

COL_PARALLEL = ("mlp.dense_h_to_4h", "word_embeddings.weight", "lm_head")
ROW_PARALLEL = ("attention.dense.weight", "mlp.dense_4h_to_h.weight",
                "self_attention.dense.weight")
QKV = ("attention.query_key_value", "self_attention.query_key_value")


class SDLoaderFactory:
    @staticmethod
    def get_sd_loader_json(json_file, checkpoint_engine=None):
        """Resolve a ds_inference checkpoint descriptor (reference:
        SDLoaderFactory.get_sd_loader_json): a path to a json file or an
        already-parsed dict with {type, checkpoints, version, mp_size}."""
        if isinstance(json_file, str):
            with open(json_file) as f:
                data = json.load(f)
            base = os.path.dirname(os.path.abspath(json_file))
        else:
            data = dict(json_file)
            base = data.get("base_dir", "")
        ckpt_list = [os.path.join(base, c) if base and not os.path.isabs(c)
                     else c for c in data["checkpoints"]]
        return SDLoaderFactory.get_sd_loader(
            ckpt_list, sd_type=data.get("type", "Megatron"),
            version=data.get("version", 1.0))

    @staticmethod
    def get_sd_loader(ckpt_list: List[str], sd_type: str = "Megatron",
                      version=1.0):
        if sd_type.lower() != "megatron":
            raise ValueError(f"unsupported checkpoint type {sd_type!r} "
                             "(only 'Megatron' has a versioned loader; HF "
                             "checkpoints load via module_inject)")
        return MegatronSDLoader(ckpt_list, version=version)


class MegatronSDLoader:
    """Merge/split Megatron mp-sharded state dicts (numpy level)."""

    def __init__(self, ckpt_list, version=1.0):
        self.ckpt_list = list(ckpt_list)
        self.version = float(version)

    # -- loading -------------------------------------------------------
    def _load_shard(self, path_or_sd) -> Dict[str, np.ndarray]:
        if isinstance(path_or_sd, dict):
            return path_or_sd
        from ..module_inject.load_checkpoint import _load_torch_file
        return _load_torch_file(path_or_sd)

    def load(self, mp_world_size: int = 1, mp_rank: int = 0):
        """Reference MegatronSDLoader.load: return the state dict for
        (mp_world_size, mp_rank), merging or splitting as needed."""
        n = len(self.ckpt_list)
        shards = [self._load_shard(c) for c in self.ckpt_list]
        if n == mp_world_size:
            return shards[mp_rank]
        full = self.merge_state_dict(shards)
        if mp_world_size == 1:
            return full
        return self.split_state_dict(full, mp_world_size, mp_rank)

    # -- qkv handling (version-aware) ---------------------------------
    def merge_query_key_value(self, parts: List[np.ndarray]) -> np.ndarray:
        if self.version >= 2.0:
            return np.concatenate(parts, axis=0)
        # v1.0: each rank holds [q_r; k_r; v_r] stacked on dim 0 — regroup
        cats = [[], [], []]
        for p in parts:
            if p.shape[0] % 3 != 0:
                raise ValueError(f"qkv dim {p.shape[0]} not divisible by 3")
            for c, chunk in enumerate(np.split(p, 3, axis=0)):
                cats[c].append(chunk)
        return np.concatenate([np.concatenate(c, axis=0) for c in cats],
                              axis=0)

    def split_query_key_value(self, full: np.ndarray, n: int,
                              rank: int) -> np.ndarray:
        if self.version >= 2.0:
            return np.split(full, n, axis=0)[rank]
        q, k, v = np.split(full, 3, axis=0)
        return np.concatenate([np.split(t, n, axis=0)[rank]
                               for t in (q, k, v)], axis=0)

    # -- merge / split ------------------------------------------------
    def merge_state_dict(self, shards: List[Dict[str, np.ndarray]]):
        full = {}
        for key in shards[0]:
            parts = [np.asarray(s[key]) for s in shards]
            if any(t in key for t in QKV):
                full[key] = self.merge_query_key_value(parts)
            elif any(t in key for t in ROW_PARALLEL):
                full[key] = np.concatenate(parts, axis=1)
            elif any(t in key for t in COL_PARALLEL):
                # matches both .weight and .bias of column-parallel layers
                full[key] = np.concatenate(parts, axis=0)
            else:
                full[key] = parts[0]
        return full

    def split_state_dict(self, full: Dict[str, np.ndarray], n: int,
                         rank: int):
        out = {}
        for key, val in full.items():
            val = np.asarray(val)
            if any(t in key for t in QKV):
                out[key] = self.split_query_key_value(val, n, rank)
            elif any(t in key for t in ROW_PARALLEL):
                out[key] = np.split(val, n, axis=1)[rank]
            elif any(t in key for t in COL_PARALLEL):
                out[key] = np.split(val, n, axis=0)[rank]
            else:
                out[key] = val
        return out
