"""Runtime helper utilities.

Reference: deepspeed/runtime/utils.py (1018 LoC): ``clip_grad_norm_``,
``get_global_norm``, ``get_grad_norm``, ``CheckOverflow``,
``see_memory_usage`` and partitioning helpers. The tensor-surgery helpers
(flatten/unflatten partitioning) have no TPU analog — pytrees plus the
SPMD partitioner replace them — so this module keeps the *numerical* and
*observability* surface, functionally:

- norms/clipping take and return pytrees (no in-place ``_`` mutation;
  the trailing underscore is kept on ``clip_grad_norm_`` for name parity)
- overflow checking is a jit-safe reduction over the tree (the engine's
  fp16 path uses the traced equivalent inside its step)
- ``see_memory_usage`` reads live device allocator stats plus host RSS
"""

from typing import Any, Iterable, Optional

import jax
import jax.numpy as jnp

from ..utils.logging import logger


def _leaf_sq_sum(tree):
    leaves = [l for l in jax.tree.leaves(tree) if hasattr(l, "dtype")]
    if not leaves:
        return jnp.zeros((), jnp.float32)
    return sum(jnp.sum(jnp.square(l.astype(jnp.float32))) for l in leaves)


def get_grad_norm(gradients, mpu=None) -> jnp.ndarray:
    """Global 2-norm of a gradient pytree (reference: get_grad_norm).

    Inside jit over a mesh the values are already global — no collective
    needed (XLA inserts it); ``mpu`` is accepted for signature parity."""
    return jnp.sqrt(_leaf_sq_sum(gradients))


def get_weight_norm(parameters, mpu=None) -> jnp.ndarray:
    return jnp.sqrt(_leaf_sq_sum(parameters))


def get_global_norm(norm_list: Iterable[float]) -> float:
    """sqrt of the sum of squared norms (reference: get_global_norm)."""
    total = 0.0
    for n in norm_list:
        total += float(n) ** 2
    return total ** 0.5


def clip_grad_norm_(gradients, max_norm: float, global_norm=None, mpu=None):
    """Scale ``gradients`` so their global norm is <= ``max_norm``
    (reference: clip_grad_norm_; functional — returns
    ``(clipped_gradients, total_norm)`` instead of mutating).
    """
    from ..utils.tree import clip_grads_by_global_norm
    total_norm = (get_grad_norm(gradients, mpu)
                  if global_norm is None else global_norm)
    clipped = clip_grads_by_global_norm(gradients, total_norm, max_norm)
    # the shared helper promotes bf16*fp32 -> fp32; restore input dtypes
    clipped = jax.tree.map(
        lambda c, g: c.astype(g.dtype) if hasattr(g, "dtype") else c,
        clipped, gradients)
    return clipped, total_norm


class CheckOverflow:
    """Gradient overflow detector (reference: CheckOverflow,
    runtime/utils.py). ``check(grads)`` returns a traced boolean — True
    when any grad is inf/nan; usable inside jit (the engine's loss-scaler
    cond) or eagerly."""

    def __init__(self, param_groups=None, mpu=None, zero_reduce_scatter=False,
                 deepspeed=None):
        self.mpu = mpu   # parity fields; values are global under SPMD
        self.params = param_groups

    @staticmethod
    def has_overflow_serial(grads):
        leaves = [l for l in jax.tree.leaves(grads) if hasattr(l, "dtype")]
        if not leaves:
            return jnp.asarray(False)
        flags = [jnp.logical_not(jnp.all(jnp.isfinite(
            l.astype(jnp.float32)))) for l in leaves]
        out = flags[0]
        for f in flags[1:]:
            out = jnp.logical_or(out, f)
        return out

    def check(self, param_grads=None):
        return self.has_overflow_serial(
            param_grads if param_grads is not None else self.params)

    # reference name
    has_overflow = check


def see_memory_usage(message: str, force: bool = False):
    """Log device + host memory stats (reference: see_memory_usage logs
    torch.cuda memory_allocated/max/cached + host percent)."""
    if not force:
        return
    parts = []
    for dev in jax.local_devices():
        stats = {}
        try:
            stats = dev.memory_stats() or {}
        except Exception:
            pass
        if stats:
            in_use = stats.get("bytes_in_use", 0) / 2 ** 30
            peak = stats.get("peak_bytes_in_use", 0) / 2 ** 30
            limit = stats.get("bytes_limit", 0) / 2 ** 30
            parts.append(f"{dev.device_kind or dev.platform}[{dev.id}] "
                         f"in_use {in_use:.2f}GB peak {peak:.2f}GB "
                         f"limit {limit:.2f}GB")
    try:
        import resource
        rss = resource.getrusage(resource.RUSAGE_SELF).ru_maxrss / 2 ** 20
        parts.append(f"host max RSS {rss:.2f}GB")
    except Exception:
        pass
    logger.info(f"MEM {message} | " + ("; ".join(parts) if parts
                                       else "no allocator stats"))


def call_to_str(base: str, *args, **kwargs) -> str:
    """Readable call representation (reference: call_to_str, used by the
    pipeline instruction reprs)."""
    name = f"{base}("
    if args:
        name += ", ".join(repr(a) for a in args)
        if kwargs:
            name += ", "
    if kwargs:
        name += ", ".join(f"{k}={v!r}" for k, v in kwargs.items())
    return name + ")"
