"""Power-iteration curvature estimation (MoQ's eigenvalue schedule).

Reference: runtime/eigenvalue.py:7 — estimates the max |eigenvalue| of
each layer-block's loss Hessian by power iteration over autograd
grad-of-grad products; MoQ uses the per-layer ratios to decide how fast
each layer's quantization bits shrink.

JAX edition: the Hessian-vector product is ``jvp of grad`` (forward-over-
reverse), exact and jit-compiled; one ``lax.scan``'d power loop per
requested block. Blocks are selected by a path-substring predicate over
the param tree (the reference's layer-name regex).
"""

from typing import Any, Callable, Dict, List, Optional

import numpy as np
import jax
import jax.numpy as jnp

from ..observability.programs import track_program
from ..utils.logging import logger


def _normalize(tree):
    norm = jnp.sqrt(sum(jnp.vdot(x, x).real for x in jax.tree.leaves(tree)))
    norm = jnp.maximum(norm, 1e-12)
    return jax.tree.map(lambda x: x / norm, tree), norm


class Eigenvalue:
    """reference surface: Eigenvalue(verbose, max_iter, tol, stability,
    gas_boundary_resolution, layer_name, layer_num).compute_eigenvalue"""

    def __init__(self, verbose: bool = False, max_iter: int = 100,
                 tol: float = 1e-2, stability: float = 1e-6,
                 gas_boundary_resolution: int = 1,
                 layer_name: str = "", layer_num: int = 0):
        self.verbose = verbose
        self.max_iter = max_iter
        self.tol = tol
        self.stability = stability
        self.gas_boundary_resolution = gas_boundary_resolution
        self.layer_name = layer_name
        self.layer_num = layer_num

    def _block_masks(self, params) -> List[Any]:
        """One boolean mask tree per block (params whose path contains
        layer_name; all-in-one block when no name given)."""
        flat, treedef = jax.tree.flatten_with_path(params)
        if not self.layer_name:
            return [jax.tree.unflatten(treedef, [True] * len(flat))]
        masks = []
        n = max(self.layer_num, 1)
        for i in range(n):
            # component-exact match via keystr's quoting ("['h_1']"), so
            # block 1 does not also claim layers 10..19 by substring
            key = (f"'{self.layer_name}_{i}'" if self.layer_num
                   else self.layer_name)
            masks.append(jax.tree.unflatten(
                treedef, [key in jax.tree_util.keystr(p) for p, _ in flat]))
        return masks

    def compute_eigenvalue(self, loss_fn: Callable, params,
                           rng: Optional[jax.Array] = None) -> List[float]:
        """Max |eigenvalue| per block of the Hessian of
        ``loss_fn(params)`` (reference: compute_eigenvalue; the torch
        version seeds random +-1 vectors and iterates grad-of-grad)."""
        rng = rng if rng is not None else jax.random.PRNGKey(0)
        grad_fn = jax.grad(loss_fn)

        def hvp(p, v):
            return jax.jvp(grad_fn, (p,), (v,))[1]

        results = []
        for mask in self._block_masks(params):
            def masked(tree):
                return jax.tree.map(
                    lambda x, m: x if m else jnp.zeros_like(x), tree, mask)

            flat_p, treedef = jax.tree.flatten(params)
            flat_m = jax.tree.leaves(mask)
            v = jax.tree.unflatten(treedef, [
                (jax.random.rademacher(jax.random.fold_in(rng, i), x.shape,
                                       dtype=jnp.float32).astype(x.dtype)
                 if m else jnp.zeros_like(x))
                for i, (x, m) in enumerate(zip(flat_p, flat_m))])
            v, _ = _normalize(v)

            def power_step(v):
                hv = masked(hvp(params, v))
                return _normalize(hv)
            # one program PER BLOCK by construction (each closes over its
            # own mask/hvp); re-registering the name per block keeps the
            # registry pointing at the live program
            power_step = track_program(
                "eigenvalue/power_step",
                jax.jit(power_step),  # ds-tpu: lint-ok[CC002]
                subsystem="eigenvalue")

            eig_prev = jnp.float32(0.0)
            eig = jnp.float32(0.0)
            for i in range(self.max_iter):
                v, eig = power_step(v)
                if i > 0 and abs(float(eig - eig_prev)) / max(
                        float(abs(eig)), 1e-12) < self.tol:
                    break
                eig_prev = eig
            results.append(float(eig) + self.stability)
            if self.verbose:
                logger.info(f"eigenvalue block {len(results)-1}: "
                            f"{results[-1]:.4e} ({i+1} iters)")
        return results


def post_process_eigenvalues(values: List[float]) -> List[float]:
    """Ratios in (0, 1] for MoQQuantizer.layer_ratios: the LARGEST
    curvature gets the SMALLEST ratio (longest quantization period — most
    sensitive layers quantize last, the reference's eigenvalue mode)."""
    if not values:
        return []
    mn = min(v for v in values if v > 0) if any(v > 0 for v in values) else 1.0
    return [mn / v if v > 0 else 1.0 for v in values]
