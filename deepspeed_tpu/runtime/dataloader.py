"""Data loading.

Reference: deepspeed/runtime/dataloader.py — DeepSpeedDataLoader (:33)
builds a DistributedSampler-based torch loader; RepeatingLoader (:10) wraps
any iterator to repeat forever.

TPU-native: single-host, one process feeds the whole mesh — the loader
yields *global* batches of numpy arrays and the engine shards them onto the
mesh (batch dim over the DP axes). Multi-host: each process yields its
contiguous 1/process_count slice of every global batch (the engine
assembles the global array via make_array_from_process_local_data).
"""

import numpy as np

from ..utils.logging import logger


class RepeatingLoader:
    """Wrap an iterator to restart when exhausted (reference: :10)."""

    def __init__(self, loader):
        self.loader = loader
        self.data_iter = iter(self.loader)

    def __iter__(self):
        return self

    def __next__(self):
        try:
            return next(self.data_iter)
        except StopIteration:
            self.data_iter = iter(self.loader)
            return next(self.data_iter)


class PrefetchingLoader:
    """Pipeline host-side batch preparation with device compute
    (reference: DeepSpeedDataLoader's num_local_io_workers / torch
    DataLoader workers): a daemon thread runs the wrapped iterator and
    keeps up to ``prefetch`` ready batches in a queue, so indexing /
    collation / augmentation for batch k+1 overlaps the jitted step on
    batch k. Exceptions in the worker re-raise at the consuming site."""

    _DONE = object()

    def __init__(self, loader, prefetch: int = 2):
        self.loader = loader
        self.prefetch = max(1, int(prefetch))

    def __len__(self):
        return len(self.loader)

    def __getattr__(self, name):
        # preserve the wrapped loader's surface (batch_size, dataset,
        # num_batches, ...) — initialize() returns this wrapper in place
        # of the bare DeepSpeedDataLoader
        return getattr(self.loader, name)

    def __iter__(self):
        import queue
        import threading
        q: "queue.Queue" = queue.Queue(maxsize=self.prefetch)
        stop = threading.Event()

        def work():
            try:
                for item in self.loader:
                    while not stop.is_set():
                        try:
                            q.put(item, timeout=0.1)
                            break
                        except queue.Full:
                            continue
                    if stop.is_set():
                        return
            except BaseException as e:  # forwarded to the consumer
                if not stop.is_set():
                    try:
                        q.put(e, timeout=1.0)
                    except queue.Full:
                        pass
                return
            try:
                q.put(self._DONE, timeout=1.0)
            except queue.Full:
                pass

        t = threading.Thread(target=work, daemon=True)
        t.start()
        try:
            while True:
                item = q.get()
                if item is self._DONE:
                    return
                if isinstance(item, BaseException):
                    raise item
                yield item
        finally:
            # abandoned iteration (break / generator close): release the
            # worker — it checks the event between bounded puts — so
            # neither the thread nor its queued batches outlive the loop
            stop.set()


class DeepSpeedDataLoader:
    """Batch a map-style dataset into global-batch dicts of numpy arrays.

    ``dataset`` may be: a dict of arrays (column store), a sequence of
    per-example dicts, or a torch-style Dataset with __len__/__getitem__.
    """

    def __init__(self, dataset, batch_size, collate_fn=None, shuffle=True,
                 seed=0, drop_last=True):
        self.dataset = dataset
        self.batch_size = batch_size
        self.collate_fn = collate_fn or _default_collate
        self.shuffle = shuffle
        self.seed = seed
        self.drop_last = drop_last
        self._epoch = 0
        if isinstance(dataset, dict):
            self._len = len(next(iter(dataset.values())))
        else:
            self._len = len(dataset)
        self.num_batches = (self._len // batch_size if drop_last
                            else -(-self._len // batch_size))

    def __len__(self):
        return self.num_batches

    def __iter__(self):
        order = np.arange(self._len)
        if self.shuffle:
            np.random.default_rng(self.seed + self._epoch).shuffle(order)
        self._epoch += 1
        try:
            import jax
            nproc, pid = jax.process_count(), jax.process_index()
        except Exception:
            nproc, pid = 1, 0
        share = self.batch_size // nproc
        for b in range(self.num_batches):
            idx = order[b * self.batch_size:(b + 1) * self.batch_size]
            if nproc > 1:
                idx = idx[pid * share:(pid + 1) * share]
            if isinstance(self.dataset, dict):
                yield {k: np.asarray(v)[idx] for k, v in self.dataset.items()}
            else:
                yield self.collate_fn([self.dataset[int(i)] for i in idx])


def _default_collate(examples):
    if isinstance(examples[0], dict):
        return {k: np.stack([e[k] for e in examples]) for k in examples[0]}
    if isinstance(examples[0], (tuple, list)):
        return tuple(np.stack([e[i] for e in examples])
                     for i in range(len(examples[0])))
    return np.stack(examples)
