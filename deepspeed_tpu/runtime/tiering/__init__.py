"""Tiered parameter/optimizer residency (HBM <-> host RAM <-> disk).

One manager owns where every parameter and optimizer-state leaf lives
and when it moves — the ZeRO-Infinity memory hierarchy (arXiv
2104.07857) expressed as a per-leaf ``ResidencyPlan`` plus a prefetch
schedule whose overlap is *measured* by the goodput ledger's
``data_stall`` fraction, not claimed. See docs/offload.md.
"""

from .bandwidth import BandwidthEstimate, probe_bandwidths  # noqa: F401
from .config import PLAN_NAMES, TieringConfig  # noqa: F401
from .disk import DiskTier, TornSwapError  # noqa: F401
from .plan import (ResidencyPlan, TIER_DISK, TIER_HBM,  # noqa: F401
                   TIER_HOST, build_plan)


def __getattr__(name):
    # the manager pulls jax (via StreamedHostAdam); keep this package
    # importable from jax-free tooling (config parsing, the linter)
    if name == "TieredResidencyManager":
        from .manager import TieredResidencyManager
        return TieredResidencyManager
    raise AttributeError(name)
