"""Residency plans: per-leaf tier assignment over the memory hierarchy.

A plan answers, for every parameter leaf and its optimizer-state
(Adam moment) leaves, *where it lives between uses*:

- ``hbm``  — device-resident (the all-fits default),
- ``host`` — the accelerator host's pinned memory; streamed per leaf
  through HBM inside the jitted step (the StreamedHostAdam walk,
  double-buffered so leaf N+1's h2d hides under leaf N's math),
- ``disk`` — on SSD between steps via the aio swapper; staged through
  host RAM around the step with async prefetched reads (the
  ZeRO-Infinity NVMe tier, arXiv 2104.07857).

Assignment is budget-driven and follows LAYER EXECUTION ORDER (the
pytree flatten order the streamed walk consumes — scan-carry models
stack all blocks into one leaf, unrolled models enumerate them): HBM
fills first, then host, and the *tail* of the walk spills to disk —
tail leaves are the ones whose prefetched reads have the longest
compute window ahead of their use. ``auto`` picks the first named plan
whose footprint fits the budgets, priced by the bandwidth probes.

Stdlib-only: plan construction is pure arithmetic over names/sizes so
the autotuner and tests can walk plan spaces without jax.
"""

from dataclasses import dataclass, field
from typing import Dict, List, Optional

from ...utils.logging import logger

TIER_HBM = "hbm"
TIER_HOST = "host"
TIER_DISK = "disk"
TIERS = (TIER_HBM, TIER_HOST, TIER_DISK)

# forced plans, in cost order; "auto" resolves to the first that fits
PLAN_LADDER = ("all_resident", "host_offload", "host_disk")


@dataclass
class LeafPlan:
    """One parameter leaf's residency: the param itself and its two
    fp32 Adam moments (which always share a tier)."""
    name: str
    param_bytes: int
    opt_bytes: int
    param_tier: str = TIER_HBM
    opt_tier: str = TIER_HBM
    offloadable: bool = True   # stacked block kernels may leave HBM

    def to_dict(self):
        return {"name": self.name, "param_bytes": self.param_bytes,
                "opt_bytes": self.opt_bytes, "param_tier": self.param_tier,
                "opt_tier": self.opt_tier}


@dataclass
class ResidencyPlan:
    name: str
    leaves: List[LeafPlan] = field(default_factory=list)
    hbm_budget_bytes: Optional[int] = None
    host_budget_bytes: Optional[int] = None

    def bytes_by_tier(self) -> Dict[str, int]:
        out = {t: 0 for t in TIERS}
        for leaf in self.leaves:
            out[leaf.param_tier] += leaf.param_bytes
            out[leaf.opt_tier] += leaf.opt_bytes
        return out

    def fits(self) -> bool:
        by_tier = self.bytes_by_tier()
        if (self.hbm_budget_bytes is not None
                and by_tier[TIER_HBM] > self.hbm_budget_bytes):
            return False
        if (self.host_budget_bytes is not None
                and by_tier[TIER_HOST] > self.host_budget_bytes):
            return False
        return True

    def est_step_seconds(self, bw) -> float:
        """Per-step transfer cost (seconds) under a ``BandwidthEstimate``:
        host-tier leaves round-trip host<->device inside the step; disk
        leaves additionally round-trip SSD<->host between steps. An
        upper bound — overlap (the whole point) only reduces it — used
        to ORDER plans, not to predict wall clock."""
        by_tier = self.bytes_by_tier()
        host_rt = by_tier[TIER_HOST] * (1.0 / bw.h2d_bytes_per_s
                                        + 1.0 / bw.d2h_bytes_per_s)
        disk_rt = by_tier[TIER_DISK] * (
            1.0 / bw.disk_read_bytes_per_s + 1.0 / bw.disk_write_bytes_per_s
            + 1.0 / bw.h2d_bytes_per_s + 1.0 / bw.d2h_bytes_per_s)
        return host_rt + disk_rt

    def disk_leaf_names(self) -> List[str]:
        return [l.name for l in self.leaves if l.opt_tier == TIER_DISK]

    def to_dict(self):
        return {"name": self.name,
                "hbm_budget_bytes": self.hbm_budget_bytes,
                "host_budget_bytes": self.host_budget_bytes,
                "bytes_by_tier": self.bytes_by_tier(),
                "leaves": [l.to_dict() for l in self.leaves]}


def _fresh_leaves(names, param_nbytes, opt_nbytes, offloadable):
    return [LeafPlan(n, int(pb), int(ob), offloadable=bool(off))
            for n, pb, ob, off in zip(names, param_nbytes, opt_nbytes,
                                      offloadable)]


def _apply_named_plan(plan_name, leaves, hbm_budget, host_budget,
                      offload_params=True):
    """Mutate ``leaves`` into the named layout. Budget-driven within the
    plan's shape: host_offload moves every moment host-side (the
    ZeRO-Offload contract) and only as many offloadable param leaves as
    the HBM budget demands; host_disk additionally spills the tail of
    the host walk to disk until host RAM fits."""
    if plan_name == "all_resident":
        return
    # --- host_offload and beyond: moments leave HBM; offloadable
    # (stacked-block) params move host-side as a unit — the scan-xs
    # placement the streaming module implements is whole-tree, so the
    # plan mirrors the mechanism instead of pretending per-leaf
    # granularity the engine cannot deliver -----------------------------
    for leaf in leaves:
        leaf.opt_tier = TIER_HOST
        if offload_params and leaf.offloadable:
            leaf.param_tier = TIER_HOST
    if plan_name == "host_offload":
        return
    # --- host_disk: spill the tail of the host walk to SSD ------------
    if host_budget is not None:
        host_used = sum(l.opt_bytes for l in leaves
                        if l.opt_tier == TIER_HOST)
        host_used += sum(l.param_bytes for l in leaves
                         if l.param_tier == TIER_HOST)
        for leaf in reversed(leaves):
            if host_used <= host_budget:
                break
            if leaf.opt_tier == TIER_HOST:
                leaf.opt_tier = TIER_DISK
                host_used -= leaf.opt_bytes
    else:
        # no host budget given but the plan was FORCED: spill the last
        # moment leaf so the disk path is actually exercised
        if leaves:
            leaves[-1].opt_tier = TIER_DISK


def build_plan(names, param_nbytes, opt_nbytes, *,
               offloadable=None, plan: str = "auto",
               hbm_budget_bytes: Optional[int] = None,
               host_budget_bytes: Optional[int] = None,
               bandwidths=None, offload_params: bool = True
               ) -> ResidencyPlan:
    """Derive the residency plan for a model.

    ``names``/``param_nbytes``/``opt_nbytes`` are aligned with the
    pytree flatten order (= execution order of the streamed walk);
    ``offloadable`` marks leaves whose params may leave HBM (the
    engine's stacked-block mask). ``plan="auto"`` walks the ladder and
    returns the first layout that fits both budgets (priced for the
    report by ``bandwidths``); a named plan is honored even when it
    does not fit (the caller asked for it)."""
    if offloadable is None:
        offloadable = [True] * len(names)
    candidates = PLAN_LADDER if plan == "auto" else (plan,)
    chosen = None
    for cand in candidates:
        p = ResidencyPlan(cand,
                          _fresh_leaves(names, param_nbytes, opt_nbytes,
                                        offloadable),
                          hbm_budget_bytes, host_budget_bytes)
        _apply_named_plan(cand, p.leaves, hbm_budget_bytes,
                          host_budget_bytes, offload_params=offload_params)
        chosen = p
        if plan != "auto" or p.fits():
            break
    if plan == "auto" and not chosen.fits():
        logger.warning(
            "tiering: no plan fits the declared budgets "
            f"(hbm={hbm_budget_bytes}, host={host_budget_bytes}); "
            f"using {chosen.name} (deepest ladder rung) anyway")
    if bandwidths is not None:
        cost = chosen.est_step_seconds(bandwidths)
        logger.info(f"tiering plan {chosen.name}: "
                    f"{chosen.bytes_by_tier()} est transfer "
                    f"{cost * 1e3:.2f} ms/step")
    return chosen
