"""``tiering`` config block — the residency-manager knobs.

Stdlib-only (the dependency-free config contract: ``DeepSpeedConfig``
must parse and validate without jax), consumed by
``runtime/tiering/manager.py``. Reference semantics: ZeRO-Infinity's
offload configuration (arXiv 2104.07857 §5 — bandwidth-centric
partitioning across GPU/CPU/NVMe), expressed as explicit per-tier byte
budgets plus a plan selector instead of the reference's
offload_param/offload_optimizer device strings.
"""

from dataclasses import dataclass
from typing import Optional

PLAN_NAMES = ("auto", "all_resident", "host_offload", "host_disk")


@dataclass
class TieringConfig:
    """One residency manager for parameters + optimizer state.

    - ``plan``: ``auto`` picks the cheapest plan whose residency fits
      the budgets (all_resident -> host_offload -> host_disk); a named
      plan forces that tier layout regardless of fit.
    - ``hbm_budget_bytes``: device bytes the plan may occupy with
      params + optimizer state. None = the device's reported memory
      limit when available, else unbounded (all_resident always fits).
      Tests and the offload bench set a SYNTHETIC budget here to train
      models "larger than HBM" on the CPU backend.
    - ``host_budget_bytes``: host-RAM bytes for host-tier leaves; the
      overflow spills to the disk tier. None = unbounded.
    - ``disk_path``: the disk tier's swap directory (one subdir per
      process, like the NVMe offload paths).
    - ``prefetch``: double-buffer the in-step host->device moment walk
      (``utils.streaming.double_buffered``) AND issue the disk tier's
      read-ahead right after the post-step write-back, so reads overlap
      the inter-step host work. Off = every transfer is waited for at
      its use site (the bench's stall-fraction control arm).
    - ``write_protection``: keep the last written host buffer of every
      disk-tier leaf until the NEXT read verifies; a torn/truncated
      ``.swp`` is then re-materialized from the host copy instead of
      raising. Costs one transient host copy of the disk-tier state —
      turn off to reclaim that RAM and get a hard
      ``TornSwapError`` instead (docs/offload.md).
    - ``probe_bandwidth``: measure host<->device and disk bandwidth at
      manager construction (one-shot, cached process-wide) to price
      plans; off = cost estimates use the declared fallbacks below.
    - ``host_bytes_per_s`` / ``disk_bytes_per_s``: declared bandwidths
      used when probing is off (or fails) — deterministic plan costing
      for tests and the autotuner.
    """
    enabled: bool = False
    plan: str = "auto"
    hbm_budget_bytes: Optional[int] = None
    host_budget_bytes: Optional[int] = None
    disk_path: str = "/tmp/ds_tpu_tiering"
    prefetch: bool = True
    write_protection: bool = True
    probe_bandwidth: bool = True
    probe_bytes: int = 4 << 20
    aio_threads: int = 4
    host_bytes_per_s: float = 8e9     # ~PCIe3 x16 order of magnitude
    disk_bytes_per_s: float = 1e9     # ~NVMe order of magnitude
    offload_params: bool = True       # stacked block params may leave HBM

    def __post_init__(self):
        if self.plan not in PLAN_NAMES:
            raise ValueError(
                f"tiering.plan must be one of {PLAN_NAMES}, got "
                f"{self.plan!r}")
        for knob in ("hbm_budget_bytes", "host_budget_bytes"):
            v = getattr(self, knob)
            if v is not None and int(v) < 0:
                raise ValueError(f"tiering.{knob} must be >= 0, got {v}")
        if int(self.probe_bytes) <= 0:
            raise ValueError("tiering.probe_bytes must be > 0")
        if int(self.aio_threads) < 1:
            raise ValueError("tiering.aio_threads must be >= 1")
        for knob in ("host_bytes_per_s", "disk_bytes_per_s"):
            if float(getattr(self, knob)) <= 0:
                raise ValueError(f"tiering.{knob} must be > 0")
