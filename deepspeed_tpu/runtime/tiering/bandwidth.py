"""Bandwidth probes: measure the memory hierarchy once, price plans.

ZeRO-Infinity's partitioning is *bandwidth-centric* (arXiv 2104.07857
§5): what a tier costs per step is bytes-moved / measured-bandwidth, so
the plan builder needs real numbers for host<->device and disk. Probes
run ONCE per process at manager construction (cached — autotuner
candidates building many engines must not re-pay them) and never inside
the step path, so the compile-once and host-sync disciplines are
untouched.

On backends where a probe cannot run (no writable disk path, jax
absent) the declared config fallbacks are used and ``probed`` stays
False — plan costing degrades to deterministic estimates instead of
failing.
"""

import os
import tempfile
import time
from dataclasses import dataclass
from typing import Optional

from ...utils.logging import logger


@dataclass
class BandwidthEstimate:
    h2d_bytes_per_s: float
    d2h_bytes_per_s: float
    disk_write_bytes_per_s: float
    disk_read_bytes_per_s: float
    probed: bool = False

    def to_dict(self):
        return {
            "h2d_bytes_per_s": self.h2d_bytes_per_s,
            "d2h_bytes_per_s": self.d2h_bytes_per_s,
            "disk_write_bytes_per_s": self.disk_write_bytes_per_s,
            "disk_read_bytes_per_s": self.disk_read_bytes_per_s,
            "probed": self.probed,
        }


_CACHE: Optional[BandwidthEstimate] = None


def _probe_host_device(nbytes: int):
    """Time one h2d placement and one d2h materialization of a pinned
    host buffer. A handful of ms at init; never on the step path."""
    import jax
    import numpy as np
    buf = np.zeros(max(1, nbytes // 4), dtype=np.float32)
    t0 = time.perf_counter()
    arr = jax.device_put(buf)
    arr.block_until_ready()
    h2d = time.perf_counter() - t0
    t0 = time.perf_counter()
    np.array(arr)
    d2h = time.perf_counter() - t0
    return buf.nbytes / max(h2d, 1e-9), buf.nbytes / max(d2h, 1e-9)


def _probe_disk(path: str, nbytes: int):
    """Synchronous write+fsync then read of one probe file — the
    sustained-bandwidth floor the async swapper improves on."""
    os.makedirs(path, exist_ok=True)
    data = b"\0" * nbytes
    fd, probe_path = tempfile.mkstemp(dir=path, suffix=".probe")
    try:
        t0 = time.perf_counter()
        with os.fdopen(fd, "wb") as f:
            f.write(data)
            f.flush()
            os.fsync(f.fileno())
        write = time.perf_counter() - t0
        t0 = time.perf_counter()
        with open(probe_path, "rb") as f:
            f.read()
        read = time.perf_counter() - t0
    finally:
        try:
            os.unlink(probe_path)
        except OSError:
            pass
    return nbytes / max(write, 1e-9), nbytes / max(read, 1e-9)


def probe_bandwidths(disk_path: str, nbytes: int = 4 << 20, *,
                     fallback_host: float = 8e9, fallback_disk: float = 1e9,
                     enabled: bool = True,
                     force: bool = False) -> BandwidthEstimate:
    """Measure (or recall) the process's bandwidth estimate.
    ``enabled=False`` ALWAYS returns the caller's declared fallbacks
    with ``probed=False`` (deterministic costing for tests/autotuning
    regardless of what other engines in the process did); ``enabled=
    True`` probes once per process and caches ONLY a successful probe,
    so call order between enabled and disabled managers cannot leak
    measurements either way."""
    global _CACHE
    fallback = BandwidthEstimate(fallback_host, fallback_host,
                                 fallback_disk, fallback_disk,
                                 probed=False)
    if not enabled:
        return fallback
    if _CACHE is not None and not force:
        return _CACHE
    try:
        h2d, d2h = _probe_host_device(int(nbytes))
        dw, dr = _probe_disk(disk_path, int(nbytes))
        _CACHE = BandwidthEstimate(h2d, d2h, dw, dr, probed=True)
        return _CACHE
    except Exception as e:  # ds-tpu: lint-ok[PY001] — a probe failure of any kind must degrade to fallbacks, never block engine construction
        logger.warning(f"tiering bandwidth probe failed ({e}); using "
                       "declared fallback bandwidths")
        return fallback


def reset_bandwidth_cache():
    """Test isolation: forget the cached probe."""
    global _CACHE
    _CACHE = None
