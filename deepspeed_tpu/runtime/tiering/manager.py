"""TieredResidencyManager: one owner for param/optimizer residency.

The unification ROADMAP item 4 calls for: ``StreamedHostAdam`` (PR 1's
double-buffered host-moment walk), the engine's param host streaming,
and the NVMe swapper are three views of one question — *where does each
leaf live, and when does it move* — and this manager answers it from a
single ``ResidencyPlan``:

- **hbm** leaves never move: their "home" sharding is device memory and
  the streamed walk's fetch/put collapse to identity.
- **host** leaves live in the accelerator host's pinned memory and are
  streamed through HBM per leaf inside the jitted step, DOUBLE-BUFFERED
  via ``utils.streaming.double_buffered`` (leaf N+1's h2d issued before
  leaf N's update math) so XLA's latency-hiding scheduler overlaps the
  transfer chain with the compute chain.
- **disk** leaves additionally leave host RAM between steps through the
  ``DiskTier`` (aio swapper + verification): ``stage_out`` writes the
  freshly updated moments after the step and — with prefetch on —
  immediately issues the read-ahead, so the reads complete under the
  inter-step host work (batch prep, monitor, dispatch) and
  ``stage_in``'s blocking wait shrinks toward zero. Every blocking wait
  is a goodput-ledger ``data_stall`` site, which is what lets the PR-8
  instrument *prove* the overlap instead of claiming it.

The update math is EXACTLY ``StreamedHostAdam``'s (the manager's Adam
is a per-leaf-sharding specialization of it), and every transfer is
identity math — so any two plans produce bitwise-identical training
trajectories, the acceptance invariant the tiering tests assert.
"""

import os
from typing import Any, Dict, Optional

import numpy as np

from ...observability.goodput import timed as _goodput
from ...observability.metrics import get_registry
from ...observability.trace import span as _span
from ...utils.logging import logger, log_dist
from ..zero.offload_optimizer import StreamedHostAdam
from .bandwidth import probe_bandwidths
from .config import TieringConfig
from .disk import DiskTier
from .plan import TIER_DISK, TIER_HBM, TIER_HOST, build_plan


class _TieredStreamedAdam(StreamedHostAdam):
    """StreamedHostAdam with PER-LEAF moment homes: hbm-tier leaves keep
    their moments device-resident (the host round-trip collapses to
    identity), host/disk-tier leaves keep the pinned-host home. The walk
    order, double buffering, and update math are inherited unchanged —
    the bitwise-parity guarantee across plans rests on exactly that."""

    def __init__(self, *args, opt_tiers=None, **kwargs):
        super().__init__(*args, **kwargs)
        if opt_tiers:
            import jax
            dev_flat, treedef = jax.tree.flatten(self.dev_shardings)
            host_flat = jax.tree.leaves(self.host_shardings)
            homes = [dev if tier == TIER_HBM else host
                     for dev, host, tier in zip(dev_flat, host_flat,
                                                opt_tiers)]
            self.host_shardings = jax.tree.unflatten(treedef, homes)


class TieredResidencyManager:
    """Engine-facing residency manager (duck-typed as the engine's
    ``streamed_offload``: ``state_shardings`` / ``init`` /
    ``clipped_apply`` / ``apply``), plus the staging hooks the engine
    calls around dispatch (``stage_in`` / ``stage_out``)."""

    def __init__(self, tcfg: TieringConfig, opt_params: Dict[str, Any],
                 adamw: bool, param_specs, param_shapes, mesh,
                 zero_stage: int, param_names=None,
                 offload_mask=None, params_offloaded: bool = False):
        import jax
        self.config = tcfg
        flat, _treedef = jax.tree.flatten_with_path(param_shapes)
        names = [jax.tree_util.keystr(p) for p, _ in flat]
        shapes = [leaf for _, leaf in flat]
        param_bytes = [int(np.prod(s.shape)) * np.dtype(s.dtype).itemsize
                       for s in shapes]
        # two fp32 Adam moments per param leaf (mu + nu)
        opt_bytes = [2 * int(np.prod(s.shape)) * 4 for s in shapes]
        if offload_mask is not None:
            offloadable = [bool(m) for m in jax.tree.leaves(offload_mask)]
        else:
            offloadable = [("layers" in (n or "") and len(s.shape) >= 3)
                           for n, s in zip(names, shapes)]

        self.bandwidths = probe_bandwidths(
            tcfg.disk_path, tcfg.probe_bytes,
            fallback_host=tcfg.host_bytes_per_s,
            fallback_disk=tcfg.disk_bytes_per_s,
            enabled=tcfg.probe_bandwidth)
        hbm_budget = tcfg.hbm_budget_bytes
        if hbm_budget is None:
            from ...observability.memory import device_memory_stats
            stats = device_memory_stats()
            if stats and stats.get("bytes_limit"):
                hbm_budget = int(stats["bytes_limit"])
        self.plan = build_plan(
            names, param_bytes, opt_bytes, offloadable=offloadable,
            plan=tcfg.plan, hbm_budget_bytes=hbm_budget,
            host_budget_bytes=tcfg.host_budget_bytes,
            bandwidths=self.bandwidths,
            offload_params=bool(tcfg.offload_params and params_offloaded))

        opt_tiers = [leaf.opt_tier for leaf in self.plan.leaves]
        self.adam = _TieredStreamedAdam(
            opt_params, adamw, param_specs, param_shapes, mesh, zero_stage,
            param_names=param_names, prefetch=tcfg.prefetch,
            opt_tiers=opt_tiers)
        self.prefetch = bool(tcfg.prefetch)

        # disk tier: constructed only when the plan spilled something
        self._disk_idx = [i for i, t in enumerate(opt_tiers)
                          if t == TIER_DISK]
        self._names = names
        self.disk: Optional[DiskTier] = None
        if self._disk_idx:
            self.disk = DiskTier(
                os.path.join(tcfg.disk_path,
                             f"proc{jax.process_index()}_opt"),
                n_threads=tcfg.aio_threads,
                protect=tcfg.write_protection)
        self._staged_out = False
        self._publish_gauges()
        log_dist(
            f"tiering: plan={self.plan.name} "
            f"by_tier={self.plan.bytes_by_tier()} "
            f"disk_leaves={len(self._disk_idx)} prefetch={self.prefetch}",
            ranks=[0])

    # -- StreamedHostAdam surface (the engine's streamed_offload) ------
    def state_shardings(self):
        return self.adam.state_shardings()

    def init(self, params):
        return self.adam.init(params)

    def apply(self, params, grads, state, lr, grad_scale=None):
        return self.adam.apply(params, grads, state, lr,
                               grad_scale=grad_scale)

    def clipped_apply(self, params, grads, state, lr, gnorm, clip):
        return self.adam.clipped_apply(params, grads, state, lr, gnorm,
                                       clip)

    @property
    def _trace_events(self):
        return self.adam._trace_events

    # -- disk staging around the dispatch ------------------------------
    def _moment_name(self, which: str, i: int) -> str:
        return f"{which}{self._names[i]}"

    def stage_out(self, params, opt_state):
        """After the step: write disk-tier moments to SSD (async), join
        the writes, issue the read-ahead, and drop the host/device
        arrays — between steps the disk tier holds them alone. No-op
        without disk leaves or when already staged out. Returns the
        (params, opt_state) trees with disk leaves as abstract
        placeholders (same avals -> the compiled step is reused)."""
        if self.disk is None or self._staged_out:
            return params, opt_state
        import jax
        with _span("tiering/stage_out"):
            new_state = dict(opt_state)
            for which in ("mu", "nu"):
                flat, treedef = jax.tree.flatten(opt_state[which])
                for i in self._disk_idx:
                    arr = flat[i]
                    # materializing waits on the dispatched step — that
                    # wait is compute, not I/O; the ledger should not
                    # book device time as a disk stall
                    with _goodput("compute"):
                        val = np.array(arr)  # ds-tpu: lint-ok[TS002] — the disk-tier write-back is the sanctioned d2h of this design (docs/offload.md), outside any jit
                    self.disk.swap_out(self._moment_name(which, i), val)
                    flat[i] = jax.ShapeDtypeStruct(val.shape, val.dtype)
                new_state[which] = jax.tree.unflatten(treedef, flat)
            self.disk.flush()
            if self.prefetch:
                # read-ahead NOW: the aio pool reads while the host does
                # inter-step work; stage_in then waits only the remainder
                for which in ("mu", "nu"):
                    for i in self._disk_idx:
                        self.disk.prefetch(self._moment_name(which, i))
        self._staged_out = True
        self._publish_gauges()
        return params, new_state

    def stage_in(self, params, opt_state):
        """Before the next dispatch (or a checkpoint save): page the
        disk-tier moments back and rebuild concrete leaves at their home
        shardings. Verified reads — a torn file re-materializes from the
        protected copy or raises ``TornSwapError``."""
        if self.disk is None or not self._staged_out:
            return params, opt_state
        import jax
        home_flat = jax.tree.leaves(self.adam.host_shardings)
        with _span("tiering/stage_in"):
            new_state = dict(opt_state)
            for which in ("mu", "nu"):
                flat, treedef = jax.tree.flatten(opt_state[which])
                for i in self._disk_idx:
                    buf = self.disk.swap_in(self._moment_name(which, i))
                    flat[i] = jax.device_put(buf, home_flat[i])
                new_state[which] = jax.tree.unflatten(treedef, flat)
        self._staged_out = False
        return params, new_state

    # -- reporting -----------------------------------------------------
    def _publish_gauges(self):
        reg = get_registry()
        by_tier = self.plan.bytes_by_tier()
        for tier in (TIER_HBM, TIER_HOST, TIER_DISK):
            reg.gauge(f"mem/by_tier/{tier}").set(by_tier[tier])
        if self.disk is not None:
            reg.gauge("tiering/disk_resident_bytes").set(
                self.disk.resident_bytes())

    def report(self) -> dict:
        """JSON-able plan + bandwidth + transfer summary (bench
        artifacts, /statusz-style consumers)."""
        out = {"plan": self.plan.to_dict(),
               "bandwidths": self.bandwidths.to_dict(),
               "prefetch": self.prefetch}
        if self.disk is not None:
            out["disk"] = {"resident_bytes": self.disk.resident_bytes(),
                           "recoveries": self.disk.recoveries,
                           "swap_dir": self.disk.swap_dir}
        return out

    def close(self):
        if self.disk is not None:
            disk, self.disk = self.disk, None
            disk.close()
