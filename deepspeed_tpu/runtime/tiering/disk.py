"""DiskTier: the residency manager's SSD tier over the aio swapper.

``swap_tensor/swapper.py``'s ``AsyncTensorSwapper`` is the raw
primitive (async pwrite/pread with same-name hazard handling); this
wrapper is what every disk-tier consumer in the runtime goes through —
the residency manager, the engine's param NVMe eviction, and the
native offload optimizer's moment swap — adding the three things the
raw swapper deliberately does not do:

1. **Integrity**: every read is verified against the written byte
   count (``os.path.getsize`` before AND after the read — a truncated
   ``.swp`` mid-run must never be loaded into a master shard). A short
   read either re-materializes from the retained host copy
   (``protect=True``) or raises the named ``TornSwapError``.
2. **Accounting**: per-direction transfer counters
   (``tiering/transfer_bytes/{host_to_disk,disk_to_host}``) and trace
   spans (``tiering/swap_out`` / ``tiering/swap_in``) on every
   transfer, plus goodput-ledger ``data_stall`` sites on every
   BLOCKING wait — the issue-side of an async write/read is free, so
   the ledger measures exactly the non-overlapped remainder. That is
   what makes prefetch-on vs prefetch-off comparable on the PR-8
   instrument.
3. **Protection** (optional): the last written buffer of each name is
   retained until its next read verifies, so a torn file recovers
   bitwise (docs/offload.md, chaos ``torn_swap`` scenario).
"""

import os
from typing import Dict, Optional

import numpy as np

from ...observability.goodput import timed as _goodput
from ...observability.metrics import get_registry
from ...observability.trace import span as _span
from ...utils.logging import logger
from ..swap_tensor.swapper import AsyncTensorSwapper


class _NullCtx:
    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


_NULL_CTX = _NullCtx()


class TornSwapError(RuntimeError):
    """A disk-tier file failed read verification (truncated / short /
    unreadable) and no protected host copy was available to
    re-materialize from. Raised INSTEAD of returning garbage."""

    def __init__(self, name: str, path: str, expected: int, actual):
        self.name, self.path = name, path
        self.expected_bytes, self.actual_bytes = expected, actual
        super().__init__(
            f"torn swap file for '{name}': {path} holds {actual} bytes, "
            f"expected {expected} (truncated mid-run?) and no protected "
            "host copy is retained — refusing to load garbage; restore "
            "from checkpoint or enable tiering.write_protection")


class DiskTier:
    """Named numpy buffers on SSD with verification + accounting."""

    def __init__(self, swap_dir: str, n_threads: int = 4,
                 protect: bool = False, counter_prefix: str = "tiering",
                 ledger_category: Optional[str] = "data_stall"):
        """``counter_prefix`` namespaces the transfer counters — the
        residency manager uses the default ``tiering`` namespace (what
        ``ds_tpu_report`` renders as the tiering section); the legacy
        NVMe consumers pass their own so their traffic is not mistaken
        for an active residency manager. ``ledger_category=None``
        disables the goodput sites — for callers whose blocking waits
        already run inside a ``timed("compute")`` window (the native
        cpu_adam step), where booking them again would double-count."""
        self._swapper = AsyncTensorSwapper(swap_dir, n_threads=n_threads)
        self.swap_dir = self._swapper.swap_dir
        self.protect = bool(protect)
        self._nbytes: Dict[str, int] = {}
        self._protected: Dict[str, np.ndarray] = {}
        self._prefix = counter_prefix
        self._ledger_category = ledger_category
        self.recoveries = 0

    def _timed_wait(self):
        if self._ledger_category is None:
            return _NULL_CTX
        return _goodput(self._ledger_category)

    # -- write side ----------------------------------------------------
    def swap_out(self, name: str, array: np.ndarray):
        """Issue the async write (non-blocking). The array must not be
        mutated until ``flush()``; with ``protect`` it is additionally
        retained until the NEXT verified read of ``name``."""
        array = np.ascontiguousarray(array)
        with _span("tiering/swap_out", {"name": name,
                                        "bytes": array.nbytes}):
            self._swapper.swap_out(name, array)
        self._nbytes[name] = int(array.nbytes)
        if self.protect:
            self._protected[name] = array
        reg = get_registry()
        reg.counter(f"{self._prefix}/transfer_bytes/host_to_disk").inc(
            array.nbytes)
        reg.counter(f"{self._prefix}/transfers/host_to_disk").inc()

    def flush(self):
        """Join outstanding writes — the blocking (ledger-visible) half
        of the write path. Prefetch reads stay in flight."""
        with self._timed_wait():
            self._swapper.flush()

    # -- read side -----------------------------------------------------
    def prefetch(self, name: str):
        self._swapper.prefetch(name)

    def _file_bytes(self, name: str):
        try:
            return os.path.getsize(self._swapper.path(name))
        except OSError:
            return None

    def _recover(self, name: str, actual):
        expected = self._nbytes.get(name, -1)
        path = self._swapper.path(name)
        # a prefetched read of the torn file may still be in flight; its
        # buffer/status is untrustworthy either way
        self._swapper.discard_read(name)
        copy = self._protected.get(name)
        if copy is None:
            raise TornSwapError(name, path, expected, actual)
        logger.warning(
            f"tiering: torn swap file for '{name}' ({path}: {actual} vs "
            f"{expected} expected bytes) — re-materializing from the "
            "protected host copy and re-writing the tier")
        self.recoveries += 1
        get_registry().counter(
            f"{self._prefix}/torn_swap_recovered_total").inc()
        self.swap_out(name, copy)    # heal the file for the next reader
        self.flush()
        return copy

    def swap_in(self, name: str) -> np.ndarray:
        """Blocking read with verification. Returns the host buffer
        (bitwise what was written, or the protected copy on a detected
        tear)."""
        expected = self._nbytes.get(name)
        if expected is None:
            # the tier has no in-memory metadata for cross-process
            # reads, so a name never written through THIS instance has
            # no verification basis — refuse rather than read unverified
            raise KeyError(
                f"nothing swapped out under '{name}' through this "
                "DiskTier")
        size = self._file_bytes(name)
        if size != expected:
            return self._recover(name, size)
        try:
            with _span("tiering/swap_in", {"name": name,
                                           "bytes": expected}), \
                    self._timed_wait():
                buf = self._swapper.swap_in(name)
        except OSError as e:
            logger.warning(f"tiering: disk-tier read of '{name}' failed "
                           f"({e})")
            return self._recover(name, self._file_bytes(name))
        # re-check: a truncation landing between the size check and the
        # read completion left the buffer tail unwritten
        size = self._file_bytes(name)
        if size != expected or buf.nbytes != expected:
            return self._recover(name, size)
        self._protected.pop(name, None)   # the disk copy proved good
        reg = get_registry()
        reg.counter(f"{self._prefix}/transfer_bytes/disk_to_host").inc(
            expected)
        reg.counter(f"{self._prefix}/transfers/disk_to_host").inc()
        return buf

    # -- lifecycle -----------------------------------------------------
    def resident_bytes(self) -> int:
        """Bytes currently on the tier (written names)."""
        return sum(self._nbytes.values())

    def remove(self, name: str):
        self._nbytes.pop(name, None)
        self._protected.pop(name, None)
        self._swapper.remove(name)

    def close(self):
        self._protected.clear()
        self._swapper.close()
