"""Config plumbing shared by all sub-configs.

Analog of the reference's ``deepspeed/runtime/config_utils.py`` (pydantic-ish
``DeepSpeedConfigObject``) using plain dataclasses: each sub-config is a
dataclass with a ``from_dict`` that accepts the reference's JSON key names,
warns on unknown keys, and validates types.
"""

import dataclasses
from ..utils.logging import logger


class DeepSpeedConfigError(Exception):
    pass


def dict_to_dataclass(cls, d: dict, path: str = ""):
    """Build dataclass ``cls`` from dict ``d``; unknown keys warn, not fail."""
    if d is None:
        d = {}
    if not isinstance(d, dict):
        raise DeepSpeedConfigError(f"Config section '{path}' must be a dict, got {type(d)}")
    field_names = {f.name for f in dataclasses.fields(cls)}
    kwargs = {}
    for k, v in d.items():
        if k in field_names:
            kwargs[k] = v
        else:
            logger.warning(f"Unknown config key '{path}.{k}' ignored")
    return cls(**kwargs)


def get_scalar_param(param_dict, param_name, param_default_value):
    return param_dict.get(param_name, param_default_value)


def dataclass_to_dict(obj):
    if dataclasses.is_dataclass(obj):
        return {f.name: dataclass_to_dict(getattr(obj, f.name)) for f in dataclasses.fields(obj)}
    if isinstance(obj, dict):
        return {k: dataclass_to_dict(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        return [dataclass_to_dict(v) for v in obj]
    return obj
