"""User-facing activation-checkpointing API.

Reference: deepspeed/runtime/activation_checkpointing/checkpointing.py —
Megatron-compatible surface: ``configure()`` (:825) reads the
``activation_checkpointing`` config block, ``checkpoint(function, *args)``
(:743) recomputes the wrapped region in backward, with options to
partition saved activations across model-parallel ranks (:367), stash
them on the host (CPU checkpointing, :480), and a model-parallel RNG
tracker (:122) so dropout inside recomputation replays identically.

TPU-native mapping:
- ``checkpoint`` -> ``jax.checkpoint`` with a policy chosen by the
  configured knobs; recompute-in-backward is native to XLA remat.
- ``partition_activations`` -> saved residuals get a sharding constraint
  over the TP ("model") mesh axis, so each rank stores 1/mp of every
  checkpointed input (what gather_partitioned_activations undoes in the
  reference, :259 — here XLA re-gathers on demand).
- ``checkpoint_in_cpu`` -> offload policy: saveable dots are staged to
  ``pinned_host`` memory instead of HBM.
- RNG: jax PRNG keys are values, not global state, so recompute is
  deterministic BY CONSTRUCTION — the tracker exists for API/porting
  parity and hands out named, forkable keys.
"""

import functools
from typing import Any, Optional

import jax
import jax.numpy as jnp

# module-level knobs (reference keeps the same module-global pattern)
_CONFIGURED = False
PARTITION_ACTIVATIONS = False
CPU_CHECKPOINT = False
CONTIGUOUS_CHECKPOINTING = False
SYNCHRONIZE = False
PROFILE_TIME = False
REMAT_POLICY = None      # models.gpt.REMAT_POLICIES key; None = default
_NUM_LAYERS = None
_MPU = None


def set_remat_policy(name):
    """Select WHAT a checkpointed region saves (NEW TPU knob; the
    reference always recomputes everything). ``name``: a
    ``models.gpt.REMAT_POLICIES`` key ("full", "dots", "attn_out",
    "offload", ...) or None to restore the default."""
    global REMAT_POLICY
    if name is not None:
        from ...models.gpt import REMAT_POLICIES
        if name not in REMAT_POLICIES:
            raise ValueError(f"unknown remat policy {name!r} "
                             f"(known: {sorted(REMAT_POLICIES)})")
    REMAT_POLICY = name


def _policy():
    """jax.checkpoint policy for the current knob settings."""
    if REMAT_POLICY is not None:
        if REMAT_POLICY == "none":
            # inside an explicit checkpoint() region "no remat" means
            # save-everything — REMAT_POLICIES maps "none" to the policy
            # value None, which jax.checkpoint would read as its
            # recompute-everything DEFAULT (the opposite)
            return jax.checkpoint_policies.everything_saveable
        from ...models.gpt import REMAT_POLICIES
        return REMAT_POLICIES[REMAT_POLICY]
    if CPU_CHECKPOINT:
        return jax.checkpoint_policies.offload_dot_with_no_batch_dims(
            "device", "pinned_host")
    # default: recompute everything (the reference always recomputes the
    # region; saved tensors are only the region *inputs*)
    return jax.checkpoint_policies.nothing_saveable


def _partition_constraint(x):
    """Shard a saved activation's seq dim (axis 1 of [b, s, ...]) over the
    TP axis when configured; no-op without a mesh/model axis."""
    if not PARTITION_ACTIVATIONS or not hasattr(x, "ndim") or x.ndim < 2:
        return x
    try:
        from ...comm.mesh import peek_global_mesh
        mesh = peek_global_mesh()
        if mesh is None:
            return x
        mp = mesh.shape.get("model", 1)
        if mp == 1 or x.shape[1] % mp != 0:
            return x
        from jax.sharding import NamedSharding, PartitionSpec as P
        spec = [None] * x.ndim
        spec[1] = "model"
        return jax.lax.with_sharding_constraint(
            x, NamedSharding(mesh, P(*spec)))
    except Exception:
        return x


def checkpoint(function, *args, **kwargs):
    """Checkpoint a model region (reference: checkpointing.py:743).

    Returns ``function(*args, **kwargs)``; in backward the region is
    recomputed instead of storing its internals. Saved inputs honor
    ``partition_activations`` / ``checkpoint_in_cpu``. Like the
    reference (where non-tensor args pass through untraced), only
    array-like positional args are traced: bools/ints/strings/None and
    all kwargs are closed over statically, so ported layers that branch
    on a flag (``if causal:``) don't hit TracerBoolConversionError."""
    is_arr = [hasattr(a, "ndim") for a in args]
    arr_args = tuple(_partition_constraint(a)
                     for a, t in zip(args, is_arr) if t)

    def on_arrays(*arrs):
        it = iter(arrs)
        full = [next(it) if t else a for a, t in zip(args, is_arr)]
        return function(*full, **kwargs)

    fn = jax.checkpoint(on_arrays, policy=_policy())
    if PROFILE_TIME:
        with jax.named_scope("act_checkpoint"):
            return fn(*arr_args)
    return fn(*arr_args)


def checkpoint_wrapper(function):
    """Decorator form: ``layer = checkpoint_wrapper(layer_fn)``."""
    @functools.wraps(function)
    def wrapped(*args, **kwargs):
        return checkpoint(function, *args, **kwargs)
    return wrapped


def partition_activations_in_checkpoint(partition_activation):
    """Reference: checkpointing.py:755 — toggle partitioning only."""
    global PARTITION_ACTIVATIONS
    PARTITION_ACTIVATIONS = bool(partition_activation)


def set_num_layers(nlayers):
    global _NUM_LAYERS
    _NUM_LAYERS = nlayers


def configure(mpu_=None, deepspeed_config=None, partition_activations=None,
              contiguous_checkpointing=None, num_checkpoints=None,
              checkpoint_in_cpu=None, synchronize=None, profile=None,
              remat_policy=None):
    """Reference: checkpointing.py:825 — same signature plus the TPU-only
    ``remat_policy`` selector; knobs without a TPU analog (contiguous
    buffers, explicit synchronize) are accepted and recorded but do not
    change compilation."""
    global _CONFIGURED, _MPU, PARTITION_ACTIVATIONS, CPU_CHECKPOINT
    global CONTIGUOUS_CHECKPOINTING, SYNCHRONIZE, PROFILE_TIME, _NUM_LAYERS

    if deepspeed_config is not None:
        block = deepspeed_config
        if not isinstance(block, dict):
            from ..config import DeepSpeedConfig
            cfg = (block if isinstance(block, DeepSpeedConfig)
                   else DeepSpeedConfig.from_file(block))
            acfg = cfg.activation_checkpointing
            block = {
                "partition_activations": acfg.partition_activations,
                "cpu_checkpointing": acfg.cpu_checkpointing,
                "contiguous_memory_optimization":
                    acfg.contiguous_memory_optimization,
                "synchronize_checkpoint_boundary":
                    acfg.synchronize_checkpoint_boundary,
                "profile": acfg.profile,
                "number_checkpoints": acfg.number_checkpoints,
                "remat_policy": acfg.remat_policy,
            }
        else:
            block = block.get("activation_checkpointing", block)
        PARTITION_ACTIVATIONS = bool(block.get("partition_activations", False))
        CPU_CHECKPOINT = bool(block.get("cpu_checkpointing", False))
        if block.get("remat_policy") is not None:
            set_remat_policy(block["remat_policy"])
        CONTIGUOUS_CHECKPOINTING = bool(
            block.get("contiguous_memory_optimization", False))
        SYNCHRONIZE = bool(block.get("synchronize_checkpoint_boundary", False))
        PROFILE_TIME = bool(block.get("profile", False))
        if block.get("number_checkpoints"):
            _NUM_LAYERS = block["number_checkpoints"]

    if partition_activations is not None:
        PARTITION_ACTIVATIONS = bool(partition_activations)
    if contiguous_checkpointing is not None:
        CONTIGUOUS_CHECKPOINTING = bool(contiguous_checkpointing)
    if num_checkpoints is not None:
        _NUM_LAYERS = num_checkpoints
    if checkpoint_in_cpu is not None:
        CPU_CHECKPOINT = bool(checkpoint_in_cpu)
    if synchronize is not None:
        SYNCHRONIZE = bool(synchronize)
    if profile is not None:
        PROFILE_TIME = bool(profile)
    if remat_policy is not None:
        set_remat_policy(remat_policy)
    if CPU_CHECKPOINT and jax.default_backend() == "cpu":
        from ...utils.logging import logger
        logger.warning("checkpoint_in_cpu: pinned_host offload unsupported "
                       "on the CPU backend — using full recompute")
        CPU_CHECKPOINT = False
    _MPU = mpu_
    _CONFIGURED = True


def is_configured():
    return _CONFIGURED


def reset():
    """Reference: checkpointing.py:768 — clear configured state."""
    global _CONFIGURED, _MPU, PARTITION_ACTIVATIONS, CPU_CHECKPOINT
    global CONTIGUOUS_CHECKPOINTING, SYNCHRONIZE, PROFILE_TIME, _NUM_LAYERS
    global REMAT_POLICY
    _CONFIGURED = False
    _MPU = None
    PARTITION_ACTIVATIONS = CPU_CHECKPOINT = False
    CONTIGUOUS_CHECKPOINTING = SYNCHRONIZE = PROFILE_TIME = False
    REMAT_POLICY = None
    _NUM_LAYERS = None


class RNGStatesTracker:
    """Named PRNG key registry (reference: CudaRNGStatesTracker,
    checkpointing.py:122). JAX keys are functional, so the tracker is a
    bookkeeping convenience for ports: register a named seed once, then
    ``fork(name)`` hands back a fresh subkey each call — recomputation
    under ``jax.checkpoint`` replays the SAME key by construction, which
    is the determinism the reference's state save/restore machinery
    exists to provide."""

    def __init__(self):
        self._states = {}

    def reset(self):
        self._states.clear()

    def get_states(self):
        return dict(self._states)

    def set_states(self, states):
        self._states = dict(states)

    def add(self, name, seed):
        if name in self._states:
            raise ValueError(f"rng state {name} already exists")
        self._states[name] = jax.random.PRNGKey(seed)

    def fork(self, name="model-parallel-rng"):
        if name not in self._states:
            raise ValueError(f"rng state {name} was never added")
        key = self._states[name]
        try:
            from jax._src.core import trace_state_clean
            tracing = not trace_state_clean()
        except Exception:
            tracing = False
        if isinstance(key, jax.core.Tracer) or tracing:
            # fork() mutates HOST state; inside a traced region the
            # mutation would bake one frozen key into the compiled step
            # (identical dropout every execution) or leak a tracer into
            # the registry. Ports must split OUTSIDE jit and pass keys in
            # (rngs={...}) — fail loudly instead of silently derailing.
            raise RuntimeError(
                "RNGStatesTracker.fork() called inside a traced (jit/"
                "checkpoint) region: the split would not replay across "
                "steps. Fork outside the jitted step and pass the key in "
                "(e.g. flax rngs={'dropout': key}).")
        self._states[name], sub = jax.random.split(key)
        return sub


_RNG_TRACKER = RNGStatesTracker()


def get_rng_tracker():
    return _RNG_TRACKER


# reference-name alias (get_cuda_rng_tracker, checkpointing.py:193)
get_cuda_rng_tracker = get_rng_tracker


def model_parallel_seed(seed, mesh=None):
    """Reference: model_parallel_cuda_manual_seed (checkpointing.py:198):
    data-parallel regions share ``seed``; model-parallel regions get a
    distinct, deterministic offset per TP rank. Returns the tracker after
    installing both named states."""
    _RNG_TRACKER.reset()
    _RNG_TRACKER.add("data-parallel-rng", seed)
    _RNG_TRACKER.add("model-parallel-rng", seed + 2718)
    return _RNG_TRACKER


model_parallel_cuda_manual_seed = model_parallel_seed
