from . import checkpointing
from .checkpointing import (checkpoint, checkpoint_wrapper, configure,
                            get_rng_tracker, is_configured,
                            model_parallel_seed,
                            partition_activations_in_checkpoint, reset,
                            set_num_layers)

__all__ = ["checkpointing", "checkpoint", "checkpoint_wrapper", "configure",
           "get_rng_tracker", "is_configured", "model_parallel_seed",
           "partition_activations_in_checkpoint", "reset", "set_num_layers"]
