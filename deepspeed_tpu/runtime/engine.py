"""The training engine.

TPU-native analog of ``DeepSpeedEngine`` (reference: runtime/engine.py:180,
3236 LoC). Same responsibilities — distributed init, precision setup,
optimizer wiring, forward/backward/step, grad reduction, LR scheduling,
checkpointing, logging — but the mechanism is one *fused, jitted train step*
over a named mesh instead of hook-driven tensor surgery:

- ZeRO stages are sharding rule sets (runtime/zero/sharding.py); XLA's SPMD
  partitioner emits the reduce-scatter / all-gather traffic the reference
  hand-codes in stage_1_and_2.py / stage3.py.
- Gradient accumulation is a ``lax.scan`` over the microbatch axis inside
  the step (reference: the forward/backward loop with
  is_gradient_accumulation_boundary, engine.py:1676).
- fp16 dynamic loss scaling is traced state (runtime/fp16/loss_scaler.py);
  an overflow skips the update via ``lax.cond`` rather than a Python branch.

The reference's ``engine(batch)`` / ``engine.backward(loss)`` /
``engine.step()`` calling convention is preserved for drop-in familiarity,
implemented on top of the fused path; ``train_batch(batch)`` is the
recommended fast path (one jit call per optimizer step).
"""

import os
import time
from typing import Any, Callable, Dict, Optional

import numpy as np
import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P, NamedSharding

from .. import comm as dist
from ..comm.mesh import DENSE_DP_AXES
from ..models.layers import set_activation_rules
from ..observability.goodput import get_ledger as _goodput_ledger
from ..observability.goodput import timed as _goodput
from ..observability.programs import track_program
from ..observability.trace import span as _span
from ..utils.logging import logger, log_dist
from ..utils.timer import (SynchronizedWallClockTimer, ThroughputTimer,
                           FORWARD_GLOBAL_TIMER, BACKWARD_GLOBAL_TIMER,
                           STEP_GLOBAL_TIMER)
from ..utils.tree import map_opt_state_sharding
from .config import DeepSpeedConfig
from .config_utils import DeepSpeedConfigError
from .fp16.loss_scaler import (LossScaleState, init_loss_scale, grads_finite,
                               update_scale)
from .lr_schedules import get_lr_schedule
from .optimizers import build_optimizer
from .zero.sharding import (extract_logical_names, make_param_rules,
                            make_opt_state_rules)

try:
    from flax.core import meta as flax_meta
except Exception:  # pragma: no cover
    flax_meta = None


def _tree_names_is_leaf(x):
    return x is None or (isinstance(x, tuple)
                         and all(isinstance(e, (str, type(None))) for e in x))


class DeepSpeedEngine:
    """Train-loop owner. Construct via ``deepspeed_tpu.initialize``."""

    def __init__(self,
                 model,                      # flax nn.Module (or None if apply_fn given)
                 config: DeepSpeedConfig,
                 *,
                 loss_fn: Callable,          # (model, params, batch, rng, train) -> loss
                 params=None,                # initialized variables (else init from sample)
                 sample_batch=None,          # batch dict used for shape-based init
                 rng: Optional[jax.Array] = None,
                 mesh=None,
                 optimizer=None,             # optax transform overriding config block
                 lr_scheduler=None,          # schedule fn overriding config block
                 mpu=None):                  # accepted for API parity; mesh supersedes it
        self.module = model
        self._loss_fn = loss_fn
        self.client_optimizer = optimizer
        self.global_steps = 0
        self.global_samples = 0
        self.micro_steps = 0
        self._skipped_steps_host = 0
        self._skipped_steps_dev = None   # on-device fp16-skip accumulator
        self._monitor_buffer = []        # queued (label, device value, step)
        self._compiled = {}
        self.tiering = None              # TieredResidencyManager when configured

        dist.init_distributed()

        # ---- config first (mesh shape comes from it), then mesh, then
        # batch arithmetic against the mesh's dp degree -----------------
        if isinstance(config, dict):
            config = DeepSpeedConfig.from_dict(config)
        if mesh is None:
            mesh = dist.build_mesh(config.mesh.to_spec())
        else:
            dist.set_global_mesh(mesh)
        self.mesh = mesh
        self.dp_world_size = dist.dp_world_size(mesh)
        self.mp_world_size = dist.mp_world_size(mesh)
        config.resolve_batch_sizes(self.dp_world_size)
        self.config = config
        self.zero_stage = config.zero_optimization.stage

        # activation sharding rules for models built from our layer library
        self._activation_rules = {"batch": DENSE_DP_AXES, "seq": None,
                                  "embed": None, "mlp": "model", "qkv": "model"}
        self._apply_activation_checkpointing_config()
        self._apply_param_offload_config()
        self._warn_inert_zero_knobs()
        set_activation_rules(self._activation_rules)

        # ---- precision ----------------------------------------------
        self.fp16_enabled = config.fp16.enabled
        self.bf16_enabled = config.bf16.enabled
        self.loss_scale_state = init_loss_scale(
            0.0 if config.fp16.dynamic_loss_scale else config.fp16.loss_scale,
            config.fp16.initial_scale_power,
            hysteresis=config.fp16.hysteresis) if self.fp16_enabled else None

        # ---- params --------------------------------------------------
        self.rng = rng if rng is not None else jax.random.PRNGKey(42)
        self._init_params(params, sample_batch)

        # ---- optimizer ----------------------------------------------
        self._configure_optimizer(optimizer, lr_scheduler)

        # ---- sharding consistency gate ------------------------------
        # "validate_sharding": true runs the analysis-subsystem checker
        # over the param/opt/grad spec trees against the live mesh —
        # undefined axes, double-sharded dims, indivisible shapes, and
        # opt-state specs that contradict their param's sharding fail
        # here with a readable listing instead of deep inside GSPMD.
        if config.validate_sharding:
            from ..analysis.validate import validate_engine_sharding
            validate_engine_sharding(self)

        # ---- monitors / timers --------------------------------------
        self.timers = SynchronizedWallClockTimer()
        self.tput_timer = ThroughputTimer(
            batch_size=config.train_batch_size or 1,
            steps_per_output=config.steps_per_print)
        from ..monitor.monitor import MonitorMaster
        self.monitor = MonitorMaster(config)

        # ---- observability (observability/, docs/observability.md) ----
        # window-gated trace spans + the shared metrics registry + MFU/
        # step-time accounting; when the block is absent the span() call
        # sites below reduce to the module no-op (near-free by the
        # microbenchmark test)
        self.observability = None
        self._tokens_per_step = None
        if config.observability is not None and config.observability.enabled:
            from ..observability import Observability
            self.observability = Observability(
                config.observability, steps_per_print=config.steps_per_print)

        # ---- goodput ledger (observability/goodput.py) ---------------
        # always-on like the HBM accountant: two host clock reads per
        # instrumented phase, no device syncs. Starting the process
        # ledger arms the _goodput() sites in the hot path below.
        _goodput_ledger().start()

        # ---- live telemetry endpoint (observability/export.py) -------
        # /metrics (Prometheus) + /healthz + /statusz served from a
        # daemon thread over metrics_snapshot() — host floats only, so a
        # scrape never adds a device sync to the step path
        self.telemetry = None
        if (config.observability is not None
                and config.observability.export.enabled):
            from ..observability.export import TelemetryServer
            exp = config.observability.export
            self.telemetry = TelemetryServer(
                self.metrics_snapshot, host=exp.host,
                port=exp.port).start()
            log_dist(f"telemetry endpoint: http://{exp.host}:"
                     f"{self.telemetry.port}/metrics (+/healthz /statusz)",
                     ranks=[0])

        # ---- HBM accounting (observability/memory.py) ----------------
        # attribute this engine's long-lived buffers to subsystems in
        # the process-wide accountant (mem/by_subsystem/* gauges, the
        # ds_tpu_mem report sections, OOM forensics). Shape metadata
        # only — never a device read, and init-time only. On by default
        # even without an observability block; observability.memory
        # {"enabled": false} turns off attribution, live sampling, AND
        # the OOM forensics hook together.
        self._memory_cfg = (config.observability.memory
                            if config.observability is not None else None)
        self._memory_enabled = (self._memory_cfg is None
                                or self._memory_cfg.enabled)
        self._grad_buffers_accounted = False
        if self._memory_enabled:
            self._account_static_memory()

        # ---- resilience (runtime/resilience/, docs/resilience.md) ----
        # divergence sentinel + rollback, preemption emergency save, and
        # the step-hang watchdog; constructed after the monitor so every
        # recovery transition can emit events
        self._last_save_dir = None
        self.resilience = None
        if config.resilience is not None and config.resilience.enabled:
            from .resilience.manager import ResilienceManager
            self.resilience = ResilienceManager(self, config.resilience)

        from .data_pipeline.curriculum_scheduler import CurriculumScheduler
        self.curriculum_scheduler = (
            CurriculumScheduler(config.curriculum_learning)
            if config.curriculum_learning.enabled else None)
        from .progressive_layer_drop import ProgressiveLayerDrop
        self.progressive_layer_drop = (
            ProgressiveLayerDrop(theta=config.progressive_layer_drop.theta,
                                 gamma=config.progressive_layer_drop.gamma)
            if config.progressive_layer_drop.enabled else None)
        # PLD theta reaches the model through the loss_fn: it is threaded
        # as a traced scalar kwarg when the loss_fn declares it (reference:
        # engine.py:1603 passes the PLD state into the module forward)
        import inspect
        try:
            _sig = inspect.signature(loss_fn).parameters
            self._loss_fn_kwargs = {
                name for name, p in _sig.items()
                if p.kind in (p.KEYWORD_ONLY, p.POSITIONAL_OR_KEYWORD)
            } | ({"*"} if any(p.kind == p.VAR_KEYWORD
                              for p in _sig.values()) else set())
        except (TypeError, ValueError):  # builtins/partials without sigs
            self._loss_fn_kwargs = {"*"}
        if (self.progressive_layer_drop is not None
                and not self._loss_accepts("layer_keep_prob")):
            logger.warning(
                "progressive_layer_drop is enabled but loss_fn does not "
                "accept a 'layer_keep_prob' kwarg — theta cannot reach the "
                "model and PLD is a no-op")

        # compression-aware training + MoQ quantize-aware training, applied
        # to the weights at the gradient-accumulation boundary (reference:
        # compression scheduler stepped at engine.py:1885; MoQ applied
        # inside the training step)
        from ..compression.compress import init_compression
        self.compression_scheduler = init_compression(config.compression_training)
        self._act_quant_on = False
        self._sync_activation_quantization()
        self.moq_quantizer = None
        qt = dict(config.quantize_training or {})
        if qt.get("enabled", False):
            from .config_utils import dict_to_dataclass
            from .quantize import MoQConfig, MoQQuantizer
            self.moq_quantizer = MoQQuantizer(
                dict_to_dataclass(MoQConfig, qt, "quantize_training"))
        self._next_eigenvalue_step = 0
        self._eigenvalue = None

        # state for the forward/backward/step calling convention
        self._pending_grads = None
        self._accum_grads = None
        self._accum_count = 0
        self._last_loss = None
        self._last_eval_batch = None   # one microbatch, kept for eigenvalue
        self._last_extra = {}

        log_dist(
            f"DeepSpeedEngine ready: zero_stage={self.zero_stage} "
            f"dp={self.dp_world_size} mp={self.mp_world_size} "
            f"micro_batch={config.train_micro_batch_size_per_gpu} "
            f"gas={config.gradient_accumulation_steps} "
            f"precision={'fp16' if self.fp16_enabled else 'bf16' if self.bf16_enabled else 'fp32'}",
            ranks=[0])

    # ------------------------------------------------------------------
    # setup
    # ------------------------------------------------------------------

    def _loss_accepts(self, kwarg: str) -> bool:
        return "*" in self._loss_fn_kwargs or kwarg in self._loss_fn_kwargs

    # ------------------------------------------------------------------
    # fp16 skip counter: accumulated ON DEVICE each step (one async
    # scalar add), materialized on the host only when read — the per-step
    # `int(metrics["skipped"])` sync this replaces stalled the whole ICI
    # ring once per step (ds_tpu_lint TS002).
    # ------------------------------------------------------------------

    @property
    def skipped_steps(self) -> int:
        if self._skipped_steps_dev is not None:
            self._skipped_steps_host += int(self._skipped_steps_dev)
            self._skipped_steps_dev = None
        return self._skipped_steps_host

    @skipped_steps.setter
    def skipped_steps(self, value):
        # ds-tpu: lint-ok[TS002] — checkpoint restore hands a host int
        self._skipped_steps_host = int(value)
        self._skipped_steps_dev = None

    def _accumulate_skipped(self, skipped):
        """Fold one step's skip flag (device int32 scalar) into the
        device-side accumulator without syncing."""
        self._skipped_steps_dev = (skipped if self._skipped_steps_dev is None
                                   else self._skipped_steps_dev + skipped)

    def _apply_activation_checkpointing_config(self):
        """Honor the DeepSpeed ``activation_checkpointing`` config block
        (reference: runtime/activation_checkpointing/config.py:27-43;
        CheckpointFunction checkpointing.py:493). The JSON is the spine:
        setting the block must change the compiled program, not silently
        parse. Mapping onto the TPU design:

        - block present -> the model's remat policy is forced on ("full"
          = nothing_saveable, the reference's recompute-everything), for
          models from our models/ library (they carry a dataclass config
          with a ``remat`` field and are rebuilt here).
        - ``cpu_checkpointing`` -> the "offload" remat policy: saveable
          residuals are staged to pinned host memory (TPU analog of
          checkpointing.py CPU checkpointing). Device-memory-kind backends
          (the CPU test backend) fall back to "full" with a warning.
        - ``partition_activations`` -> saved activations' *sequence* dim is
          sharded over the TP axis via the activation rules (Megatron
          partition_activations: each TP rank keeps 1/mp of every saved
          activation); XLA re-gathers where attention needs the full
          sequence.
        - knobs with no TPU analog (contiguous_memory_optimization,
          synchronize_checkpoint_boundary, number_checkpoints) warn loudly.
        """
        raw = self.config._raw.get("activation_checkpointing")
        if raw is None:
            return
        acfg = self.config.activation_checkpointing
        for knob in ("contiguous_memory_optimization",
                     "synchronize_checkpoint_boundary"):
            if raw.get(knob):
                logger.warning(
                    f"activation_checkpointing.{knob} has no TPU analog "
                    "(XLA owns buffer layout/synchronization) — ignored")
        if raw.get("number_checkpoints"):
            logger.warning(
                "activation_checkpointing.number_checkpoints is ignored: "
                "remat granularity is per transformer block (the scan body)")

        if acfg.partition_activations:
            if self.mp_world_size > 1:
                self._activation_rules["seq"] = "model"
            else:
                logger.warning(
                    "activation_checkpointing.partition_activations needs a "
                    "model-parallel mesh axis (mp=1 here) — no-op")

        mcfg = getattr(self.module, "config", None)
        if mcfg is None or not hasattr(mcfg, "remat"):
            logger.warning(
                "activation_checkpointing block set but the model does not "
                "expose a rematerialization config (models from "
                "deepspeed_tpu.models do) — apply jax.checkpoint in your "
                "own model code to honor it")
            return
        remat = mcfg.remat if mcfg.remat != "none" else "full"
        if acfg.remat_policy is not None:
            # explicit policy selection (NEW TPU knob): which activations
            # the checkpointed region saves — walked by the autotuner and
            # the kernel-tuning sweep. Validated by the config dataclass;
            # re-checked against the live table in case they drift.
            from ..models.gpt import REMAT_POLICIES
            if acfg.remat_policy not in REMAT_POLICIES:
                raise DeepSpeedConfigError(
                    f"activation_checkpointing.remat_policy "
                    f"{acfg.remat_policy!r} is not a model remat policy "
                    f"(known: {sorted(REMAT_POLICIES)})")
            remat = acfg.remat_policy
        if acfg.cpu_checkpointing:
            if jax.default_backend() == "cpu":
                logger.warning(
                    "activation_checkpointing.cpu_checkpointing: pinned_host "
                    "offload unsupported on the CPU backend — falling back "
                    "to full recompute")
            elif acfg.remat_policy not in (None, "offload"):
                logger.warning(
                    "activation_checkpointing: both cpu_checkpointing and "
                    f"remat_policy={acfg.remat_policy!r} set — the explicit "
                    "policy wins (use remat_policy='offload' for host-"
                    "staged residuals)")
            else:
                remat = "offload"
        if remat != mcfg.remat:
            import dataclasses
            self.module = type(self.module)(
                dataclasses.replace(mcfg, remat=remat))
            log_dist(f"activation_checkpointing: model remat policy set to "
                     f"'{remat}'", ranks=[0])

    def _apply_param_offload_config(self):
        """ZeRO-Infinity parameter offload (reference: offload_param ->
        params on CPU/NVMe swapped in per-layer with prefetch,
        partitioned_param_swapper.py:36, partitioned_param_coordinator.py
        :444). TPU-native: block params live in the accelerator host's
        memory space; the model's scan step fetches each block's params
        just-in-time (models/gpt.py offload_params + utils/streaming.py),
        and XLA's latency-hiding scheduler overlaps block k+1's h2d with
        block k's compute — the coordinator's prefetch, by compilation."""
        off = self.config.zero_optimization.offload_param
        self._param_swapper = None
        self._params_on_disk = False
        if off is None or off.device not in ("cpu", "nvme"):
            self._offload_params = False
            self._apply_tiering_param_offload()
            return
        if self.config.fp16.enabled:
            raise DeepSpeedConfigError(
                "offload_param currently supports bf16/fp32 training only "
                "(fp16 overflow checks would pull host grads to device)")
        if self.config.zero_optimization.offload_optimizer_device not in (
                "cpu", "nvme"):
            raise DeepSpeedConfigError(
                "offload_param requires offload_optimizer.device: cpu "
                "(params and optimizer state offload together, like the "
                "reference's ZeRO-Infinity configuration)")
        from ..utils.streaming import ensure_streaming_module
        self.module = ensure_streaming_module(
            self.module, error_cls=DeepSpeedConfigError,
            context="offload_param")
        self._offload_params = True
        if off.device == "nvme":
            # NVMe tier (reference: partitioned_param_swapper.py:36): the
            # stacked block params persist on SSD and leave host RAM
            # BETWEEN steps when they exceed max_in_cpu;
            # _ensure_params_resident pages them back with async
            # prefetched reads before any use. During the step the full
            # stacked tree must be host-resident (the fused jit consumes
            # whole arrays as autodiff inputs — the reference's per-layer
            # in-step window does not compose with whole-tree autodiff
            # under jit; the in-step h2d window is still per-block via
            # stream_in). Constructed AFTER the config validations so a
            # rejected config never spawns the aio thread pool. The
            # tiering DiskTier wraps the raw swapper with verified reads
            # + transfer accounting (runtime/tiering/disk.py) — one disk
            # tier implementation for every consumer.
            import os as _os
            from .tiering.disk import DiskTier
            # own counter namespace: offload_param traffic must not
            # render as an active residency manager in ds_tpu_report
            self._param_swapper = DiskTier(
                _os.path.join(off.nvme_path, "zero_params"),
                n_threads=max(2, int(off.buffer_count)),
                counter_prefix="offload_param_nvme")
        log_dist("ZeRO-Infinity param offload: block params in host "
                 "memory, streamed per scan step"
                 + (" (NVMe tier between steps)"
                    if self._param_swapper else ""), ranks=[0])

    def _apply_tiering_param_offload(self):
        """Tiering's parameter tier: when the residency plan can move
        stacked block params off-device (plan forced past all_resident,
        or auto with a declared HBM budget), rebuild the module for
        per-scan-step streaming — the same mechanism as offload_param,
        owned by the plan instead of a device string. Deliberately
        PLAN-INDEPENDENT: any tiering-enabled engine with
        ``offload_params`` uses the streamed forward even under an
        all_resident plan (the fetch is identity there), so switching
        plans changes PLACEMENT only, never the traced program — the
        invariant behind the cross-plan bitwise guarantee. Models
        without streaming support silently keep params resident (the
        plan reports them hbm-tier); the manager's plan is built against
        whatever this decided (``params_offloaded``)."""
        tcfg = self.config.tiering
        if tcfg is None or not tcfg.enabled or not tcfg.offload_params:
            return
        mcfg = getattr(self.module, "config", None)
        if (mcfg is None or not hasattr(mcfg, "offload_params")
                or not getattr(mcfg, "scan_layers", False)):
            logger.warning(
                "tiering: model does not support parameter streaming "
                "(needs a deepspeed_tpu.models model with "
                "scan_layers=True) — params stay HBM-resident; only "
                "optimizer state is tiered")
            return
        if self.config.fp16.enabled:
            raise DeepSpeedConfigError(
                "tiering.offload_params with fp16 is unsupported (fp16 "
                "overflow checks would pull host grads to device) — "
                "train bf16/fp32 or set tiering.offload_params=false")
        from ..utils.streaming import ensure_streaming_module
        self.module = ensure_streaming_module(
            self.module, error_cls=DeepSpeedConfigError, context="tiering")
        self._offload_params = True
        log_dist("tiering: stacked block params host-tiered, streamed "
                 "per scan step", ranks=[0])

    def _warn_inert_zero_knobs(self):
        """Stage-3 fetch-coordinator knobs are subsumed by the
        scan-over-layers design (one block's params live at a time; XLA
        schedules the gather prefetch) — warn loudly when a user sets
        them expecting the reference's imperative coordinator
        (partitioned_param_coordinator.py:42)."""
        raw = (self.config._raw.get("zero_optimization") or {})
        for knob in ("stage3_max_live_parameters", "stage3_max_reuse_distance",
                     "stage3_prefetch_bucket_size"):
            if knob in raw:
                logger.warning(
                    f"zero_optimization.{knob} has no effect: per-layer "
                    "param residency is fixed by the scan-over-layers design "
                    "(one block live at a time) and prefetch is scheduled by "
                    "XLA; use stage3_param_persistence_threshold to control "
                    "which params stay replicated")

    def _remember_extra(self, extra, loss_kwargs):
        """Record the step's extra-operand STRUCTURE for later consumers
        (flops-profiler lowering; MoQ eigenvalue refresh). Caller
        loss_kwargs are remembered as abstract ShapeDtypeStructs — keeping
        live values would pin (and, once the producing engine's next
        donated step deletes them, dangle) another model's buffers between
        steps; engine-internal scalars stay concrete."""
        abstract_kwargs = {
            k: jax.tree.map(
                lambda a: jax.ShapeDtypeStruct(jnp.shape(a),
                                               jnp.result_type(a)), v)
            for k, v in loss_kwargs.items()}
        self._last_extra = {**extra, **abstract_kwargs}

    def _init_params(self, params, sample_batch):
        cfg = self.config
        zcfg = cfg.zero_optimization
        if params is None:
            if sample_batch is None:
                raise DeepSpeedConfigError(
                    "initialize() needs either params or sample_batch")
            init_rng = self.rng
            abstract = jax.eval_shape(
                lambda r: self.module.init(r, **_init_kwargs(sample_batch)), init_rng)
            values_abs, names = extract_logical_names(abstract)
            self._param_names = names
            self._param_shapes = jax.tree.map(
                lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype), values_abs)
            self._build_param_shardings()
            # jit-init directly into the sharded layout (no host round-trip)
            init_fn = track_program(
                "train/param_init",
                jax.jit(
                    lambda r: extract_logical_names(
                        self.module.init(r, **_init_kwargs(sample_batch)))[0],
                    out_shardings=self.param_shardings),
                subsystem="train")
            self.params = init_fn(init_rng)
        else:
            values, names = extract_logical_names(params)
            self._param_names = names
            self._param_shapes = jax.tree.map(
                lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype), values)
            self._build_param_shardings()
            self.params = jax.device_put(values, self.param_shardings)

    def _build_param_shardings(self):
        zcfg = self.config.zero_optimization
        rules = make_param_rules(self.zero_stage,
                                 zcfg.stage3_param_persistence_threshold
                                 if self.zero_stage == 3 else 0)
        self.param_specs = jax.tree.map(
            lambda n, s: rules(n, s.shape, self.mesh),
            self._param_names, self._param_shapes,
            is_leaf=_tree_names_is_leaf)
        self.param_shardings = jax.tree.map(
            lambda spec: NamedSharding(self.mesh, spec),
            self.param_specs, is_leaf=lambda x: isinstance(x, P))
        # ZeRO-Infinity: scan-stacked block KERNELS ("layers" leading
        # axis, >=3-D) live in host memory; bias/scale leaves (<3-D
        # stacked, KB-scale) and everything else stay in HBM — the
        # reference's persistence-threshold semantics
        # (stage3_param_persistence_threshold: small params stay
        # resident), and required on TPU: host-space scan xs with ndim<3
        # leaves hit XLA layout bugs (see models/gpt.py offload branch)
        self._offload_mask = jax.tree.map(
            lambda n, s: bool(n and "layers" in n and len(s.shape) >= 3),
            self._param_names, self._param_shapes,
            is_leaf=_tree_names_is_leaf)
        if getattr(self, "_offload_params", False):
            self.param_shardings = jax.tree.map(
                lambda sh, off: _host_kind(sh) if off else sh,
                self.param_shardings, self._offload_mask,
                is_leaf=lambda x: isinstance(x, NamedSharding))

    def _configure_optimizer(self, client_optimizer, client_scheduler):
        cfg = self.config
        # LR schedule: client > config.scheduler > constant from optimizer lr
        base_lr = (cfg.optimizer.params.get("lr", 1e-3) if cfg.optimizer else 1e-3)
        if client_scheduler is not None:
            self.lr_schedule = client_scheduler
        elif cfg.scheduler and cfg.scheduler.type:
            self.lr_schedule = get_lr_schedule(cfg.scheduler.type, cfg.scheduler.params)
        else:
            self.lr_schedule = lambda step: base_lr

        if client_optimizer is not None:
            self.optimizer = client_optimizer
        else:
            opt_type = cfg.optimizer.type if cfg.optimizer else "Adam"
            opt_params = dict(cfg.optimizer.params) if cfg.optimizer else {}
            self.optimizer = build_optimizer(opt_type, opt_params,
                                             lr_schedule=self.lr_schedule)
        # gradient clipping wraps the transform (reference: clip_grad_norm_
        # against the *global* norm across shards — same semantics here
        # since grads inside jit are global values)
        if cfg.gradient_clipping and cfg.gradient_clipping > 0:
            import optax
            self.optimizer = optax.chain(
                optax.clip_by_global_norm(cfg.gradient_clipping), self.optimizer)

        # Native ZeRO-Offload: the C++ cpu_adam kernel owns the step and
        # the optimizer state lives in host numpy (reference dataflow).
        self.native_offload = None
        self.streamed_offload = None
        off = cfg.zero_optimization.offload_optimizer
        opt_type = (cfg.optimizer.type if cfg.optimizer else "Adam")

        # Tiered residency manager (runtime/tiering/, docs/offload.md):
        # ONE plan owns param + optimizer placement across HBM / host /
        # disk; supersedes the offload_* blocks (config.validate rejects
        # the combination). Math is StreamedHostAdam's, so any plan is
        # bitwise-identical to all-resident training.
        tcfg = cfg.tiering
        if tcfg is not None and tcfg.enabled:
            if client_optimizer is not None:
                raise DeepSpeedConfigError(
                    "tiering is incompatible with a client optimizer — "
                    "configure the optimizer via the config dict")
            if opt_type.lower() not in ("adam", "adamw"):
                raise DeepSpeedConfigError(
                    f"tiering supports Adam/AdamW, got {opt_type}")
            from .tiering.manager import TieredResidencyManager
            opt_params = dict(cfg.optimizer.params) if cfg.optimizer else {}
            adamw = _resolve_adamw(opt_type, opt_params)
            self.tiering = TieredResidencyManager(
                tcfg, opt_params, adamw, self.param_specs,
                self._param_shapes, self.mesh, self.zero_stage,
                param_names=self._param_names,
                offload_mask=self._offload_mask,
                params_offloaded=getattr(self, "_offload_params", False))
            self.streamed_offload = self.tiering  # duck-typed apply surface
            if (getattr(self, "_offload_params", False)
                    and not any(l.param_tier != "hbm"
                                for l in self.tiering.plan.leaves)):
                # the plan kept every param leaf device-resident (e.g.
                # auto resolved to all_resident): strip the host memory
                # kinds the streaming setup staged — the streamed
                # forward's fetch is identity for device leaves, so the
                # traced program is unchanged, only placement reverts
                from .zero.offload_optimizer import _device_memory
                self.param_shardings = jax.tree.map(
                    _device_memory, self.param_shardings,
                    is_leaf=lambda x: isinstance(x, NamedSharding))
                self.params = jax.device_put(self.params,
                                             self.param_shardings)
            self.opt_shardings = self.tiering.state_shardings()
            self.optimizer_state = jax.jit(
                self.tiering.init,
                out_shardings=self.opt_shardings)(self.params)
            # evict the fresh zeros now: step 1 then runs the same
            # staged path (stage_in -> dispatch -> stage_out) as every
            # later step — one compiled program, uniform residency
            self.params, self.optimizer_state = self.tiering.stage_out(
                self.params, self.optimizer_state)
            return

        if off is not None and getattr(off, "native", False):
            if off.device not in ("cpu", "nvme"):
                raise DeepSpeedConfigError(
                    "offload_optimizer.native=true needs device 'cpu' or "
                    f"'nvme' (got {off.device!r}) — without it the native "
                    "path would be silently skipped and optimizer state "
                    "would stay in HBM")
            if client_optimizer is not None:
                raise DeepSpeedConfigError(
                    "offload_optimizer.native is incompatible with a client "
                    "optimizer — configure optimizer via the config dict")
            if opt_type.lower() not in ("adam", "adamw"):
                raise DeepSpeedConfigError(
                    f"offload_optimizer.native supports Adam/AdamW, got {opt_type}")
            self._configure_native_offload(off, opt_type)
            return

        # Declarative ZeRO-Offload: Adam moments in the accelerator host's
        # pinned memory, streamed per-leaf through HBM inside the step
        # (reference dataflow: cpu_offload + pipelined swapper; here XLA
        # memory-kind transfers instead of host kernels).
        offload_dev = cfg.zero_optimization.offload_optimizer_device
        if offload_dev in ("cpu", "nvme"):
            if offload_dev == "nvme":
                logger.warning(
                    "offload_optimizer.device=nvme without native=true has "
                    "no NVMe tier; streaming moments via host memory instead "
                    "(set native=true for the aio/SSD path)")
            if client_optimizer is not None:
                raise DeepSpeedConfigError(
                    "offload_optimizer is incompatible with a client "
                    "optimizer — configure the optimizer via the config dict")
            if opt_type.lower() not in ("adam", "adamw"):
                raise DeepSpeedConfigError(
                    f"offload_optimizer supports Adam/AdamW, got {opt_type}")
            from .zero.offload_optimizer import StreamedHostAdam
            opt_params = dict(cfg.optimizer.params) if cfg.optimizer else {}
            adamw = _resolve_adamw(opt_type, opt_params)
            self.streamed_offload = StreamedHostAdam(
                opt_params, adamw, self.param_specs, self._param_shapes,
                self.mesh, self.zero_stage,
                param_names=self._param_names)
            self.opt_shardings = self.streamed_offload.state_shardings()
            self.optimizer_state = jax.jit(
                self.streamed_offload.init,
                out_shardings=self.opt_shardings)(self.params)
            log_dist(f"streamed host offload enabled (device={offload_dev}, "
                     "moments in pinned host memory)", ranks=[0])
            return

        # optimizer state: eval shape, shard per ZeRO stage, init sharded
        opt_shapes = jax.eval_shape(self.optimizer.init, self._param_shapes)
        opt_rule = make_opt_state_rules(self.zero_stage, self.mesh)
        self.opt_shardings = map_opt_state_sharding(
            opt_shapes, self._param_shapes, self.param_specs, opt_rule,
            self.mesh, param_names=self._param_names)
        self.optimizer_state = jax.jit(
            self.optimizer.init, out_shardings=self.opt_shardings)(self.params)

    # ------------------------------------------------------------------
    # ZeRO-Infinity param NVMe tier: page offloaded block params between
    # SSD and host RAM around the step (swap_tensor/swapper.py)
    # ------------------------------------------------------------------

    def _evict_params_to_nvme(self):
        """After the step: async-write the offloaded (host-side stacked
        block) param leaves to SSD, then drop the host arrays — between
        steps host RAM holds only the small resident params.

        Gated on ``offload_param.max_in_cpu`` (reference semantics: bytes
        of params allowed to stay in host RAM): models under the
        threshold skip the per-step SSD round-trip entirely."""
        if self._param_swapper is None or self._params_on_disk:
            return
        off = self.config.zero_optimization.offload_param
        offloaded_bytes = sum(
            leaf.size * jnp.dtype(leaf.dtype).itemsize
            for leaf, m in zip(jax.tree.leaves(self.params),
                               jax.tree.leaves(self._offload_mask)) if m)
        if offloaded_bytes <= int(off.max_in_cpu):
            return
        flat, treedef = jax.tree.flatten(self.params)
        paths = [p for p, _ in jax.tree.flatten_with_path(self.params)[0]]
        mask = jax.tree.leaves(self._offload_mask)
        new_leaves = []
        for path, leaf, off in zip(paths, flat, mask):
            if off:
                name = "param" + jax.tree_util.keystr(path)
                self._param_swapper.swap_out(name, np.asarray(leaf))
                new_leaves.append(jax.ShapeDtypeStruct(leaf.shape, leaf.dtype))
            else:
                new_leaves.append(leaf)
        # join writes BEFORE dropping the jax arrays backing the buffers
        self._param_swapper.flush()
        self.params = jax.tree.unflatten(treedef, new_leaves)
        self._params_on_disk = True

    def _ensure_params_resident(self):
        """Page NVMe-evicted param leaves back into host memory. Reads
        are all issued first (the aio thread pool overlaps them), then
        consumed in order — the reference's prefetch pipelining. Also
        the residency manager's stage-in point: disk-tier optimizer
        moments page back (verified reads) before any dispatch or
        checkpoint save consumes them."""
        if self.tiering is not None:
            self.params, self.optimizer_state = self.tiering.stage_in(
                self.params, self.optimizer_state)
        if not self._params_on_disk:
            return
        flat, treedef = jax.tree.flatten(self.params)
        paths = [p for p, _ in jax.tree.flatten_with_path(self.params)[0]]
        mask = jax.tree.leaves(self._offload_mask)
        shardings = jax.tree.leaves(
            self.param_shardings,
            is_leaf=lambda x: isinstance(x, NamedSharding))
        names = ["param" + jax.tree_util.keystr(p) for p in paths]
        for name, off in zip(names, mask):
            if off:
                self._param_swapper.prefetch(name)
        new_leaves = []
        for name, leaf, off, sh in zip(names, flat, mask, shardings):
            if off:
                buf = self._param_swapper.swap_in(name)
                new_leaves.append(jax.device_put(buf, sh))
            else:
                new_leaves.append(leaf)
        self.params = jax.tree.unflatten(treedef, new_leaves)
        self._params_on_disk = False

    def _zero_grad_shardings(self, stage):
        """NamedSharding tree for gradients under the ZeRO partition:
        the (names-aware) opt-state rule applied to every param — the
        reduce-scatter target the reference hand-codes in
        stage_1_and_2.py:895 average_tensor."""
        opt_rule = make_opt_state_rules(stage, self.mesh)
        grad_specs = jax.tree.map(
            lambda n, spec, s: opt_rule(spec, s.shape, n),
            self._param_names, self.param_specs, self._param_shapes,
            is_leaf=_tree_names_is_leaf)
        return jax.tree.map(
            lambda spec: NamedSharding(self.mesh, spec), grad_specs,
            is_leaf=lambda x: isinstance(x, P))

    def _configure_native_offload(self, off, opt_type):
        """Grad shardings = the ZeRO partition, landing in pinned host
        memory; host state built from the current params."""
        from .zero.offload_optimizer import CPUAdamOffloadOptimizer
        self.grad_shardings = _with_host_memory(
            self._zero_grad_shardings(max(self.zero_stage, 1)))
        opt_params = dict(self.config.optimizer.params) if self.config.optimizer else {}
        adamw = _resolve_adamw(opt_type, opt_params)
        self.native_offload = CPUAdamOffloadOptimizer(
            self.params, self.grad_shardings, self.param_shardings,
            opt_params, adamw=adamw,
            nvme_swap_dir=(off.nvme_path if off.device == "nvme" else None),
            aio_threads=off.aio_threads)
        self.optimizer_state = ()
        self.opt_shardings = ()
        log_dist(f"native ZeRO-Offload enabled (device={off.device}, "
                 f"kernel=cpu_adam)", ranks=[0])

    # ------------------------------------------------------------------
    # the fused train step
    # ------------------------------------------------------------------

    def _batch_sharding(self, tree, with_gas_dim):
        lead = (None, DENSE_DP_AXES) if with_gas_dim else (DENSE_DP_AXES,)

        def shard_one(x):
            # scalar leaves (a temperature, a flag) replicate: a spec
            # longer than the rank would be a placement error
            spec = (lead + (None,) * (x.ndim - len(lead)))[:x.ndim]
            return NamedSharding(self.mesh, P(*spec))
        return jax.tree.map(shard_one, tree)

    def _place_batch(self, batch, with_gas_dim):
        """Place a batch onto the mesh. Single-host: plain device_put.
        Multi-host: each process passes its LOCAL slice of the batch (the
        dataloader yields per-host slices) and we assemble the global array
        (reference analog: per-rank DistributedSampler shards)."""
        shardings = self._batch_sharding(batch, with_gas_dim)
        if jax.process_count() == 1:
            return jax.device_put(batch, shardings)
        return jax.tree.map(
            lambda x, sh: jax.make_array_from_process_local_data(sh, np.asarray(x)),
            batch, shardings)

    def _make_accumulate_fn(self):
        """The shared microbatch-scan gradient accumulation: returns
        fn(params, scaler, batch, rng) -> (unscaled grads, mean_loss,
        gnorm). Used by BOTH the fused train step and the native-offload
        grad step so the accumulation/unscale semantics cannot drift."""
        gas = self.config.gradient_accumulation_steps
        fp16 = self.fp16_enabled
        model = self.module
        loss_fn = self._loss_fn
        offloaded = getattr(self, "_offload_params", False)
        # reference data_types.grad_accum_dtype: fp32 (default) keeps the
        # reduce-in-fp32 semantics; bf16 halves the resident grad buffer
        accum_dtype = jnp.dtype(self.config.data_types.resolve())
        if fp16 and accum_dtype != jnp.float32:
            raise DeepSpeedConfigError(
                "data_types.grad_accum_dtype=bf16 is incompatible with "
                "fp16 loss scaling (unscale needs fp32 headroom)")

        # ZeRO stage >= 2: the grad-accum scan carry is pinned to the ZeRO
        # partition (same rule as the opt state), so full-shape fp32 grads
        # never persist across microbatches — XLA emits the reduce-scatter
        # the reference hand-codes in stage_1_and_2.py:895 average_tensor.
        grad_constraint = None
        if self.zero_stage >= 2 and self.native_offload is None:
            grad_shardings = self._zero_grad_shardings(self.zero_stage)

            def grad_constraint(g):
                if offloaded:
                    # host-space grads keep their placement; the ZeRO
                    # partition constraint applies to device leaves only
                    return jax.tree.map(
                        lambda x, sh, off: x if off
                        else jax.lax.with_sharding_constraint(x, sh),
                        g, grad_shardings, self._offload_mask)
                return jax.lax.with_sharding_constraint(g, grad_shardings)

        def microbatch_loss(params, batch, rng, scale, extra):
            # xprof phase scope: forward ops carry "fwd" in their
            # op_name (cotangents show as transpose(fwd)), lining device
            # profiles up with the host-side trace spans
            with jax.named_scope("fwd"):
                loss = loss_fn(model, params, batch, rng, True, **extra)
            return loss * scale / gas, loss

        def accumulate(params, scaler, batch, rng, extra):
            scale = scaler.scale if fp16 else jnp.float32(1.0)

            def micro(carry, xs):
                grads_acc, loss_acc, i = carry
                mb = jax.tree.map(lambda x: x[i], batch)
                mrng = jax.random.fold_in(rng, i)
                (_, loss), grads = jax.value_and_grad(
                    microbatch_loss, has_aux=True)(params, mb, mrng, scale, extra)
                grads_acc = jax.tree.map(
                    lambda a, g: a + g.astype(a.dtype), grads_acc, grads)
                if grad_constraint is not None:
                    grads_acc = grad_constraint(grads_acc)
                return (grads_acc, loss_acc + loss, i + 1), None

            zero_grads = jax.tree.map(
                lambda s: jnp.zeros(s.shape, accum_dtype), self._param_shapes)
            if offloaded:
                # offloaded params produce host-space cotangents: their
                # accumulation buffers must live host-side too (the param
                # shardings already carry the host memory kind; SPMD needs
                # memory transfers to have explicit shardings)
                zero_grads = jax.tree.map(
                    lambda z, off, sh: jax.device_put(z, sh) if off else z,
                    zero_grads, self._offload_mask, self.param_shardings)
            if grad_constraint is not None:
                zero_grads = grad_constraint(zero_grads)
            (grads, loss_sum, _), _ = jax.lax.scan(
                micro, (zero_grads, jnp.float32(0.0), 0), None, length=gas)
            mean_loss = loss_sum / gas

            # unscale (fp16) — grads currently hold sum over gas of
            # grad(loss*scale/gas) = scale * mean-grad. The reference's
            # gradient_predivide_factor guards fp16 NCCL reductions against
            # overflow; XLA reduces in fp32 here, so it is unnecessary.
            if fp16:
                grads = jax.tree.map(lambda g: g * (1.0 / scale), grads)
            # per-leaf partial norms: host-space leaves reduce host-side,
            # only their scalars cross to device
            rep_dev = NamedSharding(self.mesh, P())
            gnorm = jnp.sqrt(sum(
                jax.device_put(jnp.sum(jnp.square(g.astype(jnp.float32))),
                               rep_dev) if offloaded
                else jnp.sum(jnp.square(g.astype(jnp.float32)))
                for g in jax.tree.leaves(grads)))
            return grads, mean_loss, gnorm

        return accumulate

    def _make_train_step(self):
        cfg = self.config
        fp16 = self.fp16_enabled
        optimizer = self.optimizer
        accumulate = self._make_accumulate_fn()

        streamed = self.streamed_offload
        lr_schedule = self.lr_schedule

        def train_step(params, opt_state, scaler, batch, rng, extra):
            grads, mean_loss, gnorm = accumulate(params, scaler, batch, rng, extra)

            if streamed is not None:
                def apply(operand):
                    params_, opt_state_, grads_ = operand
                    with jax.named_scope("optimizer_step"):
                        return streamed.clipped_apply(
                            params_, grads_, opt_state_,
                            lr_schedule(opt_state_["count"]), gnorm,
                            cfg.gradient_clipping)
            else:
                def apply(operand):
                    params_, opt_state_, grads_ = operand
                    import optax
                    with jax.named_scope("optimizer_step"):
                        updates, new_opt = optimizer.update(grads_, opt_state_,
                                                            params_)
                        new_params = optax.apply_updates(params_, updates)
                    return new_params, new_opt

            if fp16:
                finite = grads_finite(grads)
                new_params, new_opt = jax.lax.cond(
                    finite, apply,
                    lambda op: (op[0], op[1]),
                    (params, opt_state, grads))
                new_scaler = update_scale(
                    scaler, finite, dynamic=cfg.fp16.dynamic_loss_scale,
                    scale_window=cfg.fp16.loss_scale_window,
                    hysteresis=cfg.fp16.hysteresis,
                    min_scale=cfg.fp16.min_loss_scale)
                skipped = jnp.where(finite, 0, 1)
            else:
                new_params, new_opt = apply((params, opt_state, grads))
                new_scaler = scaler
                skipped = jnp.int32(0)

            metrics = {"loss": mean_loss, "grad_norm": gnorm,
                       "skipped": skipped,
                       "loss_scale": scaler.scale if fp16 else jnp.float32(1.0)}
            return new_params, new_opt, new_scaler, metrics

        dummy_scaler = self.loss_scale_state or init_loss_scale(1.0)
        rep = NamedSharding(self.mesh, P())
        scaler_sh = jax.tree.map(lambda _: rep, dummy_scaler)
        return jax.jit(
            train_step,
            donate_argnums=(0, 1, 2),
            out_shardings=(self.param_shardings, self.opt_shardings, scaler_sh, None),
        )

    def _make_grad_step(self):
        """Native-offload variant: jit computes the accumulated, unscaled
        gradient partition (into pinned host memory) + metrics; the C++
        cpu_adam step happens host-side in train_batch."""
        cfg = self.config
        fp16 = self.fp16_enabled
        accumulate = self._make_accumulate_fn()

        def grad_step(params, scaler, batch, rng, extra):
            from ..utils.tree import clip_grads_by_global_norm
            grads, mean_loss, gnorm = accumulate(params, scaler, batch, rng, extra)
            grads = clip_grads_by_global_norm(grads, gnorm,
                                              cfg.gradient_clipping)
            if fp16:
                finite = grads_finite(grads)
                new_scaler = update_scale(
                    scaler, finite, dynamic=cfg.fp16.dynamic_loss_scale,
                    scale_window=cfg.fp16.loss_scale_window,
                    hysteresis=cfg.fp16.hysteresis,
                    min_scale=cfg.fp16.min_loss_scale)
            else:
                finite = jnp.bool_(True)
                new_scaler = scaler
            metrics = {"loss": mean_loss, "grad_norm": gnorm,
                       "finite": finite,
                       "loss_scale": scaler.scale if fp16 else jnp.float32(1.0)}
            return grads, new_scaler, metrics

        dummy_scaler = self.loss_scale_state or init_loss_scale(1.0)
        rep = NamedSharding(self.mesh, P())
        scaler_sh = jax.tree.map(lambda _: rep, dummy_scaler)
        return jax.jit(grad_step,
                       out_shardings=(self.grad_shardings, scaler_sh, None))

    def _native_offload_batch(self, batch, scaler, rng, extra):
        if "grad_step" not in self._compiled:
            self._compiled["grad_step"] = track_program(
                "train/grad_step", self._make_grad_step(), subsystem="train")
        grads, new_scaler, metrics = self._compiled["grad_step"](
            self.params, scaler, batch, rng, extra)
        # ds-tpu: lint-ok[TS002] — the host-side cpu_adam step needs the
        # finite flag on the host to decide whether to apply the update;
        # this sync is the native-offload contract, not an accident.
        finite = bool(metrics["finite"])
        lr = float(self.lr_schedule(self.global_steps)) if callable(
            self.lr_schedule) else float(self.lr_schedule)
        new_params = self.native_offload.step(grads, lr=lr, finite=finite)
        if new_params is not None:
            self.params = new_params
        metrics["skipped"] = jnp.int32(0 if finite else 1)
        return new_scaler, metrics

    def train_batch(self, batch: Dict[str, Any], **loss_kwargs):
        """One full optimizer step over a global batch
        [train_batch_size, ...] (reference: PipelineEngine.train_batch
        naming; for the base engine this fuses fwd+bwd+step).

        ``loss_kwargs``: extra keyword operands forwarded to
        ``loss_fn(model, params, batch, rng, train, **loss_kwargs)`` as
        TRACED arrays (stable shapes across steps -> no recompiles, no
        per-microbatch splitting, no batch-dim constraint). The channel
        for inputs that aren't per-example data — e.g. the other model's
        parameters in adversarial (GAN) training, auxiliary targets, or
        schedule scalars."""
        cfg = self.config
        gas = cfg.gradient_accumulation_steps
        micro_global = cfg.train_micro_batch_size_per_gpu * self.dp_world_size
        nproc = jax.process_count()
        local_rows = gas * micro_global // nproc  # this host's slice

        # Curriculum learning: step the difficulty, then TRUNCATE the batch
        # seq dim to it (reference: engine.py:1609-1615 passes
        # curriculum_seqlen into the model forward, which truncates).
        # Difficulties are bucketed by the scheduler so XLA sees only a few
        # shapes, each compiled once and cached by jit.
        if (self.curriculum_scheduler is not None
                and self.curriculum_scheduler.config.curriculum_type == "seqlen"):
            seqlen = self.curriculum_scheduler.update_difficulty(
                self.global_steps + 1)
            batch = jax.tree.map(
                lambda x: x[:, :seqlen]
                if (hasattr(x, "ndim") and x.ndim >= 2
                    and x.shape[1] > seqlen) else x, batch)

        def to_micro(x):
            x = np.asarray(x) if nproc > 1 else jnp.asarray(x)
            if x.shape[0] != local_rows:
                raise ValueError(
                    f"batch leading dim {x.shape[0]} != "
                    f"{'per-host share of ' if nproc > 1 else ''}train_batch_size "
                    f"{local_rows}")
            return x.reshape(gas, micro_global // nproc, *x.shape[1:])
        obs = self.observability
        if obs is not None:
            obs.begin_step(self.global_steps + 1)
            self._tokens_per_step = _count_tokens(batch, cfg.train_batch_size)
        with _span("data"), _goodput("data_stall"):
            batch = jax.tree.map(to_micro, batch)
            batch = self._place_batch(batch, with_gas_dim=True)

        self.tput_timer.start()
        if self.resilience is not None:
            self.resilience.on_step_start()
        self._ensure_params_resident()
        self._sync_activation_quantization()
        scaler = self.loss_scale_state or init_loss_scale(1.0)
        rng = jax.random.fold_in(self.rng, self.global_steps + 1)
        extra = dict(loss_kwargs)
        if (self.progressive_layer_drop is not None
                and self._loss_accepts("layer_keep_prob")):
            theta = self.progressive_layer_drop.update_state(self.global_steps)
            extra["layer_keep_prob"] = jnp.float32(theta)  # traced: no recompile
        self._remember_extra(extra, loss_kwargs)
        if (self.moq_quantizer is not None
                and self.moq_quantizer.config.eigenvalue_enabled
                and self.config.eigenvalue.enabled):
            self._last_eval_batch = jax.tree.map(lambda x: x[0], batch)
        # the fused jit is one program, so host-side it is one span;
        # the fwd / bwd / optimizer split lives in the device profile
        # (named_scope above) and in the split calling convention
        with _span("fwd_bwd_step"), _goodput("compute"):
            try:
                if self.native_offload is not None:
                    new_scaler, metrics = self._native_offload_batch(
                        batch, scaler, rng, extra)
                else:
                    if "train_step" not in self._compiled:
                        self._compiled["train_step"] = track_program(
                            "train/train_step", self._make_train_step(),
                            subsystem="train")
                    step_fn = self._compiled["train_step"]
                    self.params, self.optimizer_state, new_scaler, metrics = \
                        step_fn(self.params, self.optimizer_state, scaler,
                                batch, rng, extra)
            except Exception as err:
                # allocation failures get a forensics dump (attribution
                # + program table) before the error propagates
                self._note_dispatch_failure(err)
                raise
        if self.fp16_enabled:
            self.loss_scale_state = new_scaler
            self._accumulate_skipped(metrics["skipped"])

        self.global_steps += 1
        self.micro_steps += gas
        self.global_samples += cfg.train_batch_size
        self._apply_weight_projections()
        self.tput_timer.stop(global_step=True)
        self._last_loss = metrics["loss"]
        self._last_grad_norm = metrics["grad_norm"]
        if obs is not None:
            self._observe_step(metrics)

        if (cfg.flops_profiler.enabled
                and self.global_steps == cfg.flops_profiler.profile_step):
            self._print_flops_profile(batch)

        if self.global_steps % cfg.steps_per_print == 0:
            self._report_step(metrics)
        self._write_monitor(metrics)
        self._evict_params_to_nvme()
        if self.tiering is not None:
            self.params, self.optimizer_state = self.tiering.stage_out(
                self.params, self.optimizer_state)
        if self.resilience is not None:
            # device-side health fold every step; host check (and possible
            # rollback) only on the bounded check_interval cadence
            self.resilience.on_step_end(metrics)
        return metrics["loss"]

    def _sync_activation_quantization(self):
        """Toggle activation fake-quant at its schedule_offset (reference:
        basic_layer.py:424 applies it in every compressed layer's forward
        once enabled). Model forwards read a module-level rule table;
        crossing the offset flips it and drops the compiled step so the
        next call retraces with quantized activations — one recompile per
        toggle, zero cost inside the step."""
        from ..models.layers import set_activation_quantization
        comp = self.compression_scheduler
        aq = comp.config.activation_quantization if comp is not None else None
        on = bool(aq is not None and aq.enabled
                  and self.global_steps >= aq.schedule_offset)
        # ALWAYS re-assert the table (the rule table is process-global:
        # this also clears rules another engine left behind — e.g. a
        # distillation teacher built after a quantized student must not
        # inherit the student's 4-bit forward)
        if on:
            set_activation_quantization([
                {"modules": g.modules,
                 "bits": int(g.params.get("bits", 8)),
                 "symmetric": g.params.get("quantization_type",
                                           "symmetric") == "symmetric"}
                for g in aq.groups.values()] or
                [{"modules": ["*"], "bits": 8, "symmetric": True}])
        else:
            set_activation_quantization(None)
        if on == self._act_quant_on:
            return
        self._act_quant_on = on
        for key in ("train_step", "fwd_grads", "eval", "grad_step"):
            self._compiled.pop(key, None)

    def _apply_weight_projections(self):
        """Gas-boundary weight projections (reference: compression
        scheduler stepped at engine.py:1885; MoQ quantize applied during
        training): fake-quant / pruning masks / bit-annealed snap applied
        to the freshly stepped params. Pure jitted projections — sharding
        follows the inputs."""
        step = self.global_steps
        if (self.compression_scheduler is not None
                and self.compression_scheduler.active(step)):
            self.params = self.compression_scheduler.apply(self.params, step)
        if self.moq_quantizer is not None:
            if (self.moq_quantizer.config.eigenvalue_enabled
                    and self.config.eigenvalue.enabled
                    and step >= self._next_eigenvalue_step):
                self._refresh_moq_eigenvalue_ratios()
            self.params = self.moq_quantizer.quantize(self.params, step)

    def _refresh_moq_eigenvalue_ratios(self):
        """Power-iteration curvature ratios for MoQ's eigenvalue mode
        (reference: engine computes eigenvalues at gas boundaries every
        gas_boundary_resolution steps; here refreshed once per quantize
        period — the only boundaries where ratios change bits). The HVP
        power loop re-traces per refresh (params/batch change), bounded
        to once per quantize_period."""
        ev_cfg = self.config.eigenvalue
        if self._eigenvalue is None:
            from .eigenvalue import Eigenvalue
            self._eigenvalue = Eigenvalue(
                verbose=ev_cfg.verbose, max_iter=ev_cfg.max_iter,
                tol=ev_cfg.tol, stability=ev_cfg.stability,
                gas_boundary_resolution=ev_cfg.gas_boundary_resolution,
                layer_name=ev_cfg.layer_name, layer_num=ev_cfg.layer_num)
        if self._last_eval_batch is None:
            return
        from .eigenvalue import post_process_eigenvalues
        model, loss_fn, rng = self.module, self._loss_fn, self.rng
        if any(isinstance(leaf, jax.ShapeDtypeStruct)
               for leaf in jax.tree.leaves(self._last_extra,
                                           is_leaf=lambda x: isinstance(
                                               x, jax.ShapeDtypeStruct))):
            from ..utils.logging import warn_once
            warn_once("MoQ eigenvalue refresh skipped: loss_kwargs operands "
                      "are remembered only abstractly (live cross-engine "
                      "buffers must not be retained between steps) and the "
                      "HVP loop needs their values")
            return
        mb, extra = self._last_eval_batch, dict(self._last_extra)
        values = self._eigenvalue.compute_eigenvalue(
            lambda p: loss_fn(model, p, mb, rng, True, **extra),
            self.params, rng)
        ratios = post_process_eigenvalues(values)
        if ev_cfg.layer_num:
            # component-exact keys ("'h_1'" not "h_1") so layer 1 cannot
            # swallow layers 10..19 by substring
            self.moq_quantizer.layer_ratios = {
                f"'{ev_cfg.layer_name}_{i}'": r for i, r in enumerate(ratios)}
        elif ratios:
            self.moq_quantizer.layer_ratios = {"": ratios[0]}
        period = max(self.moq_quantizer.config.quantize_period, 1)
        self._next_eigenvalue_step = self.global_steps + period

    # ------------------------------------------------------------------
    # reference-style forward / backward / step calling convention
    # ------------------------------------------------------------------

    def forward(self, batch: Dict[str, Any], **loss_kwargs):
        """Compute loss AND cache grads for the following backward()
        (autodiff needs the forward anyway; caching avoids recompute).
        Applies the same curriculum truncation / PLD theta as the fused
        train_batch path. ``loss_kwargs`` is the same traced extra-operand
        channel train_batch accepts (see there) — both calling
        conventions stay capability-equal."""
        self._ensure_params_resident()
        self._sync_activation_quantization()
        if "fwd_grads" not in self._compiled:
            model, loss_fn = self.module, self._loss_fn
            fp16 = self.fp16_enabled

            def fwd(params, batch, rng, scale, extra):
                # fp16: differentiate the SCALED loss (underflow
                # protection — the whole point of loss scaling; grads come
                # back scaled and step() unscales), return the raw loss
                def lf(p):
                    l = loss_fn(model, p, batch, rng, True, **extra)
                    return l * scale if fp16 else l

                scaled_loss, grads = jax.value_and_grad(lf)(params)
                return (scaled_loss / scale if fp16 else scaled_loss), grads
            # ZeRO stage >= 2: grads leave the step already in the ZeRO
            # partition, so the host-persistent accumulation buffer
            # (self._accum_grads, carried across backward() calls) is
            # sharded like the opt state instead of replicated — the
            # parity-API analog of the fused path's scan-carry constraint.
            # With offloaded params, host-space grad leaves keep their
            # own placement (None = unconstrained), mirroring the fused
            # path's per-leaf _offload_mask handling.
            grad_out = None
            if self.zero_stage >= 2 and self.native_offload is None:
                grad_out = self._zero_grad_shardings(self.zero_stage)
                if getattr(self, "_offload_params", False):
                    grad_out = jax.tree.map(
                        lambda sh, off: None if off else sh,
                        grad_out, self._offload_mask,
                        is_leaf=lambda x: isinstance(x, NamedSharding))
            self._compiled["fwd_grads"] = track_program(
                "train/fwd_grads",
                jax.jit(fwd, out_shardings=None if grad_out is None
                        else (None, grad_out)), subsystem="train")
        if (self.curriculum_scheduler is not None
                and self.curriculum_scheduler.config.curriculum_type == "seqlen"):
            seqlen = self.curriculum_scheduler.update_difficulty(
                self.global_steps + 1)
            batch = jax.tree.map(
                lambda x: x[:, :seqlen]
                if (hasattr(x, "ndim") and x.ndim >= 2
                    and x.shape[1] > seqlen) else x, batch)
        extra = dict(loss_kwargs)
        if (self.progressive_layer_drop is not None
                and self._loss_accepts("layer_keep_prob")):
            theta = self.progressive_layer_drop.update_state(self.global_steps)
            extra["layer_keep_prob"] = jnp.float32(theta)
        self._remember_extra(extra, loss_kwargs)
        if self.observability is not None:
            self.observability.begin_step(self.global_steps + 1)
            # a parity-API optimizer step consumes gas microbatches
            self._tokens_per_step = _count_tokens(
                batch, self.config.train_batch_size)
        with _span("data"), _goodput("data_stall"):
            batch = self._place_batch(batch, with_gas_dim=False)
        rng = jax.random.fold_in(self.rng, self.micro_steps + 1)
        scale = (self.loss_scale_state or init_loss_scale(1.0)).scale
        self.timers(FORWARD_GLOBAL_TIMER).start()
        with _span("fwd"), _goodput("compute"):
            try:
                loss, grads = self._compiled["fwd_grads"](
                    self.params, batch, rng, scale, extra)
            except Exception as err:
                self._note_dispatch_failure(err)
                raise
        self.timers(FORWARD_GLOBAL_TIMER).stop()
        self._pending_grads = grads
        self._last_loss = loss
        return loss

    __call__ = None  # set below

    def backward(self, loss=None):
        """Accumulate the cached microbatch grads (reference:
        engine.backward scales by 1/gas and fires the reduction hooks)."""
        if self._pending_grads is None:
            raise RuntimeError("backward() called without a preceding forward()")
        gas = self.config.gradient_accumulation_steps
        self.timers(BACKWARD_GLOBAL_TIMER).start()
        with _span("bwd"), _goodput("compute"):
            # accumulate in grad_accum_dtype (fp32 default) like the fused
            # path's buffer — summing many /gas-scaled microbatch grads in
            # bf16 rounds the small contributions away
            accum_dtype = jnp.dtype(self.config.data_types.resolve())
            scaled = jax.tree.map(lambda g: (g / gas).astype(accum_dtype),
                                  self._pending_grads)
            if self._accum_grads is None:
                self._accum_grads = scaled
            else:
                self._accum_grads = jax.tree.map(jnp.add, self._accum_grads,
                                                 scaled)
        if self._memory_enabled and not self._grad_buffers_accounted:
            # the parity path's host-persistent accumulation buffer is a
            # real resident allocation — tag it once (shape walk only)
            self._grad_buffers_accounted = True
            from ..observability.memory import get_accountant
            get_accountant().account("train/gradient_buffers",
                                     self._accum_grads)
        self._pending_grads = None
        self._accum_count += 1
        self.micro_steps += 1
        self.timers(BACKWARD_GLOBAL_TIMER).stop()

    def is_gradient_accumulation_boundary(self) -> bool:
        return self._accum_count >= self.config.gradient_accumulation_steps

    def step(self):
        """Apply the optimizer at the gas boundary (reference: engine.step
        -> _take_model_step): unscale the fp16-scaled accumulated grads,
        skip-on-overflow, step, and do the same bookkeeping (samples,
        monitor events, NVMe evict) as the fused train_batch."""
        if not self.is_gradient_accumulation_boundary():
            return
        self.timers(STEP_GLOBAL_TIMER).start()
        if self.resilience is not None:
            self.resilience.on_step_start()
        if self.tiering is not None:
            self.params, self.optimizer_state = self.tiering.stage_in(
                self.params, self.optimizer_state)
        scaler = self.loss_scale_state or init_loss_scale(1.0)
        with _span("step"), _goodput("compute"):
            if self.native_offload is not None:
                gnorm, new_scaler, skipped = self._native_offload_step(scaler)
            else:
                gnorm, new_scaler, skipped = self._device_step(scaler)
        if self.fp16_enabled:
            self.loss_scale_state = new_scaler
            self._accumulate_skipped(skipped)
        self._accum_grads = None
        self._accum_count = 0
        self.global_steps += 1
        self.global_samples += self.config.train_batch_size
        self._last_grad_norm = gnorm
        self._apply_weight_projections()
        self._evict_params_to_nvme()
        if self.tiering is not None:
            self.params, self.optimizer_state = self.tiering.stage_out(
                self.params, self.optimizer_state)
        self.timers(STEP_GLOBAL_TIMER).stop()
        metrics = {"loss": self._last_loss, "grad_norm": gnorm,
                   "skipped": skipped,
                   "loss_scale": scaler.scale if self.fp16_enabled
                   else jnp.float32(1.0)}
        if self.global_steps % self.config.steps_per_print == 0:
            log_dist(f"step={self.global_steps} lr={self.get_lr():.3e} "
                     f"grad_norm={float(gnorm):.3f}", ranks=[0])
        if self.observability is not None:
            self._observe_step(metrics)
        self._write_monitor(metrics)
        if self.resilience is not None:
            self.resilience.on_step_end(metrics)

    def _device_step(self, scaler):
        if "apply_grads" not in self._compiled:
            optimizer, cfg, fp16 = self.optimizer, self.config, self.fp16_enabled
            streamed, lr_schedule = self.streamed_offload, self.lr_schedule

            def apply_step(params, opt_state, scaler, grads):
                if fp16:
                    inv = 1.0 / scaler.scale
                    grads = jax.tree.map(lambda g: g * inv, grads)
                gnorm = jnp.sqrt(sum(
                    jnp.sum(jnp.square(g.astype(jnp.float32)))
                    for g in jax.tree.leaves(grads)))

                def do(op):
                    import optax
                    p, s, g = op
                    if streamed is not None:
                        return streamed.clipped_apply(
                            p, g, s, lr_schedule(s["count"]), gnorm,
                            cfg.gradient_clipping)
                    updates, new_s = optimizer.update(g, s, p)
                    return optax.apply_updates(p, updates), new_s

                if fp16:
                    finite = grads_finite(grads)
                    new_params, new_opt = jax.lax.cond(
                        finite, do, lambda op: (op[0], op[1]),
                        (params, opt_state, grads))
                    new_scaler = update_scale(
                        scaler, finite, dynamic=cfg.fp16.dynamic_loss_scale,
                        scale_window=cfg.fp16.loss_scale_window,
                        hysteresis=cfg.fp16.hysteresis,
                        min_scale=cfg.fp16.min_loss_scale)
                    skipped = jnp.where(finite, 0, 1)
                else:
                    new_params, new_opt = do((params, opt_state, grads))
                    new_scaler, skipped = scaler, jnp.int32(0)
                return new_params, new_opt, new_scaler, gnorm, skipped

            self._compiled["apply_grads"] = track_program(
                "train/apply_grads",
                jax.jit(apply_step, donate_argnums=(0, 1, 3),
                        out_shardings=(self.param_shardings,
                                       self.opt_shardings,
                                       None, None, None)),
                subsystem="train")

        self.params, self.optimizer_state, new_scaler, gnorm, skipped = \
            self._compiled["apply_grads"](self.params, self.optimizer_state,
                                          scaler, self._accum_grads)
        return gnorm, new_scaler, skipped

    def _native_offload_step(self, scaler):
        """Parity-API leg of native ZeRO-Offload: unscale/clip/check the
        accumulated grads on device (mirroring _make_grad_step's
        post-accumulate stage), then run the host cpu_adam step."""
        if "prep_native" not in self._compiled:
            cfg, fp16 = self.config, self.fp16_enabled

            def prep(grads, scaler):
                from ..utils.tree import clip_grads_by_global_norm
                if fp16:
                    inv = 1.0 / scaler.scale
                    grads = jax.tree.map(lambda g: g * inv, grads)
                gnorm = jnp.sqrt(sum(
                    jnp.sum(jnp.square(g.astype(jnp.float32)))
                    for g in jax.tree.leaves(grads)))
                grads = clip_grads_by_global_norm(grads, gnorm,
                                                  cfg.gradient_clipping)
                if fp16:
                    finite = grads_finite(grads)
                    new_scaler = update_scale(
                        scaler, finite, dynamic=cfg.fp16.dynamic_loss_scale,
                        scale_window=cfg.fp16.loss_scale_window,
                        hysteresis=cfg.fp16.hysteresis,
                        min_scale=cfg.fp16.min_loss_scale)
                else:
                    finite, new_scaler = jnp.bool_(True), scaler
                return grads, gnorm, finite, new_scaler

            self._compiled["prep_native"] = track_program(
                "train/prep_native",
                jax.jit(prep, out_shardings=(self.grad_shardings,
                                             None, None, None)),
                subsystem="train")

        grads, gnorm, finite, new_scaler = self._compiled["prep_native"](
            self._accum_grads, scaler)
        lr = (float(self.lr_schedule(self.global_steps))
              if callable(self.lr_schedule) else float(self.lr_schedule))
        # host cpu_adam needs the finite flag on the host (native-offload
        # contract); one sync per optimizer step, not per microbatch.
        new_params = self.native_offload.step(grads, lr=lr,
                                              finite=bool(finite))  # ds-tpu: lint-ok[TS002]
        if new_params is not None:
            self.params = new_params
        return gnorm, new_scaler, jnp.int32(0 if bool(finite) else 1)  # ds-tpu: lint-ok[TS002]

    def eval_batch(self, batch: Dict[str, Any], **loss_kwargs):
        self._ensure_params_resident()
        self._sync_activation_quantization()
        if "eval" not in self._compiled:
            model, loss_fn = self.module, self._loss_fn
            self._compiled["eval"] = track_program(
                "train/eval",
                jax.jit(lambda p, b, e: loss_fn(model, p, b,
                                                jax.random.PRNGKey(0),
                                                False, **e)),
                subsystem="train")
        batch = self._place_batch(batch, with_gas_dim=False)
        return self._compiled["eval"](self.params, batch, loss_kwargs)

    # ------------------------------------------------------------------
    # accessors (reference: engine.py:464-762 config property zoo)
    # ------------------------------------------------------------------

    def get_lr(self):
        return float(self.lr_schedule(self.global_steps))

    def get_loss_scale(self):
        return float(self.loss_scale_state.scale) if self.fp16_enabled else 1.0

    def zero_optimization_stage(self):
        return self.zero_stage

    def train_micro_batch_size_per_gpu(self):
        return self.config.train_micro_batch_size_per_gpu

    def train_batch_size(self):
        return self.config.train_batch_size

    def gradient_accumulation_steps(self):
        return self.config.gradient_accumulation_steps

    def get_global_grad_norm(self):
        """Global (pre-clip) grad norm of the most recent step (reference:
        engine.get_global_grad_norm fed by the ZeRO optimizer's
        _global_grad_norm)."""
        if getattr(self, "_last_grad_norm", None) is None:
            return None
        return float(self._last_grad_norm)

    def wall_clock_breakdown(self):
        return self.config.wall_clock_breakdown

    # ------------------------------------------------------------------
    # checkpointing (reference: engine.py:2815 save_checkpoint /
    # :2472 load_checkpoint) — orbax sharded async-capable checkpoints
    # ------------------------------------------------------------------

    def save_checkpoint(self, save_dir, tag=None, client_state=None,
                        save_latest=True, async_save=False):
        """``async_save=True`` snapshots device state synchronously but
        writes files in the background — training continues during the
        write; the ``latest`` tag is published when the save is durable
        (at the next save, or via ``wait_checkpoint()``)."""
        self._ensure_params_resident()
        from .checkpointing import save_engine_checkpoint
        with _span("checkpoint_save"), _goodput("checkpoint_save"):
            return save_engine_checkpoint(self, save_dir, tag=tag,
                                          client_state=client_state,
                                          save_latest=save_latest,
                                          async_save=async_save)

    def wait_checkpoint(self):
        """Join the in-flight async save and publish its latest tag."""
        from .checkpointing import finalize_pending_checkpoint
        return finalize_pending_checkpoint(self)

    def destroy(self):
        """Release engine-held background resources: the async
        checkpointer's worker (after joining any pending save) and the
        NVMe param swapper's aio threads (reference: engine.destroy)."""
        obs = getattr(self, "observability", None)
        if obs is not None:
            obs.close()   # release the module-global tracer if held
        telemetry = getattr(self, "telemetry", None)
        if telemetry is not None:
            self.telemetry = None
            telemetry.stop()   # a destroyed engine must not serve stale state
        from ..observability.memory import get_accountant
        acct = get_accountant()
        for tag in ("train/params", "train/optimizer_state",
                    "train/gradient_buffers"):
            acct.discard(tag)   # a destroyed engine's buffers release
        res = getattr(self, "resilience", None)
        if res is not None:
            self.resilience = None
            res.close()   # uninstall signal handlers, stop the watchdog
        from .checkpointing import close_async_checkpointer
        close_async_checkpointer(self)
        swapper = getattr(self, "_param_swapper", None)
        if swapper is not None:
            self._param_swapper = None
            swapper.close()
        tiering = getattr(self, "tiering", None)
        if tiering is not None:
            self.tiering = None
            tiering.close()
        native = getattr(self, "native_offload", None)
        if native is not None:
            inner = getattr(native, "swapper", None)
            if inner is not None:
                native.swapper = None
                inner.close()

    def load_checkpoint(self, load_dir, tag=None,
                        load_optimizer_states=True, load_lr_scheduler_states=True,
                        load_module_only=False):
        # the loaded params supersede any NVMe-evicted copies: just drop
        # the on-disk flag (restore templates come from _param_shapes, so
        # paging the stale tree back in would be wasted SSD traffic)
        self._params_on_disk = False
        if self.tiering is not None:
            # disk-tier moment placeholders must be concrete before the
            # restore template is built; the restored values re-evict at
            # the next step's stage_out
            self.params, self.optimizer_state = self.tiering.stage_in(
                self.params, self.optimizer_state)
        self.wait_checkpoint()   # an in-flight async save must land first
        from .checkpointing import load_engine_checkpoint
        return load_engine_checkpoint(self, load_dir, tag=tag,
                                      load_optimizer_states=load_optimizer_states,
                                      load_module_only=load_module_only)

    def _zero3_consolidated_16bit_state_dict(self, dtype=jnp.bfloat16):
        """Gather the FULL (unsharded) params host-side, floating leaves
        downcast to ``dtype`` (reference: engine.py:3132 — there via
        GatheredParameters contexts walking every ZeRO-3 shard; here
        ``jax.device_get`` on a sharded array materializes the complete
        logical value, the all-gather the reference hand-codes)."""
        self._ensure_params_resident()
        import numpy as np
        multihost = jax.process_count() > 1

        def one(x):
            if multihost and hasattr(x, "sharding"):
                # device_get raises on arrays whose shards live on other
                # hosts; allgather materializes the full value per process
                from jax.experimental import multihost_utils
                arr = np.asarray(
                    multihost_utils.process_allgather(x, tiled=True))
            else:
                arr = jax.device_get(x)
            if np.issubdtype(arr.dtype, np.floating):
                arr = np.asarray(arr, jnp.dtype(dtype))
            return arr
        return jax.tree.map(one, self.params)

    def save_16bit_model(self, save_dir, save_filename="model_states.msgpack",
                         dtype=jnp.bfloat16):
        """Write the consolidated half-precision model weights as one flax
        msgpack file — loadable without this engine, any mesh, or ZeRO
        metadata (reference: save_16bit_model, engine.py:3202, the
        serving-handoff export). Returns the path."""
        import os
        from flax import serialization
        sd = self._zero3_consolidated_16bit_state_dict(dtype=dtype)
        path = os.path.join(save_dir, save_filename)
        # every process gathers (collective), process 0 alone writes —
        # concurrent writers on a shared filesystem would tear the file
        if jax.process_index() == 0:
            os.makedirs(save_dir, exist_ok=True)
            with open(path, "wb") as f:
                f.write(serialization.to_bytes(sd))
        log_dist(f"16-bit model saved to {path}", ranks=[0])
        return path

    # ------------------------------------------------------------------

    def _print_flops_profile(self, placed_batch):
        """FLOPS profile of the actual compiled train step at profile_step
        (reference: FlopsProfiler printed from engine.py:1599/:1976 —
        there by functional monkey-patching, here from XLA cost analysis
        of the very executable that runs)."""
        import numpy as np
        try:
            scaler = self.loss_scale_state or init_loss_scale(1.0)
            rng = jax.random.fold_in(self.rng, self.global_steps)
            if self.native_offload is not None:
                lowered = self._compiled["grad_step"].lower(
                    self.params, scaler, placed_batch, rng, self._last_extra)
            else:
                lowered = self._compiled["train_step"].lower(
                    self.params, self.optimizer_state, scaler,
                    placed_batch, rng, self._last_extra)
            compiled = lowered.compile()
            cost = compiled.cost_analysis() or {}
            if isinstance(cost, list):
                cost = cost[0] if cost else {}
            flops = float(cost.get("flops", 0.0))
            n_params = int(sum(np.prod(x.shape)
                               for x in jax.tree.leaves(self.params)))
            step_s = self.tput_timer.avg_step_time() if hasattr(
                self.tput_timer, "avg_step_time") else None
            line = (f"flops profiler @ step {self.global_steps}: "
                    f"params={n_params/1e6:.1f}M "
                    f"train-step flops={flops/1e9:.2f}G "
                    f"bytes={float(cost.get('bytes accessed', 0))/1e9:.2f}G")
            if step_s:
                line += f" achieved={flops/step_s/1e12:.1f} TFLOPS"
            log_dist(line, ranks=[0])
            # per-module tree (reference: print_model_profile's module
            # rows, profiler.py:88-113/481) from HLO op_name metadata —
            # own try so a parse failure never loses the summary line
            table = ""
            if self.config.flops_profiler.module_depth != 0:
                try:
                    from ..profiling.flops_profiler import (
                        per_module_breakdown, format_module_profile,
                        params_by_module)
                    depth = self.config.flops_profiler.module_depth
                    breakdown = per_module_breakdown(
                        compiled, max_depth=depth if depth > 0 else 4)
                    table = format_module_profile(
                        breakdown, params_by_module(
                            self.params,
                            max_depth=depth if depth > 0 else 4))
                    log_dist("per-module profile:\n" + table, ranks=[0])
                except Exception as e:
                    logger.warning(f"per-module profile failed: {e}")
            out_file = self.config.flops_profiler.output_file
            if out_file and jax.process_index() == 0:
                with open(out_file, "w") as f:
                    f.write(line + "\n")
                    if table:
                        f.write(table + "\n")
                    for k, v in sorted(cost.items()):
                        f.write(f"{k}: {v}\n")
        except Exception as e:  # profiling must never kill training
            logger.warning(f"flops profiler failed: {e}")

    def _report_step(self, metrics):
        # Caller gates this to the steps_per_print cadence; materializing
        # the scalars here is the logging sync, not a per-step one.
        loss = float(metrics["loss"])  # ds-tpu: lint-ok[TS002]
        extra = ""
        if self.fp16_enabled:
            extra = f" loss_scale={float(metrics['loss_scale']):.0f}"  # ds-tpu: lint-ok[TS002]
        log_dist(
            f"step={self.global_steps} loss={loss:.4f} "
            f"lr={self.get_lr():.3e} grad_norm={float(metrics['grad_norm']):.3f}"  # ds-tpu: lint-ok[TS002]
            f"{extra} samples/sec={self.tput_timer.avg_samples_per_sec():.1f}",
            ranks=[0])
        if self.config.wall_clock_breakdown:
            self.timers.log([FORWARD_GLOBAL_TIMER, BACKWARD_GLOBAL_TIMER,
                             STEP_GLOBAL_TIMER])

    # ------------------------------------------------------------------
    # observability (deepspeed_tpu/observability/, docs/observability.md)
    # ------------------------------------------------------------------

    def _observe_step(self, metrics):
        """Post-step observability hook: the bounded-cadence device
        probe (the ONLY sync this subsystem ever performs —
        ``DeviceProbe.host_reads`` counts it, the trace-probe test
        asserts it) + a host wall-clock step-time sample, then the
        perf/registry flush on the metrics cadence."""
        obs = self.observability
        obs.end_step(self.global_steps, sync_value=metrics["loss"],
                     tokens=self._tokens_per_step)
        if self.global_steps % obs.metrics_interval == 0:
            self._flush_perf_metrics()

    def _flush_perf_metrics(self):
        """Throughput/MFU gauges into the shared registry and the
        monitor fan-out (host floats only — nothing here reads the
        device). The per-step FLOPs figure resolves lazily from the
        static estimator once batch geometry is known."""
        obs = self.observability
        perf = obs.perf
        if perf.flops_per_step is None and perf.tokens_per_step:
            from ..profiling.flops_profiler import (_count_params,
                                                    estimate_step_flops)
            mcfg = getattr(self.module, "config", None)
            batch_size = self.config.train_batch_size or 1
            perf.flops_per_step = estimate_step_flops(
                _count_params(self._param_shapes), batch_size,
                perf.tokens_per_step // batch_size,
                n_layers=getattr(mcfg, "n_layers", 0) or 0,
                d_model=getattr(mcfg, "d_model", 0) or 0)
        reg = obs.registry
        reg.gauge("train/global_steps").set(self.global_steps)
        reg.gauge("train/samples").set(self.global_samples)
        for key, value in perf.summary().items():
            reg.gauge(f"train/{key}").set(value)
        reg.flush_to_monitor(self.monitor, self.global_samples)

    def _account_static_memory(self):
        """Tag this engine's long-lived device buffers in the process
        HBM accountant (observability/memory.py). Params come from the
        abstract shape tree, optimizer state from leaf metadata — no
        device data is ever read. The fused path's gradients are XLA
        scratch (visible via the program registry's temp_bytes, not
        here); the parity path's host-persistent accumulation buffer is
        accounted when first materialized in backward()."""
        from ..observability.memory import get_accountant
        acct = get_accountant()
        acct.account("train/params", self._param_shapes)
        opt_state = getattr(self, "optimizer_state", None)
        if opt_state is not None:
            acct.account("train/optimizer_state", opt_state)

    def _note_dispatch_failure(self, err):
        """Allocation-failure forensics: when a dispatch dies of device
        OOM, dump the accountant's attribution + the compiled-program
        table + the last live snapshot (observability/memory.py), then
        record the event on the resilience emergency path. Every other
        error passes through untouched — the caller re-raises either
        way."""
        from ..observability.memory import (is_oom_error, oom_forensics,
                                            write_oom_forensics)
        if not is_oom_error(err):
            return
        mem_cfg = self._memory_cfg
        if not self._memory_enabled or (mem_cfg is not None
                                        and not mem_cfg.oom_forensics):
            return
        report = oom_forensics(
            reason=f"step {self.global_steps + 1}: {type(err).__name__}",
            top=mem_cfg.top_buffers if mem_cfg is not None else 8)
        path = (mem_cfg.oom_dump_path
                if mem_cfg is not None and mem_cfg.oom_dump_path
                else "oom_forensics.json")
        try:
            write_oom_forensics(path, report)
            logger.error(
                f"device allocation failure at step {self.global_steps + 1} "
                f"— OOM forensics (attribution + program table) -> {path}")
        except OSError as e:
            logger.error(f"OOM forensics dump failed: {e}")
        if self.resilience is not None:
            self.resilience.on_allocation_failure(path)

    def dump_trace(self, path: str) -> str:
        """Write captured spans as Chrome-trace JSON (load in Perfetto /
        chrome://tracing). Requires the ``observability`` block; see
        ``bin/ds_tpu_trace`` for the windowed-capture CLI."""
        if self.observability is None:
            raise RuntimeError(
                "observability is not enabled — add "
                '{"observability": {"enabled": true}} to the config')
        return self.observability.write_trace(path)

    def metrics_snapshot(self) -> dict:
        """JSON-able registry + perf + probe state (the payload
        ``ds_tpu_trace --metrics-out`` writes and ``ds_tpu_report``
        prints)."""
        if self.observability is None:
            from ..observability import get_registry
            from ..observability.memory import get_accountant
            from ..observability.programs import get_program_registry
            return {"registry": get_registry().snapshot(),
                    "goodput": _goodput_ledger().breakdown(),
                    "memory": get_accountant().report(),
                    "programs": get_program_registry().table()}
        return self.observability.snapshot()

    def _write_monitor(self, metrics):
        """Queue this step's monitor events with the scalars still ON
        DEVICE; they are materialized in one batched transfer at the
        steps_per_print cadence (flush_monitor). The old per-step
        ``float(metrics["loss"])`` here was a hidden host sync every
        step whenever any monitor backend was enabled (ds_tpu_lint
        TS002's first real catch)."""
        if not self.monitor.enabled:
            return
        events = [("Train/Samples/train_loss", metrics["loss"],
                   self.global_samples),
                  ("Train/Samples/lr", self.get_lr(), self.global_samples)]
        if self.fp16_enabled:
            events.append(("Train/Samples/loss_scale",
                           metrics["loss_scale"], self.global_samples))
        self._monitor_buffer.extend(events)
        if self.global_steps % self.config.steps_per_print == 0:
            self.flush_monitor()

    def flush_monitor(self):
        """Materialize queued monitor events (one batched device_get) and
        hand them to the writers. Runs at the steps_per_print cadence,
        from checkpoint save, and on engine teardown; call it directly
        before reading the monitor files mid-run."""
        if not self._monitor_buffer:
            return
        with _span("monitor_flush"):
            values = jax.device_get([v for _, v, _ in self._monitor_buffer])
            events = [(label, float(v), step) for (label, _, step), v
                      in zip(self._monitor_buffer, values)]
            self._monitor_buffer = []
            self.monitor.write_events(events)

    def __del__(self):
        # Tail events after the last cadence boundary must not be lost
        # when training ends without a final checkpoint. Teardown may run
        # at interpreter shutdown with the backend half-dead — best
        # effort only, never raise from a destructor.
        try:
            self.flush_monitor()
        except Exception:  # ds-tpu: lint-ok[PY001] — destructor, backend may be gone
            pass


def _count_tokens(global_batch, rows):
    """Token count of one optimizer step from batch SHAPES (host
    metadata only — never reads a buffer): global batch rows x the
    sequence dim of the first >=2-D leaf."""
    for leaf in jax.tree.leaves(global_batch):
        if hasattr(leaf, "ndim") and leaf.ndim >= 2:
            return int(rows) * int(leaf.shape[1])
    return int(rows)


def _init_kwargs(sample_batch):
    """Map a batch dict onto model.init kwargs: by convention our models
    take input_ids positionally; anything else is ignored at init time."""
    if isinstance(sample_batch, dict):
        ids = sample_batch.get("input_ids")
        if ids is None:
            raise DeepSpeedConfigError("sample_batch must contain 'input_ids'")
        return {"input_ids": jnp.asarray(ids)}
    return {"input_ids": jnp.asarray(sample_batch)}


def _host_kind(sharding):
    """One sharding moved to pinned host memory (no-op on CPU backends)."""
    if jax.default_backend() == "cpu":
        return sharding
    try:
        return sharding.with_memory_kind("pinned_host")
    except Exception:
        logger.warning("pinned_host unsupported; param offload inert")
        return sharding


def _with_host_memory(shardings):
    """Move a sharding tree to pinned host memory (ZeRO-Offload analog:
    optimizer shards live in host RAM, reference: cpu_adam +
    stage_1_and_2.py cpu_offload)."""
    from .zero.offload_optimizer import _with_host_memory_tree
    return _with_host_memory_tree(shardings)


def _resolve_adamw(opt_type: str, opt_params: dict) -> bool:
    """Decay semantics shared by every Adam path (optax, native cpu_adam,
    streamed host offload): 'Adam' with weight_decay>0 honors adam_w_mode
    (default True -> decoupled decay), matching build_optimizer so the
    same config trains identically on all three."""
    wd = opt_params.get("weight_decay", 0.0)
    name = opt_type.lower().replace("deepspeed", "").replace("_", "")
    return name == "adamw" or (wd > 0 and opt_params.get("adam_w_mode", True))


# `engine(batch)` == engine.forward(batch), matching the reference's
# module-call convention (engine.py __call__ -> forward).
DeepSpeedEngine.__call__ = DeepSpeedEngine.forward
