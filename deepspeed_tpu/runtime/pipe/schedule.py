"""Pipeline instruction schedules.

Reference surface: deepspeed/runtime/pipe/schedule.py — generator classes
yielding ``PipeInstruction`` lists per step: TrainSchedule (:182, 1F1B),
InferenceSchedule (:129), DataParallelSchedule (:292). The instruction
vocabulary and per-step streams match the reference's contract; the 1F1B
step map here is an independent closed-form derivation from microbatch
launch clocks (see TrainSchedule).

On TPU the *hot path* does not interpret these instruction streams — the
SPMD collective-permute program in pipe/engine.py bakes the schedule into
one jitted computation. The classes serve two real consumers: (a) schedule-semantics tests
(reference test_pipe_schedule.py), and (b) the host-driven executor
(pipe/host_engine.py HostDrivenPipelineEngine) which dispatches these
exact instruction streams for heterogeneous LayerSpec stacks.
"""


class PipeInstruction:
    def __init__(self, **kwargs):
        self.name = self.__class__.__name__
        self.kwargs = kwargs
        for k, v in kwargs.items():
            setattr(self, k, v)

    def __repr__(self):
        kw = [f"{k}={v}" for k, v in self.kwargs.items()]
        return f"{self.name}({', '.join(kw)})"

    def __eq__(self, other):
        return (self.__class__ == other.__class__
                and self.kwargs == other.kwargs)


class OptimizerStep(PipeInstruction):
    pass


class ReduceGrads(PipeInstruction):
    pass


class ReduceTiedGrads(PipeInstruction):
    pass


class BufferOpInstruction(PipeInstruction):
    def __init__(self, buffer_id, **kwargs):
        super().__init__(buffer_id=buffer_id, **kwargs)


class LoadMicroBatch(BufferOpInstruction):
    pass


class ForwardPass(BufferOpInstruction):
    pass


class BackwardPass(BufferOpInstruction):
    pass


class SendActivation(BufferOpInstruction):
    pass


class RecvActivation(BufferOpInstruction):
    pass


class SendGrad(BufferOpInstruction):
    pass


class RecvGrad(BufferOpInstruction):
    pass


class PipeSchedule:
    """Base generator (reference :9)."""

    def __init__(self, micro_batches, stages, stage_id):
        self.micro_batches = micro_batches
        self.stages = stages
        self.stage_id = stage_id
        self.prev_stage = self.stage_id - 1
        self.next_stage = self.stage_id + 1

    def steps(self):
        raise NotImplementedError

    def num_pipe_buffers(self):
        return self.micro_batches

    def _valid_micro_batch(self, micro_batch_id):
        return 0 <= micro_batch_id < self.micro_batches

    def _valid_stage(self, stage_id):
        return 0 <= stage_id < self.stages

    @property
    def stage(self):
        return self.stage_id

    @property
    def num_stages(self):
        return self.stages

    @property
    def num_micro_batches(self):
        return self.micro_batches

    @property
    def is_first_stage(self):
        return self.stage_id == 0

    @property
    def is_last_stage(self):
        return self.stage_id == self.stages - 1

    def _buffer_idx(self, micro_batch_id):
        assert self._valid_micro_batch(micro_batch_id)
        return micro_batch_id % self.num_pipe_buffers()

    def __iter__(self):
        self.it = None
        return self

    def __next__(self):
        if self.it is None:
            self.it = self.steps()
        return next(self.it)


class InferenceSchedule(PipeSchedule):
    """Forward-only pipelining: microbatch m reaches stage s at clock
    s + m (one hop per clock, a new microbatch every clock — no backward
    lane, so no alternation and no 2x clock stretch)."""

    def steps(self):
        for step_id in range(self.micro_batches + self.stages - 1):
            cmds = []
            micro_batch_id = step_id - self.stage_id
            if self._valid_micro_batch(micro_batch_id):
                buf = self._buffer_idx(micro_batch_id)
                if self.is_first_stage or self.is_last_stage:
                    cmds.append(LoadMicroBatch(buf))
                if not self.is_first_stage:
                    cmds.append(RecvActivation(buf))
                cmds.append(ForwardPass(buf))
                if not self.is_last_stage:
                    cmds.append(SendActivation(buf))
            yield cmds

    def num_pipe_buffers(self):
        return 2


class TrainSchedule(PipeSchedule):
    """1F1B interleaved schedule.

    Derivation (original closed form; produces the reference's exact
    instruction streams, verified slot-for-slot in test_pipe.py): run a
    global pipeline clock t. Microbatch m's FORWARD enters stage 0 at
    clock 2m (one new microbatch every other clock) and advances one
    stage per clock, so stage s computes it at

        t_fwd(s, m) = s + 2m.

    Its BACKWARD leaves the last stage on the clock right after that
    stage's forward and flows back one stage per clock:

        t_bwd(s, m) = (2*stages - 1 - s) + 2m.

    The two launch clocks differ by the odd constant 2*(stages - s) - 1,
    so each stage strictly alternates forward and backward slots —
    inverting whichever identity matches the clock's parity yields the
    slot's microbatch id directly (negative / >= num_micro ids are the
    warmup and drain bubbles)."""

    def _clock_role(self, t):
        """(micro_batch_id, is_forward) for pipeline clock ``t`` at this
        stage; the id is out of range during warmup/drain bubbles."""
        if (t - self.stage_id) % 2 == 0:
            return (t - self.stage_id) // 2, True
        return (t - (2 * self.stages - 1 - self.stage_id)) // 2, False

    def steps(self):
        # every microbatch crosses every stage twice (fwd + bwd): the
        # last backward finishes at t_bwd(0, M-1) = 2(M + S - 1) - 1
        total_steps = 2 * (self.micro_batches + self.stages - 1)
        for step_id in range(total_steps):
            micro_batch_id, is_forward = self._clock_role(step_id)
            cmds = []

            # ship what the PREVIOUS clock produced (slots alternate, so
            # the previous slot ran the opposite direction): a forward's
            # activation goes downstream, a backward's grad upstream
            if step_id > 0:
                prev_micro, prev_fwd = self._clock_role(step_id - 1)
                if self._valid_micro_batch(prev_micro):
                    buf = self._buffer_idx(prev_micro)
                    if prev_fwd and not self.is_last_stage:
                        cmds.append(SendActivation(buf))
                    elif not prev_fwd and not self.is_first_stage:
                        cmds.append(SendGrad(buf))

            if self._valid_micro_batch(micro_batch_id):
                buf = self._buffer_idx(micro_batch_id)
                # receive this slot's operand from the neighbor that
                # produced it on the previous clock
                if is_forward and not self.is_first_stage:
                    cmds.append(RecvActivation(buf))
                elif not is_forward and not self.is_last_stage:
                    cmds.append(RecvGrad(buf))
                if is_forward:
                    if self.is_first_stage or self.is_last_stage:
                        cmds.append(LoadMicroBatch(buf))
                    cmds.append(ForwardPass(buf))
                else:
                    cmds.append(BackwardPass(buf))

            # model step once the drain completes
            if step_id == total_steps - 1:
                cmds.append(ReduceTiedGrads())
                cmds.append(ReduceGrads())
                cmds.append(OptimizerStep())

            yield cmds

    def num_pipe_buffers(self):
        """Live activations at stage s: forwards run ahead of backwards
        by the clock gap t_bwd - t_fwd = 2(stages - s) - 1, i.e. roughly
        stages - s microbatches are in flight before the first grad
        returns (capped by the microbatch count, floored at double
        buffering)."""
        return max(2, min(self.stages - self.stage_id + 1,
                          self.micro_batches))


class DataParallelSchedule(PipeSchedule):
    """Degenerate single-stage schedule: no pipelining, every microbatch
    is a load/forward/backward on one buffer, with the reduce+step after
    the last one."""

    def steps(self):
        for step_id in range(self.micro_batches):
            cmds = [LoadMicroBatch(0), ForwardPass(0), BackwardPass(0)]
            if step_id == self.micro_batches - 1:
                cmds.extend([ReduceGrads(), OptimizerStep()])
            yield cmds

    def num_pipe_buffers(self):
        return 1
