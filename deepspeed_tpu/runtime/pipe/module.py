"""Pipeline module description.

Reference: deepspeed/runtime/pipe/module.py — PipelineModule (:85) takes a
list of LayerSpec (:23) / TiedLayerSpec (:71) and partitions them across
stages (_partition_layers :361, methods uniform / parameters / type:regex).

TPU-native: the hot path executes the pipeline as ONE SPMD program over the
mesh's "stage" axis (pipe/engine.py), which requires the repeated trunk to
be homogeneous — exactly the transformer case. A PipelineModule therefore
describes three sections:

  embed  - first-stage-only prologue (token/pos embeddings)
  block  - ONE flax module repeated ``n_blocks`` times; its stacked params
           [n_blocks, ...] shard over the "stage" axis
  head   - last-stage epilogue (final LN + LM head) + loss_fn

A generic LayerSpec list is also accepted and partitioned with the
reference's methods; homogeneous runs auto-collapse into the block form
(the SPMD fast path), and heterogeneous stacks execute the 1F1B
instruction stream on the host-driven engine (pipe/host_engine.py).
"""

import re
from typing import Callable, Optional

import numpy as np

from ...utils.logging import logger


class LayerSpec:
    """Deferred layer construction (reference :23)."""

    def __init__(self, typename, *module_args, **module_kwargs):
        self.typename = typename
        self.module_args = module_args
        self.module_kwargs = module_kwargs
        if not issubclass(typename, object):
            raise RuntimeError("LayerSpec typename must be a class")

    def build(self, log=False):
        if log:
            logger.info(f"building {repr(self)}")
        return self.typename(*self.module_args, **self.module_kwargs)

    def __repr__(self):
        return f"LayerSpec({self.typename.__name__})"


class TiedLayerSpec(LayerSpec):
    """Layer whose params are shared across stages by ``key``
    (reference :71). In the functional engine, tying is free: the tied
    params appear once in the pytree and autodiff sums their gradients —
    the reference's tied-weight allreduce (module.py:417) is implicit."""

    def __init__(self, key, typename, *module_args, forward_fn=None,
                 tied_weight_attr="weight", **module_kwargs):
        super().__init__(typename, *module_args, **module_kwargs)
        self.key = key
        self.forward_fn = forward_fn
        self.tied_weight_attr = tied_weight_attr


def partition_balanced(weights, num_parts):
    """Split ``weights`` into ``num_parts`` contiguous chunks minimizing the
    max chunk weight (reference: deepspeed/runtime/utils.py
    partition_balanced / prefix-sum + binary search)."""
    weights = list(weights)
    n = len(weights)
    if num_parts > n:
        raise ValueError(f"cannot split {n} layers into {num_parts} parts")
    prefix = np.concatenate([[0], np.cumsum(weights)])

    def can_split(limit):
        parts, start = 0, 0
        for i in range(1, n + 1):
            if prefix[i] - prefix[start] > limit:
                parts += 1
                start = i - 1
                if prefix[i] - prefix[start] > limit:
                    return None
        parts += 1
        return parts <= num_parts

    lo = max(weights) if weights else 0
    hi = prefix[-1]
    while lo < hi:
        mid = (lo + hi) // 2
        if can_split(mid):
            hi = mid
        else:
            lo = mid + 1

    # build boundaries greedily under limit lo, then pad to num_parts
    bounds = [0]
    start = 0
    for i in range(1, n + 1):
        if prefix[i] - prefix[start] > lo:
            bounds.append(i - 1)
            start = i - 1
    bounds.append(n)
    while len(bounds) < num_parts + 1:
        # split the largest remaining part
        sizes = [(bounds[j + 1] - bounds[j], j) for j in range(len(bounds) - 1)]
        _, j = max(sizes)
        mid = (bounds[j] + bounds[j + 1]) // 2
        bounds.insert(j + 1, mid)
        bounds = sorted(set(bounds))
    return bounds[:num_parts + 1]


class PipelineModule:
    """Pipeline-parallel model description (reference :85)."""

    def __init__(self, layers=None, num_stages=None, topology=None,
                 loss_fn: Optional[Callable] = None,
                 partition_method: str = "parameters",
                 activation_checkpoint_interval: int = 0,
                 seed_layers=False, base_seed=1234,
                 *, embed=None, block=None, n_blocks: Optional[int] = None,
                 head=None):
        self.loss_fn = loss_fn
        self.partition_method = partition_method
        self.activation_checkpoint_interval = activation_checkpoint_interval
        self.topology = topology
        self.num_stages = num_stages or (topology.get_dim("pipe")
                                         if topology else 1)
        self.embed = embed
        self.block = block
        self.n_blocks = n_blocks
        self.head = head
        self._layer_specs = list(layers) if layers is not None else None
        self.parts = None

        if self._layer_specs is not None:
            self._partition_layers()
            if self.block is None:
                self._try_collapse_homogeneous()

        # Heterogeneous LayerSpec stacks keep their per-stage partitions and
        # run on the host-driven schedule executor
        # (pipe/host_engine.py HostDrivenPipelineEngine); homogeneous stacks
        # collapse to the fused SPMD fast path (pipe/engine.py).
        self.heterogeneous = self.block is None
        if self.heterogeneous and self._layer_specs is None:
            raise ValueError(
                "PipelineModule needs either embed=/block=/n_blocks=/head= "
                "or a LayerSpec list")
        if not self.heterogeneous and self.n_blocks % self.num_stages != 0:
            raise ValueError(
                f"n_blocks={self.n_blocks} must divide evenly over "
                f"{self.num_stages} stages")

    def build_stage_layers(self):
        """Build every LayerSpec and group them per stage by the partition
        boundaries (reference: _partition_layers' local layer build,
        module.py:361). Returns list[stage] -> list of built modules."""
        if self._layer_specs is None:
            raise ValueError("build_stage_layers needs a LayerSpec list")
        built = [s.build() if isinstance(s, LayerSpec) else s
                 for s in self._layer_specs]
        return [built[self.parts[s]:self.parts[s + 1]]
                for s in range(self.num_stages)]

    # -- reference-parity partition bookkeeping ------------------------

    def _layer_weights(self):
        method = self.partition_method.lower()
        specs = self._layer_specs
        if method == "uniform":
            return [1] * len(specs)
        if method == "parameters":
            weights = []
            for spec in specs:
                n = 1
                kw = spec.module_kwargs if isinstance(spec, LayerSpec) else {}
                cfg = kw.get("config") or (spec.module_args[0]
                                           if isinstance(spec, LayerSpec)
                                           and spec.module_args else None)
                if hasattr(cfg, "num_params"):
                    n = cfg.num_params()
                elif hasattr(spec, "num_params"):
                    n = spec.num_params
                weights.append(max(int(n), 1))
            return weights
        if method.startswith("type:"):
            pattern = method.split(":", 1)[1]
            return [1 if re.search(pattern, spec.typename.__name__,
                                   re.IGNORECASE) else 0
                    for spec in self._layer_specs]
        raise NotImplementedError(f"partition method {self.partition_method}")

    def _partition_layers(self):
        weights = self._layer_weights()
        self.parts = partition_balanced(weights, self.num_stages)
        logger.info(f"pipeline partition boundaries: {self.parts}")

    def _try_collapse_homogeneous(self):
        """Detect [embed?] + N*Block + [head...] shape in a LayerSpec list."""
        specs = self._layer_specs

        def same(a, b):
            # identical construction -> one shared module repeated (the
            # stacked-scan representation requires equal param shapes)
            return (a.typename is b.typename
                    and a.module_args == b.module_args
                    and a.module_kwargs == b.module_kwargs)

        # longest run of one repeated (type, args) spec
        best_start, best_len = 0, 0
        i = 0
        while i < len(specs):
            j = i
            while j < len(specs) and same(specs[j], specs[i]):
                j += 1
            if j - i > best_len:
                best_start, best_len = i, j - i
            i = j
        if best_len < self.num_stages:
            return
        self.n_blocks = best_len
        self.block = specs[best_start].build()
        pre = [s.build() for s in specs[:best_start]]
        post = [s.build() for s in specs[best_start + best_len:]]
        self.embed = _Sequential(pre) if pre else None
        self.head = _Sequential(post) if post else None

    def stage_of_layer(self, layer_idx: int) -> int:
        if self.parts is None:
            per = self.n_blocks // self.num_stages
            return min(layer_idx // per, self.num_stages - 1)
        for s in range(self.num_stages):
            if self.parts[s] <= layer_idx < self.parts[s + 1]:
                return s
        raise IndexError(layer_idx)

    def ckpt_prefix(self, checkpoint_engine_tag, layer_idx):
        """Layer-file naming parity (reference module.py:529)."""
        return f"layer_{layer_idx:02d}-model_states.pt"


class _Sequential:
    """Minimal callable chain for pre/post sections built from specs."""

    def __init__(self, modules):
        self.modules = modules

    def __call__(self, *args, **kwargs):
        out = args
        for m in self.modules:
            out = m(*out) if isinstance(out, tuple) else m(out)
            out = (out,)
        return out[0]
