"""Host-driven pipeline schedule executor.

Reference: PipelineEngine._exec_schedule (runtime/pipe/engine.py:1354)
dispatching the TrainSchedule instruction stream through _INSTRUCTION_MAP
(:1341) — LoadMicroBatch / ForwardPass / BackwardPass / Send*/Recv* /
ReduceGrads / OptimizerStep.

This engine executes that SAME instruction stream host-side, one jitted
program per stage-compute instruction, which is what makes heterogeneous
``LayerSpec`` stacks (different module types per stage — the reference's
type:regex / parameters partitions, module.py:361) runnable: each stage
is its own params/apply pair, no stacked-scan homogeneity required.

Differences from the SPMD fast path (pipe/engine.py), by design:
- Send/Recv are mailbox moves between host-tracked buffers — on one JAX
  client the arrays already live on the right devices; the instructions
  still execute so the schedule semantics (buffer lifetime, 1F1B
  ordering) are faithfully exercised.
- BackwardPass recomputes the stage forward (activation-checkpointing
  semantics — the reference runs pipelines with AC enabled for the same
  reason): device memory holds only each in-flight microbatch's stage
  INPUT, not its residuals.

The fused SPMD engine remains the fast path for homogeneous trunks.
"""

from typing import Any, Callable, Dict, List, Optional

import numpy as np
import jax
import jax.numpy as jnp

from ... import comm as dist
from ...observability.goodput import timed as _goodput
from ...observability.programs import track_program
from ...observability.trace import span as _span
from ...utils.logging import log_dist
from ..config import DeepSpeedConfig
from ..config_utils import DeepSpeedConfigError
from ..lr_schedules import get_lr_schedule
from ..optimizers import build_optimizer
from .module import PipelineModule
from .schedule import (TrainSchedule, InferenceSchedule, LoadMicroBatch,
                       ForwardPass, BackwardPass, SendActivation,
                       RecvActivation, SendGrad, RecvGrad, ReduceGrads,
                       ReduceTiedGrads, OptimizerStep)


class HostDrivenPipelineEngine:
    """Executes TrainSchedule instruction streams for every stage on one
    JAX client. Construct via ``deepspeed_tpu.initialize`` with a
    heterogeneous ``PipelineModule``."""

    def __init__(self, module: PipelineModule, config, *, loss_fn=None,
                 sample_batch=None, rng=None, optimizer=None,
                 lr_scheduler=None, mesh=None, params=None):
        self.pipe = module
        if isinstance(config, dict):
            config = DeepSpeedConfig.from_dict(config)
        dist.init_distributed()
        # Data parallelism composes with the host-driven schedule: stage
        # params are replicated over the mesh's "data" axis and every
        # micro batch is sharded on its leading dim, so each jitted
        # stage program runs data-parallel and the recompute-vjp's
        # param grads come back already psum'd by SPMD (the reference's
        # ReduceGrads). Other parallel axes do not apply to this
        # executor (stages are host-scheduled, not mesh axes).
        self.mesh = mesh
        self.dp_world_size = 1
        if mesh is not None:
            bad = {a: s for a, s in mesh.shape.items()
                   if a != "data" and s > 1}
            if bad:
                raise DeepSpeedConfigError(
                    "HostDrivenPipelineEngine composes with DATA "
                    f"parallelism only; mesh has non-data axes {bad} — "
                    "use the SPMD PipelineEngine (homogeneous stacks) "
                    "for tp/fsdp/stage meshes")
            self.dp_world_size = mesh.shape.get("data", 1)
        config.resolve_batch_sizes(self.dp_world_size)
        self.config = config
        self.loss_fn = loss_fn or module.loss_fn
        if self.loss_fn is None:
            raise DeepSpeedConfigError("PipelineModule requires a loss_fn")
        self.num_stages = module.num_stages
        self.micro_batches = config.gradient_accumulation_steps
        self.rng = rng if rng is not None else jax.random.PRNGKey(0)
        self.global_steps = 0
        self.global_samples = 0

        self.stage_layers = module.build_stage_layers()
        self._init_params(sample_batch, params)
        self._configure_optimizer(optimizer, lr_scheduler)
        self._compiled: Dict[Any, Any] = {}
        log_dist(
            f"HostDrivenPipelineEngine: stages={self.num_stages} "
            f"micro_batches={self.micro_batches} "
            f"layers/stage={[len(s) for s in self.stage_layers]}", ranks=[0])

    # -- setup ---------------------------------------------------------

    def _init_params(self, sample_batch, prebuilt=None):
        if prebuilt is not None:
            params = self._partition_prebuilt(prebuilt)
            if sample_batch is not None:
                self._validate_prebuilt(params, sample_batch)
        else:
            if sample_batch is None:
                raise DeepSpeedConfigError("HostDrivenPipelineEngine needs "
                                           "sample_batch (or params=)")
            params = self._build_stage_params(self._sample_ids(sample_batch))
        if self.mesh is not None:
            from jax.sharding import NamedSharding, PartitionSpec as P
            rep = NamedSharding(self.mesh, P())
            params = jax.tree.map(lambda a: jax.device_put(a, rep), params)
        self.params = params

    @staticmethod
    def _sample_ids(sample_batch):
        return jnp.asarray(sample_batch["input_ids"]
                           if isinstance(sample_batch, dict)
                           else sample_batch)

    def _build_stage_params(self, ids):
        """Per-stage per-layer variables from the module's init chain —
        run directly for fresh init, or under jax.eval_shape (abstract
        ids, zero FLOPs) as the validation oracle for a pre-built tree."""
        from flax.core import meta as flax_meta
        params: List[List[Any]] = []
        x = ids
        key = self.rng
        for layers in self.stage_layers:
            stage_params = []
            for layer in layers:
                key, sub = jax.random.split(key)
                variables = flax_meta.unbox(layer.init(sub, x))
                stage_params.append(variables)
                x = layer.apply(variables, x)
            params.append(stage_params)
        return params

    def _partition_prebuilt(self, prebuilt):
        """Partition a provided params tree across stages: accepts a FLAT
        list (one variables dict per layer, checkpoint/export order) and
        splits it by this module's stage boundaries, or an already-nested
        [stage][layer] list matching them."""
        from flax.core import meta as flax_meta
        prebuilt = flax_meta.unbox(prebuilt)
        sizes = [len(layers) for layers in self.stage_layers]
        if (isinstance(prebuilt, (list, tuple))
                and len(prebuilt) == self.num_stages
                and all(isinstance(s, (list, tuple)) and len(s) == n
                        for s, n in zip(prebuilt, sizes))):
            return [list(s) for s in prebuilt]
        if isinstance(prebuilt, (list, tuple)) and len(prebuilt) == sum(sizes):
            out, it = [], iter(prebuilt)
            for n in sizes:
                out.append([next(it) for _ in range(n)])
            return out
        raise DeepSpeedConfigError(
            "HostDrivenPipelineEngine params= must be a flat list of "
            f"per-layer variables (len {sum(sizes)}) or a nested "
            f"[stage][layer] list matching stage sizes {sizes}; got "
            f"{type(prebuilt).__name__} of len "
            f"{len(prebuilt) if hasattr(prebuilt, '__len__') else '?'}")

    def _validate_prebuilt(self, params, sample_batch):
        """Fail fast with named leaves on a wrong-dimension checkpoint
        (same contract as the SPMD engine's params= path) instead of an
        opaque XLA shape error inside the first jitted stage."""
        from ...utils.tree import validate_params_tree
        ids = self._sample_ids(sample_batch)
        want = jax.eval_shape(self._build_stage_params,
                              jax.ShapeDtypeStruct(ids.shape, ids.dtype))
        try:
            validate_params_tree(params, want)
        except ValueError as e:
            raise DeepSpeedConfigError(str(e)) from None

    def _place_micro(self, tree):
        """Shard a micro batch's leading dim over the data axis (no-op
        without a mesh; non-divisible leading dims replicate)."""
        if self.mesh is None or self.dp_world_size == 1:
            return tree
        from jax.sharding import NamedSharding, PartitionSpec as P

        def one(x):
            x = jnp.asarray(x)
            if x.ndim == 0:   # scalar leaves replicate (rank-1 specs
                              # are invalid on rank-0 arrays)
                return jax.device_put(x, NamedSharding(self.mesh, P()))
            spec = ("data",) if x.shape[0] % self.dp_world_size == 0 \
                else (None,)
            return jax.device_put(x, NamedSharding(
                self.mesh, P(*spec, *(None,) * (x.ndim - 1))))
        return jax.tree.map(one, tree)

    def _stage_forward(self, s: int):
        """fn(stage_params, x) -> y, jitted once per stage."""
        layers = self.stage_layers[s]

        def fwd(stage_params, x):
            for layer, p in zip(layers, stage_params):
                x = layer.apply(p, x)
            return x
        return fwd

    def _configure_optimizer(self, client_optimizer, client_scheduler):
        cfg = self.config
        base_lr = (cfg.optimizer.params.get("lr", 1e-3)
                   if cfg.optimizer else 1e-3)
        if client_scheduler is not None:
            self.lr_schedule = client_scheduler
        elif cfg.scheduler and cfg.scheduler.type:
            self.lr_schedule = get_lr_schedule(cfg.scheduler.type,
                                               cfg.scheduler.params)
        else:
            self.lr_schedule = lambda step: base_lr
        if client_optimizer is not None:
            self.optimizer = client_optimizer
        else:
            opt_type = cfg.optimizer.type if cfg.optimizer else "Adam"
            opt_params = dict(cfg.optimizer.params) if cfg.optimizer else {}
            self.optimizer = build_optimizer(opt_type, opt_params,
                                             lr_schedule=self.lr_schedule)
        if cfg.gradient_clipping and cfg.gradient_clipping > 0:
            import optax
            self.optimizer = optax.chain(
                optax.clip_by_global_norm(cfg.gradient_clipping),
                self.optimizer)
        self.optimizer_state = self.optimizer.init(self.params)

    # -- jitted per-instruction programs -------------------------------

    def _fwd_prog(self, s):
        key = ("fwd", s)
        if key not in self._compiled:
            self._compiled[key] = track_program(
                f"pipe_host/fwd_stage{s}", jax.jit(self._stage_forward(s)),
                subsystem="pipe_host")
        return self._compiled[key]

    def _last_fwd_prog(self):
        key = ("fwd_last",)
        if key not in self._compiled:
            fwd = self._stage_forward(self.num_stages - 1)
            loss_fn = self.loss_fn

            def run(stage_params, x, batch):
                return loss_fn(fwd(stage_params, x), batch)
            self._compiled[key] = track_program(
                "pipe_host/fwd_last", jax.jit(run), subsystem="pipe_host")
        return self._compiled[key]

    def _bwd_prog(self, s):
        """Recompute-forward vjp: (params_s, x, cotangent) ->
        (dparams_s, dx)."""
        key = ("bwd", s)
        if key not in self._compiled:
            fwd = self._stage_forward(s)

            def run(stage_params, x, cot):
                _, vjp = jax.vjp(fwd, stage_params, x)
                return vjp(cot)
            self._compiled[key] = track_program(
                f"pipe_host/bwd_stage{s}", jax.jit(run),
                subsystem="pipe_host")
        return self._compiled[key]

    def _last_bwd_prog(self):
        key = ("bwd_last",)
        if key not in self._compiled:
            fwd = self._stage_forward(self.num_stages - 1)
            loss_fn = self.loss_fn

            def run(stage_params, x, batch):
                def f(p, xx):
                    return loss_fn(fwd(p, xx), batch)
                _, vjp = jax.vjp(f, stage_params, x)
                return vjp(jnp.float32(1.0 / self.micro_batches))
            self._compiled[key] = track_program(
                "pipe_host/bwd_last", jax.jit(run), subsystem="pipe_host")
        return self._compiled[key]

    # -- the executor --------------------------------------------------

    def train_batch(self, batch):
        cfg = self.config
        ids = jnp.asarray(batch["input_ids"])
        B = ids.shape[0]
        if B != cfg.train_batch_size:
            raise ValueError(f"batch dim {B} != train_batch_size "
                             f"{cfg.train_batch_size}")
        n_micro = self.micro_batches
        mb = B // n_micro
        micro_ids = [self._place_micro(
            jax.tree.map(lambda x: x[i * mb:(i + 1) * mb], batch))
            for i in range(n_micro)]

        S = self.num_stages
        schedules = [TrainSchedule(n_micro, S, s) for s in range(S)]
        streams = [list(sched.steps()) for sched in schedules]
        n_buf = max(sched.num_pipe_buffers() for sched in schedules)

        # Buffer-id spaces are PER STAGE (each stage sizes its own ring,
        # e.g. 3 buffers on stage 0 vs 2 on stage 1) — cross-stage mail is
        # therefore keyed by MICRO id, recovered from the schedule step.
        act_in = [[None] * n_buf for _ in range(S)]     # stage input, by buf
        out_act = [[None] * n_buf for _ in range(S)]    # fwd output, by buf
        out_micro = [[None] * n_buf for _ in range(S)]
        dx_pending = [[None] * n_buf for _ in range(S)]
        dx_micro = [[None] * n_buf for _ in range(S)]
        grads_in = [[None] * n_buf for _ in range(S)]
        act_mail: Dict[Any, Any] = {}                   # (stage, micro) -> act
        grad_mail: Dict[Any, Any] = {}                  # (stage, micro) -> dx
        grad_accum: List[Any] = [None] * S              # per-stage param grads
        losses = []

        def micro_of(s, t):
            m, _ = schedules[s]._clock_role(t)
            return m

        def add_grads(acc, new):
            if acc is None:
                return new
            return jax.tree.map(jnp.add, acc, new)

        total_steps = len(streams[0])
        for t in range(total_steps):
            # phase 1: sends (mailbox writes) across all stages
            for s in range(S):
                for cmd in streams[s][t]:
                    b = getattr(cmd, "buffer_id", None)
                    if isinstance(cmd, SendActivation):
                        act_mail[(s + 1, out_micro[s][b])] = out_act[s][b]
                        out_act[s][b] = None
                    elif isinstance(cmd, SendGrad):
                        grad_mail[(s - 1, dx_micro[s][b])] = dx_pending[s][b]
                        dx_pending[s][b] = None
            # phase 2: recv + compute per stage
            for s in range(S):
                for cmd in streams[s][t]:
                    b = getattr(cmd, "buffer_id", None)
                    if isinstance(cmd, LoadMicroBatch):
                        if s == 0:
                            m = micro_of(s, t)
                            act_in[s][b] = micro_ids[m]["input_ids"]
                    elif isinstance(cmd, RecvActivation):
                        act_in[s][b] = act_mail.pop((s, micro_of(s, t)))
                    elif isinstance(cmd, RecvGrad):
                        grads_in[s][b] = grad_mail.pop((s, micro_of(s, t)))
                    elif isinstance(cmd, ForwardPass):
                        # per-(stage, micro) span: the host-driven
                        # schedule is where micro-batch stage phases are
                        # individually visible (the SPMD engine fuses
                        # them into one program)
                        m = micro_of(s, t)
                        x = act_in[s][b]
                        with _span("pipe/fwd", {"stage": s, "micro": m}), \
                                _goodput("compute"):
                            if s == S - 1:
                                loss = self._last_fwd_prog()(
                                    self.params[s], x, micro_ids[m])
                                losses.append(loss)
                            else:
                                out_act[s][b] = self._fwd_prog(s)(
                                    self.params[s], x)
                                out_micro[s][b] = m
                    elif isinstance(cmd, BackwardPass):
                        m = micro_of(s, t)
                        x = act_in[s][b]
                        with _span("pipe/bwd", {"stage": s, "micro": m}), \
                                _goodput("compute"):
                            if s == S - 1:
                                dp, dx = self._last_bwd_prog()(
                                    self.params[s], x, micro_ids[m])
                            else:
                                cot = grads_in[s][b]
                                grads_in[s][b] = None
                                dp, dx = self._bwd_prog(s)(self.params[s],
                                                           x, cot)
                            grad_accum[s] = add_grads(grad_accum[s], dp)
                        dx_pending[s][b] = dx
                        dx_micro[s][b] = m
                        act_in[s][b] = None
                    elif isinstance(cmd, (ReduceGrads, ReduceTiedGrads)):
                        # one JAX client: with params replicated over the
                        # data axis, SPMD already psum'd the vjp's param
                        # grads — the reduction this instruction names
                        pass
                    elif isinstance(cmd, OptimizerStep):
                        if s == S - 1:   # run the step exactly once
                            with _span("pipe/step"), _goodput("compute"):
                                self._take_step(grad_accum)
                            grad_accum = [None] * S

        self.global_steps += 1
        self.global_samples += B
        mean_loss = jnp.mean(jnp.stack(losses))
        if self.global_steps % cfg.steps_per_print == 0:
            log_dist(f"step={self.global_steps} loss={float(mean_loss):.4f}",
                     ranks=[0])
        return mean_loss

    def _take_step(self, grad_accum):
        grads = [acc if acc is not None
                 else jax.tree.map(jnp.zeros_like, self.params[s])
                 for s, acc in enumerate(grad_accum)]
        self._apply_step(grads)

    def _apply_step(self, grads):
        if "opt_step" not in self._compiled:
            optimizer = self.optimizer

            def step(params, opt_state, grads):
                import optax
                updates, new_state = optimizer.update(grads, opt_state,
                                                      params)
                return optax.apply_updates(params, updates), new_state
            self._compiled["opt_step"] = track_program(
                "pipe_host/opt_step", jax.jit(step, donate_argnums=(0, 1)),
                subsystem="pipe_host")
        self.params, self.optimizer_state = self._compiled["opt_step"](
            self.params, self.optimizer_state, grads)

    # -- eval ----------------------------------------------------------

    def eval_batch(self, batch, micro_batches: Optional[int] = None):
        """Forward-only pipelined evaluation executing the
        ``InferenceSchedule`` instruction stream (reference:
        InferenceSchedule, schedule.py:129, run by _exec_schedule) — the
        same mailbox executor as train_batch minus backward/step, so
        stage k evaluates micro m while stage k-1 runs micro m+1."""
        ids = jnp.asarray(batch["input_ids"])
        B = ids.shape[0]
        n_micro = micro_batches or self.micro_batches
        if B % n_micro:
            raise ValueError(f"batch dim {B} not divisible by micro count "
                             f"{n_micro}")
        mbsz = B // n_micro
        micro_ids = [self._place_micro(
            jax.tree.map(lambda x: x[i * mbsz:(i + 1) * mbsz], batch))
            for i in range(n_micro)]
        S = self.num_stages
        scheds = [InferenceSchedule(n_micro, S, s) for s in range(S)]
        streams = [list(sc.steps()) for sc in scheds]
        # per-stage buffer counts (ADVICE r3: no hardcoded n_buf; a
        # schedule may size buffers per stage, like TrainSchedule does)
        act_in = [[None] * sc.num_pipe_buffers() for sc in scheds]
        micro_of = [[None] * sc.num_pipe_buffers() for sc in scheds]
        # micro identity rides with the BUFFER: LoadMicroBatch consumes
        # micros in order from the stage's iterator (the reference's
        # data-iterator contract) and pins the micro to its buffer; the
        # point-to-point channel is a per-receiver FIFO — sends and recvs
        # pair in order regardless of either side's buffer numbering.
        next_load = [0] * S
        from collections import deque
        mail: Dict[int, Any] = {s: deque() for s in range(S)}
        losses = []
        for t in range(len(streams[0])):
            for s in range(S):
                for cmd in streams[s][t]:
                    b = getattr(cmd, "buffer_id", None)
                    if isinstance(cmd, LoadMicroBatch):
                        micro_of[s][b] = micro_ids[next_load[s]]
                        next_load[s] += 1
                        if s == 0:
                            act_in[s][b] = micro_of[s][b]["input_ids"]
                    elif isinstance(cmd, RecvActivation):
                        act_in[s][b] = mail[s].popleft()
                    elif isinstance(cmd, ForwardPass):
                        x = act_in[s][b]
                        if s == S - 1:
                            losses.append(self._last_fwd_prog()(
                                self.params[s], x, micro_of[s][b]))
                        else:   # output reuses the buffer until the send
                            act_in[s][b] = self._fwd_prog(s)(
                                self.params[s], x)
                    elif isinstance(cmd, SendActivation):
                        mail[s + 1].append(act_in[s][b])
                        act_in[s][b] = None
        return jnp.mean(jnp.stack(losses))
