"""Process topology bookkeeping.

Reference surface: deepspeed/runtime/pipe/topology.py — ProcessTopology
(:9) maps ranks <-> (axis, coord) tuples; PipeDataParallelTopology /
PipeModelDataParallelTopology (:243) fix the axis order;
PipelineParallelGrid (:249) builds the torch process groups.

Implementation here is row-major mixed-radix arithmetic on numpy's
ravel/unravel (no rank<->coord dictionary): a rank IS the row-major index
of its coordinate tuple, so every query is one index computation or one
vectorized coordinate decode. The API and rank numbering match the
reference's contract (tests and checkpoint naming depend on it), but
"building groups" is free — groups are mesh axes.
"""

from collections import namedtuple

import numpy as np


class ProcessTopology:
    """Named-axis cartesian topology with row-major rank numbering:
    rank = ravel(coord, dims), coord = unravel(rank, dims)."""

    def __init__(self, axes, dims):
        self.axes = list(axes)
        self.dims = list(dims)
        self.ProcessCoord = namedtuple("ProcessCoord", self.axes)

    def world_size(self):
        return int(np.prod(self.dims, dtype=np.int64))

    def get_axis_names(self):
        return self.axes

    def get_dim(self, axis):
        return self.dims[self.axes.index(axis)] if axis in self.axes else 0

    def get_rank(self, **coord_kwargs):
        if set(coord_kwargs) != set(self.axes):
            raise ValueError(f"get_rank() needs all axes {self.axes}")
        coord = tuple(coord_kwargs[a] for a in self.axes)
        for a, c, d in zip(self.axes, coord, self.dims):
            assert 0 <= c < d, f"coord {a}={c} outside dim {d}"
        return int(np.ravel_multi_index(coord, self.dims))

    def get_coord(self, rank):
        if not 0 <= rank < self.world_size():
            raise ValueError(f"rank {rank} not in topology")
        return self.ProcessCoord(
            *(int(c) for c in np.unravel_index(rank, self.dims)))

    def get_rank_repr(self, rank, omit_axes=("data",), inner_sep="_",
                      outer_sep="-"):
        coord = self.get_coord(rank)._asdict()
        return outer_sep.join(
            f"{ax}{inner_sep}{coord[ax]:02d}"
            for ax in self.axes if ax not in tuple(omit_axes))

    def _coords_of_all_ranks(self):
        """[n_axes] arrays of per-rank coordinates, vectorized decode."""
        return np.unravel_index(np.arange(self.world_size()), self.dims)

    def filter_match(self, **filter_kwargs):
        """Ranks whose coordinates equal the given axis values, ascending
        (rank order IS coordinate row-major order)."""
        coords = self._coords_of_all_ranks()
        sel = np.ones(self.world_size(), bool)
        for axis, val in filter_kwargs.items():
            sel &= coords[self.axes.index(axis)] == val
        return [int(r) for r in np.nonzero(sel)[0]]

    def get_axis_list(self, axis, idx):
        return self.filter_match(**{axis: idx})

    def get_axis_comm_lists(self, axis):
        """Rank groups that vary only along ``axis``: each group anchors
        at an axis-coordinate-0 rank and steps by the axis's row-major
        stride (the product of all inner dims)."""
        if axis not in self.axes:
            return []
        i = self.axes.index(axis)
        stride = int(np.prod(self.dims[i + 1:], dtype=np.int64))
        return [[anchor + j * stride for j in range(self.dims[i])]
                for anchor in self.filter_match(**{axis: 0})]

    def __str__(self):
        return str({self.get_coord(r): r for r in range(self.world_size())})


class PipeDataParallelTopology(ProcessTopology):
    """axes = [pipe, data] (reference :229)."""

    def __init__(self, num_pp, num_dp):
        super().__init__(axes=["pipe", "data"], dims=[num_pp, num_dp])


class PipeModelDataParallelTopology(ProcessTopology):
    """axes = [pipe, data, model] (reference :243)."""

    def __init__(self, num_pp, num_mp, num_dp):
        super().__init__(axes=["pipe", "data", "model"],
                         dims=[num_pp, num_dp, num_mp])


class PipelineParallelGrid:
    """Axis-degree accessors (reference :249). Group handles are mesh axis
    names instead of torch process groups."""

    def __init__(self, topology=None, mesh=None):
        if topology is None and mesh is not None:
            topology = PipeModelDataParallelTopology(
                num_pp=mesh.shape.get("stage", 1),
                num_mp=mesh.shape.get("model", 1),
                num_dp=int(mesh.size // (mesh.shape.get("stage", 1)
                                         * mesh.shape.get("model", 1))))
        self._topo = topology
        self.data_parallel_size = max(topology.get_dim("data"), 1)
        self.pipe_parallel_size = max(topology.get_dim("pipe"), 1)
        self.model_parallel_size = max(topology.get_dim("model"), 1)

    def get_pipe_parallel_world_size(self):
        return self.pipe_parallel_size

    def get_data_parallel_world_size(self):
        return self.data_parallel_size

    def get_slice_parallel_world_size(self):
        return self.model_parallel_size

    def get_model_parallel_world_size(self):
        return self.model_parallel_size

    @property
    def topology(self):
        return self._topo

    def get_stage_group(self):
        return "stage"

    def get_data_parallel_group(self):
        return ("data", "fsdp", "expert")

    def get_model_parallel_group(self):
        return "model"
