"""Process topology bookkeeping.

Reference: deepspeed/runtime/pipe/topology.py — ProcessTopology (:9) maps
ranks <-> (axis, coord) tuples; PipeDataParallelTopology /
PipeModelDataParallelTopology (:243) fix the axis order;
PipelineParallelGrid (:249) builds the torch process groups.

Here ranks are *mesh coordinates*: the same coordinate algebra is kept
(tests and checkpoint naming depend on it) but "building groups" is free —
groups are mesh axes.
"""

from collections import namedtuple
from itertools import product


class ProcessTopology:
    """Cartesian product topology over named axes (reference :9)."""

    def __init__(self, axes, dims):
        self.axes = axes
        self.dims = dims
        self.ProcessCoord = namedtuple("ProcessCoord", axes)
        self.mapping = {}
        ranges = [range(d) for d in dims]
        for global_rank, coord in enumerate(product(*ranges)):
            key = {axis: coord[self.axes.index(axis)] for axis in self.axes}
            key = self.ProcessCoord(**key)
            self.mapping[key] = global_rank

    def get_rank(self, **coord_kwargs):
        if len(coord_kwargs) != len(self.axes):
            raise ValueError(f"get_rank() needs all axes {self.axes}")
        key = self.ProcessCoord(**coord_kwargs)
        assert key in self.mapping, f"coord {key} not in topology"
        return self.mapping[key]

    def get_axis_names(self):
        return self.axes

    def get_rank_repr(self, rank, omit_axes=("data",), inner_sep="_",
                      outer_sep="-"):
        omit_axes = list(omit_axes)
        axes = [a for a in self.get_axis_names() if a not in omit_axes]
        names = []
        for ax in axes:
            ax_rank = getattr(self.get_coord(rank=rank), ax)
            names.append(f"{ax}{inner_sep}{ax_rank:02d}")
        return outer_sep.join(names)

    def get_dim(self, axis):
        if axis not in self.axes:
            return 0
        return self.dims[self.axes.index(axis)]

    def get_coord(self, rank):
        for coord, r in self.mapping.items():
            if r == rank:
                return coord
        raise ValueError(f"rank {rank} not in topology")

    def get_axis_comm_lists(self, axis):
        """Lists of ranks that vary only along ``axis`` (reference group
        construction)."""
        if axis not in self.axes:
            return []
        other_axes = [a for a in self.axes if a != axis]
        lists = []
        ranges = [range(self.get_dim(a)) for a in other_axes]
        for coord in product(*ranges):
            other = dict(zip(other_axes, coord))
            ranks = [self.get_rank(**{axis: i}, **other)
                     for i in range(self.get_dim(axis))]
            lists.append(ranks)
        return lists

    def filter_match(self, **filter_kwargs):
        def criteria(x):
            return all(getattr(x, k) == v for k, v in filter_kwargs.items())
        return [self.mapping[c] for c in sorted(self.mapping.keys(),
                                                key=lambda c: self.mapping[c])
                if criteria(c)]

    def get_axis_list(self, axis, idx):
        return self.filter_match(**{axis: idx})

    def world_size(self):
        return len(self.mapping)

    def __str__(self):
        return str(self.mapping)


class PipeDataParallelTopology(ProcessTopology):
    """axes = [pipe, data] (reference :229)."""

    def __init__(self, num_pp, num_dp):
        super().__init__(axes=["pipe", "data"], dims=[num_pp, num_dp])


class PipeModelDataParallelTopology(ProcessTopology):
    """axes = [pipe, data, model] (reference :243)."""

    def __init__(self, num_pp, num_mp, num_dp):
        super().__init__(axes=["pipe", "data", "model"],
                         dims=[num_pp, num_dp, num_mp])


class PipelineParallelGrid:
    """Axis-degree accessors (reference :249). Group handles are mesh axis
    names instead of torch process groups."""

    def __init__(self, topology=None, mesh=None):
        if topology is None and mesh is not None:
            topology = PipeModelDataParallelTopology(
                num_pp=mesh.shape.get("stage", 1),
                num_mp=mesh.shape.get("model", 1),
                num_dp=int(mesh.size // (mesh.shape.get("stage", 1)
                                         * mesh.shape.get("model", 1))))
        self._topo = topology
        self.data_parallel_size = max(topology.get_dim("data"), 1)
        self.pipe_parallel_size = max(topology.get_dim("pipe"), 1)
        self.model_parallel_size = max(topology.get_dim("model"), 1)

    def get_pipe_parallel_world_size(self):
        return self.pipe_parallel_size

    def get_data_parallel_world_size(self):
        return self.data_parallel_size

    def get_slice_parallel_world_size(self):
        return self.model_parallel_size

    def get_model_parallel_world_size(self):
        return self.model_parallel_size

    @property
    def topology(self):
        return self._topo

    def get_stage_group(self):
        return "stage"

    def get_data_parallel_group(self):
        return ("data", "fsdp", "expert")

    def get_model_parallel_group(self):
        return "model"
