"""Pipeline-parallel training engine.

Reference: deepspeed/runtime/pipe/engine.py — PipelineEngine (:36) executes
the TrainSchedule instruction stream with torch.distributed P2P
(send/recv activations, 1F1B interleaving, tied-grad allreduce).

TPU-native: the whole pipelined step is ONE jitted SPMD program.

- The repeated trunk's params are stacked [n_blocks, ...] and sharded over
  the mesh "stage" axis — each stage holds n_blocks/S contiguous blocks.
- The forward is a ``shard_map`` over ONLY the "stage" axis: a lax.scan
  over T = n_micro + S - 1 ticks; each tick runs the local blocks and
  rotates activations to the next stage with ``lax.ppermute`` (the
  reference's p2p.send/recv). Other mesh axes (data/model) stay under
  automatic GSPMD sharding, giving PP x DP x TP composition for free.
- The backward is jax.grad THROUGH the scan: autodiff reverses the
  ppermute ring automatically — the reference's SendGrad/RecvGrad
  instructions fall out of the chain rule instead of being scheduled by
  hand. Microbatch gradient accumulation is the sum the scan computes.
- Tied weights (embedding reused by the head) are one pytree entry, so
  their gradient is summed by autodiff — the reference's tied-grad
  allreduce (ReduceTiedGrads) is implicit.

The 1F1B instruction stream itself lives in pipe/schedule.py and is
executed directly by the host-driven engine (pipe/host_engine.py) for
heterogeneous LayerSpec stacks; here XLA's scheduler overlaps the compute
and ICI transfers of consecutive ticks, which is where 1F1B's benefit
came from.
"""

from typing import Callable, Optional

import numpy as np
import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P, NamedSharding

from ... import comm as dist
from ...observability.goodput import timed as _goodput
from ...observability.programs import track_program
from ...observability.trace import span as _span
from ...utils.jax_compat import shard_map
from ...utils.logging import log_dist
from ...utils.tree import map_opt_state_sharding
from ..config import DeepSpeedConfig
from ..config_utils import DeepSpeedConfigError
from ..engine import DeepSpeedEngine, _init_kwargs
from ..fp16.loss_scaler import init_loss_scale, grads_finite, update_scale
from ..zero.sharding import extract_logical_names, make_param_rules, make_opt_state_rules
from .module import PipelineModule
from .topology import PipelineParallelGrid, PipeModelDataParallelTopology


def _prepend_layers(names_tree):
    return jax.tree.map(
        lambda n: ("layers",) + tuple(n) if n is not None else None,
        names_tree,
        is_leaf=lambda x: x is None or (isinstance(x, tuple) and all(
            isinstance(e, (str, type(None))) for e in x)))


class PipelineEngine(DeepSpeedEngine):
    """Construct via deepspeed_tpu.initialize(model=PipelineModule(...))."""

    def __init__(self, module: PipelineModule, config, *, loss_fn=None,
                 sample_batch=None, rng=None, mesh=None, optimizer=None,
                 lr_scheduler=None, params=None):
        self.pipe = module
        if isinstance(config, dict):
            config = DeepSpeedConfig.from_dict(config)
        if mesh is None and config.mesh.stage == 1 and module.num_stages > 1:
            config.mesh.stage = module.num_stages
        loss_fn = loss_fn or module.loss_fn
        if loss_fn is None:
            raise DeepSpeedConfigError("PipelineModule requires a loss_fn")
        super().__init__(module, config, loss_fn=loss_fn, params=params,
                         sample_batch=sample_batch, rng=rng, mesh=mesh,
                         optimizer=optimizer, lr_scheduler=lr_scheduler)
        self.num_stages = dist.pp_world_size(self.mesh)
        if module.n_blocks % self.num_stages != 0:
            raise DeepSpeedConfigError(
                f"n_blocks={module.n_blocks} must divide the mesh stage "
                f"axis ({self.num_stages}); adjust num_stages or the mesh")
        self.micro_batches = self.config.gradient_accumulation_steps
        self.grid = PipelineParallelGrid(
            PipeModelDataParallelTopology(
                num_pp=self.num_stages,
                num_mp=self.mp_world_size,
                num_dp=self.dp_world_size))
        log_dist(f"PipelineEngine: stages={self.num_stages} "
                 f"micro_batches={self.micro_batches} "
                 f"blocks/stage={self.pipe.n_blocks // self.num_stages}",
                 ranks=[0])

    # ------------------------------------------------------------------

    def _init_params(self, params, sample_batch):
        module = self.pipe
        if sample_batch is None:
            raise DeepSpeedConfigError(
                "PipelineEngine needs sample_batch"
                + (" (with params= it still derives the partitioning "
                   "metadata from a tiny abstract init)" if params is not None
                   else ""))
        ids = jnp.asarray(_init_kwargs(sample_batch)["input_ids"])
        r_embed, r_block, r_head = jax.random.split(self.rng, 3)

        def build_abstract():
            embed_vars = module.embed.init(r_embed, ids)
            x = module.embed.apply(embed_vars, ids)
            block_rngs = jax.random.split(r_block, module.n_blocks)
            blocks_vars = jax.vmap(
                lambda r: module.block.init(r, x))(block_rngs)
            head_vars = module.head.init(r_head, x)
            return embed_vars, blocks_vars, head_vars

        emb_abs, blk_abs, head_abs = jax.eval_shape(build_abstract)
        emb_v, emb_n = extract_logical_names(emb_abs)
        blk_v, blk_n = extract_logical_names(blk_abs)
        head_v, head_n = extract_logical_names(head_abs)
        self._param_names = {"embed": emb_n,
                             "blocks": _prepend_layers(blk_n),
                             "head": head_n}
        self._param_shapes = jax.tree.map(
            lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype),
            {"embed": emb_v, "blocks": blk_v, "head": head_v})
        self._build_param_shardings()

        if params is not None:
            # pre-built tree (e.g. a restored checkpoint): validate
            # against the abstract init, then PARTITION it across the
            # stage/TP/ZeRO axes with one device_put — loading a
            # pretrained model into the pipeline is just a placement
            import flax.core.meta as flax_meta
            from ...utils.tree import validate_params_tree
            params = flax_meta.unbox(params)
            want = self._param_shapes
            try:
                validate_params_tree(params, want)
            except ValueError as e:
                raise DeepSpeedConfigError(str(e)) from None
            self.params = jax.jit(
                lambda t: jax.tree.map(
                    lambda p, w: p.astype(w.dtype), t, want),
                out_shardings=self.param_shardings)(params)
            return

        init_fn = track_program(
            "pipe/param_init",
            jax.jit(
                lambda: jax.tree.map(
                    lambda t: t,
                    {k: extract_logical_names(v)[0] for k, v in
                     zip(("embed", "blocks", "head"), build_abstract())}),
                out_shardings=self.param_shardings),
            subsystem="pipe")
        self.params = init_fn()

    def _build_param_shardings(self):
        zcfg = self.config.zero_optimization
        stage = self.zero_stage
        rules = make_param_rules(
            stage, zcfg.stage3_param_persistence_threshold if stage == 3 else 0,
            layers_axis="stage")
        from ..engine import _tree_names_is_leaf
        self.param_specs = jax.tree.map(
            lambda n, s: rules(n, s.shape, self.mesh),
            self._param_names, self._param_shapes, is_leaf=_tree_names_is_leaf)
        self.param_shardings = jax.tree.map(
            lambda spec: NamedSharding(self.mesh, spec),
            self.param_specs, is_leaf=lambda x: isinstance(x, P))

    # ------------------------------------------------------------------

    def _pipelined_trunk(self, blocks_params, x_micro, train, rng=None):
        """SPMD collective-permute pipeline over the stage axis.

        x_micro: [n_micro, mb, s, d]; returns last-stage outputs
        [n_micro, mb, s, d]."""
        module = self.pipe
        S = self.num_stages
        n_micro = x_micro.shape[0]
        T = n_micro + S - 1
        remat = module.activation_checkpoint_interval != 0

        def block_apply(p, h):
            rngs = None
            if train and rng is not None:
                rngs = {"dropout": jax.random.fold_in(rng, 1),
                        "gating": jax.random.fold_in(rng, 2)}
            out = module.block.apply(p, h, deterministic=not train, rngs=rngs)
            return out[0] if isinstance(out, tuple) else out

        def run_local(blocks_local, x):
            def body(h, p):
                f = jax.checkpoint(block_apply) if remat else block_apply
                return f(p, h), None
            h, _ = jax.lax.scan(body, x, blocks_local)
            return h

        def stage_prog(blocks_local, xs):
            stage = jax.lax.axis_index("stage")
            mb_shape = xs.shape[1:]
            carry = jnp.zeros(mb_shape, xs.dtype)
            ys = jnp.zeros((n_micro,) + mb_shape, xs.dtype)

            def tick(state, t):
                # xprof phase scope: each micro-batch pipeline tick's
                # compute + ppermute rotation groups under "pipe_tick"
                with jax.named_scope("pipe_tick"):
                    carry, ys = state
                    inject = jax.lax.dynamic_index_in_dim(
                        xs, jnp.clip(t, 0, n_micro - 1), 0, keepdims=False)
                    x = jnp.where(stage == 0, inject, carry)
                    y = run_local(blocks_local, x)
                    out_idx = t - (S - 1)
                    valid = jnp.logical_and(out_idx >= 0, out_idx < n_micro)
                    ys_new = jax.lax.dynamic_update_index_in_dim(
                        ys, y, jnp.clip(out_idx, 0, n_micro - 1), 0)
                    ys = jnp.where(valid, ys_new, ys)
                    nxt = jax.lax.ppermute(
                        y, "stage", [(i, (i + 1) % S) for i in range(S)])
                return (nxt, ys), None

            (carry, ys), _ = jax.lax.scan(tick, (carry, ys), jnp.arange(T))
            return ys

        out = shard_map(stage_prog, self.mesh,
                        in_specs=(P("stage"), P()), out_specs=P("stage"),
                        axis_names={"stage"})(blocks_params, x_micro)
        # out: [S * n_micro, mb, s, d] — the last stage's slice is the model
        # output (other stages hold in-flight garbage)
        return out.reshape(S, n_micro, *out.shape[1:])[-1]

    def _pipe_loss(self, params, batch, rng, train=True):
        module = self.pipe
        ids = jnp.asarray(batch["input_ids"])
        B = ids.shape[0]
        n_micro = self.micro_batches
        emb = module.embed.apply(params["embed"], ids)
        x_micro = emb.reshape(n_micro, B // n_micro, *emb.shape[1:])
        outs = self._pipelined_trunk(params["blocks"], x_micro, train, rng)
        h = outs.reshape(B, *outs.shape[2:])
        logits = module.head.apply(params["head"], h)
        return self._loss_fn(logits, batch)

    def _make_train_step(self):
        cfg = self.config
        fp16 = self.fp16_enabled
        optimizer = self.optimizer

        def train_step(params, opt_state, scaler, batch, rng):
            scale = scaler.scale if fp16 else jnp.float32(1.0)

            def scaled_loss(p):
                return self._pipe_loss(p, batch, rng) * scale

            loss_scaled, grads = jax.value_and_grad(scaled_loss)(params)
            loss = loss_scaled / scale
            if fp16:
                grads = jax.tree.map(lambda g: g / scale, grads)
            gnorm = jnp.sqrt(sum(jnp.sum(jnp.square(g))
                                 for g in jax.tree.leaves(grads)))

            def apply(op):
                import optax
                p, s, g = op
                updates, new_s = optimizer.update(g, s, p)
                return optax.apply_updates(p, updates), new_s

            if fp16:
                finite = grads_finite(grads)
                new_params, new_opt = jax.lax.cond(
                    finite, apply, lambda op: (op[0], op[1]),
                    (params, opt_state, grads))
                new_scaler = update_scale(
                    scaler, finite, dynamic=cfg.fp16.dynamic_loss_scale,
                    scale_window=cfg.fp16.loss_scale_window,
                    hysteresis=cfg.fp16.hysteresis,
                    min_scale=cfg.fp16.min_loss_scale)
                skipped = jnp.where(finite, 0, 1)
            else:
                new_params, new_opt = apply((params, opt_state, grads))
                new_scaler, skipped = scaler, jnp.int32(0)
            metrics = {"loss": loss, "grad_norm": gnorm, "skipped": skipped,
                       "loss_scale": scaler.scale if fp16 else jnp.float32(1.0)}
            return new_params, new_opt, new_scaler, metrics

        dummy = self.loss_scale_state or init_loss_scale(1.0)
        rep = NamedSharding(self.mesh, P())
        scaler_sh = jax.tree.map(lambda _: rep, dummy)
        return jax.jit(train_step, donate_argnums=(0, 1, 2),
                       out_shardings=(self.param_shardings,
                                      self.opt_shardings, scaler_sh, None))

    def train_batch(self, batch):
        """Reference: PipelineEngine.train_batch (engine.py:292) — consumes
        a full global batch, pipelines gas microbatches, steps once."""
        cfg = self.config
        expect = cfg.train_batch_size
        # ds-tpu: lint-ok[TS002] — batch arrives as host numpy from the
        # dataloader; this is input validation, not a device readback.
        ids = np.asarray(batch["input_ids"])
        if ids.shape[0] != expect:
            raise ValueError(f"batch dim {ids.shape[0]} != train_batch_size "
                             f"{expect}")
        obs = self.observability
        if obs is not None:
            obs.begin_step(self.global_steps + 1)
            self._tokens_per_step = expect * int(ids.shape[1])
        with _span("data"), _goodput("data_stall"):
            dev_batch = self._place_batch(batch, with_gas_dim=False)
        if "train_step" not in self._compiled:
            self._compiled["train_step"] = track_program(
                "pipe/train_step", self._make_train_step(),
                subsystem="pipe")
        scaler = self.loss_scale_state or init_loss_scale(1.0)
        rng = jax.random.fold_in(self.rng, self.global_steps + 1)
        self.tput_timer.start()
        if self.resilience is not None:
            self.resilience.on_step_start()
        with _span("fwd_bwd_step"), _goodput("compute"):
            try:
                self.params, self.optimizer_state, new_scaler, metrics = \
                    self._compiled["train_step"](self.params,
                                                 self.optimizer_state,
                                                 scaler, dev_batch, rng)
            except Exception as err:
                self._note_dispatch_failure(err)   # OOM forensics dump
                raise
        if self.fp16_enabled:
            self.loss_scale_state = new_scaler
            self._accumulate_skipped(metrics["skipped"])
        self.global_steps += 1
        self.global_samples += expect
        self.tput_timer.stop(global_step=True)
        if obs is not None:
            self._observe_step(metrics)
        if self.global_steps % cfg.steps_per_print == 0:
            self._report_step(metrics)
        self._write_monitor(metrics)
        if self.resilience is not None:
            self.resilience.on_step_end(metrics)
        return metrics["loss"]

    def eval_batch(self, batch):
        if "eval" not in self._compiled:
            self._compiled["eval"] = track_program(
                "pipe/eval",
                jax.jit(lambda p, b: self._pipe_loss(
                    p, b, jax.random.PRNGKey(0), train=False)),
                subsystem="pipe")
        return self._compiled["eval"](self.params, batch)

    # forward/backward/step split is not meaningful when the pipeline is a
    # single fused program; reference parity points to train_batch.
    def forward(self, *a, **k):
        raise RuntimeError("PipelineEngine: use train_batch()/eval_batch() "
                           "(reference PipelineEngine also overrides these)")

    backward = forward
    step = forward
