"""Static + dynamic loss scaling.

Reference: deepspeed/runtime/fp16/loss_scaler.py (LossScaler :54,
DynamicLossScaler :77). Functional here: the scaler state is a small pytree
of device scalars carried through the jitted train step, and the
skip/grow/shrink decision is lax-traced (the reference checks overflow on
the host and skips the step in Python).
"""

from typing import NamedTuple

import jax
import jax.numpy as jnp


class LossScaleState(NamedTuple):
    scale: jnp.ndarray            # current loss scale (f32 scalar)
    growth_tracker: jnp.ndarray   # consecutive non-overflow steps (i32)
    overflows: jnp.ndarray        # total overflowed/skipped steps (i32)
    hysteresis_left: jnp.ndarray  # overflows tolerated before next shrink (i32)


def init_loss_scale(static_scale: float = 0.0, initial_scale_power: int = 16,
                    hysteresis: int = 2) -> LossScaleState:
    scale = static_scale if static_scale > 0 else 2.0 ** initial_scale_power
    return LossScaleState(scale=jnp.asarray(scale, jnp.float32),
                          growth_tracker=jnp.zeros((), jnp.int32),
                          overflows=jnp.zeros((), jnp.int32),
                          hysteresis_left=jnp.asarray(hysteresis, jnp.int32))


def grads_finite(grads) -> jnp.ndarray:
    """Overflow check over a grad pytree (reference: CheckOverflow,
    runtime/utils.py — an allreduce(MAX) over ranks; here the grads are
    already global values inside jit so a local isfinite suffices)."""
    leaves = jax.tree.leaves(grads)
    if not leaves:
        return jnp.asarray(True)
    finite = [jnp.all(jnp.isfinite(g)) for g in leaves]
    return jnp.stack(finite).all()


def update_scale(state: LossScaleState, finite: jnp.ndarray, *,
                 dynamic: bool = True, scale_window: int = 1000,
                 hysteresis: int = 2, consecutive_hysteresis: bool = False,
                 min_scale: float = 1.0,
                 scale_factor: float = 2.0) -> LossScaleState:
    """Dynamic policy, reference-faithful
    (DynamicLossScaler.update_scale, fp16/loss_scaler.py:151): an
    overflow consumes one unit of hysteresis; the scale halves only when
    hysteresis is exhausted — and stays exhausted (every further overflow
    shrinks) until a REFILL event. The refill event is: every clean step
    when ``consecutive_hysteresis=True``; the scale-GROWTH step (after
    ``scale_window`` clean steps) when False (the reference default) —
    NOT every clean step, or non-consecutive overflows could never
    shrink the scale."""
    if not dynamic:
        return state._replace(overflows=state.overflows + jnp.where(finite, 0, 1))

    def on_overflow(s):
        exhausted = s.hysteresis_left <= 1
        return LossScaleState(
            scale=jnp.where(exhausted,
                            jnp.maximum(s.scale / scale_factor, min_scale),
                            s.scale),
            growth_tracker=jnp.zeros((), jnp.int32),
            overflows=s.overflows + 1,
            # no refill on shrink (reference keeps cur_hysteresis at 1)
            hysteresis_left=jnp.where(exhausted, s.hysteresis_left,
                                      s.hysteresis_left - 1))

    def on_clean(s):
        tracker = s.growth_tracker + 1
        grow = tracker >= scale_window
        full = jnp.asarray(hysteresis, jnp.int32)
        if consecutive_hysteresis:
            hyst = full
        else:
            hyst = jnp.where(grow, full, s.hysteresis_left)
        return LossScaleState(
            scale=jnp.where(grow, s.scale * scale_factor, s.scale),
            growth_tracker=jnp.where(grow, 0, tracker),
            overflows=s.overflows,
            hysteresis_left=hyst)

    return jax.lax.cond(finite, on_clean, on_overflow, state)
