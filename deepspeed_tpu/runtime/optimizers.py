"""Optimizer registry.

Reference: the basic-optimizer dispatch in DeepSpeedEngine
(runtime/engine.py:901 registry + :1141 _configure_basic_optimizer):
Adam/AdamW (torch or FusedAdam/CPUAdam), LAMB (FusedLamb), OnebitAdam,
OnebitLamb, ZeroOneAdam, Adagrad, SGD.

TPU-native: every optimizer is an optax ``GradientTransformation`` operating
on the fp32 master params (the model computes in bf16/fp16 via flax's dtype
casting — this replaces the reference's fp16 master-weight optimizers,
runtime/fp16/fused_optimizer.py). "Fused" variants resolve to the Pallas
fused kernels in deepspeed_tpu.ops when available, else to optax (XLA fuses
the update chain anyway — the Pallas path exists to beat it on HBM traffic
for very large flat shards).
"""

from typing import Callable, Optional, Union

import optax

from ..utils.logging import logger

ADAM_OPTIMIZER = "adam"
ADAMW_OPTIMIZER = "adamw"
FUSED_ADAM = "fusedadam"
CPU_ADAM = "cpuadam"  # deepspeedcpuadam
LAMB_OPTIMIZER = "lamb"
FUSED_LAMB = "fusedlamb"
ONEBIT_ADAM_OPTIMIZER = "onebitadam"
ONEBIT_LAMB_OPTIMIZER = "onebitlamb"
ZERO_ONE_ADAM_OPTIMIZER = "zerooneadam"
ADAGRAD_OPTIMIZER = "adagrad"
SGD_OPTIMIZER = "sgd"

DEEPSPEED_OPTIMIZERS = [
    ADAM_OPTIMIZER, ADAMW_OPTIMIZER, FUSED_ADAM, CPU_ADAM, LAMB_OPTIMIZER,
    FUSED_LAMB, ONEBIT_ADAM_OPTIMIZER, ONEBIT_LAMB_OPTIMIZER,
    ZERO_ONE_ADAM_OPTIMIZER, ADAGRAD_OPTIMIZER, SGD_OPTIMIZER,
]


def _adam_args(params):
    return dict(
        b1=params.get("betas", (0.9, 0.999))[0],
        b2=params.get("betas", (0.9, 0.999))[1],
        eps=params.get("eps", 1e-8),
    )


def build_optimizer(opt_type: str, params: dict,
                    lr_schedule: Optional[Union[float, Callable]] = None,
                    use_pallas: bool = True) -> optax.GradientTransformation:
    """Build the optax transform for a config ``optimizer`` block.

    ``lr_schedule`` overrides params["lr"] when given (engine wires the
    scheduler block here).
    """
    name = opt_type.lower().replace("deepspeed", "").replace("_", "")
    lr = lr_schedule if lr_schedule is not None else params.get("lr", 1e-3)
    wd = params.get("weight_decay", 0.0)

    if name in (ADAM_OPTIMIZER, FUSED_ADAM, CPU_ADAM):
        if name == FUSED_ADAM and use_pallas:
            try:
                from ..ops.pallas.fused_adam import fused_adamw
                return fused_adamw(lr, weight_decay=wd, **_adam_args(params))
            except Exception as e:  # pragma: no cover
                logger.warning(f"Pallas fused adam unavailable ({e}); using optax")
        if wd > 0 and params.get("adam_w_mode", True):
            return optax.adamw(lr, weight_decay=wd, **_adam_args(params))
        tx = optax.adam(lr, **_adam_args(params))
        if wd > 0:  # plain Adam + L2 (reference adam_w_mode=False path)
            tx = optax.chain(optax.add_decayed_weights(wd), tx)
        return tx

    if name == ADAMW_OPTIMIZER:
        return optax.adamw(lr, weight_decay=wd, **_adam_args(params))

    if name in (LAMB_OPTIMIZER, FUSED_LAMB):
        if name == FUSED_LAMB and use_pallas:
            try:
                from ..ops.pallas.fused_lamb import fused_lamb
                return fused_lamb(lr, weight_decay=wd,
                                  eps=params.get("eps", 1e-6),
                                  b1=params.get("betas", (0.9, 0.999))[0],
                                  b2=params.get("betas", (0.9, 0.999))[1])
            except Exception as e:  # pragma: no cover
                logger.warning(f"Pallas fused lamb unavailable ({e}); using optax")
        return optax.lamb(lr, weight_decay=wd, **_adam_args(params))

    if name == ADAGRAD_OPTIMIZER:
        return optax.adagrad(lr, eps=params.get("eps", 1e-10))

    if name == SGD_OPTIMIZER:
        return optax.sgd(lr, momentum=params.get("momentum", 0.0),
                         nesterov=params.get("nesterov", False))

    if name in (ONEBIT_ADAM_OPTIMIZER, ZERO_ONE_ADAM_OPTIMIZER):
        # error-feedback momentum compression (reference: onebit/adam.py:10,
        # zoadam.py:10); the wire-level analog lives in
        # runtime/comm_compression.compressed_allreduce
        from .comm_compression import onebit_adam, zero_one_adam
        kw = dict(weight_decay=wd, **_adam_args(params))
        if name == ZERO_ONE_ADAM_OPTIMIZER:
            return zero_one_adam(
                lr, var_freeze_step=params.get("var_freeze_step", 100),
                var_update_scaler=params.get("var_update_scaler", 16), **kw)
        return onebit_adam(lr, freeze_step=params.get("freeze_step", 100),
                           **kw)

    if name == ONEBIT_LAMB_OPTIMIZER:
        from .comm_compression import onebit_lamb
        return onebit_lamb(lr, weight_decay=wd,
                           freeze_step=params.get("freeze_step", 100),
                           **_adam_args(params))

    raise ValueError(f"Unknown optimizer type '{opt_type}' "
                     f"(valid: {DEEPSPEED_OPTIMIZERS})")
