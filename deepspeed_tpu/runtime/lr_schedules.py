"""LR schedules.

Reference: deepspeed/runtime/lr_schedules.py (854 LoC): LRRangeTest (:308),
OneCycle (:415), WarmupLR (:704), WarmupDecayLR (:800). Here each schedule
is a pure fn step->lr (optax-compatible), plus a registry used by the
config's ``scheduler`` block.
"""

import math
from typing import Callable

import jax.numpy as jnp

LR_RANGE_TEST = "LRRangeTest"
ONE_CYCLE = "OneCycle"
WARMUP_LR = "WarmupLR"
WARMUP_DECAY_LR = "WarmupDecayLR"

VALID_LR_SCHEDULES = [LR_RANGE_TEST, ONE_CYCLE, WARMUP_LR, WARMUP_DECAY_LR]


def lr_range_test(lr_range_test_min_lr: float = 1e-3,
                  lr_range_test_step_size: int = 2000,
                  lr_range_test_step_rate: float = 1.0,
                  lr_range_test_staircase: bool = False) -> Callable:
    """LR sweep for finding usable ranges (reference :308)."""
    def schedule(step):
        interval = step / lr_range_test_step_size
        if lr_range_test_staircase:
            interval = jnp.floor(interval)
        return lr_range_test_min_lr * (1.0 + interval * lr_range_test_step_rate)
    return schedule


def one_cycle(cycle_min_lr: float, cycle_max_lr: float,
              cycle_first_step_size: int = 2000,
              cycle_second_step_size: int = None,
              decay_step_size: int = 0,
              decay_lr_rate: float = 0.0,
              cycle_first_stair_count: int = 0,
              cycle_second_stair_count: int = None,
              **_ignored) -> Callable:
    """Triangular cyclic LR with optional post-cycle decay (reference :415)."""
    second = cycle_second_step_size if cycle_second_step_size is not None else cycle_first_step_size
    total_cycle = cycle_first_step_size + second

    def schedule(step):
        step = jnp.asarray(step, jnp.float32)
        up_frac = jnp.clip(step / cycle_first_step_size, 0.0, 1.0)
        down_frac = jnp.clip((step - cycle_first_step_size) / second, 0.0, 1.0)
        in_cycle_lr = jnp.where(
            step <= cycle_first_step_size,
            cycle_min_lr + (cycle_max_lr - cycle_min_lr) * up_frac,
            cycle_max_lr - (cycle_max_lr - cycle_min_lr) * down_frac)
        if decay_step_size > 0 and decay_lr_rate > 0:
            decay_steps = jnp.maximum(step - total_cycle, 0.0) / decay_step_size
            decayed = cycle_min_lr / (1.0 + decay_lr_rate * decay_steps)
            return jnp.where(step > total_cycle, decayed, in_cycle_lr)
        return in_cycle_lr
    return schedule


def warmup_lr(warmup_min_lr: float = 0.0, warmup_max_lr: float = 0.001,
              warmup_num_steps: int = 1000, warmup_type: str = "log",
              **_ignored) -> Callable:
    """Warm up then hold (reference :704; log warmup is its default)."""
    warmup_num_steps = max(warmup_num_steps, 2)

    def schedule(step):
        step = jnp.asarray(step, jnp.float32)
        if warmup_type == "log":
            frac = jnp.log1p(jnp.minimum(step, warmup_num_steps)) / math.log(warmup_num_steps + 1)
        else:
            frac = jnp.minimum(step, warmup_num_steps) / warmup_num_steps
        lr = warmup_min_lr + (warmup_max_lr - warmup_min_lr) * frac
        return jnp.where(step >= warmup_num_steps, warmup_max_lr, lr)
    return schedule


def warmup_decay_lr(total_num_steps: int, warmup_min_lr: float = 0.0,
                    warmup_max_lr: float = 0.001, warmup_num_steps: int = 1000,
                    warmup_type: str = "log", **_ignored) -> Callable:
    """Warm up then linear decay to zero (reference :800)."""
    base = warmup_lr(warmup_min_lr, warmup_max_lr, warmup_num_steps, warmup_type)
    warmup_num_steps_ = max(warmup_num_steps, 2)

    def schedule(step):
        step = jnp.asarray(step, jnp.float32)
        decay_frac = jnp.clip(
            (total_num_steps - step) / max(total_num_steps - warmup_num_steps_, 1),
            0.0, 1.0)
        return jnp.where(step < warmup_num_steps_, base(step), warmup_max_lr * decay_frac)
    return schedule


SCHEDULE_REGISTRY = {
    LR_RANGE_TEST: lr_range_test,
    ONE_CYCLE: one_cycle,
    WARMUP_LR: warmup_lr,
    WARMUP_DECAY_LR: warmup_decay_lr,
}


def get_lr_schedule(name: str, params: dict) -> Callable:
    if name not in SCHEDULE_REGISTRY:
        raise ValueError(f"Unknown scheduler '{name}'. Valid: {VALID_LR_SCHEDULES}")
    return SCHEDULE_REGISTRY[name](**params)


def add_tuning_arguments(parser):
    """Reference: lr_schedules.py:55 — argparse surface for schedule
    tuning (used by the convergence-tuning workflow and ds CLI)."""
    group = parser.add_argument_group(
        "Convergence Tuning", "Convergence tuning configurations")
    group.add_argument("--lr_schedule", type=str, default=None,
                       help="LR schedule for training")
    group.add_argument("--lr_range_test_min_lr", type=float, default=0.001)
    group.add_argument("--lr_range_test_step_rate", type=float, default=1.0)
    group.add_argument("--lr_range_test_step_size", type=int, default=1000)
    group.add_argument("--lr_range_test_staircase", type=bool, default=False)
    group.add_argument("--cycle_first_step_size", type=int, default=1000)
    group.add_argument("--cycle_first_stair_count", type=int, default=-1)
    group.add_argument("--cycle_second_step_size", type=int, default=-1)
    group.add_argument("--cycle_second_stair_count", type=int, default=-1)
    group.add_argument("--decay_step_size", type=int, default=1000)
    group.add_argument("--cycle_min_lr", type=float, default=0.01)
    group.add_argument("--cycle_max_lr", type=float, default=0.1)
    group.add_argument("--decay_lr_rate", type=float, default=0.0)
    group.add_argument("--warmup_min_lr", type=float, default=0)
    group.add_argument("--warmup_max_lr", type=float, default=0.001)
    group.add_argument("--warmup_num_steps", type=int, default=1000)
    group.add_argument("--warmup_type", type=str, default="log")
    return parser


def parse_arguments():
    """Reference: lr_schedules.py:159."""
    import argparse
    parser = argparse.ArgumentParser()
    parser = add_tuning_arguments(parser)
    args, unknown = parser.parse_known_args()
    return args, unknown


def get_lr_from_config(config: dict):
    """Reference: lr_schedules.py:269 — (initial_lr, reason) from a
    scheduler config dict."""
    if "type" not in config:
        return None, "LR schedule type not defined in config"
    if "params" not in config:
        return None, "LR schedule params not defined in config"
    name, params = config["type"], config["params"]
    if name not in VALID_LR_SCHEDULES:
        return None, f"{name} is not a valid LR schedule"
    if name == "LRRangeTest":
        return params.get("lr_range_test_min_lr", 1e-3), ""
    if name == "OneCycle":
        return params.get("cycle_max_lr", 0.1), ""
    return params.get("warmup_max_lr", 0.001), ""
