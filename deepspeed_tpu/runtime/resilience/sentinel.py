"""Divergence sentinel: on-device bad-step detection, bounded host checks.

A NaN/Inf loss or an exploding grad norm must be *detected* every step
but *acted on* only rarely — reading a device scalar back to the host
every step stalls the ICI ring (the exact per-step-sync class the TS002
lint rule exists to catch; see the PR-2 skipped-steps fix this design
copies). The sentinel therefore folds each step's health flag into an
on-device consecutive-bad counter with a handful of eager scalar ops
(asynchronous dispatch, no sync) and materializes that counter on the
host only at the ``check_interval`` cadence.

``consecutive`` semantics: ``where(bad, consec + 1, 0)`` per step, so a
short recovered spike (fewer than ``patience`` bad steps in a row) never
triggers a rollback — the skipped-step-hysteresis analog of the
reference's fp16 path applied to bf16/fp32 divergence. The host reads
the *peak* streak since its last check, so a burst that meets
``patience`` but ends before the next check boundary is still detected.
"""

from typing import Optional

import jax.numpy as jnp


class DivergenceError(RuntimeError):
    """Training diverged and automatic rollback is exhausted/impossible."""


class DivergenceSentinel:
    """Folds per-step health into device counters; host-reads on demand."""

    def __init__(self, config):
        self.config = config
        self._consec = None        # device int32: current consecutive streak
        self._peak = None          # device int32: max streak since last read
        self._total_bad = None     # device int32: all-time bad steps
        self.folds = 0             # host counter: steps folded (trace probe)
        self.host_reads = 0        # host counter: device->host materializations

    def fold(self, metrics: dict) -> None:
        """Fold one step's health flag into the device counters. Pure
        eager jnp scalar ops on values the step already produced —
        dispatches asynchronously, never blocks on the device."""
        cfg = self.config
        loss = metrics.get("loss")
        gnorm = metrics.get("grad_norm")
        bad = ~jnp.isfinite(loss)
        if cfg.loss_abs_threshold > 0:
            bad = bad | (jnp.abs(loss) > cfg.loss_abs_threshold)
        if gnorm is not None:
            bad = bad | ~jnp.isfinite(gnorm)
            if cfg.grad_norm_threshold > 0:
                bad = bad | (gnorm > cfg.grad_norm_threshold)
        skipped = metrics.get("skipped")
        if skipped is not None:
            # an fp16 loss-scale overflow step is HANDLED divergence: the
            # update was skipped and the scaler is already backing off —
            # counting it here would roll back healthy dynamic-loss-scale
            # warmup (the scaler's own hysteresis owns that failure mode)
            bad = bad & (skipped == 0)
        bad_i = bad.astype(jnp.int32)
        self._consec = (bad_i if self._consec is None
                        else jnp.where(bad, self._consec + 1, 0))
        # peak-since-last-read: a burst that meets patience but ENDS before
        # the next check boundary must still be detected — the current
        # streak alone would have been reset to 0 by the first good step
        self._peak = (self._consec if self._peak is None
                      else jnp.maximum(self._peak, self._consec))
        self._total_bad = (bad_i if self._total_bad is None
                           else self._total_bad + bad_i)
        self.folds += 1

    def read_consecutive(self) -> int:
        """Materialize the longest consecutive-bad streak since the last
        read (ONE host sync; callers must stay on the bounded check
        cadence). Reading consumes the peak — the next window starts from
        the still-running current streak."""
        if self._peak is None:
            return 0
        self.host_reads += 1
        # bounded-cadence read by contract (manager enforces the cadence)
        peak = int(self._peak)  # ds-tpu: lint-ok[TS002]
        self._peak = self._consec
        return peak

    def read_total_bad(self) -> int:
        if self._total_bad is None:
            return 0
        self.host_reads += 1
        return int(self._total_bad)  # ds-tpu: lint-ok[TS002]

    def reset(self) -> None:
        """Forget the streak (after a rollback restores good state)."""
        self._consec = None
        self._peak = None
