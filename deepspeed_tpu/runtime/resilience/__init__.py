"""Fault-tolerant training (docs/resilience.md).

Lazy exports (PEP 562, same pattern as ``serving/``): ``config`` and
``manifest`` stay importable without jax so ``runtime/config.py`` and
file-level checkpoint tooling work in dependency-free jobs; the
jax-touching members load on first access.
"""

from .config import ResilienceConfig

__all__ = ["ResilienceConfig", "ResilienceManager", "DivergenceSentinel",
           "DivergenceError", "PreemptionHandler", "Watchdog",
           "emergency_save", "Fault", "FaultInjector", "injected",
           "CheckpointCorruptionError", "write_manifest", "verify_manifest",
           "resolve_verified_tag", "gc_checkpoints", "write_latest"]

_LAZY = {
    "ResilienceManager": ".manager",
    "DivergenceSentinel": ".sentinel",
    "DivergenceError": ".sentinel",
    "PreemptionHandler": ".preemption",
    "Watchdog": ".preemption",
    "emergency_save": ".preemption",
    "Fault": ".faults",
    "FaultInjector": ".faults",
    "injected": ".faults",
    "CheckpointCorruptionError": ".manifest",
    "write_manifest": ".manifest",
    "verify_manifest": ".manifest",
    "resolve_verified_tag": ".manifest",
    "gc_checkpoints": ".manifest",
    "write_latest": ".manifest",
}


def __getattr__(name):
    if name in _LAZY:
        import importlib
        mod = importlib.import_module(_LAZY[name], __name__)
        return getattr(mod, name)
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
