"""Preemption handling and step-hang watchdog.

TPU pods on preemptible capacity go away on a SIGTERM with a short
grace window (Varuna's premise: checkpoint/resume discipline is what
makes cheap capacity usable). The handler turns that signal into a
best-effort *emergency save*: join any in-flight async checkpoint first
(its ``latest`` tag publishes only after durability), then write a
fresh synchronous checkpoint — manifest and atomic ``latest`` included
via the normal save path — and finally chain to the previously
installed handler so the process still terminates the way the
orchestrator expects.

The watchdog covers the failure preemption doesn't: a *hang* (a wedged
collective, a deadlocked host callback) where no signal ever arrives.
A daemon thread arms at step start, disarms at step end, and fires when
one step stays in flight past ``step_timeout_s`` — dumping last-good
step, pending-checkpoint state, and every thread's live stack before
aborting with a distinct exit code the fleet layer can restart on.
"""

import os
import signal
import sys
import threading
import time
import traceback
from typing import Callable, Optional

from ...utils.logging import logger


def emergency_save(engine, save_dir: str, tag: Optional[str] = None) -> str:
    """Best-effort durable checkpoint for a process about to die: join the
    in-flight async save (publishing its tag), then save synchronously.
    Returns the checkpoint path."""
    engine.wait_checkpoint()
    tag = tag or f"emergency_step{engine.global_steps}"
    return engine.save_checkpoint(save_dir, tag=tag, save_latest=True,
                                  async_save=False)


class PreemptionHandler:
    """SIGTERM/SIGINT -> emergency save, then the prior handler."""

    def __init__(self, engine, save_dir_fn: Callable[[], Optional[str]],
                 signals=("SIGTERM", "SIGINT"), tag: Optional[str] = None,
                 chain: bool = True):
        self.engine = engine
        self._save_dir_fn = save_dir_fn
        self._signal_names = tuple(signals)
        self._tag = tag
        self._chain = chain
        self._prev = {}
        self.triggered: Optional[int] = None
        self.saved_path: Optional[str] = None

    def install(self) -> "PreemptionHandler":
        for name in self._signal_names:
            signum = getattr(signal, name)
            self._prev[signum] = signal.signal(signum, self._handle)
        return self

    def uninstall(self) -> None:
        for signum, prev in self._prev.items():
            signal.signal(signum, prev)
        self._prev = {}

    def _handle(self, signum, frame):
        self.triggered = signum
        save_dir = self._save_dir_fn()
        if save_dir is None:
            logger.warning(
                f"signal {signum}: no checkpoint directory known "
                "(resilience.checkpoint_dir unset and nothing saved yet) — "
                "emergency save skipped")
        else:
            try:
                self.saved_path = emergency_save(self.engine, save_dir,
                                                 tag=self._tag)
                logger.warning(f"signal {signum}: emergency checkpoint at "
                               f"{self.saved_path}")
            except Exception as e:  # ds-tpu: lint-ok[PY001] — the process is
                # dying either way; a failed save must still chain to the
                # prior handler so termination semantics are preserved
                logger.error(f"signal {signum}: emergency save failed: {e}")
        self._deliver_prior(signum, frame)

    def _deliver_prior(self, signum, frame):
        prev = self._prev.get(signum)
        if not self._chain:
            return
        if callable(prev):
            prev(signum, frame)
        elif prev == signal.SIG_DFL:
            # restore and re-deliver: the default action (terminate) runs
            # exactly as if this handler never existed
            signal.signal(signum, signal.SIG_DFL)
            os.kill(os.getpid(), signum)
        # SIG_IGN: nothing to do


class Watchdog:
    """Daemon thread that aborts when one train step hangs.

    Armed between ``step_started()`` and ``step_finished()`` only — idle
    time between steps (evaluation, user code, waiting on data) never
    trips it.
    """

    def __init__(self, engine, step_timeout_s: float,
                 poll_interval_s: float = 0.0, exit_code: int = 70,
                 abort_fn: Optional[Callable[[str], None]] = None):
        self.engine = engine
        self.step_timeout_s = float(step_timeout_s)
        self.poll_interval_s = (float(poll_interval_s) if poll_interval_s > 0
                                else max(0.05, self.step_timeout_s / 4))
        self.exit_code = exit_code
        self._abort_fn = abort_fn
        self._lock = threading.Lock()
        self._armed_at: Optional[float] = None
        self._stop = threading.Event()
        self.fired = False
        self.last_report: Optional[str] = None
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="ds-tpu-watchdog")

    def start(self) -> "Watchdog":
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()

    def step_started(self) -> None:
        with self._lock:
            self._armed_at = time.monotonic()

    def step_finished(self) -> None:
        with self._lock:
            self._armed_at = None

    def _run(self):
        while not self._stop.wait(self.poll_interval_s):
            with self._lock:
                armed_at = self._armed_at
            if armed_at is None:
                continue
            stuck_s = time.monotonic() - armed_at
            if stuck_s >= self.step_timeout_s and not self.fired:
                self.fired = True
                self._fire(stuck_s)
                return

    def _fire(self, stuck_s: float):
        report = self._diagnostics(stuck_s)
        self.last_report = report
        logger.error(report)
        if self._abort_fn is not None:
            self._abort_fn(report)
        else:
            # clean abort: a distinct exit code the orchestrator restarts
            # on; os._exit because the main thread is, by definition, stuck
            os._exit(self.exit_code)

    def _diagnostics(self, stuck_s: float) -> str:
        eng = self.engine
        lines = [
            f"WATCHDOG: train step stuck for {stuck_s:.1f}s "
            f"(step_timeout_s={self.step_timeout_s})",
            f"  last completed step: {getattr(eng, 'global_steps', '?')}",
            f"  pending async checkpoint: "
            f"{getattr(eng, '_pending_ckpt', None)}",
        ]
        frames = sys._current_frames()
        names = {t.ident: t.name for t in threading.enumerate()}
        for ident, frame in frames.items():
            if ident == threading.get_ident():
                continue
            lines.append(f"  -- thread {names.get(ident, ident)} stack:")
            lines.extend("    " + ln.rstrip()
                         for ln in traceback.format_stack(frame))
        return "\n".join(lines)
