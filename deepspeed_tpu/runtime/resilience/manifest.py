"""Checkpoint integrity manifests, atomic ``latest`` tags, retention GC.

Durability must be verified, not assumed (the Orbax-async lesson): a
checkpoint directory on a shared filesystem can hold torn writes —
truncated shard files from a crash mid-save, partially replicated
objects, or a ``latest`` tag pointing at a save that never finished.
Every save therefore writes a ``manifest.json`` at the tag root listing
each file's size and digest; every load verifies the manifest before
restoring and, on mismatch, walks the retained-tag chain to the newest
*verified-good* checkpoint instead of crashing.

No jax imports: verification is pure file I/O, so the chaos CLI and
tests can check checkpoints without touching the accelerator stack.

Layout under ``save_dir``::

    save_dir/latest              <- tag name, written atomically
    save_dir/<tag>/manifest.json <- this module's integrity record
    save_dir/<tag>/state/...     <- orbax tree (opaque here; hashed as files)
    save_dir/<tag>/engine_meta.json
"""

import hashlib
import json
import os
import shutil
import zlib
from typing import Dict, List, Optional, Tuple

from ...utils.logging import logger

MANIFEST_FILE = "manifest.json"
LATEST_FILE = "latest"      # single source of truth; checkpointing imports it
QUARANTINE_FILE = MANIFEST_FILE + ".quarantined"
MANIFEST_VERSION = 1
_CHUNK = 1 << 20


class CheckpointCorruptionError(Exception):
    """A checkpoint failed integrity verification and no verified-good
    fallback tag exists."""


def _digest_file(path: str, algorithm: str) -> str:
    if algorithm == "crc32":
        crc = 0
        with open(path, "rb") as f:
            while True:
                chunk = f.read(_CHUNK)
                if not chunk:
                    break
                crc = zlib.crc32(chunk, crc)
        return f"{crc & 0xFFFFFFFF:08x}"
    if algorithm == "sha256":
        h = hashlib.sha256()
        with open(path, "rb") as f:
            while True:
                chunk = f.read(_CHUNK)
                if not chunk:
                    break
                h.update(chunk)
        return h.hexdigest()
    raise ValueError(f"unknown digest algorithm {algorithm!r}")


def _walk_files(tag_path: str) -> List[str]:
    """Relative paths of every file under the tag dir, except the manifest
    itself (it cannot self-certify). Sorted for a stable manifest."""
    out = []
    for root, _dirs, files in os.walk(tag_path):
        for name in files:
            rel = os.path.relpath(os.path.join(root, name), tag_path)
            if rel != MANIFEST_FILE:
                out.append(rel.replace(os.sep, "/"))
    return sorted(out)


def write_manifest(tag_path: str, *, step: Optional[int] = None,
                   tag: Optional[str] = None,
                   algorithm: str = "crc32") -> str:
    """Record every file under ``tag_path`` (size + digest) into
    ``manifest.json``. Written tmp-then-replace so a crash mid-write
    leaves either no manifest (tag unverifiable -> skipped by the
    fallback walk) or a complete one — never a torn manifest that
    'verifies' garbage."""
    files: Dict[str, Dict[str, object]] = {}
    for rel in _walk_files(tag_path):
        full = os.path.join(tag_path, rel)
        files[rel] = {"size": os.path.getsize(full),
                      "digest": _digest_file(full, algorithm)}
    manifest = {
        "version": MANIFEST_VERSION,
        "tag": tag if tag is not None else os.path.basename(tag_path),
        "step": step,
        "algorithm": algorithm,
        "framework_version": _framework_version(),
        "files": files,
    }
    path = os.path.join(tag_path, MANIFEST_FILE)
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(manifest, f, indent=1, sort_keys=True)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)
    _fsync_dir(tag_path)
    return path


def _framework_version() -> str:
    try:
        from ... import __version__
        return __version__
    except ImportError:
        return "unknown"


def read_manifest(tag_path: str) -> Optional[dict]:
    """The parsed manifest, or None when absent/unparseable (a torn
    manifest means the tag is unverifiable, not that verification
    should crash)."""
    path = os.path.join(tag_path, MANIFEST_FILE)
    if not os.path.isfile(path):
        return None
    try:
        with open(path) as f:
            return json.load(f)
    except (OSError, ValueError) as e:
        logger.warning(f"unreadable checkpoint manifest {path}: {e}")
        return None


def verify_manifest(tag_path: str) -> List[str]:
    """Check every manifest-listed file's existence, size, and digest.
    Returns the list of mismatch descriptions — empty means verified.
    A missing manifest is itself a finding (the tag is unverifiable)."""
    manifest = read_manifest(tag_path)
    if manifest is None:
        return [f"no readable {MANIFEST_FILE} under {tag_path}"]
    algorithm = manifest.get("algorithm", "crc32")
    if algorithm not in ("crc32", "sha256"):
        # corrupted field, or a newer framework's algorithm: the tag is
        # unverifiable — a verification ERROR, never a crash (the fallback
        # machinery must survive exactly this kind of damaged metadata)
        return [f"unknown digest algorithm {algorithm!r} in manifest"]
    errors = []
    for rel, rec in manifest.get("files", {}).items():
        full = os.path.join(tag_path, rel)
        if not os.path.isfile(full):
            errors.append(f"{rel}: missing")
            continue
        size = os.path.getsize(full)
        if size != rec.get("size"):
            errors.append(f"{rel}: size {size} != manifest {rec.get('size')}"
                          " (torn write?)")
            continue
        digest = _digest_file(full, algorithm)
        if digest != rec.get("digest"):
            errors.append(f"{rel}: {algorithm} {digest} != manifest "
                          f"{rec.get('digest')}")
    return errors


def manifest_step(tag_path: str) -> Optional[int]:
    manifest = read_manifest(tag_path)
    return manifest.get("step") if manifest else None


def list_tags(save_dir: str) -> List[Tuple[str, Optional[int]]]:
    """Every tag directory under ``save_dir`` paired with its manifest
    step (None for unmanifested tags), newest first — manifested tags
    ordered by step, unmanifested tags last by mtime."""
    entries = []
    if not os.path.isdir(save_dir):
        return entries
    for name in os.listdir(save_dir):
        path = os.path.join(save_dir, name)
        if not os.path.isdir(path):
            continue
        step = manifest_step(path)
        mtime = os.path.getmtime(path)
        entries.append((name, step, mtime))
    entries.sort(key=lambda e: (e[1] is not None,
                                e[1] if e[1] is not None else 0, e[2]),
                 reverse=True)
    return [(name, step) for name, step, _ in entries]


def resolve_verified_tag(save_dir: str, prefer_tag: Optional[str] = None
                         ) -> Tuple[Optional[str], Dict[str, List[str]]]:
    """The tag to restore: ``prefer_tag`` when it verifies (or carries no
    manifest — legacy saves stay loadable), else the newest tag whose
    manifest verifies. Returns (tag, {tag: errors}) where the error map
    covers every rejected candidate; (None, errors) when nothing
    survives."""
    errors: Dict[str, List[str]] = {}
    candidates = []
    if prefer_tag is not None:
        candidates.append(prefer_tag)
    candidates.extend(t for t, _ in list_tags(save_dir)
                      if t not in candidates)
    for tag in candidates:
        tag_path = os.path.join(save_dir, tag)
        if not os.path.isdir(tag_path):
            errors[tag] = ["tag directory does not exist"]
            continue
        if os.path.isfile(os.path.join(tag_path, QUARANTINE_FILE)):
            # integrity-valid but numerically unhealthy (a rollback landed
            # on it and found non-finite params): never restore it again,
            # not even as an explicitly requested legacy tag
            errors[tag] = ["quarantined (restored params were non-finite)"]
            continue
        if read_manifest(tag_path) is None:
            if tag == prefer_tag:
                # pre-manifest checkpoint explicitly (or via latest)
                # requested: integrity cannot be checked, honor it
                return tag, errors
            errors[tag] = [f"no {MANIFEST_FILE} (unverifiable)"]
            continue
        errs = verify_manifest(tag_path)
        if not errs:
            return tag, errors
        errors[tag] = errs
    return None, errors


def quarantine_tag(tag_path: str) -> None:
    """Mark an integrity-valid tag as numerically unhealthy: the manifest
    is renamed aside, so the tag drops out of the fallback walk (and the
    prefer-tag legacy path — ``resolve_verified_tag`` checks the marker)
    while its files stay on disk for post-mortem. Used by rollback when a
    restored checkpoint turns out to hold non-finite params — a save that
    landed inside an undetected divergence window."""
    src = os.path.join(tag_path, MANIFEST_FILE)
    if os.path.isfile(src):
        os.replace(src, os.path.join(tag_path, QUARANTINE_FILE))
    else:
        # legacy/unmanifested tag: the marker alone blocks restoration
        with open(os.path.join(tag_path, QUARANTINE_FILE), "w") as f:
            f.write("{}")
    logger.warning(f"checkpoint quarantined (non-finite params): {tag_path}")


def write_latest(save_dir: str, tag: str) -> None:
    """Publish the ``latest`` tag durably: tmp file + fsync + atomic
    ``os.replace`` + directory fsync. A crash at any point leaves either
    the previous ``latest`` or the new one — never a truncated tag file
    that breaks every future load."""
    path = os.path.join(save_dir, LATEST_FILE)
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        f.write(str(tag))
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)
    _fsync_dir(save_dir)


def _fsync_dir(path: str) -> None:
    """fsync a directory so a rename inside it is durable (POSIX); some
    filesystems/platforms refuse O_RDONLY dir fsync — degrade silently,
    the rename itself is still atomic."""
    try:
        fd = os.open(path, os.O_RDONLY)
    except OSError:
        return
    try:
        os.fsync(fd)
    except OSError:
        pass
    finally:
        os.close(fd)


def gc_checkpoints(save_dir: str, keep_last_n: int,
                   protect: Tuple[str, ...] = ()) -> List[str]:
    """Delete the oldest *manifested* tag directories beyond
    ``keep_last_n``, never touching ``protect`` entries, the tag
    ``latest`` points at, or unmanifested directories (they may be user
    data this framework does not own). Returns the removed tag names."""
    if keep_last_n <= 0:
        return []
    protected = set(protect)
    latest_path = os.path.join(save_dir, LATEST_FILE)
    if os.path.isfile(latest_path):
        try:
            with open(latest_path) as f:
                protected.add(f.read().strip())
        except OSError:
            pass
    managed = [t for t, step in list_tags(save_dir) if step is not None]
    removed = []
    for tag in managed[keep_last_n:]:
        if tag in protected:
            continue
        shutil.rmtree(os.path.join(save_dir, tag), ignore_errors=True)
        removed.append(tag)
    if removed:
        logger.info(f"checkpoint GC (keep_last_n={keep_last_n}): removed "
                    f"{removed}")
    return removed
