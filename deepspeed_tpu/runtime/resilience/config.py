"""Resilience configuration (the ``resilience`` config block).

Stdlib-only on purpose (same contract as ``serving/config.py``):
``runtime/config.py`` wires this dataclass into ``DeepSpeedConfig``, and
that module must stay importable without jax for dependency-free tooling
jobs.

Reference frame: DeepSpeed's engine hardens the same paths imperatively —
skipped-step overflow handling, the ``latest``-tag checkpoint discipline,
elasticity's restart contract. Here the knobs are declarative and the
mechanisms live in ``runtime/resilience/`` (docs/resilience.md has the
failure model and recovery matrix).
"""

from dataclasses import dataclass, field
from typing import List, Optional

from ..config_utils import DeepSpeedConfigError, dict_to_dataclass

_DIGESTS = ("crc32", "sha256")


@dataclass
class IntegrityConfig:
    """Checkpoint integrity: every save writes a ``manifest.json`` (per-file
    sizes + digests); loads verify it and fall back along the retained-tag
    chain on mismatch instead of restoring corrupt state."""
    enabled: bool = True
    algorithm: str = "crc32"          # crc32 (fast) | sha256 (cryptographic)
    verify_on_load: bool = True
    fallback_on_corruption: bool = True
    keep_last_n: int = 0              # 0 = retain every tag (no GC)

    def __post_init__(self):
        if self.algorithm not in _DIGESTS:
            raise DeepSpeedConfigError(
                f"resilience.integrity.algorithm must be one of {_DIGESTS}, "
                f"got {self.algorithm!r}")
        if self.keep_last_n < 0:
            raise DeepSpeedConfigError(
                "resilience.integrity.keep_last_n must be >= 0, got "
                f"{self.keep_last_n}")


@dataclass
class DivergenceConfig:
    """Divergence sentinel: per-step non-finite / exploding loss & grad-norm
    flags fold into an on-device accumulator (no per-step host sync); a host
    check every ``check_interval`` steps triggers rollback to the last
    verified-good checkpoint after ``patience`` consecutive bad steps."""
    enabled: bool = True
    patience: int = 3                 # consecutive bad steps before rollback
    check_interval: int = 10          # host-check cadence (optimizer steps)
    loss_abs_threshold: float = 0.0   # |loss| above this is "bad" (0 = off)
    grad_norm_threshold: float = 0.0  # grad norm above this is "bad" (0 = off)
    max_rollbacks: int = 3            # give up (raise) past this many
    reseed_on_rollback: bool = False  # fold the rollback count into the rng

    def __post_init__(self):
        if self.patience < 1:
            raise DeepSpeedConfigError(
                f"resilience.divergence.patience must be >= 1, got "
                f"{self.patience}")
        if self.check_interval < 1:
            raise DeepSpeedConfigError(
                f"resilience.divergence.check_interval must be >= 1, got "
                f"{self.check_interval}")
        if self.max_rollbacks < 0:
            raise DeepSpeedConfigError(
                "resilience.divergence.max_rollbacks must be >= 0, got "
                f"{self.max_rollbacks}")


@dataclass
class PreemptionConfig:
    """Preemption handling: on the listed signals, join any in-flight async
    save and write a best-effort emergency checkpoint before the process
    goes down (Varuna-style preemptible-capacity discipline)."""
    enabled: bool = False
    signals: List[str] = field(
        default_factory=lambda: ["SIGTERM", "SIGINT"])
    emergency_tag: Optional[str] = None   # default: emergency_step{N}
    chain_handler: bool = True            # re-deliver to the prior handler

    def __post_init__(self):
        import signal as _signal
        for name in self.signals:
            if not hasattr(_signal, name):
                raise DeepSpeedConfigError(
                    f"resilience.preemption.signals entry {name!r} is not a "
                    "signal name (e.g. SIGTERM, SIGINT)")


@dataclass
class WatchdogConfig:
    """Hang detection: a daemon thread that fires when a train step stays
    in flight past ``step_timeout_s``, dumps diagnostics (last good step,
    pending checkpoint state, live stacks) and aborts cleanly."""
    enabled: bool = False
    step_timeout_s: float = 1800.0
    poll_interval_s: float = 0.0      # 0 -> step_timeout_s / 4
    exit_code: int = 70               # EX_SOFTWARE; orchestrators restart on it

    def __post_init__(self):
        if self.step_timeout_s <= 0:
            raise DeepSpeedConfigError(
                "resilience.watchdog.step_timeout_s must be > 0, got "
                f"{self.step_timeout_s}")
        if self.poll_interval_s < 0:
            raise DeepSpeedConfigError(
                "resilience.watchdog.poll_interval_s must be >= 0, got "
                f"{self.poll_interval_s}")


@dataclass
class ResilienceConfig:
    """Top-level ``resilience`` block. ``checkpoint_dir`` is the rollback /
    emergency-save root; when unset, the engine uses the directory of its
    most recent ``save_checkpoint`` call."""
    enabled: bool = True
    checkpoint_dir: Optional[str] = None
    integrity: IntegrityConfig = field(default_factory=IntegrityConfig)
    divergence: DivergenceConfig = field(default_factory=DivergenceConfig)
    preemption: PreemptionConfig = field(default_factory=PreemptionConfig)
    watchdog: WatchdogConfig = field(default_factory=WatchdogConfig)

    def __post_init__(self):
        if isinstance(self.integrity, dict):
            self.integrity = dict_to_dataclass(
                IntegrityConfig, self.integrity, "resilience.integrity")
        if isinstance(self.divergence, dict):
            self.divergence = dict_to_dataclass(
                DivergenceConfig, self.divergence, "resilience.divergence")
        if isinstance(self.preemption, dict):
            self.preemption = dict_to_dataclass(
                PreemptionConfig, self.preemption, "resilience.preemption")
        if isinstance(self.watchdog, dict):
            self.watchdog = dict_to_dataclass(
                WatchdogConfig, self.watchdog, "resilience.watchdog")
