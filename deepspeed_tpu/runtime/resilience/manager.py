"""ResilienceManager — the engine-facing coordinator.

One object owns the fault-tolerance lifecycle around the train loop:

- per-step: heartbeat the watchdog, run step-scoped fault injections,
  fold the step's health scalars into the on-device sentinel;
- per-cadence (``divergence.check_interval`` steps): ONE host read of
  the consecutive-bad counter; at ``patience`` consecutive bad steps,
  roll back to the newest verified-good checkpoint and resume;
- at init: install the preemption signal handler and start the watchdog
  when their blocks opt in.

Every transition (divergence detected, rollback, emergency save) emits
a monitor event through the engine's buffered monitor path and is
recorded host-side in ``self.events`` so tests and the chaos CLI can
assert on the exact recovery sequence.
"""

from typing import List, Optional, Tuple

from ...observability.goodput import timed as _goodput
from ...observability.metrics import get_registry
from ...observability.trace import span as _span
from ...utils.logging import logger, log_dist
from .faults import active_injector
from .sentinel import DivergenceError, DivergenceSentinel


class ResilienceManager:
    def __init__(self, engine, config):
        self.engine = engine
        self.config = config
        self.rollbacks = 0
        self.events: List[Tuple[str, float, int]] = []  # (label, value, step)
        self.sentinel = (DivergenceSentinel(config.divergence)
                         if config.divergence.enabled else None)
        self.preemption = None
        if config.preemption.enabled:
            from .preemption import PreemptionHandler
            self.preemption = PreemptionHandler(
                engine, self.checkpoint_dir,
                signals=tuple(config.preemption.signals),
                tag=config.preemption.emergency_tag,
                chain=config.preemption.chain_handler).install()
        self.watchdog = None
        if config.watchdog.enabled:
            from .preemption import Watchdog
            self.watchdog = Watchdog(
                engine, config.watchdog.step_timeout_s,
                poll_interval_s=config.watchdog.poll_interval_s,
                exit_code=config.watchdog.exit_code).start()

    # ------------------------------------------------------------------
    def checkpoint_dir(self) -> Optional[str]:
        """Rollback/emergency root: the configured dir, else wherever the
        engine last saved."""
        return (self.config.checkpoint_dir
                or getattr(self.engine, "_last_save_dir", None))

    def close(self) -> None:
        if self.preemption is not None:
            self.preemption.uninstall()
        if self.watchdog is not None:
            self.watchdog.stop()

    # -- train-loop hooks --------------------------------------------------
    def on_step_start(self) -> None:
        if self.watchdog is not None:
            self.watchdog.step_started()
        inj = active_injector()
        if inj is not None:
            inj.on_step_start(self.engine.global_steps, self.engine)

    def on_step_end(self, metrics: dict) -> None:
        """After the step's bookkeeping: disarm the watchdog, run
        post-step injections, fold health, host-check on cadence. Device
        work here is a handful of asynchronous scalar ops; the only
        device->host sync is the cadence-gated sentinel read."""
        eng = self.engine
        if self.watchdog is not None:
            self.watchdog.step_finished()
        inj = active_injector()
        if inj is not None:
            inj.on_step_end(eng.global_steps, eng)
        if self.sentinel is None:
            return
        self.sentinel.fold(metrics)
        if eng.global_steps % self.config.divergence.check_interval == 0:
            self._host_check()

    def on_allocation_failure(self, forensics_path: str) -> None:
        """Device OOM during a dispatch (the engine already wrote the
        memory-forensics dump — observability/memory.py): record the
        event on the emergency path so the recovery timeline shows the
        allocation failure alongside rollbacks and preemptions."""
        self._emit("resilience/oom_forensics", 1.0,
                   self.engine.global_steps)
        logger.error(
            f"resilience: device allocation failure at step "
            f"{self.engine.global_steps}; forensics at {forensics_path}")

    # -- divergence / rollback ---------------------------------------------
    def _host_check(self) -> None:
        consec = self.sentinel.read_consecutive()
        if consec < self.config.divergence.patience:
            return
        eng = self.engine
        self._emit("resilience/divergence_detected", consec,
                   eng.global_steps)
        self.rollback(reason=f"{consec} consecutive bad steps "
                      f"(patience={self.config.divergence.patience})")

    def rollback(self, reason: str = "") -> str:
        """Restore the newest verified-good checkpoint and resume. Raises
        ``DivergenceError`` when rollback is exhausted or impossible —
        silently continuing a diverged run corrupts it."""
        eng = self.engine
        cfg = self.config.divergence
        if self.rollbacks >= cfg.max_rollbacks:
            raise DivergenceError(
                f"training diverged ({reason}) and max_rollbacks="
                f"{cfg.max_rollbacks} is exhausted — the run is not "
                "recovering; inspect data/LR before resuming")
        load_dir = self.checkpoint_dir()
        if load_dir is None:
            raise DivergenceError(
                f"training diverged ({reason}) but no checkpoint exists to "
                "roll back to — set resilience.checkpoint_dir or call "
                "save_checkpoint() periodically")
        self.rollbacks += 1
        logger.warning(f"resilience: rolling back ({reason}) — restoring "
                       f"from {load_dir} [rollback {self.rollbacks}/"
                       f"{cfg.max_rollbacks}]")
        # the restore walk is badput: the span + goodput ledger attribute
        # its wall clock to rollback_recovery, so a chaos-injected
        # rollback is visible in /metrics and the goodput breakdown
        with _span("rollback_recovery"), _goodput("rollback_recovery"):
            path = self._load_healthy(load_dir, reason)
            if cfg.reseed_on_rollback:
                import jax
                # shift the rng stream so the resumed run draws a
                # different data/dropout order and does not march into
                # the same cliff
                eng.rng = jax.random.fold_in(eng.rng, 0x5EED + self.rollbacks)
        if self.sentinel is not None:   # rollback() is callable with the
            self.sentinel.reset()       # sentinel disabled (public API)
        self._emit("resilience/rollback", self.rollbacks, eng.global_steps)
        log_dist(f"resilience: resumed from {path} at step "
                 f"{eng.global_steps}", ranks=[0])
        return path

    def _load_healthy(self, load_dir: str, reason: str) -> str:
        """Restore the newest verified tag whose params are actually
        FINITE. Manifest verification proves file integrity, not numeric
        health — a periodic save that landed inside an undetected
        divergence window is manifest-valid NaN state, and restoring it
        would just re-trigger until max_rollbacks. Such tags are
        quarantined (dropped from the walk, files kept for post-mortem)
        and the walk continues to the next older tag."""
        import jax
        from .manifest import list_tags, quarantine_tag, write_latest
        # bounded: each failed attempt quarantines one tag
        attempts = len(list_tags(load_dir)) + 1
        for attempt in range(attempts):
            path, _ = self.engine.load_checkpoint(load_dir)
            if path is None:
                raise DivergenceError(
                    f"training diverged ({reason}) and no loadable "
                    f"checkpoint was found under {load_dir}")
            if self._params_finite():
                return path
            self._emit("resilience/checkpoint_quarantined", 1.0,
                       self.engine.global_steps)
            # filesystem mutations from process 0 only (same discipline
            # as checkpoint publication); the finite verdict came from a
            # global device reduction, so every process agrees on it
            if jax.process_index() == 0:
                quarantine_tag(path)
                # point latest past the quarantined tag so the next
                # iteration (and any later restart) walks straight to the
                # survivor set
                newest = next((t for t, s in list_tags(load_dir)
                               if s is not None), None)
                if newest is not None:
                    write_latest(load_dir, newest)
            if jax.process_count() > 1:
                from jax.experimental import multihost_utils
                multihost_utils.sync_global_devices(
                    f"quarantine_{self.rollbacks}_{attempt}")
        raise DivergenceError(
            f"training diverged ({reason}) and every retained checkpoint "
            f"under {load_dir} holds non-finite params")

    def _params_finite(self) -> bool:
        """Global all-finite reduction over the float param leaves, run
        under jit so sharded (incl. multi-host) arrays reduce correctly;
        the replicated scalar verdict is identical on every process. One
        rare host read per rollback attempt, never on the step path."""
        import jax
        import jax.numpy as jnp
        leaves = [p for p in jax.tree.leaves(self.engine.params)
                  if jnp.issubdtype(p.dtype, jnp.floating)]
        if not leaves:
            return True
        ok = jax.jit(lambda ls: jnp.all(jnp.stack(
            [jnp.all(jnp.isfinite(l)) for l in ls])))(leaves)
        return bool(ok)  # ds-tpu: lint-ok[TS002] — rollback-only read

    # -- event plumbing ----------------------------------------------------
    def _emit(self, label: str, value, step: int) -> None:
        """Host-side event record + the engine's buffered monitor path.
        Transitions are rare, so flush immediately — a post-mortem must
        see the rollback event even if the run dies next step. Every
        event also bumps a cumulative counter in the shared
        observability registry, so ``ds_tpu_report`` / metrics snapshots
        show recovery activity alongside throughput — under a distinct
        ``<label>/total`` name, because the registry flush writes
        counters to the SAME monitor fan-out and the bare label already
        carries this event's immediate value/step semantics below."""
        self.events.append((label, float(value), step))
        get_registry().counter(f"{label}/total").inc()
        eng = self.engine
        if getattr(eng, "monitor", None) is not None and eng.monitor.enabled:
            eng.monitor.write_event(label, float(value), step)
