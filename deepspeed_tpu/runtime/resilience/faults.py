"""Deterministic fault injection — recovery paths are tested, not trusted.

Every recovery mechanism in this package (manifest fallback, divergence
rollback, emergency save, watchdog abort) is exercised by tier-1 tests
through this harness rather than waiting for production to produce the
failure. Faults are *deterministic*: they fire at chosen engine steps /
save ordinals, are one-shot by default (a rollback rewinds
``global_steps``, so a step-matched fault must not re-fire when the
counter passes the same value again), and log every firing for
assertions.

Fault kinds:

- ``nan_grads``  — at step(s) k..k+repeat-1, poison the float params the
  way a NaN gradient burst would (the post-update state of an Adam step
  fed NaN grads): the next step's loss/grad-norm go non-finite and the
  divergence sentinel sees exactly the injected burst.
- ``torn_write`` — on the Nth checkpoint save, truncate or delete a shard
  file AFTER the manifest is written: the on-disk state a crash mid-copy
  (or a shared-FS partial replication) leaves behind, with ``latest``
  already pointing at the damaged tag.
- ``delay_step`` — sleep ``duration_s`` inside step k (exercises the
  watchdog without a real deadlock).
- ``preempt``    — raise ``signum`` against this process at step k
  (exercises the emergency-save path with a real signal delivery).
- ``torn_swap``  — at step k, truncate the largest ``.swp`` file in the
  engine's tiering disk tier (runtime/tiering/): the on-disk state a
  crash/filesystem fault leaves mid-swap. The residency manager must
  detect the short read at the next stage-in and re-materialize from
  the protected host copy or raise ``TornSwapError`` — never load
  garbage into a master shard.

Usage::

    plan = [Fault("nan_grads", step=5, repeat=2),
            Fault("torn_write", save_index=1)]
    with injected(plan) as inj:
        ... train ...
    assert inj.fired == [...]

The injector is process-global while installed; the engine and the
checkpoint writer poll ``active_injector()`` at their hook points.
"""

import os
import signal as _signal
import time
from dataclasses import dataclass, field
from typing import List, Optional

from ...utils.logging import logger


@dataclass
class Fault:
    kind: str                          # nan_grads|torn_write|delay_step|preempt
    step: Optional[int] = None         # engine global_steps to fire at
    save_index: Optional[int] = None   # torn_write: Nth save (0-based)
    repeat: int = 1                    # nan_grads: burst length in steps
    duration_s: float = 0.0            # delay_step: sleep length
    signum: int = int(_signal.SIGTERM)  # preempt: signal to raise
    mode: str = "truncate"             # torn_write: truncate | delete
    target_index: int = 0              # torn_write: file rank (largest first)
    fires_left: int = field(init=False)

    def __post_init__(self):
        kinds = ("nan_grads", "torn_write", "delay_step", "preempt",
                 "torn_swap")
        if self.kind not in kinds:
            raise ValueError(f"fault kind must be one of {kinds}, "
                             f"got {self.kind!r}")
        if self.kind == "torn_write":
            if self.save_index is None:
                raise ValueError("torn_write faults fire on a save ordinal: "
                                 "set save_index")
            if self.mode not in ("truncate", "delete"):
                raise ValueError(f"torn_write mode must be truncate|delete, "
                                 f"got {self.mode!r}")
        elif self.step is None:
            raise ValueError(f"{self.kind} faults fire on a step: set step")
        self.fires_left = max(1, self.repeat)


class FaultInjector:
    """Drives a fault plan against the engine/checkpoint hook points."""

    def __init__(self, faults: List[Fault]):
        self.faults = list(faults)
        self.fired: List[tuple] = []   # (kind, where) log for assertions
        self._save_count = 0

    # -- engine hook points ------------------------------------------------
    def on_step_start(self, step: int, engine) -> None:
        """Before the step's device dispatch: delays and preemptions."""
        for f in self.faults:
            if f.fires_left <= 0 or f.step != step:
                continue
            if f.kind == "delay_step":
                f.fires_left -= 1
                self.fired.append(("delay_step", step))
                logger.warning(f"FAULT delay_step: sleeping {f.duration_s}s "
                               f"at step {step}")
                time.sleep(f.duration_s)
            elif f.kind == "preempt":
                f.fires_left -= 1
                self.fired.append(("preempt", step))
                logger.warning(f"FAULT preempt: raising signal {f.signum} "
                               f"at step {step}")
                os.kill(os.getpid(), f.signum)
            elif f.kind == "torn_swap":
                f.fires_left -= 1
                victim = _truncate_swap_file(engine, f.target_index)
                if victim is None:
                    logger.warning("FAULT torn_swap: engine has no disk-"
                                   "tier .swp files to damage")
                    continue
                self.fired.append(("torn_swap", victim))

    def on_step_end(self, step: int, engine) -> None:
        """After the optimizer applied: gradient-poisoning faults. The
        params are set to the state a NaN gradient burst leaves behind
        (every float leaf non-finite), so detection and rollback run
        against realistic post-divergence state."""
        for f in self.faults:
            if (f.kind != "nan_grads" or f.fires_left <= 0
                    or f.step is None or step < f.step
                    or step >= f.step + f.repeat):
                continue
            f.fires_left -= 1
            self.fired.append(("nan_grads", step))
            logger.warning(f"FAULT nan_grads: poisoning params after "
                           f"step {step}")
            engine.params = _poison_params(engine.params)

    # -- checkpoint hook point --------------------------------------------
    def on_checkpoint_saved(self, tag_path: str) -> None:
        """After a save is fully written (manifest included): torn writes."""
        idx = self._save_count
        self._save_count += 1
        for f in self.faults:
            if (f.kind != "torn_write" or f.fires_left <= 0
                    or f.save_index != idx):
                continue
            f.fires_left -= 1
            victim = _pick_victim(tag_path, f.target_index)
            if victim is None:
                logger.warning(f"FAULT torn_write: no data file under "
                               f"{tag_path} to damage")
                continue
            self.fired.append(("torn_write", victim))
            if f.mode == "delete":
                logger.warning(f"FAULT torn_write: deleting {victim}")
                os.remove(victim)
            else:
                size = os.path.getsize(victim)
                logger.warning(f"FAULT torn_write: truncating {victim} "
                               f"({size} -> {size // 2} bytes)")
                with open(victim, "r+b") as fh:
                    fh.truncate(size // 2)


def _poison_params(params):
    """Float leaves -> NaN (what an unguarded optimizer step does with a
    NaN gradient); integer/bool leaves keep their values."""
    import jax
    import jax.numpy as jnp

    def one(p):
        if hasattr(p, "dtype") and jnp.issubdtype(p.dtype, jnp.floating):
            return p * jnp.asarray(float("nan"), p.dtype)
        return p
    return jax.tree.map(one, params)


def _truncate_swap_file(engine, target_index: int) -> Optional[str]:
    """torn_swap: halve the largest ``.swp`` in the engine's tiering
    disk tier (deterministic victim: size-ranked, like torn_write)."""
    tier = getattr(getattr(engine, "tiering", None), "disk", None)
    swap_dir = getattr(tier, "swap_dir", None)
    if swap_dir is None or not os.path.isdir(swap_dir):
        return None
    files = sorted(
        ((-os.path.getsize(os.path.join(swap_dir, n)),
          os.path.join(swap_dir, n))
         for n in os.listdir(swap_dir) if n.endswith(".swp")))
    if not files:
        return None
    victim = files[min(target_index, len(files) - 1)][1]
    size = os.path.getsize(victim)
    logger.warning(f"FAULT torn_swap: truncating {victim} "
                   f"({size} -> {size // 2} bytes)")
    with open(victim, "r+b") as fh:
        fh.truncate(size // 2)
    return victim


def _pick_victim(tag_path: str, target_index: int) -> Optional[str]:
    """Deterministic target file: data files under the tag dir (manifest
    excluded — damaging the manifest makes the tag merely *unverifiable*,
    which is the weaker scenario), largest first."""
    from .manifest import MANIFEST_FILE
    files = []
    for root, _dirs, names in os.walk(tag_path):
        for name in names:
            if name == MANIFEST_FILE:
                continue
            full = os.path.join(root, name)
            files.append((-os.path.getsize(full), full))
    files.sort()
    if not files:
        return None
    return files[min(target_index, len(files) - 1)][1]


# -- process-global installation -------------------------------------------

_ACTIVE: Optional[FaultInjector] = None


def active_injector() -> Optional[FaultInjector]:
    return _ACTIVE


def install(injector: FaultInjector) -> FaultInjector:
    global _ACTIVE
    if _ACTIVE is not None:
        raise RuntimeError("a FaultInjector is already installed")
    _ACTIVE = injector
    return injector


def uninstall() -> None:
    global _ACTIVE
    _ACTIVE = None


class injected:
    """Context manager: ``with injected([Fault(...)]) as inj: ...``"""

    def __init__(self, faults: List[Fault]):
        self.injector = FaultInjector(faults)

    def __enter__(self) -> FaultInjector:
        return install(self.injector)

    def __exit__(self, *exc):
        uninstall()
        return False
