"""Training-time mixed-precision quantization (MoQ).

Reference: runtime/quantize.py (Quantizer, 186 LoC) + weight_quantizer.py
— MoQ anneals weight precision from ``start_bits`` to ``target_bits``
over ``quantize_period`` steps (doubling the period each bit drop), with
an optional eigenvalue mode where layers with larger Hessian curvature
shrink more slowly. The fake-quant snap itself is shared with the
compression package (same grid math as csrc/quantization kernels).
"""

from dataclasses import dataclass
from typing import Dict, List, Optional

import jax
import jax.numpy as jnp

from ..compression.compress import fake_quantize
from ..utils.logging import logger


@dataclass
class MoQConfig:
    """reference: the ``quantize_training`` config block."""
    enabled: bool = False
    quantize_verbose: bool = False
    quantizer_kernel: bool = False          # reference: CUDA kernel; Pallas/XLA here
    quantize_type: str = "symmetric"        # symmetric | asymmetric
    quantize_bits_start: int = 16
    quantize_bits_target: int = 8
    quantize_period: int = 100
    quantize_groups: int = 1
    fp16_mixed_quantize: bool = False
    quantize_change_ratio: float = 0.001
    eigenvalue_enabled: bool = False


class MoQQuantizer:
    """Stepwise bit-annealing quantizer (reference: Quantizer.quantize).

    ``bits(step)``: start_bits, dropping one bit toward target_bits with
    the period doubling at each drop (the reference's
    update_fp16_ratio schedule); per-layer ratios
    (from Eigenvalue) stretch the period of high-curvature layers:
    ``layer_ratios`` maps a param-path substring to its ratio in (0, 1]
    (post_process_eigenvalues output) — smaller ratio = longer period =
    that layer quantizes more slowly.
    """

    def __init__(self, config: MoQConfig,
                 layer_ratios: Optional[Dict[str, float]] = None):
        self.config = config
        self.layer_ratios = dict(layer_ratios or {})
        self._jitted = {}

    def _ratio_for(self, path: str) -> float:
        for pattern, r in self.layer_ratios.items():
            if pattern in path:
                return float(r)
        return 1.0

    def bits_at(self, step: int, ratio: float = 1.0) -> int:
        c = self.config
        bits = c.quantize_bits_start
        period = max(int(c.quantize_period / max(ratio, 1e-3)), 1)
        t = step
        while bits > c.quantize_bits_target and t >= period:
            t -= period
            period *= 2   # each precision drop holds twice as long
            bits = max(bits - 1, c.quantize_bits_target)
        return bits

    def quantize(self, params, step: int):
        """Snap floating-point weight matrices to their current per-layer
        bit grid (bits depend on the layer's eigenvalue ratio)."""
        if not self.config.enabled:
            return params
        sym = self.config.quantize_type == "symmetric"
        flat, treedef = jax.tree.flatten_with_path(params)
        leaf_bits = tuple(
            self.bits_at(step, self._ratio_for(jax.tree_util.keystr(p)))
            for p, _ in flat)
        if all(b >= 16 for b in leaf_bits):  # fp16-mixed region: no snap yet
            return params
        key = (leaf_bits, sym)
        if key not in self._jitted:
            def project(leaves):
                return [fake_quantize(w, bits=b, symmetric=sym)
                        if (b < 16 and hasattr(w, "ndim") and w.ndim >= 2
                            and jnp.issubdtype(w.dtype, jnp.floating)) else w
                        for w, b in zip(leaves, leaf_bits)]
            import zlib
            from ..observability.programs import track_program
            # crc32, not hash(): registry names must agree across
            # processes (PYTHONHASHSEED salts hash() per process)
            tag = f"{zlib.crc32(repr(key).encode()):08x}"
            self._jitted[key] = track_program(
                f"moq/project_{tag}", jax.jit(project), subsystem="moq")
        if self.config.quantize_verbose:
            logger.info(f"MoQ: step {step} -> bits {sorted(set(leaf_bits))}")
        return jax.tree.unflatten(treedef,
                                  self._jitted[key]([w for _, w in flat]))
