"""Communication-compressed optimization (1-bit Adam family).

Reference: runtime/fp16/onebit/{adam,lamb,zoadam}.py over the compressed
allreduce in runtime/comm/nccl.py:51 — after a warmup of exact Adam, the
variance term is frozen and the *momentum* is communicated as 1-bit signs
+ a scale, with the quantization error fed back into the next step
(error-feedback compression).

TPU mapping: XLA already reduces gradients in-network over ICI, so the
wire format of the default path is not ours to change. What this module
provides:

- ``compressed_allreduce(x, axis_name)``: the 1-bit collective itself
  (sign + mean-|x| scale, psum of signs, error feedback returned to the
  caller) for shard_map-based pipelines that own their collectives —
  the EQuARX-style quantized-collective analog.
- ``onebit_adam(...)``: an optax GradientTransformation implementing the
  reference's optimizer math: exact Adam during warmup, then frozen
  variance + error-feedback sign compression of the momentum. The
  compression error lives in the transform state, so convergence behavior
  matches the reference even where the transport is XLA's.
"""

from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp
from jax import lax
import optax

from ..comm.comm import _note_collective, _payload_nbytes


def compress_1bit(x, error):
    """Error-feedback sign compression: returns (signs, scale, new_error).
    corrected = x + error; scale = mean(|corrected|); decompressed =
    scale * sign(corrected); new_error = corrected - decompressed
    (reference: nccl.py compressed_allreduce's server/worker error)."""
    corrected = x + error
    scale = jnp.mean(jnp.abs(corrected))
    signs = jnp.sign(corrected)
    signs = jnp.where(signs == 0, 1.0, signs)  # sign(0) -> +1, like packbits
    new_error = corrected - scale * signs
    return signs, scale, new_error


def _sign_wire_dtype(n):
    """Wire dtype for the sign psum. bf16 (half the fp32 bytes) carries
    partial sums of ±1 EXACTLY only while they fit 8 significand bits —
    integers through 256; at 257 participants a ring partial sum can land
    on a non-representable odd integer and silently round. Past that the
    signs ship fp32 (correctness over compression; chunking the axis
    would preserve the ratio but no current mesh is that deep). ``n`` is
    static (lax.psum of a python int) under shard_map/pmap; a traced size
    conservatively gets fp32."""
    if isinstance(n, int) and n <= 256:
        return jnp.bfloat16
    return jnp.float32


def compressed_allreduce(x, error, axis_name: str):
    """1-bit mean-allreduce inside shard_map/pmap: TWO psums actually on
    the wire — the sign tensor (bf16 while the axis size keeps the ±1
    partial sums exactly representable, see ``_sign_wire_dtype``) and one
    fp32 scalar. Result = mean_scale * mean_sign — the mean-scale
    approximation of mean_i(scale_i*sign_i) (exact when scales agree,
    e.g. axis size 1 or homogeneous shards). Error feedback compensates
    against the value the aggregate ACTUALLY used on this worker's
    behalf, mean_scale*sign_i — i.e. the per-worker aggregation residual
    (scale_i - mean_scale)*sign_i is folded into the carried error
    alongside the local quantization residual, so the mean-scale
    approximation error is re-injected (and corrected) on later steps
    instead of silently accumulating. Returns (reduced, new_error).

    NOTE: upcasting signs to fp32 before the psum (when bf16 is exact)
    would silently ship full fp32 traffic — the whole point of the
    compression (r5 review)."""
    n = lax.psum(1, axis_name)
    corrected = x + error
    scale = jnp.mean(jnp.abs(corrected))
    signs = jnp.sign(corrected)
    signs = jnp.where(signs == 0, 1.0, signs)  # sign(0) -> +1, like packbits
    wire_signs = signs.astype(_sign_wire_dtype(n))
    # wire accounting: the sign tensor in its WIRE dtype plus one fp32
    # scalar — the whole point of the compression is that this is what
    # ships, so this is what the collective accountant records
    wire_bytes = _payload_nbytes(wire_signs) + 4
    with _note_collective("compressed_allreduce", axis_name, wire_signs,
                          nbytes=wire_bytes):
        summed_signs = lax.psum(wire_signs, axis_name).astype(jnp.float32)
        mean_scale = lax.psum(scale, axis_name) / n
    # EF identity per worker: mean_scale*sign_i + new_error_i == x_i + e_i
    new_error = corrected - mean_scale * signs
    return mean_scale * summed_signs / n, new_error


def int8_compressed_allreduce(x, error, axis_name: str, chunk: int = 256):
    """int8 mean-allreduce inside shard_map (pattern: EQuARX — quantized
    AllReduce in XLA, PAPERS.md — and the reference's quantized-gradient
    backends): both wire phases carry int8 + per-chunk fp32 scales, a 4x
    comm-volume cut vs fp32.

    reduce-scatter phase: each participant splits its (error-corrected)
    tensor into N shards, quantizes per ``chunk`` elements, and
    all-to-alls the int8 shards; every participant dequantizes the N
    received shards and sums them in fp32. all-gather phase: the reduced
    shard is re-quantized and all-gathered int8. Error feedback keeps
    the phase-1 quantization residual local, like compress_1bit.
    Returns (mean-reduced x, new_error)."""
    n = lax.psum(1, axis_name)
    flat = x.reshape(-1) + error.reshape(-1)
    size = flat.shape[0]
    pad = (-size) % (n * chunk)
    flat = jnp.pad(flat, (0, pad))

    def quant(v):                       # v [..., chunk] -> int8 + scale
        c = v.reshape(*v.shape[:-1], -1, chunk)
        scale = jnp.max(jnp.abs(c), axis=-1, keepdims=True) / 127.0
        scale = jnp.maximum(scale, 1e-12)
        q = jnp.clip(jnp.round(c / scale), -127, 127).astype(jnp.int8)
        return q, scale.astype(jnp.float32)

    def dequant(q, scale):
        return (q.astype(jnp.float32) * scale).reshape(
            *q.shape[:-2], q.shape[-2] * chunk)

    parts = flat.reshape(n, -1)          # my contribution, one row/peer
    q, s = quant(parts)
    new_error = (flat - dequant(q, s).reshape(-1))[:size].reshape(x.shape)
    # exchange: row j goes to participant j (int8 + scales on the wire);
    # each phase records its ACTUAL wire payload (int8 tensors + fp32
    # scales) in the collective accountant — the 4x comm-volume cut vs
    # fp32 is visible in comm/traced_bytes, not just claimed
    with _note_collective("int8_allreduce", axis_name, q,
                          nbytes=_payload_nbytes(q) + _payload_nbytes(s)):
        qx = lax.all_to_all(q, axis_name, split_axis=0, concat_axis=0,
                            tiled=True)
        sx = lax.all_to_all(s, axis_name, split_axis=0, concat_axis=0,
                            tiled=True)
    my_shard = dequant(qx, sx).sum(axis=0)          # fp32 accumulate
    q2, s2 = quant(my_shard)                        # re-quantize reduced
    with _note_collective("int8_allreduce", axis_name, q2,
                          nbytes=_payload_nbytes(q2) + _payload_nbytes(s2)):
        qg = lax.all_gather(q2, axis_name, tiled=True)
        sg = lax.all_gather(s2, axis_name, tiled=True)
    out = dequant(qg, sg)[: size] / n
    return out.reshape(x.shape), new_error


def _map_compressed(warm, compress, mu, error):
    """Per-leaf (used_momentum, new_error) under a traced warm/frozen
    switch. The pair rides as a {"m","e"} DICT, not a tuple — a tuple
    marker would misfire on params pytrees whose containers are
    themselves tuples (optax allows them), grabbing a subtree as a
    'pair'."""
    pairs = jax.tree.map(
        lambda m, e: jax.lax.cond(
            warm, lambda me: {"m": me["m"], "e": me["e"]},
            lambda me: dict(zip(("m", "e"), compress(me["m"], me["e"]))),
            {"m": m, "e": e}),
        mu, error)
    is_pair = lambda x: isinstance(x, dict) and set(x) == {"m", "e"}
    return (jax.tree.map(lambda p: p["m"], pairs, is_leaf=is_pair),
            jax.tree.map(lambda p: p["e"], pairs, is_leaf=is_pair))


class OneBitAdamState(NamedTuple):
    count: jnp.ndarray
    mu: optax.Updates        # momentum (the compressed quantity)
    nu: optax.Updates        # variance — frozen after warmup
    error: optax.Updates     # error-feedback residual


def onebit_adam(learning_rate, b1: float = 0.9, b2: float = 0.999,
                eps: float = 1e-8, weight_decay: float = 0.0,
                freeze_step: int = 100) -> optax.GradientTransformation:
    """1-bit Adam (reference: OnebitAdam, onebit/adam.py:10): exact Adam
    for ``freeze_step`` warmup steps, then the variance stops updating and
    the momentum passes through error-feedback 1-bit quantization."""

    def init_fn(params):
        z = lambda: jax.tree.map(jnp.zeros_like, params)
        return OneBitAdamState(jnp.zeros((), jnp.int32), z(), z(), z())

    def update_fn(grads, state, params=None):
        count = state.count + 1
        warm = count <= freeze_step

        mu = jax.tree.map(lambda m, g: b1 * m + (1 - b1) * g, state.mu, grads)
        nu = jax.tree.map(
            lambda v, g: jnp.where(warm, b2 * v + (1 - b2) * g * g, v),
            state.nu, grads)

        # after warmup: quantize momentum with error feedback (the values
        # the reference would put on the wire)
        def compress(m, e):
            signs, scale, new_e = compress_1bit(m, e)
            return scale * signs, new_e

        mu_used, error = _map_compressed(warm, compress, mu, state.error)

        bc1 = 1 - b1 ** count.astype(jnp.float32)
        bc2 = 1 - b2 ** jnp.minimum(count, freeze_step).astype(jnp.float32)
        lr = learning_rate(count) if callable(learning_rate) else learning_rate

        if weight_decay and params is None:
            raise ValueError(
                "onebit_adam with weight_decay > 0 needs params (call "
                "update(grads, state, params) — decaying anything else "
                "would be silently wrong)")

        def upd(m, v, p):
            step = (m / bc1) / (jnp.sqrt(v / bc2) + eps)
            if weight_decay:
                step = step + weight_decay * p
            return -lr * step

        updates = jax.tree.map(upd, mu_used, nu,
                               params if params is not None else mu_used)
        return updates, OneBitAdamState(count, mu, nu, error)

    return optax.GradientTransformation(init_fn, update_fn)


def zero_one_adam(learning_rate, b1=0.9, b2=0.999, eps=1e-8,
                  weight_decay=0.0, var_freeze_step: int = 100,
                  var_update_scaler: int = 16,
                  local_step_scaler: int = 1000):
    """0/1 Adam (reference: ZeroOneAdam, onebit/zoadam.py:10): like 1-bit
    Adam but the variance keeps refreshing at a DECAYED cadence after the
    freeze point — intervals start at ``var_update_scaler`` and double
    each refresh (the paper's k_{j+1} = 2 k_j policy), capped at
    ``local_step_scaler`` (fixed cadence from there on). The schedule is
    static, so the traced predicate is a small OR over precomputed
    refresh steps."""

    base = onebit_adam(learning_rate, b1, b2, eps, weight_decay,
                       freeze_step=var_freeze_step)

    # refresh offsets past the freeze point: S, S+2S, S+2S+4S, ... with
    # the interval capped at local_step_scaler
    thresholds = []
    t, interval = 0, var_update_scaler
    while interval < local_step_scaler:
        t += interval
        thresholds.append(t)
        interval *= 2
    cap_anchor = thresholds[-1] if thresholds else 0

    def init_fn(params):
        return base.init(params)

    def update_fn(grads, state, params=None):
        count = state.count + 1
        t_post = count - var_freeze_step
        refresh = jnp.asarray(False)
        for th in thresholds:
            refresh = jnp.logical_or(refresh, t_post == th)
        refresh = jnp.logical_or(
            refresh,
            jnp.logical_and(t_post > cap_anchor,
                            (t_post - cap_anchor) % local_step_scaler == 0))
        refresh = jnp.logical_and(t_post > 0, refresh)
        # borrow the 1-bit step, then optionally refresh the variance
        updates, new_state = base.update(grads, state, params)
        nu = jax.tree.map(
            lambda v, g: jnp.where(refresh, b2 * v + (1 - b2) * g * g, v),
            new_state.nu, grads)
        return updates, new_state._replace(nu=nu)

    return optax.GradientTransformation(init_fn, update_fn)


class OneBitLambState(NamedTuple):
    count: jnp.ndarray
    mu: optax.Updates        # momentum (compressed after warmup)
    nu: optax.Updates        # variance — frozen after warmup
    error: optax.Updates     # error-feedback residual
    frozen_ratio: optax.Updates  # per-leaf trust ratio captured at freeze


def onebit_lamb(learning_rate, b1: float = 0.9, b2: float = 0.999,
                eps: float = 1e-6, weight_decay: float = 0.0,
                freeze_step: int = 100) -> optax.GradientTransformation:
    """1-bit LAMB (reference: OnebitLamb, onebit/lamb.py:11): exact LAMB
    during warmup while recording each layer's trust ratio; after
    ``freeze_step`` the variance stops updating, the momentum passes
    through error-feedback 1-bit quantization (the wire format of the
    reference's compressed allreduce), and the per-layer trust ratios are
    FROZEN at their last warmup value — the reference's 'fused scaling
    coefficients', which cannot be recomputed from compressed momentum."""

    def init_fn(params):
        z = lambda: jax.tree.map(jnp.zeros_like, params)
        ones = jax.tree.map(lambda p: jnp.ones((), jnp.float32), params)
        return OneBitLambState(jnp.zeros((), jnp.int32), z(), z(), z(), ones)

    def update_fn(grads, state, params=None):
        assert params is not None, "onebit_lamb requires params"
        count = state.count + 1
        warm = count <= freeze_step

        mu = jax.tree.map(lambda m, g: b1 * m + (1 - b1) * g, state.mu, grads)
        nu = jax.tree.map(
            lambda v, g: jnp.where(warm, b2 * v + (1 - b2) * g * g, v),
            state.nu, grads)

        def compress(m, e):
            signs, scale, new_e = compress_1bit(m, e)
            return scale * signs, new_e

        mu_used, error = _map_compressed(warm, compress, mu, state.error)

        bc1 = 1 - b1 ** count.astype(jnp.float32)
        bc2 = 1 - b2 ** jnp.minimum(count, freeze_step).astype(jnp.float32)
        lr = learning_rate(count) if callable(learning_rate) else learning_rate

        def leaf_update(m, v, p, fr):
            u = (m / bc1) / (jnp.sqrt(v / bc2) + eps)
            if weight_decay:
                u = u + weight_decay * p
            p_norm = jnp.sqrt(jnp.sum(jnp.square(p)))
            u_norm = jnp.sqrt(jnp.sum(jnp.square(u)))
            live_ratio = jnp.where((p_norm > 0.0) & (u_norm > 0.0),
                                   p_norm / jnp.maximum(u_norm, 1e-30), 1.0)
            # the applied ratio IS the carried state: captured live while
            # warm, frozen (reused) afterwards
            ratio = jnp.where(warm, live_ratio, fr)
            return {"u": -lr * ratio * u, "r": ratio}

        outs = jax.tree.map(leaf_update, mu_used, nu, params,
                            state.frozen_ratio)
        is_out = lambda x: isinstance(x, dict) and set(x) == {"u", "r"}
        updates = jax.tree.map(lambda o: o["u"], outs, is_leaf=is_out)
        frozen = jax.tree.map(lambda o: o["r"], outs, is_leaf=is_out)
        return updates, OneBitLambState(count, mu, nu, error, frozen)

    return optax.GradientTransformation(init_fn, update_fn)
