"""Sparse gradient container.

Reference: runtime/sparse_tensor.py SparseTensor + engine.sparse_allreduce
(engine.py:2248) — torch emits sparse COO grads for
``nn.Embedding(sparse=True)`` and DeepSpeed allreduces (indices, values)
instead of the dense table.

JAX computes dense embedding grads (scatter-add into the table), and
XLA's in-network allreduce makes the dense reduction the fast path on
ICI, so this container exists for (a) API parity, (b) bandwidth-starved
DCN links where row-sparse exchange wins. It holds the row-compressed
form of an embedding gradient; ``sparse_allreduce`` sums over a mesh
axis inside shard_map via gather-of-rows (the reference's
all-gather-based sparse allreduce, engine.py:2295).
"""

from typing import NamedTuple

import jax
import jax.numpy as jnp
from jax import lax


class SparseTensor(NamedTuple):
    """Row-sparse view of a [vocab, dim] gradient (reference surface:
    SparseTensor(indices, values, dense_size))."""
    indices: jnp.ndarray      # [nnz] row ids
    values: jnp.ndarray       # [nnz, dim]
    dense_shape: tuple

    @classmethod
    def from_dense(cls, dense, max_rows: int):
        """Top-|max_rows| nonzero rows (static nnz keeps it jittable)."""
        row_norm = jnp.sum(jnp.abs(dense), axis=tuple(range(1, dense.ndim)))
        _, idx = lax.top_k(row_norm, max_rows)
        return cls(idx, dense[idx], tuple(dense.shape))

    def to_dense(self):
        out = jnp.zeros(self.dense_shape, self.values.dtype)
        return out.at[self.indices].add(self.values)

    @property
    def sparse_size(self):
        return self.indices.size + self.values.size


def sparse_allreduce(st: SparseTensor, axis_name: str) -> SparseTensor:
    """Sum row-sparse grads across an axis inside shard_map: all-gather
    (indices, values) and re-compress (reference: sparse_allreduce's
    gather + unique path).

    Capacity of the result = n_participants * local nnz — the union's true
    upper bound. Compressing back to the local nnz would silently DROP
    rows whenever participants touch different rows (the normal DP case).
    """
    n = lax.psum(1, axis_name)
    all_idx = lax.all_gather(st.indices, axis_name, tiled=True)
    all_val = lax.all_gather(st.values, axis_name, tiled=True)
    dense = jnp.zeros(st.dense_shape, st.values.dtype).at[all_idx].add(all_val)
    capacity = min(int(n) * st.indices.shape[0], st.dense_shape[0])
    return SparseTensor.from_dense(dense, max_rows=capacity)
