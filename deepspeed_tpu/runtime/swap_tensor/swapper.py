"""NVMe tensor swapping (ZeRO-Infinity).

Reference: runtime/swap_tensor/ — AsyncPartitionedParameterSwapper
(partitioned_param_swapper.py:36), PartitionedOptimizerSwapper,
AsyncTensorSwapper (async_swapper.py) over the aio op: host buffers are
written to local-SSD files asynchronously so optimizer/param shards far
larger than host RAM+HBM can be trained.

TPU-native shape: state shards here are numpy arrays (the host side of
the offload path), swapped whole-leaf to one file per leaf. Writes are
fire-and-forget until ``flush``; reads can be prefetched ahead of use —
the same pipelining contract the reference's pipelined_optimizer_swapper
implements with double buffers.
"""

import os
from typing import Dict, Optional

import numpy as np

from ...utils.logging import logger


class AsyncTensorSwapper:
    """Swap named numpy buffers to ``<dir>/<name>.swp`` via async I/O."""

    def __init__(self, swap_dir: str, n_threads: int = 4):
        from ...ops.aio import AsyncIOHandle
        self.swap_dir = swap_dir
        os.makedirs(swap_dir, exist_ok=True)
        self.handle = AsyncIOHandle(n_threads=n_threads)
        self._meta: Dict[str, tuple] = {}      # name -> (shape, dtype)
        self._write_tickets: Dict[str, int] = {}
        self._read_tickets: Dict[str, tuple] = {}  # name -> (ticket, buf)

    def path(self, name: str) -> str:
        """On-disk path for ``name`` (the tiering layer verifies file
        sizes against it)."""
        safe = name.replace("/", "__")
        return os.path.join(self.swap_dir, f"{safe}.swp")

    _path = path

    def swap_out(self, name: str, array: np.ndarray):
        """Async write; the array must not be mutated until flush()."""
        # same-name hazards: an in-flight write to the same file would
        # interleave (torn file) and its popped-unwaited ticket would leak
        # the pinned buffer; an in-flight read would race the write
        if name in self._write_tickets:
            self.handle.wait(self._write_tickets.pop(name))
        if name in self._read_tickets:
            ticket, _buf = self._read_tickets.pop(name)
            self.handle.wait(ticket)
        array = np.ascontiguousarray(array)
        self._meta[name] = (array.shape, array.dtype)
        self._write_tickets[name] = self.handle.pwrite(self._path(name), array)

    def prefetch(self, name: str):
        """Start an async read; pair with swap_in(name)."""
        if name in self._read_tickets:
            return
        if name in self._write_tickets:   # read-after-write hazard
            self.handle.wait(self._write_tickets.pop(name))
        shape, dtype = self._meta[name]
        buf = np.empty(shape, dtype)
        self._read_tickets[name] = (self.handle.pread(self._path(name), buf), buf)

    def swap_in(self, name: str) -> np.ndarray:
        if name not in self._meta:
            raise KeyError(f"nothing swapped out under '{name}'")
        self.prefetch(name)
        ticket, buf = self._read_tickets.pop(name)
        self.handle.wait(ticket)
        return buf

    def discard_read(self, name: str):
        """Drop an in-flight read of ``name`` without trusting its
        result (the tiering layer calls this when the file failed size
        verification — the read may have errored or filled a short
        buffer)."""
        if name in self._read_tickets:
            ticket, _buf = self._read_tickets.pop(name)
            try:
                self.handle.wait(ticket)
            except OSError:
                pass   # a short/failed read of a torn file is expected

    def flush(self):
        """Join all outstanding WRITES (call before reusing source
        buffers). Pending prefetch reads stay in flight — a flush between
        prefetch and swap_in must not consume their tickets."""
        for name in list(self._write_tickets):
            self.handle.wait(self._write_tickets.pop(name))

    def remove(self, name: str):
        self._meta.pop(name, None)
        try:
            os.unlink(self._path(name))
        except FileNotFoundError:
            pass

    def close(self):
        try:
            self.handle.wait_all()
        finally:
            self.handle.close()


class OptimizerStateSwapper:
    """Swap a pytree of host optimizer-state shards to NVMe between steps.

    Reference: PartitionedOptimizerSwapper (runtime/swap_tensor/
    partitioned_optimizer_swapper.py) — state lives on disk except while
    its sub-group steps. Usage: ``swap_out_tree`` after the step,
    ``swap_in_tree`` (or per-leaf prefetch) before the next.
    """

    def __init__(self, swap_dir: str, n_threads: int = 4):
        self.swapper = AsyncTensorSwapper(swap_dir, n_threads=n_threads)

    def swap_out_tree(self, tree, prefix: str = "opt"):
        import jax
        flat, _ = jax.tree.flatten_with_path(tree)
        for path, leaf in flat:
            self.swapper.swap_out(prefix + jax.tree_util.keystr(path),
                                  np.asarray(leaf))
        self.swapper.flush()

    def swap_in_tree(self, tree_template, prefix: str = "opt"):
        import jax
        flat, treedef = jax.tree.flatten_with_path(tree_template)
        names = [prefix + jax.tree_util.keystr(p) for p, _ in flat]
        for n in names:
            self.swapper.prefetch(n)
        leaves = [self.swapper.swap_in(n) for n in names]
        return jax.tree.unflatten(treedef, leaves)

    def close(self):
        self.swapper.close()
