from .swapper import AsyncTensorSwapper, OptimizerStateSwapper

__all__ = ["AsyncTensorSwapper", "OptimizerStateSwapper"]
