"""Tiled linear layers.

Reference: runtime/zero/tiling.py:27 TiledLinear — splits a huge linear
into in/out tiles so ZeRO-3 can fetch/partition pieces instead of one
giant tensor (copy_params_from :206 imports a dense layer's weights).

On TPU the fsdp sharding rules already partition any big matmul, so the
remaining reasons to tile are the reference's other two: bounding the
*transient* memory of gather-before-use (each tile all-gathers
separately under scan) and aligning huge vocab projections to mesh-
divisible chunks. The flax module keeps the reference's splits/API; XLA
fuses the per-tile matmuls back into efficient MXU work.
"""

from typing import Any

import numpy as np
import jax
import jax.numpy as jnp
import flax.linen as nn



def split_dim(total: int, splits: int):
    """Reference: partition_uniform — sizes of each tile (last absorbs)."""
    if splits < 1 or total < splits:
        raise ValueError(f"cannot split {total} into {splits} tiles")
    base = total // splits
    sizes = [base] * splits
    sizes[-1] += total - base * splits
    return sizes


class TiledLinear(nn.Module):
    """y = x @ W + b computed as out-tiles of in-tile partial sums
    (reference: TiledLinear with in_splits x out_splits sub-linears)."""
    features: int
    in_splits: int = 1
    out_splits: int = 1
    use_bias: bool = True
    dtype: Any = jnp.bfloat16
    param_dtype: Any = jnp.float32
    logical_names: tuple = ("embed", "mlp")

    @nn.compact
    def __call__(self, x):
        in_dim = x.shape[-1]
        in_sizes = split_dim(in_dim, self.in_splits)
        out_sizes = split_dim(self.features, self.out_splits)
        in_offs = np.cumsum([0] + in_sizes)
        outs = []
        for o, osz in enumerate(out_sizes):
            acc = None
            for i, isz in enumerate(in_sizes):
                w = self.param(
                    f"tile_{i}_{o}",
                    nn.with_logical_partitioning(
                        nn.initializers.lecun_normal(), self.logical_names),
                    (isz, osz), self.param_dtype)
                xi = jax.lax.slice_in_dim(x, in_offs[i], in_offs[i + 1],
                                          axis=-1)
                part = jnp.dot(xi, w.astype(self.dtype))
                acc = part if acc is None else acc + part
            outs.append(acc)
        y = jnp.concatenate(outs, axis=-1)
        if self.use_bias:
            b = self.param("bias", nn.with_logical_partitioning(
                nn.initializers.zeros, (self.logical_names[-1],)),
                (self.features,), self.param_dtype)
            y = y + b.astype(self.dtype)
        return y

    @staticmethod
    def copy_params_from(dense_kernel, dense_bias, in_splits: int,
                         out_splits: int):
        """Dense weights -> tiled param dict (reference:
        copy_params_from tiling.py:206)."""
        in_dim, out_dim = np.shape(dense_kernel)
        in_sizes = split_dim(in_dim, in_splits)
        out_sizes = split_dim(out_dim, out_splits)
        io = np.cumsum([0] + in_sizes)
        oo = np.cumsum([0] + out_sizes)
        params = {}
        for o in range(out_splits):
            for i in range(in_splits):
                params[f"tile_{i}_{o}"] = jnp.asarray(
                    dense_kernel[io[i]:io[i + 1], oo[o]:oo[o + 1]])
        if dense_bias is not None:
            params["bias"] = jnp.asarray(dense_bias)
        return params
