"""ZeRO package: sharding-rule stages + the ``zero.Init`` construction
context (reference: deepspeed/runtime/zero/ + partition_parameters.py:525).
"""

import jax

from .sharding import (FSDP_AXIS, extract_logical_names, make_opt_state_rules,
                       make_param_rules, param_shardings)
from .tiling import TiledLinear


class Init:
    """Analog of ``deepspeed.zero.Init`` (partition_parameters.py:525).

    The reference intercepts ``nn.Module.__init__`` so every parameter is
    scattered to its ZeRO-3 shard the moment it is constructed — no rank
    ever holds the full model. In JAX, module *construction* is free
    (flax modules are dataclasses; no tensors exist until ``init``), so
    the same guarantee — parameters are born sharded, with no host or
    single-device round-trip — is given by jit-initializing straight into
    the sharded layout (``out_shardings``). ``Init`` packages that:

        with zero.Init(mesh=mesh) as zinit:
            model = GPT(cfg)                       # free, no tensors
        params = zinit.materialize(model, rng, sample_batch)

    ``materialize`` returns the param pytree already partitioned per the
    stage-3 rules (fsdp axis, persistence threshold for small params);
    every device only ever materializes its own shard.

    The context-manager form exists for reference API parity; tracking
    module construction inside the block is unnecessary (and is therefore
    not done) because construction allocates nothing.
    """

    def __init__(self, mesh=None, config=None, config_dict_or_path=None,
                 dtype=None, stage: int = 3,
                 persistence_threshold: int = 0, **_parity_kwargs):
        cfg = config if config is not None else config_dict_or_path
        if cfg is not None:
            from ..config import DeepSpeedConfig
            if not isinstance(cfg, DeepSpeedConfig):
                cfg = DeepSpeedConfig.from_dict(cfg) if isinstance(cfg, dict) \
                    else DeepSpeedConfig.from_file(cfg)
            stage = cfg.zero_optimization.stage
            persistence_threshold = \
                cfg.zero_optimization.stage3_param_persistence_threshold
        self.stage = stage
        self.persistence_threshold = persistence_threshold
        self.dtype = dtype
        self._mesh = mesh

    @property
    def mesh(self):
        if self._mesh is None:
            from ...comm.mesh import get_global_mesh
            self._mesh = get_global_mesh()
        return self._mesh

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False

    def shardings(self, module, rng, *init_args, **init_kwargs):
        """Abstract pass only: (param_shapes, NamedSharding tree)."""
        abstract = jax.eval_shape(
            lambda r: module.init(r, *init_args, **init_kwargs), rng)
        values, names = extract_logical_names(abstract)
        shardings = param_shardings(
            names, values, self.mesh, self.stage, self.persistence_threshold)
        return values, shardings

    def materialize(self, module, rng, *init_args, **init_kwargs):
        """Jit-init ``module`` directly into the ZeRO-sharded layout."""
        _, shardings = self.shardings(module, rng, *init_args, **init_kwargs)

        def init_fn(r):
            variables = module.init(r, *init_args, **init_kwargs)
            values, _ = extract_logical_names(variables)
            if self.dtype is not None:
                values = jax.tree.map(
                    lambda x: x.astype(self.dtype)
                    if jax.numpy.issubdtype(x.dtype, jax.numpy.floating) else x,
                    values)
            return values
        return jax.jit(init_fn, out_shardings=shardings)(rng)


__all__ = ["Init", "TiledLinear", "FSDP_AXIS", "extract_logical_names",
           "make_opt_state_rules", "make_param_rules", "param_shardings"]
