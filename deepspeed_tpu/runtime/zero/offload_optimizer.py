"""ZeRO-Offload / ZeRO-Infinity optimizer with the native cpu_adam kernel.

Reference dataflow (stage_1_and_2.py cpu_offload + csrc/adam/cpu_adam.cpp,
swap via runtime/swap_tensor/): the device reduce-scatters gradients, the
host steps Adam on its fp32 master shard in C++, and the updated weights
are gathered back to the device. Here:

- the jitted grad-step emits gradients already sharded over the DP axes
  (the ZeRO partition) into host-pinned memory,
- each process steps the native kernel over its addressable shards
  (numpy masters + moments in host RAM),
- updated shards are placed back per-device and the param sharding's
  all-gather happens on the subsequent ``device_put`` reshard.

With ``device="nvme"``, the Adam moments live on local SSD between steps
(aio op), prefetched one leaf ahead of the update loop — the pipelined
read/write overlap of the reference's PipelinedOptimizerSwapper.
"""

import os
from typing import Any, Dict, List, Optional

import numpy as np
import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding

from ...utils.logging import logger, log_dist


def _leaf_names(tree):
    flat, _ = jax.tree.flatten_with_path(tree)
    return [jax.tree_util.keystr(p) for p, _ in flat]


class CPUAdamOffloadOptimizer:
    """Host-side Adam over the ZeRO partition of every parameter."""

    def __init__(self, params, grad_shardings, param_shardings,
                 opt_params: Dict[str, Any], adamw: bool = True,
                 nvme_swap_dir: Optional[str] = None, aio_threads: int = 4):
        from ...ops.adam import DeepSpeedCPUAdam

        betas = tuple(opt_params.get("betas", (0.9, 0.999)))
        self.adam = DeepSpeedCPUAdam(
            lr=opt_params.get("lr", 1e-3), betas=betas,
            eps=opt_params.get("eps", 1e-8),
            weight_decay=opt_params.get("weight_decay", 0.0),
            adamw_mode=adamw)
        self.param_shardings = param_shardings
        self.grad_shardings = grad_shardings

        self.swapper = None
        if nvme_swap_dir is not None:
            # the residency manager's DiskTier (verified reads, transfer
            # accounting, ledger stalls) IS the NVMe path now — this
            # optimizer no longer owns a private swapper flavor
            from ..tiering.disk import DiskTier
            # own counter namespace (this is not the residency manager)
            # and NO ledger sites: these waits run inside the engine's
            # timed("compute") dispatch window — booking them again as
            # data_stall would double-count wall clock
            self.swapper = DiskTier(
                os.path.join(nvme_swap_dir, f"proc{jax.process_index()}"),
                n_threads=aio_threads,
                counter_prefix="offload_native_nvme",
                ledger_category=None)

        # Host state per leaf: {index_key: [master, m, v, devices]}
        flat_params, self._treedef = jax.tree.flatten(params)
        flat_gsh = jax.tree.leaves(grad_shardings)
        self._names = _leaf_names(params)
        self._shapes = [p.shape for p in flat_params]
        self._dtypes = [p.dtype for p in flat_params]
        self._state: List[Dict[Any, list]] = []
        for leaf, gsh in zip(flat_params, flat_gsh):
            # view the param through the gradient (ZeRO-partition) sharding
            shard_view = jax.device_put(leaf, _device_memory(gsh))
            per_leaf: Dict[Any, list] = {}
            for shard in shard_view.addressable_shards:
                key = _index_key(shard.index)
                if key in per_leaf:
                    per_leaf[key][3].append(shard.device)
                else:
                    # np.array (not asarray): shard.data views the jax
                    # buffer zero-copy on CPU and arrives read-only
                    master = np.array(shard.data, dtype=np.float32)
                    per_leaf[key] = [master, np.zeros_like(master),
                                     np.zeros_like(master), [shard.device],
                                     shard.index]
            self._state.append(per_leaf)
        self._swap_out_all()

    # -- NVMe swap of the Adam moments ---------------------------------
    def _swap_name(self, li, key, which):
        return f"{self._names[li]}__{key}__{which}"

    def _swap_out_all(self):
        if self.swapper is None:
            return
        for li, per_leaf in enumerate(self._state):
            for key, ent in per_leaf.items():
                self.swapper.swap_out(self._swap_name(li, key, "m"), ent[1])
                self.swapper.swap_out(self._swap_name(li, key, "v"), ent[2])
        self.swapper.flush()
        for per_leaf in self._state:
            for ent in per_leaf.values():
                ent[1] = ent[2] = None  # moments now live on SSD only

    def _prefetch_leaf(self, li):
        if self.swapper is None:
            return
        for key in self._state[li]:
            self.swapper.prefetch(self._swap_name(li, key, "m"))
            self.swapper.prefetch(self._swap_name(li, key, "v"))

    # ------------------------------------------------------------------
    def step(self, grads_tree, lr: float, finite: bool = True):
        """Apply one Adam step; returns the updated param tree (device)."""
        if not finite:
            return None  # caller keeps old params (loss-scale skip)
        # ONE bias-correction step shared by every leaf/shard this call
        self.adam.set_steps(self.adam.steps + 1)
        global_step = self.adam.steps
        flat_grads = jax.tree.leaves(grads_tree)
        flat_psh = jax.tree.leaves(self.param_shardings)
        new_leaves = []
        # one-leaf-ahead NVMe read pipelining via the SHARED double-buffer
        # helper (utils/streaming.py): leaf li+1's moment reads are issued
        # before leaf li's cpu_adam math — the same overlap contract the
        # streamed host walk and the tiering manager use.
        if self.swapper is not None and self._state:
            from ...utils.streaming import double_buffered
            walk = double_buffered(range(len(self._state)),
                                   self._prefetch_leaf)
        else:
            walk = ((li, None) for li in range(len(self._state)))
        for li, _prefetched in walk:
            g_leaf, per_leaf, psh = (flat_grads[li], self._state[li],
                                     flat_psh[li])
            shards = {(_index_key(s.index)): s for s in g_leaf.addressable_shards}
            bufs = []
            for key, ent in per_leaf.items():
                master, m, v, devices, index = ent
                if self.swapper is not None:
                    m = self.swapper.swap_in(self._swap_name(li, key, "m"))
                    v = self.swapper.swap_in(self._swap_name(li, key, "v"))
                # host cpu_adam consumes the grad shard host-side: the
                # d2h here is the native-offload contract, one per leaf
                # per optimizer step (docs/config.md offload_optimizer)
                g = np.array(shards[key].data, dtype=np.float32)  # ds-tpu: lint-ok[TS002]
                flat_master = master.reshape(-1)
                out_dtype = self._dtypes[li]
                out_bf16 = (np.empty(flat_master.shape, np.uint16)
                            if out_dtype == jnp.bfloat16 else None)
                self.adam.step(flat_master, g.reshape(-1), m.reshape(-1),
                               v.reshape(-1), lr=lr, out_bf16=out_bf16,
                               global_step=global_step)
                if out_bf16 is not None:
                    import ml_dtypes
                    updated = out_bf16.view(ml_dtypes.bfloat16).reshape(
                        master.shape)
                else:
                    updated = flat_master.reshape(master.shape).astype(out_dtype)
                for d in devices:
                    # device_put straight from numpy: asarray first would
                    # commit to the default device and pay a second copy
                    bufs.append(jax.device_put(updated, d))
                if self.swapper is not None:
                    self.swapper.swap_out(self._swap_name(li, key, "m"), m)
                    self.swapper.swap_out(self._swap_name(li, key, "v"), v)
            gsh = _device_memory(g_leaf.sharding)
            arr = jax.make_array_from_single_device_arrays(
                self._shapes[li], gsh, bufs)
            new_leaves.append(jax.device_put(arr, psh))  # ZeRO all-gather
        if self.swapper is not None:
            self.swapper.flush()
        return jax.tree.unflatten(self._treedef, new_leaves)

    # -- checkpoint hooks ----------------------------------------------
    def reset_from_params(self, params, skip_moments: bool = False):
        """Re-seed the fp32 masters from a (restored) param tree. Checkpoint
        load MUST call this before (optionally) overlaying saved state:
        masters are otherwise still the construction-time weights and the
        next step would silently revert the model to initialization.

        ``skip_moments=True`` when load_state_dict will immediately follow
        (it rewrites m/v anyway — avoids a full extra NVMe write)."""
        flat_params = jax.tree.leaves(params)
        flat_gsh = jax.tree.leaves(self.grad_shardings)
        for li, (leaf, per_leaf) in enumerate(zip(flat_params, self._state)):
            shard_view = jax.device_put(leaf, _device_memory(flat_gsh[li]))
            fresh = {_index_key(s.index): s for s in shard_view.addressable_shards}
            for key, ent in per_leaf.items():
                ent[0] = np.array(fresh[key].data, dtype=np.float32)
                if skip_moments:
                    continue
                zeros = np.zeros_like(ent[0])
                if self.swapper is not None:
                    self.swapper.swap_out(self._swap_name(li, key, "m"), zeros)
                    self.swapper.swap_out(self._swap_name(li, key, "v"),
                                          zeros.copy())
                else:
                    ent[1] = np.zeros_like(ent[0])
                    ent[2] = np.zeros_like(ent[0])
        if self.swapper is not None and not skip_moments:
            self.swapper.flush()
        self.adam.set_steps(0)

    def state_dict(self) -> Dict[str, np.ndarray]:
        """Flat host-state dict for this process's shards (reference:
        per-rank zero_pp_rank_N files)."""
        out = {"__step__": np.int64(self.adam.steps)}
        for li, per_leaf in enumerate(self._state):
            for key, ent in per_leaf.items():
                master, m, v = ent[0], ent[1], ent[2]
                if self.swapper is not None:
                    # swap_in only reads — the .swp files stay intact on
                    # disk, so no write-back is needed
                    m = self.swapper.swap_in(self._swap_name(li, key, "m"))
                    v = self.swapper.swap_in(self._swap_name(li, key, "v"))
                base = f"{li}|{key}"
                out[base + "|master"] = master
                out[base + "|m"] = m
                out[base + "|v"] = v
        return out

    def load_state_dict(self, sd: Dict[str, np.ndarray]):
        self.adam.set_steps(int(sd["__step__"]))
        for li, per_leaf in enumerate(self._state):
            for key, ent in per_leaf.items():
                base = f"{li}|{key}"
                ent[0][...] = sd[base + "|master"]
                m, v = np.array(sd[base + "|m"]), np.array(sd[base + "|v"])
                if self.swapper is not None:
                    self.swapper.swap_out(self._swap_name(li, key, "m"), m)
                    self.swapper.swap_out(self._swap_name(li, key, "v"), v)
                else:
                    ent[1][...] = m
                    ent[2][...] = v
        if self.swapper is not None:
            self.swapper.flush()


class StreamedHostAdam:
    """XLA-streamed ZeRO-Offload: fp32 Adam moments live in the
    accelerator host's pinned memory and are streamed leaf-by-leaf
    through HBM inside the jitted train step (h2d -> fused update math
    -> d2h), so device-resident optimizer state is bounded by ONE leaf.

    This is the declarative twin of CPUAdamOffloadOptimizer: the
    reference's cpu_adam + pipelined swapper dataflow
    (stage_1_and_2.py cpu_offload, runtime/swap_tensor/
    pipelined_optimizer_swapper.py), expressed as memory-kind transfers
    that XLA's latency-hiding scheduler overlaps with the neighboring
    leaves' compute. The per-leaf walk is DOUBLE-BUFFERED (leaf N+1's
    moment h2d issued before leaf N's update math — see
    ``utils.streaming.double_buffered``), so the transfer and compute
    chains stay exactly one leaf apart for the scheduler to overlap.
    Unlike the native path, traffic rides the accelerator host's PCIe —
    nothing crosses the client process, so it works at full speed on
    remote/tunneled backends.

    Update math matches ``build_optimizer``'s Adam/AdamW exactly
    (bias-corrected moments; adamw=True -> decoupled weight decay,
    False -> L2 into the gradient), proven by the parity test.
    """

    def __init__(self, opt_params: Dict[str, Any], adamw: bool,
                 param_specs, param_shapes, mesh, zero_stage: int,
                 param_names=None, prefetch: bool = True):
        from jax.sharding import PartitionSpec as P
        from .sharding import make_opt_state_rules

        betas = opt_params.get("betas", (0.9, 0.999))
        self.b1, self.b2 = float(betas[0]), float(betas[1])
        self.eps = float(opt_params.get("eps", 1e-8))
        self.wd = float(opt_params.get("weight_decay", 0.0))
        self.adamw = adamw

        opt_rule = make_opt_state_rules(max(zero_stage, 1), mesh)
        if param_names is not None:
            from ...utils.tree import _is_names
            moment_specs = jax.tree.map(
                lambda n, spec, s: opt_rule(spec, s.shape, n),
                param_names, param_specs, param_shapes,
                is_leaf=_is_names)
        else:
            moment_specs = jax.tree.map(
                lambda spec, s: opt_rule(spec, s.shape),
                param_specs, param_shapes, is_leaf=lambda x: isinstance(x, P))
        self.dev_shardings = jax.tree.map(
            lambda spec: NamedSharding(mesh, spec), moment_specs,
            is_leaf=lambda x: isinstance(x, P))
        self.host_shardings = _with_host_memory_tree(self.dev_shardings)
        # device-kind shardings for the params themselves (the h2d fetch
        # target when offload_param keeps them host-side; the SPMD
        # partitioner requires memory transfers to carry explicit shardings)
        self.param_dev_shardings = jax.tree.map(
            lambda spec: NamedSharding(mesh, spec), param_specs,
            is_leaf=lambda x: isinstance(x, P))
        self._rep = NamedSharding(mesh, jax.sharding.PartitionSpec())
        # double-buffer the per-leaf host->device moment fetches: leaf
        # N+1's h2d is issued before leaf N's update math (the reference's
        # PipelinedOptimizerSwapper read-ahead). Math is IDENTICAL either
        # way — prefetch only reorders trace emission (parity-tested).
        self.prefetch = bool(prefetch)
        # trace-time event log of the most recent apply(): ("fetch", i) /
        # ("compute", i) in emission order — the overlap-ordering probe
        # the double-buffering test asserts on
        self._trace_events = []

    def state_shardings(self):
        return {"mu": self.host_shardings, "nu": self.host_shardings,
                "count": self._rep}

    def init(self, params):
        zeros = lambda: jax.tree.map(
            lambda p: jnp.zeros(p.shape, jnp.float32), params)
        return {"mu": zeros(), "nu": zeros(), "count": jnp.int32(0)}

    def clipped_apply(self, params, grads, state, lr, gnorm, clip):
        """apply() with the engine's global-norm clipping folded in —
        the ONE entry point for both the fused train step and the
        forward/backward/step convention, so clipping semantics cannot
        drift between them. The clip factor is applied per leaf AFTER the
        h2d fetch (host-space grad leaves cannot mix with the device
        scalar); formula matches optax.clip_by_global_norm."""
        factor = None
        if clip and clip > 0:
            factor = jnp.minimum(1.0, clip / jnp.maximum(gnorm, 1e-12))
        return self.apply(params, grads, state, lr, grad_scale=factor)

    def apply(self, params, grads, state, lr, grad_scale=None):
        """Traced: one bias-corrected Adam step, streamed per leaf with
        the NEXT leaf's host moments prefetched while the current leaf
        computes (``utils.streaming.double_buffered``)."""
        count = state["count"] + 1
        c = count.astype(jnp.float32)
        bc1 = 1.0 - self.b1 ** c
        bc2 = 1.0 - self.b2 ** c

        p_flat, treedef = jax.tree.flatten(params)
        leaves = list(zip(p_flat, jax.tree.leaves(grads),
                          jax.tree.leaves(state["mu"]),
                          jax.tree.leaves(state["nu"]),
                          jax.tree.leaves(self.dev_shardings),
                          jax.tree.leaves(self.host_shardings),
                          jax.tree.leaves(self.param_dev_shardings)))
        self._trace_events = events = []

        def fetch(i):
            p, g, mu, nu, dsh, _, psh = leaves[i]
            events.append(("fetch", i))
            # with offload_param, p and g arrive host-space too: fetch for
            # the update math (no-op for device leaves); the train step's
            # out_shardings place new_p back in its home space
            return (jax.device_put(mu, dsh), jax.device_put(nu, dsh),
                    jax.device_put(g, dsh), jax.device_put(p, psh))

        def compute(i, fetched):
            p, *_rest, hsh, _psh = leaves[i]
            mu_d, nu_d, g, p_d = fetched
            events.append(("compute", i))
            g32 = g.astype(jnp.float32)
            if grad_scale is not None:
                g32 = g32 * grad_scale
            p32 = p_d.astype(jnp.float32)
            if not self.adamw and self.wd > 0.0:
                g32 = g32 + self.wd * p32           # classic L2
            mu_n = self.b1 * mu_d + (1.0 - self.b1) * g32
            nu_n = self.b2 * nu_d + (1.0 - self.b2) * jnp.square(g32)
            upd = (mu_n / bc1) / (jnp.sqrt(nu_n / bc2) + self.eps)
            if self.adamw and self.wd > 0.0:
                upd = upd + self.wd * p32           # decoupled decay
            return ((p32 - lr * upd).astype(p.dtype),
                    jax.device_put(mu_n, hsh), jax.device_put(nu_n, hsh))

        new_p, new_mu, new_nu = [], [], []
        if self.prefetch:
            from ...utils.streaming import double_buffered
            stream = double_buffered(range(len(leaves)), fetch)
        else:
            stream = ((i, fetch(i)) for i in range(len(leaves)))
        for i, fetched in stream:
            p_n, mu_n, nu_n = compute(i, fetched)
            new_p.append(p_n)
            new_mu.append(mu_n)
            new_nu.append(nu_n)

        return (jax.tree.unflatten(treedef, new_p),
                {"mu": jax.tree.unflatten(treedef, new_mu),
                 "nu": jax.tree.unflatten(treedef, new_nu),
                 "count": count})


def _with_host_memory_tree(shardings):
    if jax.default_backend() == "cpu":
        return shardings   # CPU device memory IS host RAM

    def to_host(s):
        try:
            return s.with_memory_kind("pinned_host")
        except Exception:
            logger.warning("pinned_host memory kind unsupported; optimizer "
                           "state stays in device memory")
            return s
    return jax.tree.map(to_host, shardings,
                        is_leaf=lambda x: isinstance(x, NamedSharding))


def _index_key(index) -> str:
    return repr(tuple((s.start, s.stop, s.step) for s in index))


def _device_memory(sharding):
    """The same sharding placed in default device memory (grads arrive in
    pinned_host; the rebuilt params go straight to HBM)."""
    try:
        if getattr(sharding, "memory_kind", None) not in (None, "device"):
            return sharding.with_memory_kind("device")
    except Exception:
        pass
    return sharding
