"""ZeRO stages as sharding rules.

The reference implements ZeRO with imperative tensor surgery:
- stage 1: optimizer states partitioned over DP ranks
  (runtime/zero/stage_1_and_2.py:93 DeepSpeedZeroOptimizer, flattened
  per-group buffers + allgather of updated partitions)
- stage 2: + gradients reduce-scattered into the owning partition
  (stage_1_and_2.py:895 average_tensor)
- stage 3: + parameters sharded, all-gathered just-in-time per submodule
  (stage3.py, partition_parameters.py, partitioned_param_coordinator.py)

TPU-native, each stage is a *declarative sharding rule set* over the same
mesh; the XLA SPMD partitioner inserts exactly the collectives the reference
hand-codes (all-gather of params before use, reduce-scatter of grads,
all-gather of updated shards after the step):

- stage 0: params/grads/opt-state replicated over DP; grads psum'd.
- stage 1: opt state (fp32 master + moments) sharded over the DP axes along
  each param's largest free dim. XLA emits reduce-scatter(grads)->update
  shard->all-gather(params), i.e. the stage-1 comm pattern.
- stage 2: + the gradient *accumulation buffer* (held across microbatches
  when gradient_accumulation_steps > 1) is sharded like the opt state, so
  full grads never persist — the reference's ipg-bucket reduce-scatter.
- stage 3: + params themselves sharded over the ``fsdp`` axis ("embed" rule,
  plus largest-dim fallback); per-layer all-gather falls out of the
  scan-over-layers model structure (the coordinator's fetch granularity).
  ``stage3_param_persistence_threshold`` keeps small params replicated
  exactly like the reference (partition_parameters.py ds_persist).

Tensor parallelism (the reference delegates to Megatron's mpu) is the
"model" axis rules below — qkv/mlp/vocab dims sharded, psum at row-parallel
boundaries inserted by XLA.
"""


import numpy as np
import jax
from jax.sharding import PartitionSpec as P, NamedSharding

from ...comm.mesh import DENSE_DP_AXES

# Logical-name -> mesh-axis rule tables. None = replicate that dim.
TP_RULES = {
    "qkv": "model",       # column-parallel attention in/out
    "mlp": "model",       # column-parallel FFN hidden
    "vocab": "model",     # vocab-parallel embedding / lm head
    "heads": "model",
    "experts": "expert",  # stacked-expert dim -> expert-parallel axis
    "embed": None,
    "embed_out": None,
    "pos": None,
    "layers": None,       # scan axis; pipeline may claim it later ("stage")
    "batch": ("data", "fsdp"),
    "seq": "seq",
}

FSDP_AXIS = "fsdp"


def make_param_rules(stage: int, persistence_threshold: int = 0,
                     layers_axis=None):
    """Return fn(names, shape, mesh) -> PartitionSpec for a parameter.

    ``layers_axis``: mesh axis for the "layers" logical dim — None for
    scan-over-layers models, "stage" for pipeline-parallel stacks."""
    table = dict(TP_RULES)
    if layers_axis is not None:
        table["layers"] = layers_axis

    def rules(names, shape, mesh):
        if names is None:
            names = (None,) * len(shape)
        axes = [table.get(n) if n is not None else None for n in names]
        axes = [a if _divisible(shape, i, a, mesh) else None
                for i, a in enumerate(axes)]

        if stage == 3 and int(np.prod(shape)) > persistence_threshold:
            # Gather tables (a "vocab"/"pos" row dim): put fsdp on the ROW
            # dim, stacked onto any TP axis already there. An embed-dim
            # shard of a lookup table forces the SPMD partitioner to move
            # the fsdp axis from the feature dim onto the (data, fsdp)
            # batch tile of the gather output — an involuntary full
            # rematerialization in fwd and bwd. Row-sharding instead folds
            # into the masked-local-gather + psum vocab-parallel pattern.
            placed = False
            for i, n in enumerate(names):
                # dim 0 only: the row dim of a 2-D lookup table. An untied
                # lm_head matmul kernel ("embed", "vocab") is NOT a gather
                # table and keeps the embed-dim rule below.
                if i != 0 or n not in ("vocab", "pos") or len(shape) != 2:
                    continue
                existing = axes[i]
                prior = (tuple(existing) if isinstance(existing, (tuple, list))
                         else (existing,) if existing is not None else ())
                combo = (*prior, FSDP_AXIS)
                if _divisible(shape, i, combo, mesh):
                    axes[i] = combo if len(combo) > 1 else combo[0]
                    placed = True
                    break
            if not placed:
                # Shard over fsdp on the "embed" dim when present, else the
                # largest still-replicated dim (reference: partition along
                # flat numel; here we keep a real dim so XLA stays
                # efficient).
                cand = [i for i, n in enumerate(names)
                        if n == "embed" and axes[i] is None]
                if not cand:
                    cand = sorted((i for i, a in enumerate(axes) if a is None),
                                  key=lambda i: -shape[i])
                for i in cand:
                    if _divisible(shape, i, FSDP_AXIS, mesh):
                        axes[i] = FSDP_AXIS
                        break
        return P(*axes)

    return rules


def make_opt_state_rules(stage: int, mesh):
    """Given a param's spec+shape (+optional logical dim names), return the
    spec for its optimizer-state leaves (fp32 master copy, Adam moments...).

    stage 0: follow the param. stage >= 1: additionally shard over the
    data(+expert) axes on the largest free dim — the ZeRO-1 partition.
    """
    # the FULL dense-DP group (data, expert, fsdp): the batch is sharded
    # over all of it (engine._place_batch uses DENSE_DP_AXES), so the
    # ZeRO-1/2 partition must cover it too — omitting fsdp would leave
    # opt state / grad-accum buffers fsdp-replicated, fsdp-times the
    # promised shard per device
    base_axes = tuple(a for a in DENSE_DP_AXES if mesh.shape.get(a, 1) > 1)

    def rules(param_spec: P, shape, names=None):
        if stage < 1 or not base_axes or not shape:
            return param_spec
        axes = list(param_spec) + [None] * (len(shape) - len(param_spec))
        # Never reuse an axis the param itself is sharded over (e.g. expert
        # params already claim "expert" on their stacked dim — their opt
        # state shards over the remaining DP axes only, mirroring the
        # reference's separate expert DP groups, groups.py:107).
        used = set()
        for a in axes:
            for x in (a if isinstance(a, (tuple, list)) else (a,)):
                if x is not None:
                    used.add(x)
        shard_axes = tuple(a for a in base_axes if a not in used)
        if not shard_axes:
            return P(*axes)
        # Gather tables (a "vocab"/"pos" row dim): stack the ZeRO partition
        # onto the ROW dim, combined with any TP/fsdp axis already there.
        # A feature-dim shard on a table GRAD forces the backward scatter's
        # updates (batch-sharded cotangents) through an involuntary-full-
        # rematerialization reshard; a row shard folds into the masked
        # scatter + reduce the partitioner already emits.
        if names:
            for i, n in enumerate(names):
                # dim 0 of a 2-D table only — see make_param_rules: an
                # untied lm_head kernel ("embed", "vocab") is a matmul
                # weight, not a gather table
                if i != 0 or n not in ("vocab", "pos") or len(shape) != 2:
                    continue
                existing = axes[i]
                prior = (tuple(existing) if isinstance(existing, (tuple, list))
                         else (existing,) if existing is not None else ())
                combo = (*prior, *shard_axes)
                if _divisible(shape, i, combo, mesh):
                    axes[i] = combo if len(combo) > 1 else combo[0]
                    return P(*axes)
        free = sorted((i for i, a in enumerate(axes) if a is None),
                      key=lambda i: -shape[i])
        for i in free:
            if _divisible(shape, i, shard_axes, mesh):
                axes[i] = shard_axes if len(shard_axes) > 1 else shard_axes[0]
                return P(*axes)
        # No free dim divides — stack the ZeRO axes onto an already-
        # sharded dim instead (largest first). E.g. a scan-stacked qkv
        # bias ("layers", "qkv"): the qkv dim carries the TP "model"
        # axis and the layers dim (n_layers, often < dp) can't take the
        # partition, so without stacking the grad/opt leaves would stay
        # replicated over DP — silently losing the stage-2 contract.
        taken = sorted((i for i, a in enumerate(axes) if a is not None),
                       key=lambda i: -shape[i])
        for i in taken:
            existing = axes[i]
            prior = (tuple(existing) if isinstance(existing, (tuple, list))
                     else (existing,))
            combo = (*prior, *shard_axes)
            if _divisible(shape, i, combo, mesh):
                axes[i] = combo
                break
        return P(*axes)

    return rules


def _divisible(shape, dim_idx, axis, mesh) -> bool:
    if axis is None:
        return True
    if isinstance(axis, (tuple, list)):
        size = int(np.prod([mesh.shape.get(a, 1) for a in axis]))
    else:
        size = mesh.shape.get(axis, 1)
    if size == 1:
        return True
    return dim_idx < len(shape) and shape[dim_idx] % size == 0


def extract_logical_names(variables):
    """Pull logical-name tuples off flax Partitioned/LogicallyPartitioned
    leaves; returns (pure_value_tree, names_tree)."""
    from flax.core import meta

    def get_names(leaf):
        if isinstance(leaf, meta.AxisMetadata):
            return tuple(getattr(leaf, "names", ()) or ())
        return None

    names = jax.tree.map(get_names, variables,
                         is_leaf=lambda x: isinstance(x, meta.AxisMetadata))
    values = meta.unbox(variables)
    return values, names


def param_shardings(variables_or_names, shapes, mesh, stage,
                    persistence_threshold: int = 0):
    """names_tree+shapes_tree -> NamedSharding tree for params."""
    rules = make_param_rules(stage, persistence_threshold)
    return jax.tree.map(
        lambda n, s: NamedSharding(mesh, rules(n, getattr(s, "shape", s), mesh)),
        variables_or_names, shapes,
        is_leaf=lambda x: x is None or (isinstance(x, tuple) and all(
            isinstance(e, (str, type(None))) for e in x)))


def spec_tree_for_params(names_tree, shapes_tree, mesh, stage,
                         persistence_threshold: int = 0):
    rules = make_param_rules(stage, persistence_threshold)
    return jax.tree.map(
        lambda n, s: rules(n, s, mesh), names_tree, shapes_tree,
        is_leaf=lambda x: x is None or (isinstance(x, tuple) and all(
            isinstance(e, (str, type(None))) for e in x)))
