"""Curriculum learning scheduler.

Reference: deepspeed/runtime/data_pipeline/curriculum_scheduler.py:8 — steps
a difficulty value (e.g. sequence length) each iteration; the engine injects
it into the model forward. On TPU, dynamic seqlen would trigger
recompilation, so difficulties are bucketed to multiples of
``difficulty_step`` (buckets each compile once, then cache).
"""

import math
from ...utils.logging import logger


class CurriculumScheduler:
    def __init__(self, config):
        self.state = {}
        self.first_step = True
        self.config = config
        sched = dict(config.schedule_config or {})
        self.schedule_type = config.schedule_type
        self.min_difficulty = config.min_difficulty
        self.max_difficulty = config.max_difficulty
        self.current_difficulty = config.min_difficulty
        if self.schedule_type == "fixed_linear":
            self.total_curriculum_step = sched.get("total_curriculum_step", 10000)
            self.difficulty_step = sched.get("difficulty_step", 8)
        elif self.schedule_type == "fixed_root":
            self.total_curriculum_step = sched.get("total_curriculum_step", 10000)
            self.difficulty_step = sched.get("difficulty_step", 8)
            self.root_degree = sched.get("root_degree", 2)
        elif self.schedule_type == "fixed_discrete":
            self.difficulties = sched.get("difficulty", [config.max_difficulty])
            self.max_steps = sched.get("max_step", [0])
        else:
            raise ValueError(f"Unknown curriculum schedule {self.schedule_type}")

    def get_current_difficulty(self):
        return self.current_difficulty

    def set_current_difficulty(self, difficulty):
        self.current_difficulty = difficulty

    def update_difficulty(self, global_steps):
        if self.schedule_type == "fixed_discrete":
            d = self.difficulties[-1]
            for diff, until in zip(self.difficulties, self.max_steps):
                if global_steps <= until:
                    d = diff
                    break
            self.current_difficulty = d
            return d
        if self.schedule_type == "fixed_root":
            frac = min(1.0, global_steps / self.total_curriculum_step)
            frac = frac ** (1.0 / self.root_degree)
        else:  # fixed_linear
            frac = min(1.0, global_steps / self.total_curriculum_step)
        d = self.min_difficulty + frac * (self.max_difficulty - self.min_difficulty)
        # bucket to difficulty_step so XLA shape buckets stay few
        d = int(math.floor(d / self.difficulty_step) * self.difficulty_step)
        self.current_difficulty = max(self.min_difficulty,
                                      min(d, self.max_difficulty))
        return self.current_difficulty
