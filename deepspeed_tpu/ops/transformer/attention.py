"""Attention op with a swappable backend.

The reference fuses attention inside its CUDA transformer kernel
(csrc/transformer/softmax_kernels.cu + strided_batch_gemm, orchestrated by
ds_transformer_cuda.cpp). Here the same surface is one function whose
backend is either

- ``reference``: pure jnp einsum path (runs everywhere; XLA already fuses
  the softmax chain), or
- ``pallas``: the flash-attention Pallas kernel (deepspeed_tpu.ops.pallas)
  when running on TPU with compatible shapes.

Backend selection lives here so models never care.
"""

import functools
from typing import Optional

import jax
import jax.numpy as jnp


def _reference_attention(q, k, v, bias=None, mask=None, *, causal=False,
                         softmax_scale=None, dropout_rate=0.0,
                         dropout_rng=None, deterministic=True,
                         dropout_mask=None):
    """q,k,v: [batch, seq, heads, head_dim] (BSHD, the JAX-native layout).

    ``dropout_mask``: precomputed boolean keep mask [b, h, sq, sk] —
    overrides rng sampling. Sequence-parallel callers pass their local
    slice of a globally-sampled mask (partitionable threefry makes the
    slices bit-identical to the replicated sample)."""
    *_, q_len, _, head_dim = q.shape
    k_len = k.shape[-3]
    scale = softmax_scale if softmax_scale is not None else head_dim ** -0.5

    # [b, h, sq, sk] logits in fp32 for numerical stability (the reference's
    # attn_softmax kernel also upcasts).
    logits = jnp.einsum("...qhd,...khd->...hqk", q, k,
                        preferred_element_type=jnp.float32) * scale

    if bias is not None:
        logits = logits + bias
    if causal:
        causal_mask = jnp.tril(jnp.ones((q_len, k_len), dtype=bool), k_len - q_len)
        logits = jnp.where(causal_mask, logits, jnp.finfo(jnp.float32).min)
    if mask is not None:
        # mask: [batch, 1|heads, 1|sq, sk] boolean, True = attend
        logits = jnp.where(mask, logits, jnp.finfo(jnp.float32).min)

    probs = jax.nn.softmax(logits, axis=-1)
    if dropout_mask is not None:
        probs = jnp.where(dropout_mask, probs / (1.0 - dropout_rate), 0.0)
    elif dropout_rate > 0.0 and not deterministic:
        keep = jax.random.bernoulli(dropout_rng, 1.0 - dropout_rate, probs.shape)
        probs = jnp.where(keep, probs / (1.0 - dropout_rate), 0.0)

    probs = probs.astype(v.dtype)
    return jnp.einsum("...hqk,...khd->...qhd", probs, v)


def attention(q, k, v, bias=None, mask=None, *, causal=False,
              softmax_scale=None, dropout_rate=0.0, dropout_rng=None,
              deterministic=True, backend: Optional[str] = None,
              seq_parallel: Optional[str] = None, ring_block_q: int = 1024):
    """Multi-head attention, BSHD layout.

    backend: None = auto (pallas flash kernel on TPU when eligible,
    reference otherwise) | "reference" | "pallas".
    seq_parallel: None = auto (ulysses when the mesh's ``seq`` axis > 1)
    | "ulysses" | "ring" | "none". Bias, mask and dropout ride along on
    both sequence-parallel paths (ulysses keeps the replicated path's
    exact dropout pattern via partitionable threefry; ring samples per
    k/v block). Only shape constraints fall back.
    """
    sp_mode = _resolve_seq_parallel(seq_parallel, q, bias, mask)
    if sp_mode == "ulysses":
        from ...sequence_parallel import ulysses_attention
        inner = functools.partial(attention, backend=backend,
                                  seq_parallel="none")
        return ulysses_attention(q, k, v, bias=bias, mask=mask,
                                 causal=causal, softmax_scale=softmax_scale,
                                 dropout_rate=dropout_rate,
                                 dropout_rng=dropout_rng,
                                 deterministic=deterministic, attn_fn=inner)
    if sp_mode == "ring":
        from ...sequence_parallel import ring_attention
        return ring_attention(q, k, v, bias=bias, mask=mask, causal=causal,
                              softmax_scale=softmax_scale,
                              dropout_rate=dropout_rate,
                              dropout_rng=dropout_rng,
                              deterministic=deterministic,
                              block_q=ring_block_q)

    if backend is None:
        backend = _auto_backend(q, bias, mask, dropout_rate, deterministic)
    elif backend == "pallas" and (
            bias is not None or mask is not None
            or (dropout_rate > 0.0 and not deterministic)):
        # the flash kernel takes no bias/mask/dropout operands — honor the
        # semantics over the explicit backend request (e.g. alibi or
        # KV-cache masks with attn_backend="pallas").
        _warn_pallas_fallback()
        backend = "reference"
    if backend == "pallas":
        from ..pallas import flash_attention
        return flash_attention(q, k, v, causal=causal, softmax_scale=softmax_scale)
    return _reference_attention(q, k, v, bias=bias, mask=mask, causal=causal,
                                softmax_scale=softmax_scale,
                                dropout_rate=dropout_rate,
                                dropout_rng=dropout_rng,
                                deterministic=deterministic)


def _resolve_seq_parallel(seq_parallel, q, bias, mask):
    """Pick the sequence-parallel mode; "none" when inapplicable.
    Dropout never disqualifies (both SP paths sample it locally)."""
    if seq_parallel == "none":
        return "none"
    from ...comm.mesh import get_global_mesh, _GLOBAL_MESH
    if seq_parallel is None and _GLOBAL_MESH is None:
        return "none"  # auto never forces a mesh into existence
    sp = get_global_mesh().shape.get("seq", 1)
    if sp == 1:
        if seq_parallel in ("ulysses", "ring"):
            _warn_sp_no_axis()  # explicit request, but no seq axis to use
        return "none"
    # bias/mask/dropout ride along (sharded operands / partitionable
    # threefry); only SHAPES disqualify: decode-time q (seq=1 chunks,
    # XLA all-gathers the seq shards transparently) and operands whose
    # broadcast dims the region specs can't express (b/h/sq must be 1 or
    # full-size, the forms every model in models/ produces).
    def _op_ok(t):
        return t is None or (
            t.ndim == 4
            and all(t.shape[i] in (1, full)
                    for i, full in ((0, q.shape[0]), (1, q.shape[2]),
                                    (2, q.shape[1])))
            and t.shape[3] == q.shape[1])
    eligible = (q.ndim == 4 and q.shape[1] % sp == 0
                and _op_ok(bias) and _op_ok(mask))
    if not eligible:
        if seq_parallel is not None:
            _warn_sp_fallback()
        return "none"
    if seq_parallel is None:
        # auto mode must degrade, never raise: ulysses additionally needs
        # heads/tp divisible by sp — fall back to ring (no head constraint)
        tp = get_global_mesh().shape.get("model", 1)
        if (q.shape[2] // max(tp, 1)) % sp != 0:
            return "ring"
        return "ulysses"
    return seq_parallel


@functools.lru_cache(None)
def _warn_sp_no_axis():
    import warnings
    warnings.warn("seq_parallel requested but the active mesh has no 'seq' "
                  "axis (size 1) — running fully replicated. Build the mesh "
                  "with MeshSpec(seq=N) to enable it.")


@functools.lru_cache(None)
def _warn_sp_fallback():
    import warnings
    warnings.warn("sequence-parallel attention requested but the q/bias/"
                  "mask shapes (decode-time seq=1 chunks, non-broadcast "
                  "operand dims) require the replicated path; falling back")


@functools.lru_cache(None)
def _warn_pallas_fallback():
    import warnings
    warnings.warn("attn_backend='pallas' requested but bias/mask/dropout "
                  "operands require the reference path; falling back")


def _on_tpu():
    from ..pallas._common import on_tpu
    return on_tpu()


@functools.lru_cache(None)
def _pallas_available():
    try:
        from ..pallas import flash_attention  # noqa: F401
        return True
    except Exception:
        return False


def _auto_backend(q, bias, mask, dropout_rate, deterministic):
    head_dim = q.shape[-1]
    seq = q.shape[-3]
    eligible = (_on_tpu() and _pallas_available() and bias is None
                and mask is None and (dropout_rate == 0.0 or deterministic)
                and head_dim in (64, 128, 256) and seq % 128 == 0)
    return "pallas" if eligible else "reference"
