"""Attention op with a swappable backend.

The reference fuses attention inside its CUDA transformer kernel
(csrc/transformer/softmax_kernels.cu + strided_batch_gemm, orchestrated by
ds_transformer_cuda.cpp). Here the same surface is one function whose
backend is either

- ``reference``: pure jnp einsum path (runs everywhere; XLA already fuses
  the softmax chain), or
- ``pallas``: the flash-attention Pallas kernel (deepspeed_tpu.ops.pallas)
  when running on TPU with compatible shapes.

Backend selection lives here so models never care.
"""

import functools
from typing import Optional

import jax
import jax.numpy as jnp


def _reference_attention(q, k, v, bias=None, mask=None, *, causal=False,
                         softmax_scale=None, dropout_rate=0.0,
                         dropout_rng=None, deterministic=True,
                         dropout_mask=None, dropout_offsets=None):
    """q,k,v: [batch, seq, heads, head_dim] (BSHD, the JAX-native layout).

    Dropout samples the SAME counter-based keep mask as the Pallas flash
    kernel (``ops.pallas.flash_attention.attention_dropout_keep``): bits
    are a pure function of (rng, batch, head, row, col), so dense and
    flash backends — and replicated vs sequence-parallel layouts — are
    bit-identical given the same rng. ``dropout_offsets``
    (total_heads, head_offset, batch_offset) lets a shard_map-local
    caller reproduce the global sample. ``dropout_mask`` (a precomputed
    boolean keep mask) overrides sampling."""
    *_, q_len, _, head_dim = q.shape
    k_len = k.shape[-3]
    scale = softmax_scale if softmax_scale is not None else head_dim ** -0.5

    # [b, h, sq, sk] logits in fp32 for numerical stability (the reference's
    # attn_softmax kernel also upcasts).
    logits = jnp.einsum("...qhd,...khd->...hqk", q, k,
                        preferred_element_type=jnp.float32) * scale

    if bias is not None:
        logits = logits + bias
    if causal:
        causal_mask = jnp.tril(jnp.ones((q_len, k_len), dtype=bool), k_len - q_len)
        logits = jnp.where(causal_mask, logits, jnp.finfo(jnp.float32).min)
    if mask is not None:
        # mask: [batch, 1|heads, 1|sq, sk] boolean, True = attend
        logits = jnp.where(mask, logits, jnp.finfo(jnp.float32).min)

    probs = jax.nn.softmax(logits, axis=-1)
    if dropout_mask is not None:
        probs = jnp.where(dropout_mask, probs / (1.0 - dropout_rate), 0.0)
    elif dropout_rate > 0.0 and not deterministic:
        from ..pallas.flash_attention import attention_dropout_keep
        th, ho, bo = dropout_offsets or (probs.shape[1], 0, 0)
        keep = attention_dropout_keep(dropout_rng, dropout_rate, probs.shape,
                                      total_heads=th, head_offset=ho,
                                      batch_offset=bo)
        probs = jnp.where(keep, probs / (1.0 - dropout_rate), 0.0)

    probs = probs.astype(v.dtype)
    return jnp.einsum("...hqk,...khd->...qhd", probs, v)


def attention(q, k, v, bias=None, mask=None, *, causal=False,
              softmax_scale=None, dropout_rate=0.0, dropout_rng=None,
              deterministic=True, backend: Optional[str] = None,
              seq_parallel: Optional[str] = None, ring_block_q: int = 1024,
              dropout_offsets=None):
    """Multi-head attention, BSHD layout.

    backend: None = auto (pallas flash kernel on TPU when eligible,
    reference otherwise) | "reference" | "pallas". Bias, mask and dropout
    are FUSED into the flash kernel (mask folds into one additive bias
    operand; dropout samples a counter-based keep mask in-kernel) — only
    operand shapes the kernel's block specs can't express fall back.
    seq_parallel: None = auto (ulysses when the mesh's ``seq`` axis > 1)
    | "ulysses" | "ring" | "none". Bias, mask and dropout ride along on
    both sequence-parallel paths (ulysses reproduces the replicated
    path's exact dropout bits via the position-keyed hash + head/batch
    offsets; ring samples per k/v block). Only shape constraints fall
    back.
    dropout_offsets: (total_heads, head_offset, batch_offset) — set by
    shard_map-local callers (Ulysses) so local tiles sample the global
    keep mask; leave None under plain jit/pjit (global view).
    """
    sp_mode = _resolve_seq_parallel(seq_parallel, q, bias, mask)
    if sp_mode == "ulysses":
        from ...sequence_parallel import ulysses_attention
        inner = functools.partial(attention, backend=backend,
                                  seq_parallel="none")
        return ulysses_attention(q, k, v, bias=bias, mask=mask,
                                 causal=causal, softmax_scale=softmax_scale,
                                 dropout_rate=dropout_rate,
                                 dropout_rng=dropout_rng,
                                 deterministic=deterministic, attn_fn=inner)
    if sp_mode == "ring":
        from ...sequence_parallel import ring_attention
        return ring_attention(q, k, v, bias=bias, mask=mask, causal=causal,
                              softmax_scale=softmax_scale,
                              dropout_rate=dropout_rate,
                              dropout_rng=dropout_rng,
                              deterministic=deterministic,
                              block_q=ring_block_q)

    drop_on = dropout_rate > 0.0 and not deterministic
    if backend is None:
        backend = _auto_backend(q, k, bias, mask, drop_on, dropout_rng)
    elif backend == "pallas" and not _pallas_operands_ok(
            q, k, bias, mask, drop_on, dropout_rng):
        # operand shapes the kernel's block specs can't express — honor
        # the semantics over the explicit backend request
        _warn_pallas_fallback()
        backend = "reference"
    if backend == "pallas":
        from ..pallas import flash_attention
        return flash_attention(
            q, k, v, bias=_combined_bias(bias, mask), causal=causal,
            softmax_scale=softmax_scale,
            dropout_rate=dropout_rate if drop_on else 0.0,
            dropout_rng=dropout_rng if drop_on else None,
            dropout_offsets=dropout_offsets,
            # a mask-only combined bias is statically non-trainable: let
            # eager grads skip the dense dBias recompute
            bias_grad=bias is not None)
    return _reference_attention(q, k, v, bias=bias, mask=mask, causal=causal,
                                softmax_scale=softmax_scale,
                                dropout_rate=dropout_rate,
                                dropout_rng=dropout_rng,
                                deterministic=deterministic,
                                dropout_offsets=dropout_offsets)


def _combined_bias(bias, mask):
    """Fold a boolean keep mask into the additive bias operand the flash
    kernel takes (0 where attending, NEG_INF where masked — the encoding
    the kernels' fully-masked-row thresholds depend on)."""
    if mask is None:
        return bias
    from ..pallas._common import NEG_INF
    mb = jnp.where(mask, 0.0, NEG_INF).astype(jnp.float32)
    return mb if bias is None else bias + mb


def _resolve_seq_parallel(seq_parallel, q, bias, mask):
    """Pick the sequence-parallel mode; "none" when inapplicable.
    Dropout never disqualifies (both SP paths sample it locally)."""
    if seq_parallel == "none":
        return "none"
    from ...comm.mesh import get_global_mesh, _GLOBAL_MESH
    if seq_parallel is None and _GLOBAL_MESH is None:
        return "none"  # auto never forces a mesh into existence
    sp = get_global_mesh().shape.get("seq", 1)
    if sp == 1:
        if seq_parallel in ("ulysses", "ring"):
            _warn_sp_no_axis()  # explicit request, but no seq axis to use
        return "none"
    # bias/mask/dropout ride along (sharded operands / the position-keyed
    # keep hash); only SHAPES disqualify: decode-time q (seq=1 chunks,
    # XLA all-gathers the seq shards transparently) and operands whose
    # broadcast dims the region specs can't express (b/h/sq must be 1 or
    # full-size, the forms every model in models/ produces).
    def _op_ok(t):
        return t is None or (
            t.ndim == 4
            and all(t.shape[i] in (1, full)
                    for i, full in ((0, q.shape[0]), (1, q.shape[2]),
                                    (2, q.shape[1])))
            and t.shape[3] == q.shape[1])
    eligible = (q.ndim == 4 and q.shape[1] % sp == 0
                and _op_ok(bias) and _op_ok(mask))
    if not eligible:
        if seq_parallel is not None:
            _warn_sp_fallback()
        return "none"
    if seq_parallel is None:
        # auto mode must degrade, never raise: ulysses additionally needs
        # heads/tp divisible by sp — fall back to ring (no head constraint)
        tp = get_global_mesh().shape.get("model", 1)
        if (q.shape[2] // max(tp, 1)) % sp != 0:
            return "ring"
        return "ulysses"
    return seq_parallel


@functools.lru_cache(None)
def _warn_sp_no_axis():
    import warnings
    warnings.warn("seq_parallel requested but the active mesh has no 'seq' "
                  "axis (size 1) — running fully replicated. Build the mesh "
                  "with MeshSpec(seq=N) to enable it.")


@functools.lru_cache(None)
def _warn_sp_fallback():
    import warnings
    warnings.warn("sequence-parallel attention requested but the q/bias/"
                  "mask shapes (decode-time seq=1 chunks, non-broadcast "
                  "operand dims) require the replicated path; falling back")


@functools.lru_cache(None)
def _warn_pallas_fallback():
    import warnings
    warnings.warn("attn_backend='pallas' requested but the bias/mask "
                  "operand shapes (or dropout without an rng) require the "
                  "reference path; falling back")


def _on_tpu():
    from ..pallas._common import on_tpu
    return on_tpu()


@functools.lru_cache(None)
def _pallas_available():
    try:
        from ..pallas import flash_attention  # noqa: F401
        return True
    except Exception:
        return False


def _pallas_operands_ok(q, k, bias, mask, drop_on, dropout_rng):
    """Shapes the flash kernel's block specs can express: 4-D operands
    with b/h/sq each full-size or broadcast (1) and sk full; dropout
    needs an rng to seed the in-kernel hash."""
    if drop_on and dropout_rng is None:
        return False
    b, sq, h, _ = q.shape
    sk = k.shape[1]

    def ok(t):
        return t is None or (
            t.ndim == 4
            and t.shape[0] in (1, b) and t.shape[1] in (1, h)
            and t.shape[2] in (1, sq) and t.shape[3] == sk)

    return ok(bias) and ok(mask)


def _auto_backend(q, k, bias, mask, drop_on, dropout_rng):
    head_dim = q.shape[-1]
    seq = q.shape[-3]
    eligible = (_on_tpu() and _pallas_available()
                and head_dim in (64, 128, 256) and seq % 128 == 0
                and _pallas_operands_ok(q, k, bias, mask, drop_on,
                                        dropout_rng))
    return "pallas" if eligible else "reference"
