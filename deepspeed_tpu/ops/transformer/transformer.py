"""User-facing fused transformer layer, the drop-in analog of the
reference's CUDA training kernel surface.

Reference: ``DeepSpeedTransformerConfig`` / ``DeepSpeedTransformerLayer``
(deepspeed/ops/transformer/transformer.py:36,459), whose forward/backward is
the hand-fused BERT layer in csrc/transformer/ds_transformer_cuda.cpp:1034
(QKV gemm -> attention softmax+dropout -> projection -> residual+LN ->
GELU FFN -> residual+LN, pre- or post-LN).

TPU design: one flax module holding the reference's *flat parameter
surface* (attn_qkvw/attn_qkvb/attn_ow/attn_ob/attn_nw/attn_nb/inter_w/
inter_b/output_w/output_b/norm_w/norm_b, torch [out, in] weight layout so
reference checkpoints port 1:1) executed as jnp matmuls + the shared
attention op (flash-attention Pallas kernel when eligible). XLA fuses the
bias/GELU/dropout/residual epilogues into the matmuls — the fusions the
reference wrote by hand in gelu_kernels.cu / dropout_kernels.cu /
normalize_kernels.cu.

Memory knobs map to rematerialization instead of kernel variants:
- ``gelu_checkpoint``     -> recompute the FFN in backward
  (reference: drops the intermediate GELU activation buffer)
- ``attn_dropout_checkpoint`` -> recompute attention in backward
  (reference: drops the attention-dropout buffer; the flash kernel never
  materializes [b,h,s,s] probs in the first place)
- ``normalize_invertible``    -> recompute both LN sub-blocks in backward
  (reference: recovers LN inputs from outputs)
- ``stochastic_mode``         -> accepted, no-op: its CUDA meaning
  (non-deterministic fast reductions) has no TPU analog; XLA reductions
  are deterministic at equal speed.
"""

import math
from dataclasses import dataclass, field
from typing import Any, Optional

import jax
import jax.numpy as jnp
import flax.linen as nn

from .attention import attention


@dataclass
class TransformerConfig:
    """Base config (reference: TransformerConfig, transformer.py:17)."""
    batch_size: int = -1
    hidden_size: int = -1
    intermediate_size: int = -1
    heads: int = -1
    attn_dropout_ratio: float = -1.0
    hidden_dropout_ratio: float = -1.0
    num_hidden_layers: int = -1
    initializer_range: float = -1.0
    layer_id: int = field(default=-1, init=False)


@dataclass
class DeepSpeedTransformerConfig(TransformerConfig):
    """Reference: DeepSpeedTransformerConfig (transformer.py:37) — same
    knob names; TPU interpretations documented in the module docstring.

    ``fp16=True`` selects bfloat16 compute (the TPU-native half format;
    fp16 has no hardware advantage on the MXU). ``batch_size`` and
    ``local_rank``/``seed`` are accepted for signature parity: shapes are
    taken from the inputs at trace time and RNG comes from flax rngs.
    """
    layer_norm_eps: float = 1e-12
    local_rank: int = -1
    seed: int = -1
    fp16: bool = False
    pre_layer_norm: bool = True
    normalize_invertible: bool = False
    gelu_checkpoint: bool = False
    adjust_init_range: bool = True
    attn_dropout_checkpoint: bool = False
    stochastic_mode: bool = False
    return_tuple: bool = False
    training: bool = True

    def __post_init__(self):
        if self.intermediate_size <= 0 < self.hidden_size:
            self.intermediate_size = 4 * self.hidden_size
        if self.attn_dropout_ratio < 0:
            self.attn_dropout_ratio = 0.0
        if self.hidden_dropout_ratio < 0:
            self.hidden_dropout_ratio = 0.0

    @classmethod
    def from_dict(cls, json_object):
        cfg = cls()
        for key, value in json_object.items():
            if hasattr(cfg, key):
                setattr(cfg, key, value)
        cfg.__post_init__()
        return cfg

    @classmethod
    def from_json_file(cls, json_file):
        import json
        with open(json_file, "r", encoding="utf-8") as f:
            return cls.from_dict(json.loads(f.read()))

    @property
    def dtype(self):
        return jnp.bfloat16 if self.fp16 else jnp.float32


def _normal(std):
    return nn.initializers.normal(stddev=std)


class DeepSpeedTransformerLayer(nn.Module):
    """Drop-in fused transformer layer (reference:
    DeepSpeedTransformerLayer, transformer.py:459).

    Parameters carry the reference's exact names and torch ``[out, in]``
    weight layout, so state dicts exported from the reference layer load
    directly (transpose-free). Forward signature mirrors the reference:
    ``layer(hidden_states, attention_mask)`` with an additive or boolean
    [batch, 1, 1, seq] (or [batch, seq]) mask.

    Unlike the CUDA layer there is no per-layer global registry or
    max-batch preallocation: jit re-specializes on shapes, and layer_id
    bookkeeping is unnecessary (kept as a config field for parity).
    """
    config: DeepSpeedTransformerConfig

    @nn.compact
    def __call__(self, hidden_states, attention_mask=None, *,
                 deterministic: Optional[bool] = None, grads=None):
        cfg = self.config
        if deterministic is None:
            deterministic = not cfg.training
        H, I = cfg.hidden_size, cfg.intermediate_size
        n_layers = max(cfg.num_hidden_layers, 1)
        std = cfg.initializer_range if cfg.initializer_range > 0 else 0.02
        out_std = std / math.sqrt(2.0 * n_layers) if cfg.adjust_init_range else std

        # --- the reference's flat parameter surface, torch [out, in] layout
        p = self.param
        attn_qkvw = p("attn_qkvw", _normal(std), (3 * H, H), jnp.float32)
        attn_qkvb = p("attn_qkvb", nn.initializers.zeros, (3 * H,), jnp.float32)
        attn_ow = p("attn_ow", _normal(out_std), (H, H), jnp.float32)
        attn_ob = p("attn_ob", nn.initializers.zeros, (H,), jnp.float32)
        attn_nw = p("attn_nw", nn.initializers.ones, (H,), jnp.float32)
        attn_nb = p("attn_nb", nn.initializers.zeros, (H,), jnp.float32)
        inter_w = p("inter_w", _normal(std), (I, H), jnp.float32)
        inter_b = p("inter_b", nn.initializers.zeros, (I,), jnp.float32)
        output_w = p("output_w", _normal(out_std), (H, I), jnp.float32)
        output_b = p("output_b", nn.initializers.zeros, (H,), jnp.float32)
        norm_w = p("norm_w", nn.initializers.ones, (H,), jnp.float32)
        norm_b = p("norm_b", nn.initializers.zeros, (H,), jnp.float32)

        dtype = cfg.dtype
        x = hidden_states.astype(dtype)
        mask = _canonical_mask(attention_mask)

        rngs = {}
        needs_rng = (not deterministic and
                     (cfg.attn_dropout_ratio > 0 or cfg.hidden_dropout_ratio > 0))
        if needs_rng:
            rngs["attn"], rngs["hidden1"], rngs["hidden2"] = \
                jax.random.split(self.make_rng("dropout"), 3)

        def ln(y, scale, bias):
            y32 = y.astype(jnp.float32)
            mean = jnp.mean(y32, axis=-1, keepdims=True)
            var = jnp.mean(jnp.square(y32 - mean), axis=-1, keepdims=True)
            out = (y32 - mean) * jax.lax.rsqrt(var + cfg.layer_norm_eps)
            return (out * scale + bias).astype(dtype)

        if cfg.normalize_invertible:
            # recompute LN (and everything downstream of it inside each
            # sub-block) in backward instead of saving LN inputs
            ln = jax.checkpoint(ln, static_argnums=())

        def attn_block(y):
            qkv = y @ attn_qkvw.astype(dtype).T + attn_qkvb.astype(dtype)
            b, s, _ = qkv.shape
            head_dim = H // cfg.heads
            q, k, v = jnp.split(qkv, 3, axis=-1)
            q = q.reshape(b, s, cfg.heads, head_dim)
            k = k.reshape(b, s, cfg.heads, head_dim)
            v = v.reshape(b, s, cfg.heads, head_dim)
            ctx = attention(
                q, k, v, mask=mask, causal=False,
                dropout_rate=cfg.attn_dropout_ratio,
                dropout_rng=rngs.get("attn"),
                deterministic=deterministic, seq_parallel="none")
            ctx = ctx.reshape(b, s, H)
            out = ctx @ attn_ow.astype(dtype).T + attn_ob.astype(dtype)
            return _dropout(out, cfg.hidden_dropout_ratio, rngs.get("hidden1"),
                            deterministic)

        def ffn_block(y):
            h = y @ inter_w.astype(dtype).T + inter_b.astype(dtype)
            h = jax.nn.gelu(h, approximate=False)
            h = h @ output_w.astype(dtype).T + output_b.astype(dtype)
            return _dropout(h, cfg.hidden_dropout_ratio, rngs.get("hidden2"),
                            deterministic)

        if cfg.attn_dropout_checkpoint:
            attn_block = jax.checkpoint(attn_block)
        if cfg.gelu_checkpoint:
            ffn_block = jax.checkpoint(ffn_block)

        if cfg.pre_layer_norm:
            x = x + attn_block(ln(x, attn_nw, attn_nb))
            x = x + ffn_block(ln(x, norm_w, norm_b))
        else:
            x = ln(x + attn_block(x), attn_nw, attn_nb)
            x = ln(x + ffn_block(x), norm_w, norm_b)

        return (x,) if cfg.return_tuple else x


def _canonical_mask(attention_mask):
    """Accept [b, s] multiplicative masks (1=keep, 0=drop), [b, 1, 1, s]
    boolean, or HF-style additive float masks (0 keep / large-negative
    drop); emit the boolean layout the attention op expects, or None."""
    if attention_mask is None:
        return None
    m = attention_mask
    if m.ndim == 2:
        # 2-D masks are multiplicative by convention regardless of dtype
        return (m > 0.5 if jnp.issubdtype(m.dtype, jnp.floating)
                else m.astype(bool))[:, None, None, :]
    if jnp.issubdtype(m.dtype, jnp.floating):
        return m > -1.0   # additive masks use ~-1e4/-inf for "drop"
    return m.astype(bool)


def _dropout(x, rate, rng, deterministic):
    if rate <= 0.0 or deterministic or rng is None:
        return x
    keep = jax.random.bernoulli(rng, 1.0 - rate, x.shape)
    return jnp.where(keep, x / (1.0 - rate), 0.0)
