from .attention import attention
from .transformer import (DeepSpeedTransformerConfig,
                          DeepSpeedTransformerLayer, TransformerConfig)

__all__ = ["attention", "DeepSpeedTransformerConfig",
           "DeepSpeedTransformerLayer", "TransformerConfig"]
