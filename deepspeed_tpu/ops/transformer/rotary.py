"""Rotary position embedding.

Reference: csrc/transformer/inference/csrc/apply_rotary_pos_emb.cu — a CUDA
kernel rotating q/k pairs. On TPU this is pure VPU elementwise work that XLA
fuses into the surrounding matmuls; no Pallas needed.
"""

import jax.numpy as jnp


def rotary_embedding(positions, dim, base=10000.0, dtype=jnp.float32):
    """[seq] (or [batch, seq]) positions -> (sin, cos) [..., dim/2]."""
    inv_freq = 1.0 / (base ** (jnp.arange(0, dim, 2, dtype=jnp.float32) / dim))
    freqs = positions.astype(jnp.float32)[..., None] * inv_freq
    return jnp.sin(freqs).astype(dtype), jnp.cos(freqs).astype(dtype)


def _rotate_half(x):
    x1, x2 = jnp.split(x, 2, axis=-1)
    return jnp.concatenate([-x2, x1], axis=-1)


def apply_rotary_pos_emb(q, k, rotary_dim=None, positions=None, base=10000.0):
    """q,k: [batch, seq, heads, head_dim]; rotates the first rotary_dim dims.

    GPT-NeoX style (half-split rotation), matching the reference kernel's
    neox path (apply_rotary_pos_emb.cu rotate_half). ``positions`` may be
    [seq] (shared across the batch) or [batch, seq] (per-row — the ragged
    decode path, where every slot sits at its own sequence position).
    """
    head_dim = q.shape[-1]
    rotary_dim = rotary_dim or head_dim
    seq = q.shape[1]
    if positions is None:
        positions = jnp.arange(seq)
    sin, cos = rotary_embedding(positions, rotary_dim, base=base, dtype=q.dtype)
    sin = jnp.concatenate([sin, sin], axis=-1)
    cos = jnp.concatenate([cos, cos], axis=-1)
    if positions.ndim == 1:
        sin, cos = sin[None], cos[None]              # [1, s, dim]
    sin = sin[:, :, None, :]                         # [b?, s, 1, dim]
    cos = cos[:, :, None, :]

    def rot(x):
        x_rot, x_pass = x[..., :rotary_dim], x[..., rotary_dim:]
        x_rot = x_rot * cos + _rotate_half(x_rot) * sin
        return jnp.concatenate([x_rot, x_pass], axis=-1) if rotary_dim < head_dim else x_rot

    return rot(q), rot(k)
