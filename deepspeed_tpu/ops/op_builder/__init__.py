"""JIT builder/loader for the native host-side ops.

TPU-native analog of the reference's op_builder/ (builder.py:1 OpBuilder,
JIT-load via torch cpp_extension): here the native ops are host C++ only
(device math is Pallas), compiled on first use with g++ into a per-user
cache dir and loaded via ctypes — pybind11/torch are deliberately not in
the loop. Each builder reports ``is_compatible()`` so ds_report-style
tooling can print the op support matrix.
"""

import ctypes
import hashlib
import os
import shutil
import subprocess
import tempfile
from typing import List, Optional

from ...utils.logging import logger

_CSRC = os.path.normpath(os.path.join(os.path.dirname(__file__),
                                      "..", "..", "csrc"))


def _cache_dir() -> str:
    root = os.environ.get("DS_TPU_BUILD_DIR") or os.path.join(
        os.path.expanduser("~"), ".cache", "deepspeed_tpu", "ops")
    os.makedirs(root, exist_ok=True)
    return root


class OpBuilder:
    """Compile sources from csrc/ into a shared lib, load with ctypes."""

    NAME: str = ""
    SOURCES: List[str] = []
    EXTRA_FLAGS: List[str] = []

    _loaded: Optional[ctypes.CDLL] = None

    @classmethod
    def absolute_sources(cls):
        return [os.path.join(_CSRC, s) for s in cls.SOURCES]

    @classmethod
    def is_compatible(cls) -> bool:
        if shutil.which("g++") is None:
            return False
        return all(os.path.exists(s) for s in cls.absolute_sources())

    @classmethod
    def compat_reason(cls) -> str:
        if shutil.which("g++") is None:
            return "g++ not found"
        missing = [s for s in cls.absolute_sources() if not os.path.exists(s)]
        if missing:
            return f"missing sources {missing}"
        return "ok"

    @classmethod
    def _signature(cls) -> str:
        h = hashlib.sha256()
        for src in cls.absolute_sources():
            with open(src, "rb") as f:
                h.update(f.read())
        h.update(" ".join(cls.EXTRA_FLAGS).encode())
        return h.hexdigest()[:16]

    @classmethod
    def load(cls) -> ctypes.CDLL:
        if cls._loaded is not None:
            return cls._loaded
        if not cls.is_compatible():
            raise RuntimeError(
                f"op '{cls.NAME}' is not buildable here: {cls.compat_reason()}")
        lib_path = os.path.join(_cache_dir(),
                                f"{cls.NAME}_{cls._signature()}.so")
        if not os.path.exists(lib_path):
            cls._build(lib_path)
        cls._loaded = ctypes.CDLL(lib_path)
        return cls._loaded

    @classmethod
    def _build(cls, lib_path: str):
        # pid-unique temp then atomic rename: concurrent processes (multi-
        # host launch, pytest-xdist) may race to build the same op
        tmp = f"{lib_path}.{os.getpid()}.tmp"
        cmd = (["g++", "-O3", "-fPIC", "-shared", "-std=c++17"]
               + cls.EXTRA_FLAGS + cls.absolute_sources() + ["-o", tmp])
        logger.info(f"building native op {cls.NAME}: {' '.join(cmd)}")
        try:
            subprocess.run(cmd, check=True, capture_output=True, text=True)
        except subprocess.CalledProcessError as e:
            raise RuntimeError(
                f"native build of '{cls.NAME}' failed:\n{e.stderr}") from e
        os.replace(tmp, lib_path)


class CPUAdamBuilder(OpBuilder):
    """Reference: op_builder/cpu_adam.py + csrc/adam/cpu_adam.cpp."""
    NAME = "cpu_adam"
    SOURCES = ["cpu_adam.cpp"]
    EXTRA_FLAGS = ["-march=native", "-fopenmp"]

    @classmethod
    def load(cls):
        lib = super().load()
        lib.ds_adam_create.argtypes = [
            ctypes.c_int, ctypes.c_float, ctypes.c_float, ctypes.c_float,
            ctypes.c_float, ctypes.c_float, ctypes.c_int]
        lib.ds_adam_update.argtypes = [
            ctypes.c_int, ctypes.c_int64, ctypes.c_float,
            ctypes.POINTER(ctypes.c_float), ctypes.POINTER(ctypes.c_float),
            ctypes.POINTER(ctypes.c_float), ctypes.POINTER(ctypes.c_float),
            ctypes.c_int64, ctypes.c_void_p]
        lib.ds_adam_destroy.argtypes = [ctypes.c_int]
        return lib


class CPUAdagradBuilder(OpBuilder):
    """Reference: op_builder/cpu_adagrad.py + csrc/adagrad/cpu_adagrad.cpp."""
    NAME = "cpu_adagrad"
    SOURCES = ["cpu_adagrad.cpp"]
    EXTRA_FLAGS = ["-march=native", "-fopenmp"]

    @classmethod
    def load(cls):
        lib = super().load()
        lib.ds_adagrad_create.argtypes = [
            ctypes.c_int, ctypes.c_float, ctypes.c_float, ctypes.c_float]
        lib.ds_adagrad_update.argtypes = [
            ctypes.c_int, ctypes.c_float,
            ctypes.POINTER(ctypes.c_float), ctypes.POINTER(ctypes.c_float),
            ctypes.POINTER(ctypes.c_float), ctypes.c_int64, ctypes.c_void_p]
        lib.ds_adagrad_destroy.argtypes = [ctypes.c_int]
        return lib


class AsyncIOBuilder(OpBuilder):
    """Reference: op_builder/async_io.py + csrc/aio/."""
    NAME = "async_io"
    SOURCES = ["aio.cpp"]
    EXTRA_FLAGS = ["-pthread"]

    @classmethod
    def load(cls):
        lib = super().load()
        lib.ds_aio_new.restype = ctypes.c_void_p
        lib.ds_aio_new.argtypes = [ctypes.c_int]
        lib.ds_aio_free.argtypes = [ctypes.c_void_p]
        lib.ds_aio_pread.restype = ctypes.c_int64
        lib.ds_aio_pread.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                     ctypes.c_void_p, ctypes.c_int64,
                                     ctypes.c_int64]
        lib.ds_aio_pwrite.restype = ctypes.c_int64
        lib.ds_aio_pwrite.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                      ctypes.c_void_p, ctypes.c_int64,
                                      ctypes.c_int64]
        lib.ds_aio_wait.restype = ctypes.c_int
        lib.ds_aio_wait.argtypes = [ctypes.c_void_p, ctypes.c_int64]
        lib.ds_aio_wait_all.restype = ctypes.c_int
        lib.ds_aio_wait_all.argtypes = [ctypes.c_void_p]
        lib.ds_aio_backend.restype = ctypes.c_int
        lib.ds_aio_backend.argtypes = [ctypes.c_void_p]
        return lib


ALL_OPS = {b.NAME: b for b in (CPUAdamBuilder, CPUAdagradBuilder, AsyncIOBuilder)}


def op_report():
    """ds_report-style (reference deepspeed/env_report.py:23) build matrix."""
    rows = []
    for name, b in ALL_OPS.items():
        rows.append((name, b.is_compatible(), b.compat_reason()))
    return rows
