from .cpu_adam import DeepSpeedCPUAdam

__all__ = ["DeepSpeedCPUAdam"]
