"""Host-side fused Adam/AdamW over numpy shards (ZeRO-Offload inner
optimizer).

Reference: DeepSpeedCPUAdam (deepspeed/ops/adam/cpu_adam.py:12) backed by
csrc/adam/cpu_adam.cpp. Here the native kernel is csrc/cpu_adam.cpp
(OpenMP + auto-vectorized), loaded via ctypes; state tensors are numpy
fp32 arrays living in host RAM, stepped on the gradient shard the device
reduce-scattered. ``step`` optionally emits a bf16 weight copy in the
same call (the reference's adam_update_copy fused variant).
"""

import itertools
import ctypes
from typing import Optional

import numpy as np

from ...analysis import lint_ok
from ..op_builder import CPUAdamBuilder

_ids = itertools.count()


def _f32ptr(a: np.ndarray):
    return a.ctypes.data_as(ctypes.POINTER(ctypes.c_float))


class DeepSpeedCPUAdam:
    def __init__(self, lr=1e-3, betas=(0.9, 0.999), eps=1e-8,
                 weight_decay=0.0, adamw_mode=True):
        self.lib = CPUAdamBuilder.load()
        self.opt_id = next(_ids)
        self._step = 0
        self.defaults = dict(lr=lr, betas=betas, eps=eps,
                             weight_decay=weight_decay, adamw_mode=adamw_mode)
        rc = self.lib.ds_adam_create(self.opt_id, lr, betas[0], betas[1],
                                     eps, weight_decay, int(adamw_mode))
        if rc != 0:
            raise RuntimeError("ds_adam_create failed")

    @lint_ok("TS002")  # operands are host numpy by contract (ZeRO-Offload)
    def step(self, params: np.ndarray, grads: np.ndarray,
             exp_avg: np.ndarray, exp_avg_sq: np.ndarray,
             lr: Optional[float] = None,
             out_bf16: Optional[np.ndarray] = None,
             global_step: Optional[int] = None):
        """One fused step over a flat fp32 shard, in place.

        ``global_step``: 1-based optimizer step for bias correction. When a
        model's leaves/shards are stepped by separate calls, the caller MUST
        pass the shared step (one counter per optimizer step, not per call);
        None auto-increments the internal counter (single-tensor use)."""
        for name, a in (("params", params), ("grads", grads),
                        ("exp_avg", exp_avg), ("exp_avg_sq", exp_avg_sq)):
            if a.dtype != np.float32 or not a.flags.c_contiguous:
                raise ValueError(f"{name} must be contiguous float32")
        n = params.size
        if not (grads.size == exp_avg.size == exp_avg_sq.size == n):
            raise ValueError("size mismatch")
        out_ptr = None
        if out_bf16 is not None:
            if out_bf16.dtype != np.uint16 or out_bf16.size != n:
                raise ValueError("out_bf16 must be uint16 (bf16 bits) of same size")
            out_ptr = out_bf16.ctypes.data_as(ctypes.c_void_p)
        if global_step is None:
            self._step += 1
            global_step = self._step
        else:
            self._step = int(global_step)
        rc = self.lib.ds_adam_update(
            self.opt_id, int(global_step),
            -1.0 if lr is None else float(lr), _f32ptr(grads),
            _f32ptr(params), _f32ptr(exp_avg), _f32ptr(exp_avg_sq), n, out_ptr)
        if rc != 0:
            raise RuntimeError("ds_adam_update failed")

    @property
    def steps(self) -> int:
        return self._step

    def set_steps(self, step: int):
        self._step = int(step)  # ds-tpu: lint-ok[TS002] — host int, checkpoint restore

    def __del__(self):
        try:
            self.lib.ds_adam_destroy(self.opt_id)
        except Exception:
            pass
