"""Async NVMe I/O handle (ZeRO-Infinity swap backend).

Reference: AsyncIOBuilder().load() aio handle over csrc/aio/
(deepspeed_py_aio_handle.h, libaio io_submit). Here csrc/aio.cpp via
ctypes: an io_uring engine (raw syscalls — kernel-async submission, no
userspace I/O threads) with a worker-thread pread/pwrite pool as the
fallback where io_uring_setup is filtered. ``backend`` reports which
engine the kernel gave us. Buffers are numpy arrays; submissions return
tickets, ``wait``/``wait_all`` join them.
"""

import ctypes
import os

import numpy as np

from ..op_builder import AsyncIOBuilder


class AsyncIOHandle:
    def __init__(self, n_threads: int = 4):
        self.lib = AsyncIOBuilder.load()
        self._h = self.lib.ds_aio_new(n_threads)
        self._pinned = {}  # ticket -> buffer keep-alive

    @property
    def backend(self) -> str:
        """"io_uring" or "threads" (the engine ds_aio_new picked)."""
        return "io_uring" if self.lib.ds_aio_backend(self._h) else "threads"

    def pread(self, path: str, buf: np.ndarray, offset: int = 0) -> int:
        t = self.lib.ds_aio_pread(self._h, os.fsencode(path),
                                  buf.ctypes.data_as(ctypes.c_void_p),
                                  buf.nbytes, offset)
        if t < 0:
            raise RuntimeError("aio pread submit failed")
        self._pinned[t] = buf
        return t

    def pwrite(self, path: str, buf: np.ndarray, offset: int = 0) -> int:
        t = self.lib.ds_aio_pwrite(self._h, os.fsencode(path),
                                   buf.ctypes.data_as(ctypes.c_void_p),
                                   buf.nbytes, offset)
        if t < 0:
            raise RuntimeError("aio pwrite submit failed")
        self._pinned[t] = buf
        return t

    def wait(self, ticket: int):
        err = self.lib.ds_aio_wait(self._h, ticket)
        self._pinned.pop(ticket, None)
        if err != 0:
            raise OSError(err, os.strerror(err))

    def wait_all(self):
        """Barrier + consume every ticket THIS handle tracks. The C++
        barrier leaves completion records intact, so tickets a caller is
        still holding (e.g. a swapper prefetch) remain individually
        waitable."""
        err = self.lib.ds_aio_wait_all(self._h)
        for t in list(self._pinned):
            e = self.lib.ds_aio_wait(self._h, t)  # immediate: all complete
            err = err or e
            self._pinned.pop(t, None)
        if err != 0:
            raise OSError(err, os.strerror(err))

    def close(self):
        if self._h is not None:
            self.lib.ds_aio_free(self._h)
            self._h = None

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass
