"""Pallas TPU kernels — the replacement for the reference's csrc/ CUDA tree.

| reference (csrc/)                       | here                     |
|-----------------------------------------|--------------------------|
| transformer attention + softmax kernels | flash_attention          |
| inference softmax_context (KV cache)    | decode_attention         |
| (no reference analog: paged serving)    | paged_attention          |
| adam/multi_tensor_adam.cu               | fused_adam.fused_adamw   |
| lamb/fused_lamb_cuda.cpp (trust ratios) | fused_lamb.fused_lamb    |
| transformer/normalize_kernels.cu        | layernorm.fused_layer_norm |
| quantization/quantizer.cu               | quantizer.quantize/dequantize |

Kernels run in interpreter mode automatically off-TPU so the whole suite
tests on the CPU mesh.
"""

from .decode_attention import decode_attention
from .flash_attention import flash_attention
from .paged_attention import paged_attention
from .fused_adam import fused_adamw, FusedAdamState
from .fused_lamb import fused_lamb, FusedLambState
from .layernorm import fused_layer_norm
from .quantizer import quantize, dequantize
