"""Fused AdamW Pallas kernel.

TPU-native replacement for the reference's multi-tensor fused Adam
(csrc/adam/multi_tensor_adam.cu + fused_adam_frontend.cpp exposing
``multi_tensor_adam``). One elementwise kernel updates param, m and v in a
single pass over HBM (4 reads + 3 writes per element instead of the
read/write traffic of an unfused update chain); exposed as an optax
GradientTransformation so the engine can slot it in wherever optax.adamw
fits.
"""

import functools
from typing import NamedTuple, Union, Callable

import jax
import jax.numpy as jnp
import optax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

BLOCK = 1024 * 128  # elements per grid step (flat layout)
LANE = 128


from ._common import interpret_mode as _interpret


def _adam_kernel(p_ref, g_ref, m_ref, v_ref, sc_ref,
                 new_p_ref, new_m_ref, new_v_ref, *, b1, b2, eps, wd):
    # sc_ref (SMEM): [lr, step_size_corr1, corr2_inv_sqrt... ] precomputed
    lr = sc_ref[0]
    c1 = sc_ref[1]   # 1/(1-b1^t)
    c2 = sc_ref[2]   # 1/(1-b2^t)
    g = g_ref[:].astype(jnp.float32)
    p = p_ref[:].astype(jnp.float32)
    m = b1 * m_ref[:] + (1.0 - b1) * g
    v = b2 * v_ref[:] + (1.0 - b2) * g * g
    m_hat = m * c1
    v_hat = v * c2
    update = m_hat / (jnp.sqrt(v_hat) + eps) + wd * p
    new_p_ref[:] = (p - lr * update).astype(new_p_ref.dtype)
    new_m_ref[:] = m
    new_v_ref[:] = v


def _fused_update_flat(p, g, m, v, scalars, *, b1, b2, eps, wd):
    """p/g/m/v: [n, LANE] flat-padded arrays."""
    n = p.shape[0]
    rows = BLOCK // LANE
    block_rows = min(rows, n)
    grid = (pl.cdiv(n, block_rows),)
    spec = pl.BlockSpec((block_rows, LANE), lambda i: (i, 0))
    out_shape = (jax.ShapeDtypeStruct(p.shape, p.dtype),
                 jax.ShapeDtypeStruct(m.shape, jnp.float32),
                 jax.ShapeDtypeStruct(v.shape, jnp.float32))
    return pl.pallas_call(
        functools.partial(_adam_kernel, b1=b1, b2=b2, eps=eps, wd=wd),
        grid=grid,
        in_specs=[spec, spec, spec, spec,
                  pl.BlockSpec(memory_space=pltpu.SMEM)],
        out_specs=(spec, spec, spec),
        out_shape=out_shape,
        input_output_aliases={0: 0, 2: 1, 3: 2},
        interpret=_interpret(),
    )(p, g, m, v, scalars)


class FusedAdamState(NamedTuple):
    count: jnp.ndarray
    mu: optax.Updates
    nu: optax.Updates


def fused_adamw(learning_rate: Union[float, Callable] = 1e-3,
                b1: float = 0.9, b2: float = 0.999, eps: float = 1e-8,
                weight_decay: float = 0.0) -> optax.GradientTransformation:
    """Drop-in for optax.adamw backed by the fused Pallas kernel.

    Returns *updates* = new_params - params so it composes with
    optax.apply_updates like any other transform.
    """

    def init(params):
        return FusedAdamState(
            count=jnp.zeros((), jnp.int32),
            mu=jax.tree.map(lambda p: jnp.zeros(p.shape, jnp.float32), params),
            nu=jax.tree.map(lambda p: jnp.zeros(p.shape, jnp.float32), params))

    def update(grads, state, params=None):
        assert params is not None, "fused_adamw requires params"
        count = state.count + 1
        lr = learning_rate(count) if callable(learning_rate) else learning_rate
        c1 = 1.0 / (1.0 - b1 ** count.astype(jnp.float32))
        c2 = 1.0 / (1.0 - b2 ** count.astype(jnp.float32))
        scalars = jnp.stack([jnp.asarray(lr, jnp.float32), c1, c2])

        def one(p, g, m, v):
            shape = p.shape
            n = max(1, int(jnp.size(p)))
            pad = (-n) % LANE
            def flat(x, dt):
                f = x.reshape(-1).astype(dt)
                if pad:
                    f = jnp.pad(f, (0, pad))
                return f.reshape(-1, LANE)
            fp, fg = flat(p, p.dtype), flat(g, jnp.float32)
            fm, fv = flat(m, jnp.float32), flat(v, jnp.float32)
            np_, nm, nv = _fused_update_flat(fp, fg, fm, fv, scalars,
                                             b1=b1, b2=b2, eps=eps,
                                             wd=weight_decay)
            unflat = lambda x, dt: x.reshape(-1)[:n].reshape(shape).astype(dt)
            return (unflat(np_, p.dtype) - p, unflat(nm, jnp.float32),
                    unflat(nv, jnp.float32))

        # flatten-zip-unflatten: robust to tuple-containing param pytrees
        # (is_leaf=isinstance(tuple) would fire on structural tuples)
        p_leaves, treedef = jax.tree.flatten(params)
        g_leaves = treedef.flatten_up_to(grads)
        m_leaves = treedef.flatten_up_to(state.mu)
        v_leaves = treedef.flatten_up_to(state.nu)
        outs = [one(p, g, m, v) for p, g, m, v in
                zip(p_leaves, g_leaves, m_leaves, v_leaves)]
        updates = jax.tree.unflatten(treedef, [o[0] for o in outs])
        mu = jax.tree.unflatten(treedef, [o[1] for o in outs])
        nu = jax.tree.unflatten(treedef, [o[2] for o in outs])
        return updates, FusedAdamState(count=count, mu=mu, nu=nu)

    return optax.GradientTransformation(init, update)
