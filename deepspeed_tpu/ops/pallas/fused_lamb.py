"""Fused LAMB Pallas kernel.

TPU-native replacement for the reference's fused LAMB
(csrc/lamb/fused_lamb_cuda.cpp:108 + fused_lamb_cuda_kernel.cu): LAMB is
Adam plus a per-layer trust ratio ||p|| / ||update||, which the CUDA
kernel computes with in-kernel block reductions. Here phase 1 is one
fused pass that updates the moments, forms the Adam-style update AND
accumulates the squared-norm partials per grid block (the in-kernel
reduction); phase 2 — scaling by lr * trust_ratio — is a trivially fused
elementwise op left to XLA.

Math matches optax.lamb exactly (scale_by_adam -> add_decayed_weights ->
scale_by_trust_ratio -> scale(-lr)), proven by the parity test.
"""

import functools
from typing import NamedTuple, Union, Callable

import jax
import jax.numpy as jnp
import optax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from ._common import interpret_mode as _interpret

BLOCK = 1024 * 128
LANE = 128


def _lamb_phase1_kernel(p_ref, g_ref, m_ref, v_ref, sc_ref,
                        u_ref, new_m_ref, new_v_ref, pn_ref, un_ref,
                        *, b1, b2, eps, wd):
    c1 = sc_ref[0]   # 1/(1-b1^t)
    c2 = sc_ref[1]   # 1/(1-b2^t)
    g = g_ref[:].astype(jnp.float32)
    p = p_ref[:].astype(jnp.float32)
    m = b1 * m_ref[:] + (1.0 - b1) * g
    v = b2 * v_ref[:] + (1.0 - b2) * g * g
    u = (m * c1) / (jnp.sqrt(v * c2) + eps) + wd * p
    u_ref[:] = u
    new_m_ref[:] = m
    new_v_ref[:] = v
    # in-kernel norm reduction partials (one scalar per grid block)
    pn_ref[0, 0] = jnp.sum(p * p)
    un_ref[0, 0] = jnp.sum(u * u)


def _lamb_phase1_flat(p, g, m, v, scalars, *, b1, b2, eps, wd):
    n = p.shape[0]
    rows = BLOCK // LANE
    block_rows = min(rows, n)
    # pad the ragged last block with explicit zeros: the in-kernel norm
    # reductions would otherwise fold Pallas's UNSPECIFIED out-of-bounds
    # padding into p_norm/u_norm (zeros are exact — they add nothing)
    pad_rows = (-n) % block_rows
    if pad_rows:
        p, g, m, v = (jnp.pad(x, ((0, pad_rows), (0, 0)))
                      for x in (p, g, m, v))
        n = n + pad_rows
    grid = (pl.cdiv(n, block_rows),)
    spec = pl.BlockSpec((block_rows, LANE), lambda i: (i, 0))
    part = pl.BlockSpec((1, 1), lambda i: (i, 0))
    nblocks = grid[0]
    out_shape = (jax.ShapeDtypeStruct(p.shape, jnp.float32),     # u
                 jax.ShapeDtypeStruct(m.shape, jnp.float32),
                 jax.ShapeDtypeStruct(v.shape, jnp.float32),
                 jax.ShapeDtypeStruct((nblocks, 1), jnp.float32),
                 jax.ShapeDtypeStruct((nblocks, 1), jnp.float32))
    return pl.pallas_call(
        functools.partial(_lamb_phase1_kernel, b1=b1, b2=b2, eps=eps, wd=wd),
        grid=grid,
        in_specs=[spec, spec, spec, spec,
                  pl.BlockSpec(memory_space=pltpu.SMEM)],
        out_specs=(spec, spec, spec, part, part),
        out_shape=out_shape,
        input_output_aliases={2: 1, 3: 2},
        interpret=_interpret(),
    )(p, g, m, v, scalars)


class FusedLambState(NamedTuple):
    count: jnp.ndarray
    mu: optax.Updates
    nu: optax.Updates


def fused_lamb(learning_rate: Union[float, Callable] = 1e-3,
               b1: float = 0.9, b2: float = 0.999, eps: float = 1e-6,
               weight_decay: float = 0.0) -> optax.GradientTransformation:
    """Drop-in for optax.lamb backed by the fused Pallas phase-1 kernel."""

    def init(params):
        return FusedLambState(
            count=jnp.zeros((), jnp.int32),
            mu=jax.tree.map(lambda p: jnp.zeros(p.shape, jnp.float32), params),
            nu=jax.tree.map(lambda p: jnp.zeros(p.shape, jnp.float32), params))

    def update(grads, state, params=None):
        assert params is not None, "fused_lamb requires params"
        count = state.count + 1
        lr = learning_rate(count) if callable(learning_rate) else learning_rate
        c1 = 1.0 / (1.0 - b1 ** count.astype(jnp.float32))
        c2 = 1.0 / (1.0 - b2 ** count.astype(jnp.float32))
        scalars = jnp.stack([c1, c2])

        def one(p, g, m, v):
            shape, dt = p.shape, p.dtype
            n = max(1, int(jnp.size(p)))
            pad = (-n) % LANE

            def flat(x, xdt):
                f = x.reshape(-1).astype(xdt)
                if pad:
                    f = jnp.pad(f, (0, pad))
                return f.reshape(-1, LANE)

            fu, nm, nv, pn, un = _lamb_phase1_flat(
                flat(p, jnp.float32), flat(g, jnp.float32),
                flat(m, jnp.float32), flat(v, jnp.float32), scalars,
                b1=b1, b2=b2, eps=eps, wd=weight_decay)
            p_norm = jnp.sqrt(jnp.sum(pn))
            u_norm = jnp.sqrt(jnp.sum(un))
            # optax scale_by_trust_ratio: zero norms -> ratio 1
            trust = jnp.where((p_norm > 0.0) & (u_norm > 0.0),
                              p_norm / jnp.maximum(u_norm, 1e-30), 1.0)
            unflat = lambda x: x.reshape(-1)[:n].reshape(shape)
            upd = (-lr * trust * unflat(fu)).astype(dt)
            return upd, unflat(nm), unflat(nv)

        p_leaves, treedef = jax.tree.flatten(params)
        g_leaves = treedef.flatten_up_to(grads)
        m_leaves = treedef.flatten_up_to(state.mu)
        v_leaves = treedef.flatten_up_to(state.nu)
        outs = [one(p, g, m, v) for p, g, m, v in
                zip(p_leaves, g_leaves, m_leaves, v_leaves)]
        updates = jax.tree.unflatten(treedef, [o[0] for o in outs])
        mu = jax.tree.unflatten(treedef, [o[1] for o in outs])
        nu = jax.tree.unflatten(treedef, [o[2] for o in outs])
        return updates, FusedLambState(count=count, mu=mu, nu=nu)

    return optax.GradientTransformation(init, update)
