"""Flash attention Pallas kernel (fwd + bwd) with fused bias/mask/dropout.

TPU-native replacement for the reference's fused CUDA attention
(csrc/transformer/softmax_kernels.cu + strided batched gemms orchestrated in
ds_transformer_cuda.cpp, attention dropout in
csrc/transformer/dropout_kernels.cu; inference variant softmax_context in
csrc/transformer/inference/). Design:

- layout: kernels run in BHSD ([batch, heads, seq, head_dim]) so block
  shapes keep the (sublane, lane)-aligned last two dims the Mosaic lowering
  requires; the public API takes BSHD and transposes at dispatch.
- TWO kernel structures, selected by whether K/V (lane-padded to 128) fit
  VMEM comfortably (~12MB → seq <= ~8k at head_dim 64):
  * resident: grid (b, h, q_blocks) with K/V whole in VMEM and a
    dynamic-trip fori_loop over [Bq, Bk] score tiles — fastest at
    training lengths (measured 82 TFLOPS fwd+bwd @ s1024 on v5e vs 62
    for the streamed form);
  * streamed: grid (b, h, q_blocks, k_blocks) with K/V blocks flowing
    through the grid and the online-softmax state in VMEM scratch —
    compiles and runs at any length (16k/32k+).
- causal mode never computes blocks above the diagonal (dynamic trip
  counts in resident form, compute-predication in streamed form).
- ``bias``: ONE additive [b|1, h|1, sq|1, sk] operand covering both the
  reference kernel's attn-mask input and alibi/relative biases (boolean
  masks are folded to 0/-1e30 by the dispatch layer, the same encoding
  the causal path uses). Broadcast (size-1) dims stay size-1 all the way
  into the kernel tile — a [b,1,1,sk] padding mask costs O(b*sk) HBM,
  never O(s^2).
- ``dropout``: attention-probability dropout fused into every structure
  via a COUNTER-BASED keep mask: murmur-style avalanche hashing of
  (seed, global batch*head, absolute row, absolute col). Stateless
  per-element sampling means the fwd kernel and all three backward
  tilings regenerate bit-identical masks with zero operand traffic, and
  the same pure-jnp helper (attention_dropout_keep) runs OUTSIDE Pallas
  for the dense path and sequence-parallel layouts — replicated, Ulysses
  (via head/batch offsets) and dense-reference runs all sample the same
  bits, which is what makes cross-backend parity exactly testable. The
  keep mask drops softmax PROBS (post-normalization, scaled 1/(1-rate)),
  matching the reference's dropout placement; the softmax denominator
  accumulates UN-dropped probabilities.
- forward emits the log-sum-exp rows; backward is two passes sharing that
  LSE (no softmax recompute pass): q-major for dQ, k-major for dK/dV.
  dBias is computed in the custom_vjp bwd rule as a dense recompute that
  XLA dead-code-eliminates whenever the bias is not being differentiated
  (the common case: masks and alibi).
- all matmuls run in the operand dtype (bf16 hot path) with fp32
  accumulation via preferred_element_type — the same bf16-in/fp32-acc
  contract as the XLA einsum path.
- autodiff via jax.custom_vjp (the reference wires fwd/bwd kernels through
  torch.autograd.Function the same way).
"""

import functools
import math

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

# Hand-picked FALLBACK tilings (swept once on v5e at s1024: the resident
# fori prefers block_k 512, the streamed grid 1024). The dispatch consults
# the shape-keyed tuning cache (tuning.py — runtime table, then the
# $DS_TPU_KERNEL_TUNING_CACHE artifact, then the committed default table)
# FIRST; these constants only apply on a full cache miss.
DEFAULT_BLOCK_Q = 512
RESIDENT_BLOCK_K = 512
STREAMED_BLOCK_K = 1024

from . import tuning as _tuning
from ._common import NEG_INF
from ._common import interpret_mode as _interpret


# ---------------------------------------------------------------------------
# counter-based attention dropout
# ---------------------------------------------------------------------------

def _mix32(x):
    """murmur3 finalizer: full avalanche on a uint32 lane."""
    x = x ^ (x >> 16)
    x = x * jnp.uint32(0x85EBCA6B)
    x = x ^ (x >> 13)
    x = x * jnp.uint32(0xC2B2AE35)
    return x ^ (x >> 16)


def _keep_from_coords(s0, s1, bh, i, j, rate):
    """Bernoulli(1-rate) keep decision per (seed, flat batch*head, row,
    col) coordinate. Inputs are broadcastable uint32 arrays/scalars; two
    avalanche rounds decorrelate the structured (i, j) lattice. Pure jnp,
    so the SAME code runs inside Pallas kernels (2-D tiles) and outside
    (4-D full shapes)."""
    x = ((i * jnp.uint32(0x27D4EB2F)) ^ (j * jnp.uint32(0x165667B1))
         ^ (bh * jnp.uint32(0x9E3779B1)) ^ s0)
    x = _mix32(x ^ s1)
    x = _mix32(x + jnp.uint32(0x9E3779B9))
    return x >= jnp.uint32(min(int(rate * 2 ** 32), 2 ** 32 - 1))


def _seed_words(key):
    """Two uint32 words from a JAX PRNG key (typed or raw)."""
    if jnp.issubdtype(key.dtype, jax.dtypes.prng_key):
        data = jax.random.key_data(key)
    else:
        data = jnp.asarray(key)
    data = data.astype(jnp.uint32).reshape(-1)
    w1 = data[-1] if data.size > 1 else jnp.uint32(0x6A09E667)
    return data[0], w1


def pack_dropout_seeds(dropout_rng, head_offset=0, batch_offset=0):
    """int32[4] SMEM operand for the in-kernel keep hash:
    [seed0, seed1, head_offset, batch_offset]. Shared by the flash and
    block-sparse kernels."""
    s0, s1 = _seed_words(dropout_rng)
    return jnp.stack([s0, s1, jnp.uint32(head_offset),
                      jnp.uint32(batch_offset)]).astype(jnp.int32)


def resolve_dropout(dropout_rate, dropout_rng, dropout_offsets,
                    default_heads):
    """(rate, seeds, total_heads) for a kernel dispatch — the ONE place
    the offsets contract is interpreted, shared by the flash and
    block-sparse dispatchers so they can never sample different bits.
    rate 0 / missing rng disables (seeds None)."""
    if dropout_rate <= 0.0 or dropout_rng is None:
        return 0.0, None, int(default_heads)
    th, ho, bo = dropout_offsets or (default_heads, 0, 0)
    return float(dropout_rate), pack_dropout_seeds(dropout_rng, ho, bo), \
        int(th)


def attention_dropout_keep(dropout_rng, rate, shape, total_heads=None,
                           head_offset=0, batch_offset=0,
                           q_offset=0, k_offset=0):
    """Full-shape [b, h, sq, sk] keep mask — bit-identical to what the
    flash kernels sample per tile. ``total_heads``/offsets let a
    shard_map region (Ulysses: local heads/batch) reproduce the global
    replicated sample; the defaults are correct for unsharded or
    GSPMD-sharded (global-view) callers."""
    u = functools.partial(jax.lax.broadcasted_iota, jnp.uint32, shape)
    s0, s1 = _seed_words(dropout_rng)
    bi = u(0) + jnp.uint32(batch_offset)
    hi = u(1) + jnp.uint32(head_offset)
    i = u(2) + jnp.uint32(q_offset)
    j = u(3) + jnp.uint32(k_offset)
    bh = bi * jnp.uint32(total_heads if total_heads else shape[1]) + hi
    return _keep_from_coords(s0, s1, bh, i, j, rate)


def _tile_keep(sm_ref, bi, hi, q_start, k_start, shape, rate, total_heads):
    """In-kernel [Bq, Bk] keep tile at absolute coordinates. sm_ref (SMEM,
    int32[4]): [seed0, seed1, head_offset, batch_offset]."""
    s0 = sm_ref[0].astype(jnp.uint32)
    s1 = sm_ref[1].astype(jnp.uint32)
    gh = jnp.uint32(hi) + sm_ref[2].astype(jnp.uint32)
    gb = jnp.uint32(bi) + sm_ref[3].astype(jnp.uint32)
    bh = gb * jnp.uint32(total_heads) + gh
    i = jax.lax.broadcasted_iota(jnp.uint32, shape, 0) + jnp.uint32(q_start)
    j = jax.lax.broadcasted_iota(jnp.uint32, shape, 1) + jnp.uint32(k_start)
    return _keep_from_coords(s0, s1, bh, i, j, rate)


# ---------------------------------------------------------------------------
# shared tile math
# ---------------------------------------------------------------------------

def _causal_mask(s, q_off, k_off):
    row = jax.lax.broadcasted_iota(jnp.int32, s.shape, 0) + q_off
    col = jax.lax.broadcasted_iota(jnp.int32, s.shape, 1) + k_off
    return jnp.where(col <= row, s, NEG_INF)


from ._common import pick_block as _block

# training-length gate for the single-pass resident backward (its [Bq, S]
# fp32 tiles + fp32 dK/dV accumulators outgrow VMEM beyond this); module
# constant so tests can lower it to exercise the long-seq structures
MONOLITHIC_BWD_MAX_SEQ = 4096

# a full-extent [.., Bq, sk] bias tile shares VMEM with K/V in the
# resident structures; cap its footprint
_BIAS_TILE_BUDGET = 4 * 2 ** 20


def _kv_fits_vmem(s, d, itemsize=2):
    """Lane-padded, double-buffered K+V bytes within a ~12MB budget."""
    return s * max(d, 128) * itemsize * 2 * 2 <= 12 * 2 ** 20


def _probs(q, k, lse, scale, causal, q_off, k_off, bias=None):
    """Probability tile from the saved LSE (one matmul, no running
    softmax): p = exp(s - lse); causal-masked, bias-masked (-1e30) and
    fully-masked (lse = -inf) entries come out exactly 0."""
    s = jnp.dot(q, k.T, preferred_element_type=jnp.float32) * scale
    if bias is not None:
        s = s + bias.astype(jnp.float32)
    if causal:
        s = _causal_mask(s, q_off, k_off)
    return jnp.where(lse > NEG_INF / 2, jnp.exp(s - lse), 0.0)


def _online_step(q, k, v, scale, causal, q_off, k_off, acc, m_acc, l_acc,
                 bias=None, keep=None, inv_keep=1.0):
    """One [Bq, Bk] online-softmax update (shared by both structures).
    ``keep`` drops post-softmax probabilities: the denominator l
    accumulates the UN-dropped sum (true softmax normalizer), the PV
    numerator the dropped/rescaled one."""
    s = jnp.dot(q, k.T, preferred_element_type=jnp.float32) * scale
    if bias is not None:
        s = s + bias.astype(jnp.float32)
    if causal:
        s = _causal_mask(s, q_off, k_off)
    m_new = jnp.maximum(m_acc, jnp.max(s, axis=-1, keepdims=True))
    # rows with no visible key yet (m still -inf, e.g. shifted-causal top
    # rows or fully bias-masked rows) must contribute p=0, not
    # exp(-inf - -inf) = 1
    p = jnp.where(m_new > NEG_INF / 2, jnp.exp(s - m_new), 0.0)
    alpha = jnp.exp(m_acc - m_new)
    l_new = l_acc * alpha + jnp.sum(p, axis=-1, keepdims=True)
    if keep is not None:
        p = jnp.where(keep, p * inv_keep, 0.0)
    # PV matmul in the value dtype (bf16 MXU rate); probs are in [0,1] so
    # the downcast loses at most 2^-9 relative — inside bf16 output noise
    acc = acc * alpha + jnp.dot(p.astype(v.dtype), v,
                                preferred_element_type=jnp.float32)
    return acc, m_new, l_new


def _bwd_tile(p, do, v, delta, scale, keep, inv_keep, q_dtype):
    """Shared backward tile math. With dropout D = keep/(1-rate):
    o = (P∘D)v / l  =>  dV = (P∘D)ᵀ do,  dS = P∘(D∘(do Vᵀ) - delta)·scale
    where delta = rowsum(do∘o) — the same delta as the no-dropout case
    (the dropped terms cancel: delta_i = do_i·o_i either way)."""
    dp = jnp.dot(do, v.T, preferred_element_type=jnp.float32)
    if keep is not None:
        dfac = jnp.where(keep, inv_keep, 0.0)
        ds = (p * (dfac * dp - delta) * scale).astype(q_dtype)
        pv = (p * dfac).astype(do.dtype)
    else:
        ds = (p * (dp - delta) * scale).astype(q_dtype)
        pv = p.astype(do.dtype)
    return ds, pv


def _emit_o_lse(acc, m, l, o_ref, lse_ref):
    safe_l = jnp.where(l > 0.0, l, 1.0)   # fully-masked rows -> zeros
    o_ref[0, 0] = (acc / safe_l).astype(o_ref.dtype)
    # LSE residual for backward; -inf rows stay -inf so bwd re-zeroes them
    lse_ref[0, 0] = jnp.where(l > 0.0, m + jnp.log(safe_l), NEG_INF)


def _unpack_refs(refs, has_bias, has_drop):
    """Kernel ref unpacking: [bias_ref?] [sm_ref?] then outputs/scratch."""
    i = 0
    bias_ref = refs[i] if has_bias else None
    i += 1 if has_bias else 0
    sm_ref = refs[i] if has_drop else None
    i += 1 if has_drop else 0
    return (bias_ref, sm_ref) + tuple(refs[i:])


def _bias_rows(bias_ref, bias_q_full, row_ds):
    """Bias tile rows for q rows ``row_ds`` (pl.ds) — all rows when the
    bias q dim is broadcast (size 1)."""
    if bias_q_full:
        return bias_ref[0, 0, row_ds, :]
    return bias_ref[0, 0, :, :]


# ---------------------------------------------------------------------------
# resident structure: K/V whole in VMEM, fori over k tiles
# ---------------------------------------------------------------------------

def _fwd_kernel_resident(q_ref, k_ref, v_ref, *refs, scale, causal, block_q,
                         block_k, causal_shift, has_bias, dropout_rate,
                         total_heads):
    has_drop = dropout_rate > 0.0
    bias_ref, sm_ref, o_ref, lse_ref = _unpack_refs(refs, has_bias, has_drop)
    bi, hi, qi = pl.program_id(0), pl.program_id(1), pl.program_id(2)
    q = q_ref[0, 0]                                    # [Bq, d] native dtype
    d = q.shape[-1]
    nkb = k_ref.shape[2] // block_k
    q_off = qi * block_q + causal_shift
    q_abs = qi * block_q                               # dropout coordinates
    inv_keep = 1.0 / (1.0 - dropout_rate) if has_drop else 1.0

    def body(j, carry):
        ks = pl.ds(j * block_k, block_k)
        bias = bias_ref[0, 0, :, ks] if has_bias else None
        keep = (_tile_keep(sm_ref, bi, hi, q_abs, j * block_k,
                           (block_q, block_k), dropout_rate, total_heads)
                if has_drop else None)
        return _online_step(q, k_ref[0, 0, ks, :], v_ref[0, 0, ks, :],
                            scale, causal, q_off, j * block_k, *carry,
                            bias=bias, keep=keep, inv_keep=inv_keep)

    trips = (jnp.clip((q_off + block_q - 1) // block_k + 1, 1, nkb)
             if causal else nkb)
    acc, m, l = jax.lax.fori_loop(
        0, trips, body,
        (jnp.zeros((block_q, d), jnp.float32),
         jnp.full((block_q, 1), NEG_INF, jnp.float32),
         jnp.zeros((block_q, 1), jnp.float32)))
    _emit_o_lse(acc, m, l, o_ref, lse_ref)


def _dq_kernel_resident(q_ref, k_ref, v_ref, do_ref, delta_ref, lse_ref,
                        *refs, scale, causal, block_q, block_k,
                        causal_shift, has_bias, dropout_rate, total_heads):
    has_drop = dropout_rate > 0.0
    bias_ref, sm_ref, dq_ref = _unpack_refs(refs, has_bias, has_drop)
    bi, hi, qi = pl.program_id(0), pl.program_id(1), pl.program_id(2)
    q = q_ref[0, 0]
    do = do_ref[0, 0]
    delta = delta_ref[0, 0]
    lse = lse_ref[0, 0]
    d = q.shape[-1]
    nkb = k_ref.shape[2] // block_k
    q_off = qi * block_q + causal_shift
    q_abs = qi * block_q
    inv_keep = 1.0 / (1.0 - dropout_rate) if has_drop else 1.0

    def body(j, acc):
        ks = pl.ds(j * block_k, block_k)
        k = k_ref[0, 0, ks, :]
        v = v_ref[0, 0, ks, :]
        bias = bias_ref[0, 0, :, ks] if has_bias else None
        p = _probs(q, k, lse, scale, causal, q_off, j * block_k, bias=bias)
        keep = (_tile_keep(sm_ref, bi, hi, q_abs, j * block_k,
                           (block_q, block_k), dropout_rate, total_heads)
                if has_drop else None)
        ds, _ = _bwd_tile(p, do, v, delta, scale, keep, inv_keep, q.dtype)
        return acc + jnp.dot(ds, k, preferred_element_type=jnp.float32)

    trips = (jnp.clip((q_off + block_q - 1) // block_k + 1, 1, nkb)
             if causal else nkb)
    acc = jax.lax.fori_loop(0, trips, body,
                            jnp.zeros((block_q, d), jnp.float32))
    dq_ref[0, 0] = acc.astype(dq_ref.dtype)


def _dkv_kernel_resident(q_ref, k_ref, v_ref, do_ref, delta_ref, lse_ref,
                         *refs, scale, causal, block_q, block_k,
                         seq_q, causal_shift, has_bias, bias_q_full,
                         dropout_rate, total_heads):
    has_drop = dropout_rate > 0.0
    bias_ref, sm_ref, dk_ref, dv_ref = _unpack_refs(refs, has_bias, has_drop)
    bi, hi, ki = pl.program_id(0), pl.program_id(1), pl.program_id(2)
    k = k_ref[0, 0]                                    # [Bk, d] this block
    v = v_ref[0, 0]
    d = k.shape[-1]
    nqb = seq_q // block_q
    k_off = ki * block_k
    inv_keep = 1.0 / (1.0 - dropout_rate) if has_drop else 1.0

    if causal:
        # first q block whose bottom row reaches this k block
        q_lo = jnp.clip((k_off - causal_shift) // block_q, 0, nqb - 1)
        trips = nqb - q_lo
    else:
        q_lo = 0
        trips = nqb

    def body(i, carry):
        dk_acc, dv_acc = carry
        j = q_lo + i
        qs = pl.ds(j * block_q, block_q)
        q = q_ref[0, 0, qs, :]
        do = do_ref[0, 0, qs, :]
        delta = delta_ref[0, 0, qs, :]
        lse = lse_ref[0, 0, qs, :]
        bias = _bias_rows(bias_ref, bias_q_full, qs) if has_bias else None
        p = _probs(q, k, lse, scale, causal,
                   j * block_q + causal_shift, k_off, bias=bias)
        keep = (_tile_keep(sm_ref, bi, hi, j * block_q, k_off,
                           (block_q, block_k), dropout_rate, total_heads)
                if has_drop else None)
        ds, pv = _bwd_tile(p, do, v, delta, scale, keep, inv_keep, q.dtype)
        dk_acc = dk_acc + jnp.dot(ds.T, q, preferred_element_type=jnp.float32)
        dv_acc = dv_acc + jnp.dot(pv.T, do, preferred_element_type=jnp.float32)
        return dk_acc, dv_acc

    dk_acc, dv_acc = jax.lax.fori_loop(
        0, trips, body,
        (jnp.zeros((k.shape[0], d), jnp.float32),
         jnp.zeros((k.shape[0], d), jnp.float32)))
    dk_ref[0, 0] = dk_acc.astype(dk_ref.dtype)
    dv_ref[0, 0] = dv_acc.astype(dv_ref.dtype)


def _bwd_kernel_monolithic(q_ref, k_ref, v_ref, o_ref, do_ref, *refs,
                           scale, causal, block_q, seq_q, causal_shift,
                           has_bias, dropout_rate, total_heads):
    """Single-pass resident backward: grid (b, h); K/V (and dK/dV fp32
    accumulators) whole in VMEM, one fori over q blocks recomputing the
    [Bq, S] softmax from (q, k, o). Measured fastest at training lengths
    (one kernel launch, K/V and q/do each loaded once). Bias here is
    restricted to broadcast-q ([.., 1, sk]) by the dispatch — a full
    [sq, sk] bias won't fit VMEM at this structure's lengths."""
    has_drop = dropout_rate > 0.0
    bias_ref, sm_ref, dq_ref, dk_ref, dv_ref = _unpack_refs(
        refs, has_bias, has_drop)
    bi, hi = pl.program_id(0), pl.program_id(1)
    k = k_ref[0, 0]                                    # [S, d] native dtype
    v = v_ref[0, 0]
    sk = k.shape[0]
    inv_keep = 1.0 / (1.0 - dropout_rate) if has_drop else 1.0

    def body(i, carry):
        dk_acc, dv_acc = carry
        qs = pl.ds(i * block_q, block_q)
        q = q_ref[0, 0, qs, :]                         # [Bq, d]
        o = o_ref[0, 0, qs, :].astype(jnp.float32)
        do = do_ref[0, 0, qs, :]

        s = jnp.dot(q, k.T, preferred_element_type=jnp.float32) * scale
        if has_bias:
            s = s + bias_ref[0, 0, :, :].astype(jnp.float32)   # [1, S]
        if causal:
            s = _causal_mask(s, i * block_q + causal_shift, 0)
        m = jnp.max(s, axis=-1, keepdims=True)
        # guard fully-masked rows (bias = -1e30 everywhere): m ~ -1e30
        p_un = jnp.where(m > NEG_INF / 2, jnp.exp(s - m), 0.0)
        l = jnp.sum(p_un, axis=-1, keepdims=True)
        p = p_un / jnp.where(l > 0.0, l, 1.0)          # [Bq, S] fp32

        delta = jnp.sum(do.astype(jnp.float32) * o, axis=-1, keepdims=True)
        keep = (_tile_keep(sm_ref, bi, hi, i * block_q, 0,
                           (block_q, sk), dropout_rate, total_heads)
                if has_drop else None)
        ds, pv = _bwd_tile(p, do, v, delta, scale, keep, inv_keep, q.dtype)

        dq_ref[0, 0, qs, :] = jnp.dot(
            ds, k, preferred_element_type=jnp.float32).astype(dq_ref.dtype)
        dk_acc = dk_acc + jnp.dot(ds.T, q, preferred_element_type=jnp.float32)
        dv_acc = dv_acc + jnp.dot(pv.T, do, preferred_element_type=jnp.float32)
        return dk_acc, dv_acc

    dk_acc, dv_acc = jax.lax.fori_loop(
        0, seq_q // block_q, body,
        (jnp.zeros(k.shape, jnp.float32), jnp.zeros(v.shape, jnp.float32)))
    dk_ref[0, 0] = dk_acc.astype(dk_ref.dtype)
    dv_ref[0, 0] = dv_acc.astype(dv_ref.dtype)


# ---------------------------------------------------------------------------
# streamed structure: K/V blocks flow through the grid, scratch accumulators
# ---------------------------------------------------------------------------

def _fwd_kernel_streamed(q_ref, k_ref, v_ref, *refs, scale, causal, block_q,
                         block_k, causal_shift, nkb, has_bias, dropout_rate,
                         total_heads):
    has_drop = dropout_rate > 0.0
    bias_ref, sm_ref, o_ref, lse_ref, acc_ref, m_ref, l_ref = _unpack_refs(
        refs, has_bias, has_drop)
    bi, hi = pl.program_id(0), pl.program_id(1)
    qi, ki = pl.program_id(2), pl.program_id(3)
    q_off = qi * block_q + causal_shift
    inv_keep = 1.0 / (1.0 - dropout_rate) if has_drop else 1.0

    @pl.when(ki == 0)
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref)
        m_ref[...] = jnp.full_like(m_ref, NEG_INF)
        l_ref[...] = jnp.zeros_like(l_ref)

    live = True if not causal else ki * block_k <= q_off + block_q - 1

    @pl.when(live)
    def _compute():
        bias = bias_ref[0, 0] if has_bias else None
        keep = (_tile_keep(sm_ref, bi, hi, qi * block_q, ki * block_k,
                           (block_q, block_k), dropout_rate, total_heads)
                if has_drop else None)
        acc, m, l = _online_step(
            q_ref[0, 0], k_ref[0, 0], v_ref[0, 0], scale, causal, q_off,
            ki * block_k, acc_ref[...], m_ref[...], l_ref[...],
            bias=bias, keep=keep, inv_keep=inv_keep)
        acc_ref[...], m_ref[...], l_ref[...] = acc, m, l

    @pl.when(ki == nkb - 1)
    def _emit():
        _emit_o_lse(acc_ref[...], m_ref[...], l_ref[...], o_ref, lse_ref)


def _dq_kernel_streamed(q_ref, k_ref, v_ref, do_ref, delta_ref, lse_ref,
                        *refs, scale, causal, block_q, block_k,
                        causal_shift, nkb, has_bias, dropout_rate,
                        total_heads):
    has_drop = dropout_rate > 0.0
    bias_ref, sm_ref, dq_ref, acc_ref = _unpack_refs(refs, has_bias, has_drop)
    bi, hi = pl.program_id(0), pl.program_id(1)
    qi, ki = pl.program_id(2), pl.program_id(3)
    q_off = qi * block_q + causal_shift
    inv_keep = 1.0 / (1.0 - dropout_rate) if has_drop else 1.0

    @pl.when(ki == 0)
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref)

    live = True if not causal else ki * block_k <= q_off + block_q - 1

    @pl.when(live)
    def _compute():
        q = q_ref[0, 0]
        do = do_ref[0, 0]
        bias = bias_ref[0, 0] if has_bias else None
        p = _probs(q, k_ref[0, 0], lse_ref[0, 0], scale, causal, q_off,
                   ki * block_k, bias=bias)
        keep = (_tile_keep(sm_ref, bi, hi, qi * block_q, ki * block_k,
                           (block_q, block_k), dropout_rate, total_heads)
                if has_drop else None)
        ds, _ = _bwd_tile(p, do, v_ref[0, 0], delta_ref[0, 0], scale,
                          keep, inv_keep, q.dtype)
        acc_ref[...] += jnp.dot(ds, k_ref[0, 0],
                                preferred_element_type=jnp.float32)

    @pl.when(ki == nkb - 1)
    def _emit():
        dq_ref[0, 0] = acc_ref[...].astype(dq_ref.dtype)


def _dkv_kernel_streamed(q_ref, k_ref, v_ref, do_ref, delta_ref, lse_ref,
                         *refs, scale, causal, block_q, block_k,
                         causal_shift, nqb, has_bias, dropout_rate,
                         total_heads):
    has_drop = dropout_rate > 0.0
    bias_ref, sm_ref, dk_ref, dv_ref, dk_acc, dv_acc = _unpack_refs(
        refs, has_bias, has_drop)
    bi, hi = pl.program_id(0), pl.program_id(1)
    ki, qi = pl.program_id(2), pl.program_id(3)
    q_off = qi * block_q + causal_shift
    k_off = ki * block_k
    inv_keep = 1.0 / (1.0 - dropout_rate) if has_drop else 1.0

    @pl.when(qi == 0)
    def _init():
        dk_acc[...] = jnp.zeros_like(dk_acc)
        dv_acc[...] = jnp.zeros_like(dv_acc)

    live = True if not causal else q_off + block_q - 1 >= k_off

    @pl.when(live)
    def _compute():
        q = q_ref[0, 0]
        do = do_ref[0, 0]
        bias = bias_ref[0, 0] if has_bias else None
        p = _probs(q, k_ref[0, 0], lse_ref[0, 0], scale, causal, q_off,
                   k_off, bias=bias)
        keep = (_tile_keep(sm_ref, bi, hi, qi * block_q, k_off,
                           (block_q, block_k), dropout_rate, total_heads)
                if has_drop else None)
        ds, pv = _bwd_tile(p, do, v_ref[0, 0], delta_ref[0, 0], scale,
                           keep, inv_keep, q.dtype)
        dk_acc[...] += jnp.dot(ds.T, q, preferred_element_type=jnp.float32)
        dv_acc[...] += jnp.dot(pv.T, do, preferred_element_type=jnp.float32)

    @pl.when(qi == nqb - 1)
    def _emit():
        dk_ref[0, 0] = dk_acc[...].astype(dk_ref.dtype)
        dv_ref[0, 0] = dv_acc[...].astype(dv_ref.dtype)


# ---------------------------------------------------------------------------
# dispatch
# ---------------------------------------------------------------------------

def _resolve_blocks(structure, sq, sk, d, dtype, causal, block_q,
                    fallback_bq, fallback_bk=None, record=True):
    """Trace-time block-size resolution for one kernel structure: an
    explicit caller ``block_q`` wins, else the shape-keyed tuning cache,
    else the hand-picked fallback constants. Every size passes through
    ``_block`` (divisor + 128-lane alignment), so a stale or foreign
    cache entry can never produce an illegal tiling. Returns
    (block_q, block_k-or-None) and — unless this is a provisional
    resolution (``record=False``: the caller may still demote the
    structure on a bias VMEM-budget check) — records the dispatch for
    the ``tuning.last_dispatch`` probe, which must only ever name
    structures that actually run (the sweep harness tunes exactly what
    the probe reports)."""
    entry, key, source = _tuning.lookup(
        "flash_attention", structure, sq=sq, sk=sk, d=d, dtype=dtype,
        causal=causal)
    want_q = (block_q if block_q is not None
              else int(entry.get("block_q", fallback_bq)))
    bq = _block(sq, min(want_q, sq))
    rec = dict(block_q=bq)
    bk = None
    if fallback_bk is not None:
        bk = _block(sk, min(int(entry.get("block_k", fallback_bk)), sk))
        rec["block_k"] = bk
    if record:
        _tuning.record_dispatch(
            "flash_attention", structure, key,
            "caller" if block_q is not None else source, **rec)
    return bq, bk


def _bias_meta(bias):
    """(batched, headed, q_full) broadcast flags of a [b', h', sq', sk]
    bias operand."""
    return bias.shape[0] > 1, bias.shape[1] > 1, bias.shape[2] > 1


def _bias_spec3(bias, block_q):
    """BlockSpec for 3-D grids (b, h, qi): full sk extent per tile."""
    bb, bh, bq_full = _bias_meta(bias)
    sk = bias.shape[3]
    shape = (1, 1, block_q if bq_full else 1, sk)
    return pl.BlockSpec(shape, lambda bi, hi, qi: (
        bi if bb else 0, hi if bh else 0, qi if bq_full else 0, 0))


def _bias_spec3_k(bias, block_k, seq_q):
    """BlockSpec for the resident dkv grid (b, h, ki): full sq extent,
    one k block."""
    bb, bh, bq_full = _bias_meta(bias)
    shape = (1, 1, seq_q if bq_full else 1, block_k)
    return pl.BlockSpec(shape, lambda bi, hi, ki: (
        bi if bb else 0, hi if bh else 0, 0, ki))


def _bias_spec4(bias, block_q, block_k, q_pos, k_pos):
    """BlockSpec for 4-D streamed grids; q_pos/k_pos say which grid axes
    carry the q/k block indices (2, 3) or (3, 2)."""
    bb, bh, bq_full = _bias_meta(bias)
    shape = (1, 1, block_q if bq_full else 1, block_k)

    def idx(*g):
        return (g[0] if bb else 0, g[1] if bh else 0,
                g[q_pos] if bq_full else 0, g[k_pos])

    return pl.BlockSpec(shape, idx)


def _bias_spec2(bias):
    """BlockSpec for the monolithic (b, h) grid: bias is broadcast-q
    ([.., 1, sk]) here by construction."""
    bb, bh, _ = _bias_meta(bias)
    return pl.BlockSpec((1, 1, 1, bias.shape[3]), lambda bi, hi: (
        bi if bb else 0, hi if bh else 0, 0, 0))


_SM_SPEC = pl.BlockSpec(memory_space=pltpu.SMEM)


def _extra_ops(bias, seeds, bias_spec):
    """(operands, specs) for the optional bias/seed inputs."""
    ops, specs = [], []
    if bias is not None:
        ops.append(bias)
        specs.append(bias_spec)
    if seeds is not None:
        ops.append(seeds)
        specs.append(_SM_SPEC)
    return tuple(ops), tuple(specs)


def _flash_fwd(q, k, v, bias, seeds, scale, causal, dropout_rate,
               total_heads, block_q):
    b, h, sq, d = q.shape
    sk = k.shape[2]
    has_bias = bias is not None
    drop = dropout_rate if seeds is not None else 0.0
    common = dict(scale=scale, causal=causal, has_bias=has_bias,
                  dropout_rate=drop, total_heads=total_heads)
    out_shape = (jax.ShapeDtypeStruct(q.shape, q.dtype),
                 jax.ShapeDtypeStruct((b, h, sq, 1), jnp.float32))
    caller_bq = block_q   # keep the caller's request distinct from the
    resident = _kv_fits_vmem(sk, d, q.dtype.itemsize)   # resolved values
    if resident:
        block_q, block_k = _resolve_blocks(
            "fwd_resident", sq, sk, d, q.dtype, causal, caller_bq,
            DEFAULT_BLOCK_Q, RESIDENT_BLOCK_K, record=False)
        if has_bias and bias.shape[2] > 1 and (
                # a full-extent bias tile [Bq, sk] shares VMEM with
                # resident K/V
                block_q * sk * bias.dtype.itemsize > _BIAS_TILE_BUDGET):
            resident = False
    if resident:
        _resolve_blocks("fwd_resident", sq, sk, d, q.dtype, causal,
                        caller_bq, DEFAULT_BLOCK_Q, RESIDENT_BLOCK_K)
    else:
        block_q, block_k = _resolve_blocks(
            "fwd_streamed", sq, sk, d, q.dtype, causal, caller_bq,
            DEFAULT_BLOCK_Q, STREAMED_BLOCK_K)
    q_blk3 = pl.BlockSpec((1, 1, block_q, d),
                          lambda bi, hi, qi: (bi, hi, qi, 0))
    lse_blk3 = pl.BlockSpec((1, 1, block_q, 1),
                            lambda bi, hi, qi: (bi, hi, qi, 0))
    if resident:
        extra, extra_specs = _extra_ops(
            bias, seeds, _bias_spec3(bias, block_q) if has_bias else None)
        kv_full = pl.BlockSpec((1, 1, sk, d),
                               lambda bi, hi, qi: (bi, hi, 0, 0))
        o, lse = pl.pallas_call(
            functools.partial(_fwd_kernel_resident, block_q=block_q,
                              block_k=block_k,
                              causal_shift=sk - sq, **common),
            grid=(b, h, sq // block_q),
            in_specs=[q_blk3, kv_full, kv_full, *extra_specs],
            out_specs=(q_blk3, lse_blk3),
            out_shape=out_shape,
            interpret=_interpret(),
        )(q, k, v, *extra)
        return o, lse
    nkb = sk // block_k
    extra, extra_specs = _extra_ops(
        bias, seeds,
        _bias_spec4(bias, block_q, block_k, 2, 3) if has_bias else None)
    o, lse = pl.pallas_call(
        functools.partial(_fwd_kernel_streamed, block_q=block_q,
                          block_k=block_k, causal_shift=sk - sq, nkb=nkb,
                          **common),
        grid=(b, h, sq // block_q, nkb),
        in_specs=[
            pl.BlockSpec((1, 1, block_q, d),
                         lambda bi, hi, qi, ki: (bi, hi, qi, 0)),
            pl.BlockSpec((1, 1, block_k, d),
                         lambda bi, hi, qi, ki: (bi, hi, ki, 0)),
            pl.BlockSpec((1, 1, block_k, d),
                         lambda bi, hi, qi, ki: (bi, hi, ki, 0)),
            *extra_specs,
        ],
        out_specs=(
            pl.BlockSpec((1, 1, block_q, d),
                         lambda bi, hi, qi, ki: (bi, hi, qi, 0)),
            pl.BlockSpec((1, 1, block_q, 1),
                         lambda bi, hi, qi, ki: (bi, hi, qi, 0)),
        ),
        out_shape=out_shape,
        scratch_shapes=[pltpu.VMEM((block_q, d), jnp.float32),
                        pltpu.VMEM((block_q, 1), jnp.float32),
                        pltpu.VMEM((block_q, 1), jnp.float32)],
        interpret=_interpret(),
    )(q, k, v, *extra)
    return o, lse


def _dbias_dense(q, k, v, o, lse, g, bias, seeds, scale, causal,
                 dropout_rate, total_heads):
    """dBias via dense recompute from the saved LSE, reduced to the bias's
    broadcast shape. Lives OUTSIDE the Pallas kernels on purpose: when the
    bias is not differentiated (masks, alibi — the common case) XLA
    dead-code-eliminates this whole chain, so the flash path pays nothing;
    when it IS differentiated (T5-style trainable bias) the caller already
    holds O(s^2) bias storage, and XLA fuses the elementwise chain into
    the reduction."""
    f32 = jnp.float32
    s = jnp.einsum("bhqd,bhkd->bhqk", q.astype(f32), k.astype(f32)) * scale
    s = s + bias.astype(f32)
    sq, sk = q.shape[2], k.shape[2]
    if causal:
        cm = jnp.tril(jnp.ones((sq, sk), bool), sk - sq)
        s = jnp.where(cm, s, NEG_INF)
    p = jnp.where(lse > NEG_INF / 2, jnp.exp(s - lse), 0.0)
    dp = jnp.einsum("bhqd,bhkd->bhqk", g.astype(f32), v.astype(f32))
    if dropout_rate > 0.0 and seeds is not None:
        keep = attention_dropout_keep(
            seeds[:2], dropout_rate, p.shape, total_heads=total_heads,
            head_offset=seeds[2], batch_offset=seeds[3])
        dp = jnp.where(keep, dp / (1.0 - dropout_rate), 0.0)
    delta = jnp.sum(g.astype(f32) * o.astype(f32), axis=-1, keepdims=True)
    dbias_full = p * (dp - delta)
    reduce_dims = tuple(i for i in range(3) if bias.shape[i] == 1)
    dbias = jnp.sum(dbias_full, axis=reduce_dims, keepdims=True)
    return dbias.astype(bias.dtype)


def _flash_bwd(scale, causal, dropout_rate, block_q, total_heads,
               bias_grad, res, g):
    q, k, v, bias, seeds, o, lse = res
    b, h, sq, d = q.shape
    sk = k.shape[2]
    has_bias = bias is not None
    drop = dropout_rate if seeds is not None else 0.0
    bias_q_full = has_bias and bias.shape[2] > 1
    common = dict(scale=scale, causal=causal, has_bias=has_bias,
                  dropout_rate=drop, total_heads=total_heads)

    # bias_grad=False (statically known non-trainable bias, e.g. a folded
    # mask): zero cotangent at the bias's own (broadcast) shape — the
    # dense O(s^2) recompute is never built, which matters in EAGER grads
    # where XLA's DCE can't elide it
    if not has_bias:
        dbias = None
    elif bias_grad:
        dbias = _dbias_dense(q, k, v, o, lse, g, bias, seeds, scale,
                             causal, drop, total_heads)
    else:
        dbias = jnp.zeros_like(bias)
    dseeds = (np.zeros(seeds.shape, jax.dtypes.float0)
              if seeds is not None else None)

    # Training lengths: the single-pass resident backward wins (one
    # launch; K/V, q, do each read once; measured best 125M e2e on v5e).
    # Its VMEM budget: K/V + fp32 dK/dV accumulators + 3 [Bq, S] fp32
    # tiles — comfortable through 4k. A full-extent bias can't ride in
    # this structure (its [sq, sk] tile outgrows VMEM) — two-pass then.
    if (sk <= MONOLITHIC_BWD_MAX_SEQ and sq <= MONOLITHIC_BWD_MAX_SEQ
            and not bias_q_full):
        entry, key, source = _tuning.lookup(
            "flash_attention", "bwd_monolithic", sq=sq, sk=sk, d=d,
            dtype=q.dtype, causal=causal)
        want = (block_q if block_q is not None
                else int(entry.get("block_q", DEFAULT_BLOCK_Q)))
        # VMEM cap on the [Bq, S] fp32 score tiles stays authoritative
        # over any cache entry
        cap = max(128, (2 ** 19 // max(sk, 1)) // 128 * 128)
        bq = math.gcd(sq, min(want, sq, cap))
        if bq % 8 != 0:
            bq = sq
        _tuning.record_dispatch(
            "flash_attention", "bwd_monolithic", key,
            "caller" if block_q is not None else source, block_q=bq)
        extra, extra_specs = _extra_ops(
            bias, seeds, _bias_spec2(bias) if has_bias else None)
        full_q = pl.BlockSpec((1, 1, sq, d), lambda bi, hi: (bi, hi, 0, 0))
        full_k = pl.BlockSpec((1, 1, sk, d), lambda bi, hi: (bi, hi, 0, 0))
        dq, dk, dv = pl.pallas_call(
            functools.partial(_bwd_kernel_monolithic, block_q=bq, seq_q=sq,
                              causal_shift=sk - sq, **common),
            grid=(b, h),
            in_specs=[full_q, full_k, full_k, full_q, full_q, *extra_specs],
            out_specs=(full_q, full_k, full_k),
            out_shape=(jax.ShapeDtypeStruct(q.shape, q.dtype),
                       jax.ShapeDtypeStruct(k.shape, k.dtype),
                       jax.ShapeDtypeStruct(v.shape, v.dtype)),
            interpret=_interpret(),
        )(q, k, v, o, g, *extra)
        return (dq, dk, dv, dbias, dseeds)

    caller_bq = block_q
    resident = (_kv_fits_vmem(sk, d, q.dtype.itemsize)
                and _kv_fits_vmem(sq, d, q.dtype.itemsize))
    if resident:
        block_q, block_k = _resolve_blocks(
            "bwd_resident", sq, sk, d, q.dtype, causal, caller_bq,
            DEFAULT_BLOCK_Q, RESIDENT_BLOCK_K, record=False)
        if bias_q_full and (
                # both passes load full-extent bias tiles: [Bq, sk] in dq
                # and [sq, Bk] in dkv — budget the larger one
                max(block_q * sk, sq * block_k) * bias.dtype.itemsize
                > _BIAS_TILE_BUDGET):
            resident = False
    if resident:
        _resolve_blocks("bwd_resident", sq, sk, d, q.dtype, causal,
                        caller_bq, DEFAULT_BLOCK_Q, RESIDENT_BLOCK_K)
    else:
        block_q, block_k = _resolve_blocks(
            "bwd_streamed", sq, sk, d, q.dtype, causal, caller_bq,
            DEFAULT_BLOCK_Q, STREAMED_BLOCK_K)
    nqb, nkb = sq // block_q, sk // block_k
    # delta = rowsum(do * o): cheap elementwise outside the kernels
    delta = jnp.sum(g.astype(jnp.float32) * o.astype(jnp.float32),
                    axis=-1, keepdims=True)

    if resident:
        q_blk = pl.BlockSpec((1, 1, block_q, d),
                             lambda bi, hi, qi: (bi, hi, qi, 0))
        q_stat = pl.BlockSpec((1, 1, block_q, 1),
                              lambda bi, hi, qi: (bi, hi, qi, 0))
        kv_full = pl.BlockSpec((1, 1, sk, d),
                               lambda bi, hi, qi: (bi, hi, 0, 0))
        extra, extra_specs = _extra_ops(
            bias, seeds, _bias_spec3(bias, block_q) if has_bias else None)
        dq = pl.pallas_call(
            functools.partial(_dq_kernel_resident, block_q=block_q,
                              block_k=block_k, causal_shift=sk - sq,
                              **common),
            grid=(b, h, nqb),
            in_specs=[q_blk, kv_full, kv_full, q_blk, q_stat, q_stat,
                      *extra_specs],
            out_specs=q_blk,
            out_shape=jax.ShapeDtypeStruct(q.shape, q.dtype),
            interpret=_interpret(),
        )(q, k, v, g, delta, lse, *extra)

        k_blk = pl.BlockSpec((1, 1, block_k, d),
                             lambda bi, hi, ki: (bi, hi, ki, 0))
        q_full = pl.BlockSpec((1, 1, sq, d),
                              lambda bi, hi, ki: (bi, hi, 0, 0))
        stat_full = pl.BlockSpec((1, 1, sq, 1),
                                 lambda bi, hi, ki: (bi, hi, 0, 0))
        extra_k, extra_k_specs = _extra_ops(
            bias, seeds,
            _bias_spec3_k(bias, block_k, sq) if has_bias else None)
        dk, dv = pl.pallas_call(
            functools.partial(_dkv_kernel_resident, block_q=block_q,
                              block_k=block_k, seq_q=sq,
                              causal_shift=sk - sq,
                              bias_q_full=bias_q_full, **common),
            grid=(b, h, nkb),
            in_specs=[q_full, k_blk, k_blk, q_full, stat_full, stat_full,
                      *extra_k_specs],
            out_specs=(k_blk, k_blk),
            out_shape=(jax.ShapeDtypeStruct(k.shape, k.dtype),
                       jax.ShapeDtypeStruct(v.shape, v.dtype)),
            interpret=_interpret(),
        )(q, k, v, g, delta, lse, *extra_k)
        return (dq, dk, dv, dbias, dseeds)

    q_blk = lambda bi, hi, qi, ki: (bi, hi, qi, 0)
    k_blk = lambda bi, hi, qi, ki: (bi, hi, ki, 0)
    extra, extra_specs = _extra_ops(
        bias, seeds,
        _bias_spec4(bias, block_q, block_k, 2, 3) if has_bias else None)
    dq = pl.pallas_call(
        functools.partial(_dq_kernel_streamed, block_q=block_q,
                          block_k=block_k, causal_shift=sk - sq, nkb=nkb,
                          **common),
        grid=(b, h, nqb, nkb),
        in_specs=[pl.BlockSpec((1, 1, block_q, d), q_blk),
                  pl.BlockSpec((1, 1, block_k, d), k_blk),
                  pl.BlockSpec((1, 1, block_k, d), k_blk),
                  pl.BlockSpec((1, 1, block_q, d), q_blk),
                  pl.BlockSpec((1, 1, block_q, 1), q_blk),
                  pl.BlockSpec((1, 1, block_q, 1), q_blk),
                  *extra_specs],
        out_specs=pl.BlockSpec((1, 1, block_q, d), q_blk),
        out_shape=jax.ShapeDtypeStruct(q.shape, q.dtype),
        scratch_shapes=[pltpu.VMEM((block_q, d), jnp.float32)],
        interpret=_interpret(),
    )(q, k, v, g, delta, lse, *extra)

    kq_k = lambda bi, hi, ki, qi: (bi, hi, ki, 0)
    kq_q = lambda bi, hi, ki, qi: (bi, hi, qi, 0)
    extra_k, extra_k_specs = _extra_ops(
        bias, seeds,
        _bias_spec4(bias, block_q, block_k, 3, 2) if has_bias else None)
    dk, dv = pl.pallas_call(
        functools.partial(_dkv_kernel_streamed, block_q=block_q,
                          block_k=block_k, causal_shift=sk - sq, nqb=nqb,
                          **common),
        grid=(b, h, nkb, nqb),
        in_specs=[pl.BlockSpec((1, 1, block_q, d), kq_q),
                  pl.BlockSpec((1, 1, block_k, d), kq_k),
                  pl.BlockSpec((1, 1, block_k, d), kq_k),
                  pl.BlockSpec((1, 1, block_q, d), kq_q),
                  pl.BlockSpec((1, 1, block_q, 1), kq_q),
                  pl.BlockSpec((1, 1, block_q, 1), kq_q),
                  *extra_k_specs],
        out_specs=(pl.BlockSpec((1, 1, block_k, d), kq_k),
                   pl.BlockSpec((1, 1, block_k, d), kq_k)),
        out_shape=(jax.ShapeDtypeStruct(k.shape, k.dtype),
                   jax.ShapeDtypeStruct(v.shape, v.dtype)),
        scratch_shapes=[pltpu.VMEM((block_k, d), jnp.float32),
                        pltpu.VMEM((block_k, d), jnp.float32)],
        interpret=_interpret(),
    )(q, k, v, g, delta, lse, *extra_k)
    return (dq, dk, dv, dbias, dseeds)


@functools.partial(jax.custom_vjp, nondiff_argnums=(5, 6, 7, 8, 9, 10))
def _flash_attention_bhsd(q, k, v, bias, seeds, scale, causal,
                          dropout_rate, block_q, total_heads, bias_grad):
    o, _ = _flash_fwd(q, k, v, bias, seeds, scale, causal, dropout_rate,
                      total_heads, block_q)
    return o


def _fwd_rule(q, k, v, bias, seeds, scale, causal, dropout_rate, block_q,
              total_heads, bias_grad):
    o, lse = _flash_fwd(q, k, v, bias, seeds, scale, causal, dropout_rate,
                        total_heads, block_q)
    return o, (q, k, v, bias, seeds, o, lse)


_flash_attention_bhsd.defvjp(_fwd_rule, _flash_bwd)


def flash_attention(q, k, v, *, bias=None, causal=True, softmax_scale=None,
                    dropout_rate=0.0, dropout_rng=None, dropout_offsets=None,
                    bias_grad=True, block_q=None):
    """q,k,v: [batch, seq, heads, head_dim] (BSHD). Returns like q.

    block_q: None (default) = table-driven — each kernel structure reads
    its block sizes from the shape-keyed tuning cache (ops.pallas.tuning:
    runtime table > $DS_TPU_KERNEL_TUNING_CACHE artifact > committed
    default table > hand-picked constants). An explicit int forces that
    q-block for every structure (block_k stays table-driven).

    bias: optional additive [b|1, h|1, sq|1, sk] operand (fold boolean
    masks to 0/-1e30 before calling — ``ops.transformer.attention`` does).
    bias_grad=False declares the bias non-trainable (masks, alibi): the
    backward rule then emits a zero cotangent instead of the dense dBias
    recompute — under jit the recompute is DCE'd anyway when unused, but
    eager-mode grads would otherwise pay its O(s^2) cost.
    dropout_rate/dropout_rng: fused attention-probability dropout (active
    when both are set). dropout_offsets: (total_heads, head_offset,
    batch_offset) so shard_map callers with local head/batch windows
    sample the same global keep mask as a replicated run.
    """
    d = q.shape[-1]
    scale = softmax_scale if softmax_scale is not None else 1.0 / math.sqrt(d)
    sq = q.shape[1]
    bq = None
    if block_q is not None:
        bq = min(int(block_q), sq)
        if sq % bq != 0:
            raise ValueError(f"flash_attention: seq {sq} must be divisible "
                             f"by block_q {bq}")
    bias4 = None
    if bias is not None:
        full = (q.shape[0], q.shape[2], sq)
        if (bias.ndim != 4 or bias.shape[3] != k.shape[1]
                or any(bias.shape[i] not in (1, full[i]) for i in range(3))):
            # dims 0-2 must each be broadcast (1) or full-size: a partial
            # extent would make the BlockSpec index maps read clamped
            # (wrong) blocks instead of failing
            raise ValueError(
                f"flash_attention: bias must be [b|1, h|1, sq|1, sk], got "
                f"{bias.shape} for q {q.shape}, sk={k.shape[1]}")
        # full-extent biases ride VMEM in bf16 (the kernel adds in fp32);
        # broadcast-q biases (masks, alibi rows) are small — keep fp32
        bias4 = bias.astype(q.dtype if bias.shape[2] > 1 else jnp.float32)
    rate, seeds, total_heads = resolve_dropout(
        dropout_rate, dropout_rng, dropout_offsets, q.shape[2])
    qt, kt, vt = (jnp.swapaxes(t, 1, 2) for t in (q, k, v))
    o = _flash_attention_bhsd(qt, kt, vt, bias4, seeds, scale, causal,
                              rate, bq, total_heads, bool(bias_grad))
    return jnp.swapaxes(o, 1, 2)
