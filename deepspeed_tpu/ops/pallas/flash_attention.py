"""Flash attention Pallas kernel (fwd + bwd).

TPU-native replacement for the reference's fused CUDA attention
(csrc/transformer/softmax_kernels.cu + strided batched gemms orchestrated in
ds_transformer_cuda.cpp; inference variant softmax_context in
csrc/transformer/inference/). Design:

- layout: kernels run in BHSD ([batch, heads, seq, head_dim]) so block
  shapes keep the (sublane, lane)-aligned last two dims the Mosaic lowering
  requires; the public API takes BSHD and transposes at dispatch.
- forward: grid (batch, heads, q_blocks); one q block [Bq, d] against the
  full K/V [S, d] resident in VMEM (S·d·2B ≤ ~0.5 MB for S≤4096, d≤128 —
  comfortably inside the ~16 MB VMEM budget), fp32 softmax.
- backward: grid (batch, heads); fori_loop over q blocks *recomputing* the
  softmax (flash-style recompute — no S×S matrix and no saved LSE),
  accumulating dK/dV in registers/VMEM.
- autodiff via jax.custom_vjp (the reference wires fwd/bwd kernels through
  torch.autograd.Function the same way).
"""

import functools
import math

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

DEFAULT_BLOCK_Q = 512
NEG_INF = -1e30


from ._common import interpret_mode as _interpret


def _softmax_tile(q, k, scale, causal, q_offset):
    """[Bq,d]x[S,d] -> probability tile [Bq,S] (fp32) and the row stats.

    ``q_offset`` already includes the bottom-right causal alignment shift
    (sk - sq), matching the reference backend's ``tril(..., k_len - q_len)``
    so both backends agree when sk != sq (decode with KV cache)."""
    s = jnp.dot(q, k.T, preferred_element_type=jnp.float32) * scale
    if causal:
        row = jax.lax.broadcasted_iota(jnp.int32, s.shape, 0) + q_offset
        col = jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
        s = jnp.where(col <= row, s, NEG_INF)
    m = jnp.max(s, axis=-1, keepdims=True)
    p = jnp.exp(s - m)
    l = jnp.sum(p, axis=-1, keepdims=True)
    return p, l


def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, *, scale, causal, block_q,
                causal_shift):
    q = q_ref[0, 0].astype(jnp.float32)                # [Bq, d]
    k = k_ref[0, 0].astype(jnp.float32)                # [S, d]
    v = v_ref[0, 0].astype(jnp.float32)
    p, l = _softmax_tile(q, k, scale, causal,
                         pl.program_id(2) * block_q + causal_shift)
    o = jnp.dot(p, v, preferred_element_type=jnp.float32) / l
    o_ref[0, 0] = o.astype(o_ref.dtype)


def _flash_fwd(q, k, v, scale, causal, block_q):
    b, h, sq, d = q.shape
    sk = k.shape[2]
    block_q = min(block_q, sq)
    grid = (b, h, pl.cdiv(sq, block_q))
    return pl.pallas_call(
        functools.partial(_fwd_kernel, scale=scale, causal=causal,
                          block_q=block_q, causal_shift=sk - sq),
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, 1, block_q, d), lambda bi, hi, qi: (bi, hi, qi, 0)),
            pl.BlockSpec((1, 1, sk, d), lambda bi, hi, qi: (bi, hi, 0, 0)),
            pl.BlockSpec((1, 1, sk, d), lambda bi, hi, qi: (bi, hi, 0, 0)),
        ],
        out_specs=pl.BlockSpec((1, 1, block_q, d),
                               lambda bi, hi, qi: (bi, hi, qi, 0)),
        out_shape=jax.ShapeDtypeStruct(q.shape, q.dtype),
        interpret=_interpret(),
    )(q, k, v)


def _bwd_kernel(q_ref, k_ref, v_ref, o_ref, do_ref,
                dq_ref, dk_ref, dv_ref, *, scale, causal, block_q, seq_q,
                causal_shift):
    k = k_ref[0, 0].astype(jnp.float32)                # [S, d]
    v = v_ref[0, 0].astype(jnp.float32)

    def body(i, carry):
        dk_acc, dv_acc = carry
        qs = pl.ds(i * block_q, block_q)
        q = q_ref[0, 0, qs, :].astype(jnp.float32)     # [Bq, d]
        o = o_ref[0, 0, qs, :].astype(jnp.float32)
        do = do_ref[0, 0, qs, :].astype(jnp.float32)

        p_un, l = _softmax_tile(q, k, scale, causal,
                                i * block_q + causal_shift)
        p = p_un / l                                   # [Bq, S]

        delta = jnp.sum(do * o, axis=-1, keepdims=True)
        dp = jnp.dot(do, v.T, preferred_element_type=jnp.float32)
        ds = p * (dp - delta) * scale

        dq_ref[0, 0, qs, :] = jnp.dot(
            ds, k, preferred_element_type=jnp.float32).astype(dq_ref.dtype)
        dk_acc = dk_acc + jnp.dot(ds.T, q, preferred_element_type=jnp.float32)
        dv_acc = dv_acc + jnp.dot(p.T, do, preferred_element_type=jnp.float32)
        return dk_acc, dv_acc

    dk_acc, dv_acc = jax.lax.fori_loop(
        0, seq_q // block_q, body,
        (jnp.zeros_like(k), jnp.zeros_like(v)))
    dk_ref[0, 0] = dk_acc.astype(dk_ref.dtype)
    dv_ref[0, 0] = dv_acc.astype(dv_ref.dtype)


def _flash_bwd(scale, causal, block_q, res, g):
    q, k, v, o = res
    b, h, sq, d = q.shape
    sk = k.shape[2]
    # Smaller q block than fwd: bwd holds three [Bq, S] fp32 tiles
    # (p, dp, ds) plus fp32 dK/dV accumulators in VMEM. Bound the tiles to
    # ~6 MB: Bq*S*4B*3 <= 6MB  =>  Bq <= 2^19/S, floored to a 128 multiple.
    cap = max(128, (2 ** 19 // max(sk, 1)) // 128 * 128)
    # Largest block <= cap that divides sq: gcd keeps the 128-alignment
    # whenever sq is itself a multiple of 128 (the pallas-path requirement),
    # avoiding a degenerate halving spiral for seqs like 1280.
    block_q = math.gcd(sq, min(block_q, sq, cap))
    if block_q % 8 != 0:  # non-128-multiple seq: fall back to full rows
        block_q = sq
    full_q = pl.BlockSpec((1, 1, sq, d), lambda bi, hi: (bi, hi, 0, 0))
    full_k = pl.BlockSpec((1, 1, sk, d), lambda bi, hi: (bi, hi, 0, 0))
    dq, dk, dv = pl.pallas_call(
        functools.partial(_bwd_kernel, scale=scale, causal=causal,
                          block_q=block_q, seq_q=sq, causal_shift=sk - sq),
        grid=(b, h),
        in_specs=[full_q, full_k, full_k, full_q, full_q],
        out_specs=(full_q, full_k, full_k),
        out_shape=(jax.ShapeDtypeStruct(q.shape, q.dtype),
                   jax.ShapeDtypeStruct(k.shape, k.dtype),
                   jax.ShapeDtypeStruct(v.shape, v.dtype)),
        interpret=_interpret(),
    )(q, k, v, o, g)
    return dq, dk, dv


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5))
def _flash_attention_bhsd(q, k, v, scale, causal, block_q):
    return _flash_fwd(q, k, v, scale, causal, block_q)


def _fwd_rule(q, k, v, scale, causal, block_q):
    o = _flash_fwd(q, k, v, scale, causal, block_q)
    return o, (q, k, v, o)


_flash_attention_bhsd.defvjp(_fwd_rule, _flash_bwd)


def flash_attention(q, k, v, *, causal=True, softmax_scale=None,
                    block_q=DEFAULT_BLOCK_Q):
    """q,k,v: [batch, seq, heads, head_dim] (BSHD). Returns like q."""
    d = q.shape[-1]
    scale = softmax_scale if softmax_scale is not None else 1.0 / math.sqrt(d)
    sq = q.shape[1]
    bq = min(block_q, sq)
    if sq % bq != 0:
        raise ValueError(f"flash_attention: seq {sq} must be divisible by "
                         f"block_q {bq}")
    qt, kt, vt = (jnp.swapaxes(t, 1, 2) for t in (q, k, v))
    o = _flash_attention_bhsd(qt, kt, vt, scale, causal, bq)
    return jnp.swapaxes(o, 1, 2)
