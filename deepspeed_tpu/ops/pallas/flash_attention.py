"""Flash attention Pallas kernel (fwd + bwd).

TPU-native replacement for the reference's fused CUDA attention
(csrc/transformer/softmax_kernels.cu + strided batched gemms orchestrated in
ds_transformer_cuda.cpp; inference variant softmax_context in
csrc/transformer/inference/). Design:

- layout: kernels run in BHSD ([batch, heads, seq, head_dim]) so block
  shapes keep the (sublane, lane)-aligned last two dims the Mosaic lowering
  requires; the public API takes BSHD and transposes at dispatch.
- forward: grid (batch, heads, q_blocks); one q block [Bq, d] against the
  full K/V [S, d] resident in VMEM (S·d·2B ≤ ~0.5 MB for S≤4096, d≤128 —
  comfortably inside the ~16 MB VMEM budget), fp32 softmax.
- backward: grid (batch, heads); fori_loop over q blocks *recomputing* the
  softmax (flash-style recompute — no S×S matrix and no saved LSE),
  accumulating dK/dV in registers/VMEM.
- autodiff via jax.custom_vjp (the reference wires fwd/bwd kernels through
  torch.autograd.Function the same way).
"""

import functools
import math

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

DEFAULT_BLOCK_Q = 512
NEG_INF = -1e30


from ._common import interpret_mode as _interpret


def _softmax_tile(q, k, scale, causal, q_offset):
    """[Bq,d]x[S,d] -> probability tile [Bq,S] (fp32) and the row stats.

    q/k stay in their native dtype (bf16 in the hot path) so the MXU runs
    at its bf16 rate; accumulation is fp32 via preferred_element_type —
    the same bf16-in/fp32-acc contract as the XLA einsum path.

    ``q_offset`` already includes the bottom-right causal alignment shift
    (sk - sq), matching the reference backend's ``tril(..., k_len - q_len)``
    so both backends agree when sk != sq (decode with KV cache)."""
    s = jnp.dot(q, k.T, preferred_element_type=jnp.float32) * scale
    if causal:
        row = jax.lax.broadcasted_iota(jnp.int32, s.shape, 0) + q_offset
        col = jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
        s = jnp.where(col <= row, s, NEG_INF)
    m = jnp.max(s, axis=-1, keepdims=True)
    p = jnp.exp(s - m)
    l = jnp.sum(p, axis=-1, keepdims=True)
    return p, l


def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, *, scale, causal, block_q,
                block_k, causal_shift):
    """Online-softmax flash forward: fori_loop over K blocks so the score
    tile is [Bq, Bk] (VMEM-bounded for any S) and, in causal mode, blocks
    strictly above the diagonal are never computed (dynamic trip count —
    q rows near the top do ~1 block, the bottom does S/Bk)."""
    q = q_ref[0, 0]                                    # [Bq, d] native dtype
    d = q.shape[-1]
    sk = k_ref.shape[2]
    nkb = sk // block_k
    q_off = pl.program_id(2) * block_q + causal_shift

    def body(j, carry):
        acc, m_acc, l_acc = carry
        ks = pl.ds(j * block_k, block_k)
        k = k_ref[0, 0, ks, :]
        v = v_ref[0, 0, ks, :]
        s = jnp.dot(q, k.T, preferred_element_type=jnp.float32) * scale
        if causal:
            row = jax.lax.broadcasted_iota(jnp.int32, s.shape, 0) + q_off
            col = jax.lax.broadcasted_iota(jnp.int32, s.shape, 1) \
                + j * block_k
            s = jnp.where(col <= row, s, NEG_INF)
        m_new = jnp.maximum(m_acc, jnp.max(s, axis=-1, keepdims=True))
        # rows with no visible key yet (m still -inf, e.g. shifted-causal
        # top rows) must contribute p=0, not exp(-inf - -inf) = 1
        p = jnp.where(m_new > NEG_INF / 2, jnp.exp(s - m_new), 0.0)
        alpha = jnp.exp(m_acc - m_new)
        l_new = l_acc * alpha + jnp.sum(p, axis=-1, keepdims=True)
        # PV matmul in the value dtype (bf16 MXU rate); probs are in [0,1]
        # so the downcast loses at most 2^-9 relative — inside bf16 noise
        acc = acc * alpha + jnp.dot(p.astype(v.dtype), v,
                                    preferred_element_type=jnp.float32)
        return acc, m_new, l_new

    if causal:
        # last k block the bottom row of this q tile can see
        trips = jnp.clip((q_off + block_q - 1) // block_k + 1, 1, nkb)
    else:
        trips = nkb
    acc, m, l = jax.lax.fori_loop(
        0, trips, body,
        (jnp.zeros((block_q, d), jnp.float32),
         jnp.full((block_q, 1), NEG_INF, jnp.float32),
         jnp.zeros((block_q, 1), jnp.float32)))
    l = jnp.where(l > 0.0, l, 1.0)   # fully-masked rows (shifted causal)
    o_ref[0, 0] = (acc / l).astype(o_ref.dtype)


def _pick_block_k(sk, want=512):
    """Largest divisor of sk <= want keeping 128 alignment; whole-S rows
    for ragged lengths."""
    bk = math.gcd(sk, min(want, sk))
    return bk if bk % 128 == 0 or bk == sk else sk


def _flash_fwd(q, k, v, scale, causal, block_q):
    b, h, sq, d = q.shape
    sk = k.shape[2]
    block_q = min(block_q, sq)
    block_k = _pick_block_k(sk)
    grid = (b, h, pl.cdiv(sq, block_q))
    return pl.pallas_call(
        functools.partial(_fwd_kernel, scale=scale, causal=causal,
                          block_q=block_q, block_k=block_k,
                          causal_shift=sk - sq),
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, 1, block_q, d), lambda bi, hi, qi: (bi, hi, qi, 0)),
            pl.BlockSpec((1, 1, sk, d), lambda bi, hi, qi: (bi, hi, 0, 0)),
            pl.BlockSpec((1, 1, sk, d), lambda bi, hi, qi: (bi, hi, 0, 0)),
        ],
        out_specs=pl.BlockSpec((1, 1, block_q, d),
                               lambda bi, hi, qi: (bi, hi, qi, 0)),
        out_shape=jax.ShapeDtypeStruct(q.shape, q.dtype),
        interpret=_interpret(),
    )(q, k, v)


def _bwd_kernel(q_ref, k_ref, v_ref, o_ref, do_ref,
                dq_ref, dk_ref, dv_ref, *, scale, causal, block_q, seq_q,
                causal_shift):
    k = k_ref[0, 0]                                    # [S, d] native dtype
    v = v_ref[0, 0]

    def body(i, carry):
        dk_acc, dv_acc = carry
        qs = pl.ds(i * block_q, block_q)
        q = q_ref[0, 0, qs, :]                         # [Bq, d]
        o = o_ref[0, 0, qs, :].astype(jnp.float32)
        do = do_ref[0, 0, qs, :]

        p_un, l = _softmax_tile(q, k, scale, causal,
                                i * block_q + causal_shift)
        p = p_un / l                                   # [Bq, S] fp32

        delta = jnp.sum(do.astype(jnp.float32) * o, axis=-1, keepdims=True)
        dp = jnp.dot(do, v.T, preferred_element_type=jnp.float32)
        ds = p * (dp - delta) * scale
        # operand downcast for the three grad matmuls (fp32 accumulate):
        # the bf16-in/fp32-acc contract standard flash backwards use
        dsl = ds.astype(q.dtype)
        pl_ = p.astype(do.dtype)

        dq_ref[0, 0, qs, :] = jnp.dot(
            dsl, k, preferred_element_type=jnp.float32).astype(dq_ref.dtype)
        dk_acc = dk_acc + jnp.dot(dsl.T, q, preferred_element_type=jnp.float32)
        dv_acc = dv_acc + jnp.dot(pl_.T, do, preferred_element_type=jnp.float32)
        return dk_acc, dv_acc

    dk_acc, dv_acc = jax.lax.fori_loop(
        0, seq_q // block_q, body,
        (jnp.zeros(k.shape, jnp.float32), jnp.zeros(v.shape, jnp.float32)))
    dk_ref[0, 0] = dk_acc.astype(dk_ref.dtype)
    dv_ref[0, 0] = dv_acc.astype(dv_ref.dtype)


def _flash_bwd(scale, causal, block_q, res, g):
    q, k, v, o = res
    b, h, sq, d = q.shape
    sk = k.shape[2]
    # Smaller q block than fwd: bwd holds three [Bq, S] fp32 tiles
    # (p, dp, ds) plus fp32 dK/dV accumulators in VMEM. Bound the tiles to
    # ~6 MB: Bq*S*4B*3 <= 6MB  =>  Bq <= 2^19/S, floored to a 128 multiple.
    cap = max(128, (2 ** 19 // max(sk, 1)) // 128 * 128)
    # Largest block <= cap that divides sq: gcd keeps the 128-alignment
    # whenever sq is itself a multiple of 128 (the pallas-path requirement),
    # avoiding a degenerate halving spiral for seqs like 1280.
    block_q = math.gcd(sq, min(block_q, sq, cap))
    if block_q % 8 != 0:  # non-128-multiple seq: fall back to full rows
        block_q = sq
    full_q = pl.BlockSpec((1, 1, sq, d), lambda bi, hi: (bi, hi, 0, 0))
    full_k = pl.BlockSpec((1, 1, sk, d), lambda bi, hi: (bi, hi, 0, 0))
    dq, dk, dv = pl.pallas_call(
        functools.partial(_bwd_kernel, scale=scale, causal=causal,
                          block_q=block_q, seq_q=sq, causal_shift=sk - sq),
        grid=(b, h),
        in_specs=[full_q, full_k, full_k, full_q, full_q],
        out_specs=(full_q, full_k, full_k),
        out_shape=(jax.ShapeDtypeStruct(q.shape, q.dtype),
                   jax.ShapeDtypeStruct(k.shape, k.dtype),
                   jax.ShapeDtypeStruct(v.shape, v.dtype)),
        interpret=_interpret(),
    )(q, k, v, o, g)
    return dq, dk, dv


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5))
def _flash_attention_bhsd(q, k, v, scale, causal, block_q):
    return _flash_fwd(q, k, v, scale, causal, block_q)


def _fwd_rule(q, k, v, scale, causal, block_q):
    o = _flash_fwd(q, k, v, scale, causal, block_q)
    return o, (q, k, v, o)


_flash_attention_bhsd.defvjp(_fwd_rule, _flash_bwd)


def flash_attention(q, k, v, *, causal=True, softmax_scale=None,
                    block_q=DEFAULT_BLOCK_Q):
    """q,k,v: [batch, seq, heads, head_dim] (BSHD). Returns like q."""
    d = q.shape[-1]
    scale = softmax_scale if softmax_scale is not None else 1.0 / math.sqrt(d)
    sq = q.shape[1]
    bq = min(block_q, sq)
    if sq % bq != 0:
        raise ValueError(f"flash_attention: seq {sq} must be divisible by "
                         f"block_q {bq}")
    qt, kt, vt = (jnp.swapaxes(t, 1, 2) for t in (q, k, v))
    o = _flash_attention_bhsd(qt, kt, vt, scale, causal, bq)
    return jnp.swapaxes(o, 1, 2)
