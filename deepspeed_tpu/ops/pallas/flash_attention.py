"""Flash attention Pallas kernel (fwd + bwd).

TPU-native replacement for the reference's fused CUDA attention
(csrc/transformer/softmax_kernels.cu + strided batched gemms orchestrated in
ds_transformer_cuda.cpp; inference variant softmax_context in
csrc/transformer/inference/). Design:

- layout: kernels run in BHSD ([batch, heads, seq, head_dim]) so block
  shapes keep the (sublane, lane)-aligned last two dims the Mosaic lowering
  requires; the public API takes BSHD and transposes at dispatch.
- TWO kernel structures, selected by whether K/V (lane-padded to 128) fit
  VMEM comfortably (~12MB → seq <= ~8k at head_dim 64):
  * resident: grid (b, h, q_blocks) with K/V whole in VMEM and a
    dynamic-trip fori_loop over [Bq, Bk] score tiles — fastest at
    training lengths (measured 82 TFLOPS fwd+bwd @ s1024 on v5e vs 62
    for the streamed form);
  * streamed: grid (b, h, q_blocks, k_blocks) with K/V blocks flowing
    through the grid and the online-softmax state in VMEM scratch —
    compiles and runs at any length (16k/32k+).
- causal mode never computes blocks above the diagonal (dynamic trip
  counts in resident form, compute-predication in streamed form).
- forward emits the log-sum-exp rows; backward is two passes sharing that
  LSE (no softmax recompute pass): q-major for dQ, k-major for dK/dV.
- all matmuls run in the operand dtype (bf16 hot path) with fp32
  accumulation via preferred_element_type — the same bf16-in/fp32-acc
  contract as the XLA einsum path.
- autodiff via jax.custom_vjp (the reference wires fwd/bwd kernels through
  torch.autograd.Function the same way).
"""

import functools
import math

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

DEFAULT_BLOCK_Q = 512
RESIDENT_BLOCK_K = 512   # swept on v5e: resident fori prefers 512,
STREAMED_BLOCK_K = 1024  # the streamed grid prefers 1024
NEG_INF = -1e30

from ._common import interpret_mode as _interpret


def _causal_mask(s, q_off, k_off):
    row = jax.lax.broadcasted_iota(jnp.int32, s.shape, 0) + q_off
    col = jax.lax.broadcasted_iota(jnp.int32, s.shape, 1) + k_off
    return jnp.where(col <= row, s, NEG_INF)


from ._common import pick_block as _block

# training-length gate for the single-pass resident backward (its [Bq, S]
# fp32 tiles + fp32 dK/dV accumulators outgrow VMEM beyond this); module
# constant so tests can lower it to exercise the long-seq structures
MONOLITHIC_BWD_MAX_SEQ = 4096


def _kv_fits_vmem(s, d, itemsize=2):
    """Lane-padded, double-buffered K+V bytes within a ~12MB budget."""
    return s * max(d, 128) * itemsize * 2 * 2 <= 12 * 2 ** 20


def _probs(q, k, lse, scale, causal, q_off, k_off):
    """Probability tile from the saved LSE (one matmul, no running
    softmax): p = exp(s - lse); causal-masked and fully-masked
    (lse = -inf) entries come out exactly 0."""
    s = jnp.dot(q, k.T, preferred_element_type=jnp.float32) * scale
    if causal:
        s = _causal_mask(s, q_off, k_off)
    return jnp.where(lse > NEG_INF / 2, jnp.exp(s - lse), 0.0)


def _online_step(q, k, v, scale, causal, q_off, k_off, acc, m_acc, l_acc):
    """One [Bq, Bk] online-softmax update (shared by both structures)."""
    s = jnp.dot(q, k.T, preferred_element_type=jnp.float32) * scale
    if causal:
        s = _causal_mask(s, q_off, k_off)
    m_new = jnp.maximum(m_acc, jnp.max(s, axis=-1, keepdims=True))
    # rows with no visible key yet (m still -inf, e.g. shifted-causal top
    # rows) must contribute p=0, not exp(-inf - -inf) = 1
    p = jnp.where(m_new > NEG_INF / 2, jnp.exp(s - m_new), 0.0)
    alpha = jnp.exp(m_acc - m_new)
    # PV matmul in the value dtype (bf16 MXU rate); probs are in [0,1] so
    # the downcast loses at most 2^-9 relative — inside bf16 output noise
    acc = acc * alpha + jnp.dot(p.astype(v.dtype), v,
                                preferred_element_type=jnp.float32)
    return acc, m_new, l_acc * alpha + jnp.sum(p, axis=-1, keepdims=True)


def _emit_o_lse(acc, m, l, o_ref, lse_ref):
    safe_l = jnp.where(l > 0.0, l, 1.0)   # fully-masked rows -> zeros
    o_ref[0, 0] = (acc / safe_l).astype(o_ref.dtype)
    # LSE residual for backward; -inf rows stay -inf so bwd re-zeroes them
    lse_ref[0, 0] = jnp.where(l > 0.0, m + jnp.log(safe_l), NEG_INF)


# ---------------------------------------------------------------------------
# resident structure: K/V whole in VMEM, fori over k tiles
# ---------------------------------------------------------------------------

def _fwd_kernel_resident(q_ref, k_ref, v_ref, o_ref, lse_ref, *, scale,
                         causal, block_q, block_k, causal_shift):
    q = q_ref[0, 0]                                    # [Bq, d] native dtype
    d = q.shape[-1]
    nkb = k_ref.shape[2] // block_k
    q_off = pl.program_id(2) * block_q + causal_shift

    def body(j, carry):
        ks = pl.ds(j * block_k, block_k)
        return _online_step(q, k_ref[0, 0, ks, :], v_ref[0, 0, ks, :],
                            scale, causal, q_off, j * block_k, *carry)

    trips = (jnp.clip((q_off + block_q - 1) // block_k + 1, 1, nkb)
             if causal else nkb)
    acc, m, l = jax.lax.fori_loop(
        0, trips, body,
        (jnp.zeros((block_q, d), jnp.float32),
         jnp.full((block_q, 1), NEG_INF, jnp.float32),
         jnp.zeros((block_q, 1), jnp.float32)))
    _emit_o_lse(acc, m, l, o_ref, lse_ref)


def _dq_kernel_resident(q_ref, k_ref, v_ref, do_ref, delta_ref, lse_ref,
                        dq_ref, *, scale, causal, block_q, block_k,
                        causal_shift):
    qi = pl.program_id(2)
    q = q_ref[0, 0]
    do = do_ref[0, 0]
    delta = delta_ref[0, 0]
    lse = lse_ref[0, 0]
    d = q.shape[-1]
    nkb = k_ref.shape[2] // block_k
    q_off = qi * block_q + causal_shift

    def body(j, acc):
        ks = pl.ds(j * block_k, block_k)
        k = k_ref[0, 0, ks, :]
        v = v_ref[0, 0, ks, :]
        p = _probs(q, k, lse, scale, causal, q_off, j * block_k)
        dp = jnp.dot(do, v.T, preferred_element_type=jnp.float32)
        ds = (p * (dp - delta) * scale).astype(q.dtype)
        return acc + jnp.dot(ds, k, preferred_element_type=jnp.float32)

    trips = (jnp.clip((q_off + block_q - 1) // block_k + 1, 1, nkb)
             if causal else nkb)
    acc = jax.lax.fori_loop(0, trips, body,
                            jnp.zeros((block_q, d), jnp.float32))
    dq_ref[0, 0] = acc.astype(dq_ref.dtype)


def _dkv_kernel_resident(q_ref, k_ref, v_ref, do_ref, delta_ref, lse_ref,
                         dk_ref, dv_ref, *, scale, causal, block_q, block_k,
                         seq_q, causal_shift):
    ki = pl.program_id(2)
    k = k_ref[0, 0]                                    # [Bk, d] this block
    v = v_ref[0, 0]
    d = k.shape[-1]
    nqb = seq_q // block_q
    k_off = ki * block_k

    if causal:
        # first q block whose bottom row reaches this k block
        q_lo = jnp.clip((k_off - causal_shift) // block_q, 0, nqb - 1)
        trips = nqb - q_lo
    else:
        q_lo = 0
        trips = nqb

    def body(i, carry):
        dk_acc, dv_acc = carry
        j = q_lo + i
        qs = pl.ds(j * block_q, block_q)
        q = q_ref[0, 0, qs, :]
        do = do_ref[0, 0, qs, :]
        delta = delta_ref[0, 0, qs, :]
        lse = lse_ref[0, 0, qs, :]
        p = _probs(q, k, lse, scale, causal,
                   j * block_q + causal_shift, k_off)
        dp = jnp.dot(do, v.T, preferred_element_type=jnp.float32)
        ds = (p * (dp - delta) * scale).astype(q.dtype)
        dk_acc = dk_acc + jnp.dot(ds.T, q, preferred_element_type=jnp.float32)
        dv_acc = dv_acc + jnp.dot(p.astype(do.dtype).T, do,
                                  preferred_element_type=jnp.float32)
        return dk_acc, dv_acc

    dk_acc, dv_acc = jax.lax.fori_loop(
        0, trips, body,
        (jnp.zeros((k.shape[0], d), jnp.float32),
         jnp.zeros((k.shape[0], d), jnp.float32)))
    dk_ref[0, 0] = dk_acc.astype(dk_ref.dtype)
    dv_ref[0, 0] = dv_acc.astype(dv_ref.dtype)


def _bwd_kernel_monolithic(q_ref, k_ref, v_ref, o_ref, do_ref,
                           dq_ref, dk_ref, dv_ref, *, scale, causal, block_q,
                           seq_q, causal_shift):
    """Single-pass resident backward: grid (b, h); K/V (and dK/dV fp32
    accumulators) whole in VMEM, one fori over q blocks recomputing the
    [Bq, S] softmax from (q, k, o). Measured fastest at training lengths
    (one kernel launch, K/V and q/do each loaded once)."""
    k = k_ref[0, 0]                                    # [S, d] native dtype
    v = v_ref[0, 0]

    def body(i, carry):
        dk_acc, dv_acc = carry
        qs = pl.ds(i * block_q, block_q)
        q = q_ref[0, 0, qs, :]                         # [Bq, d]
        o = o_ref[0, 0, qs, :].astype(jnp.float32)
        do = do_ref[0, 0, qs, :]

        s = jnp.dot(q, k.T, preferred_element_type=jnp.float32) * scale
        if causal:
            s = _causal_mask(s, i * block_q + causal_shift, 0)
        m = jnp.max(s, axis=-1, keepdims=True)
        p_un = jnp.exp(s - m)
        l = jnp.sum(p_un, axis=-1, keepdims=True)
        p = p_un / l                                   # [Bq, S] fp32

        delta = jnp.sum(do.astype(jnp.float32) * o, axis=-1, keepdims=True)
        dp = jnp.dot(do, v.T, preferred_element_type=jnp.float32)
        ds = (p * (dp - delta) * scale).astype(q.dtype)
        pl_ = p.astype(do.dtype)

        dq_ref[0, 0, qs, :] = jnp.dot(
            ds, k, preferred_element_type=jnp.float32).astype(dq_ref.dtype)
        dk_acc = dk_acc + jnp.dot(ds.T, q, preferred_element_type=jnp.float32)
        dv_acc = dv_acc + jnp.dot(pl_.T, do, preferred_element_type=jnp.float32)
        return dk_acc, dv_acc

    dk_acc, dv_acc = jax.lax.fori_loop(
        0, seq_q // block_q, body,
        (jnp.zeros(k.shape, jnp.float32), jnp.zeros(v.shape, jnp.float32)))
    dk_ref[0, 0] = dk_acc.astype(dk_ref.dtype)
    dv_ref[0, 0] = dv_acc.astype(dv_ref.dtype)


# ---------------------------------------------------------------------------
# streamed structure: K/V blocks flow through the grid, scratch accumulators
# ---------------------------------------------------------------------------

def _fwd_kernel_streamed(q_ref, k_ref, v_ref, o_ref, lse_ref, acc_ref,
                         m_ref, l_ref, *, scale, causal, block_q, block_k,
                         causal_shift, nkb):
    qi, ki = pl.program_id(2), pl.program_id(3)
    q_off = qi * block_q + causal_shift

    @pl.when(ki == 0)
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref)
        m_ref[...] = jnp.full_like(m_ref, NEG_INF)
        l_ref[...] = jnp.zeros_like(l_ref)

    live = True if not causal else ki * block_k <= q_off + block_q - 1

    @pl.when(live)
    def _compute():
        acc, m, l = _online_step(
            q_ref[0, 0], k_ref[0, 0], v_ref[0, 0], scale, causal, q_off,
            ki * block_k, acc_ref[...], m_ref[...], l_ref[...])
        acc_ref[...], m_ref[...], l_ref[...] = acc, m, l

    @pl.when(ki == nkb - 1)
    def _emit():
        _emit_o_lse(acc_ref[...], m_ref[...], l_ref[...], o_ref, lse_ref)


def _dq_kernel_streamed(q_ref, k_ref, v_ref, do_ref, delta_ref, lse_ref,
                        dq_ref, acc_ref, *, scale, causal, block_q, block_k,
                        causal_shift, nkb):
    qi, ki = pl.program_id(2), pl.program_id(3)
    q_off = qi * block_q + causal_shift

    @pl.when(ki == 0)
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref)

    live = True if not causal else ki * block_k <= q_off + block_q - 1

    @pl.when(live)
    def _compute():
        q = q_ref[0, 0]
        do = do_ref[0, 0]
        p = _probs(q, k_ref[0, 0], lse_ref[0, 0], scale, causal, q_off,
                   ki * block_k)
        dp = jnp.dot(do, v_ref[0, 0].T, preferred_element_type=jnp.float32)
        ds = (p * (dp - delta_ref[0, 0]) * scale).astype(q.dtype)
        acc_ref[...] += jnp.dot(ds, k_ref[0, 0],
                                preferred_element_type=jnp.float32)

    @pl.when(ki == nkb - 1)
    def _emit():
        dq_ref[0, 0] = acc_ref[...].astype(dq_ref.dtype)


def _dkv_kernel_streamed(q_ref, k_ref, v_ref, do_ref, delta_ref, lse_ref,
                         dk_ref, dv_ref, dk_acc, dv_acc, *, scale, causal,
                         block_q, block_k, causal_shift, nqb):
    ki, qi = pl.program_id(2), pl.program_id(3)
    q_off = qi * block_q + causal_shift
    k_off = ki * block_k

    @pl.when(qi == 0)
    def _init():
        dk_acc[...] = jnp.zeros_like(dk_acc)
        dv_acc[...] = jnp.zeros_like(dv_acc)

    live = True if not causal else q_off + block_q - 1 >= k_off

    @pl.when(live)
    def _compute():
        q = q_ref[0, 0]
        do = do_ref[0, 0]
        p = _probs(q, k_ref[0, 0], lse_ref[0, 0], scale, causal, q_off,
                   k_off)
        dp = jnp.dot(do, v_ref[0, 0].T, preferred_element_type=jnp.float32)
        ds = (p * (dp - delta_ref[0, 0]) * scale).astype(q.dtype)
        dk_acc[...] += jnp.dot(ds.T, q, preferred_element_type=jnp.float32)
        dv_acc[...] += jnp.dot(p.astype(do.dtype).T, do,
                               preferred_element_type=jnp.float32)

    @pl.when(qi == nqb - 1)
    def _emit():
        dk_ref[0, 0] = dk_acc[...].astype(dk_ref.dtype)
        dv_ref[0, 0] = dv_acc[...].astype(dv_ref.dtype)


# ---------------------------------------------------------------------------
# dispatch
# ---------------------------------------------------------------------------

def _flash_fwd(q, k, v, scale, causal, block_q):
    b, h, sq, d = q.shape
    sk = k.shape[2]
    block_q = _block(sq, min(block_q, sq))
    out_shape = (jax.ShapeDtypeStruct(q.shape, q.dtype),
                 jax.ShapeDtypeStruct((b, h, sq, 1), jnp.float32))
    q_blk3 = pl.BlockSpec((1, 1, block_q, d),
                          lambda bi, hi, qi: (bi, hi, qi, 0))
    lse_blk3 = pl.BlockSpec((1, 1, block_q, 1),
                            lambda bi, hi, qi: (bi, hi, qi, 0))
    if _kv_fits_vmem(sk, d, q.dtype.itemsize):
        kv_full = pl.BlockSpec((1, 1, sk, d),
                               lambda bi, hi, qi: (bi, hi, 0, 0))
        o, lse = pl.pallas_call(
            functools.partial(_fwd_kernel_resident, scale=scale,
                              causal=causal, block_q=block_q,
                              block_k=_block(sk, RESIDENT_BLOCK_K),
                              causal_shift=sk - sq),
            grid=(b, h, sq // block_q),
            in_specs=[q_blk3, kv_full, kv_full],
            out_specs=(q_blk3, lse_blk3),
            out_shape=out_shape,
            interpret=_interpret(),
        )(q, k, v)
        return o, lse
    block_k = _block(sk, STREAMED_BLOCK_K)
    nkb = sk // block_k
    o, lse = pl.pallas_call(
        functools.partial(_fwd_kernel_streamed, scale=scale, causal=causal,
                          block_q=block_q, block_k=block_k,
                          causal_shift=sk - sq, nkb=nkb),
        grid=(b, h, sq // block_q, nkb),
        in_specs=[
            pl.BlockSpec((1, 1, block_q, d),
                         lambda bi, hi, qi, ki: (bi, hi, qi, 0)),
            pl.BlockSpec((1, 1, block_k, d),
                         lambda bi, hi, qi, ki: (bi, hi, ki, 0)),
            pl.BlockSpec((1, 1, block_k, d),
                         lambda bi, hi, qi, ki: (bi, hi, ki, 0)),
        ],
        out_specs=(
            pl.BlockSpec((1, 1, block_q, d),
                         lambda bi, hi, qi, ki: (bi, hi, qi, 0)),
            pl.BlockSpec((1, 1, block_q, 1),
                         lambda bi, hi, qi, ki: (bi, hi, qi, 0)),
        ),
        out_shape=out_shape,
        scratch_shapes=[pltpu.VMEM((block_q, d), jnp.float32),
                        pltpu.VMEM((block_q, 1), jnp.float32),
                        pltpu.VMEM((block_q, 1), jnp.float32)],
        interpret=_interpret(),
    )(q, k, v)
    return o, lse


def _flash_bwd(scale, causal, block_q, res, g):
    q, k, v, o, lse = res
    b, h, sq, d = q.shape
    sk = k.shape[2]

    # Training lengths: the single-pass resident backward wins (one
    # launch; K/V, q, do each read once; measured best 125M e2e on v5e).
    # Its VMEM budget: K/V + fp32 dK/dV accumulators + 3 [Bq, S] fp32
    # tiles — comfortable through 4k.
    if sk <= MONOLITHIC_BWD_MAX_SEQ and sq <= MONOLITHIC_BWD_MAX_SEQ:
        cap = max(128, (2 ** 19 // max(sk, 1)) // 128 * 128)
        bq = math.gcd(sq, min(block_q, sq, cap))
        if bq % 8 != 0:
            bq = sq
        full_q = pl.BlockSpec((1, 1, sq, d), lambda bi, hi: (bi, hi, 0, 0))
        full_k = pl.BlockSpec((1, 1, sk, d), lambda bi, hi: (bi, hi, 0, 0))
        return pl.pallas_call(
            functools.partial(_bwd_kernel_monolithic, scale=scale,
                              causal=causal, block_q=bq, seq_q=sq,
                              causal_shift=sk - sq),
            grid=(b, h),
            in_specs=[full_q, full_k, full_k, full_q, full_q],
            out_specs=(full_q, full_k, full_k),
            out_shape=(jax.ShapeDtypeStruct(q.shape, q.dtype),
                       jax.ShapeDtypeStruct(k.shape, k.dtype),
                       jax.ShapeDtypeStruct(v.shape, v.dtype)),
            interpret=_interpret(),
        )(q, k, v, o, g)

    block_q = _block(sq, min(block_q, sq))
    resident = (_kv_fits_vmem(sk, d, q.dtype.itemsize)
                and _kv_fits_vmem(sq, d, q.dtype.itemsize))
    block_k = _block(sk, RESIDENT_BLOCK_K if resident else STREAMED_BLOCK_K)
    nqb, nkb = sq // block_q, sk // block_k
    # delta = rowsum(do * o): cheap elementwise outside the kernels
    delta = jnp.sum(g.astype(jnp.float32) * o.astype(jnp.float32),
                    axis=-1, keepdims=True)

    if resident:
        q_blk = pl.BlockSpec((1, 1, block_q, d),
                             lambda bi, hi, qi: (bi, hi, qi, 0))
        q_stat = pl.BlockSpec((1, 1, block_q, 1),
                              lambda bi, hi, qi: (bi, hi, qi, 0))
        kv_full = pl.BlockSpec((1, 1, sk, d),
                               lambda bi, hi, qi: (bi, hi, 0, 0))
        dq = pl.pallas_call(
            functools.partial(_dq_kernel_resident, scale=scale,
                              causal=causal, block_q=block_q,
                              block_k=block_k,
                              causal_shift=sk - sq),
            grid=(b, h, nqb),
            in_specs=[q_blk, kv_full, kv_full, q_blk, q_stat, q_stat],
            out_specs=q_blk,
            out_shape=jax.ShapeDtypeStruct(q.shape, q.dtype),
            interpret=_interpret(),
        )(q, k, v, g, delta, lse)

        k_blk = pl.BlockSpec((1, 1, block_k, d),
                             lambda bi, hi, ki: (bi, hi, ki, 0))
        q_full = pl.BlockSpec((1, 1, sq, d),
                              lambda bi, hi, ki: (bi, hi, 0, 0))
        stat_full = pl.BlockSpec((1, 1, sq, 1),
                                 lambda bi, hi, ki: (bi, hi, 0, 0))
        dk, dv = pl.pallas_call(
            functools.partial(_dkv_kernel_resident, scale=scale,
                              causal=causal, block_q=block_q,
                              block_k=block_k, seq_q=sq,
                              causal_shift=sk - sq),
            grid=(b, h, nkb),
            in_specs=[q_full, k_blk, k_blk, q_full, stat_full, stat_full],
            out_specs=(k_blk, k_blk),
            out_shape=(jax.ShapeDtypeStruct(k.shape, k.dtype),
                       jax.ShapeDtypeStruct(v.shape, v.dtype)),
            interpret=_interpret(),
        )(q, k, v, g, delta, lse)
        return dq, dk, dv

    q_blk = lambda bi, hi, qi, ki: (bi, hi, qi, 0)
    k_blk = lambda bi, hi, qi, ki: (bi, hi, ki, 0)
    dq = pl.pallas_call(
        functools.partial(_dq_kernel_streamed, scale=scale, causal=causal,
                          block_q=block_q, block_k=block_k,
                          causal_shift=sk - sq, nkb=nkb),
        grid=(b, h, nqb, nkb),
        in_specs=[pl.BlockSpec((1, 1, block_q, d), q_blk),
                  pl.BlockSpec((1, 1, block_k, d), k_blk),
                  pl.BlockSpec((1, 1, block_k, d), k_blk),
                  pl.BlockSpec((1, 1, block_q, d), q_blk),
                  pl.BlockSpec((1, 1, block_q, 1), q_blk),
                  pl.BlockSpec((1, 1, block_q, 1), q_blk)],
        out_specs=pl.BlockSpec((1, 1, block_q, d), q_blk),
        out_shape=jax.ShapeDtypeStruct(q.shape, q.dtype),
        scratch_shapes=[pltpu.VMEM((block_q, d), jnp.float32)],
        interpret=_interpret(),
    )(q, k, v, g, delta, lse)

    kq_k = lambda bi, hi, ki, qi: (bi, hi, ki, 0)
    kq_q = lambda bi, hi, ki, qi: (bi, hi, qi, 0)
    dk, dv = pl.pallas_call(
        functools.partial(_dkv_kernel_streamed, scale=scale, causal=causal,
                          block_q=block_q, block_k=block_k,
                          causal_shift=sk - sq, nqb=nqb),
        grid=(b, h, nkb, nqb),
        in_specs=[pl.BlockSpec((1, 1, block_q, d), kq_q),
                  pl.BlockSpec((1, 1, block_k, d), kq_k),
                  pl.BlockSpec((1, 1, block_k, d), kq_k),
                  pl.BlockSpec((1, 1, block_q, d), kq_q),
                  pl.BlockSpec((1, 1, block_q, 1), kq_q),
                  pl.BlockSpec((1, 1, block_q, 1), kq_q)],
        out_specs=(pl.BlockSpec((1, 1, block_k, d), kq_k),
                   pl.BlockSpec((1, 1, block_k, d), kq_k)),
        out_shape=(jax.ShapeDtypeStruct(k.shape, k.dtype),
                   jax.ShapeDtypeStruct(v.shape, v.dtype)),
        scratch_shapes=[pltpu.VMEM((block_k, d), jnp.float32),
                        pltpu.VMEM((block_k, d), jnp.float32)],
        interpret=_interpret(),
    )(q, k, v, g, delta, lse)
    return dq, dk, dv


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5))
def _flash_attention_bhsd(q, k, v, scale, causal, block_q):
    o, _ = _flash_fwd(q, k, v, scale, causal, block_q)
    return o


def _fwd_rule(q, k, v, scale, causal, block_q):
    o, lse = _flash_fwd(q, k, v, scale, causal, block_q)
    return o, (q, k, v, o, lse)


_flash_attention_bhsd.defvjp(_fwd_rule, _flash_bwd)


def flash_attention(q, k, v, *, causal=True, softmax_scale=None,
                    block_q=DEFAULT_BLOCK_Q):
    """q,k,v: [batch, seq, heads, head_dim] (BSHD). Returns like q."""
    d = q.shape[-1]
    scale = softmax_scale if softmax_scale is not None else 1.0 / math.sqrt(d)
    sq = q.shape[1]
    bq = min(block_q, sq)
    if sq % bq != 0:
        raise ValueError(f"flash_attention: seq {sq} must be divisible by "
                         f"block_q {bq}")
    qt, kt, vt = (jnp.swapaxes(t, 1, 2) for t in (q, k, v))
    o = _flash_attention_bhsd(qt, kt, vt, scale, causal, bq)
    return jnp.swapaxes(o, 1, 2)
