"""Fused LayerNorm Pallas kernel (fwd + custom-vjp bwd).

Reference: csrc/transformer/normalize_kernels.cu (fused layer_norm fwd/bwd
with saved mean/rstd). XLA fuses LN chains well on its own; this kernel
exists for the very-wide-row regime (d_model ≥ 4096) where a single-pass
Welford + on-chip residency beats XLA's default fusion, and for parity with
the reference op surface.
"""

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

BLOCK_ROWS = 256


from ._common import interpret_mode as _interpret


def _ln_fwd_kernel(x_ref, g_ref, b_ref, y_ref, mean_ref, rstd_ref, *, eps):
    x = x_ref[:].astype(jnp.float32)                     # [R, D]
    mean = jnp.mean(x, axis=-1, keepdims=True)
    var = jnp.mean(jnp.square(x - mean), axis=-1, keepdims=True)
    rstd = jax.lax.rsqrt(var + eps)
    y = (x - mean) * rstd * g_ref[:].astype(jnp.float32) + b_ref[:].astype(jnp.float32)
    y_ref[:] = y.astype(y_ref.dtype)
    mean_ref[:] = mean[:, 0]
    rstd_ref[:] = rstd[:, 0]


def _ln_fwd(x2d, gamma, beta, eps):
    n, d = x2d.shape
    rows = min(BLOCK_ROWS, n)
    grid = (pl.cdiv(n, rows),)
    y, mean, rstd = pl.pallas_call(
        functools.partial(_ln_fwd_kernel, eps=eps),
        grid=grid,
        in_specs=[pl.BlockSpec((rows, d), lambda i: (i, 0)),
                  pl.BlockSpec((d,), lambda i: (0,)),
                  pl.BlockSpec((d,), lambda i: (0,))],
        out_specs=(pl.BlockSpec((rows, d), lambda i: (i, 0)),
                   pl.BlockSpec((rows,), lambda i: (i,)),
                   pl.BlockSpec((rows,), lambda i: (i,))),
        out_shape=(jax.ShapeDtypeStruct(x2d.shape, x2d.dtype),
                   jax.ShapeDtypeStruct((n,), jnp.float32),
                   jax.ShapeDtypeStruct((n,), jnp.float32)),
        interpret=_interpret(),
    )(x2d, gamma, beta)
    return y, mean, rstd


@functools.partial(jax.custom_vjp, nondiff_argnums=(3,))
def fused_layer_norm(x, gamma, beta, eps=1e-5):
    """LayerNorm over the last dim. x: [..., D]."""
    shape = x.shape
    y, _, _ = _ln_fwd(x.reshape(-1, shape[-1]), gamma, beta, eps)
    return y.reshape(shape)


def _fused_ln_fwd(x, gamma, beta, eps):
    shape = x.shape
    x2d = x.reshape(-1, shape[-1])
    y, mean, rstd = _ln_fwd(x2d, gamma, beta, eps)
    return y.reshape(shape), (x2d, gamma, mean, rstd, shape)


def _fused_ln_bwd(eps, res, g):
    x2d, gamma, mean, rstd, shape = res
    d = shape[-1]
    g2d = g.reshape(-1, d).astype(jnp.float32)
    x32 = x2d.astype(jnp.float32)
    xhat = (x32 - mean[:, None]) * rstd[:, None]
    gg = g2d * gamma.astype(jnp.float32)[None, :]
    # standard LN backward (matches the reference's
    # cuApplyLayerNormGradient math)
    mean_gg = jnp.mean(gg, axis=-1, keepdims=True)
    mean_gg_xhat = jnp.mean(gg * xhat, axis=-1, keepdims=True)
    dx = (gg - mean_gg - xhat * mean_gg_xhat) * rstd[:, None]
    dgamma = jnp.sum(g2d * xhat, axis=0)
    dbeta = jnp.sum(g2d, axis=0)
    return (dx.astype(x2d.dtype).reshape(shape),
            dgamma.astype(gamma.dtype), dbeta.astype(gamma.dtype))


fused_layer_norm.defvjp(_fused_ln_fwd, _fused_ln_bwd)
