"""Pallas paged decode-attention kernel (page-table-direct KV attention).

The serving engine's paged KV cache (serving/paging/) stores every
slot's K/V as fixed-size pages in a global pool
``[num_pages, h, d, page_len]`` (K^T layout) addressed by a dense
``[num_slots, max_pages]`` int32 page table. Before this kernel, the
jitted decode step *gathered* each slot's pages into the classic
contiguous ``[slots, h, d, max_pages * page_len]`` view and ran the
contiguous decode kernel over it — correct, but the gathered view is
XLA-managed scratch scaling with ``slots x max_len``
(``decode_gather_transient_bytes``), which silently caps the paged
density win at high slot counts.

This kernel consumes the page table DIRECTLY: grid ``(slot,
head_block)``; each grid step walks the slot's valid pages with
double-buffered ``make_async_copy`` DMAs — the physical page index
comes from the scalar-prefetched page table, so pages stream
HBM->VMEM *in place*, one page (or a tuned multi-page block) at a
time. Flash-style online softmax (the ``_common.online_softmax_block``
inner loop shared with ``decode_attention``) accumulates partial
attention per page block; no contiguous per-slot view ever
materializes (transient ~ 0, and DMA traffic scales with the VALID
length, not the allocated table width).

The current decode step's K/V is NOT in the pool yet (the engine
scatters it after the step, quantized when the pool is int8): it
arrives as separate full-precision ``k_new``/``v_new`` operands and is
folded into the softmax as a final single-column update — bias 0 under
ALiBi (distance 0), always valid, so every row's normalizer is > 0.

int8 KV pages: when ``k_scale``/``v_scale`` page pools are given
(``[num_pages, h, 1, page_len]`` fp32 — one scale per head per token,
stored page-shaped; inference/cache.py quantizes on scatter), the page
DMAs move int8 bytes (HALF the bandwidth of bf16 — decode attention is
cache-bandwidth-bound) plus the small scale planes, and dequantization
happens in VMEM inside the page loop, right before the matmul.

Block sizes resolve through the shape-keyed tuning cache
(``ops/pallas/tuning.py``; ``bin/ds_tpu_bench kernels --kernel
paged_attention`` sweeps them): key
``paged_attention/page<page_len>/sq<slots>_sk<table_tokens>_d<d>_...``,
entries carry ``block_k`` (tokens per DMA block — a page_len multiple;
pages_per_block = block_k / page_len) and ``head_block``.

Caches whose ``page_len`` is not a 128 multiple cannot tile on real
TPU (Mosaic minor-dim alignment) and take a fused-dense jnp fallback
with IDENTICAL semantics; serving defaults page_len to 128 so hardware
always hits the kernel. Inference-only (no custom_vjp).
"""

import functools
import math

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from . import tuning
from ._common import NEG_INF
from ._common import interpret_mode as _interpret
from ._common import online_softmax_block as _attend_block
from ._common import read_slopes as _read_slopes

DEFAULT_BLOCK_TOKENS = 512
DEFAULT_HEAD_BLOCK = 8

KERNEL = "paged_attention"


def _fold_current_token(q, kn, vn, m_ref, l_ref, acc_ref):
    """Final online-softmax update for the current token's K/V — one
    always-valid column at the query's own position (ALiBi bias 0), so
    ``l`` ends >= exp(0) > 0 for every row including empty slots."""
    s = jnp.sum(q * kn, axis=-1, keepdims=True)              # [hb, 1]
    m_prev = m_ref[...]
    m_new = jnp.maximum(m_prev, s)
    corr = jnp.exp(m_prev - m_new)
    p = jnp.exp(s - m_new)                                   # [hb, 1]
    l_ref[...] = corr * l_ref[...] + p
    acc_ref[...] = corr * acc_ref[...] + p * vn
    m_ref[...] = m_new


def _dma_kernel(len_ref, ptab_ref, slopes_ref, q_ref, kn_ref, vn_ref,
                *refs, scale, page_len, ppb, hb, alibi, quant, max_pages):
    if quant:
        (kp_hbm, vp_hbm, ksp_hbm, vsp_hbm, o_ref,
         kbuf0, vbuf0, kbuf1, vbuf1, ksb0, vsb0, ksb1, vsb1,
         sem, m_ref, l_ref, acc_ref) = refs
        bufs = ((kbuf0, vbuf0, ksb0, vsb0), (kbuf1, vbuf1, ksb1, vsb1))
    else:
        (kp_hbm, vp_hbm, o_ref, kbuf0, vbuf0, kbuf1, vbuf1,
         sem, m_ref, l_ref, acc_ref) = refs
        bufs = ((kbuf0, vbuf0), (kbuf1, vbuf1))
    b, hi = pl.program_id(0), pl.program_id(1)
    length = len_ref[b]
    bt = ppb * page_len
    nb = pl.cdiv(length, bt)
    m_ref[...] = jnp.full_like(m_ref, NEG_INF)
    l_ref[...] = jnp.zeros_like(l_ref)
    acc_ref[...] = jnp.zeros_like(acc_ref)
    slopes = _read_slopes(slopes_ref, hi * hb, hb) if alibi else None

    def copies(j, slot):
        """The slot's page DMAs for block ``j``: ``ppb`` physical pages
        looked up in the prefetched table. Logical indices past the
        table (a ragged last block) clamp to the last entry — always a
        VALID physical page (unowned entries hold the null page), whose
        columns the ``col < length`` mask discards."""
        descs = []
        for i in range(ppb):
            logical = jnp.minimum(j * ppb + i, max_pages - 1)
            phys = ptab_ref[b, logical]
            dst = pl.ds(i * page_len, page_len)
            pairs = [(kp_hbm, bufs[slot][0], 0), (vp_hbm, bufs[slot][1], 1)]
            if quant:
                pairs += [(ksp_hbm, bufs[slot][2], 2),
                          (vsp_hbm, bufs[slot][3], 3)]
            for src, buf, ch in pairs:
                descs.append(pltpu.make_async_copy(
                    src.at[phys, hi], buf.at[:, :, dst], sem.at[slot, ch, i]))
        return descs

    # the prologue must not start copies a zero-block row never waits:
    # leaked semaphore signals would satisfy the NEXT grid step's wait()
    # while its own DMA is still in flight (real-TPU hazard; interpret
    # mode doesn't model semaphores)
    @pl.when(nb > 0)
    def _first_copies():
        for c in copies(0, 0):
            c.start()

    def body(j, carry):
        slot = jax.lax.rem(j, 2)

        for parity in (0, 1):
            @pl.when((slot == parity) & (j + 1 < nb))
            def _prefetch():
                for c in copies(j + 1, 1 - parity):
                    c.start()

        for parity in (0, 1):
            @pl.when(slot == parity)
            def _compute():
                for c in copies(j, parity):
                    c.wait()
                q = q_ref[0].astype(jnp.float32) * scale
                if quant:
                    kb, vb, ksb, vsb = bufs[parity]
                    kblk = kb[...].astype(jnp.float32) * ksb[...]
                    vblk = vb[...].astype(jnp.float32) * vsb[...]
                else:
                    kblk, vblk = bufs[parity]
                # pool pages EXCLUDE the current token: valid cols <
                # length, query position = length (folded in below)
                _attend_block(q, kblk, vblk, j * bt, length, length,
                              slopes, m_ref, l_ref, acc_ref, hb=hb,
                              alibi=alibi)
        return carry

    jax.lax.fori_loop(0, nb, body, 0)
    q = q_ref[0].astype(jnp.float32) * scale
    _fold_current_token(q, kn_ref[0].astype(jnp.float32),
                        vn_ref[0].astype(jnp.float32), m_ref, l_ref, acc_ref)
    o_ref[0] = (acc_ref[...] / l_ref[...]).astype(o_ref.dtype)


def _paged_dma(q_bhd, kp, vp, ptab, lengths, kn, vn, ks, vs, *, scale,
               page_len, ppb, hb, alibi, slopes):
    b, heads, d = q_bhd.shape
    num_pages = kp.shape[0]
    max_pages = ptab.shape[1]
    nhb = heads // hb
    quant = ks is not None
    kpr = kp.reshape(num_pages, nhb, hb, d, page_len)
    vpr = vp.reshape(num_pages, nhb, hb, d, page_len)
    pools = [kpr, vpr]
    if quant:
        pools += [ks.reshape(num_pages, nhb, hb, 1, page_len),
                  vs.reshape(num_pages, nhb, hb, 1, page_len)]
    bt = ppb * page_len
    kv_buf = lambda: pltpu.VMEM((hb, d, bt), kp.dtype)
    scratch = [kv_buf(), kv_buf(), kv_buf(), kv_buf()]
    if quant:
        sc_buf = lambda: pltpu.VMEM((hb, 1, bt), jnp.float32)
        scratch += [sc_buf(), sc_buf(), sc_buf(), sc_buf()]
    scratch += [
        pltpu.SemaphoreType.DMA((2, 4 if quant else 2, ppb)),
        pltpu.VMEM((hb, 1), jnp.float32),
        pltpu.VMEM((hb, 1), jnp.float32),
        pltpu.VMEM((hb, d), jnp.float32),
    ]
    tok_spec = lambda: pl.BlockSpec((1, hb, d), lambda bi, hi, *_: (bi, hi, 0))
    return pl.pallas_call(
        functools.partial(_dma_kernel, scale=scale, page_len=page_len,
                          ppb=ppb, hb=hb, alibi=alibi, quant=quant,
                          max_pages=max_pages),
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=3,
            grid=(b, nhb),
            in_specs=[tok_spec(), tok_spec(), tok_spec()]
            + [pl.BlockSpec(memory_space=pl.ANY)] * len(pools),
            out_specs=tok_spec(),
            scratch_shapes=scratch,
        ),
        out_shape=jax.ShapeDtypeStruct((b, heads, d), q_bhd.dtype),
        # jax renamed TPUCompilerParams -> CompilerParams around 0.5;
        # support both so the kernel runs on the pinned CI jax too
        compiler_params=getattr(pltpu, "CompilerParams",
                                getattr(pltpu, "TPUCompilerParams", None))(
            dimension_semantics=("parallel", "parallel")),
        interpret=_interpret(),
    )(lengths, ptab, slopes, q_bhd, kn, vn, *pools)


def _paged_dense(q_bhd, kp, vp, ptab, lengths, kn, vn, ks, vs, *, scale,
                 alibi, slopes):
    """jnp fallback with IDENTICAL semantics for pools the kernel cannot
    tile (page_len not a 128 multiple on real TPU) — and the reference
    the kernel parity suite checks against. Gathers the table's pages
    (XLA scratch — exactly what the kernel path eliminates), attends
    cols < length plus the current token as one extra column."""
    b, heads, d = q_bhd.shape
    page_len = kp.shape[3]
    gk = kp[ptab]                                  # [B, M, H, d, p]
    gv = vp[ptab]
    if ks is not None:
        gk = gk.astype(jnp.float32) * ks[ptab]
        gv = gv.astype(jnp.float32) * vs[ptab]
    m = ptab.shape[1]
    s_tot = m * page_len
    k_all = gk.transpose(0, 2, 3, 1, 4).reshape(b, heads, d, s_tot)
    v_all = gv.transpose(0, 2, 3, 1, 4).reshape(b, heads, d, s_tot)

    qf = q_bhd.astype(jnp.float32) * scale
    logits = jnp.einsum("bhd,bhdk->bhk", qf, k_all.astype(jnp.float32))
    col = jnp.arange(s_tot)[None, None, :]
    ln = lengths[:, None, None]
    if alibi:
        logits = logits + slopes[None, :, None] * (col - ln)
    logits = jnp.where(col < ln, logits, NEG_INF)
    s_cur = jnp.einsum("bhd,bhd->bh", qf,
                       kn.astype(jnp.float32))[..., None]    # [B, H, 1]
    probs = jax.nn.softmax(jnp.concatenate([logits, s_cur], axis=-1),
                           axis=-1)
    # unowned/null-page columns may hold garbage (NaN poison in tests):
    # 0-probability x NaN = NaN, so zero masked V columns explicitly
    v_hist = jnp.where(col[:, :, None, :] < ln[:, :, None, :],
                       v_all.astype(jnp.float32), 0.0)
    out = jnp.einsum("bhk,bhdk->bhd", probs[..., :s_tot], v_hist)
    out = out + probs[..., s_tot:] * vn.astype(jnp.float32)
    return out.astype(q_bhd.dtype)


def paged_attention(q, k_pages, v_pages, page_table, lengths, k_new, v_new,
                    *, softmax_scale=None, alibi_slopes=None, k_scale=None,
                    v_scale=None, block_tokens=None, head_block=None,
                    impl=None):
    """Single-token attention straight over a paged KV pool.

    q: [B, 1, H, d] (or [B, H, d]) — the current token's queries.
    k_pages, v_pages: [num_pages, H, d, page_len] page pool (K^T
        layout); int8 when ``k_scale``/``v_scale`` are given.
    page_table: [B, max_pages] int32 — physical page per logical page;
        unowned entries hold the null page (always safe to read).
    lengths: [B] int32 — tokens already IN the pool per row (the
        current token is NOT among them; it attends via ``k_new``).
    k_new, v_new: [B, H, d, 1] (or [B, H, d]) — the current token's
        K/V in compute precision (quantized on scatter AFTER the step).
    k_scale, v_scale: optional [num_pages, H, 1, page_len] fp32 per-
        token-per-head scale planes of an int8 pool.
    impl: None (auto), "kernel", or "dense" — parity/testing override.

    Returns [B, 1, H, d] (or [B, H, d], matching q's rank): softmax
    attention over the row's ``lengths`` pool tokens plus the current
    token (``lengths + 1`` total; a row with length 0 attends only
    itself — never NaN).
    """
    squeeze = q.ndim == 3
    if squeeze:
        q = q[:, None]
    b, one, heads, d = q.shape
    if one != 1:
        raise ValueError(f"paged_attention is single-token (q_len 1), "
                         f"got {one}")
    if (k_scale is None) != (v_scale is None):
        raise ValueError("k_scale and v_scale must be given together")
    page_len = k_pages.shape[3]
    max_pages = page_table.shape[1]
    scale = softmax_scale if softmax_scale is not None else 1.0 / math.sqrt(d)

    lengths = jnp.broadcast_to(jnp.asarray(lengths, jnp.int32), (b,))
    page_table = jnp.asarray(page_table, jnp.int32)
    kn = k_new.reshape(b, heads, d)
    vn = v_new.reshape(b, heads, d)
    alibi = alibi_slopes is not None
    slopes = (jnp.asarray(alibi_slopes, jnp.float32) if alibi
              else jnp.zeros((heads,), jnp.float32))
    q_bhd = jnp.swapaxes(q, 1, 2)[:, :, 0, :]                # [B, H, d]

    # block resolution through the shape-keyed tuning cache: block_k is
    # tokens per DMA block (a page_len multiple), head_block the grid's
    # head tile — constants only on a full miss
    structure = f"page{page_len}"
    entry, key, source = tuning.lookup(
        KERNEL, structure, sq=b, sk=max_pages * page_len, d=d,
        dtype=k_pages.dtype, causal=True)
    bt = int(entry.get("block_k") or block_tokens or DEFAULT_BLOCK_TOKENS)
    hb = math.gcd(heads, int(entry.get("head_block") or head_block
                             or DEFAULT_HEAD_BLOCK))
    ppb = max(1, min(bt // page_len, max_pages))

    kernel_ok = page_len % 128 == 0 or _interpret()
    use_kernel = kernel_ok if impl is None else impl == "kernel"
    if impl == "kernel" and not kernel_ok:
        raise ValueError(
            f"paged_attention kernel needs page_len % 128 == 0 on TPU "
            f"(got {page_len}); use page_len=128 or impl='dense'")
    tuning.record_dispatch(
        KERNEL, structure, key, source, block_k=ppb * page_len,
        head_block=hb, impl="kernel" if use_kernel else "dense")
    if use_kernel:
        out = _paged_dma(q_bhd, k_pages, v_pages, page_table, lengths, kn,
                         vn, k_scale, v_scale, scale=scale,
                         page_len=page_len, ppb=ppb, hb=hb, alibi=alibi,
                         slopes=slopes)
    else:
        out = _paged_dense(q_bhd, k_pages, v_pages, page_table, lengths,
                           kn, vn, k_scale, v_scale, scale=scale,
                           alibi=alibi, slopes=slopes)
    out = out[:, None]                                       # [B, 1, H, d]
    return out[:, 0].reshape(b, heads, d) if squeeze else out
