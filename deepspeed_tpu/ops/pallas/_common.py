"""Shared kernel-dispatch helpers."""

import jax

TPU_BACKENDS = ("tpu", "axon")

# The additive masked-out encoding shared by the attention kernels and the
# mask->bias folding in ops.transformer.attention: kernels classify a row
# as fully masked via thresholds on NEG_INF/2, so every producer of masked
# logits must use THIS constant (fp32- and bf16-representable).
NEG_INF = -1e30


def on_tpu() -> bool:
    try:
        return jax.default_backend() in TPU_BACKENDS
    except Exception:
        return False


def interpret_mode() -> bool:
    """Pallas kernels interpret off-TPU so the suite runs on the CPU mesh."""
    return not on_tpu()


def pick_block(dim: int, want: int) -> int:
    """Largest divisor of ``dim`` <= ``want`` keeping 128-lane alignment
    (whole dim for small/ragged sizes) — the shared tiling heuristic for
    the flash / int8-matmul kernels."""
    import math
    b = math.gcd(dim, min(want, dim))
    return b if b % 128 == 0 or b == dim else dim


def read_slopes(slopes_ref, h0: int, hb: int):
    """[hb, 1] ALiBi slope column for one head block from a prefetched
    [H] slope vector (shared by the decode / paged-decode kernels)."""
    import jax.numpy as jnp
    return jnp.stack([slopes_ref[h0 + h] for h in range(hb)]).reshape(hb, 1)


def online_softmax_block(q, kblk, vblk, start, valid_len, q_pos, slopes,
                         m_ref, l_ref, acc_ref, *, hb, alibi):
    """One online-softmax update for an [hb, d, Bk] K^T/V block — THE
    inner loop shared by the decode-attention and paged-attention
    kernels (one definition, or the two online-softmax recurrences
    silently drift).

    q is pre-scaled [hb, d] fp32; ``kblk``/``vblk`` are [hb, d, Bk]
    refs or arrays (any float dtype — int8 pages dequantize BEFORE this
    call). Per-head scores are hb small matmuls (MHA has distinct K per
    head, so there is no single big matmul); the softmax/statistics
    update is vectorized across the head block.

    ``valid_len`` masks columns (``start + i < valid_len`` attend);
    ``q_pos`` is the query's absolute position — the ALiBi center
    (``slope * (col - q_pos)``). The single-token decode kernel attends
    a cache that already holds the current token, so it passes
    ``valid_len=length, q_pos=length-1``; the paged kernel attends
    pool pages EXCLUDING the current token and folds it in separately,
    so it passes ``valid_len=length, q_pos=length``.
    """
    import jax
    import jax.numpy as jnp
    rows = []
    for h in range(hb):
        kh = kblk[h].astype(jnp.float32)                     # [d, Bk]
        rows.append(jnp.dot(q[h:h + 1], kh,
                            preferred_element_type=jnp.float32))  # [1, Bk]
    s = jnp.concatenate(rows, axis=0)                        # [hb, Bk]
    col = jax.lax.broadcasted_iota(jnp.int32, s.shape, 1) + start
    if alibi:
        s = s + slopes * (col - q_pos).astype(jnp.float32)
    valid = col < valid_len
    s = jnp.where(valid, s, NEG_INF)

    m_prev = m_ref[...]                                      # [hb, 1]
    m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1, keepdims=True))
    corr = jnp.exp(m_prev - m_new)
    p = jnp.exp(s - m_new)                                   # [hb, Bk]
    outs = []
    for h in range(hb):
        # columns past the valid prefix may hold padding garbage —
        # 0-probability x NaN = NaN, so zero the V columns explicitly
        vh = jnp.where(valid[h:h + 1], vblk[h].astype(jnp.float32), 0.0)
        outs.append(jax.lax.dot_general(
            p[h:h + 1], vh, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32))             # [1, d]
    pv = jnp.concatenate(outs, axis=0)                       # [hb, d]
    l_ref[...] = corr * l_ref[...] + jnp.sum(p, axis=-1, keepdims=True)
    acc_ref[...] = corr * acc_ref[...] + pv
    m_ref[...] = m_new
