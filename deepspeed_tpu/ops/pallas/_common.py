"""Shared kernel-dispatch helpers."""

import jax

TPU_BACKENDS = ("tpu", "axon")


def on_tpu() -> bool:
    try:
        return jax.default_backend() in TPU_BACKENDS
    except Exception:
        return False


def interpret_mode() -> bool:
    """Pallas kernels interpret off-TPU so the suite runs on the CPU mesh."""
    return not on_tpu()
