"""Shared kernel-dispatch helpers."""

import jax

TPU_BACKENDS = ("tpu", "axon")

# The additive masked-out encoding shared by the attention kernels and the
# mask->bias folding in ops.transformer.attention: kernels classify a row
# as fully masked via thresholds on NEG_INF/2, so every producer of masked
# logits must use THIS constant (fp32- and bf16-representable).
NEG_INF = -1e30


def on_tpu() -> bool:
    try:
        return jax.default_backend() in TPU_BACKENDS
    except Exception:
        return False


def interpret_mode() -> bool:
    """Pallas kernels interpret off-TPU so the suite runs on the CPU mesh."""
    return not on_tpu()


def pick_block(dim: int, want: int) -> int:
    """Largest divisor of ``dim`` <= ``want`` keeping 128-lane alignment
    (whole dim for small/ragged sizes) — the shared tiling heuristic for
    the flash / int8-matmul kernels."""
    import math
    b = math.gcd(dim, min(want, dim))
    return b if b % 128 == 0 or b == dim else dim
