"""Quantization Pallas kernels.

Reference: csrc/quantization/quantizer.cu + pt_binding.cpp exposing
``ds_quantize_fp32/16`` (symmetric), ``ds_sr_quantize_*`` (stochastic
rounding), ``ds_quantize_asym_*``. Used by MoQ training-time quantization
and by the compressed-collective path (EQuARX-style int8 all-reduce is the
TPU analog of the reference's 1-bit NCCL backend).

Group-wise int8: x is viewed as [groups, group_size]; each group gets a
fp32 scale (and zero-point for asymmetric).
"""

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu


from ._common import interpret_mode as _interpret


def _quant_sym_kernel(x_ref, q_ref, scale_ref):
    x = x_ref[:].astype(jnp.float32)                      # [G, N]
    absmax = jnp.max(jnp.abs(x), axis=-1, keepdims=True)
    scale = jnp.maximum(absmax, 1e-8) / 127.0
    q = jnp.clip(jnp.round(x / scale), -127, 127).astype(jnp.int8)
    q_ref[:] = q
    scale_ref[:] = scale


def _quant_asym_kernel(x_ref, q_ref, scale_ref, zp_ref):
    x = x_ref[:].astype(jnp.float32)
    xmin = jnp.min(x, axis=-1, keepdims=True)
    xmax = jnp.max(x, axis=-1, keepdims=True)
    scale = jnp.maximum(xmax - xmin, 1e-8) / 255.0
    zp = xmin
    # Mosaic has no f32->uint8 cast: emit the code offset by -128 as int8;
    # dispatch rebiases to uint8 outside the kernel.
    q = jnp.clip(jnp.round((x - zp) / scale) - 128.0, -128, 127).astype(jnp.int8)
    q_ref[:] = q
    scale_ref[:] = scale
    zp_ref[:] = zp


def _quant_sr_kernel(x_ref, seed_ref, q_ref, scale_ref):
    pltpu.prng_seed(seed_ref[0])
    x = x_ref[:].astype(jnp.float32)
    absmax = jnp.max(jnp.abs(x), axis=-1, keepdims=True)
    scale = jnp.maximum(absmax, 1e-8) / 127.0
    scaled = x / scale
    floor = jnp.floor(scaled)
    frac = scaled - floor
    # prng_random_bits yields int32 — bitcast to uint32 so the shift is
    # logical (arithmetic shift sign-extends and biases u negative), then
    # back to int32 for the f32 cast (Mosaic lacks uint32->f32); the top-24
    # value is < 2^24 so the int32 reinterpretation is exact and positive.
    bits = pltpu.bitcast(pltpu.prng_random_bits(scaled.shape), jnp.uint32)
    top24 = pltpu.bitcast(bits >> 8, jnp.int32)
    u = top24.astype(jnp.float32) / float(1 << 24)  # uniform [0,1)
    q = jnp.clip(floor + (u < frac).astype(jnp.float32), -127, 127)
    q_ref[:] = q.astype(jnp.int8)
    scale_ref[:] = scale


def quantize(x, groups: int = 1, *, asymmetric: bool = False,
             stochastic: bool = False, seed: int = 0):
    """Quantize to int8 (sym, [-127,127]) or uint8 (asym). Returns
    (q, scales[, zero_points]) with q shaped like x."""
    shape = x.shape
    n = x.size
    assert n % groups == 0, f"{n} elements not divisible into {groups} groups"
    gs = n // groups
    x2d = x.reshape(groups, gs)

    # Block over groups so a multi-GB tensor never lands in VMEM whole:
    # each program handles G_BLK complete groups ([G_BLK, gs] slab).
    g_blk = _group_block(groups, gs)

    def call(kernel, out_shapes, extra_in=(), extra_in_specs=()):
        grid = (pl.cdiv(groups, g_blk),)
        in_specs = [pl.BlockSpec((g_blk, gs), lambda i: (i, 0))]
        in_specs += list(extra_in_specs)
        out_specs = []
        for os in out_shapes:
            if os.shape == (groups, 1):  # per-group scalars, kept 2D for tiling
                out_specs.append(pl.BlockSpec((g_blk, 1), lambda i: (i, 0)))
            else:
                out_specs.append(pl.BlockSpec((g_blk, gs), lambda i: (i, 0)))
        outs = pl.pallas_call(
            kernel, grid=grid, in_specs=in_specs,
            out_specs=tuple(out_specs), out_shape=tuple(out_shapes),
            interpret=_interpret(),
        )(x2d, *extra_in)
        return tuple(o[:, 0] if o.shape == (groups, 1) else o for o in outs)

    if asymmetric:
        q, scale, zp = call(
            _quant_asym_kernel,
            (jax.ShapeDtypeStruct((groups, gs), jnp.int8),
             jax.ShapeDtypeStruct((groups, 1), jnp.float32),
             jax.ShapeDtypeStruct((groups, 1), jnp.float32)))
        q = (q.astype(jnp.int16) + 128).astype(jnp.uint8)
        return q.reshape(shape), scale, zp
    if stochastic:
        if _interpret():
            # pltpu.prng_* has no CPU-interpret lowering; equivalent jax path
            absmax = jnp.max(jnp.abs(x2d), axis=-1, keepdims=True)
            scale2 = jnp.maximum(absmax, 1e-8) / 127.0
            scaled = x2d / scale2
            floor = jnp.floor(scaled)
            u = jax.random.uniform(jax.random.PRNGKey(seed), scaled.shape)
            q = jnp.clip(floor + (u < (scaled - floor)), -127, 127)
            return q.astype(jnp.int8).reshape(shape), scale2[:, 0]
        q, scale = call(
            _quant_sr_kernel,
            (jax.ShapeDtypeStruct((groups, gs), jnp.int8),
             jax.ShapeDtypeStruct((groups, 1), jnp.float32)),
            extra_in=(jnp.asarray([seed], jnp.int32),),
            extra_in_specs=(pl.BlockSpec(memory_space=pltpu.SMEM),))
        return q.reshape(shape), scale
    q, scale = call(
        _quant_sym_kernel,
        (jax.ShapeDtypeStruct((groups, gs), jnp.int8),
         jax.ShapeDtypeStruct((groups, 1), jnp.float32)))
    return q.reshape(shape), scale


def _group_block(groups, gs):
    """Groups per program: slab bounded to ~4 MB fp32, sublane-friendly."""
    max_groups = max(1, (4 * 2 ** 20) // max(4 * gs, 1))
    g_blk = min(groups, max_groups)
    if g_blk >= 8:
        g_blk = g_blk // 8 * 8
    while groups % g_blk != 0:
        g_blk -= 1
    return g_blk


def dequantize(q, scales, zero_points=None, dtype=jnp.float32):
    """Inverse of quantize (group-wise)."""
    groups = scales.shape[0]
    shape = q.shape
    q2d = q.reshape(groups, -1).astype(jnp.float32)
    if zero_points is not None:
        out = q2d * scales[:, None] + zero_points[:, None]
    else:
        out = q2d * scales[:, None]
    return out.reshape(shape).astype(dtype)
