"""Pallas decode-attention kernel (KV-cache single-token attention).

TPU-native replacement for THE inference kernel of DS-Inference:
``softmax_context`` (reference: csrc/transformer/inference/csrc/
pt_binding.cpp:1197-1244 + softmax.cu) — one query token per (batch,
head) attends to the valid prefix of a preallocated KV cache.

Design (shaped by Mosaic's constraint that dynamically-indexed slices
need a 128-aligned minor dim):

- **transposed caches**: K/V live as [batch, heads, head_dim, max_len]
  ("K^T layout") so the minor dim is the sequence — any head_dim (64 of
  GPT-2 or 128 of BLOOM/LLaMA class) tiles cleanly, q·K is a direct
  [1,d]x[d,Bk] MXU matmul, and HBM block slices are 128-aligned.
- **manual-DMA kernel**: grid (batch, head_blocks); the kernel streams
  K/V blocks HBM->VMEM with double-buffered ``make_async_copy`` inside a
  ``fori_loop`` whose trip count is ``ceil(length / block_k)`` — DMA
  traffic AND compute scale with the *valid* cache length, not the
  allocated max_len (the reference kernel reads only ``total_count``
  history the same way). Two statically-addressed buffer pairs switched
  by ``pl.when`` on loop parity (Mosaic cannot dynamically index a
  buffer stack with a sub-128 lane dim). Measured on v5e at
  B4/H32/S2048/D128: ~par with the dense XLA path at full cache,
  ~2.5x faster at half length.
- the causal/length mask lives IN the kernel (``col < length`` from a
  scalar-prefetched per-batch length vector) — no [B,H,1,S] mask tensor
  is ever materialized (the dense fallback builds one per decode step).
- ALiBi (BLOOM serving) computed in-kernel from per-head slopes:
  ``slope * (col - (length-1))``, matching models/layers.py alibi_bias.
- caches whose max_len is not a multiple of 128 take a fused-dense jnp
  fallback (kernel semantics, XLA codegen) — the generation path rounds
  its cache allocation up to 128 so serving always hits the kernel.

Inference-only: no custom_vjp (the reference kernel is fwd-only too).
"""

import functools
import math

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from ._common import NEG_INF
from ._common import interpret_mode as _interpret
from ._common import online_softmax_block as _attend_block
from ._common import read_slopes as _read_slopes

DEFAULT_BLOCK_K = 512
DEFAULT_HEAD_BLOCK = 8


def _dma_kernel(len_ref, slopes_ref, q_ref, k_hbm, v_hbm, o_ref,
                kbuf0, vbuf0, kbuf1, vbuf1, sem, m_ref, l_ref, acc_ref,
                *, scale, block_k, hb, alibi):
    b, hi = pl.program_id(0), pl.program_id(1)
    length = len_ref[b]
    nb = pl.cdiv(length, block_k)
    m_ref[...] = jnp.full_like(m_ref, NEG_INF)
    l_ref[...] = jnp.zeros_like(l_ref)
    acc_ref[...] = jnp.zeros_like(acc_ref)
    slopes = _read_slopes(slopes_ref, hi * hb, hb) if alibi else None
    bufs = ((kbuf0, vbuf0), (kbuf1, vbuf1))

    def copies(j, slot):
        start = j * block_k
        kb, vb = bufs[slot]
        ck = pltpu.make_async_copy(
            k_hbm.at[b, hi, :, :, pl.ds(start, block_k)], kb, sem.at[slot, 0])
        cv = pltpu.make_async_copy(
            v_hbm.at[b, hi, :, :, pl.ds(start, block_k)], vb, sem.at[slot, 1])
        return ck, cv

    # the prologue must not start copies a zero-block row never waits:
    # leaked semaphore signals would satisfy the NEXT grid step's wait()
    # while its own DMA is still in flight (real-TPU hazard; interpret
    # mode doesn't model semaphores)
    @pl.when(nb > 0)
    def _first_copies():
        ck, cv = copies(0, 0)
        ck.start()
        cv.start()

    def body(j, carry):
        slot = jax.lax.rem(j, 2)

        for parity in (0, 1):
            @pl.when((slot == parity) & (j + 1 < nb))
            def _prefetch():
                nk, nv = copies(j + 1, 1 - parity)
                nk.start()
                nv.start()

        for parity in (0, 1):
            @pl.when(slot == parity)
            def _compute():
                wk, wv = copies(j, parity)
                wk.wait()
                wv.wait()
                q = q_ref[0].astype(jnp.float32) * scale
                kb, vb = bufs[parity]
                _attend_block(q, kb, vb, j * block_k, length, length - 1,
                              slopes, m_ref, l_ref, acc_ref, hb=hb,
                              alibi=alibi)
        return carry

    jax.lax.fori_loop(0, nb, body, 0)
    # length <= 0 rows (empty serving slots) ran zero blocks: l stays 0 and
    # acc/l would be NaN. Select zeros instead — valid rows always have
    # l >= 1 (the max-score column contributes exp(0)), so this is a no-op
    # for them.
    l = l_ref[...]
    safe = acc_ref[...] / jnp.where(l > 0.0, l, 1.0)
    o_ref[0] = jnp.where(l > 0.0, safe, 0.0).astype(o_ref.dtype)


def _decode_dma(q_bhd, k, v, lengths, slopes, *, scale, block_k, hb, alibi):
    b, heads, d = q_bhd.shape
    s = k.shape[3]
    kr = k.reshape(b, heads // hb, hb, d, s)
    vr = v.reshape(b, heads // hb, hb, d, s)
    kv_buf = lambda: pltpu.VMEM((hb, d, block_k), k.dtype)
    return pl.pallas_call(
        functools.partial(_dma_kernel, scale=scale, block_k=block_k,
                          hb=hb, alibi=alibi),
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=2,
            grid=(b, heads // hb),
            in_specs=[
                pl.BlockSpec((1, hb, d), lambda bi, hi, *_: (bi, hi, 0)),
                pl.BlockSpec(memory_space=pl.ANY),
                pl.BlockSpec(memory_space=pl.ANY),
            ],
            out_specs=pl.BlockSpec((1, hb, d), lambda bi, hi, *_: (bi, hi, 0)),
            scratch_shapes=[
                kv_buf(), kv_buf(), kv_buf(), kv_buf(),
                pltpu.SemaphoreType.DMA((2, 2)),
                pltpu.VMEM((hb, 1), jnp.float32),
                pltpu.VMEM((hb, 1), jnp.float32),
                pltpu.VMEM((hb, d), jnp.float32),
            ],
        ),
        out_shape=jax.ShapeDtypeStruct((b, heads, d), q_bhd.dtype),
        # jax renamed TPUCompilerParams -> CompilerParams around 0.5;
        # support both so the kernel runs on the pinned CI jax too
        compiler_params=getattr(pltpu, "CompilerParams",
                                getattr(pltpu, "TPUCompilerParams", None))(
            dimension_semantics=("parallel", "parallel")),
        interpret=_interpret(),
    )(lengths, slopes, q_bhd, kr, vr)


def _decode_dense(q_bhd, k, v, lengths, slopes, *, scale, alibi):
    """jnp fallback with IDENTICAL semantics for caches the kernel cannot
    tile (max_len not a multiple of 128). XLA fuses the chain; the mask
    still never leaves registers as a [B,H,1,S] tensor thanks to fusion."""
    s = k.shape[3]
    logits = jnp.einsum("bhd,bhdk->bhk", q_bhd.astype(jnp.float32) * scale,
                        k.astype(jnp.float32))
    col = jnp.arange(s)[None, None, :]
    ln = lengths[:, None, None]
    if alibi:
        logits = logits + slopes[None, :, None] * (col - (ln - 1))
    logits = jnp.where(col < ln, logits, NEG_INF)
    p = jax.nn.softmax(logits, axis=-1)
    out = jnp.einsum("bhk,bhdk->bhd", p, v.astype(jnp.float32))
    # length <= 0 rows have every column masked; softmax degenerates to
    # uniform weights over cache garbage. Match the kernel: emit zeros.
    out = jnp.where(lengths[:, None, None] > 0, out, 0.0)
    return out.astype(q_bhd.dtype)


def decode_attention(q, k, v, length, *, softmax_scale=None,
                     alibi_slopes=None, block_k=DEFAULT_BLOCK_K,
                     head_block=DEFAULT_HEAD_BLOCK):
    """Single-token KV-cache attention over transposed caches.

    q: [B, 1, H, d] (or [B, H, d]) — the current token's queries (BSHD).
    k, v: [B, H, d, S] — the preallocated cache in K^T layout.
    length: int32 scalar or [B] — number of valid cache slots per row
        (the query sits at position length-1). Rows with length <= 0
        (empty serving slots) return zeros.
    alibi_slopes: optional [H] per-head ALiBi slopes (BLOOM).

    Returns [B, 1, H, d] (or [B, H, d], matching q's rank).
    """
    squeeze = q.ndim == 3
    if squeeze:
        q = q[:, None]
    b, one, heads, d = q.shape
    if one != 1:
        raise ValueError(f"decode_attention is single-token (q_len 1), got {one}")
    s = k.shape[3]
    scale = softmax_scale if softmax_scale is not None else 1.0 / math.sqrt(d)
    hb = math.gcd(heads, head_block)

    lengths = jnp.broadcast_to(jnp.asarray(length, jnp.int32), (b,))
    alibi = alibi_slopes is not None
    slopes = (jnp.asarray(alibi_slopes, jnp.float32) if alibi
              else jnp.zeros((heads,), jnp.float32))
    q_bhd = jnp.swapaxes(q, 1, 2)[:, :, 0, :]                # [B, H, d]

    # block size: a 128-multiple divisor of max_len (Mosaic minor-dim
    # alignment); otherwise the dense fallback
    bk = min(block_k, s)
    bk = (bk // 128) * 128
    while bk >= 128 and s % bk != 0:
        bk -= 128
    if bk >= 128:
        out = _decode_dma(q_bhd, k, v, lengths, slopes, scale=scale,
                          block_k=bk, hb=hb, alibi=alibi)
    else:
        out = _decode_dense(q_bhd, k, v, lengths, slopes, scale=scale,
                            alibi=alibi)
    out = out[:, None]                                       # [B, 1, H, d]
    return out[:, 0].reshape(b, heads, d) if squeeze else out
