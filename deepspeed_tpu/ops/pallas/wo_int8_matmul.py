"""Weight-only int8 matmul: fused in-kernel dequantization.

Reference: the int8 inference gemms (csrc/transformer/inference/csrc/
pt_binding.cpp:1197-1244 qkv_gemm_int8 / mlp_gemm_int8 / vector_matmul_int8)
— activations stay half precision, weights are stored int8 with
per-output-channel scales and dequantized inside the gemm.

Why a kernel instead of `x @ (q * scale).astype(bf16)`: inside a jitted
decode loop XLA hoists that loop-invariant dequantization out of the
`lax.scan`, materializing the full bf16 weight copy in HBM — doubling
weight memory (fatal for 6.7B-class serving on a 16 GB chip) and reading
bf16 bytes every step. This kernel reads int8 HBM bytes (half the
bandwidth of bf16 — decode is weight-bandwidth-bound) and converts
tile-by-tile in VMEM.

Grid (m_blocks, n_blocks, k_blocks), k innermost; fp32 accumulator
scratch persists across the k walk; the per-channel scale multiplies the
accumulated tile once at the end (x @ (q·s) == (x @ q)·s for per-n
scales). Decode (m small) runs one m-block exactly as before; prefill
(m large) tiles the row dim so long prompts stay int8-resident too —
no full bf16 weight copy ever lands in HBM.
"""

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from ._common import interpret_mode as _interpret

DEFAULT_BLOCK_M = 512
DEFAULT_BLOCK_N = 512
DEFAULT_BLOCK_K = 1024

# decode (m=1) GEMV tiles: bigger than the matmul tiles — the VPU path
# has no MXU residency pressure and wants long HBM bursts
GEMV_BLOCK_N = 2048
GEMV_BLOCK_K = 1024


def _gemv_enabled() -> bool:
    """The m=1 VPU GEMV is numerically proven (interpret-mode parity
    across the shape matrix) but its Mosaic lowering had not been timed
    on a real chip when this shipped — the axon tunnel died before the
    perf run (2026-07-31), so routing is CALIBRATION-DRIVEN:

    - DS_TPU_INT8_GEMV=1 / =0 forces the path either way;
    - otherwise, if a committed hardware-calibration artifact
      (benchmarks/results/gemv_r5_*.json, produced by
      tools/validate_gemv.py — tools/tpu_watch.sh runs it automatically
      on the first tunnel-up window) recommends the GEMV at >= 2x the
      MXU path, it becomes the default;
    - with no artifact, the default stays the measured MXU path so the
      benchmark can't regress on an unvalidated codepath (analysis says
      ~5x: MXU weight ingestion caps m=1 at ~146 GB/s vs ~820 GB/s HBM).
    """
    import os
    # any SET value (including '' / '0', false per env_flag) is an
    # explicit override; only an absent variable defers to calibration
    if os.environ.get("DS_TPU_INT8_GEMV") is not None:
        from ...utils import env_flag
        return env_flag("DS_TPU_INT8_GEMV")
    return _gemv_calibration()


@functools.lru_cache(None)
def _gemv_calibration() -> bool:
    """Newest committed gemv calibration artifact's recommendation, False
    when none exists (source checkouts only — the artifact dir isn't
    shipped in wheels, which is fine: calibration is per-fleet anyway)."""
    import glob
    import json
    import os
    root = os.environ.get(
        "DS_TPU_GEMV_CALIBRATION_DIR",
        os.path.join(os.path.dirname(__file__), "..", "..", "..",
                     "benchmarks", "results"))
    arts = sorted(glob.glob(os.path.join(root, "gemv_r5_*.json")))
    for path in reversed(arts):
        try:
            with open(path) as f:
                rec = json.load(f)
        except (OSError, ValueError):
            continue
        # only COMPLETE runs (both paths measured -> "speedup" present)
        # carry routing authority; a later wedged/partial diagnostic must
        # not revoke an earlier successful calibration
        if "speedup" in rec and "recommend_default_gemv" in rec:
            return bool(rec["recommend_default_gemv"])
    return False


def _kernel(x_ref, q_ref, s_ref, o_ref, acc_ref, *, n_kb, out_dtype):
    ki = pl.program_id(2)

    @pl.when(ki == 0)
    def _zero():
        acc_ref[...] = jnp.zeros_like(acc_ref)

    x = x_ref[...]                       # [bm, bk] activation dtype
    w = q_ref[...].astype(x.dtype)       # int8 -> activation dtype (VPU)
    acc_ref[...] += jnp.dot(x, w, preferred_element_type=jnp.float32)

    @pl.when(ki == n_kb - 1)
    def _emit():
        o_ref[...] = (acc_ref[...] * s_ref[...].astype(jnp.float32)) \
            .astype(out_dtype)


def _gemv_kernel(x_ref, q_ref, s_ref, o_ref, acc_ref, *, n_kb, out_dtype):
    """Decode GEMV on the VPU. With m=1 the MXU path is bound by weight
    ingestion into the systolic array (~146 GB/s measured on v5e,
    2026-07-31 — the array loads weights at a fixed rate no matter how
    few rows flow through), not by HBM. Elementwise multiply + sublane
    reduction reads the same int8 bytes but never touches the MXU.
    ``x`` arrives as a COLUMN [bk, 1] so the product broadcasts along
    lanes; an in-kernel [1,bk]->[bk,1] transpose would be a cross-vreg
    shuffle Mosaic compiles catastrophically (hung the backend when
    tried)."""
    ki = pl.program_id(1)

    @pl.when(ki == 0)
    def _zero():
        acc_ref[...] = jnp.zeros_like(acc_ref)

    xc = x_ref[...].astype(jnp.float32)          # [bk, 1]
    w = q_ref[...].astype(jnp.float32)           # [bk, bn] int8 -> f32
    acc_ref[...] += jnp.sum(xc * w, axis=0, keepdims=True)

    @pl.when(ki == n_kb - 1)
    def _emit():
        o_ref[...] = (acc_ref[...] * s_ref[...].astype(jnp.float32)) \
            .astype(out_dtype)


def _wo_int8_gemv(x, q, scale, block_n, block_k, out_dtype):
    """m=1 fast path: grid (n_blocks, k_blocks), k innermost; fp32
    accumulator row persists across the k walk."""
    from ._common import pick_block
    k, n = q.shape
    block_n = pick_block(n, block_n)
    block_k = pick_block(k, block_k)
    if block_n * block_k > 8 * 2 ** 20:
        # ragged dims forced a >8MB VMEM weight tile (pick_block always
        # returns a divisor, so e.g. a 50257-vocab head yields the whole
        # dim) — fall back to the matmul path, which has its own guard
        return None
    n_kb = k // block_k
    grid = (n // block_n, n_kb)
    return pl.pallas_call(
        functools.partial(_gemv_kernel, n_kb=n_kb, out_dtype=out_dtype),
        grid=grid,
        in_specs=[
            pl.BlockSpec((block_k, 1), lambda ni, ki: (ki, 0)),
            pl.BlockSpec((block_k, block_n), lambda ni, ki: (ki, ni)),
            pl.BlockSpec((1, block_n), lambda ni, ki: (0, ni)),
        ],
        out_specs=pl.BlockSpec((1, block_n), lambda ni, ki: (0, ni)),
        out_shape=jax.ShapeDtypeStruct((1, n), out_dtype),
        scratch_shapes=[pltpu.VMEM((1, block_n), jnp.float32)],
        interpret=_interpret(),
    )(x.reshape(k, 1), q, scale.reshape(1, n))


def _wo_int8_2d(x, q, scale, block_m, block_n, block_k, out_dtype):
    from ._common import pick_block
    m, k = x.shape
    _, n = q.shape
    block_n = pick_block(n, block_n)
    block_k = pick_block(k, block_k)
    if n % block_n or k % block_k:
        return None   # caller falls back
    if block_n * block_k > 8 * 2 ** 20:
        return None   # ragged dims forced a >8MB VMEM weight tile
    # decode: one row-block of exactly m; prefill: tile m. Prefer an
    # aligned divisor of m (no padding, no extra x round-trip); only a
    # ragged m with no VMEM-sized divisor pays a zero-padded tail (rows
    # are independent — padding contributes nothing and is sliced off).
    block_m = min(block_m, m)
    bm = pick_block(m, block_m)
    if bm <= 2 * block_m:   # caller's block_m is the VMEM budget
        block_m, pad_m = bm, 0
    else:
        pad_m = (-m) % block_m
        x = jnp.pad(x, ((0, pad_m), (0, 0)))
    m_pad = m + pad_m
    n_kb = k // block_k
    grid = (m_pad // block_m, n // block_n, n_kb)
    out = pl.pallas_call(
        functools.partial(_kernel, n_kb=n_kb, out_dtype=out_dtype),
        grid=grid,
        in_specs=[
            pl.BlockSpec((block_m, block_k), lambda mi, ni, ki: (mi, ki)),
            pl.BlockSpec((block_k, block_n), lambda mi, ni, ki: (ki, ni)),
            pl.BlockSpec((1, block_n), lambda mi, ni, ki: (0, ni)),
        ],
        out_specs=pl.BlockSpec((block_m, block_n),
                               lambda mi, ni, ki: (mi, ni)),
        out_shape=jax.ShapeDtypeStruct((m_pad, n), out_dtype),
        scratch_shapes=[pltpu.VMEM((block_m, block_n), jnp.float32)],
        interpret=_interpret(),
    )(x, q, scale.reshape(1, n))
    return out[:m] if pad_m else out


def wo_int8_matmul(x, q, scale, *, block_m=None, block_n=None,
                   block_k=None, out_dtype=None):
    """``x @ (q * scale)`` with int8 ``q`` dequantized in-kernel.

    x: [..., k] activations (bf16/f32); q: [k, n] int8; scale: per-output
    -channel, any shape broadcastable to [1, n] (module_quantize stores
    [1, n]). Returns [..., n] in ``out_dtype`` (default: x.dtype).
    Any m is supported (decode m=1 through long-prompt prefill — the m
    dim is tiled at ``block_m`` with zero-padded ragged tails).

    ``block_*``: VMEM tile budget knobs. Defaults differ per path
    (decode GEMV wants longer tiles than the MXU matmul), so None means
    "the path's default"; an explicit value is honored on both paths.

    Shapes the kernel cannot tile (ragged dims forcing an oversized
    VMEM tile) fall back to the jnp dequant matmul — numerically
    identical, but subject to XLA's loop hoisting; serving-size models
    are always 128-aligned in practice.
    """
    out_dtype = out_dtype or x.dtype
    k, n = q.shape
    lead = x.shape[:-1]
    x2 = x.reshape(-1, k)
    scale = jnp.asarray(scale).reshape(-1)
    if scale.size == 1:
        scale = jnp.broadcast_to(scale, (n,))
    if scale.size != n:
        raise ValueError(f"scale has {scale.size} elements for n={n}")
    out = None
    if x2.shape[0] == 1 and _gemv_enabled():
        out = _wo_int8_gemv(x2, q, scale, block_n or GEMV_BLOCK_N,
                            block_k or GEMV_BLOCK_K, out_dtype)
    if out is None:
        out = _wo_int8_2d(x2, q, scale, block_m or DEFAULT_BLOCK_M,
                          block_n or DEFAULT_BLOCK_N,
                          block_k or DEFAULT_BLOCK_K, out_dtype)
    if out is None:
        w = (q.astype(jnp.float32) * scale[None, :]).astype(x.dtype)
        out = jnp.dot(x2, w, preferred_element_type=jnp.float32) \
            .astype(out_dtype)
    return out.reshape(*lead, n)
