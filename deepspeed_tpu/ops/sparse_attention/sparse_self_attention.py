"""Block-sparse self-attention.

Reference: ops/sparse_attention/sparse_self_attention.py (Triton SDD/DSD
matmul + sparse softmax kernels, matmul.py/softmax.py). Two execution
paths behind one interface:

- the Pallas block-sparse kernel (block_sparse_kernel.py) — work scales
  with the number of active layout blocks, like the reference's Triton
  kernels; used whenever the layout tiles at 128 granularity.
- a dense-mask fallback (the layout expanded to [1, heads, S, S] bool and
  fed to the fused attention op) for shapes/extra-mask combinations the
  kernel doesn't cover; numerically identical, no FLOP savings.
"""


from typing import Any, Optional

import numpy as np
import jax.numpy as jnp
import flax.linen as nn

from ..transformer.attention import attention
from .sparsity_config import SparsityConfig, FixedSparsityConfig


_MASK_CACHE = {}


def layout_to_dense_mask(config: SparsityConfig, seq_len: int):
    """Expand the block layout to a [1, heads, S, S] boolean mask, cached
    by config VALUE (not identity — configs are routinely rebuilt per
    call, e.g. SparseSelfAttention's default Fixed config)."""
    try:
        key = (config.cache_key(), seq_len)
    except TypeError:   # unhashable custom attribute: compute uncached
        key = None
    if key is not None and key in _MASK_CACHE:
        # cache holds NUMPY: a jnp array built inside one jit trace is a
        # tracer-backed constant and must not leak into another trace
        # (e.g. prefill's jit populating it, the decode loop's jit
        # reusing it)
        return jnp.asarray(_MASK_CACHE[key])
    layout = config.make_layout(seq_len)
    mask_np = np.kron(
        layout, np.ones((config.block, config.block), np.int8))[None] \
        .astype(bool)  # [1, H, S, S]
    if getattr(config, "attention", None) == "unidirectional":
        # the block layout is tril at BLOCK granularity; unidirectional
        # semantics are strictly causal at the ELEMENT level (reference:
        # the triton softmax kernel's triangular masking inside diagonal
        # blocks) — without this, position i attends up to block-1
        # future positions inside its own diagonal block
        mask_np = mask_np & np.tril(np.ones((seq_len, seq_len), bool))
    if key is not None:
        if len(_MASK_CACHE) >= 32:
            _MASK_CACHE.pop(next(iter(_MASK_CACHE)))
        _MASK_CACHE[key] = mask_np
    return jnp.asarray(mask_np)


def sparse_attention(q, k, v, sparsity_config: SparsityConfig, *,
                     softmax_scale=None, key_padding_mask=None,
                     attn_mask=None, backend: Optional[str] = None,
                     dropout_rate=0.0, dropout_rng=None,
                     deterministic=True):
    """q/k/v [batch, seq, heads, head_dim]; pattern from the config
    (reference: SparseSelfAttention.forward, with the Triton softmax
    kernel's fused attention dropout).

    backend: None = auto (Pallas kernel when the layout tiles and no
    extra masks are given), "pallas" = require the kernel, "dense" =
    force the dense-mask path. Dropout (dropout_rate > 0, deterministic
    False, an rng given) is fused into the kernel via the flash kernel's
    counter-based keep hash; the dense-mask path samples the identical
    bits, so both paths agree bit-for-bit under dropout."""
    if backend not in (None, "dense", "pallas"):
        raise ValueError(f"sparse_attention backend must be None, 'dense' "
                         f"or 'pallas', got {backend!r}")
    s = q.shape[1]
    drop_on = dropout_rate > 0.0 and not deterministic
    if drop_on and dropout_rng is None:
        raise ValueError("sparse_attention: dropout_rate > 0 with "
                         "deterministic=False requires dropout_rng")
    if backend != "dense":
        extra_masks = key_padding_mask is not None or attn_mask is not None
        if backend == "pallas" and extra_masks:
            raise ValueError(
                "sparse_attention backend='pallas' does not support "
                "key_padding_mask/attn_mask — drop them or use the dense "
                "path")
        from ..pallas._common import on_tpu
        if extra_masks and on_tpu():
            # a padding-masked BERT silently loses the kernel's FLOP
            # savings — say so once instead of degrading quietly (ADVICE
            # r3: folding the padding mask into the kernel's fine-mask
            # path is the future fix). Only warn where the kernel was
            # actually reachable (off-TPU auto mode never takes it).
            from ...utils.logging import warn_once
            warn_once(
                "sparse_attention: key_padding_mask/attn_mask present — "
                "taking the dense-mask path (the block-sparse kernel "
                "takes no mask operands); FLOP savings of the sparsity "
                "pattern are not realized")
        # auto mode takes the kernel only on real TPUs — off-TPU it would
        # run in interpret mode, orders of magnitude slower than the dense
        # XLA path; backend="pallas" forces it anyway (tests)
        if not extra_masks and (backend == "pallas" or on_tpu()):
            from .block_sparse_kernel import block_sparse_attention
            out = block_sparse_attention(
                q, k, v, sparsity_config, softmax_scale=softmax_scale,
                dropout_rate=dropout_rate if drop_on else 0.0,
                dropout_rng=dropout_rng if drop_on else None)
            if out is not None:
                return out
            if backend == "pallas":
                raise ValueError(
                    "sparse_attention backend='pallas' but the layout cannot "
                    "be tiled at 128 granularity (need seq % 128 == 0 and "
                    "block dividing 128, and no all-zero rows)")
    mask = layout_to_dense_mask(sparsity_config, s)
    if key_padding_mask is not None:
        # [batch, S] True=keep -> broadcast over heads and query pos
        mask = jnp.logical_and(mask,
                               key_padding_mask[:, None, None, :].astype(bool))
    if attn_mask is not None:
        mask = jnp.logical_and(mask, attn_mask.astype(bool))
    # unidirectional causality (block AND element level) is encoded in
    # the dense mask by layout_to_dense_mask; no separate causal flag
    return attention(q, k, v, mask=mask, softmax_scale=softmax_scale,
                     dropout_rate=dropout_rate, dropout_rng=dropout_rng,
                     deterministic=not drop_on, seq_parallel="none")


class SparseSelfAttention(nn.Module):
    """Drop-in attention module with a sparsity pattern (reference:
    SparseSelfAttention nn.Module, sparse_self_attention.py:11)."""
    sparsity_config: Any = None
    num_heads: Optional[int] = None    # used for the default Fixed config
    softmax_scale: Optional[float] = None

    @nn.compact
    def __call__(self, q, k, v, key_padding_mask=None, attn_mask=None):
        cfg = self.sparsity_config or FixedSparsityConfig(
            num_heads=self.num_heads or q.shape[2])
        return sparse_attention(q, k, v, cfg,
                                softmax_scale=self.softmax_scale,
                                key_padding_mask=key_padding_mask,
                                attn_mask=attn_mask)
