"""BERT-style sparse self-attention layer.

Reference: ``BertSparseSelfAttention`` (deepspeed/ops/sparse_attention/
bert_sparse_self_attention.py:9) — separate q/k/v projections in BERT's
naming, feeding SparseSelfAttention so a dense BERT checkpoint's
attention weights carry over unchanged.
"""

from typing import Any, Optional

import jax.numpy as jnp
import flax.linen as nn

from .sparse_self_attention import sparse_attention
from .sparsity_config import FixedSparsityConfig


class BertSparseSelfAttention(nn.Module):
    """Drop-in replacement for a BERT self-attention sub-layer
    (projections named ``query``/``key``/``value`` like HF/reference BERT,
    so existing weights load by name).

    Call: ``layer(hidden_states, attention_mask)`` where attention_mask is
    a [batch, seq] key-padding mask (1/True = attend). Returns the
    [batch, seq, hidden] context (the caller keeps its own output
    projection, as in the reference usage).
    """
    hidden_size: int
    num_attention_heads: int
    sparsity_config: Any = None
    dtype: Any = jnp.float32
    param_dtype: Any = jnp.float32

    @classmethod
    def from_bert_config(cls, config, sparsity_config=None, **kwargs):
        """Build from a BERT-ish config object exposing ``hidden_size`` and
        ``num_attention_heads`` (HF) or ``d_model``/``n_heads`` (ours)."""
        hidden = getattr(config, "hidden_size", None) or config.d_model
        heads = getattr(config, "num_attention_heads", None) or config.n_heads
        return cls(hidden_size=hidden, num_attention_heads=heads,
                   sparsity_config=sparsity_config, **kwargs)

    @nn.compact
    def __call__(self, hidden_states, attention_mask=None):
        if self.hidden_size % self.num_attention_heads != 0:
            raise ValueError(
                f"The hidden size ({self.hidden_size}) is not a multiple of "
                f"the number of attention heads ({self.num_attention_heads})")
        head_dim = self.hidden_size // self.num_attention_heads
        scfg = self.sparsity_config or FixedSparsityConfig(
            num_heads=self.num_attention_heads)

        def proj(name):
            return nn.DenseGeneral(
                features=self.hidden_size, dtype=self.dtype,
                param_dtype=self.param_dtype, name=name)(hidden_states)

        b, s, _ = hidden_states.shape
        q = proj("query").reshape(b, s, self.num_attention_heads, head_dim)
        k = proj("key").reshape(b, s, self.num_attention_heads, head_dim)
        v = proj("value").reshape(b, s, self.num_attention_heads, head_dim)

        key_padding_mask = None
        if attention_mask is not None:
            m = attention_mask
            if m.ndim > 2:          # [b,1,1,s] layout: additive when float
                m = m.reshape(m.shape[0], m.shape[-1])
                if jnp.issubdtype(m.dtype, jnp.floating):
                    m = m > -1.0    # 0 keep / -1e4|-inf drop
            elif jnp.issubdtype(m.dtype, jnp.floating):
                m = m > 0.5         # 2-D masks are multiplicative (1=keep)
            key_padding_mask = m.astype(bool)

        ctx = sparse_attention(q, k, v, scfg,
                               key_padding_mask=key_padding_mask)
        return ctx.reshape(b, s, self.hidden_size)
