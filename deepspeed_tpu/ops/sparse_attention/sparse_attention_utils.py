"""Utilities for adapting pretrained models to sparse self-attention.

Reference: ``SparseAttentionUtils`` (deepspeed/ops/sparse_attention/
sparse_attention_utils.py:13): extend position embeddings, swap dense
attention for sparse, pad/unpad sequences to the sparsity block size.

TPU adaptation: models here are (module-def, param-pytree) pairs, so
"replacing a layer" splits into two pure steps — rewrite the *config*
(the module definition picks up sparse attention) and rewrite the
*params* (position table extension). Both return new values; nothing is
mutated in place.
"""

from typing import Any, Optional

import jax.numpy as jnp

from .sparsity_config import SparsityConfig, FixedSparsityConfig


def _is_mapping(x):
    try:
        return hasattr(x, "keys") and hasattr(x, "__getitem__")
    except Exception:
        return False


class SparseAttentionUtils:
    """Static helpers (reference: sparse_attention_utils.py:13)."""

    @staticmethod
    def extend_position_embedding(params, max_position,
                                  key="position_embeddings",
                                  reserved_rows=0):
        """Tile a position-embedding table inside a param pytree up to
        ``max_position`` rows (reference behavior: repeat the pretrained
        table whole multiples; RoBERTa's 2 reserved rows -> reserved_rows=2).

        Returns a NEW param tree; the input is untouched.
        """
        hits = []

        def rewrite(tree):
            if not _is_mapping(tree):
                return tree
            out = {}
            for name, sub in tree.items():
                # flax logical-partitioning boxes (nn.Partitioned /
                # LogicallyPartitioned) wrap the array; unbox, rewrite,
                # rebox so sharding metadata survives
                val = sub.unbox() if hasattr(sub, "unbox") else sub
                if name == key and hasattr(val, "shape") and val.ndim == 2:
                    head = val[:reserved_rows]
                    body = val[reserved_rows:]
                    orig = body.shape[0]
                    if max_position <= orig:
                        raise ValueError(
                            f"new max position {max_position} must exceed the "
                            f"original {orig}")
                    reps = -(-max_position // orig)   # ceil: never short
                    ext = jnp.concatenate([body] * reps, axis=0)[:max_position]
                    new_val = jnp.concatenate([head, ext], axis=0)
                    out[name] = (sub.replace_boxed(new_val)
                                 if hasattr(sub, "replace_boxed") else new_val)
                    hits.append(orig * reps)
                else:
                    out[name] = rewrite(sub)
            return out

        new_params = rewrite(params)
        if not hits:
            raise ValueError(
                f"no 2-D '{key}' table found in the param tree — pass the "
                f"embedding param name via key=")
        return new_params

    @staticmethod
    def update_tokenizer_model_max_length(tokenizer, max_position):
        """Reference: sparse_attention_utils.py:69 — same contract; works
        on any HF tokenizer object."""
        tokenizer.model_max_length = max_position
        if hasattr(tokenizer, "init_kwargs"):
            tokenizer.init_kwargs["model_max_length"] = max_position
        return tokenizer

    @staticmethod
    def replace_model_self_attention_with_sparse_self_attention(
            config, params, max_position,
            sparsity_config: Optional[SparsityConfig] = None):
        """Reference: sparse_attention_utils.py:85. Dense attention ->
        sparse attention on a model built from ``deepspeed_tpu.models``
        configs (BertConfig/GPTConfig): returns ``(new_config, new_params)``
        where the config carries the sparsity pattern (every Block routes
        through the block-sparse kernel) and the params have the position
        table extended to ``max_position``.

        The q/k/v/output projection weights are untouched — sparsity only
        changes which score blocks are computed, exactly like the
        reference's layer swap that reuses query/key/value modules.
        """
        import dataclasses
        if sparsity_config is None:
            sparsity_config = FixedSparsityConfig(num_heads=config.n_heads)
        field_names = {f.name for f in dataclasses.fields(config)}
        if "sparsity_config" not in field_names:
            raise ValueError(
                f"{type(config).__name__} does not support sparse attention")
        updates = {"sparsity_config": sparsity_config}
        if "max_seq_len" in field_names:
            updates["max_seq_len"] = max_position
        new_config = dataclasses.replace(config, **updates)
        new_params = SparseAttentionUtils.extend_position_embedding(
            params, max_position)
        return new_config, new_params

    @staticmethod
    def pad_to_block_size(block_size, input_ids, attention_mask,
                          token_type_ids, position_ids, inputs_embeds,
                          pad_token_id, model_embeddings=None):
        """Pad the seq dim of every given input to a multiple of
        ``block_size`` (reference: sparse_attention_utils.py:154). Returns
        ``(pad_len, input_ids, attention_mask, token_type_ids,
        position_ids, inputs_embeds)`` with None passed through.

        Note: under jit the same callable recompiles per distinct padded
        length — bucket your batch lengths (the reference has the same
        dynamic-shape cost on CUDA kernel launch shape).
        """
        if input_ids is not None:
            seq_len = input_ids.shape[1]
        else:
            seq_len = inputs_embeds.shape[1]
        pad_len = (block_size - seq_len % block_size) % block_size
        if pad_len == 0:
            return (0, input_ids, attention_mask, token_type_ids,
                    position_ids, inputs_embeds)

        def pad2d(x, value):
            if x is None:
                return None
            return jnp.pad(x, ((0, 0), (0, pad_len)), constant_values=value)

        if inputs_embeds is not None:
            if model_embeddings is not None:
                pad_ids = jnp.full((inputs_embeds.shape[0], pad_len),
                                   pad_token_id, dtype=jnp.int32)
                pad_embeds = model_embeddings(pad_ids)
            else:
                pad_embeds = jnp.zeros(
                    inputs_embeds.shape[:1] + (pad_len,)
                    + inputs_embeds.shape[2:], inputs_embeds.dtype)
            inputs_embeds = jnp.concatenate([inputs_embeds, pad_embeds],
                                            axis=1)
        input_ids = pad2d(input_ids, pad_token_id)
        position_ids = pad2d(position_ids, pad_token_id)
        attention_mask = pad2d(attention_mask, 0)
        token_type_ids = pad2d(token_type_ids, 0)
        return (pad_len, input_ids, attention_mask, token_type_ids,
                position_ids, inputs_embeds)

    @staticmethod
    def unpad_sequence_output(pad_len, sequence_output):
        """Reference: sparse_attention_utils.py:214."""
        if pad_len > 0:
            sequence_output = sequence_output[:, :-pad_len]
        return sequence_output
