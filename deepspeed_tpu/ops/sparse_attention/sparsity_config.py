"""Block-sparsity pattern configs.

Reference: deepspeed/ops/sparse_attention/sparsity_config.py (683 LoC) —
each config builds a per-head block-level layout [heads, nb, nb] with 1 =
compute this (q-block, k-block) tile. Same schema/knobs here; layouts are
numpy int8, built host-side (static at trace time).
"""

from typing import List, Optional

import numpy as np


class SparsityConfig:
    """Base (reference: SparsityConfig): block size + head layout mode."""

    def __init__(self, num_heads: int, block: int = 16,
                 different_layout_per_head: bool = False):
        self.num_heads = num_heads
        self.block = block
        self.different_layout_per_head = different_layout_per_head

    def cache_key(self):
        """Value-based key for mask caching (configs have no __eq__; two
        equal-valued instances must share one cached mask)."""
        items = tuple(sorted(
            (k, tuple(v) if isinstance(v, list) else v)
            for k, v in vars(self).items()))
        return (type(self).__name__, items)

    def setup_layout(self, seq_len: int) -> np.ndarray:
        if seq_len % self.block != 0:
            raise ValueError(
                f"seq len {seq_len} must be divisible by block {self.block}")
        nb = seq_len // self.block
        return np.zeros((self.num_heads, nb, nb), np.int8)

    def make_layout(self, seq_len: int) -> np.ndarray:
        raise NotImplementedError

    @property
    def prefix_stable(self) -> bool:
        """True when layout(S)[:s, :s] == layout(s) for every s <= S —
        i.e. the pattern a prefix sees does not depend on the total
        length. Random-block configs (BigBird, Variable with
        num_random_blocks > 0) are NOT prefix-stable: their layouts must
        be built once at the trained length and sliced."""
        return getattr(self, "num_random_blocks", 0) == 0

    def check_and_propagate_first_head_layout(self, layout: np.ndarray):
        if not self.different_layout_per_head:
            layout[1:] = layout[0]
        return layout


class DenseSparsityConfig(SparsityConfig):
    """All blocks on (reference: DenseSparsityConfig) — debugging anchor."""

    def make_layout(self, seq_len: int) -> np.ndarray:
        layout = self.setup_layout(seq_len)
        layout[:] = 1
        return layout


class FixedSparsityConfig(SparsityConfig):
    """Fixed local windows + periodic global blocks (reference:
    FixedSparsityConfig; the pattern of the Sparse Transformer paper)."""

    def __init__(self, num_heads, block=16, different_layout_per_head=False,
                 num_local_blocks: int = 4, num_global_blocks: int = 1,
                 attention: str = "bidirectional",
                 horizontal_global_attention: bool = False,
                 num_different_global_patterns: int = 1):
        super().__init__(num_heads, block, different_layout_per_head)
        if num_local_blocks % num_global_blocks != 0:
            raise ValueError("num_local_blocks must be a multiple of "
                             "num_global_blocks")
        if attention not in ("unidirectional", "bidirectional"):
            raise ValueError(f"invalid attention type {attention}")
        if horizontal_global_attention and attention != "bidirectional":
            raise ValueError("horizontal global attention requires "
                             "bidirectional attention")
        self.num_local_blocks = num_local_blocks
        self.num_global_blocks = num_global_blocks
        self.attention = attention
        self.horizontal_global_attention = horizontal_global_attention
        self.num_different_global_patterns = num_different_global_patterns

    def make_layout(self, seq_len: int) -> np.ndarray:
        layout = self.setup_layout(seq_len)
        nb = layout.shape[1]
        L, G = self.num_local_blocks, self.num_global_blocks
        for h in range(layout.shape[0]):
            # local windows
            for start in range(0, nb, L):
                end = min(start + L, nb)
                for i in range(start, end):
                    hi = (i + 1) if self.attention == "unidirectional" else end
                    layout[h, i, start:hi] = 1
            # global: representative block indices per window; heads may
            # rotate which sub-block is global (different patterns)
            pat = (h % self.num_different_global_patterns
                   if self.different_layout_per_head else 0)
            for start in range(0, nb, L):
                first = start + (L - (pat + 1) * G
                                 if self.attention == "unidirectional"
                                 else pat * G)
                for g in range(first, min(first + G, nb)):
                    if g < 0:
                        continue
                    # vertical: everyone (causally after g) attends block g
                    rows = (slice(g, nb) if self.attention == "unidirectional"
                            else slice(0, nb))
                    layout[h, rows, g] = 1
                    if self.horizontal_global_attention:
                        layout[h, g, :] = 1
        if self.attention == "unidirectional":
            layout = np.tril(layout)
        return self.check_and_propagate_first_head_layout(layout)


class VariableSparsityConfig(SparsityConfig):
    """User-chosen local window sizes + explicit global block indices
    (reference: VariableSparsityConfig)."""

    def __init__(self, num_heads, block=16, different_layout_per_head=False,
                 num_random_blocks: int = 0,
                 local_window_blocks: Optional[List[int]] = None,
                 global_block_indices: Optional[List[int]] = None,
                 global_block_end_indices: Optional[List[int]] = None,
                 attention: str = "bidirectional",
                 horizontal_global_attention: bool = False):
        super().__init__(num_heads, block, different_layout_per_head)
        self.num_random_blocks = num_random_blocks
        self.local_window_blocks = local_window_blocks or [4]
        self.global_block_indices = global_block_indices or [0]
        self.global_block_end_indices = global_block_end_indices
        self.attention = attention
        self.horizontal_global_attention = horizontal_global_attention

    def make_layout(self, seq_len: int) -> np.ndarray:
        layout = self.setup_layout(seq_len)
        nb = layout.shape[1]
        rng = np.random.default_rng(0)
        for h in range(layout.shape[0]):
            # variable local windows: first len(list)-1 explicit, last repeats
            start = 0
            wi = 0
            while start < nb:
                w = self.local_window_blocks[min(wi,
                                                 len(self.local_window_blocks) - 1)]
                end = min(start + w, nb)
                for i in range(start, end):
                    hi = (i + 1) if self.attention == "unidirectional" else end
                    layout[h, i, start:hi] = 1
                start, wi = end, wi + 1
            # globals
            if self.global_block_end_indices:
                spans = zip(self.global_block_indices,
                            self.global_block_end_indices)
            else:
                spans = ((g, g + 1) for g in self.global_block_indices)
            for lo, hi in spans:
                for g in range(lo, min(hi, nb)):
                    layout[h, :, g] = 1
                    if self.horizontal_global_attention:
                        layout[h, g, :] = 1
            for _ in range(self.num_random_blocks):
                i, j = rng.integers(0, nb, 2)
                layout[h, i, j] = 1
        if self.attention == "unidirectional":
            layout = np.tril(layout)
        return self.check_and_propagate_first_head_layout(layout)


class BigBirdSparsityConfig(SparsityConfig):
    """random + sliding-window + global blocks (reference:
    BigBirdSparsityConfig, the ITC pattern)."""

    def __init__(self, num_heads, block=16, different_layout_per_head=False,
                 num_random_blocks: int = 1,
                 num_sliding_window_blocks: int = 3,
                 num_global_blocks: int = 1,
                 attention: str = "bidirectional"):
        super().__init__(num_heads, block, different_layout_per_head)
        self.num_random_blocks = num_random_blocks
        self.num_sliding_window_blocks = num_sliding_window_blocks
        self.num_global_blocks = num_global_blocks
        self.attention = attention

    def make_layout(self, seq_len: int) -> np.ndarray:
        layout = self.setup_layout(seq_len)
        nb = layout.shape[1]
        w = self.num_sliding_window_blocks // 2
        rng = np.random.default_rng(0)
        for h in range(layout.shape[0]):
            for i in range(nb):
                layout[h, i, max(0, i - w):min(nb, i + w + 1)] = 1
            g = self.num_global_blocks
            layout[h, :g, :] = 1
            layout[h, :, :g] = 1
            if self.attention == "bidirectional":
                layout[h, -g:, :] = 1
                layout[h, :, -g:] = 1
            choices = rng.integers(0, nb, (nb, self.num_random_blocks))
            for i in range(nb):
                layout[h, i, choices[i]] = 1
        if self.attention == "unidirectional":
            layout = np.tril(layout)
        return self.check_and_propagate_first_head_layout(layout)


class BSLongformerSparsityConfig(SparsityConfig):
    """Longformer: sliding window + designated global positions
    (reference: BSLongformerSparsityConfig)."""

    def __init__(self, num_heads, block=16, different_layout_per_head=False,
                 num_sliding_window_blocks: int = 3,
                 global_block_indices: Optional[List[int]] = None,
                 global_block_end_indices: Optional[List[int]] = None,
                 attention: str = "bidirectional"):
        super().__init__(num_heads, block, different_layout_per_head)
        self.num_sliding_window_blocks = num_sliding_window_blocks
        self.global_block_indices = global_block_indices or [0]
        self.global_block_end_indices = global_block_end_indices
        self.attention = attention

    def make_layout(self, seq_len: int) -> np.ndarray:
        layout = self.setup_layout(seq_len)
        nb = layout.shape[1]
        w = self.num_sliding_window_blocks // 2
        for h in range(layout.shape[0]):
            for i in range(nb):
                layout[h, i, max(0, i - w):min(nb, i + w + 1)] = 1
            if self.global_block_end_indices:
                spans = zip(self.global_block_indices,
                            self.global_block_end_indices)
            else:
                spans = ((g, g + 1) for g in self.global_block_indices)
            for lo, hi in spans:
                for g in range(lo, min(hi, nb)):
                    layout[h, g, :] = 1
                    layout[h, :, g] = 1
        if self.attention == "unidirectional":
            layout = np.tril(layout)
        return self.check_and_propagate_first_head_layout(layout)
