from .sparsity_config import (SparsityConfig, DenseSparsityConfig,
                              FixedSparsityConfig, VariableSparsityConfig,
                              BigBirdSparsityConfig,
                              BSLongformerSparsityConfig)
from .sparse_self_attention import SparseSelfAttention, sparse_attention
from .bert_sparse_self_attention import BertSparseSelfAttention
from .sparse_attention_utils import SparseAttentionUtils

__all__ = ["SparsityConfig", "DenseSparsityConfig", "FixedSparsityConfig",
           "VariableSparsityConfig", "BigBirdSparsityConfig",
           "BSLongformerSparsityConfig", "SparseSelfAttention",
           "sparse_attention", "BertSparseSelfAttention",
           "SparseAttentionUtils"]
