from .sparsity_config import (SparsityConfig, DenseSparsityConfig,
                              FixedSparsityConfig, VariableSparsityConfig,
                              BigBirdSparsityConfig,
                              BSLongformerSparsityConfig)
from .sparse_self_attention import SparseSelfAttention, sparse_attention

__all__ = ["SparsityConfig", "DenseSparsityConfig", "FixedSparsityConfig",
           "VariableSparsityConfig", "BigBirdSparsityConfig",
           "BSLongformerSparsityConfig", "SparseSelfAttention",
           "sparse_attention"]
