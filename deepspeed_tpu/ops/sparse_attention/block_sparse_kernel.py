"""Block-sparse attention Pallas kernel (splash-attention style).

The real TPU replacement for the reference's Triton block-sparse SDD/DSD
matmuls + sparse softmax (reference: deepspeed/ops/sparse_attention/
matmul.py:6, softmax.py, csrc/sparse_attention/utils.cpp): compute is
proportional to the number of ACTIVE layout blocks, not S².

Design (vs the reference's separate sdd/softmax/dsd kernel pipeline — one
fused pass per direction):

- The [H, S/B, S/B] block layout from a SparsityConfig is compiled
  host-side into per-(head, q-tile) lists of active 128-aligned k-tiles
  (scalar-prefetched to SMEM). The grid is (batch, heads, q_tiles); each
  kernel invocation keeps the full K/V for its (batch, head) resident in
  VMEM (refetched only when the head changes) and runs a
  dynamic-trip-count ``fori_loop`` over exactly that row's active tiles —
  BigBird's dense global rows simply loop longer, without padding the
  sparse window rows.
- Fine-grained layouts (block < 128, the DeepSpeed default of 16) keep
  exact semantics: each (q-tile, k-tile) pair applies a [128,128] mask
  expanded from the fine layout. Masks are deduplicated host-side
  (window/global patterns produce a handful of distinct tiles) and live
  as one [U,128,128] VMEM-resident array indexed per loop step.
- Backward = two more sparse passes sharing the plan: a q-major pass for
  dQ and a k-major pass (transposed lists) for dK/dV, both recomputing
  probabilities from the saved softmax stats (m, l).

Falls back to the dense-mask path (sparse_self_attention.py) for shapes
it cannot tile (S % 128 != 0, 128 % block != 0, all-empty rows).
"""

import functools
import math
from dataclasses import dataclass
from typing import Optional

import numpy as np
import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from ..pallas._common import NEG_INF
from ..pallas._common import interpret_mode as _interpret
from ..pallas.flash_attention import resolve_dropout, _tile_keep

DEFAULT_TILE = 256     # fewer, fatter loop iterations when seq % 256 == 0
MIN_TILE = 128


# ---------------------------------------------------------------------------
# host-side layout compilation
# ---------------------------------------------------------------------------

@dataclass
class LayoutPlan:
    """Compiled work lists for one (layout, seq) pair. All numpy."""
    kv_idx: np.ndarray         # [H, NQ, MAXK] int32, padded with 0
    kv_pid: np.ndarray         # [H, NQ, MAXK] int32 mask pattern ids
    kv_cnt: np.ndarray         # [H, NQ] int32
    qt_idx: np.ndarray         # [H, NQ, MAXQ] int32 (k-major lists)
    qt_pid: np.ndarray         # [H, NQ, MAXQ] int32
    qt_cnt: np.ndarray         # [H, NQ] int32
    masks: np.ndarray          # [U, tile, tile] int8
    tile: int
    n_heads: int
    nq: int
    active_tiles: int
    total_tiles: int

    @property
    def density(self):
        return self.active_tiles / max(self.total_tiles, 1)


_PLAN_CACHE = {}


def compile_layout(config, seq_len: int) -> Optional[LayoutPlan]:
    """Build tile work lists from a SparsityConfig. Returns None when the
    layout cannot be tiled at 128 granularity (caller falls back dense)."""
    try:
        key = (config.cache_key(), seq_len)
    except TypeError:
        key = None
    if key is not None and key in _PLAN_CACHE:
        return _PLAN_CACHE[key]

    block = config.block
    layout = np.asarray(config.make_layout(seq_len))  # [H, nb, nb] 0/1
    nheads, nb, _ = layout.shape

    def coarse_active(t):
        """Active kernel tiles at tile size t (np coarsening)."""
        if block >= t:
            return int(layout.sum()) * (block // t) ** 2
        r = t // block
        n = seq_len // t
        c = layout.reshape(nheads, n, r, n, r).any(axis=(2, 4))
        return int(c.sum())

    # Pick the tile by compute volume (active_tiles * tile²): 256-tiles
    # quarter the loop-iteration overhead but over-include on fine
    # scattered patterns (BigBird randoms); take the fat tile only when
    # its coarsening waste is small (<=1.3x the fine tile's volume).
    cands = [t for t in (DEFAULT_TILE, MIN_TILE)
             if seq_len % t == 0 and (t % block == 0 or block % t == 0)]
    if not cands:
        return None
    vols = {t: coarse_active(t) * t * t for t in cands}
    tile = cands[0]
    if len(cands) == 2 and vols[cands[0]] > 1.3 * vols[cands[1]]:
        tile = cands[1]

    if block >= tile:
        r = block // tile
        fine = np.repeat(np.repeat(layout, r, axis=1), r, axis=2)
        nq = nb * r
        rq = 1
    else:
        rq = tile // block
        nq = seq_len // tile
        fine = layout

    # every fine q row needs >= 1 active block, else the two paths diverge
    # on the empty row (dense gives a uniform softmax)
    if not fine.any(axis=-1).all():
        return None

    causal = getattr(config, "attention", None) == "unidirectional"
    masks: list = []
    mask_ids: dict = {}

    def pattern_id(sub, rel):
        """rel: "past" = tile fully before the diagonal, "diag" = the
        triangular tile (unidirectional semantics are causal at the
        ELEMENT level — the reference triton kernel's in-block masking)."""
        key_ = (sub.tobytes(), rel)
        if key_ not in mask_ids:
            expanded = np.kron(sub, np.ones((tile // sub.shape[0],
                                             tile // sub.shape[1]), np.int8))
            if rel == "diag":
                expanded = expanded * np.tril(
                    np.ones((tile, tile), np.int8))
            mask_ids[key_] = len(masks)
            masks.append(expanded.astype(np.int8))
        return mask_ids[key_]

    rows = [[[] for _ in range(nq)] for _ in range(nheads)]
    cols = [[[] for _ in range(nq)] for _ in range(nheads)]
    total = 0
    for h in range(nheads):
        for qi in range(nq):
            subrows = fine[h, qi * rq:(qi + 1) * rq] if rq > 1 else \
                fine[h, qi:qi + 1]
            for ki in range(nq):
                if causal and ki > qi:
                    continue   # entirely future: elementwise all-zero
                sub = subrows[:, ki * rq:(ki + 1) * rq] if rq > 1 else \
                    subrows[:, ki:ki + 1]
                if sub.any():
                    rel = "diag" if (causal and ki == qi) else "past"
                    pid = pattern_id(np.ascontiguousarray(sub), rel)
                    rows[h][qi].append((ki, pid))
                    cols[h][ki].append((qi, pid))
                    total += 1

    def pad(lists):
        mx = max(1, max(len(l) for hl in lists for l in hl))
        idx = np.zeros((nheads, nq, mx), np.int32)
        pid = np.zeros((nheads, nq, mx), np.int32)
        cnt = np.zeros((nheads, nq), np.int32)
        for h in range(nheads):
            for i, l in enumerate(lists[h]):
                cnt[h, i] = len(l)
                for j, (x, p) in enumerate(l):
                    idx[h, i, j] = x
                    pid[h, i, j] = p
        return idx, pid, cnt

    kv_idx, kv_pid, kv_cnt = pad(rows)
    qt_idx, qt_pid, qt_cnt = pad(cols)
    plan = LayoutPlan(kv_idx=kv_idx, kv_pid=kv_pid, kv_cnt=kv_cnt,
                      qt_idx=qt_idx, qt_pid=qt_pid, qt_cnt=qt_cnt,
                      masks=np.stack(masks), tile=tile, n_heads=nheads,
                      nq=nq, active_tiles=total, total_tiles=nheads * nq * nq)
    if key is not None:
        if len(_PLAN_CACHE) >= 16:
            _PLAN_CACHE.pop(next(iter(_PLAN_CACHE)))
        _PLAN_CACHE[key] = plan
    return plan


# ---------------------------------------------------------------------------
# kernels
# ---------------------------------------------------------------------------

def _masked_scores(q, k_ref, mask_ref, ki, pid, scale, tile):
    """[tile,d]x[tile,d] scores for one active tile, fine-masked.

    q/k stay in their native dtype (bf16 hot path) so the MXU runs at its
    bf16 rate; scores accumulate fp32 via preferred_element_type."""
    k = k_ref[0, 0, pl.ds(ki * tile, tile), :]
    live = mask_ref[pid] != 0
    s = jnp.where(live, jnp.dot(q, k.T,
                                preferred_element_type=jnp.float32) * scale,
                  NEG_INF)
    return s, live, k


def _fwd_kernel(*refs, scale, d, tile, dropout_rate, total_heads):
    # refs: [idx, pid, cnt, seeds?] (SMEM) + [q, k, v, masks] + outputs
    has_drop = dropout_rate > 0.0
    (idx_ref, pid_ref, cnt_ref), rest = refs[:3], refs[3:]
    sm_ref = rest[0] if has_drop else None
    q_ref, k_ref, v_ref, mask_ref, o_ref, m_ref, l_ref = rest[1 if has_drop
                                                              else 0:]
    bi, hi, qi = pl.program_id(0), pl.program_id(1), pl.program_id(2)
    q = q_ref[0, 0]
    inv_keep = 1.0 / (1.0 - dropout_rate) if has_drop else 1.0

    def body(j, carry):
        acc, m_acc, l_acc = carry
        ki = idx_ref[hi, qi, j]
        pid = pid_ref[hi, qi, j]
        s, live, _ = _masked_scores(q, k_ref, mask_ref, ki, pid, scale, tile)
        v = v_ref[0, 0, pl.ds(ki * tile, tile), :]
        m_new = jnp.maximum(m_acc, jnp.max(s, axis=-1, keepdims=True))
        p = jnp.where(live, jnp.exp(s - m_new), 0.0)
        alpha = jnp.exp(m_acc - m_new)
        l_new = l_acc * alpha + jnp.sum(p, axis=-1, keepdims=True)
        if has_drop:
            # same counter-based keep bits as the flash kernel: the
            # dense-mask fallback path samples identically, so the two
            # sparse paths stay bit-compatible under dropout
            keep = _tile_keep(sm_ref, bi, hi, qi * tile, ki * tile,
                              (tile, tile), dropout_rate, total_heads)
            p = jnp.where(keep, p * inv_keep, 0.0)
        acc = acc * alpha + jnp.dot(p.astype(v.dtype), v,
                                    preferred_element_type=jnp.float32)
        return acc, m_new, l_new

    acc, m, l = jax.lax.fori_loop(
        0, cnt_ref[hi, qi], body,
        (jnp.zeros((tile, d), jnp.float32),
         jnp.full((tile, 1), NEG_INF, jnp.float32),
         jnp.zeros((tile, 1), jnp.float32)))
    safe = jnp.where(l > 0.0, l, 1.0)
    o_ref[0, 0] = (acc / safe).astype(o_ref.dtype)
    m_ref[0, 0] = m
    l_ref[0, 0] = safe


def _dq_kernel(*refs, scale, d, tile, dropout_rate, total_heads):
    has_drop = dropout_rate > 0.0
    (idx_ref, pid_ref, cnt_ref), rest = refs[:3], refs[3:]
    sm_ref = rest[0] if has_drop else None
    (q_ref, k_ref, v_ref, do_ref, dl_ref, m_ref, l_ref, mask_ref,
     dq_ref) = rest[1 if has_drop else 0:]
    bi, hi, qi = pl.program_id(0), pl.program_id(1), pl.program_id(2)
    q = q_ref[0, 0]
    do = do_ref[0, 0]
    delta = dl_ref[0, 0]
    m, l = m_ref[0, 0], l_ref[0, 0]
    inv_keep = 1.0 / (1.0 - dropout_rate) if has_drop else 1.0

    def body(j, acc):
        ki = idx_ref[hi, qi, j]
        pid = pid_ref[hi, qi, j]
        s, live, k = _masked_scores(q, k_ref, mask_ref, ki, pid, scale, tile)
        v = v_ref[0, 0, pl.ds(ki * tile, tile), :]
        p = jnp.where(live, jnp.exp(s - m), 0.0) / l
        dp = jnp.dot(do, v.T, preferred_element_type=jnp.float32)
        if has_drop:
            keep = _tile_keep(sm_ref, bi, hi, qi * tile, ki * tile,
                              (tile, tile), dropout_rate, total_heads)
            dp = jnp.where(keep, dp * inv_keep, 0.0)
        ds = (p * (dp - delta) * scale).astype(q.dtype)
        return acc + jnp.dot(ds, k, preferred_element_type=jnp.float32)

    acc = jax.lax.fori_loop(0, cnt_ref[hi, qi], body,
                            jnp.zeros((tile, d), jnp.float32))
    dq_ref[0, 0] = acc.astype(dq_ref.dtype)


def _dkv_kernel(*refs, scale, d, tile, dropout_rate, total_heads):
    has_drop = dropout_rate > 0.0
    (idx_ref, pid_ref, cnt_ref), rest = refs[:3], refs[3:]
    sm_ref = rest[0] if has_drop else None
    (q_ref, k_ref, v_ref, do_ref, dl_ref, m_ref, l_ref, mask_ref,
     dk_ref, dv_ref) = rest[1 if has_drop else 0:]
    bi, hi, ki = pl.program_id(0), pl.program_id(1), pl.program_id(2)
    k = k_ref[0, 0]                          # this column's k tile
    v = v_ref[0, 0]
    inv_keep = 1.0 / (1.0 - dropout_rate) if has_drop else 1.0

    def body(j, carry):
        dk_acc, dv_acc = carry
        qi = idx_ref[hi, ki, j]
        pid = pid_ref[hi, ki, j]
        qs = pl.ds(qi * tile, tile)
        q = q_ref[0, 0, qs, :]
        do = do_ref[0, 0, qs, :]
        delta = dl_ref[0, 0, qs, :]
        m = m_ref[0, 0, qs, :]
        l = l_ref[0, 0, qs, :]
        live = mask_ref[pid] != 0
        s = jnp.where(live, jnp.dot(q, k.T,
                                    preferred_element_type=jnp.float32)
                      * scale, NEG_INF)
        p = jnp.where(live, jnp.exp(s - m), 0.0) / l
        dp = jnp.dot(do, v.T, preferred_element_type=jnp.float32)
        if has_drop:
            keep = _tile_keep(sm_ref, bi, hi, qi * tile, ki * tile,
                              (tile, tile), dropout_rate, total_heads)
            dfac = jnp.where(keep, inv_keep, 0.0)
            dp = dp * dfac
            pl_ = (p * dfac).astype(do.dtype)
        else:
            pl_ = p.astype(do.dtype)
        ds = (p * (dp - delta) * scale).astype(q.dtype)
        dk_acc = dk_acc + jnp.dot(ds.T, q, preferred_element_type=jnp.float32)
        dv_acc = dv_acc + jnp.dot(pl_.T, do, preferred_element_type=jnp.float32)
        return dk_acc, dv_acc

    dk_acc, dv_acc = jax.lax.fori_loop(
        0, cnt_ref[hi, ki], body,
        (jnp.zeros((tile, d), jnp.float32),
         jnp.zeros((tile, d), jnp.float32)))
    dk_ref[0, 0] = dk_acc.astype(dk_ref.dtype)
    dv_ref[0, 0] = dv_acc.astype(dv_ref.dtype)


# ---------------------------------------------------------------------------
# dispatch
# ---------------------------------------------------------------------------

def _specs(d, S, U, tile):
    tile_q = pl.BlockSpec((1, 1, tile, d),
                          lambda bi, hi, qi, *_: (bi, hi, qi, 0))
    full_kv = pl.BlockSpec((1, 1, S, d), lambda bi, hi, qi, *_: (bi, hi, 0, 0))
    stat_q = pl.BlockSpec((1, 1, tile, 1),
                          lambda bi, hi, qi, *_: (bi, hi, qi, 0))
    full_stat = pl.BlockSpec((1, 1, S, 1),
                             lambda bi, hi, qi, *_: (bi, hi, 0, 0))
    masks = pl.BlockSpec((U, tile, tile), lambda bi, hi, qi, *_: (0, 0, 0))
    return tile_q, full_kv, stat_q, full_stat, masks


def _drop_args(seeds):
    """(extra scalar-prefetch operands, n_scalar, static kwargs pieces)."""
    return ((seeds,), 4) if seeds is not None else ((), 3)


def _sparse_fwd(q, k, v, masks, idx, pid, cnt, scale, tile, seeds=None,
                dropout_rate=0.0, total_heads=1):
    b, h, S, d = q.shape
    U = masks.shape[0]
    tile_q, full_kv, stat_q, _, mask_spec = _specs(d, S, U, tile)
    extra, nsp = _drop_args(seeds)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=nsp,
        grid=(b, h, S // tile),
        in_specs=[tile_q, full_kv, full_kv, mask_spec],
        out_specs=[tile_q, stat_q, stat_q])
    o, m, l = pl.pallas_call(
        functools.partial(_fwd_kernel, scale=scale, d=d, tile=tile,
                          dropout_rate=dropout_rate if seeds is not None
                          else 0.0, total_heads=total_heads),
        grid_spec=grid_spec,
        out_shape=(jax.ShapeDtypeStruct(q.shape, q.dtype),
                   jax.ShapeDtypeStruct((b, h, S, 1), jnp.float32),
                   jax.ShapeDtypeStruct((b, h, S, 1), jnp.float32)),
        interpret=_interpret(),
    )(idx, pid, cnt, *extra, q, k, v, masks)
    return o, m, l


def _sparse_dq(q, k, v, do, delta, m, l, masks, idx, pid, cnt, scale, tile,
               seeds=None, dropout_rate=0.0, total_heads=1):
    b, h, S, d = q.shape
    U = masks.shape[0]
    tile_q, full_kv, stat_q, _, mask_spec = _specs(d, S, U, tile)
    extra, nsp = _drop_args(seeds)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=nsp,
        grid=(b, h, S // tile),
        in_specs=[tile_q, full_kv, full_kv, tile_q, stat_q, stat_q, stat_q,
                  mask_spec],
        out_specs=[tile_q])
    (dq,) = pl.pallas_call(
        functools.partial(_dq_kernel, scale=scale, d=d, tile=tile,
                          dropout_rate=dropout_rate if seeds is not None
                          else 0.0, total_heads=total_heads),
        grid_spec=grid_spec,
        out_shape=(jax.ShapeDtypeStruct(q.shape, q.dtype),),
        interpret=_interpret(),
    )(idx, pid, cnt, *extra, q, k, v, do, delta, m, l, masks)
    return dq


def _sparse_dkv(q, k, v, do, delta, m, l, masks, idx, pid, cnt, scale, tile,
                seeds=None, dropout_rate=0.0, total_heads=1):
    b, h, S, d = q.shape
    U = masks.shape[0]
    _, full_kv, _, full_stat, mask_spec = _specs(d, S, U, tile)
    tile_k = pl.BlockSpec((1, 1, tile, d),
                          lambda bi, hi, ki, *_: (bi, hi, ki, 0))
    extra, nsp = _drop_args(seeds)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=nsp,
        grid=(b, h, S // tile),
        in_specs=[full_kv, tile_k, tile_k, full_kv, full_stat, full_stat,
                  full_stat, mask_spec],
        out_specs=[tile_k, tile_k])
    dk, dv = pl.pallas_call(
        functools.partial(_dkv_kernel, scale=scale, d=d, tile=tile,
                          dropout_rate=dropout_rate if seeds is not None
                          else 0.0, total_heads=total_heads),
        grid_spec=grid_spec,
        out_shape=(jax.ShapeDtypeStruct(k.shape, k.dtype),
                   jax.ShapeDtypeStruct(v.shape, v.dtype)),
        interpret=_interpret(),
    )(idx, pid, cnt, *extra, q, k, v, do, delta, m, l, masks)
    return dk, dv


@functools.lru_cache(maxsize=16)
def _build_sparse_fn(plan_key, scale, dropout_rate, total_heads):
    """custom_vjp'd BHSD sparse attention bound to one compiled plan.
    The plan's arrays are jit constants (they ARE the program). With
    dropout_rate > 0 the function takes a seeds operand (int32[4]:
    [seed0, seed1, head_offset, batch_offset]) feeding the in-kernel
    counter-based keep hash shared with the flash kernel."""
    plan = _PLAN_CACHE[plan_key]
    masks = jnp.asarray(plan.masks)
    kv = (jnp.asarray(plan.kv_idx), jnp.asarray(plan.kv_pid),
          jnp.asarray(plan.kv_cnt))
    qt = (jnp.asarray(plan.qt_idx), jnp.asarray(plan.qt_pid),
          jnp.asarray(plan.qt_cnt))
    dkw = dict(dropout_rate=dropout_rate, total_heads=total_heads)

    @jax.custom_vjp
    def fn(q, k, v, seeds):
        o, _, _ = _sparse_fwd(q, k, v, masks, *kv, scale, plan.tile,
                              seeds=seeds, **dkw)
        return o

    def fwd(q, k, v, seeds):
        o, m, l = _sparse_fwd(q, k, v, masks, *kv, scale, plan.tile,
                              seeds=seeds, **dkw)
        return o, (q, k, v, seeds, o, m, l)

    def bwd(res, g):
        q, k, v, seeds, o, m, l = res
        delta = jnp.sum(g.astype(jnp.float32) * o.astype(jnp.float32),
                        axis=-1, keepdims=True)
        dq = _sparse_dq(q, k, v, g, delta, m, l, masks, *kv, scale,
                        plan.tile, seeds=seeds, **dkw)
        dk, dv = _sparse_dkv(q, k, v, g, delta, m, l, masks, *qt, scale,
                             plan.tile, seeds=seeds, **dkw)
        dseeds = (np.zeros(seeds.shape, jax.dtypes.float0)
                  if seeds is not None else None)
        return dq, dk, dv, dseeds

    fn.defvjp(fwd, bwd)
    return fn


def block_sparse_attention(q, k, v, sparsity_config, *, softmax_scale=None,
                           dropout_rate=0.0, dropout_rng=None,
                           dropout_offsets=None):
    """q/k/v: [batch, seq, heads, head_dim] (BSHD). Sparse Pallas path;
    returns None when the layout can't be tiled (caller falls back).
    Attention-probability dropout (reference: the Triton softmax kernel's
    fused dropout) samples the flash kernel's position-keyed hash —
    active when both dropout_rate and dropout_rng are set."""
    b, s, h, d = q.shape
    plan = compile_layout(sparsity_config, s)
    if plan is None or plan.n_heads != h:
        return None
    try:
        plan_key = (sparsity_config.cache_key(), s)
    except TypeError:
        return None   # uncacheable config: dense fallback
    scale = softmax_scale if softmax_scale is not None else 1.0 / math.sqrt(d)
    rate, seeds, total_heads = resolve_dropout(
        dropout_rate, dropout_rng, dropout_offsets, h)
    fn = _build_sparse_fn(plan_key, float(scale), rate, total_heads)
    qt, kt, vt = (jnp.swapaxes(t, 1, 2) for t in (q, k, v))
    o = fn(qt, kt, vt, seeds)
    return jnp.swapaxes(o, 1, 2)
