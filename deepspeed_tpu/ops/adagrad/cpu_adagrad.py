"""Host-side fused Adagrad over numpy shards (ZeRO-Offload inner
optimizer, Adagrad flavor).

Reference: DeepSpeedCPUAdagrad (deepspeed/ops/adagrad/cpu_adagrad.py:10)
backed by csrc/adagrad/cpu_adagrad.cpp. Same ctypes C-ABI pattern as
ops/adam/cpu_adam.py; update math matches optax.adagrad (proven by
test_native_ops.py).
"""

import itertools
import ctypes
from typing import Optional

import numpy as np

from ...analysis import lint_ok
from ..op_builder import CPUAdagradBuilder

_ids = itertools.count()


def _f32ptr(a: np.ndarray):
    return a.ctypes.data_as(ctypes.POINTER(ctypes.c_float))


class DeepSpeedCPUAdagrad:
    def __init__(self, lr=1e-2, eps=1e-10, weight_decay=0.0):
        self.lib = CPUAdagradBuilder.load()
        self.opt_id = next(_ids)
        self.defaults = dict(lr=lr, eps=eps, weight_decay=weight_decay)
        rc = self.lib.ds_adagrad_create(self.opt_id, lr, eps, weight_decay)
        if rc != 0:
            raise RuntimeError("ds_adagrad_create failed")

    @lint_ok("TS002")  # operands are host numpy by contract (ZeRO-Offload)
    def step(self, params: np.ndarray, grads: np.ndarray,
             exp_avg_sq: np.ndarray, lr: Optional[float] = None,
             out_bf16: Optional[np.ndarray] = None):
        """One fused step over a flat fp32 shard, in place."""
        for name, a in (("params", params), ("grads", grads),
                        ("exp_avg_sq", exp_avg_sq)):
            if a.dtype != np.float32 or not a.flags.c_contiguous:
                raise ValueError(f"{name} must be contiguous float32")
        n = params.size
        if not (grads.size == exp_avg_sq.size == n):
            raise ValueError("size mismatch")
        out_ptr = None
        if out_bf16 is not None:
            if out_bf16.dtype != np.uint16 or out_bf16.size != n:
                raise ValueError(
                    "out_bf16 must be uint16 (bf16 bits) of same size")
            out_ptr = out_bf16.ctypes.data_as(ctypes.c_void_p)
        rc = self.lib.ds_adagrad_update(
            self.opt_id, -1.0 if lr is None else float(lr), _f32ptr(grads),
            _f32ptr(params), _f32ptr(exp_avg_sq), n, out_ptr)
        if rc != 0:
            raise RuntimeError("ds_adagrad_update failed")

    def __del__(self):
        try:
            self.lib.ds_adagrad_destroy(self.opt_id)
        except Exception:
            pass
