from .cpu_adagrad import DeepSpeedCPUAdagrad

__all__ = ["DeepSpeedCPUAdagrad"]
