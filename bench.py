"""Benchmark driver: GPT-2 training throughput on the local chip(s).

Prints ONE JSON line:
  {"metric": "gpt2_125m_train_tokens_per_sec_per_chip", "value": N,
   "unit": "tokens/s/chip", "vs_baseline": R}

vs_baseline is measured against REF_TOKENS_PER_SEC_PER_CHIP, a stand-in for
the reference stack's per-accelerator training throughput on its own
headline benchmarks (BASELINE.md: DeepSpeed's published V100-class numbers;
no in-repo reference value exists for this exact config, BASELINE.json
.published = {}). 50k tokens/s/chip ~= the reference's BERT-Large 272
samples/s@seq128 fused-kernel figure normalized per chip.
"""

import json
import sys
import time

REF_TOKENS_PER_SEC_PER_CHIP = 50_000.0

SEQ = 1024
STEPS = 5
WARMUP = 2


def main():
    import numpy as np
    import jax
    import jax.numpy as jnp
    import deepspeed_tpu as ds
    from deepspeed_tpu.models import GPT, GPT2_PRESETS, gpt_loss_fn
    import dataclasses

    n_chips = len(jax.devices())
    mcfg = dataclasses.replace(GPT2_PRESETS["gpt2-125m"],
                               dtype=jnp.bfloat16, scan_layers=True,
                               remat="full")

    from deepspeed_tpu.models import gpt_chunked_loss_fn

    def loss_fn(model, params, batch, rng, train):
        ids = batch["input_ids"]
        # chunked vocab loss: the full [B,S,V] logits never materialize,
        # buying ~2x larger per-chip batch at seq 1024
        h, wte = model.apply(params, ids, deterministic=not train,
                             return_hidden=True)
        return gpt_chunked_loss_fn(h[:, :-1], wte, ids[:, 1:], chunk=128)

    batch_per_chip = 32
    global_batch = batch_per_chip * n_chips
    config = {
        "train_batch_size": global_batch,
        "train_micro_batch_size_per_gpu": batch_per_chip,
        "gradient_accumulation_steps": 1,
        "optimizer": {"type": "Adam", "params": {"lr": 1e-4}},
        "bf16": {"enabled": True},
        "zero_optimization": {"stage": 1},
        "steps_per_print": 10_000,
    }

    rng = np.random.default_rng(0)
    batch = {"input_ids": rng.integers(0, mcfg.vocab_size,
                                       size=(global_batch, SEQ), dtype=np.int32)}
    engine, _, _, _ = ds.initialize(
        model=GPT(mcfg), config=config, loss_fn=loss_fn,
        sample_batch={"input_ids": batch["input_ids"][:1]},
        rng=jax.random.PRNGKey(0))

    def fetch_scalar(tree):
        # device->host copy forces the dependency chain (block_until_ready
        # can ack early through remote-relay backends)
        leaf = jax.tree.leaves(tree)[0]
        return np.asarray(leaf.reshape(-1)[0])

    for _ in range(WARMUP):
        engine.train_batch(batch)
    fetch_scalar(engine.params)

    t0 = time.time()
    for _ in range(STEPS):
        loss = engine.train_batch(batch)
    _ = np.asarray(loss)
    fetch_scalar(engine.params)
    dt = (time.time() - t0) / STEPS

    tokens_per_sec = global_batch * SEQ / dt
    per_chip = tokens_per_sec / n_chips
    # model flops: ~6*N per token fwd+bwd
    n_params = mcfg.num_params()
    tflops_per_chip = 6 * n_params * per_chip / 1e12

    result = {
        "metric": "gpt2_125m_train_tokens_per_sec_per_chip",
        "value": round(per_chip, 1),
        "unit": "tokens/s/chip",
        "vs_baseline": round(per_chip / REF_TOKENS_PER_SEC_PER_CHIP, 3),
    }
    print(json.dumps(result))
    print(f"# loss={float(loss):.3f} step={dt*1e3:.1f}ms chips={n_chips} "
          f"model_tflops/chip={tflops_per_chip:.1f}", file=sys.stderr)


if __name__ == "__main__":
    main()
